"""Flagship benchmark: GP-UCB suggest() latency at 1000 trials / 20-D.

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": "ms", "vs_baseline": N}``.

The north-star target (BASELINE.md) is suggest() p50 < 1000 ms at 1000
trials, 20-D, on TPU; ``vs_baseline`` is target_ms / measured_p50 (>1 beats
the target). The measured step is the full device-side suggest compute:
output-warped labels → ARD train (multi-restart L-BFGS) → ensemble
posterior → UCB + trust region → vectorized Eagle sweep (75k evaluations)
→ top-k candidates, excluding the first-compile run (jit caches are
reusable across suggests in a real serving process).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _progress(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def _probe_backend(timeout_s: float = 180.0) -> bool:
    """True iff `import jax; jax.devices()` completes in a subprocess.

    A dead TPU tunnel makes backend *initialization* hang forever (round-1
    failure mode: rc 124, no number at all). Probing in a killable
    subprocess lets the benchmark fall back to CPU and still print an
    honest JSON line instead of timing out silently.
    """
    import subprocess

    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            capture_output=True,
        )
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _arm_watchdog(budget_s: float) -> None:
    """Hard-exits with a stack dump if the benchmark wedges mid-run.

    The CPU-fallback probe only covers backend *init*; a tunnel that dies
    mid-run would otherwise hang a device call until the driver's timeout
    with zero diagnostics. The watchdog leaves a traceback on stderr and a
    prompt non-zero exit instead.
    """
    import faulthandler
    import threading

    def fire():
        _progress(f"WATCHDOG: no completion after {budget_s:.0f}s; dumping stacks")
        faulthandler.dump_traceback(file=sys.stderr)
        os._exit(3)

    t = threading.Timer(budget_s, fire)
    t.daemon = True
    t.start()


def _static_flop_budget(
    n_pad: int, dim: int, max_evals: int, pool: int, restarts: int, maxiter: int
) -> dict:
    """Static per-suggest flop budget (docs/guides/tpu_architecture.md).

    Upper-bound model of the measured device-side step (ARD train + one
    acquisition sweep) in raw flops:

    - ARD: ``restarts`` L-BFGS runs x (maxiter grad evals + ~1 line-search
      NLL eval per iteration) x per-eval cost, where one NLL+grad eval is
      ~3x the forward Gram + Cholesky (reverse-mode factor ~2):
      fwd = 2*n_pad^2*dim (Gram) + n_pad^3/3 (Cholesky). At 1024x20 this is
      ~1.2 GFLOP/eval — the guide's "~1 GFLOP" line item. The ftol early
      exit makes this an upper bound, so MFU below is a LOWER bound.
    - Sweep: (max_evals/pool) eagle iterations x 2*(pool*n_pad*dim kernel
      row + n_pad^2*pool ``linv @ k_star^T`` matmul) — ~160 GFLOP at the
      1000x20-D/75k-eval north-star point, matching the guide.
    """
    fwd = 2.0 * n_pad * n_pad * dim + n_pad**3 / 3.0
    ard = restarts * (2.0 * maxiter) * (3.0 * fwd)
    iters = max(max_evals // pool, 1)
    sweep = iters * 2.0 * (pool * n_pad * dim + n_pad * n_pad * pool)
    return {"ard_flops": ard, "sweep_flops": sweep, "total_flops": ard + sweep}


# Nominal peak f32 throughput per backend for the MFU denominator. TPU is
# the guide's ~49 f32 TFLOP/s per v5e chip; CPU is a nominal 50 GFLOP/s
# single-socket SIMD figure (the CPU number proves the accounting, not the
# hardware). Override with VIZIER_PEAK_FLOPS.
_PEAK_FLOPS = {"tpu": 49.0e12, "cpu": 50.0e9}


def _surrogate_env_config() -> dict:
    """The process-wide VIZIER_SPARSE* config, for artifact provenance."""
    from vizier_tpu.surrogates import SurrogateConfig

    return SurrogateConfig.from_env().as_dict()


def _speculative_env_config() -> dict:
    """The process-wide VIZIER_SPECULATIVE* config, for provenance."""
    from vizier_tpu.serving.speculative import SpeculativeConfig

    return SpeculativeConfig.from_env().as_dict()


def _registered_programs() -> list:
    """The registered compute-IR program kinds, for provenance."""
    from vizier_tpu.compute import registry as compute_registry

    return list(compute_registry.kinds())


def _loadgen_env_config() -> dict:
    """The process-wide VIZIER_LOADGEN* scenario config, for provenance."""
    from vizier_tpu.loadgen import ScenarioConfig

    config = ScenarioConfig.from_env()
    return {
        "name": config.name,
        "seed": config.seed,
        "scale": config.scale,
        "num_studies": config.num_studies,
        "total_studies": config.total_studies,
        "target": config.target,
        "events": [e.as_dict() for e in config.events],
    }


def _mesh_env_config() -> dict:
    """The process-wide VIZIER_MESH* config, for artifact provenance."""
    import dataclasses

    from vizier_tpu.parallel.mesh import MeshConfig

    return dataclasses.asdict(MeshConfig.from_env())


def _slo_env_config() -> dict:
    """The process-wide VIZIER_SLO* config, for artifact provenance."""
    from vizier_tpu.observability.slo import SloConfig

    return SloConfig.from_env().as_dict()


def main() -> None:
    backend_tag = None
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if "cpu" not in platforms.split(","):
        _progress("probe: checking the accelerator backend is alive (<=180s)")
        if not _probe_backend():
            _progress("probe: backend init hung/failed -> CPU fallback")
            os.environ["JAX_PLATFORMS"] = "cpu"
            backend_tag = "cpu_fallback_tpu_unreachable"
            # Full budget on CPU risks the driver's timeout; shrink unless
            # the caller pinned a scale explicitly.
            os.environ.setdefault("VIZIER_BENCH_SCALE", "0.25")
    # A CPU-fallback run is legitimately slower; give it a longer leash.
    default_watchdog = 900.0 if backend_tag else 540.0
    _arm_watchdog(float(os.environ.get("VIZIER_BENCH_WATCHDOG_S", default_watchdog)))

    _progress("init: importing jax + applying platform env")
    # Round-1 lesson: without the config-level platform pin, the image's TPU
    # sitecustomize makes `JAX_PLATFORMS=cpu python bench.py` hang in
    # make_c_api_client. One shared implementation lives in __graft_entry__.
    from __graft_entry__ import _honor_platform_env

    _honor_platform_env()
    import jax

    # Persistent XLA compilation cache (satellite of the batching PR): a
    # bench run with VIZIER_COMPILE_CACHE_DIR set both populates the cache
    # and stamps its status into the JSON so compile-vs-cached runs are
    # distinguishable after the fact.
    cache_dir = os.environ.get("VIZIER_COMPILE_CACHE_DIR")
    if cache_dir:
        from vizier_tpu.serving.runtime import _apply_compilation_cache

        _apply_compilation_cache(cache_dir)

    from vizier_tpu import types
    from vizier_tpu.designers.gp import acquisitions
    from vizier_tpu.models import gp as gp_lib
    from vizier_tpu.models import kernels
    from vizier_tpu.models import output_warpers
    from vizier_tpu.optimizers import eagle as eagle_lib
    from vizier_tpu.optimizers import lbfgs as lbfgs_lib
    from vizier_tpu.optimizers import vectorized as vectorized_lib
    from vizier_tpu.designers.gp_bandit import _maximize_acquisition, _train_gp

    _progress(f"backend: {jax.default_backend()} ({len(jax.devices())} devices)")

    # SCALE < 1 shrinks the problem for smoke-testing on CPU; the driver
    # runs the full-size benchmark (SCALE unset) on TPU.
    scale = float(os.environ.get("VIZIER_BENCH_SCALE", "1.0"))

    num_trials, dim = max(int(1000 * scale), 16), 20
    n_pad = 1 << (num_trials - 1).bit_length()  # next power-of-2 bucket
    batch_count = 25  # suggestion batch (reference default batch)
    max_evals = max(int(75_000 * scale), 500)
    repeats = 5 if scale >= 1.0 else 2

    rng = np.random.default_rng(0)
    x = rng.uniform(size=(num_trials, dim)).astype(np.float32)
    y_raw = -np.sum((x - 0.5) ** 2, axis=1) + 0.1 * rng.normal(size=num_trials)
    warped = output_warpers.create_default_warper()(y_raw)

    features = types.ContinuousAndCategorical(
        continuous=types.PaddedArray.from_array(x, (n_pad, dim)),
        categorical=types.PaddedArray.from_array(
            np.zeros((num_trials, 0), np.int32), (n_pad, 0), fill_value=0
        ),
    )
    labels = types.PaddedArray.from_array(
        warped[:, None].astype(np.float32), (n_pad, 1), fill_value=np.nan
    )
    data = gp_lib.GPData.from_model_data(types.ModelData(features, labels))

    model = gp_lib.VizierGaussianProcess(num_continuous=dim, num_categorical=0)
    ard = lbfgs_lib.LbfgsOptimizer(maxiter=50)
    strategy = eagle_lib.VectorizedEagleStrategy(num_continuous=dim, category_sizes=())
    vec_opt = vectorized_lib.VectorizedOptimizer(strategy, max_evaluations=max_evals)

    def one_suggest(seed: int):
        key = jax.random.PRNGKey(seed)
        k_train, k_acq = jax.random.split(key)
        # ARD budget matches the reference's published envelope and the
        # designer's production defaults (4 restarts, maxiter 50, single
        # posterior — BASELINE.md / lbfgs_lib.DEFAULT_RANDOM_RESTARTS).
        states = _train_gp(
            model, ard, data, k_train, lbfgs_lib.DEFAULT_RANDOM_RESTARTS, 1
        )
        predictive = gp_lib.EnsemblePredictive(states)
        best_label = jax.numpy.max(
            jax.numpy.where(data.row_mask, data.labels, -jax.numpy.inf)
        )
        scoring = acquisitions.ScoringFunction(
            predictive=predictive,
            acquisition=acquisitions.UCB(1.8),
            best_label=best_label,
            trust_region=acquisitions.TrustRegion.from_data(data),
        )
        result = _maximize_acquisition(
            vec_opt, scoring, k_acq, batch_count,
            kernels.MixedFeatures(data.continuous[:10], data.categorical[:10]),
        )
        jax.block_until_ready(result)
        return result

    _progress(
        f"compile: first suggest at {num_trials}x{dim}d, {max_evals} evals "
        f"(first TPU compile can take ~20-40s)"
    )
    t0 = time.perf_counter()
    one_suggest(0)  # compile
    _progress(f"compile: done in {time.perf_counter() - t0:.1f}s")
    # Latency distribution via the observability histogram (fixed
    # exponential buckets — the same estimator a Prometheus scrape of the
    # serving process would apply), alongside the exact sample percentile
    # that remains the longitudinal headline number: bucket interpolation
    # error must not masquerade as a perf regression across rounds.
    from vizier_tpu.observability import ObservabilityConfig, MetricsRegistry

    obs_config = ObservabilityConfig.from_env()
    bench_metrics = MetricsRegistry()
    latency_hist = bench_metrics.histogram(
        "bench_suggest_latency_seconds", help="bench.py device-side suggest"
    )
    times = []
    for i in range(1, repeats + 1):
        t0 = time.perf_counter()
        one_suggest(i)
        times.append((time.perf_counter() - t0) * 1000.0)
        latency_hist.observe(times[-1] / 1000.0)
        _progress(f"repeat {i}/{repeats}: {times[-1]:.1f} ms")
    p50 = float(np.percentile(times, 50))

    # End-to-end DEFAULT-algorithm check: the full VizierGPUCBPEBandit
    # designer suggest(25) at the same scale, INCLUDING python-side trial
    # conversion, per-metric output warping, ARD training, and the UCB/PE
    # batch loop. One fresh completed trial is folded in before each repeat
    # so the GP-fit cache cannot serve stale states (matches production:
    # every suggest sees new data). Reported as an extra key on the same
    # JSON line.
    _progress("e2e: full DEFAULT designer suggest() at bench scale")
    from vizier_tpu import pyvizier as vz
    from vizier_tpu.algorithms import core as core_lib
    from vizier_tpu.designers.gp_ucb_pe import VizierGPUCBPEBandit

    problem = vz.ProblemStatement()
    for d in range(dim):
        problem.search_space.root.add_float_param(f"x{d}", 0.0, 1.0)
    problem.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    designer = VizierGPUCBPEBandit(
        problem, max_acquisition_evaluations=max_evals
    )
    trials = []
    for i in range(num_trials):
        t = vz.Trial(
            id=i + 1,
            parameters={f"x{d}": float(x[i, d]) for d in range(dim)},
        )
        t.complete(vz.Measurement(metrics={"obj": float(y_raw[i])}))
        trials.append(t)
    designer.update(core_lib.CompletedTrials(trials))
    t0 = time.perf_counter()
    designer.suggest(batch_count)  # compile
    _progress(f"e2e compile: done in {time.perf_counter() - t0:.1f}s")
    e2e_times = []
    e2e_hist = bench_metrics.histogram(
        "bench_e2e_suggest_latency_seconds", help="bench.py e2e designer suggest"
    )
    next_id = num_trials + 1
    for i in range(repeats):
        fresh = vz.Trial(
            id=next_id,
            parameters={
                f"x{d}": float(v)
                for d, v in enumerate(rng.uniform(size=dim))
            },
        )
        fresh.complete(vz.Measurement(metrics={"obj": float(-i)}))
        next_id += 1
        t0 = time.perf_counter()
        designer.update(core_lib.CompletedTrials([fresh]))
        designer.suggest(batch_count)
        e2e_times.append((time.perf_counter() - t0) * 1000.0)
        e2e_hist.observe(e2e_times[-1] / 1000.0)
        _progress(f"e2e repeat {i + 1}/{repeats}: {e2e_times[-1]:.1f} ms")
    e2e_p50 = float(np.percentile(e2e_times, 50))

    def _hist_ms(hist, q):
        value = hist.percentile(q)
        return round(value * 1000.0, 1) if value is not None else None

    target_ms = 1000.0
    if scale == 1.0:
        # Stable id for longitudinal tracking across rounds.
        metric = "gp_ucb_suggest_p50@1000x20d_75k_evals"
    else:
        metric = f"gp_ucb_suggest_p50@{num_trials}x{dim}d_{max_evals}evals_scaled"
    # MFU accounting (VERDICT r5 next-round #1): static flop budget over
    # the measured device-side p50. achieved_gflops is a lower bound (the
    # budget is an upper bound; ARD early-exits under ftol).
    budget = _static_flop_budget(
        n_pad, dim, max_evals, strategy.config.pool_size,
        lbfgs_lib.DEFAULT_RANDOM_RESTARTS, ard.maxiter,
    )
    peak = float(
        os.environ.get(
            "VIZIER_PEAK_FLOPS",
            _PEAK_FLOPS.get(jax.default_backend(), _PEAK_FLOPS["cpu"]),
        )
    )
    achieved = budget["total_flops"] / (p50 / 1000.0)
    line = {
        "metric": metric,
        "value": round(p50, 1),
        "unit": "ms",
        "vs_baseline": round(target_ms / p50, 3),
        "achieved_gflops": round(achieved / 1e9, 2),
        "mfu": round(achieved / peak, 4),
        "static_flop_budget_gflop": round(budget["total_flops"] / 1e9, 1),
        "peak_flops_assumed": peak,
        # Histogram-derived percentiles (vizier_tpu.observability buckets):
        # the distribution a Prometheus scrape of the serving process would
        # see, reported next to the exact-sample headline p50 above.
        "hist_p50_ms": _hist_ms(latency_hist, 50),
        "hist_p95_ms": _hist_ms(latency_hist, 95),
        "hist_p99_ms": _hist_ms(latency_hist, 99),
        "e2e_default_designer_suggest_p50_ms": round(e2e_p50, 1),
        "e2e_hist_p50_ms": _hist_ms(e2e_hist, 50),
        "e2e_hist_p95_ms": _hist_ms(e2e_hist, 95),
        "e2e_hist_p99_ms": _hist_ms(e2e_hist, 99),
        "observability": obs_config.as_dict(),
        # JAX persistent compilation cache (ServingConfig.compilation_cache_dir
        # / VIZIER_COMPILE_CACHE_DIR): when active, repeat bench runs pay
        # zero XLA compiles — compare first-call latencies across runs.
        "compilation_cache": {
            "dir": getattr(jax.config, "jax_compilation_cache_dir", None),
            "active": bool(
                getattr(jax.config, "jax_compilation_cache_dir", None)
            ),
        },
        # Round-4 semantics (docs/guides/tpu_architecture.md): the default
        # "first_pick_full" spends one full budget on the exploitation pick
        # plus one split across the rest (~2 sweeps per suggest) — r1-r3
        # e2e numbers spent a full budget on EVERY pick (25 sweeps).
        "e2e_budget_policy": designer.acquisition_budget_policy,
        # Which surrogate path produced these numbers: bench drives the
        # exact-GP device programs directly (and the DEFAULT UCB-PE
        # designer for e2e at a trial count below the sparse threshold),
        # so the measured mode is always "exact"; the env config rides
        # along so artifacts that DO auto-switch are distinguishable
        # (tools/surrogate_ab.py measures both sparse paths).
        "surrogates": {
            "active_mode": "exact",
            **_surrogate_env_config(),
        },
        # Speculative pre-compute (serving.speculative): bench drives the
        # designers directly, so no suggest here is ever served from a
        # parked batch — the env config rides along so artifacts from
        # speculative-enabled processes are distinguishable
        # (tools/speculative_ab.py measures the served-hit path).
        "speculative": {
            "active": False,
            **_speculative_env_config(),
        },
        # Mesh execution plane (parallel.mesh / VIZIER_MESH*): bench
        # drives designers directly (no batch executor), so no flush here
        # is mesh-dispatched — the env config plus the visible device
        # count ride along so artifacts from mesh-enabled processes are
        # distinguishable (tools/batching_ab.py --devices measures it).
        "mesh": {
            "active": False,
            "visible_devices": jax.device_count(),
            **_mesh_env_config(),
        },
        # The compute-IR program set this build registers (vizier_tpu.
        # compute.registry): artifacts from trees with more/fewer batched
        # designer programs are distinguishable after the fact.
        "compute_programs": _registered_programs(),
        # Active SLO configuration (observability.slo / VIZIER_SLO*):
        # bench itself serves no SLO traffic, but an artifact produced
        # under armed SLOs (the sampler thread + exemplar capture) must be
        # distinguishable from one produced bare.
        "slo": _slo_env_config(),
        # The loadgen scenario config (vizier_tpu.loadgen / VIZIER_LOADGEN*):
        # bench drives designers directly, not the traffic engine, but a
        # soak-adjacent artifact stamps which scenario the environment was
        # set up for (tools/soak.py produces SOAK_REPORT.json itself).
        "loadgen": _loadgen_env_config(),
    }
    if backend_tag:
        line["backend"] = backend_tag
    print(json.dumps(line))


if __name__ == "__main__":
    main()
