#!/usr/bin/env bash
# Regenerates the protobuf message stubs (messages only; the thin gRPC
# method stubs are hand-written in vizier_tpu/service/grpc_stubs.py since
# grpcio-tools is not available in this image).
#
# No protoc either? `python tools/regen_protos.py` applies schema additions
# declared there directly to the serialized descriptors in the pb2 modules
# (that is how SuggestTrialsRequest/PythiaSuggestRequest.deadline_secs were
# added); keep the .proto sources, that script, and the pb2 files in sync.
set -euo pipefail
cd "$(dirname "$0")/vizier_tpu/service/protos"
protoc --python_out=. key_value.proto study.proto vizier_service.proto pythia_service.proto
echo "Regenerated $(ls *_pb2.py | wc -l) stub modules."
