"""Standalone Vizier server demo.

Parity with the reference ``demos/run_vizier_server.py``: starts a
DefaultVizierServer (RAM or sqlite-backed) and blocks.

Usage:
  python demos/run_vizier_server.py [--host localhost] [--port 28080]
      [--database_url sqlite:////tmp/vizier.db]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import time


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="localhost")
    parser.add_argument("--port", type=int, default=None)
    parser.add_argument("--database_url", default=None)
    args = parser.parse_args()

    # Honor JAX_PLATFORMS before any backend init (env alone is not enough
    # on images whose sitecustomize pins an accelerator platform, and a
    # dead tunnel would hang the first device call).
    from __graft_entry__ import _honor_platform_env

    _honor_platform_env()

    from vizier_tpu.service.vizier_server import DefaultVizierServer

    server = DefaultVizierServer(
        host=args.host, port=args.port, database_url=args.database_url
    )
    print(f"Vizier server listening at {server.endpoint}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop(0)


if __name__ == "__main__":
    main()
