"""Client demo: optimize a toy objective against a running server.

Usage:
  python demos/run_vizier_client.py --endpoint localhost:28080 [--trials 20]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def evaluate(lr: float, layers: int) -> float:
    return 1.0 - 100.0 * (lr - 0.01) ** 2 - 0.05 * abs(layers - 3)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--endpoint", default=None)
    parser.add_argument("--trials", type=int, default=20)
    parser.add_argument("--algorithm", default="DEFAULT")
    args = parser.parse_args()

    # Honor JAX_PLATFORMS before any backend init (env alone is not enough
    # on images whose sitecustomize pins an accelerator platform, and a
    # dead tunnel would hang the first device call).
    from __graft_entry__ import _honor_platform_env

    _honor_platform_env()

    from vizier_tpu import pyvizier as vz
    from vizier_tpu.service import clients

    config = vz.StudyConfig(algorithm=args.algorithm)
    root = config.search_space.root
    root.add_float_param("learning_rate", 1e-4, 1e-1, scale_type=vz.ScaleType.LOG)
    root.add_int_param("layers", 1, 8)
    config.metric_information.append(
        vz.MetricInformation(name="accuracy", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    study = clients.Study.from_study_config(
        config, owner="demo", study_id="client-demo", endpoint=args.endpoint
    )
    for i in range(args.trials):
        for trial in study.suggest(count=1):
            params = trial.parameters
            acc = evaluate(params["learning_rate"], params["layers"])
            trial.complete(vz.Measurement(metrics={"accuracy": acc}))
            print(f"trial {i + 1}: acc={acc:.4f} params={params}")
    best = list(study.optimal_trials())[0].materialize()
    print(
        "best:", best.final_measurement.metrics["accuracy"].value,
        dict(best.parameters.as_dict()),
    )


if __name__ == "__main__":
    main()
