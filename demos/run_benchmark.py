"""Benchmark demo: compare designers on a BBOB function, save a plot.

Usage:
  python demos/run_benchmark.py --function Sphere --dim 4 --trials 30 \
      --out /tmp/convergence.png [--platform cpu]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--function", default="Sphere")
    parser.add_argument("--dim", type=int, default=4)
    parser.add_argument("--trials", type=int, default=30)
    parser.add_argument("--repeats", type=int, default=2)
    parser.add_argument("--out", default="/tmp/convergence.png")
    parser.add_argument("--platform", default=None, choices=["cpu", "tpu"])
    args = parser.parse_args()

    # One owner for the platform write: route the flag through the env and
    # the shared guarded helper (already-initialized backends tolerated).
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    from __graft_entry__ import _honor_platform_env

    _honor_platform_env()

    import matplotlib

    matplotlib.use("Agg")

    from vizier_tpu import benchmarks
    from vizier_tpu.benchmarks.analyzers import plot_utils
    from vizier_tpu.benchmarks.experimenters.synthetic import bbob
    from vizier_tpu.designers import QuasiRandomDesigner, RandomDesigner
    from vizier_tpu.designers.gp_bandit import VizierGPBandit

    functions = dict(bbob.BBOB_FUNCTIONS, **bbob.EXTRA_FUNCTIONS)
    fn = functions[args.function]

    factories = {
        "random": lambda p, **kw: RandomDesigner(p.search_space, seed=kw.get("seed", 0)),
        "quasirandom": lambda p, **kw: QuasiRandomDesigner(
            p.search_space, seed=kw.get("seed", 0)
        ),
        "gp_ucb": lambda p, **kw: VizierGPBandit(
            p, rng_seed=kw.get("seed") or 0, max_acquisition_evaluations=5000
        ),
    }
    states, names = [], []
    for name, factory in factories.items():
        for r in range(args.repeats):
            exp = benchmarks.NumpyExperimenter(fn, benchmarks.bbob_problem(args.dim))
            state = benchmarks.BenchmarkState.from_designer_factory(exp, factory, seed=r)
            benchmarks.BenchmarkRunner(
                [benchmarks.GenerateAndEvaluate(2)],
                num_repeats=-(-args.trials // 2),  # ceil: honor odd budgets
            ).run(state)
            states.append(state)
            names.append(name)
            print(f"{name} repeat {r} done", flush=True)
    ax = plot_utils.plot_states(
        states, algorithm_names=names, title=f"{args.function} {args.dim}D"
    )
    ax.get_figure().savefig(args.out, dpi=120)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
