"""Side-by-side parity measurement: this framework vs the reference.

BASELINE.md: the reference publishes no benchmark numbers, so it must be run
(CPU) as its own baseline. This suite runs reference designers and this
repo's designers against the SAME experimenter objects (reference trials are
adapted through a thin parameter-dict bridge, so both sides optimize the
byte-identical objective with the same seeds and budgets), builds
convergence curves, and scores statistical parity with the comparator
machinery (win-rate / log-efficiency bands).

Scope note (documented limitation, not a choice): the reference's GP stack
imports equinox + tensorflow_probability, which are absent from this image
and may not be installed. Its runnable algorithms — random, quasi-random,
eagle (firefly), NSGA2 — are measured; eagle-vs-eagle and random-vs-random
are direct same-algorithm parity checks, and this repo's GP designers are
additionally gated on dominating the reference's runnable baselines.

Usage:
  bash tools/build_reference_copy.sh        # once per machine
  python parity_suite.py [--scale 1.0] [--out regret_report_r2.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REF_PATH = "/tmp/refvizier"


def _progress(msg: str) -> None:
    print(f"[parity] {msg}", file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# Reference-designer adapter: drives a reference designer with OUR
# experimenter. Parameters cross the bridge as plain python dicts.
# ---------------------------------------------------------------------------


def _to_ref_problem(problem):
    """Builds a reference ProblemStatement mirroring ours."""
    from vizier import pyvizier as ref_vz

    from vizier_tpu.pyvizier import parameter_config as pc

    ref = ref_vz.ProblemStatement()
    root = ref.search_space.root
    for cfg in problem.search_space.parameters:
        if cfg.type == pc.ParameterType.DOUBLE:
            lo, hi = cfg.bounds
            root.add_float_param(cfg.name, lo, hi)
        elif cfg.type == pc.ParameterType.INTEGER:
            lo, hi = cfg.bounds
            root.add_int_param(cfg.name, int(lo), int(hi))
        elif cfg.type == pc.ParameterType.DISCRETE:
            root.add_discrete_param(cfg.name, list(cfg.feasible_values))
        else:
            root.add_categorical_param(
                cfg.name, [str(v) for v in cfg.feasible_values]
            )
    for m in problem.metric_information:
        goal = (
            ref_vz.ObjectiveMetricGoal.MAXIMIZE
            if m.goal.is_maximize
            else ref_vz.ObjectiveMetricGoal.MINIMIZE
        )
        ref.metric_information.append(
            ref_vz.MetricInformation(name=m.name, goal=goal)
        )
    return ref


def run_reference_designer(designer_factory, experimenter, num_trials, batch):
    """suggest→evaluate→update loop for a REFERENCE designer over OUR
    experimenter; returns our completed Trial objects."""
    from vizier import algorithms as ref_vza
    from vizier import pyvizier as ref_vz

    from vizier_tpu.pyvizier import trial as trial_lib

    problem = experimenter.problem_statement()
    ref_problem = _to_ref_problem(problem)
    designer = designer_factory(ref_problem)
    ours: list = []
    tid = 0
    while tid < num_trials:
        count = min(batch, num_trials - tid)
        suggestions = designer.suggest(count)
        if not suggestions:
            break
        batch_ours, batch_ref = [], []
        for s in suggestions:
            tid += 1
            params = {name: v.value for name, v in s.parameters.items()}
            batch_ours.append(trial_lib.Trial(id=tid, parameters=params))
        experimenter.evaluate(batch_ours)
        for s, t in zip(suggestions, batch_ours):
            rt = s.to_trial(t.id)
            if t.final_measurement is None:
                rt.complete(
                    ref_vz.Measurement(),
                    infeasibility_reason=t.infeasibility_reason or "infeasible",
                )
            else:
                rt.complete(
                    ref_vz.Measurement(
                        metrics={
                            k: m.value
                            for k, m in t.final_measurement.metrics.items()
                        }
                    )
                )
            batch_ref.append(rt)
        designer.update(
            ref_vza.CompletedTrials(batch_ref), ref_vza.ActiveTrials([])
        )
        ours.extend(batch_ours)
    return ours


def run_our_designer(designer_factory, experimenter, num_trials, batch):
    from vizier_tpu.algorithms import core as core_lib

    problem = experimenter.problem_statement()
    designer = designer_factory(problem)
    ours: list = []
    tid = 0
    while tid < num_trials:
        count = min(batch, num_trials - tid)
        batch_trials = []
        for s in designer.suggest(count):
            tid += 1
            batch_trials.append(s.to_trial(tid))
        experimenter.evaluate(batch_trials)
        designer.update(core_lib.CompletedTrials(batch_trials))
        ours.extend(batch_trials)
    return ours


# ---------------------------------------------------------------------------
# Suite.
# ---------------------------------------------------------------------------


def rank_sum_p(a, b) -> float:
    """Two-sided Mann-Whitney p (normal approximation): H0 = same dist."""
    from scipy import stats

    a, b = np.asarray(a, float), np.asarray(b, float)
    n, m = len(a), len(b)
    if n == 0 or m == 0:
        return 1.0
    ranks = stats.rankdata(np.concatenate([a, b]))
    u = ranks[:n].sum() - n * (n + 1) / 2.0
    mu = n * m / 2.0
    sigma = np.sqrt(n * m * (n + m + 1) / 12.0)
    z = (u - mu) / max(sigma, 1e-9)
    return float(2.0 * (1.0 - stats.norm.cdf(abs(z))))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--scale", type=float, default=1.0)
    parser.add_argument("--out", default="regret_report_r3.json")
    parser.add_argument("--platform", default="cpu", choices=["cpu", "tpu"])
    parser.add_argument(
        "--only",
        default=None,
        choices=(
            "branin_2d",
            "mixed_space_default",
            "bbob20d_sphere",
            "bbob20d_rastrigin",
            "zdt1_hypervolume",
            "nasbench201_synthetic",
        ),
        help="Run a single config by report name (e.g. nasbench201_synthetic).",
    )
    args = parser.parse_args()
    s = args.scale

    if not os.path.isdir(REF_PATH):
        raise SystemExit(
            f"{REF_PATH} missing — run tools/build_reference_copy.sh first."
        )
    sys.path.insert(0, REF_PATH)

    import jax

    if args.platform:
        try:
            jax.config.update("jax_platforms", args.platform)
        except Exception:
            pass

    from vizier_tpu import benchmarks
    from vizier_tpu import pyvizier as vz
    from vizier_tpu.benchmarks.analyzers import convergence_curve as cc
    from vizier_tpu.benchmarks.analyzers import state_analyzer as sa
    from vizier_tpu.benchmarks.experimenters.synthetic import bbob, multiobjective
    from vizier_tpu.designers import RandomDesigner
    from vizier_tpu.designers.eagle_strategy import EagleStrategyDesigner
    from vizier_tpu.designers.evolution import NSGA2Designer
    from vizier_tpu.designers.gp_bandit import VizierGPBandit
    from vizier_tpu.designers.gp_ucb_pe import VizierGPUCBPEBandit

    # Reference designers (imported from the patched /tmp copy).
    from vizier._src.algorithms.designers import quasi_random as ref_qr
    from vizier._src.algorithms.designers import random as ref_random
    from vizier._src.algorithms.designers.eagle_strategy import (
        eagle_strategy as ref_eagle,
    )
    from vizier._src.algorithms.evolution import nsga2 as ref_nsga2

    report: dict = {
        "note": (
            "Reference GP designers are unmeasurable in this image "
            "(equinox/tensorflow_probability absent; installation banned). "
            "Parity is asserted same-algorithm (random↔random, eagle↔eagle, "
            "nsga2↔nsga2) and by this repo's GP designers dominating the "
            "reference's runnable baselines on identical objectives/seeds."
        ),
        "scale": s,
        "configs": {},
    }
    t_start = time.time()

    def curve_for(trials, metric):
        return cc.ConvergenceCurveConverter(metric, flip_signs_for_min=True).convert(
            trials
        )

    def algorithms_for(config_name):
        """name -> (side, factory(problem, seed)) at this config's budgets."""
        gp_evals = max(int(25_000 * s), 1500)

        def my_gp(p, seed):
            return VizierGPBandit(
                p,
                rng_seed=seed,
                max_acquisition_evaluations=gp_evals,
                num_seed_trials=5,
            )

        def my_ucbpe(p, seed):
            return VizierGPUCBPEBandit(
                p,
                rng_seed=seed,
                max_acquisition_evaluations=gp_evals,
                num_seed_trials=5,
            )

        return {
            "ref-random": ("ref", lambda p, seed: ref_random.RandomDesigner(p.search_space, seed=seed)),
            "ref-quasirandom": ("ref", lambda p, seed: ref_qr.QuasiRandomDesigner(p.search_space, seed=seed)),
            "ref-eagle": ("ref", lambda p, seed: ref_eagle.EagleStrategyDesigner(p, seed=seed)),
            "my-random": ("mine", lambda p, seed: RandomDesigner(p.search_space, seed=seed)),
            "my-eagle": ("mine", lambda p, seed: EagleStrategyDesigner(p, seed=seed)),
            "my-gp-ucb": ("mine", my_gp),
            "my-ucbpe-default": ("mine", my_ucbpe),
        }

    def run_config(name, experimenter, num_trials, batch, seeds, skip=()):
        if args.only and name != args.only:
            return
        # ``experimenter`` may be a factory ``seed -> Experimenter`` so
        # configs can randomize per seed (e.g. shifted BBOB optima).
        if isinstance(experimenter, benchmarks.Experimenter):
            exp_of = lambda _seed, _e=experimenter: _e  # noqa: E731
        else:
            exp_of = experimenter
        metric = next(
            m
            for m in exp_of(0).problem_statement().metric_information
            if not m.is_safety_metric
        )
        records = []
        finals: dict = {}
        cheap = {"ref-random", "ref-quasirandom", "my-random", "ref-eagle", "my-eagle"}
        for algo_name, (side, factory) in algorithms_for(name).items():
            if algo_name in skip:
                continue
            # Cheap algorithms get extra seeds: the parity rank-sum tests
            # need sample size, and these runs cost almost nothing.
            algo_seeds = (
                tuple(seeds) + tuple(100 + i for i in range(len(seeds), 6))
                if algo_name in cheap
                else seeds
            )
            curves = []
            for seed in algo_seeds:
                _progress(f"{name}: {algo_name} seed={seed}")
                np.random.seed(seed)  # some reference paths use np global rng
                runner = run_reference_designer if side == "ref" else run_our_designer
                trials = runner(
                    lambda p, _seed=seed: factory(p, _seed),
                    exp_of(seed),
                    num_trials,
                    batch,
                )
                curves.append(curve_for(trials, metric))
            combined = cc.ConvergenceCurve.align_xs(curves)
            finals[algo_name] = [float(c.ys[0, -1]) for c in curves]
            records.append(
                sa.BenchmarkRecord(
                    algorithm=algo_name,
                    experimenter_metadata={"config": name},
                    plot_elements={"objective": sa.PlotElement(combined)},
                )
            )
        sa.BenchmarkRecordAnalyzer.add_comparison_metrics(records, "ref-random")
        rows = sa.BenchmarkRecordAnalyzer.summarize(records)

        # Parity verdicts.
        def row(algo):
            return next((r for r in rows if r["algorithm"] == algo), None)

        verdicts = {}
        ref_rand, my_rand = row("ref-random"), row("my-random")
        if ref_rand and my_rand:
            # Same algorithm, same objective: per-seed finals must be
            # statistically indistinguishable (two-sided rank-sum).
            p = rank_sum_p(finals["my-random"], finals["ref-random"])
            verdicts["random_parity"] = {
                "rank_sum_p": p,
                "finals_mine": finals["my-random"],
                "finals_ref": finals["ref-random"],
                "pass": bool(p > 0.05),
            }
        ref_e, my_e = row("ref-eagle"), row("my-eagle")
        if ref_e and my_e:
            gap = my_e["objective_final_median"] - ref_e["objective_final_median"]
            spread = abs(
                ref_rand["objective_final_median"] - ref_e["objective_final_median"]
            ) if ref_rand else 1.0
            p = rank_sum_p(finals["my-eagle"], finals["ref-eagle"])
            # Parity: statistically indistinguishable, or mine ahead, or the
            # deficit within half the ref's improvement-over-random (with an
            # absolute floor for configs where eagle ≈ random and the spread
            # is pure noise).
            tolerance = max(
                0.5 * spread, 0.05 * abs(ref_e["objective_final_median"]), 1e-3
            )
            verdicts["eagle_parity"] = {
                "final_median_gap": gap,
                "rank_sum_p": p,
                "tolerance": tolerance,
                "pass": bool(p > 0.05 or gap >= -tolerance),
            }
        for gp_name in ("my-gp-ucb", "my-ucbpe-default"):
            r = row(gp_name)
            if r and ref_rand:
                verdicts[f"{gp_name}_beats_random"] = {
                    "log_efficiency": r.get("log_efficiency_vs_ref-random"),
                    "final_median_vs_random": r["objective_final_median"]
                    - ref_rand["objective_final_median"],
                    "pass": bool(
                        r["objective_final_median"]
                        >= ref_rand["objective_final_median"]
                    ),
                }
        report["configs"][name] = {"rows": rows, "verdicts": verdicts}
        _progress(f"{name}: done ({time.time() - t_start:.0f}s elapsed)")

    # -- Config 1: Branin 2-D (classic GP benchmark) ------------------------
    run_config(
        "branin_2d",
        benchmarks.NumpyExperimenter(
            bbob.Branin, benchmarks.bbob_problem(2, metric_name="bbob_eval")
        ),
        num_trials=max(int(60 * s), 16),
        batch=2,
        seeds=(1, 2, 3),
    )

    # -- Config 2: mixed int/float/categorical (README space), DEFAULT -----
    def mixed_experimenter():
        problem = vz.ProblemStatement()
        root = problem.search_space.root
        root.add_float_param("lr", 1e-4, 1e-1, scale_type=vz.ScaleType.LOG)
        root.add_int_param("layers", 1, 8)
        root.add_categorical_param("opt", ["adam", "sgd", "rmsprop"])
        problem.metric_information.append(
            vz.MetricInformation(name="acc", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
        from vizier_tpu.pyvizier import trial as trial_lib

        class MixedExp(benchmarks.Experimenter):
            def evaluate(self, suggestions):
                for t in suggestions:
                    lr = t.parameters.get_value("lr")
                    layers = t.parameters.get_value("layers")
                    opt = str(t.parameters.get_value("opt"))
                    acc = (
                        1.0
                        - (np.log10(lr) + 2.0) ** 2 * 0.2
                        - 0.03 * abs(int(layers) - 4)
                        + (0.05 if opt == "adam" else 0.0)
                    )
                    t.complete(trial_lib.Measurement(metrics={"acc": acc}))

            def problem_statement(self):
                return problem

        return MixedExp()

    run_config(
        "mixed_space_default",
        mixed_experimenter(),
        num_trials=max(int(45 * s), 15),
        batch=3,
        seeds=(1, 2),
    )

    # -- Config 3: 20-D BBOB (Sphere, Rastrigin) — eagle's home turf -------
    # Shifted per seed (matching the reference factory's shift-application,
    # ``experimenter_factory.py:151-153``) so the optimum never coincides
    # with the search-box center that GP designers default-seed: an
    # unshifted run measures seeding, not optimization. ONE shared instance
    # definition pins this report, the CI gate, and the budget A/B together.
    from vizier_tpu.benchmarks.experimenters import experimenter_factory

    for fn_name in ("Sphere", "Rastrigin"):

        def shifted_bbob(seed, _fn=fn_name):
            return experimenter_factory.shifted_bbob_instance(_fn, seed)

        run_config(
            f"bbob20d_{fn_name.lower()}",
            shifted_bbob,
            num_trials=max(int(150 * s), 30),
            batch=10,
            seeds=(1, 2),
            skip=("my-gp-ucb", "ref-quasirandom"),  # UCB-PE covers the GP side
        )

    # -- Config 4: multi-objective ZDT1 hypervolume ------------------------
    def run_mo():
        if args.only and args.only != "zdt1_hypervolume":
            return
        exp = multiobjective.MultiObjectiveExperimenter.zdt("zdt1", dimension=6)
        metrics = list(exp.problem_statement().metric_information)
        ref_point = np.array([-1.1, -6.0], dtype=np.float32)
        n = max(int(80 * s), 20)
        results = {}

        def hv(trials):
            curve = cc.HypervolumeCurveConverter(
                metrics, reference_point=ref_point
            ).convert(trials)
            return float(curve.ys[0, -1])

        mo_algos = {
            "ref-nsga2": (
                "ref",
                lambda p, seed: ref_nsga2.NSGA2Designer(p, population_size=20, seed=seed),
            ),
            "ref-random": (
                "ref",
                lambda p, seed: ref_random.RandomDesigner(p.search_space, seed=seed),
            ),
            "my-nsga2": (
                "mine",
                lambda p, seed: NSGA2Designer(p, population_size=20, seed=seed),
            ),
            "my-ucbpe-default": (
                "mine",
                lambda p, seed: VizierGPUCBPEBandit(
                    p,
                    rng_seed=seed,
                    max_acquisition_evaluations=max(int(10_000 * s), 1000),
                    num_seed_trials=5,
                ),
            ),
        }
        for algo_name, (side, factory) in mo_algos.items():
            hvs = []
            for seed in (1, 2):
                _progress(f"zdt1: {algo_name} seed={seed}")
                runner = (
                    run_reference_designer if side == "ref" else run_our_designer
                )
                trials = runner(
                    lambda p, _seed=seed: factory(p, _seed), exp, n, 5
                )
                hvs.append(hv(trials))
            results[algo_name] = float(np.median(hvs))
        verdicts = {
            "nsga2_parity": {
                "ref": results["ref-nsga2"],
                "mine": results["my-nsga2"],
                "pass": bool(
                    results["my-nsga2"]
                    >= results["ref-nsga2"]
                    - 0.5 * (results["ref-nsga2"] - results["ref-random"])
                ),
            },
            "ucbpe_beats_random": {
                "pass": bool(results["my-ucbpe-default"] >= results["ref-random"])
            },
        }
        report["configs"]["zdt1_hypervolume"] = {
            "rows": results,
            "verdicts": verdicts,
        }
        _progress("zdt1: done")

    run_mo()

    # -- Config 5: NASBench-201 cell space (BASELINE.md's NAS config) ------
    # The real dataset isn't bundled in this image; the handler's synthetic
    # table preserves the pipeline (6-op categorical cells -> snap-to-table
    # accuracy) so the full tabular NAS benchmark path is measured e2e.
    # (BASELINE names this config "via PyGlove"; pyglove itself is absent,
    # so the same space runs through the designer path instead.)
    from vizier_tpu.benchmarks.experimenters import surrogates

    run_config(
        "nasbench201_synthetic",
        surrogates.NASBench201Handler().make_synthetic_experimenter(seed=0),
        num_trials=max(int(80 * s), 20),
        batch=5,
        seeds=(1, 2),
        skip=("my-gp-ucb", "ref-quasirandom"),  # UCB-PE covers the GP side
    )

    report["elapsed_secs"] = round(time.time() - t_start, 1)
    report["all_pass"] = all(
        v.get("pass", True)
        for cfg in report["configs"].values()
        for v in cfg["verdicts"].values()
    )
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
