"""Deadline budgets: arithmetic, propagation, typed DEADLINE_EXCEEDED ops."""

import pytest

from tests.reliability import harness
from vizier_tpu.reliability import DeadlineExceededError, ReliabilityConfig
from vizier_tpu.reliability.deadline import Deadline
from vizier_tpu.service import vizier_client as vizier_client_lib


class TestDeadline:
    def test_budget_arithmetic(self):
        clock = [100.0]
        deadline = Deadline.from_budget(10.0, clock=lambda: clock[0])
        assert deadline.is_set
        assert deadline.remaining() == pytest.approx(10.0)
        clock[0] = 104.0
        assert deadline.remaining() == pytest.approx(6.0)
        assert deadline.wire_budget() == pytest.approx(6.0)
        assert not deadline.expired
        clock[0] = 111.0
        assert deadline.expired
        assert deadline.wire_budget() == 0.0

    def test_none_never_expires(self):
        deadline = Deadline.none()
        assert not deadline.is_set
        assert deadline.remaining() == float("inf")
        assert deadline.wire_budget() == 0.0
        deadline.check("anything")  # no raise

    def test_zero_budget_means_no_deadline(self):
        assert not Deadline.from_budget(0.0).is_set
        assert not Deadline.from_budget(-1.0).is_set

    def test_check_raises_typed_marked_error(self):
        clock = [0.0]
        deadline = Deadline.from_budget(1.0, clock=lambda: clock[0])
        clock[0] = 2.0
        with pytest.raises(DeadlineExceededError, match="TRANSIENT: DEADLINE_EXCEEDED"):
            deadline.check("the GP train")


class TestServiceDeadline:
    def test_over_budget_computation_completes_op_with_typed_error(self, monkeypatch):
        """A slow designer fails the op at the deadline, not at the 600 s poll."""
        factory = harness.SlowPolicyFactory(delay_secs=0.6)
        servicer, pythia, client = harness.make_stack(
            factory, reliability=ReliabilityConfig(retries=False)
        )
        monkeypatch.setattr(
            vizier_client_lib.environment_variables, "polling_delay_secs", 0.01
        )
        with pytest.raises(RuntimeError) as excinfo:
            client.get_suggestions(1, deadline_secs=0.15)
        message = str(excinfo.value)
        assert "TRANSIENT:" in message
        assert "DEADLINE_EXCEEDED" in message
        assert pythia.serving_stats()["deadline_exceeded"] >= 1
        # The computation ran once; its result was discarded, not returned.
        assert factory.computations == 1

    def test_generous_deadline_succeeds(self, monkeypatch):
        factory = harness.SlowPolicyFactory(delay_secs=0.05)
        servicer, pythia, client = harness.make_stack(
            factory, reliability=ReliabilityConfig()
        )
        monkeypatch.setattr(
            vizier_client_lib.environment_variables, "polling_delay_secs", 0.01
        )
        trials = client.get_suggestions(1, deadline_secs=30.0)
        assert len(trials) == 1
        assert pythia.serving_stats()["deadline_exceeded"] == 0

    def test_deadlines_off_restores_fail_slow_behavior(self, monkeypatch):
        """With deadlines off the op completes normally despite a tiny budget."""
        factory = harness.SlowPolicyFactory(delay_secs=0.1)
        servicer, pythia, client = harness.make_stack(
            factory, reliability=ReliabilityConfig(deadlines=False)
        )
        monkeypatch.setattr(
            vizier_client_lib.environment_variables, "polling_delay_secs", 0.01
        )
        trials = client.get_suggestions(1, deadline_secs=0.01)
        assert len(trials) == 1

    def test_expired_budget_rejected_before_compute(self):
        """A request arriving with zero budget never runs the designer."""
        from vizier_tpu.service.protos import pythia_service_pb2

        factory = harness.SlowPolicyFactory(delay_secs=0.0)
        servicer, pythia, client = harness.make_stack(
            factory, reliability=ReliabilityConfig()
        )
        preq = pythia_service_pb2.PythiaSuggestRequest(
            count=1,
            algorithm="RANDOM_SEARCH",
            study_name=harness.STUDY,
            deadline_secs=1e-9,
        )
        import time

        config_proto = servicer.datastore.load_study(harness.STUDY).study_spec
        preq.study_descriptor.config.CopyFrom(config_proto)
        preq.study_descriptor.guid = harness.STUDY
        time.sleep(0.01)  # the budget has certainly elapsed
        presp = pythia.Suggest(preq)
        assert "DEADLINE_EXCEEDED" in presp.error
        assert factory.computations == 0
