"""RetryPolicy: schedules, classification, client RPC retries, ResponseWaiter."""

import random

import pytest

from vizier_tpu.reliability import (
    DeadlineExceededError,
    ReliabilityConfig,
    RetryPolicy,
    TransientError,
    format_op_error,
    has_transient_marker,
    is_transient_exception,
    mark_transient,
)
from vizier_tpu.reliability.deadline import Deadline
from vizier_tpu.service.pythia_util import ResponseWaiter


class TestClassification:
    @pytest.mark.parametrize(
        "error",
        [
            TransientError("x"),
            DeadlineExceededError("x"),
            TimeoutError("x"),
            ConnectionError("x"),
            RuntimeError("Pythia error: TRANSIENT: TimeoutError: y"),
        ],
    )
    def test_transient(self, error):
        assert is_transient_exception(error)

    @pytest.mark.parametrize(
        "error", [ValueError("bad search space"), RuntimeError("permanent"), KeyError("k")]
    )
    def test_permanent(self, error):
        assert not is_transient_exception(error)

    def test_marker_survives_nesting_and_is_not_doubled(self):
        text = mark_transient("TimeoutError: x")
        assert text.startswith("TRANSIENT:")
        assert mark_transient(text) == text
        wrapped = f"RuntimeError: Pythia error: {text}"
        assert has_transient_marker(wrapped)

    def test_format_op_error(self):
        assert format_op_error(ValueError("bad")) == "ValueError: bad"
        marked = format_op_error(TimeoutError("slow"))
        assert marked == "TRANSIENT: TimeoutError: slow"
        # Already-marked text is not double-prefixed.
        rewrapped = format_op_error(TransientError("TRANSIENT: inner"))
        assert rewrapped.count("TRANSIENT:") == 1


class TestRetryPolicy:
    def test_deterministic_schedule(self):
        a = RetryPolicy(max_attempts=4, base_delay_secs=0.1, max_delay_secs=10.0,
                        rng=random.Random(7))
        b = RetryPolicy(max_attempts=4, base_delay_secs=0.1, max_delay_secs=10.0,
                        rng=random.Random(7))
        assert list(a.delays()) == list(b.delays())

    def test_full_jitter_bounds(self):
        policy = RetryPolicy(max_attempts=10, base_delay_secs=0.1,
                             max_delay_secs=0.5, rng=random.Random(3))
        for attempt, delay in enumerate(policy.delays()):
            assert 0.0 <= delay <= min(0.5, 0.1 * 2**attempt)

    def test_no_jitter_is_pure_exponential_with_cap(self):
        policy = RetryPolicy(max_attempts=5, base_delay_secs=0.1,
                             max_delay_secs=0.4, jitter=False)
        assert list(policy.delays()) == [0.1, 0.2, 0.4, 0.4]

    def test_retries_transient_then_succeeds(self):
        sleeps = []
        policy = RetryPolicy(max_attempts=3, base_delay_secs=0.01,
                             sleep_fn=sleeps.append, rng=random.Random(0))
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionError("flaky")
            return "ok"

        retried = []
        assert policy.call(flaky, on_retry=lambda e, a: retried.append(a)) == "ok"
        assert len(calls) == 3
        assert retried == [0, 1]
        assert len(sleeps) == 2

    def test_permanent_error_not_retried(self):
        policy = RetryPolicy(max_attempts=5, sleep_fn=lambda s: None)
        calls = []

        def broken():
            calls.append(1)
            raise ValueError("permanent")

        with pytest.raises(ValueError):
            policy.call(broken)
        assert len(calls) == 1

    def test_attempts_exhausted_reraises(self):
        policy = RetryPolicy(max_attempts=2, base_delay_secs=0.0,
                             sleep_fn=lambda s: None)
        with pytest.raises(ConnectionError):
            policy.call(lambda: (_ for _ in ()).throw(ConnectionError("down")))

    def test_deadline_stops_retry_loop(self):
        clock = [0.0]
        deadline = Deadline.from_budget(0.05, clock=lambda: clock[0])
        policy = RetryPolicy(max_attempts=5, base_delay_secs=10.0, jitter=False,
                             sleep_fn=lambda s: None)
        calls = []

        def failing():
            calls.append(1)
            raise ConnectionError("down")

        # First retry delay (10 s) exceeds the 0.05 s budget: no retry.
        with pytest.raises(ConnectionError):
            policy.call(failing, deadline=deadline)
        assert len(calls) == 1

    def test_from_config_respects_off_switch(self):
        on = RetryPolicy.from_config(ReliabilityConfig(), seed=0)
        off = RetryPolicy.from_config(ReliabilityConfig.disabled(), seed=0)
        assert on.max_attempts > 1
        assert off.max_attempts == 1


class TestResponseWaiter:
    def test_timeout_names_the_operation(self):
        waiter = ResponseWaiter(operation_name="owners/o/ops/7")
        with pytest.raises(TimeoutError, match="owners/o/ops/7"):
            waiter.WaitForResponse(timeout=0.01)

    def test_timeout_without_name_still_raises(self):
        with pytest.raises(TimeoutError, match="Timed out waiting"):
            ResponseWaiter().WaitForResponse(timeout=0.01)

    def test_cross_thread_error_preserves_traceback_text(self):
        waiter = ResponseWaiter(operation_name="op")

        def compute():
            raise RuntimeError("designer blew up")

        try:
            compute()
        except RuntimeError as e:
            waiter.ReportError(e)

        with pytest.raises(RuntimeError) as excinfo:
            waiter.WaitForResponse(timeout=1)
        message = str(excinfo.value)
        assert "designer blew up" in message
        # The reporting thread's frames survive the hop, and ``from None``
        # suppressed the re-raise context.
        assert "in compute" in message
        assert excinfo.value.__suppress_context__

    def test_report_after_completion_rejected(self):
        waiter = ResponseWaiter()
        waiter.Report("done")
        with pytest.raises(RuntimeError, match="already completed"):
            waiter.Report("again")
        assert waiter.WaitForResponse(timeout=1) == "done"
