"""Fallback + breaker through the full policy path (client → service → Pythia)."""

import pytest

from tests.reliability import harness
from vizier_tpu.reliability import ReliabilityConfig, is_fallback_suggestion
from vizier_tpu.reliability import fallback as fallback_lib
from vizier_tpu.service import vizier_client as vizier_client_lib
from vizier_tpu.testing import failing


@pytest.fixture(autouse=True)
def _fast_polling(monkeypatch):
    monkeypatch.setattr(
        vizier_client_lib.environment_variables, "polling_delay_secs", 0.005
    )


class TestSuggestFallback:
    def test_stamped_and_deterministic_at_a_frontier(self):
        problem = harness.study_config().to_problem()
        a = fallback_lib.suggest_fallback(
            problem, 3, study_name="owners/o/studies/s", max_trial_id=5, reason="r"
        )
        b = fallback_lib.suggest_fallback(
            problem, 3, study_name="owners/o/studies/s", max_trial_id=5, reason="r"
        )
        assert [s.parameters.as_dict() for s in a] == [
            s.parameters.as_dict() for s in b
        ]
        for s in a:
            assert is_fallback_suggestion(s.metadata)
            assert s.metadata.ns("reliability")["fallback_reason"] == "r"

    def test_advances_with_the_frontier(self):
        problem = harness.study_config().to_problem()
        at_0 = fallback_lib.suggest_fallback(
            problem, 1, study_name="s", max_trial_id=0, reason="r"
        )
        at_7 = fallback_lib.suggest_fallback(
            problem, 1, study_name="s", max_trial_id=7, reason="r"
        )
        assert at_0[0].parameters.as_dict() != at_7[0].parameters.as_dict()

    def test_conditional_space_degrades_to_random(self):
        import vizier_tpu.pyvizier as vz

        config = vz.StudyConfig(algorithm="RANDOM_SEARCH")
        root = config.search_space.root
        sel = root.add_categorical_param("model", ["a", "b"])
        sel.select_values(["a"]).add_float_param("lr", 0.0, 1.0)
        config.metric_information.append(
            vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
        suggestions = fallback_lib.suggest_fallback(
            config.to_problem(), 2, study_name="s", max_trial_id=0, reason="r"
        )
        assert len(suggestions) == 2
        assert all(is_fallback_suggestion(s.metadata) for s in suggestions)


class TestAlternateFailingDesignerPolicyPath:
    """Satellite: AlternateFailingDesigner through the full policy path."""

    def _stack(self, reliability):
        from vizier_tpu.designers import random as random_designer

        factory = harness.DesignerPolicyFactory(
            lambda p: failing.AlternateFailingDesigner(
                random_designer.RandomDesigner(p.search_space, seed=0)
            )
        )
        return harness.make_stack(factory, reliability=reliability)

    def test_reliability_off_fails_every_other_suggest(self):
        servicer, pythia, client = self._stack(ReliabilityConfig.disabled())
        # Odd designer calls fail. One fresh designer per request (stateless
        # DesignerPolicy path), so EVERY suggest hits an odd first call.
        with pytest.raises(RuntimeError, match="AlternateFailingDesigner"):
            client.get_suggestions(1)

    def test_fallback_converts_failures_into_quasi_random(self):
        servicer, pythia, client = self._stack(
            ReliabilityConfig(breaker=False)  # isolate the fallback behavior
        )
        for i in range(1, 5):
            (trial,) = client.get_suggestions(1)
            assert trial.id == i
            # Every suggest fails (fresh designer, odd call) and every
            # failure is converted into a marked quasi-random suggestion.
            assert is_fallback_suggestion(trial.metadata)
            harness.complete(client, trial, value=0.1 * i)
        stats = pythia.serving_stats()
        assert stats["designer_failures"] == 4
        assert stats["fallbacks"] == 4

    def test_cached_designer_alternates_through_fallback(self):
        """With a cached (stateful) designer the failures really alternate."""
        from vizier_tpu.designers import random as random_designer

        designers = []

        def designer_factory(problem):
            designers.append(
                failing.AlternateFailingDesigner(
                    random_designer.RandomDesigner(problem.search_space, seed=0)
                )
            )
            return designers[-1]

        class CachingFactory:
            def __call__(self, problem, algorithm, supporter, study_name):
                from vizier_tpu.algorithms import designer_policy

                policy = designer_policy.InRamDesignerPolicy(
                    supporter, designer_factory
                )
                return policy

        servicer, pythia, client = harness.make_stack(
            CachingFactory(), reliability=ReliabilityConfig(breaker=False)
        )
        outcomes = []
        for i in range(1, 7):
            (trial,) = client.get_suggestions(1)
            outcomes.append(is_fallback_suggestion(trial.metadata))
            harness.complete(client, trial)
        # One designer, alternating odd-fail/even-succeed across requests.
        assert len(designers) == 1
        assert outcomes == [True, False, True, False, True, False]


class TestBreakerOnServicePath:
    def test_breaker_opens_short_circuits_and_half_opens(self):
        reliability = ReliabilityConfig(
            breaker_failure_threshold=3,
            breaker_window_secs=60.0,
            breaker_cooldown_secs=0.15,
        )
        factory = harness.DesignerPolicyFactory(
            lambda p: failing.FailingDesigner()
        )
        servicer, pythia, client = harness.make_stack(
            factory, reliability=reliability
        )
        # 3 failures open the breaker (each still served via fallback).
        for _ in range(3):
            (trial,) = client.get_suggestions(1)
            assert is_fallback_suggestion(trial.metadata)
            harness.complete(client, trial)
        stats = pythia.serving_stats()
        assert stats["designer_failures"] == 3
        assert stats["breaker_open_transitions"] == 1
        assert stats["open_breakers"] == 1

        # While open: the designer is not even attempted (short-circuit).
        (trial,) = client.get_suggestions(1)
        assert is_fallback_suggestion(trial.metadata)
        assert trial.metadata.ns("reliability")["fallback_reason"] == "circuit_open"
        harness.complete(client, trial)
        stats = pythia.serving_stats()
        assert stats["breaker_short_circuits"] >= 1
        assert stats["designer_failures"] == 3  # unchanged

        # After the cooldown the breaker half-opens and admits a probe,
        # which fails and re-opens the circuit.
        import time

        time.sleep(0.2)
        (trial,) = client.get_suggestions(1)
        harness.complete(client, trial)
        stats = pythia.serving_stats()
        assert stats["breaker_half_open_transitions"] == 1
        assert stats["designer_failures"] == 4  # the probe ran and failed
        assert stats["breaker_open_transitions"] == 2  # reopened

    def test_breaker_open_without_fallback_errors_transient(self):
        reliability = ReliabilityConfig(
            fallback=False,
            retries=False,
            breaker_failure_threshold=2,
            breaker_cooldown_secs=60.0,
        )
        factory = harness.DesignerPolicyFactory(
            lambda p: failing.FailingDesigner()
        )
        servicer, pythia, client = harness.make_stack(
            factory, reliability=reliability
        )
        for _ in range(2):
            with pytest.raises(RuntimeError):
                client.get_suggestions(1)
        with pytest.raises(RuntimeError, match="CIRCUIT_OPEN"):
            client.get_suggestions(1)
        assert pythia.serving_stats()["breaker_short_circuits"] == 1

    def test_delete_study_resets_breaker(self):
        reliability = ReliabilityConfig(breaker_failure_threshold=1)
        factory = harness.DesignerPolicyFactory(
            lambda p: failing.FailingDesigner()
        )
        servicer, pythia, client = harness.make_stack(
            factory, reliability=reliability
        )
        (trial,) = client.get_suggestions(1)  # opens the breaker
        assert pythia.serving_stats()["open_breakers"] == 1
        client.delete_study()
        assert pythia.serving_stats()["open_breakers"] == 0
