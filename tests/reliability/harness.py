"""Shared fixtures for the reliability/chaos tests: tiny in-process stacks."""

import time

from vizier_tpu import pyvizier as vz
from vizier_tpu.algorithms import designer_policy
from vizier_tpu.pythia import policy as policy_lib
from vizier_tpu.service import proto_converters as pc
from vizier_tpu.service import pythia_service, vizier_client, vizier_service
from vizier_tpu.service.protos import vizier_service_pb2

STUDY = "owners/o/studies/s"


def study_config(algorithm="RANDOM_SEARCH"):
    config = vz.StudyConfig(algorithm=algorithm)
    config.search_space.root.add_float_param("x", 0.0, 1.0)
    config.search_space.root.add_float_param("y", -1.0, 1.0)
    config.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return config


class DesignerPolicyFactory:
    """Routes every algorithm to a DesignerPolicy over ``designer_factory``."""

    def __init__(self, designer_factory):
        self._designer_factory = designer_factory

    def __call__(self, problem, algorithm, supporter, study_name):
        return designer_policy.DesignerPolicy(
            supporter, lambda p, **kw: self._designer_factory(p)
        )


class SlowPolicyFactory:
    """A policy whose suggest sleeps ``delay_secs`` (deadline tests)."""

    def __init__(self, delay_secs):
        self.delay_secs = delay_secs
        self.computations = 0

    def __call__(self, problem, algorithm, supporter, study_name):
        outer = self

        class _Slow(policy_lib.Policy):
            def suggest(self, request):
                outer.computations += 1
                time.sleep(outer.delay_secs)
                return policy_lib.SuggestDecision(
                    suggestions=[
                        vz.TrialSuggestion(parameters={"x": 0.5, "y": 0.0})
                        for _ in range(request.count)
                    ]
                )

        return _Slow()


def make_stack(
    policy_factory=None,
    *,
    reliability=None,
    client_reliability="same",
    config=None,
    client_service=None,
):
    """(servicer, pythia, client) wired in-process around one study.

    ``client_service`` lets callers interpose a chaos stub between client
    and servicer; ``client_reliability="same"`` mirrors the service config.
    """
    servicer = vizier_service.VizierServicer(reliability_config=reliability)
    pythia = pythia_service.PythiaServicer(
        servicer, policy_factory, reliability_config=reliability
    )
    servicer.set_pythia(pythia)
    servicer.CreateStudy(
        vizier_service_pb2.CreateStudyRequest(
            parent="owners/o",
            study=pc.study_to_proto(config or study_config(), STUDY),
        )
    )
    if client_reliability == "same":
        client_reliability = reliability
    client = vizier_client.VizierClient(
        client_service or servicer, STUDY, "c1", reliability=client_reliability
    )
    return servicer, pythia, client


def complete(client, trial, value=1.0):
    client.complete_trial(
        trial.id, vz.Measurement(metrics={"obj": value})
    )
