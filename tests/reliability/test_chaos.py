"""Chaos suite: seeded fault injection end-to-end through the service stack.

Acceptance scenario: with 10% injected designer-failure probability
(seeded), a 50-trial study completes every trial with reliability on —
fallback trials carrying the metadata marker — and fails with reliability
off. Transport and datastore chaos exercise the client retry path.
"""

import pytest

from tests.reliability import harness
from vizier_tpu.reliability import ReliabilityConfig, is_fallback_suggestion
from vizier_tpu.service import vizier_client as vizier_client_lib
from vizier_tpu.testing import chaos
from vizier_tpu.testing import failing


@pytest.fixture(autouse=True)
def _fast_polling(monkeypatch):
    monkeypatch.setattr(
        vizier_client_lib.environment_variables, "polling_delay_secs", 0.005
    )


def _chaos_stack(monkey, reliability, **stack_kwargs):
    from vizier_tpu.designers import random as random_designer

    factory = harness.DesignerPolicyFactory(
        chaos.chaos_designer_factory(
            lambda p, **kw: random_designer.RandomDesigner(p.search_space, seed=0),
            monkey,
        )
    )
    return harness.make_stack(factory, reliability=reliability, **stack_kwargs)


class TestChaosMonkey:
    def test_same_seed_same_fault_sequence(self):
        def pattern(seed):
            monkey = chaos.ChaosMonkey(seed=seed, failure_prob=0.3)
            out = []
            for _ in range(100):
                try:
                    monkey.strike("site")
                    out.append(0)
                except chaos.InjectedFaultError:
                    out.append(1)
            return out

        assert pattern(7) == pattern(7)
        assert pattern(7) != pattern(8)

    def test_fault_rate_tracks_probability(self):
        monkey = chaos.ChaosMonkey(seed=0, failure_prob=0.1)
        faults = 0
        for _ in range(1000):
            try:
                monkey.strike("s")
            except chaos.InjectedFaultError:
                faults += 1
        assert 60 <= faults <= 140  # ~10% with seeded slack
        assert monkey.counts()["s"]["calls"] == 1000
        assert monkey.counts()["s"]["faults"] == faults

    def test_latency_injection_uses_sleep_fn(self):
        slept = []
        monkey = chaos.ChaosMonkey(
            seed=0,
            failure_prob=0.0,
            latency_prob=1.0,
            latency_secs=0.25,
            sleep_fn=slept.append,
        )
        monkey.strike("s")
        assert slept == [0.25]

    def test_zero_prob_never_faults(self):
        monkey = chaos.ChaosMonkey(seed=0, failure_prob=0.0)
        for _ in range(100):
            monkey.strike("s")
        assert monkey.total_faults() == 0


class TestChaosDesigner:
    def test_injected_fault_surfaces_as_designer_failure(self):
        from vizier_tpu.designers import random as random_designer

        problem = harness.study_config().to_problem()
        designer = chaos.ChaosDesigner(
            random_designer.RandomDesigner(problem.search_space, seed=0),
            chaos.ChaosMonkey(seed=0, failure_prob=1.0),
        )
        with pytest.raises(failing.FailedSuggestError, match="designer.suggest"):
            designer.suggest(1)


class TestChaosStudyCompletion:
    """The acceptance scenario (50 trials, 10% designer faults, seeded)."""

    TRIALS = 50
    SEED = 11

    def test_reliability_on_completes_all_trials_with_bounded_fallback(self):
        # Breaker off in this scenario: isolated 10% faults should be
        # absorbed 1:1 by fallback; the breaker's open/half-open behavior
        # under *sustained* failure has its own scenario below.
        monkey = chaos.ChaosMonkey(seed=self.SEED, failure_prob=0.1)
        servicer, pythia, client = _chaos_stack(
            monkey, ReliabilityConfig(breaker=False)
        )
        fallback_trials = 0
        for i in range(1, self.TRIALS + 1):
            (trial,) = client.get_suggestions(1)
            assert trial.id == i
            if is_fallback_suggestion(trial.metadata):
                fallback_trials += 1
            harness.complete(client, trial, value=0.01 * i)

        stats = pythia.serving_stats()
        injected = monkey.counts()["designer.suggest"]["faults"]
        assert injected > 0, "seed produced no faults; scenario is vacuous"
        # Every trial completed; every injected fault became exactly one
        # marked fallback trial; the degradation rate stays bounded.
        assert fallback_trials == injected == stats["fallbacks"]
        assert stats["designer_failures"] == injected
        assert fallback_trials / self.TRIALS <= 0.25

    def test_reliability_off_fails_the_study(self):
        monkey = chaos.ChaosMonkey(seed=self.SEED, failure_prob=0.1)
        servicer, pythia, client = _chaos_stack(
            monkey, ReliabilityConfig.disabled()
        )
        completed = 0
        with pytest.raises(RuntimeError, match="chaos: injected fault"):
            for i in range(1, self.TRIALS + 1):
                (trial,) = client.get_suggestions(1)
                harness.complete(client, trial)
                completed += 1
        assert completed < self.TRIALS
        assert pythia.serving_stats()["fallbacks"] == 0

    def test_sustained_failure_opens_then_half_opens_breaker(self):
        """Breaker lifecycle under 100% faults, via serving_stats()."""
        monkey = chaos.ChaosMonkey(seed=0, failure_prob=1.0)
        reliability = ReliabilityConfig(
            breaker_failure_threshold=3, breaker_cooldown_secs=0.15
        )
        servicer, pythia, client = _chaos_stack(monkey, reliability)
        for _ in range(5):
            (trial,) = client.get_suggestions(1)
            assert is_fallback_suggestion(trial.metadata)
            harness.complete(client, trial)
        stats = pythia.serving_stats()
        assert stats["breaker_open_transitions"] == 1
        assert stats["designer_failures"] == 3  # then short-circuited
        assert stats["breaker_short_circuits"] == 2

        import time

        time.sleep(0.2)  # past the cooldown: next suggest is the probe
        (trial,) = client.get_suggestions(1)
        harness.complete(client, trial)
        stats = pythia.serving_stats()
        assert stats["breaker_half_open_transitions"] == 1
        assert stats["designer_failures"] == 4
        assert stats["breaker_open_transitions"] == 2  # probe failed: reopen


class TestTransportChaos:
    def test_client_retries_absorb_rpc_faults(self):
        monkey = chaos.ChaosMonkey(seed=3, failure_prob=0.15)
        reliability = ReliabilityConfig(retry_base_delay_secs=0.001)
        servicer, pythia, client = _chaos_stack(monkey, reliability)
        flaky = chaos.ChaosServiceStub(servicer, monkey)
        client = vizier_client_lib.VizierClient(
            flaky, harness.STUDY, "c1", reliability=reliability
        )
        for i in range(1, 21):
            (trial,) = client.get_suggestions(1)
            harness.complete(client, trial)
        rpc_faults = sum(
            counts["faults"]
            for site, counts in monkey.counts().items()
            if site.startswith("rpc.")
        )
        assert rpc_faults > 0, "seed produced no transport faults"
        assert pythia.serving_stats()["retries"] >= rpc_faults

    def test_datastore_chaos_is_absorbed_end_to_end(self):
        monkey = chaos.ChaosMonkey(seed=5, failure_prob=0.1)
        reliability = ReliabilityConfig(retry_base_delay_secs=0.001)
        servicer, pythia, client = _chaos_stack(monkey, reliability)
        servicer.datastore = chaos.ChaosDataStore(servicer.datastore, monkey)
        for i in range(1, 16):
            (trial,) = client.get_suggestions(1)
            harness.complete(client, trial)
        datastore_faults = sum(
            counts["faults"]
            for site, counts in monkey.counts().items()
            if site.startswith("datastore.")
        )
        assert datastore_faults > 0, "seed produced no datastore faults"
        assert servicer.datastore.max_trial_id(harness.STUDY) == 15
