"""Circuit breaker: automaton transitions, registry, stats wiring."""

from vizier_tpu.reliability.breaker import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
    CircuitBreakerRegistry,
)
from vizier_tpu.serving import ServingStats


def _breaker(clock, **kwargs):
    defaults = dict(failure_threshold=3, window_secs=60.0, cooldown_secs=30.0)
    defaults.update(kwargs)
    return CircuitBreaker(time_fn=lambda: clock[0], **defaults)


class TestCircuitBreaker:
    def test_opens_after_threshold_within_window(self):
        clock = [0.0]
        breaker = _breaker(clock)
        for _ in range(2):
            breaker.record_failure()
        assert breaker.state == CLOSED
        assert breaker.allow()
        breaker.record_failure()
        assert breaker.state == OPEN
        assert not breaker.allow()

    def test_window_slides(self):
        clock = [0.0]
        breaker = _breaker(clock, window_secs=10.0)
        breaker.record_failure()
        breaker.record_failure()
        clock[0] = 11.0  # first two failures age out of the window
        breaker.record_failure()
        assert breaker.state == CLOSED

    def test_success_clears_window(self):
        clock = [0.0]
        breaker = _breaker(clock)
        breaker.record_failure()
        breaker.record_failure()
        breaker.record_success()
        breaker.record_failure()
        assert breaker.state == CLOSED

    def test_half_open_after_cooldown_then_close_on_success(self):
        clock = [0.0]
        breaker = _breaker(clock)
        for _ in range(3):
            breaker.record_failure()
        assert breaker.state == OPEN
        clock[0] = 29.0
        assert not breaker.allow()
        clock[0] = 31.0
        assert breaker.allow()  # the probe
        assert breaker.state == HALF_OPEN
        assert not breaker.allow()  # only one probe admitted
        breaker.record_success()
        assert breaker.state == CLOSED
        assert breaker.allow()

    def test_half_open_probe_failure_reopens(self):
        clock = [0.0]
        breaker = _breaker(clock)
        for _ in range(3):
            breaker.record_failure()
        clock[0] = 31.0
        assert breaker.allow()
        breaker.record_failure()
        assert breaker.state == OPEN
        # Fresh cooldown from the probe failure.
        clock[0] = 60.0
        assert not breaker.allow()
        clock[0] = 62.0
        assert breaker.allow()


class TestRegistry:
    def test_per_study_isolation(self):
        registry = CircuitBreakerRegistry(failure_threshold=1)
        registry.get("s1").record_failure()
        assert registry.get("s1").state == OPEN
        assert registry.get("s2").state == CLOSED
        assert registry.open_count() == 1
        assert registry.states() == {"s1": OPEN, "s2": CLOSED}

    def test_invalidate_drops_breaker(self):
        registry = CircuitBreakerRegistry(failure_threshold=1)
        registry.get("s1").record_failure()
        assert registry.invalidate("s1")
        assert not registry.invalidate("s1")
        assert registry.get("s1").state == CLOSED  # fresh breaker

    def test_transitions_counted_in_stats(self):
        stats = ServingStats()
        clock = [0.0]
        registry = CircuitBreakerRegistry(
            failure_threshold=1,
            cooldown_secs=5.0,
            stats=stats,
            time_fn=lambda: clock[0],
        )
        breaker = registry.get("s")
        breaker.record_failure()  # closed -> open
        clock[0] = 6.0
        assert breaker.allow()  # open -> half_open (probe)
        breaker.record_success()  # half_open -> closed
        snap = stats.snapshot()
        assert snap["breaker_open_transitions"] == 1
        assert snap["breaker_half_open_transitions"] == 1
        assert snap["breaker_close_transitions"] == 1
