"""Spatio-temporal converter tests."""

import numpy as np

from vizier_tpu import pyvizier as vz
from vizier_tpu.converters import spatio_temporal




class TestDenseConverter:
    def test_interpolated_fixed_grid(self):
        from vizier_tpu.converters import spatio_temporal as st
        from vizier_tpu import pyvizier as vz
        from vizier_tpu.pyvizier import trial as trial_

        metrics = vz.MetricsConfig([vz.MetricInformation(name="loss")])
        extractor = st.TimedLabelsExtractor(metrics)
        conv = st.DenseSpatioTemporalConverter(extractor, num_steps=5)
        t1 = trial_.Trial(id=1, parameters={})
        for s, v in [(0.0, 0.0), (4.0, 4.0)]:
            t1.measurements.append(
                trial_.Measurement(metrics={"loss": v}, steps=s)
            )
        t2 = trial_.Trial(id=2, parameters={})  # no measurements
        values, grid = conv.to_arrays([t1, t2])
        assert values.shape == (2, 5, 1)
        import numpy as np

        np.testing.assert_allclose(values[0, :, 0], [0, 1, 2, 3, 4])
        assert np.isnan(values[1]).all()
        np.testing.assert_allclose(grid, [0, 1, 2, 3, 4])


class TestRoundTwoAdditions:
    def _trial_with_curve(self, i, values, metric="obj"):
        t = vz.Trial(id=i, parameters={"x": 0.5})
        for step, v in enumerate(values):
            t.measurements.append(
                vz.Measurement(metrics={metric: float(v)}, steps=step + 1)
            )
        return t

    def _metrics(self, goal=vz.ObjectiveMetricGoal.MAXIMIZE):
        return vz.MetricsConfig([vz.MetricInformation(name="obj", goal=goal)])

    def test_cummax_mode_is_goal_aware(self):
        t = self._trial_with_curve(1, [1.0, 3.0, 2.0])
        ext = spatio_temporal.TimedLabelsExtractor(self._metrics(), value_mode="cummax")
        np.testing.assert_allclose(
            ext.convert_trial(t).values[:, 0], [1.0, 3.0, 3.0]
        )
        ext_min = spatio_temporal.TimedLabelsExtractor(
            self._metrics(vz.ObjectiveMetricGoal.MINIMIZE), value_mode="cummax"
        )
        np.testing.assert_allclose(
            ext_min.convert_trial(t).values[:, 0], [1.0, 1.0, 1.0]
        )

    def test_invalid_value_mode_raises(self):
        import pytest as _pytest

        with _pytest.raises(ValueError):
            spatio_temporal.TimedLabelsExtractor(self._metrics(), value_mode="bogus")

    def test_extract_all_timestamps_and_normalize(self):
        trials = [
            self._trial_with_curve(1, [1.0, 2.0]),
            self._trial_with_curve(2, [5.0, 6.0, 7.0]),
        ]
        ext = spatio_temporal.TimedLabelsExtractor(self._metrics())
        stamps = ext.extract_all_timestamps(trials)
        np.testing.assert_allclose(stamps, [1.0, 2.0, 3.0])
        np.testing.assert_allclose(ext.to_timestamps(stamps), [1 / 3, 2 / 3, 1.0])

    def test_dense_to_xty(self):
        space = vz.SearchSpace()
        space.root.add_float_param("x", 0.0, 1.0)
        space.root.add_categorical_param("c", ["a", "b"])
        trials = []
        for i in range(3):
            t = vz.Trial(id=i + 1, parameters={"x": 0.25 * i, "c": "a"})
            for step in range(4):
                t.measurements.append(
                    vz.Measurement(metrics={"obj": float(step + i)}, steps=step + 1)
                )
            trials.append(t)
        conv = spatio_temporal.DenseSpatioTemporalConverter(
            spatio_temporal.TimedLabelsExtractor(self._metrics()), num_steps=8
        )
        x, t_stamps, y = conv.to_xty(trials, space)
        assert x.shape == (3, 2) and y.shape == (3, 8, 1)
        assert t_stamps.shape == (8,)
        assert t_stamps[-1] == 1.0 and np.all(np.diff(t_stamps) > 0)
        # Curves are monotone per construction; interpolation keeps them so.
        assert np.all(np.diff(y[:, :, 0], axis=1) >= -1e-9)
