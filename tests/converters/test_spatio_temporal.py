

class TestDenseConverter:
    def test_interpolated_fixed_grid(self):
        from vizier_tpu.converters import spatio_temporal as st
        from vizier_tpu import pyvizier as vz
        from vizier_tpu.pyvizier import trial as trial_

        metrics = vz.MetricsConfig([vz.MetricInformation(name="loss")])
        extractor = st.TimedLabelsExtractor(metrics)
        conv = st.DenseSpatioTemporalConverter(extractor, num_steps=5)
        t1 = trial_.Trial(id=1, parameters={})
        for s, v in [(0.0, 0.0), (4.0, 4.0)]:
            t1.measurements.append(
                trial_.Measurement(metrics={"loss": v}, steps=s)
            )
        t2 = trial_.Trial(id=2, parameters={})  # no measurements
        values, grid = conv.to_arrays([t1, t2])
        assert values.shape == (2, 5, 1)
        import numpy as np

        np.testing.assert_allclose(values[0, :, 0], [0, 1, 2, 3, 4])
        assert np.isnan(values[1]).all()
        np.testing.assert_allclose(grid, [0, 1, 2, 3, 4])
