"""Converter round-trip fuzz over randomly generated flat search spaces.

Reference analog: ``converters/core_test.py``'s per-type round-trip checks,
generalized into a property test — for arbitrary mixes of DOUBLE (linear/
log/reverse-log), INTEGER, DISCRETE, and CATEGORICAL parameters,
encode → decode must reproduce every trial's parameters exactly (exact for
discrete types, to float tolerance for doubles), and the encoded matrices
must stay inside the scaled unit ranges the GP stack assumes.
"""

import numpy as np
import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.algorithms import random_sample
from vizier_tpu.converters import core as converters


def _random_space(rng: np.random.Generator, num_params: int) -> vz.SearchSpace:
    space = vz.SearchSpace()
    root = space.root
    for i in range(num_params):
        kind = rng.integers(0, 4)
        name = f"p{i}"
        if kind == 0:
            lo = float(rng.uniform(-10, 0))
            hi = lo + float(rng.uniform(0.5, 20))
            scale = rng.choice(
                [vz.ScaleType.LINEAR, vz.ScaleType.LOG, vz.ScaleType.REVERSE_LOG]
            )
            if scale != vz.ScaleType.LINEAR:
                lo = float(rng.uniform(1e-4, 1.0))
                hi = lo * float(rng.uniform(10.0, 1e4))
            root.add_float_param(name, lo, hi, scale_type=scale)
        elif kind == 1:
            lo = int(rng.integers(-20, 10))
            hi = lo + int(rng.integers(1, 30))
            root.add_int_param(name, lo, hi)
        elif kind == 2:
            num = int(rng.integers(2, 6))
            vals = sorted(float(v) for v in rng.uniform(-5, 5, size=num))
            root.add_discrete_param(name, vals)
        else:
            num = int(rng.integers(2, 6))
            root.add_categorical_param(name, [f"c{j}" for j in range(num)])
    return space


@pytest.mark.parametrize("seed", range(8))
def test_encode_decode_roundtrip(seed):
    rng = np.random.default_rng(seed)
    space = _random_space(rng, num_params=int(rng.integers(2, 7)))
    enc = converters.SearchSpaceEncoder(space)
    trials = [
        vz.Trial(id=i + 1, parameters=random_sample.sample_parameters(rng, space))
        for i in range(17)
    ]
    cont, cat = enc.encode(trials)

    assert cont.shape == (17, enc.num_continuous)
    assert cat.shape == (17, enc.num_categorical)
    # Scaled continuous features live in [0, 1] (the GP's assumed range).
    if enc.num_continuous:
        assert cont.min() >= -1e-9 and cont.max() <= 1.0 + 1e-9

    decoded = enc.decode(cont, cat)
    assert len(decoded) == len(trials)
    for t, params in zip(trials, decoded):
        for config in space.parameters:
            orig = t.parameters.get_value(config.name)
            back = params.get_value(config.name)
            if config.type == vz.ParameterType.DOUBLE:
                lo, hi = config.bounds
                assert back == pytest.approx(orig, abs=1e-4 * (hi - lo) + 1e-9)
            else:
                assert back == orig, (config.name, config.type, orig, back)
        space.assert_contains(params)


@pytest.mark.parametrize("seed", range(4))
def test_decode_arbitrary_unit_rows_stay_in_space(seed):
    """Any point of the unit model space must decode to a feasible trial."""
    rng = np.random.default_rng(100 + seed)
    space = _random_space(rng, num_params=4)
    enc = converters.SearchSpaceEncoder(space)
    cont = rng.uniform(size=(25, enc.num_continuous))
    sizes = enc.category_sizes
    cat = np.stack(
        [rng.integers(0, s, size=25) for s in sizes], axis=-1
    ) if sizes else np.zeros((25, 0), np.int32)
    for params in enc.decode(cont, cat):
        space.assert_contains(params)


def test_max_discrete_indices_moves_small_ints_to_categorical():
    space = vz.SearchSpace()
    space.root.add_int_param("small", 0, 3)      # 4 values <= threshold
    space.root.add_int_param("large", 0, 100)    # stays continuous
    space.root.add_discrete_param("disc", [0.1, 0.7])
    enc = converters.SearchSpaceEncoder(space, max_discrete_indices=5)
    assert enc.num_categorical == 2  # small + disc
    assert enc.num_continuous == 1   # large
    t = vz.Trial(id=1, parameters={"small": 2, "large": 40, "disc": 0.7})
    cont, cat = enc.encode([t])
    (params,) = enc.decode(cont, cat)
    assert params.get_value("small") == 2
    assert params.get_value("large") == 40
    assert params.get_value("disc") == 0.7


def test_log_scaling_is_monotone_and_covers_unit_interval():
    space = vz.SearchSpace()
    space.root.add_float_param("lr", 1e-5, 1.0, scale_type=vz.ScaleType.LOG)
    enc = converters.SearchSpaceEncoder(space)
    raws = [1e-5, 1e-4, 1e-2, 1.0]
    trials = [vz.Trial(id=i + 1, parameters={"lr": v}) for i, v in enumerate(raws)]
    cont, _ = enc.encode(trials)
    col = cont[:, 0]
    assert col[0] == pytest.approx(0.0, abs=1e-6)
    assert col[-1] == pytest.approx(1.0, abs=1e-6)
    assert np.all(np.diff(col) > 0)
    # Equal log-space steps must land equally spaced in scaled space.
    assert col[1] - col[0] == pytest.approx(0.2, abs=1e-3)
