"""Tests for trial⇄array converters and padded types."""

import numpy as np
import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu import types
from vizier_tpu.converters import core as converters
from vizier_tpu.converters import padding as padding_lib


def _problem():
    p = vz.ProblemStatement()
    root = p.search_space.root
    root.add_float_param("lin", 0.0, 10.0)
    root.add_float_param("log", 1e-4, 1e-1, scale_type=vz.ScaleType.LOG)
    root.add_int_param("n", 1, 5)
    root.add_discrete_param("d", [1, 4, 9])
    root.add_categorical_param("c", ["a", "b", "z"])
    p.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return p


def _trial(i, **params):
    t = vz.Trial(id=i, parameters=params)
    return t


class TestSearchSpaceEncoder:
    def test_shapes_and_specs(self):
        enc = converters.SearchSpaceEncoder(_problem().search_space)
        assert enc.num_continuous == 4
        assert enc.num_categorical == 1
        assert enc.category_sizes == [3]
        assert enc.onehot_dim == 4 + 3

    def test_encode_ranges(self):
        enc = converters.SearchSpaceEncoder(_problem().search_space)
        trials = [
            _trial(1, lin=0.0, log=1e-4, n=1, d=1, c="a"),
            _trial(2, lin=10.0, log=1e-1, n=5, d=9, c="z"),
            _trial(3, lin=5.0, log=1e-2, n=3, d=4, c="b"),
        ]
        cont, cat = enc.encode(trials)
        assert cont.shape == (3, 4)
        assert cat.shape == (3, 1)
        np.testing.assert_allclose(cont[0], [0.0, 0.0, 0.0, 0.0], atol=1e-9)
        np.testing.assert_allclose(cont[1], [1.0, 1.0, 1.0, 1.0], atol=1e-9)
        # log param: 1e-2 is 2/3 of the way from 1e-4 to 1e-1 in log space.
        np.testing.assert_allclose(cont[2, 1], 2.0 / 3.0, atol=1e-9)
        assert list(cat[:, 0]) == [0, 2, 1]

    def test_roundtrip(self):
        space = _problem().search_space
        enc = converters.SearchSpaceEncoder(space)
        trials = [
            _trial(1, lin=3.3, log=5e-3, n=4, d=9, c="b"),
            _trial(2, lin=0.1, log=2e-4, n=1, d=1, c="a"),
        ]
        cont, cat = enc.encode(trials)
        decoded = enc.decode(cont, cat)
        for t, params in zip(trials, decoded):
            assert space.contains(params)
            assert params.get_value("n") == t.parameters.get_value("n")
            assert params.get_value("d") == t.parameters.get_value("d")
            assert params.get_value("c") == t.parameters.get_value("c")
            np.testing.assert_allclose(
                params.get_value("lin"), t.parameters.get_value("lin"), rtol=1e-6
            )
            np.testing.assert_allclose(
                params.get_value("log"), t.parameters.get_value("log"), rtol=1e-5
            )

    def test_decode_snaps_and_clips(self):
        enc = converters.SearchSpaceEncoder(_problem().search_space)
        cont = np.array([[1.7, -0.3, 0.49, 0.4]])
        cat = np.array([[99]])
        (params,) = enc.decode(cont, cat)
        assert params.get_value("lin") == 10.0  # clipped
        assert params.get_value("log") == pytest.approx(1e-4)
        assert params.get_value("n") == 3  # 1 + 0.49*4 = 2.96 -> round 3
        assert params.get_value("d") == 4.0  # nearest feasible to 0.4*8+1=4.2
        assert params.get_value("c") == "z"  # clipped to last index

    def test_onehot_roundtrip(self):
        enc = converters.SearchSpaceEncoder(_problem().search_space)
        trials = [_trial(1, lin=2.0, log=1e-3, n=2, d=4, c="b")]
        flat = enc.onehot_encode(trials)
        assert flat.shape == (1, enc.onehot_dim)
        assert flat[0, 4:].tolist() == [0.0, 1.0, 0.0]
        cont, cat = enc.onehot_to_split(flat)
        (params,) = enc.decode(cont, cat)
        assert params.get_value("c") == "b"

    def test_conditional_rejected(self):
        s = vz.SearchSpace()
        sel = s.root.add_categorical_param("m", ["a", "b"])
        sel.select_values(["a"]).add_float_param("x", 0, 1)
        with pytest.raises(ValueError):
            converters.SearchSpaceEncoder(s)

    def test_max_discrete_indices(self):
        s = vz.SearchSpace()
        s.root.add_int_param("small", 1, 3)
        s.root.add_int_param("big", 1, 100)
        enc = converters.SearchSpaceEncoder(s, max_discrete_indices=10)
        assert enc.num_continuous == 1
        assert enc.num_categorical == 1
        assert enc.category_sizes == [3]
        (params,) = enc.decode(np.array([[0.5]]), np.array([[2]]))
        assert params.get_value("small") == 3
        assert params.get_value("big") == 50


class TestMetricsEncoder:
    def test_sign_flip_and_nan(self):
        mc = vz.MetricsConfig(
            [
                vz.MetricInformation(name="up", goal=vz.ObjectiveMetricGoal.MAXIMIZE),
                vz.MetricInformation(name="down", goal=vz.ObjectiveMetricGoal.MINIMIZE),
            ]
        )
        enc = converters.MetricsEncoder(mc)
        t1 = vz.Trial(id=1)
        t1.complete(vz.Measurement(metrics={"up": 1.0, "down": 2.0}))
        t2 = vz.Trial(id=2)  # not completed
        t3 = vz.Trial(id=3)
        t3.complete(vz.Measurement(metrics={"up": 5.0}))  # missing 'down'
        labels = enc.encode([t1, t2, t3])
        np.testing.assert_allclose(labels[0], [1.0, -2.0])
        assert np.isnan(labels[1]).all()
        assert labels[2][0] == 5.0 and np.isnan(labels[2][1])
        back = enc.decode(labels)
        np.testing.assert_allclose(back[0], [1.0, 2.0])


class TestPaddedArray:
    def test_from_array_and_masks(self):
        pa = types.PaddedArray.from_array(np.arange(6.0).reshape(2, 3), (4, 3))
        assert pa.shape == (4, 3)
        assert pa.valid_mask(0).tolist() == [True, True, False, False]
        assert pa.valid_mask(1).tolist() == [True, True, True]
        assert int(pa.num_valid(0)) == 2
        np.testing.assert_array_equal(pa.unpad(), np.arange(6.0).reshape(2, 3))

    def test_replace_fill_value(self):
        pa = types.PaddedArray.from_array(np.ones((2, 2)), (3, 2), fill_value=0.0)
        pa2 = pa.replace_fill_value(-5.0)
        assert pa2.padded_array[2, 0] == -5.0
        assert pa2.padded_array[0, 0] == 1.0

    def test_pad_down_rejected(self):
        with pytest.raises(ValueError):
            types.PaddedArray.from_array(np.ones((4, 2)), (2, 2))

    def test_pytree(self):
        import jax

        pa = types.PaddedArray.from_array(np.ones((2, 2)), (4, 2))
        mapped = jax.tree_util.tree_map(lambda x: x * 2, pa)
        assert mapped.padded_array[0, 0] == 2.0

    def test_joint_mask(self):
        pa = types.PaddedArray.from_array(np.ones((2, 2)), (3, 4))
        m = pa.joint_valid_mask()
        assert m.shape == (3, 4)
        assert bool(m[1, 1]) and not bool(m[2, 1]) and not bool(m[1, 3])


class TestPadding:
    def test_powers_of_2(self):
        p = padding_lib.PaddingType.POWERS_OF_2
        assert p.pad(0) == 8
        assert p.pad(7) == 8
        assert p.pad(9) == 16
        assert p.pad(1000) == 1024

    def test_multiples_of_10(self):
        p = padding_lib.PaddingType.MULTIPLES_OF_10
        assert p.pad(1) == 10
        assert p.pad(11) == 20

    def test_stable_jit_shapes(self):
        """Growing trials within one bucket must not change padded shapes."""
        problem = _problem()
        conv = converters.TrialToModelInputConverter.from_problem(problem)
        trials = []
        for i in range(1, 9):
            t = _trial(i, lin=1.0, log=1e-3, n=2, d=4, c="a")
            t.complete(vz.Measurement(metrics={"obj": float(i)}))
            trials.append(t)
        shapes = set()
        for k in (5, 6, 7, 8):
            data = conv.to_xy(trials[:k])
            shapes.add(
                (
                    data.features.continuous.shape,
                    data.features.categorical.shape,
                    data.labels.shape,
                )
            )
        assert len(shapes) == 1  # all in the 8-bucket


class TestTrialToModelInputConverter:
    def test_to_xy(self):
        problem = _problem()
        conv = converters.TrialToModelInputConverter.from_problem(problem)
        trials = []
        for i in range(3):
            t = _trial(i + 1, lin=float(i), log=1e-3, n=2, d=4, c="a")
            t.complete(vz.Measurement(metrics={"obj": float(i)}))
            trials.append(t)
        data = conv.to_xy(trials)
        assert data.features.continuous.shape == (8, 4)
        assert data.features.categorical.shape == (8, 1)
        assert data.labels.shape == (8, 1)
        assert int(data.labels.num_valid(0)) == 3
        # Padded label rows are NaN-filled.
        assert np.isnan(np.asarray(data.labels.padded_array)[3:]).all()


class TestTrialToArrayConverter:
    def test_roundtrip(self):
        problem = _problem()
        conv = converters.TrialToArrayConverter.from_study_config(problem)
        t = _trial(1, lin=2.0, log=1e-3, n=2, d=4, c="b")
        t.complete(vz.Measurement(metrics={"obj": 3.0}))
        feats, labels = conv.to_xy([t])
        assert feats.shape == (1, conv.output_dim)
        assert labels[0, 0] == 3.0
        (params,) = conv.to_parameters(feats)
        assert problem.search_space.contains(params)
        assert params.get_value("c") == "b"


class TestReviewRegressions:
    """Regressions from the second code review."""

    def test_wide_int_range_encodes_fast(self):
        s = vz.SearchSpace()
        s.root.add_int_param("seed", 0, 50_000_000)
        enc = converters.SearchSpaceEncoder(s)
        cont, _ = enc.encode([_trial(i, seed=i * 1000) for i in range(3)])
        assert cont.shape == (3, 1)

    def test_decode_1d_continuous(self):
        s = vz.SearchSpace()
        s.root.add_float_param("x", 0.0, 1.0)
        enc = converters.SearchSpaceEncoder(s)
        out = enc.decode(np.array([0.1, 0.5, 0.9]), np.zeros((3, 0)))
        assert [round(p.get_value("x"), 2) for p in out] == [0.1, 0.5, 0.9]

    def test_decode_row_mismatch_raises(self):
        s = vz.SearchSpace()
        s.root.add_float_param("x", 0.0, 1.0)
        s.root.add_categorical_param("c", ["a", "b"])
        enc = converters.SearchSpaceEncoder(s)
        with pytest.raises(ValueError, match="Row mismatch"):
            enc.decode(np.zeros((2, 1)), np.zeros((3, 1)))

    def test_unknown_category_raises(self):
        s = vz.SearchSpace()
        s.root.add_categorical_param("c", ["a", "b"])
        enc = converters.SearchSpaceEncoder(s)
        with pytest.raises(ValueError, match="not a known category"):
            enc.encode([_trial(1, c="zzz")])

    def test_bool_param_contains_python_bool(self):
        s = vz.SearchSpace()
        s.root.add_bool_param("b")
        assert s.contains({"b": True})

    def test_complete_not_inplace_deep_copies(self):
        t = vz.Trial(id=1)
        t.measurements.append(vz.Measurement(metrics={"m": 1.0}))
        t2 = t.complete(inplace=False)
        assert t.measurements is not t2.measurements

    def test_metadata_mutable_mapping(self):
        md = vz.Metadata()
        md["k"] = "v"
        assert md.pop("k") == "v"
        assert md.setdefault("j", "w") == "w"
        md.clear()
        assert len(md) == 0
