"""Tests for spatio-temporal converters and the cross-problem scaler."""

import numpy as np
import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.converters.embedder import ProblemAndTrialsScaler
from vizier_tpu.converters.spatio_temporal import (
    SparseSpatioTemporalConverter,
    TimedLabelsExtractor,
)


def _metrics():
    return vz.MetricsConfig([vz.MetricInformation(name="acc")])


class TestTimedLabels:
    def _trial_with_curve(self, steps_values):
        t = vz.Trial(id=1, parameters={})
        for s, v in steps_values:
            t.measurements.append(vz.Measurement(metrics={"acc": v}, steps=s))
        return t

    def test_extract(self):
        extractor = TimedLabelsExtractor(_metrics())
        curve = extractor.convert_trial(
            self._trial_with_curve([(1, 0.1), (2, 0.2), (4, 0.4)])
        )
        np.testing.assert_array_equal(curve.positions, [1, 2, 4])
        np.testing.assert_allclose(curve.values[:, 0], [0.1, 0.2, 0.4])

    def test_missing_metric_is_nan(self):
        extractor = TimedLabelsExtractor(_metrics())
        t = vz.Trial(id=1, parameters={})
        t.measurements.append(vz.Measurement(metrics={"other": 1.0}, steps=1))
        curve = extractor.convert_trial(t)
        assert np.isnan(curve.values[0, 0])

    def test_aligned_grid_carry_forward(self):
        converter = SparseSpatioTemporalConverter(TimedLabelsExtractor(_metrics()))
        a = self._trial_with_curve([(1, 0.1), (3, 0.3)])
        b = self._trial_with_curve([(2, 0.5)])
        values, mask, grid = converter.to_arrays([a, b])
        np.testing.assert_array_equal(grid, [1, 2, 3])
        # Trial a: carries 0.1 forward at step 2.
        np.testing.assert_allclose(values[0, :, 0], [0.1, 0.1, 0.3])
        # Trial b starts at step 2; step 1 is masked out.
        assert not mask[1, 0] and mask[1, 1]
        np.testing.assert_allclose(values[1, 1:, 0], [0.5, 0.5])


class TestProblemAndTrialsScaler:
    def test_maps_prior_trials(self):
        current = vz.ProblemStatement()
        root = current.search_space.root
        root.add_float_param("lr", 1e-4, 1e-2, scale_type=vz.ScaleType.LOG)
        root.add_int_param("layers", 1, 4)
        root.add_categorical_param("opt", ["adam", "sgd"])
        current.metric_information.append(vz.MetricInformation(name="acc"))

        # Prior trial from a wider/looser space with an extra param and an
        # unknown category.
        prior = vz.Trial(
            id=7,
            parameters={"lr": 0.5, "layers": 9, "opt": "rmsprop", "extra": 3},
        )
        prior.complete(vz.Measurement(metrics={"acc": 0.8}))
        scaler = ProblemAndTrialsScaler(current)
        (mapped,) = scaler.map_trials([prior])
        assert mapped.parameters.get_value("lr") == pytest.approx(1e-2)  # clipped
        assert mapped.parameters.get_value("layers") == 4  # clipped
        assert mapped.parameters.get_value("opt") == "adam"  # unknown -> default
        assert "extra" not in mapped.parameters
        assert current.search_space.contains(mapped.parameters)
        assert mapped.final_measurement.metrics["acc"].value == 0.8

    def test_missing_param_takes_default(self):
        current = vz.ProblemStatement()
        current.search_space.root.add_float_param("x", 0.0, 1.0)
        current.search_space.root.add_float_param("y", 0.0, 1.0, default_value=0.25)
        current.metric_information.append(vz.MetricInformation(name="m"))
        prior = vz.Trial(id=1, parameters={"x": 0.5})
        prior.complete(vz.Measurement(metrics={"m": 1.0}))
        (mapped,) = ProblemAndTrialsScaler(current).map_trials([prior])
        assert mapped.parameters.get_value("y") == 0.25
