"""Tests for stacked-residual transfer learning, multi-objective GP, profiler."""

import datetime

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu import types
from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.models import gp as gp_lib
from vizier_tpu.models import kernels, stacked_residual
from vizier_tpu.optimizers import lbfgs as lbfgs_lib
from vizier_tpu.utils import profiler


def _data(xs, ys, n_pad=None):
    xs = np.asarray(xs, np.float32).reshape(-1, 1)
    ys = np.asarray(ys, np.float32)
    n_pad = n_pad or len(xs)
    features = types.ContinuousAndCategorical(
        continuous=types.PaddedArray.from_array(xs, (n_pad, 1)),
        categorical=types.PaddedArray.from_array(
            np.zeros((len(xs), 0), np.int32), (n_pad, 0), fill_value=0
        ),
    )
    labels = types.PaddedArray.from_array(
        ys[:, None], (n_pad, 1), fill_value=np.nan
    )
    return gp_lib.GPData.from_model_data(types.ModelData(features, labels))


class TestStackedResidualGP:
    def test_prior_informs_sparse_current_data(self):
        model = gp_lib.VizierGaussianProcess(num_continuous=1, num_categorical=0)
        f = lambda x: np.sin(6 * x)
        prior_x = np.linspace(0, 1, 20)
        prior = _data(prior_x, f(prior_x))
        current_x = np.array([0.1, 0.9])
        current = _data(current_x, f(current_x))
        stack = stacked_residual.train_stacked_residual_gp(
            model,
            lbfgs_lib.AdamOptimizer(maxiter=60),
            [prior, current],
            jax.random.PRNGKey(0),
            num_restarts=4,
        )
        query_x = np.linspace(0.2, 0.8, 7).astype(np.float32)
        query = kernels.MixedFeatures(
            jnp.asarray(query_x[:, None]), jnp.zeros((7, 0), jnp.int32)
        )
        mean, stddev = stack.predict(query)
        # With only 2 current points, accuracy must come from the prior.
        np.testing.assert_allclose(np.asarray(mean), f(query_x), atol=0.35)
        assert (np.asarray(stddev) > 0).all()

    def test_single_level_equals_plain_gp(self):
        model = gp_lib.VizierGaussianProcess(num_continuous=1, num_categorical=0)
        data = _data(np.linspace(0, 1, 8), np.linspace(-1, 1, 8))
        stack = stacked_residual.train_stacked_residual_gp(
            model,
            lbfgs_lib.AdamOptimizer(maxiter=30),
            [data],
            jax.random.PRNGKey(0),
            num_restarts=2,
        )
        assert len(stack.levels) == 1
        q = kernels.MixedFeatures(
            jnp.asarray([[0.5]], jnp.float32), jnp.zeros((1, 0), jnp.int32)
        )
        mean, stddev = stack.predict(q)
        assert mean.shape == (1,) and stddev.shape == (1,)


class TestMultiObjectiveGPBandit:
    def test_hv_scalarized_suggest(self):
        from vizier_tpu.designers.gp_bandit import VizierGPBandit

        p = vz.ProblemStatement()
        p.search_space.root.add_float_param("x", 0.0, 1.0)
        p.metric_information.append(
            vz.MetricInformation(name="f1", goal=vz.ObjectiveMetricGoal.MINIMIZE)
        )
        p.metric_information.append(
            vz.MetricInformation(name="f2", goal=vz.ObjectiveMetricGoal.MINIMIZE)
        )
        d = VizierGPBandit(
            p,
            max_acquisition_evaluations=300,
            ard_restarts=2,
            num_seed_trials=3,
            ard_optimizer=lbfgs_lib.AdamOptimizer(maxiter=20),
        )
        trials = []
        for i, x in enumerate(np.linspace(0, 1, 5)):
            t = vz.Trial(id=i + 1, parameters={"x": float(x)})
            t.complete(vz.Measurement(metrics={"f1": x**2, "f2": (x - 1) ** 2}))
            trials.append(t)
        d.update(core_lib.CompletedTrials(trials))
        suggestions = d.suggest(2)
        assert len(suggestions) == 2
        assert (
            suggestions[0].metadata.ns("gp_bandit")["acquisition_kind"]
            == "hv_scalarized_ucb"
        )

    def test_set_priors_transfer(self):
        from vizier_tpu.designers.gp_bandit import VizierGPBandit

        p = vz.ProblemStatement()
        p.search_space.root.add_float_param("x", -1.0, 1.0)
        p.metric_information.append(
            vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
        f = lambda x: -((x - 0.3) ** 2)
        prior = []
        rng = np.random.default_rng(0)
        for i in range(12):
            x = float(rng.uniform(-1, 1))
            t = vz.Trial(id=i + 1, parameters={"x": x})
            t.complete(vz.Measurement(metrics={"obj": f(x)}))
            prior.append(t)
        d = VizierGPBandit(
            p,
            max_acquisition_evaluations=300,
            ard_restarts=2,
            num_seed_trials=2,
            ard_optimizer=lbfgs_lib.AdamOptimizer(maxiter=30),
        )
        d.set_priors([prior])
        current = []
        for i, x in enumerate([-0.7, 0.6]):
            t = vz.Trial(id=i + 1, parameters={"x": x})
            t.complete(vz.Measurement(metrics={"obj": f(x)}))
            current.append(t)
        d.update(core_lib.CompletedTrials(current))
        suggestions = d.suggest(2)
        kinds = {s.metadata.ns("gp_bandit")["acquisition_kind"] for s in suggestions}
        assert kinds == {"ucb+priors"}


class TestEarlyStoppingPolicy:
    def _study_config(self):
        config = vz.StudyConfig()
        config.search_space.root.add_float_param("x", 0.0, 1.0)
        config.metric_information.append(
            vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
        return config

    def test_median_rule(self):
        from vizier_tpu.algorithms.early_stopping import MedianEarlyStopPolicy
        from vizier_tpu.pythia import local_policy_supporters
        from vizier_tpu.pythia import policy as policy_lib

        config = self._study_config()
        supporter = local_policy_supporters.InRamPolicySupporter(config)

        def add_curve(values):
            t = vz.Trial(parameters={"x": 0.5})
            for step, v in enumerate(values, start=1):
                t.measurements.append(
                    vz.Measurement(metrics={"obj": v}, steps=step)
                )
            supporter.AddTrials([t])
            return supporter.trials[-1].id

        for _ in range(3):
            add_curve([0.5, 0.7, 0.9])
        laggard = add_curve([0.05, 0.06])
        healthy = add_curve([0.8, 0.95])
        policy = MedianEarlyStopPolicy(supporter, min_num_trials=3)
        decisions = policy.early_stop(
            policy_lib.EarlyStopRequest(
                study_descriptor=supporter.study_descriptor(),
                trial_ids=frozenset([laggard, healthy]),
            )
        )
        by_id = {d.id: d.should_stop for d in decisions.decisions}
        assert by_id[laggard] is True
        assert by_id[healthy] is False

    def test_too_few_trials_no_stop(self):
        from vizier_tpu.algorithms.early_stopping import MedianEarlyStopPolicy
        from vizier_tpu.pythia import local_policy_supporters
        from vizier_tpu.pythia import policy as policy_lib

        supporter = local_policy_supporters.InRamPolicySupporter(self._study_config())
        t = vz.Trial(parameters={"x": 0.5})
        t.measurements.append(vz.Measurement(metrics={"obj": 0.1}, steps=1))
        supporter.AddTrials([t])
        policy = MedianEarlyStopPolicy(supporter, min_num_trials=5)
        decisions = policy.early_stop(
            policy_lib.EarlyStopRequest(
                study_descriptor=supporter.study_descriptor(),
                trial_ids=frozenset([1]),
            )
        )
        assert decisions.decisions[0].should_stop is False


class TestProfiler:
    def test_timeit_and_nested_scopes(self):
        with profiler.collect_events() as events:
            with profiler.timeit("outer"):
                with profiler.timeit("inner"):
                    pass
        latencies = profiler.get_latencies_dict(events)
        assert "outer" in latencies
        assert "outer::inner" in latencies
        assert latencies["outer"][0] >= latencies["outer::inner"][0]

    def test_record_runtime_decorator(self):
        @profiler.record_runtime(name="myfn", block_until_ready=True)
        def fn(x):
            import jax.numpy as jnp

            return jnp.asarray(x) * 2

        with profiler.collect_events() as events:
            fn(3.0)
        assert "myfn" in profiler.get_latencies_dict(events)

    def test_record_tracing_counts(self):
        @profiler.record_tracing(name="traced")
        def body(x):
            return x + 1

        fn = jax.jit(body)
        with profiler.collect_events() as events:
            fn(jnp.zeros(3))
            fn(jnp.ones(3))  # cache hit: no retrace
            fn(jnp.zeros(4))  # new shape: retrace
        counts = profiler.get_tracing_counts(events)
        assert counts.get("traced") == 2

    def test_disabled_outside_collect(self):
        with profiler.timeit("ignored"):
            pass
        with profiler.collect_events() as events:
            pass
        assert events == []


class TestReviewRegressions:
    """Regressions from the seventh code review."""

    def test_gp_ucb_pe_routes_multiobjective(self):
        from vizier_tpu.designers.gp_ucb_pe import VizierGPUCBPEBandit

        p = vz.ProblemStatement()
        p.search_space.root.add_float_param("x", 0.0, 1.0)
        p.metric_information.append(
            vz.MetricInformation(name="f1", goal=vz.ObjectiveMetricGoal.MINIMIZE)
        )
        p.metric_information.append(
            vz.MetricInformation(name="f2", goal=vz.ObjectiveMetricGoal.MINIMIZE)
        )
        d = VizierGPUCBPEBandit(
            p,
            max_acquisition_evaluations=300,
            ard_restarts=2,
            num_seed_trials=3,
            ard_optimizer=lbfgs_lib.AdamOptimizer(maxiter=20),
        )
        trials = []
        for i, x in enumerate(np.linspace(0, 1, 5)):
            t = vz.Trial(id=i + 1, parameters={"x": float(x)})
            t.complete(vz.Measurement(metrics={"f1": x**2, "f2": (x - 1) ** 2}))
            trials.append(t)
        d.update(core_lib.CompletedTrials(trials))
        (s, _) = d.suggest(2)
        # Multi-objective studies are handled NATIVELY by the default
        # algorithm (HV-scalarized UCB + scalarized PE penalties), not routed
        # away to the GP-bandit path.
        assert "use_ucb" in s.metadata.ns("gp_ucb_pe")

    def test_safety_warp_clears_measurement(self):
        from vizier_tpu.pyvizier import multimetric

        metrics = vz.MetricsConfig(
            [
                vz.MetricInformation(name="obj"),
                vz.MetricInformation(name="safe", safety_threshold=0.5),
            ]
        )
        checker = multimetric.SafetyChecker(metrics)
        t = vz.Trial(id=1)
        t.complete(vz.Measurement(metrics={"obj": 99.0, "safe": 0.0}))
        checker.warp_unsafe_trials([t])
        assert t.infeasible
        # Measurement data is preserved for analyzers/safety checks...
        assert t.final_measurement is not None
        # ...but label encoders see NaN for it.
        from vizier_tpu.converters import core as conv

        enc = conv.MetricsEncoder(metrics)
        assert np.isnan(enc.encode([t])).all()
