"""GP numerics vs an independent float64 numpy oracle.

Reference test strategy analog: ``stochastic_process_model_test.py`` checks
the GP stack against closed-form expectations. Here a from-scratch float64
numpy GP (same Matern-5/2 ARD + categorical index distance + noise/jitter
semantics) is the oracle; the f32 TPU-path implementation must agree to
f32 tolerance on mean, stddev, joint covariance, and the log-likelihood —
with and without padded rows, which must be exactly invisible.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from vizier_tpu import types
from vizier_tpu.models import gp as gp_lib
from vizier_tpu.models import kernels

_SQRT5 = np.sqrt(5.0)
_JITTER = 1e-5
_LOG_2PI = float(np.log(2.0 * np.pi))


def _oracle_kernel(x1, z1, x2, z2, amp, cont_ls, cat_ls):
    """float64 ARD Matern-5/2 over mixed features (index mismatch distance)."""
    sq = np.zeros((x1.shape[0], x2.shape[0]))
    if x1.shape[1]:
        diff = (x1[:, None, :] - x2[None, :, :]) / cont_ls[None, None, :]
        sq = sq + np.sum(diff * diff, axis=-1)
    if z1.shape[1]:
        mism = (z1[:, None, :] != z2[None, :, :]).astype(float)
        sq = sq + np.sum(mism / (cat_ls[None, None, :] ** 2), axis=-1)
    r = np.sqrt(np.maximum(sq, 1e-20))
    return amp**2 * (1.0 + _SQRT5 * r + (5.0 / 3.0) * sq) * np.exp(-_SQRT5 * r)


class _Oracle:
    """Exact float64 GP posterior + marginal likelihood."""

    def __init__(self, x, z, y, amp, noise, cont_ls, cat_ls):
        self.x, self.z = x, z
        self.amp, self.cont_ls, self.cat_ls = amp, cont_ls, cat_ls
        k = _oracle_kernel(x, z, x, z, amp, cont_ls, cat_ls)
        self.gram = k + (noise**2 + _JITTER) * np.eye(len(x))
        self.alpha = np.linalg.solve(self.gram, y)
        self.y = y

    def predict(self, qx, qz):
        ks = _oracle_kernel(qx, qz, self.x, self.z, self.amp, self.cont_ls, self.cat_ls)
        mean = ks @ self.alpha
        kqq = _oracle_kernel(qx, qz, qx, qz, self.amp, self.cont_ls, self.cat_ls)
        cov = kqq - ks @ np.linalg.solve(self.gram, ks.T)
        return mean, cov

    def nll(self):
        sign, logdet = np.linalg.slogdet(self.gram)
        assert sign > 0
        return 0.5 * (
            self.y @ self.alpha + logdet + len(self.y) * _LOG_2PI
        )


def _make_data(x, z, y, n_pad):
    features = types.ContinuousAndCategorical(
        continuous=types.PaddedArray.from_array(
            x.astype(np.float32), (n_pad, x.shape[1])
        ),
        categorical=types.PaddedArray.from_array(
            z.astype(np.int32), (n_pad, z.shape[1]), fill_value=0
        ),
    )
    labels = types.PaddedArray.from_array(
        y[:, None].astype(np.float32), (n_pad, 1), fill_value=np.nan
    )
    return gp_lib.GPData.from_model_data(types.ModelData(features, labels))


def _constrained_params(model, amp, noise, cont_ls, cat_ls):
    p = {"amplitude": jnp.asarray(amp, jnp.float32),
         "noise_stddev": jnp.asarray(noise, jnp.float32)}
    if model.num_continuous:
        p["continuous_length_scales"] = jnp.asarray(cont_ls, jnp.float32)
    if model.num_categorical:
        p["categorical_length_scales"] = jnp.asarray(cat_ls, jnp.float32)
    return p


@pytest.fixture(params=[(6, 3, 0, 8), (7, 2, 2, 8), (5, 0, 3, 16)])
def case(request):
    n, dc, ds, n_pad = request.param
    rng = np.random.default_rng(n * 100 + dc * 10 + ds)
    x = rng.uniform(size=(n, dc))
    z = rng.integers(0, 3, size=(n, ds))
    y = rng.normal(size=n)
    amp, noise = 1.3, 0.1
    cont_ls = rng.uniform(0.3, 1.5, size=dc)
    cat_ls = rng.uniform(0.5, 2.0, size=ds)
    oracle = _Oracle(x, z, y, amp, noise, cont_ls, cat_ls)
    model = gp_lib.VizierGaussianProcess(num_continuous=dc, num_categorical=ds)
    data = _make_data(x, z, y, n_pad)
    params = _constrained_params(model, amp, noise, cont_ls, cat_ls)
    state = model.precompute_constrained(params, data)
    qx = rng.uniform(size=(9, dc))
    qz = rng.integers(0, 3, size=(9, ds))
    query = kernels.MixedFeatures(
        jnp.asarray(qx, jnp.float32), jnp.asarray(qz, jnp.int32)
    )
    return oracle, model, params, data, state, qx, qz, query


class TestPosteriorVsOracle:
    def test_mean_and_stddev(self, case):
        oracle, _, _, _, state, qx, qz, query = case
        mean, stddev = state.predict(query)
        o_mean, o_cov = oracle.predict(qx, qz)
        np.testing.assert_allclose(np.asarray(mean), o_mean, atol=2e-3)
        np.testing.assert_allclose(
            np.asarray(stddev), np.sqrt(np.maximum(np.diag(o_cov), 1e-12)),
            atol=2e-3,
        )

    def test_joint_covariance(self, case):
        oracle, _, _, _, state, qx, qz, query = case
        mean, cov = state.predict_joint(query)
        o_mean, o_cov = oracle.predict(qx, qz)
        np.testing.assert_allclose(np.asarray(mean), o_mean, atol=2e-3)
        # The implementation adds 1e-6 jitter on the diagonal.
        np.testing.assert_allclose(
            np.asarray(cov), o_cov + 1e-6 * np.eye(len(qx)), atol=5e-3
        )
        eigs = np.linalg.eigvalsh(np.asarray(cov))
        assert eigs.min() > -1e-5

    def test_nll_matches_oracle_plus_regularizer(self, case):
        oracle, model, params, data, _, _, _, _ = case
        coll = model.param_collection()
        unconstrained = coll.unconstrain(params)
        loss = float(model.neg_log_likelihood(unconstrained, data))
        # The ARD loss = exact NLL + log-normal regularization; recover the
        # regularizer from the roundtripped constrained params.
        reg = float(coll.regularization(coll.constrain(unconstrained)))
        assert loss - reg == pytest.approx(oracle.nll(), abs=5e-2)

    def test_padding_rows_are_invisible(self, case):
        oracle, model, params, _, _, qx, qz, query = case
        # Same data at two padded capacities must give identical posteriors.
        n = len(oracle.y)
        data_a = _make_data(oracle.x, oracle.z, oracle.y, n_pad=n)
        data_b = _make_data(oracle.x, oracle.z, oracle.y, n_pad=4 * n)
        sa = model.precompute_constrained(params, data_a)
        sb = model.precompute_constrained(params, data_b)
        ma, va = sa.predict(query)
        mb, vb = sb.predict(query)
        # f32 reduction order differs with the padded Gram size; a mask
        # leak would show up at ~1e-1, not 1e-4.
        np.testing.assert_allclose(np.asarray(ma), np.asarray(mb), atol=1e-4)
        np.testing.assert_allclose(np.asarray(va), np.asarray(vb), atol=1e-4)

    def test_include_noise_adds_noise_variance(self, case):
        _, _, params, _, state, _, _, query = case
        _, s_noiseless = state.predict(query)
        _, s_noisy = state.predict(query, include_noise=True)
        noise_sq = float(params["noise_stddev"]) ** 2
        np.testing.assert_allclose(
            np.asarray(s_noisy) ** 2 - np.asarray(s_noiseless) ** 2,
            np.full(s_noisy.shape, noise_sq),
            atol=1e-4,
        )


class TestKernelProperties:
    def test_gram_is_psd_under_random_params(self):
        rng = np.random.default_rng(0)
        for trial in range(5):
            n, dc, ds = 12, 3, 2
            x = rng.uniform(size=(n, dc)).astype(np.float32)
            z = rng.integers(0, 4, size=(n, ds)).astype(np.int32)
            k = kernels.matern52_ard(
                kernels.MixedFeatures(jnp.asarray(x), jnp.asarray(z)),
                kernels.MixedFeatures(jnp.asarray(x), jnp.asarray(z)),
                amplitude=jnp.asarray(float(rng.uniform(0.1, 3.0))),
                continuous_length_scales=jnp.asarray(
                    rng.uniform(0.1, 2.0, size=dc), jnp.float32
                ),
                categorical_length_scales=jnp.asarray(
                    rng.uniform(0.3, 3.0, size=ds), jnp.float32
                ),
            )
            eigs = np.linalg.eigvalsh(np.asarray(k, np.float64))
            assert eigs.min() > -1e-4, eigs.min()

    def test_kernel_diagonal_is_amplitude_squared(self):
        x = jnp.asarray(np.random.default_rng(1).uniform(size=(5, 3)), jnp.float32)
        f = kernels.MixedFeatures(x, jnp.zeros((5, 0), jnp.int32))
        k = kernels.matern52_ard(
            f, f,
            amplitude=jnp.asarray(2.0),
            continuous_length_scales=jnp.ones((3,)),
            categorical_length_scales=jnp.ones((0,)),
        )
        np.testing.assert_allclose(np.diag(np.asarray(k)), 4.0, atol=1e-4)

    def test_ard_relevance_recovery(self):
        """ARD training shrinks the length scale of the active dim only."""
        from vizier_tpu.designers.gp_bandit import _train_gp
        from vizier_tpu.optimizers import lbfgs as lbfgs_lib

        rng = np.random.default_rng(7)
        n, dc = 48, 3
        x = rng.uniform(size=(n, dc))
        y = np.sin(7.0 * x[:, 0])  # only dim 0 matters
        y = (y - y.mean()) / y.std()
        model = gp_lib.VizierGaussianProcess(num_continuous=dc, num_categorical=0)
        data = _make_data(x, np.zeros((n, 0), np.int64), y, n_pad=64)
        states = _train_gp(
            model, lbfgs_lib.LbfgsOptimizer(maxiter=60), data,
            jax.random.PRNGKey(0), num_restarts=4, ensemble_size=1,
        )
        ls = np.asarray(states.params["continuous_length_scales"])[0]
        # The active dim needs a materially shorter length scale than the
        # two inert dims.
        assert ls[0] < 0.6 * ls[1] and ls[0] < 0.6 * ls[2], ls
