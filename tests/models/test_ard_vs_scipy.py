"""Cross-checks the pure-JAX ARD L-BFGS against scipy's L-BFGS-B.

The reference trains ARD with scipy's driver (jaxopt_wrappers.py); this
project replaced it with a hand-rolled two-loop L-BFGS to stay on-device.
This test runs BOTH optimizers on the same GP negative-log-likelihood from
the same starts: the JAX optimizer's best loss must match or beat scipy's
within a small tolerance, and the resulting posteriors must agree.
Determinism of the whole train path is asserted as well.
"""

import numpy as np
import pytest
import scipy.optimize

import jax
import jax.numpy as jnp

from vizier_tpu import types
from vizier_tpu.models import gp as gp_lib
from vizier_tpu.optimizers import lbfgs as lbfgs_lib


def _data(n=24, dc=3, seed=0, n_pad=32):
    rng = np.random.default_rng(seed)
    x = rng.uniform(size=(n, dc))
    y = np.sin(5 * x[:, 0]) + 0.5 * x[:, 1] + 0.05 * rng.normal(size=n)
    y = (y - y.mean()) / y.std()
    features = types.ContinuousAndCategorical(
        continuous=types.PaddedArray.from_array(x.astype(np.float32), (n_pad, dc)),
        categorical=types.PaddedArray.from_array(
            np.zeros((n, 0), np.int32), (n_pad, 0), fill_value=0
        ),
    )
    labels = types.PaddedArray.from_array(
        y[:, None].astype(np.float32), (n_pad, 1), fill_value=np.nan
    )
    return gp_lib.GPData.from_model_data(types.ModelData(features, labels))


class TestArdVsScipy:
    def test_matches_or_beats_scipy_from_same_starts(self):
        model = gp_lib.VizierGaussianProcess(num_continuous=3, num_categorical=0)
        data = _data()
        coll = model.param_collection()
        loss_fn = lambda u: model.neg_log_likelihood(u, data)

        inits = coll.batch_random_init_unconstrained(jax.random.PRNGKey(0), 4)
        leaves, treedef = jax.tree_util.tree_flatten(
            jax.tree_util.tree_map(lambda a: a[0], inits)
        )
        sizes = [int(np.asarray(l).size) for l in leaves]
        shapes = [np.asarray(l).shape for l in leaves]

        def flat_to_tree(z):
            out, i = [], 0
            for size, shape in zip(sizes, shapes):
                out.append(jnp.asarray(z[i : i + size], jnp.float32).reshape(shape))
                i += size
            return jax.tree_util.tree_unflatten(treedef, out)

        vg = jax.jit(jax.value_and_grad(loss_fn))

        def scipy_obj(z):
            v, g = vg(flat_to_tree(z))
            gflat = np.concatenate(
                [np.asarray(l, np.float64).ravel() for l in jax.tree_util.tree_leaves(g)]
            )
            return float(v), gflat

        scipy_best = np.inf
        for r in range(4):
            z0 = np.concatenate(
                [
                    np.asarray(l[r], np.float64).ravel()
                    for l in jax.tree_util.tree_flatten(inits)[0]
                ]
            )
            res = scipy.optimize.minimize(
                scipy_obj, z0, jac=True, method="L-BFGS-B",
                options={"maxiter": 80},
            )
            scipy_best = min(scipy_best, float(res.fun))

        opt = lbfgs_lib.LbfgsOptimizer(maxiter=80)
        result = opt(loss_fn, inits, best_n=1)
        ours_best = float(np.asarray(result.best_loss).ravel()[0])

        # Same model, same starts: the on-device optimizer must land within
        # a whisker of (or below) the scipy reference optimum.
        assert ours_best <= scipy_best + 0.15, (ours_best, scipy_best)

    def test_train_path_is_deterministic(self):
        from vizier_tpu.designers.gp_bandit import _train_gp

        model = gp_lib.VizierGaussianProcess(num_continuous=3, num_categorical=0)
        data = _data()
        opt = lbfgs_lib.LbfgsOptimizer(maxiter=30)
        s1 = _train_gp(model, opt, data, jax.random.PRNGKey(7), 4, 1)
        s2 = _train_gp(model, opt, data, jax.random.PRNGKey(7), 4, 1)
        for a, b in zip(
            jax.tree_util.tree_leaves(s1.params), jax.tree_util.tree_leaves(s2.params)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
