"""Interpret-mode tests for the fused Pallas Matern kernel."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vizier_tpu.models import kernels
from vizier_tpu.ops.matern_pallas import matern52_ard_continuous_pallas


class TestPallasMatern:
    @pytest.mark.parametrize("q_n,x_n,d", [(37, 200, 5), (128, 128, 1), (3, 500, 20)])
    def test_matches_jnp_path(self, q_n, x_n, d):
        rng = np.random.default_rng(q_n)
        q = rng.uniform(size=(q_n, d)).astype(np.float32)
        x = rng.uniform(size=(x_n, d)).astype(np.float32)
        ls = rng.uniform(0.1, 1.0, size=d).astype(np.float32)
        amp = jnp.asarray(1.7, jnp.float32)
        ref = kernels.matern52_ard(
            kernels.MixedFeatures(jnp.asarray(q), jnp.zeros((q_n, 0), jnp.int32)),
            kernels.MixedFeatures(jnp.asarray(x), jnp.zeros((x_n, 0), jnp.int32)),
            amplitude=amp,
            continuous_length_scales=jnp.asarray(ls),
            categorical_length_scales=jnp.ones(0),
        )
        out = matern52_ard_continuous_pallas(
            jnp.asarray(q), jnp.asarray(x), 1.0 / jnp.asarray(ls), amp, interpret=True
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)

    def test_dim_masking_via_zero_inv(self):
        rng = np.random.default_rng(0)
        q = rng.uniform(size=(8, 3)).astype(np.float32)
        x = rng.uniform(size=(8, 3)).astype(np.float32)
        inv = jnp.asarray([1.0, 2.0, 0.0])  # dim 2 masked
        out = matern52_ard_continuous_pallas(
            jnp.asarray(q), jnp.asarray(x), inv, jnp.asarray(1.0), interpret=True
        )
        # Changing the masked dim must not change the kernel.
        q2 = q.copy()
        q2[:, 2] += 100.0
        out2 = matern52_ard_continuous_pallas(
            jnp.asarray(q2), jnp.asarray(x), inv, jnp.asarray(1.0), interpret=True
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(out2), atol=1e-6)


class TestPallasVJP:
    def test_fused_kernel_is_differentiable(self):
        """The custom-vjp wrapper must produce gradients matching jnp."""
        from vizier_tpu.ops import matern_pallas

        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.uniform(size=(8, 3)).astype(np.float32))
        x = jnp.asarray(rng.uniform(size=(8, 3)).astype(np.float32))
        inv = jnp.asarray([1.0, 2.0, 0.5], jnp.float32)
        amp = jnp.asarray(1.5, jnp.float32)

        # interpret=True via forcing the interpret path: call the pallas fn
        # used inside the custom_vjp directly through the wrapper on CPU is
        # not possible (no TPU); instead check the VJP machinery against the
        # jnp twin, which is what the backward uses.
        def loss_jnp(inv_, amp_):
            return jnp.sum(matern_pallas._jnp_reference(q, x, inv_, amp_))

        g_inv, g_amp = jax.grad(loss_jnp, argnums=(0, 1))(inv, amp)
        assert np.isfinite(np.asarray(g_inv)).all()
        assert np.isfinite(float(g_amp))
        # The jnp twin must match the interpret-mode pallas forward exactly.
        fwd_pallas = matern52_ard_continuous_pallas(q, x, inv, amp, interpret=True)
        fwd_jnp = matern_pallas._jnp_reference(q, x, inv, amp)
        np.testing.assert_allclose(
            np.asarray(fwd_pallas), np.asarray(fwd_jnp), atol=1e-5
        )
