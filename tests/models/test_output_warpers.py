"""Property tests for the output-warper suite.

Mirrors the reference's ``output_warpers_test.py`` coverage: finiteness,
rank preservation, edge cases (all-equal, all-NaN), outlier removal,
gaussianization, and warp→unwarp round-trips.
"""

import numpy as np
import pytest

from vizier_tpu.models import output_warpers


def _rand_labels(n=25, seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(n, 1)) * 10.0 + 3.0


class TestDefaultPipeline:
    def test_finite_and_rank_preserving(self):
        rng = np.random.default_rng(1)
        y = rng.normal(size=(40, 1)) * 100
        out = output_warpers.create_default_warper().warp(y.copy())
        assert np.isfinite(out).all()
        # Rank order of finite labels is preserved.
        assert (np.argsort(out[:, 0]) == np.argsort(y[:, 0])).all()

    def test_all_equal_labels_map_to_zero(self):
        w = output_warpers.create_default_warper()
        out = w.warp(np.full((7, 1), 3.25))
        np.testing.assert_array_equal(out, np.zeros((7, 1)))
        # Unwarp shifts back by the constant — including for non-sentinel
        # inputs (GP samples around 0), which must not crash.
        np.testing.assert_allclose(w.unwarp(out), np.full((7, 1), 3.25))
        samples = np.array([[0.3], [-0.1], [0.0]])
        np.testing.assert_allclose(w.unwarp(samples), samples + 3.25)

    def test_all_nan_unwarp_of_arbitrary_values(self):
        w = output_warpers.create_default_warper()
        w.warp(np.full((3, 1), np.nan))
        out = w.unwarp(np.array([[0.5], [-1.0]]))
        assert np.isnan(out).all()

    def test_all_nan_labels_map_to_minus_one(self):
        w = output_warpers.create_default_warper()
        out = w.warp(np.full((4, 1), np.nan))
        np.testing.assert_array_equal(out, -np.ones((4, 1)))
        assert np.isnan(w.unwarp(out)).all()

    def test_neg_inf_treated_as_infeasible(self):
        w = output_warpers.create_default_warper()
        y = np.array([[1.0], [-np.inf], [2.0]])
        out = w.warp(y)
        assert np.isfinite(out).all()
        assert out[1, 0] == out.min()

    def test_pos_inf_rejected(self):
        w = output_warpers.create_default_warper()
        with pytest.raises(ValueError):
            w.warp(np.array([[1.0], [np.inf]]))

    def test_outlier_compressed(self):
        y = np.concatenate([_rand_labels(30, 2), [[-1e20]]], axis=0)
        out = output_warpers.create_default_warper().warp(y)
        assert np.isfinite(out).all()
        # Warped range is bounded (log warp maps into ~[-0.5, 0.5] + shift).
        assert out.max() - out.min() < 10.0

    def test_unwarp_round_trip_on_warped_labels(self):
        w = output_warpers.create_default_warper()
        y = _rand_labels(20, 3)
        warped = w.warp(y.copy())
        back = w.unwarp(warped)
        np.testing.assert_allclose(back, y, rtol=1e-4, atol=1e-6)


class TestLogWarper:
    def test_range_and_roundtrip(self):
        w = output_warpers.LogWarper()
        y = _rand_labels(15, 4)
        out = w.warp(y.copy())
        assert (out >= -0.5 - 1e-9).all() and (out <= 0.5 + 1e-9).all()
        np.testing.assert_allclose(w.unwarp(out), y, rtol=1e-6)

    def test_best_value_maps_to_half(self):
        w = output_warpers.LogWarper()
        y = np.array([[1.0], [5.0], [9.0]])
        out = w.warp(y)
        assert out[2, 0] == pytest.approx(0.5)
        assert out[0, 0] == pytest.approx(-0.5)

    def test_nan_passthrough(self):
        w = output_warpers.LogWarper()
        out = w.warp(np.array([[1.0], [np.nan], [2.0]]))
        assert np.isnan(out[1, 0])


class TestHalfRank:
    def test_good_half_untouched(self):
        w = output_warpers.HalfRankWarper()
        y = np.array([[0.0], [1.0], [2.0], [3.0], [-1000.0]])
        out = w.warp(y.copy())
        np.testing.assert_allclose(out[2:4], y[2:4])
        assert out.min() > -100

    def test_unwarp_recovers_observed_values(self):
        w = output_warpers.HalfRankWarper()
        y = _rand_labels(21, 5)
        warped = w.warp(y.copy())
        back = w.unwarp(warped)
        np.testing.assert_allclose(back, y, rtol=1e-5, atol=1e-7)

    def test_unwarp_extrapolates_below_image(self):
        w = output_warpers.HalfRankWarper()
        y = _rand_labels(21, 6)
        warped = w.warp(y.copy())
        below = np.full((1, 1), warped.min() - 1.0)
        back = w.unwarp(below)
        assert back[0, 0] < y.min()


class TestInfeasibleWarper:
    def test_infeasible_worse_than_all_feasible(self):
        w = output_warpers.InfeasibleWarper()
        out = w.warp(np.array([[1.0], [np.nan], [3.0]]))
        assert np.isfinite(out).all()
        assert out[1, 0] == out.min()

    def test_unwarp_restores_feasible(self):
        w = output_warpers.InfeasibleWarper()
        y = np.array([[1.0], [np.nan], [3.0]])
        out = w.warp(y.copy())
        back = w.unwarp(out)
        np.testing.assert_allclose(back[[0, 2], 0], [1.0, 3.0], rtol=1e-9)

    def test_all_nan_maps_to_zero(self):
        w = output_warpers.InfeasibleWarper()
        out = w.warp(np.full((3, 1), np.nan))
        np.testing.assert_array_equal(out, np.zeros((3, 1)))

    def test_frequency_weighted_mean_is_zero(self):
        """The documented invariant: shift applies to imputed rows too, so
        the warped column's mean is exactly zero (GP zero-mean prior)."""
        w = output_warpers.InfeasibleWarper()
        out = w.warp(np.array([[0.0], [2.0], [np.nan], [np.nan]]))
        np.testing.assert_allclose(out[:, 0], [0.5, 2.5, -1.5, -1.5])
        # p_feasible = 2.5/5 = 0.5 → weighted mean = 0.5*1.5 + 0.5*(-1.5).
        p = 2.5 / 5.0
        assert p * np.mean(out[:2, 0]) + (1 - p) * out[2, 0] == pytest.approx(0.0)

    def test_unwarp_inverts_imputed_rows(self):
        w = output_warpers.InfeasibleWarper()
        y = np.array([[0.0], [2.0], [np.nan]])
        out = w.warp(y.copy())
        back = w.unwarp(out)
        np.testing.assert_allclose(back[:2, 0], [0.0, 2.0])
        # Imputed row unwarps back to the raw bad value (lo - (range/2 + 1)).
        assert back[2, 0] == pytest.approx(-2.0)


class TestDetectOutliers:
    def test_extreme_bad_value_removed(self):
        y = np.concatenate([_rand_labels(30, 7), [[-1e6]]], axis=0)
        out = output_warpers.DetectOutliers().warp(y.copy())
        assert np.isnan(out[-1, 0])
        assert np.isfinite(out[:-1]).all()

    def test_normal_values_kept(self):
        y = _rand_labels(30, 8)
        out = output_warpers.DetectOutliers().warp(y.copy())
        assert np.isfinite(out).all()

    def test_small_sample_estimator(self):
        y = np.concatenate([_rand_labels(8, 9), [[-1e8]]], axis=0)
        out = output_warpers.DetectOutliers().warp(y.copy())
        assert np.isnan(out[-1, 0])


class TestTransformToGaussian:
    def test_output_roughly_standard_normal(self):
        y = np.exp(_rand_labels(200, 10) / 5.0)  # heavily skewed
        out = output_warpers.TransformToGaussian(use_rank=True).warp(y.copy())
        assert np.isfinite(out).all()
        assert abs(np.mean(out)) < 0.5
        assert 0.3 < np.std(out) < 3.0

    def test_rank_preserved(self):
        y = _rand_labels(50, 11)
        out = output_warpers.TransformToGaussian().warp(y.copy())
        assert (np.argsort(out[:, 0]) == np.argsort(y[:, 0])).all()


class TestWarpOutliersPipeline:
    def test_outliers_become_infeasible_then_finite(self):
        y = np.concatenate([_rand_labels(30, 12), [[-1e30]]], axis=0)
        out = output_warpers.create_warp_outliers_warper().warp(y.copy())
        assert np.isfinite(out).all()
        # The outlier lands at the bottom of the warped scale.
        assert out[-1, 0] == out.min()


class TestNormalizeLabels:
    def test_maps_to_unit_interval(self):
        w = output_warpers.NormalizeLabels()
        y = _rand_labels(10, 13)
        out = w.warp(y.copy())
        assert out.min() == pytest.approx(0.0)
        assert out.max() == pytest.approx(1.0)
        np.testing.assert_allclose(w.unwarp(out), y, rtol=1e-9)

    def test_all_equal_to_midpoint(self):
        w = output_warpers.NormalizeLabels(target_interval=(-1.0, 1.0))
        out = w.warp(np.full((5, 1), 7.0))
        np.testing.assert_array_equal(out, np.zeros((5, 1)))
