"""Tests for kernels, GP likelihood/predictive, masking, and warpers."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vizier_tpu import types
from vizier_tpu.models import gp as gp_lib
from vizier_tpu.models import kernels
from vizier_tpu.models import output_warpers
from vizier_tpu.models import params as params_lib
from vizier_tpu.optimizers import lbfgs as lbfgs_lib


def _feats(cont, cat=None):
    cont = jnp.asarray(cont, jnp.float32)
    if cat is None:
        cat = jnp.zeros((cont.shape[0], 0), jnp.int32)
    return kernels.MixedFeatures(cont, jnp.asarray(cat, jnp.int32))


class TestKernels:
    def test_matern52_at_zero(self):
        assert float(kernels.matern52(jnp.asarray(0.0))) == pytest.approx(1.0)

    def test_ard_diagonal_is_amplitude_sq(self):
        f = _feats(np.random.default_rng(0).uniform(size=(5, 3)))
        k = kernels.matern52_ard(
            f, f,
            amplitude=jnp.asarray(2.0),
            continuous_length_scales=jnp.ones(3),
            categorical_length_scales=jnp.ones(0),
        )
        np.testing.assert_allclose(np.diag(k), 4.0, rtol=1e-5)
        np.testing.assert_allclose(k, k.T, rtol=1e-5)

    def test_categorical_mismatch_reduces_kernel(self):
        f1 = _feats(np.zeros((1, 1)), np.array([[0]]))
        f2 = _feats(np.zeros((1, 1)), np.array([[1]]))
        kw = dict(
            amplitude=jnp.asarray(1.0),
            continuous_length_scales=jnp.ones(1),
            categorical_length_scales=jnp.ones(1),
        )
        same = kernels.matern52_ard(f1, f1, **kw)[0, 0]
        diff = kernels.matern52_ard(f1, f2, **kw)[0, 0]
        assert float(same) == pytest.approx(1.0)
        assert float(diff) < float(same)

    def test_dim_mask_ignores_padded_dims(self):
        rng = np.random.default_rng(1)
        base = rng.uniform(size=(4, 2)).astype(np.float32)
        junk = rng.uniform(size=(4, 1)).astype(np.float32)
        padded = np.concatenate([base, junk], axis=1)
        kw = dict(amplitude=jnp.asarray(1.0), categorical_length_scales=jnp.ones(0))
        k_base = kernels.matern52_ard(
            _feats(base), _feats(base),
            continuous_length_scales=jnp.ones(2), **kw,
        )
        k_masked = kernels.matern52_ard(
            _feats(padded), _feats(padded),
            continuous_length_scales=jnp.ones(3),
            continuous_dim_mask=jnp.asarray([True, True, False]),
            **kw,
        )
        np.testing.assert_allclose(k_base, k_masked, rtol=1e-5)


def _make_data(n, n_pad, seed=0, dc=2):
    rng = np.random.default_rng(seed)
    x = rng.uniform(size=(n, dc)).astype(np.float32)
    y = np.sin(3 * x[:, 0]) + 0.1 * rng.normal(size=n)
    features = types.ContinuousAndCategorical(
        continuous=types.PaddedArray.from_array(x, (n_pad, dc)),
        categorical=types.PaddedArray.from_array(
            np.zeros((n, 0), np.int32), (n_pad, 0), fill_value=0
        ),
    )
    labels = types.PaddedArray.from_array(
        y[:, None].astype(np.float32), (n_pad, 1), fill_value=np.nan
    )
    return gp_lib.GPData.from_model_data(types.ModelData(features, labels))


class TestGPMasking:
    def test_padding_invariance_of_loss(self):
        """The load-bearing property: padding must not change the likelihood."""
        model = gp_lib.VizierGaussianProcess(num_continuous=2, num_categorical=0)
        coll = model.param_collection()
        params = coll.random_init_unconstrained(jax.random.PRNGKey(0))
        tight = _make_data(10, 10)
        padded = _make_data(10, 32)
        l1 = float(model.neg_log_likelihood(params, tight))
        l2 = float(model.neg_log_likelihood(params, padded))
        assert l1 == pytest.approx(l2, rel=1e-4)

    def test_padding_invariance_of_predictions(self):
        model = gp_lib.VizierGaussianProcess(num_continuous=2, num_categorical=0)
        coll = model.param_collection()
        params = coll.random_init_unconstrained(jax.random.PRNGKey(1))
        query = _feats(np.array([[0.2, 0.8], [0.5, 0.5]], np.float32))
        m1, s1 = model.precompute(params, _make_data(10, 10)).predict(query)
        m2, s2 = model.precompute(params, _make_data(10, 64)).predict(query)
        np.testing.assert_allclose(m1, m2, rtol=1e-3, atol=1e-5)
        np.testing.assert_allclose(s1, s2, rtol=1e-3, atol=1e-5)

    def test_interpolation_at_observed_points(self):
        model = gp_lib.VizierGaussianProcess(num_continuous=2, num_categorical=0)
        data = _make_data(12, 16, seed=3)
        coll = model.param_collection()
        # Small noise setting → near-interpolation.
        constrained = {
            "amplitude": jnp.asarray(1.0),
            "noise_stddev": jnp.asarray(1e-3),
            "continuous_length_scales": jnp.full((2,), 0.3),
        }
        params = coll.unconstrain(constrained)
        state = model.precompute(params, data)
        query = kernels.MixedFeatures(data.continuous[:12], data.categorical[:12])
        mean, stddev = state.predict(query)
        np.testing.assert_allclose(mean, data.labels[:12], atol=0.05)
        assert np.all(np.asarray(stddev) < 0.1)

    def test_uncertainty_grows_away_from_data(self):
        model = gp_lib.VizierGaussianProcess(num_continuous=2, num_categorical=0)
        data = _make_data(10, 16)
        params = model.param_collection().unconstrain(
            {
                "amplitude": jnp.asarray(1.0),
                "noise_stddev": jnp.asarray(0.01),
                "continuous_length_scales": jnp.full((2,), 0.1),
            }
        )
        state = model.precompute(params, data)
        near = kernels.MixedFeatures(data.continuous[:1], data.categorical[:1])
        far = _feats(np.full((1, 2), 5.0, np.float32))
        _, s_near = state.predict(near)
        _, s_far = state.predict(far)
        assert float(s_far[0]) > float(s_near[0])


class TestTraining:
    def test_lbfgs_improves_loss(self):
        model = gp_lib.VizierGaussianProcess(num_continuous=2, num_categorical=0)
        data = _make_data(16, 16)
        coll = model.param_collection()
        inits = coll.batch_random_init_unconstrained(jax.random.PRNGKey(0), 4)
        loss_fn = lambda p: model.neg_log_likelihood(p, data)
        init_losses = jax.vmap(loss_fn)(inits)
        result = lbfgs_lib.LbfgsOptimizer(maxiter=30)(loss_fn, inits)
        assert float(result.best_loss) < float(jnp.min(init_losses))

    def test_lbfgs_rosenbrock_not_stopped_prematurely(self):
        """ftol early stop must not quit inside Rosenbrock's flat valley."""
        from vizier_tpu.optimizers.lbfgs import lbfgs_minimize

        def rosen(v):
            return (1.0 - v[0]) ** 2 + 100.0 * (v[1] - v[0] ** 2) ** 2

        x, f = lbfgs_minimize(rosen, jnp.asarray([-1.2, 1.0]), maxiter=200)
        assert float(f) < 1e-5
        np.testing.assert_allclose(np.asarray(x), [1.0, 1.0], atol=1e-2)

    def test_lbfgs_ill_scaled_quadratic(self):
        """Step-size carryover must still converge when the curvature forces
        tiny steps early (condition number 1e4) and full steps later."""
        from vizier_tpu.optimizers.lbfgs import lbfgs_minimize

        scales = jnp.asarray([1.0, 1e2, 1e4])

        def quad(v):
            return jnp.sum(scales * v**2)

        x, f = lbfgs_minimize(quad, jnp.asarray([3.0, 2.0, 1.0]), maxiter=100)
        assert float(f) < 1e-6

    def test_lbfgs_condition_1e7_quadratic(self):
        """Regression: the line-search warm start + ftol stop must not stall
        a condition-1e7 quadratic far from its optimum (a capped-step
        cascade once did, stopping at f=100 from f0=1e2^2*1e-2)."""
        from vizier_tpu.optimizers.lbfgs import lbfgs_minimize

        scales = jnp.asarray([1e-2, 1e5])

        def quad(v):
            return jnp.sum(scales * v**2)

        x, f = lbfgs_minimize(quad, jnp.asarray([100.0, 1.0]), maxiter=300)
        assert float(f) < 1e-6, float(f)

    def test_best_n_ensemble_shapes(self):
        model = gp_lib.VizierGaussianProcess(num_continuous=1, num_categorical=0)
        data = _make_data(8, 8, dc=1)
        coll = model.param_collection()
        inits = coll.batch_random_init_unconstrained(jax.random.PRNGKey(0), 6)
        loss_fn = lambda p: model.neg_log_likelihood(p, data)
        result = lbfgs_lib.LbfgsOptimizer(maxiter=10)(loss_fn, inits, best_n=3)
        assert result.params["amplitude"].shape == (3,)
        states = jax.vmap(lambda p: model.precompute(p, data))(result.params)
        ens = gp_lib.EnsemblePredictive(states)
        mean, stddev = ens.predict(_feats(np.array([[0.5]], np.float32)))
        assert mean.shape == (1,) and stddev.shape == (1,)

    def test_adam_optimizer_works(self):
        model = gp_lib.VizierGaussianProcess(num_continuous=1, num_categorical=0)
        data = _make_data(8, 8, dc=1)
        coll = model.param_collection()
        inits = coll.batch_random_init_unconstrained(jax.random.PRNGKey(0), 2)
        loss_fn = lambda p: model.neg_log_likelihood(p, data)
        init_losses = jax.vmap(loss_fn)(inits)
        result = lbfgs_lib.AdamOptimizer(maxiter=100)(loss_fn, inits)
        assert float(result.best_loss) < float(jnp.min(init_losses))


class TestParams:
    def test_softclip_roundtrip(self):
        b = params_lib.SoftClip(1e-3, 10.0)
        y = jnp.asarray([0.01, 0.5, 5.0])
        np.testing.assert_allclose(b.forward(b.inverse(y)), y, rtol=1e-3)

    def test_forward_in_bounds(self):
        b = params_lib.SoftClip(0.1, 2.0)
        x = jnp.linspace(-20, 20, 100)
        y = np.asarray(b.forward(x))
        assert (y >= 0.1 - 1e-6).all() and (y <= 2.0 + 1e-6).all()

    def test_init_within_range(self):
        spec = params_lib.ParameterSpec(
            "a", (4,), params_lib.SoftClip(1e-3, 100.0), 0.1, 10.0
        )
        v = np.asarray(spec.sample_constrained(jax.random.PRNGKey(0)))
        assert (v >= 0.1).all() and (v <= 10.0).all()


class TestWarpers:
    def test_zscore(self):
        w = output_warpers.ZScoreWarper()
        y = w(np.array([1.0, 2.0, 3.0, 4.0]))
        assert np.mean(y) == pytest.approx(0.0, abs=1e-9)
        assert np.std(y) == pytest.approx(1.0, abs=1e-9)

    def test_halfrank_compresses_bad_tail(self):
        w = output_warpers.HalfRankWarper()
        y = np.array([0.0, 1.0, 2.0, 3.0, -1000.0])
        out = w(y)
        # The catastrophic outlier is pulled near the pack.
        assert out.min() > -100
        # Good half untouched.
        np.testing.assert_allclose(out[2:4], y[2:4])

    def test_infeasible_imputed_below_worst(self):
        w = output_warpers.InfeasibleWarper()
        out = w(np.array([1.0, np.nan, 3.0]))
        assert out[1] < 1.0
        assert np.isfinite(out).all()

    def test_default_pipeline(self):
        w = output_warpers.create_default_warper()
        y = np.array([5.0, np.nan, -2.0, 100.0, 3.0])
        out = w(y)
        assert np.isfinite(out).all()
        assert out[1] == out.min()  # infeasible is the worst


class TestInputWarping:
    """HEBO-style Kumaraswamy input warping (hebo_gp_model parity)."""

    def test_identity_at_unit_params(self):
        model = gp_lib.VizierGaussianProcess(
            num_continuous=2, num_categorical=0, use_input_warping=True
        )
        plain = gp_lib.VizierGaussianProcess(num_continuous=2, num_categorical=0)
        data = _make_data(8, 8)
        coll = model.param_collection()
        base = plain.param_collection().random_init_unconstrained(jax.random.PRNGKey(0))
        constrained = plain.param_collection().constrain(base)
        constrained["warp_a"] = jnp.ones(2)
        constrained["warp_b"] = jnp.ones(2)
        warp_params = coll.unconstrain(constrained)
        l_warp = float(model.neg_log_likelihood(warp_params, data))
        l_plain = float(plain.neg_log_likelihood(base, data))
        # a=b=1 warps are (numerically) the identity; likelihoods differ
        # only by the extra regularizer terms (zero at the prior mode).
        assert l_warp == pytest.approx(l_plain, rel=1e-3)

    def test_warped_fit_improves_on_nonstationary_data(self):
        # Objective varies fast near 0 and slow elsewhere: warping helps.
        rng = np.random.default_rng(0)
        x = rng.uniform(size=(24, 1)).astype(np.float32)
        y = np.sin(8 * np.sqrt(x[:, 0]))
        data = _make_data(24, 32, dc=1)
        data = gp_lib.GPData(
            continuous=jnp.asarray(np.pad(x, ((0, 8), (0, 0)))),
            categorical=data.categorical,
            labels=jnp.asarray(np.pad(y, (0, 8)).astype(np.float32)),
            row_mask=jnp.arange(32) < 24,
            cont_dim_mask=jnp.ones(1, bool),
            cat_dim_mask=data.cat_dim_mask,
        )
        def best_loss(model):
            coll = model.param_collection()
            inits = coll.batch_random_init_unconstrained(jax.random.PRNGKey(1), 6)
            result = lbfgs_lib.AdamOptimizer(maxiter=120)(
                lambda p: model.neg_log_likelihood(p, data), inits
            )
            return float(result.best_loss)

        warped = best_loss(
            gp_lib.VizierGaussianProcess(
                num_continuous=1, num_categorical=0, use_input_warping=True
            )
        )
        plain = best_loss(
            gp_lib.VizierGaussianProcess(num_continuous=1, num_categorical=0)
        )
        assert warped <= plain + 1.0  # warping never much worse; usually better

    def test_nonunit_warp_changes_likelihood(self):
        """Guard: the warp must actually be applied (not a silent no-op)."""
        model = gp_lib.VizierGaussianProcess(
            num_continuous=2, num_categorical=0, use_input_warping=True
        )
        data = _make_data(8, 8)
        coll = model.param_collection()
        base = coll.random_init_unconstrained(jax.random.PRNGKey(0))
        constrained = coll.constrain(base)
        constrained["warp_a"] = jnp.ones(2)
        constrained["warp_b"] = jnp.ones(2)
        identity = float(model.neg_log_likelihood(coll.unconstrain(constrained), data))
        constrained["warp_a"] = jnp.full(2, 3.0)
        constrained["warp_b"] = jnp.full(2, 0.4)
        warped = float(model.neg_log_likelihood(coll.unconstrain(constrained), data))
        assert warped != pytest.approx(identity, rel=1e-4)


class TestJointPosterior:
    def test_predict_joint_matches_marginals(self):
        model = gp_lib.VizierGaussianProcess(num_continuous=2, num_categorical=0)
        data = _make_data(10, 16)
        params = model.param_collection().random_init_unconstrained(jax.random.PRNGKey(2))
        state = model.precompute(params, data)
        query = _feats(np.random.default_rng(3).uniform(size=(5, 2)).astype(np.float32))
        mean_m, std_m = state.predict(query)
        mean_j, cov_j = state.predict_joint(query)
        np.testing.assert_allclose(mean_j, mean_m, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(
            np.sqrt(np.diag(np.asarray(cov_j))), std_m, rtol=1e-2, atol=1e-3
        )

    def test_duplicated_points_perfectly_correlated(self):
        """The property joint qEI relies on: copies share one posterior draw."""
        model = gp_lib.VizierGaussianProcess(num_continuous=1, num_categorical=0)
        data = _make_data(8, 8, dc=1)
        params = model.param_collection().random_init_unconstrained(jax.random.PRNGKey(0))
        state = model.precompute(params, data)
        x = np.array([[0.37], [0.37]], np.float32)  # same point twice
        _, cov = state.predict_joint(_feats(x))
        cov = np.asarray(cov)
        corr = cov[0, 1] / np.sqrt(cov[0, 0] * cov[1, 1])
        assert corr == pytest.approx(1.0, abs=1e-3)
