"""Test configuration: force a virtual 8-device CPU mesh before jax imports.

Multi-chip sharding paths are exercised on CPU via
``--xla_force_host_platform_device_count`` (real TPU hardware in CI has one
chip; the driver separately dry-runs the multi-chip path).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
