"""Test configuration: force a virtual 8-device CPU mesh before jax init.

Multi-chip sharding paths are exercised on CPU via
``--xla_force_host_platform_device_count`` (real TPU hardware in CI has one
chip; the driver separately dry-runs the multi-chip path).

The platform override must go through ``jax.config`` (not just the env var):
the environment may pre-set ``JAX_PLATFORMS`` to a TPU plugin and pre-import
jax via sitecustomize, in which case only a config update before the first
backend initialization reliably selects CPU.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")
# The designers' auto-mesh would route EVERY GP test through 8-pool sharded
# sweeps; on virtual CPU devices that multiplies work ~8x with no
# parallelism gain. Dedicated mesh tests opt back in with use_mesh=True.
os.environ.setdefault("VIZIER_DISABLE_MESH", "1")

import gc  # noqa: E402

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")


@pytest.fixture(autouse=True, scope="module")
def _gc_relief():
    """Keeps full-suite GC pauses bounded (observed failure mode: ~950
    tests of jit compilations accumulate millions of live Python objects
    (~5 GB RSS), after which any full collection stalls the main thread for
    minutes — surfacing as spurious gRPC channel-ready timeouts or apparent
    hangs in whatever test the pause lands on).

    At each module boundary: drop jax's compilation caches (their jaxprs
    dominate the object graph; cross-module cache reuse is minimal anyway),
    unfreeze the previous boundary's survivors so cycles that died since
    then are reclaimable (a freeze-only policy would make suite RSS
    monotone), collect once, then ``gc.freeze()`` the survivors into the
    permanent generation so collections between boundaries scan only new
    objects.
    """
    yield
    jax.clear_caches()
    gc.unfreeze()
    gc.collect()
    gc.freeze()
