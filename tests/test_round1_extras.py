"""Tests: singleton params, parameter iterators, multi-task GP, perf stress."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.pythia.singleton_params import SingletonParameterHandler
from vizier_tpu.pyvizier.parameter_iterators import SequentialParameterBuilder


class TestSingletonParams:
    def test_strip_and_augment(self):
        problem = vz.ProblemStatement()
        root = problem.search_space.root
        root.add_float_param("x", 0.0, 1.0)
        root.add_float_param("fixed_f", 2.0, 2.0)
        root.add_categorical_param("fixed_c", ["only"])
        root.add_int_param("fixed_i", 3, 3)
        problem.metric_information.append(vz.MetricInformation(name="obj"))
        handler = SingletonParameterHandler(problem)
        assert handler.reduced_problem.search_space.parameter_names() == ["x"]
        assert handler.fixed_parameters == {"fixed_f": 2.0, "fixed_c": "only", "fixed_i": 3}
        s = vz.TrialSuggestion(parameters={"x": 0.5})
        (aug,) = handler.augment([s])
        assert aug.parameters.get_value("fixed_c") == "only"
        assert problem.search_space.contains(aug.parameters)

    def test_strip_trials(self):
        problem = vz.ProblemStatement()
        problem.search_space.root.add_float_param("x", 0.0, 1.0)
        problem.search_space.root.add_categorical_param("fixed", ["v"])
        problem.metric_information.append(vz.MetricInformation(name="obj"))
        handler = SingletonParameterHandler(problem)
        t = vz.Trial(id=1, parameters={"x": 0.3, "fixed": "v"})
        t.complete(vz.Measurement(metrics={"obj": 1.0}))
        (stripped,) = handler.strip([t])
        assert "fixed" not in stripped.parameters
        assert stripped.final_measurement is t.final_measurement

    def test_conditional_parent_not_stripped(self):
        problem = vz.ProblemStatement()
        sel = problem.search_space.root.add_categorical_param("gate", ["only"])
        sel.select_values(["only"]).add_float_param("child", 0.0, 1.0)
        problem.metric_information.append(vz.MetricInformation(name="obj"))
        handler = SingletonParameterHandler(problem)
        # Parent has children → must stay even though single-valued.
        assert "gate" in handler.reduced_problem.search_space.parameter_names()


class TestSequentialParameterBuilder:
    def test_walks_conditional_tree(self):
        space = vz.SearchSpace()
        model = space.root.add_categorical_param("model", ["linear", "dnn"])
        model.select_values(["dnn"]).add_int_param("depth", 1, 4)
        space.root.add_float_param("lr", 0.0, 1.0)

        builder = SequentialParameterBuilder(space)
        chosen = {"model": "dnn", "depth": 2, "lr": 0.5}
        visited = []
        for config in builder:
            visited.append(config.name)
            builder.choose_value(chosen[config.name])
        assert visited == ["model", "depth", "lr"]
        assert space.contains(builder.parameters)

    def test_inactive_branch_skipped(self):
        space = vz.SearchSpace()
        model = space.root.add_categorical_param("model", ["linear", "dnn"])
        model.select_values(["dnn"]).add_int_param("depth", 1, 4)
        builder = SequentialParameterBuilder(space)
        visited = []
        for config in builder:
            visited.append(config.name)
            builder.choose_value("linear" if config.name == "model" else 1)
        assert visited == ["model"]


class TestMultiTaskGP:
    def _multitask_data(self, n=12, rho=0.9):
        from vizier_tpu import types
        from vizier_tpu.models import gp as gp_lib
        from vizier_tpu.models.multitask_gp import MultiTaskData

        rng = np.random.default_rng(0)
        x = rng.uniform(size=(n, 1)).astype(np.float32)
        f = np.sin(5 * x[:, 0])
        y1 = f + 0.05 * rng.normal(size=n)
        y2 = rho * f + 0.05 * rng.normal(size=n)
        datas = []
        for y in (y1, y2):
            features = types.ContinuousAndCategorical(
                continuous=types.PaddedArray.from_array(x, (n, 1)),
                categorical=types.PaddedArray.from_array(
                    np.zeros((n, 0), np.int32), (n, 0), fill_value=0
                ),
            )
            labels = types.PaddedArray.from_array(
                y[:, None].astype(np.float32), (n, 1), fill_value=np.nan
            )
            datas.append(
                gp_lib.GPData.from_model_data(types.ModelData(features, labels))
            )
        return MultiTaskData.from_gp_datas(tuple(datas)), x, f

    def test_training_improves_likelihood(self):
        from vizier_tpu.models.multitask_gp import MultiTaskGaussianProcess
        from vizier_tpu.optimizers import lbfgs as lbfgs_lib

        data, _, _ = self._multitask_data()
        model = MultiTaskGaussianProcess(
            num_continuous=1, num_categorical=0, num_tasks=2
        )
        coll = model.param_collection()
        inits = coll.batch_random_init_unconstrained(jax.random.PRNGKey(0), 4)
        loss_fn = lambda p: model.neg_log_likelihood(p, data)
        init_losses = jax.vmap(loss_fn)(inits)
        result = lbfgs_lib.AdamOptimizer(maxiter=60)(loss_fn, inits)
        assert float(result.best_loss) < float(jnp.min(init_losses))

    def test_cross_task_transfer(self):
        """Task 2 observations should sharpen task 1 predictions."""
        from vizier_tpu.models import kernels
        from vizier_tpu.models.multitask_gp import (
            MultiTaskData,
            MultiTaskGaussianProcess,
        )
        from vizier_tpu import types
        from vizier_tpu.models import gp as gp_lib

        # Task 1: only 2 observations. Task 2 (perfectly correlated): dense.
        rng = np.random.default_rng(1)
        n = 16
        x = np.linspace(0, 1, n).astype(np.float32)[:, None]
        f = np.sin(5 * x[:, 0])

        def mk(y, mask_rows):
            features = types.ContinuousAndCategorical(
                continuous=types.PaddedArray.from_array(x, (n, 1)),
                categorical=types.PaddedArray.from_array(
                    np.zeros((n, 0), np.int32), (n, 0), fill_value=0
                ),
            )
            yy = np.where(mask_rows, y, np.nan)
            labels = types.PaddedArray.from_array(
                yy[:, None].astype(np.float32), (n, 1), fill_value=np.nan
            )
            return gp_lib.GPData.from_model_data(
                types.ModelData(features, labels)
            )

        sparse_mask = np.zeros(n, dtype=bool)
        sparse_mask[[0, n - 1]] = True
        data = MultiTaskData.from_gp_datas(
            (mk(f, sparse_mask), mk(f, np.ones(n, dtype=bool)))
        )
        model = MultiTaskGaussianProcess(
            num_continuous=1, num_categorical=0, num_tasks=2
        )
        # Hand-set correlated task covariance and good kernel params.
        coll = model.param_collection()
        constrained = {
            "amplitude": jnp.asarray(1.0),
            "noise_stddev": jnp.asarray(0.05),
            "continuous_length_scales": jnp.asarray([0.2]),
            "task_chol_diag": jnp.asarray([1.0, 0.1]),
            "task_chol_offdiag": jnp.asarray([1.0]),
        }
        state = model.precompute(coll.unconstrain(constrained), data)
        query = kernels.MixedFeatures(
            jnp.asarray([[0.5]], jnp.float32), jnp.zeros((1, 0), jnp.int32)
        )
        mean, stddev = state.predict(query)
        # Task 1 mean at 0.5 should track f despite having no nearby task-1
        # observation, thanks to the correlated task 2 data.
        assert abs(float(mean[0, 0]) - np.sin(2.5)) < 0.4
        assert mean.shape == (2, 1) and stddev.shape == (2, 1)


class TestServiceThroughput:
    """Parity with the reference performance_test.py: multi-client stress
    at its configs (clients x trials), wall time logged, no assertions on
    speed — only on correctness under concurrency."""

    @pytest.mark.parametrize("num_clients,num_trials", [(1, 10), (2, 10), (10, 4)])
    def test_stress(self, num_clients, num_trials):
        import threading

        from vizier_tpu.service import clients as clients_lib
        from vizier_tpu.service import vizier_client

        vizier_client._local_servicer = None
        config = vz.StudyConfig(algorithm="RANDOM_SEARCH")
        config.search_space.root.add_float_param("x", 0.0, 1.0)
        config.metric_information.append(
            vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
        study = clients_lib.Study.from_study_config(
            config, owner="perf", study_id=f"stress-{num_clients}x{num_trials}"
        )
        errors = []

        def worker(wid):
            try:
                for _ in range(num_trials):
                    for t in study.suggest(count=1, client_id=f"w{wid}"):
                        t.complete(vz.Measurement(metrics={"obj": 0.5}))
            except Exception as e:  # pragma: no cover
                errors.append(e)

        start = time.time()
        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(num_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.time() - start
        assert not errors
        trials = list(study.trials())
        assert len(trials) == num_clients * num_trials
        print(
            f"\n[throughput] {num_clients} clients x {num_trials} trials: "
            f"{elapsed:.2f}s ({len(trials) / elapsed:.0f} trials/s)"
        )


class TestClassification:
    def _problem(self):
        p = vz.ProblemStatement()
        p.search_space.root.add_float_param("x", 0.0, 1.0)
        p.metric_information.append(vz.MetricInformation(name="obj"))
        return p

    @pytest.mark.parametrize("kind", ["gp", "logistic"])
    def test_learns_infeasible_region(self, kind):
        from vizier_tpu.algorithms.classification import FeasibilityClassifier

        problem = self._problem()
        trials = []
        rng = np.random.default_rng(0)
        for i in range(40):
            x = float(rng.uniform())
            t = vz.Trial(id=i + 1, parameters={"x": x})
            if x > 0.5:  # right half always fails
                t.complete(infeasibility_reason="fail")
            else:
                t.complete(vz.Measurement(metrics={"obj": 1.0}))
            trials.append(t)
        clf = FeasibilityClassifier(problem, kind=kind).fit(trials)
        probs = clf.predict_proba_feasible(
            [
                vz.TrialSuggestion(parameters={"x": 0.1}),
                vz.TrialSuggestion(parameters={"x": 0.9}),
            ]
        )
        assert probs[0] > 0.7 and probs[1] < 0.3

    def test_all_feasible_constant(self):
        from vizier_tpu.algorithms.classification import FeasibilityClassifier

        problem = self._problem()
        t = vz.Trial(id=1, parameters={"x": 0.5})
        t.complete(vz.Measurement(metrics={"obj": 1.0}))
        clf = FeasibilityClassifier(problem).fit([t])
        assert clf.predict_proba_feasible(
            [vz.TrialSuggestion(parameters={"x": 0.3})]
        )[0] == 1.0


class TestCurveRegression:
    def test_power_law_extrapolation(self):
        from vizier_tpu.algorithms.classification import TrialCurveRegressor

        t = vz.Trial(id=1, parameters={})
        # y = 0.9 - 0.5 * s^-0.5
        for s in (1, 4, 16, 64):
            t.measurements.append(
                vz.Measurement(metrics={"acc": 0.9 - 0.5 * s**-0.5}, steps=s)
            )
        reg = TrialCurveRegressor("acc").fit(t)
        assert reg is not None
        assert abs(reg.predict(256) - (0.9 - 0.5 * 256**-0.5)) < 0.02
        assert abs(reg.asymptote - 0.9) < 0.05

    def test_too_few_points(self):
        from vizier_tpu.algorithms.classification import TrialCurveRegressor

        t = vz.Trial(id=1, parameters={})
        t.measurements.append(vz.Measurement(metrics={"acc": 0.5}, steps=1))
        assert TrialCurveRegressor("acc").fit(t) is None
