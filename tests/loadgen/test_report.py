"""Loadgen assertion engine: synthetic arms through every verdict path."""

import dataclasses

from vizier_tpu.loadgen import driver as driver_lib
from vizier_tpu.loadgen import models
from vizier_tpu.loadgen import report as report_lib


def _scenario(**overrides):
    config = models.smoke_config(
        kind_mix=(("random", 1.0),),
        num_studies=4,
        chaos_fault_prob=0.0,
        target="inprocess",
        **overrides,
    )
    return models.build_scenario(config)


def _outcome(spec, *, completed=None, listed=None, best=0.0, error=None):
    completed = spec.budget if completed is None else completed
    listed = (
        spec.preseed + completed if listed is None else listed
    )
    trajectory = tuple(
        (("x0", 0.1 * (i + 1)), ("x1", 0.2)) for i in range(completed)
    )
    return driver_lib.StudyOutcome(
        spec=spec,
        completed=completed,
        expected=spec.budget,
        listed_completed=listed,
        trajectory=trajectory,
        best_curve=tuple(best + 0.01 * i for i in range(completed))
        or (),
        error=error,
    )


def _result(scenario, *, arm="engine", lost=(), fallbacks=0, hits=0):
    records, outcomes = [], {}
    for spec in scenario.studies:
        outcomes[spec.index] = _outcome(
            spec,
            listed=None if spec.index not in lost else 0,
        )
        for step in range(spec.budget):
            records.append(
                driver_lib.RequestRecord(
                    spec.index,
                    spec.kind,
                    spec.tenant,
                    "suggest",
                    0.002,
                    trace_id=f"t{spec.index}-{step}",
                    fallback=fallbacks > 0 and step == 0,
                    speculative_hit=hits > 0 and step == 0,
                )
            )
    return driver_lib.SoakResult(
        arm=arm,
        scenario_fingerprint=scenario.fingerprint(),
        records=records,
        outcomes=outcomes,
        events_fired=[],
        serving_stats={},
        slo={"armed": True, "breaching": [], "statuses": []},
        wall_s=1.0,
    )


class TestAssertions:
    def test_clean_run_passes_every_assertion(self):
        scenario = _scenario()
        engine = _result(scenario)
        reference = _result(scenario, arm="reference")
        gated = _result(scenario, arm="gated_off")
        report = report_lib.build_report(scenario, engine, reference, gated)
        assert report["ok"], report["assertions"]
        assert report["scenario"]["fingerprint"] == scenario.fingerprint()

    def test_lost_study_fails_zero_lost(self):
        scenario = _scenario()
        engine = _result(scenario, lost=(0,))
        report = report_lib.build_report(scenario, engine)
        by_name = {a["name"]: a for a in report["assertions"]}
        assert not by_name["zero_lost_studies"]["ok"]
        assert not report["ok"]
        assert report["failover"]["lost_studies"] == [0]

    def test_missing_arms_fail_their_assertions(self):
        scenario = _scenario()
        report = report_lib.build_report(scenario, _result(scenario))
        by_name = {a["name"]: a for a in report["assertions"]}
        assert not by_name["regret_parity"]["ok"]
        assert not by_name["bit_identical_when_gated"]["ok"]

    def test_trajectory_mismatch_fails_bit_identity(self):
        scenario = _scenario()
        engine = _result(scenario)
        reference = _result(scenario, arm="reference")
        gated = _result(scenario, arm="gated_off")
        first = scenario.studies[0].index
        gated.outcomes[first] = dataclasses.replace(
            gated.outcomes[first],
            trajectory=((("x0", 0.999), ("x1", 0.2)),),
        )
        report = report_lib.build_report(scenario, engine, reference, gated)
        assert not report["bit_identity"]["identical"]
        assert not report["ok"]

    def test_fallback_budget_enforced(self):
        scenario = _scenario()
        config = dataclasses.replace(
            scenario.config, max_fallback_rate=0.0
        )
        scenario = models.Scenario(config, scenario.studies, scenario.events)
        engine = _result(scenario, fallbacks=1)
        report = report_lib.build_report(scenario, engine)
        by_name = {a["name"]: a for a in report["assertions"]}
        assert not by_name["fallback_rate_bounded"]["ok"]

    def test_speculative_assertion_when_armed(self):
        config = models.smoke_config(
            kind_mix=(("gp_bandit", 1.0),),
            num_studies=2,
            chaos_fault_prob=0.0,
            target="inprocess",
            planes=models.PlaneConfig(
                batching=False, speculative=True, mesh=False, slo=False
            ),
        )
        scenario = models.build_scenario(config)
        # No hits -> the armed speculative assertion fails.
        report = report_lib.build_report(scenario, _result(scenario))
        by_name = {a["name"]: a for a in report["assertions"]}
        assert not by_name["speculative_hits"]["ok"]
        # With a hit it passes.
        report = report_lib.build_report(
            scenario, _result(scenario, hits=1)
        )
        by_name = {a["name"]: a for a in report["assertions"]}
        assert by_name["speculative_hits"]["ok"]

    def test_ranksum_identical_samples_is_parity(self):
        assert report_lib.ranksum_p([1.0, 2.0, 3.0], [1.0, 2.0, 3.0]) > 0.9
        assert (
            report_lib.ranksum_p(
                [1.0, 1.1, 1.2, 1.3, 1.4], [9.0, 9.1, 9.2, 9.3, 9.4]
            )
            < 0.05
        )

    def test_render_verdict_shape(self):
        scenario = _scenario()
        report = report_lib.build_report(scenario, _result(scenario))
        text = report_lib.render_verdict(report)
        assert "soak: FAIL" in text  # reference arms missing
        assert "zero_lost_studies" in text


def _report_dict(
    *,
    p99_by_kind=None,
    assertions=None,
    hits=5,
    gp_hit_rate=0.5,
    fallbacks_by_kind=None,
    fingerprint="fp",
):
    p99_by_kind = p99_by_kind or {"random": 10.0}
    fallbacks_by_kind = fallbacks_by_kind or {}
    by_kind = {}
    for kind, p99 in p99_by_kind.items():
        by_kind[kind] = {
            "suggests": 100,
            "errors": 0,
            "fallbacks": fallbacks_by_kind.get(kind, 0),
            "speculative_hits": 0,
            "fallback_rate": fallbacks_by_kind.get(kind, 0) / 100,
            "hit_rate": 0.0,
            "latency": {"p50_ms": p99 / 2, "p99_ms": p99},
        }
    return {
        "scenario": {"fingerprint": fingerprint},
        "ok": all(ok for _n, ok in (assertions or {"a": True}).items()),
        "assertions": [
            {"name": name, "ok": ok, "detail": ""}
            for name, ok in (assertions or {"a": True}).items()
        ],
        "outcomes": {"by_kind": by_kind},
        "speculative": {"armed": True, "hits": hits, "gp_hit_rate": gp_hit_rate},
    }


class TestDiffReports:
    def test_identical_reports_are_clean(self):
        a = _report_dict()
        diff = report_lib.diff_reports(a, _report_dict())
        assert diff["ok"] and diff["regressions"] == []
        assert diff["same_scenario"]

    def test_assertion_flip_is_a_regression(self):
        a = _report_dict(assertions={"zero_lost_studies": True})
        b = _report_dict(assertions={"zero_lost_studies": False})
        diff = report_lib.diff_reports(a, b)
        assert not diff["ok"]
        assert any("zero_lost_studies" in r for r in diff["regressions"])
        assert diff["assertion_changes"]["zero_lost_studies"] == {
            "before": True,
            "after": False,
        }

    def test_assertion_fixed_is_not_a_regression(self):
        a = _report_dict(assertions={"x": False})
        b = _report_dict(assertions={"x": True})
        diff = report_lib.diff_reports(a, b)
        assert diff["ok"]
        assert diff["assertion_changes"]["x"]["after"] is True

    def test_hit_rate_drop_is_a_regression(self):
        a = _report_dict(gp_hit_rate=0.8)
        b = _report_dict(gp_hit_rate=0.3)
        diff = report_lib.diff_reports(a, b)
        assert not diff["ok"]
        assert any("hit rate" in r for r in diff["regressions"])

    def test_fallback_rise_is_a_regression(self):
        a = _report_dict()
        b = _report_dict(fallbacks_by_kind={"random": 20})
        diff = report_lib.diff_reports(a, b)
        assert not diff["ok"]
        assert any("fallback" in r for r in diff["regressions"])

    def test_kind_vanishing_is_a_regression(self):
        a = _report_dict(p99_by_kind={"random": 10.0, "gp_bandit": 50.0})
        b = _report_dict(p99_by_kind={"random": 10.0})
        diff = report_lib.diff_reports(a, b)
        assert not diff["ok"]

    def test_latency_deltas_reported_but_advisory(self):
        a = _report_dict(p99_by_kind={"random": 10.0})
        b = _report_dict(p99_by_kind={"random": 40.0})
        diff = report_lib.diff_reports(a, b)
        assert diff["ok"]  # wall clock alone never fails the gate
        assert diff["per_kind"]["random"]["p99_ms"]["ratio"] == 4.0
        # ...unless an explicit ratio budget is given.
        strict = report_lib.diff_reports(a, b, latency_ratio=2.0)
        assert not strict["ok"]

    def test_render_diff_shape(self):
        a = _report_dict(assertions={"x": True})
        b = _report_dict(assertions={"x": False})
        text = report_lib.render_diff(report_lib.diff_reports(a, b))
        assert "REGRESSED" in text and "verdict x" in text
