"""Overload-scenario loadgen pieces: the hot_tenant preset, per-tenant
kind overrides, real open-loop arrival pacing, the report's per-tenant
latency/shed tables, and the --diff shed-rate regression gate."""

import dataclasses

from vizier_tpu.loadgen import driver as driver_lib
from vizier_tpu.loadgen import models
from vizier_tpu.loadgen import report as report_lib


class TestHotTenantPreset:
    def test_builds_and_is_deterministic(self):
        a = models.build_scenario(models.hot_tenant_config())
        b = models.build_scenario(models.hot_tenant_config())
        assert a.fingerprint() == b.fingerprint()
        assert a.config.time_scale == 1.0
        assert a.config.planes.admission

    def test_hot_tenant_has_zipf_head_share_and_gp_only_traffic(self):
        scenario = models.build_scenario(models.hot_tenant_config())
        by_tenant = {}
        for spec in scenario.studies:
            by_tenant.setdefault(spec.tenant, []).append(spec)
        hot = by_tenant["hot"]
        assert len(hot) > len(scenario.studies) / 2  # the Zipf head
        assert all(s.kind == "gp_bandit" for s in hot)  # tenant override
        light = [
            s for s in scenario.studies if s.tenant.startswith("light-")
        ]
        assert light
        assert any(s.kind == "random" for s in light)  # global mix kept

    def test_tenant_kind_override_leaves_base_expansion_unchanged(self):
        base = models.hot_tenant_config(tenant_kinds=())
        overridden = models.hot_tenant_config()
        a = models.build_scenario(base)
        b = models.build_scenario(overridden)
        for sa, sb in zip(a.studies, b.studies):
            assert sa.tenant == sb.tenant
            assert sa.budget == sb.budget
            assert sa.arrival_s == sb.arrival_s
            assert sa.seed == sb.seed

    def test_owner_tenant_round_trip(self):
        assert models.owner_tenant(models.tenant_owner("hot")) == "hot"
        assert models.owner_tenant("someone-else") == "someone-else"
        scenario = models.build_scenario(models.hot_tenant_config())
        spec = scenario.studies[0]
        owner = spec.name.split("/")[1]
        assert models.owner_tenant(owner) == spec.tenant

    def test_admission_env_overlay(self):
        config = models.hot_tenant_config()
        env = driver_lib.scenario_env(config)
        assert env["VIZIER_ADMISSION"] == "1"
        assert "loadgen-hot:0.5" in env["VIZIER_ADMISSION_WEIGHTS"]
        assert env["VIZIER_ADMISSION_TENANT_INFLIGHT"] == "3"
        assert env["VIZIER_ADMISSION_RETRY_AFTER_MS"] == "250.0"
        off = dataclasses.replace(
            config, planes=dataclasses.replace(config.planes, admission=False)
        )
        env_off = driver_lib.scenario_env(off)
        assert env_off["VIZIER_ADMISSION"] == "0"
        assert "VIZIER_ADMISSION_WEIGHTS" not in env_off


class TestOpenLoopPacing:
    def _tiny_open_loop(self, **overrides):
        values = dict(
            name="pace",
            num_studies=6,
            min_trials=1,
            max_trials=1,
            target="inprocess",
            concurrency=2,
            time_scale=1.0,
            arrival_rate_per_s=10.0,
            kind_mix=(("random", 1.0),),
            chaos_fault_prob=0.0,
            parity_cohort=1,
            planes=models.PlaneConfig.gated_off(),
            events=(),
        )
        values.update(overrides)
        return models.build_scenario(models.ScenarioConfig(**values))

    def test_arrivals_are_honored_in_real_time(self):
        """time_scale=1 paces the run: the wall clock covers the arrival
        schedule even though each random-kind study completes in
        microseconds (the closed-loop driver would finish instantly)."""
        scenario = self._tiny_open_loop()
        result = driver_lib.run(scenario, arm="pace")
        assert not result.lost_studies()
        assert not result.errored_studies()
        last_arrival = scenario.studies[-1].arrival_s
        assert result.wall_s >= last_arrival * 0.9
        assert result.open_loop_capped == 0

    def test_arrivals_do_not_wait_for_busy_workers(self):
        """Open loop means every study gets its own client thread at its
        release instant: 6 studies with concurrency=2 still all run (the
        old worker pool would serialize 3-deep)."""
        scenario = self._tiny_open_loop(concurrency=1)
        result = driver_lib.run(scenario, arm="pace")
        assert len(result.outcomes) == 6
        assert not result.errored_studies()

    def test_runaway_cap_is_recorded(self):
        # Arrivals far faster than studies drain (multi-trial studies,
        # sub-ms inter-arrivals) against a 1-client cap: the pacer must
        # block and record it.
        scenario = self._tiny_open_loop(
            open_loop_max_clients=1,
            min_trials=5,
            max_trials=5,
            arrival_rate_per_s=2000.0,
        )
        result = driver_lib.run(scenario, arm="pace")
        assert not result.errored_studies()
        assert result.open_loop_capped >= 1

    def test_closed_loop_unchanged_when_time_scale_zero(self):
        scenario = self._tiny_open_loop(time_scale=0.0)
        result = driver_lib.run(scenario, arm="pace")
        assert len(result.outcomes) == 6
        # Arrival ORDER only: drains far faster than the schedule.
        assert result.wall_s < scenario.studies[-1].arrival_s + 5.0


class TestPerTenantReport:
    def _result(self, **admission):
        scenario = models.build_scenario(
            models.ScenarioConfig(
                name="t",
                num_studies=2,
                min_trials=1,
                max_trials=1,
                target="inprocess",
                tenants=(("hot", 1.0), ("light", 1.0)),
                kind_mix=(("random", 1.0),),
                chaos_fault_prob=0.0,
                events=(),
            )
        )
        records = [
            driver_lib.RequestRecord(0, "random", "hot", "suggest", 0.2),
            driver_lib.RequestRecord(
                0, "random", "hot", "suggest", 0.4,
                error="TRANSIENT: RESOURCE_EXHAUSTED: admission shed",
            ),
            driver_lib.RequestRecord(
                1, "random", "light", "suggest", 0.01
            ),
            driver_lib.RequestRecord(
                1, "random", "light", "suggest", 0.02, degraded=True
            ),
        ]
        outcomes = {
            i: driver_lib.StudyOutcome(
                spec=scenario.studies[i], completed=1, expected=1,
                listed_completed=1,
            )
            for i in range(2)
        }
        result = driver_lib.SoakResult(
            arm="engine",
            scenario_fingerprint=scenario.fingerprint(),
            records=records,
            outcomes=outcomes,
            events_fired=[],
            serving_stats={},
            slo={},
            wall_s=1.0,
            admission=admission
            or {
                "enabled": True,
                "sheds_by_tenant": {"hot": {"inflight_tenant": 3}},
                "admits_by_tenant": {"hot": 5, "light": 4},
                "degraded_by_tenant": {"hot": 1},
                "state": "shedding",
            },
        )
        return scenario, result

    def test_by_tenant_rows_carry_latency_and_sheds(self):
        scenario, result = self._result()
        tables = report_lib._outcome_tables(result)
        hot = tables["by_tenant"]["hot"]
        assert hot["sheds"] == 3  # controller view (absorbed sheds too)
        assert hot["shed_errors"] == 1  # client-visible after retries
        assert hot["latency"]["samples"] == 1  # errored suggest excluded
        light = tables["by_tenant"]["light"]
        assert light["degraded"] == 1
        assert light["sheds"] == 0
        assert light["latency"]["p99_ms"] > 0

    def test_admission_section_and_shed_rate(self):
        scenario, result = self._result()
        config = dataclasses.replace(
            scenario.config,
            planes=dataclasses.replace(scenario.config.planes, admission=True),
        )
        section = report_lib._admission_section(config, result)
        assert section["armed"]
        assert section["sheds"] == 3
        assert section["degraded_serves"] == 1
        # 3 sheds / (3 sheds + 9 admits + 1 degraded)
        assert section["shed_rate"] == round(3 / 13, 4)


class TestDiffShedGate:
    def _report(self, shed_rate, armed=True, tenant_p99=100.0):
        return {
            "ok": True,
            "assertions": [],
            "outcomes": {
                "by_kind": {},
                "by_tenant": {
                    "light": {
                        "sheds": 0,
                        "latency": {"p50_ms": 50.0, "p99_ms": tenant_p99},
                    }
                },
            },
            "admission": {"armed": armed, "shed_rate": shed_rate},
            "speculative": {},
            "scenario": {"fingerprint": "f"},
        }

    def test_shed_rise_with_plane_unchanged_regresses(self):
        diff = report_lib.diff_reports(
            self._report(0.01), self._report(0.10)
        )
        assert not diff["ok"]
        assert any("shed rate" in r for r in diff["regressions"])

    def test_shed_rise_within_budget_passes(self):
        diff = report_lib.diff_reports(
            self._report(0.01), self._report(0.05)
        )
        assert diff["ok"]

    def test_arming_the_plane_is_not_a_regression(self):
        diff = report_lib.diff_reports(
            self._report(0.0, armed=False), self._report(0.2, armed=True)
        )
        assert diff["ok"]
        assert diff["admission"]["armed"] == {"before": False, "after": True}

    def test_per_tenant_p99_deltas_reported_and_gated(self):
        advisory = report_lib.diff_reports(
            self._report(0.0, tenant_p99=100.0),
            self._report(0.0, tenant_p99=900.0),
        )
        assert advisory["ok"]  # advisory without a latency budget
        assert advisory["per_tenant"]["light"]["p99_ms"]["ratio"] == 9.0
        gated = report_lib.diff_reports(
            self._report(0.0, tenant_p99=100.0),
            self._report(0.0, tenant_p99=900.0),
            latency_ratio=3.0,
        )
        assert not gated["ok"]
        assert any("tenant light p99" in r for r in gated["regressions"])
        rendered = report_lib.render_diff(gated)
        assert "tenant light" in rendered
        assert "admission shed rate" in rendered
