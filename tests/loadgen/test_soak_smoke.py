"""The tier-1 mini-soak: the full loadgen engine, seconds-scale.

One smoke scenario end to end through the REAL stack — every registered
compute-IR program kind gets traffic (exact + sparse GP-bandit, exact +
sparse UCB-PE, with a surrogate crossover mid-run), a 2-replica
WAL-backed tier takes a kill AND a revive, batching + SLO planes armed —
then the sequential reference and gated-off arms, asserted through the
report: regret parity, zero lost studies, failover completeness, and
bit-identical gated-off trajectories. This is the wiring-regression net:
any serving-plane change that breaks composition fails here, in seconds,
not in the slow acceptance soak.
"""

import pytest

from vizier_tpu.loadgen import driver as driver_lib
from vizier_tpu.loadgen import models
from vizier_tpu.loadgen import report as report_lib


@pytest.fixture(scope="module")
def soak_arms():
    scenario = models.build_scenario(models.smoke_config())
    engine = driver_lib.run(scenario, arm="engine")
    reference = driver_lib.run_reference(scenario)
    gated = driver_lib.run_gated_off(scenario)
    return scenario, engine, reference, gated


@pytest.fixture(scope="module")
def soak_report(soak_arms):
    scenario, engine, reference, gated = soak_arms
    return report_lib.build_report(scenario, engine, reference, gated)


class TestMiniSoak:
    def test_all_assertions_pass(self, soak_report):
        failed = [a for a in soak_report["assertions"] if not a["ok"]]
        assert soak_report["ok"], failed

    def test_all_program_kinds_served(self, soak_arms, soak_report):
        scenario, engine, _, _ = soak_arms
        served = {
            kind
            for kind, row in soak_report["outcomes"]["by_kind"].items()
            if row["suggests"] - row["errors"] > 0
        }
        # Every registered DesignerProgram kind carried traffic.
        assert set(models.GP_KINDS) <= served
        # ... through real designer compute, not just the policy surface.
        stats = engine.serving_stats
        assert stats.get("cold_trains", 0) + stats.get("warm_trains", 0) > 0
        assert stats.get("sparse_suggests", 0) > 0

    def test_surrogate_crossover_happened(self, soak_arms):
        _, engine, _, _ = soak_arms
        assert engine.serving_stats.get("surrogate_crossovers", 0) >= 1

    def test_kill_and_revive_fired_and_failed_over(self, soak_arms):
        _, engine, _, _ = soak_arms
        fired = {e["kind"] for e in engine.events_fired}
        assert {"kill_replica", "revive_replica"} <= fired
        assert int(engine.serving_stats.get("failovers", 0)) >= 1

    def test_zero_lost_studies(self, soak_arms):
        _, engine, _, _ = soak_arms
        assert engine.lost_studies() == []
        assert engine.errored_studies() == []
        for outcome in engine.outcomes.values():
            assert outcome.completed == outcome.expected
            assert (
                outcome.listed_completed
                == outcome.spec.preseed + outcome.completed
            )

    def test_gated_off_is_bit_identical_to_reference(self, soak_report):
        bit = soak_report["bit_identity"]
        assert bit["identical"], bit["mismatched"]
        assert bit["studies_compared"] >= 4

    def test_outcomes_recorded_in_flight_recorder(self, soak_arms):
        _, engine, _, _ = soak_arms
        kinds = engine.recorder_event_kinds
        assert kinds.get("loadgen_outcome", 0) >= sum(
            o.completed for o in engine.outcomes.values()
        )
        assert kinds.get("replica_failover", 0) >= 1

    def test_request_records_carry_trace_ids(self, soak_arms):
        _, engine, _, _ = soak_arms
        suggests = [r for r in engine.records if r.op == "suggest"]
        assert suggests
        assert all(r.trace_id for r in suggests)

    def test_report_renders_and_serializes(self, soak_report):
        import json

        text = report_lib.render_verdict(soak_report)
        assert "soak: PASS" in text
        payload = json.loads(json.dumps(soak_report))
        assert payload["version"] == report_lib.REPORT_VERSION


class TestObsReportSoakSection:
    def test_json_round_trip(self, soak_report, tmp_path):
        import json
        import pathlib
        import sys

        sys.path.insert(
            0,
            str(pathlib.Path(__file__).resolve().parents[2] / "tools"),
        )
        import obs_report

        path = tmp_path / "SOAK_REPORT.json"
        path.write_text(json.dumps(soak_report))
        soak = obs_report.soak_activity(obs_report.load_soak(str(path)))
        assert soak["ok"] is True
        assert (
            soak["traffic"]["studies"] == soak_report["traffic"]["studies"]
        )
        assert set(models.GP_KINDS) <= set(soak["by_kind"])
        assert {a["name"] for a in soak["assertions"]} == {
            a["name"] for a in soak_report["assertions"]
        }
        text = obs_report.render_soak(soak)
        assert "soak: PASS" in text and "gp_ucb_pe_sparse" in text

    def test_empty_report_degrades(self):
        import pathlib
        import sys

        sys.path.insert(
            0,
            str(pathlib.Path(__file__).resolve().parents[2] / "tools"),
        )
        import obs_report

        soak = obs_report.soak_activity({})
        assert soak["ok"] is False
        assert obs_report.render_soak(soak).startswith("soak: FAIL")
