"""Severity-track soak: multi_kill + wal_corrupt + rolling_restart on a
3-replica tier, driven end to end with zero lost studies."""

from vizier_tpu.loadgen import driver as driver_lib
from vizier_tpu.loadgen import models


def _severity_config(**overrides):
    values = dict(
        name="severity",
        replicas=3,
        num_studies=12,
        max_trials=4,
        kind_mix=(("random", 4.0), ("quasi_random", 2.0)),
        planes=models.PlaneConfig(
            batching=False, speculative=False, mesh=False, slo=False
        ),
    )
    values.update(overrides)
    return models.smoke_config(**values)


class TestSeveritySoak:
    def test_zero_lost_through_the_full_severity_track(self):
        scenario = models.build_scenario(_severity_config())
        kinds = [e.kind for e in scenario.events]
        assert kinds == ["multi_kill", "wal_corrupt", "rolling_restart"]

        result = driver_lib.run(scenario, arm="engine")

        fired = {e["kind"]: e for e in result.events_fired}
        assert set(fired) == set(kinds)
        for event in result.events_fired:
            assert "error" not in event, event
            assert "skipped" not in event, event
        # multi_kill really killed two replicas simultaneously and one
        # sweep restored them.
        assert len(fired["multi_kill"]["replicas"]) == 2
        assert fired["multi_kill"]["restored"] >= 1
        # wal_corrupt flipped real bytes mid-file.
        assert fired["wal_corrupt"]["corruption"]["log_bytes"] > 64
        # rolling_restart revived the multi_kill victims first, then
        # cycled the rest.
        restarted = fired["rolling_restart"]
        assert sorted(
            restarted["revived_first"] + restarted["restarted"]
        ) == sorted(f"replica-{i}" for i in range(3))

        assert result.lost_studies() == []
        assert result.errored_studies() == []
        stats = result.serving_stats
        # Every replica died at least once across the track.
        assert stats["failovers"] >= 3
        # The corrupted replica's restart recovered through standby logs.
        assert stats["recovery_sources"].get("standby", 0) >= 1
        assert stats["replication"]["factor"] >= 1

    def test_gated_replication_off_still_survives_single_kill(self, monkeypatch):
        """VIZIER_DISTRIBUTED_REPLICATION=0 = the pre-replication tier:
        the classic kill/revive track (external drain gate for the
        handback) still runs clean."""
        monkeypatch.setenv("VIZIER_DISTRIBUTED_REPLICATION", "0")
        config = _severity_config(
            replicas=2,
            num_studies=8,
        )
        scenario = models.build_scenario(config)
        kinds = [e.kind for e in scenario.events]
        assert "kill_replica" in kinds and "revive_replica" in kinds
        result = driver_lib.run(scenario, arm="engine")
        for event in result.events_fired:
            assert "error" not in event, event
        assert result.lost_studies() == []
        assert result.errored_studies() == []
        assert "replication" not in result.serving_stats
