"""Loadgen traffic models: determinism, mixes, bounds, event tracks.

The contract pinned here is the one the whole subsystem stands on: a
scenario's expansion is a pure function of its config — same seed, same
workload, bit for bit (arrivals, budgets, kinds, tenants, events) — and
every knob produces what it claims (Zipf bounds, guaranteed kind
coverage, sparse preseeds, crossover-straddling budgets).
"""

import dataclasses
import random
import unittest.mock

import pytest

from vizier_tpu.loadgen import models


class TestDeterminism:
    def test_same_seed_identical_expansion(self):
        config = models.smoke_config(seed=7)
        a = models.build_scenario(config)
        b = models.build_scenario(config)
        assert a.fingerprint() == b.fingerprint()
        assert [s.as_dict() for s in a.studies] == [
            s.as_dict() for s in b.studies
        ]
        assert a.events == b.events

    def test_seed_changes_everything(self):
        a = models.build_scenario(models.smoke_config(seed=0))
        b = models.build_scenario(models.smoke_config(seed=1))
        assert a.fingerprint() != b.fingerprint()
        assert [s.arrival_s for s in a.studies] != [
            s.arrival_s for s in b.studies
        ]

    def test_objectives_and_preseeds_are_seeded(self):
        scenario = models.build_scenario(models.smoke_config(seed=3))
        spec = scenario.studies[0]
        assert scenario.optimum(spec) == scenario.optimum(spec)
        assert scenario.preseed_points(spec) == scenario.preseed_points(spec)
        params = {"x0": 0.5, "x1": 0.5}
        assert scenario.objective(spec, params) == scenario.objective(
            spec, params
        )
        # The optimum lives inside the search box, so regret is bounded.
        assert all(0.2 <= v <= 0.8 for v in scenario.optimum(spec))

    def test_fingerprint_covers_arrivals(self):
        base = models.smoke_config(seed=5)
        a = models.build_scenario(base)
        b = models.build_scenario(
            dataclasses.replace(base, arrival_rate_per_s=999.0)
        )
        assert a.fingerprint() != b.fingerprint()


class TestSamplers:
    def test_zipf_budgets_bounded_and_heavy_headed(self):
        rng = random.Random(0)
        sizes = models.zipf_budgets(rng, 2000, alpha=1.1, lo=1, hi=16)
        assert min(sizes) == 1 and max(sizes) <= 16
        # Power law: size-1 studies dominate size-16 studies.
        assert sizes.count(1) > 10 * sizes.count(16)

    def test_arrivals_monotonic_and_bursty(self):
        config = models.smoke_config(
            arrival_rate_per_s=100.0, burst_factor=8.0
        )
        times = models.arrival_times(random.Random(1), config, 500)
        assert all(b > a for a, b in zip(times, times[1:]))

    def test_weighted_choice_respects_weights(self):
        rng = random.Random(2)
        draws = [
            models.weighted_choice(rng, (("a", 9.0), ("b", 1.0)))
            for _ in range(1000)
        ]
        assert draws.count("a") > 700


class TestMixes:
    def test_every_mix_kind_gets_a_study(self):
        scenario = models.build_scenario(models.smoke_config())
        assert scenario.kinds_present() == sorted(
            k for k, _ in scenario.config.kind_mix
        )

    def test_gp_kinds_validated_against_registry(self):
        with pytest.raises(ValueError, match="Unknown traffic kinds"):
            models.ScenarioConfig(kind_mix=(("nonsense", 1.0),))

    def test_sparse_kinds_preseed_past_threshold(self):
        scenario = models.build_scenario(models.smoke_config())
        threshold = scenario.config.sparse_threshold
        for spec in scenario.studies:
            if spec.kind in models.SPARSE_KINDS:
                assert spec.preseed >= threshold
            elif spec.kind in models.GP_KINDS:
                assert spec.preseed < threshold

    def test_crossover_study_guaranteed(self):
        scenario = models.build_scenario(models.smoke_config())
        crossers = scenario.crossover_studies()
        assert crossers, "ensure_crossover must stretch one exact-GP study"
        threshold = scenario.config.sparse_threshold
        for spec in crossers:
            assert spec.preseed < threshold <= spec.preseed + spec.budget


class TestEvents:
    def test_default_track_has_kill_revive_on_replica_target(self):
        scenario = models.build_scenario(
            models.smoke_config(target="replicas", replicas=2)
        )
        kinds = [e.kind for e in scenario.events]
        assert "kill_replica" in kinds and "revive_replica" in kinds

    def test_inprocess_target_has_no_replica_events(self):
        scenario = models.build_scenario(
            models.smoke_config(target="inprocess", chaos_fault_prob=0.0)
        )
        assert scenario.events == ()

    def test_parse_event_track(self):
        config = models.smoke_config()
        events = models.parse_event_track(
            "kill_replica:owner:0@0.4,revive_replica:owner:0@0.7,"
            "chaos_on@0.5,chaos_off@0.6",
            config,
        )
        assert [e.kind for e in events] == [
            "kill_replica",
            "chaos_on",
            "chaos_off",
            "revive_replica",
        ]
        assert events[0].arg == "owner:0"
        assert all(e.at_completed >= 1 for e in events)

    def test_unknown_event_kind_rejected(self):
        with pytest.raises(ValueError, match="Unknown event kind"):
            models.EventSpec(1, "explode")

    def test_three_replica_tier_gets_the_severity_track(self):
        scenario = models.build_scenario(
            models.smoke_config(target="replicas", replicas=3)
        )
        kinds = [e.kind for e in scenario.events]
        assert "multi_kill" in kinds
        assert "wal_corrupt" in kinds
        assert "rolling_restart" in kinds
        assert "kill_replica" not in kinds  # severity replaces the pair
        multi = next(e for e in scenario.events if e.kind == "multi_kill")
        assert multi.arg == "2"

    def test_soak_config_is_three_replica_severity(self):
        config = models.soak_config()
        assert config.replicas == 3
        kinds = [e.kind for e in models.build_scenario(config).events]
        for kind in ("multi_kill", "wal_corrupt", "rolling_restart"):
            assert kind in kinds

    def test_severity_events_parse_and_fingerprint(self):
        config = models.smoke_config(replicas=3)
        events = models.parse_event_track(
            "multi_kill:2@0.35,wal_corrupt:owner:0@0.45,"
            "rolling_restart@0.75",
            config,
        )
        assert [e.kind for e in events] == [
            "multi_kill",
            "wal_corrupt",
            "rolling_restart",
        ]
        # Scripted severity events are part of the scenario identity.
        base = models.build_scenario(config)
        scripted = models.build_scenario(
            dataclasses.replace(config, events=events)
        )
        assert scripted.fingerprint() != base.fingerprint()
        again = models.build_scenario(
            dataclasses.replace(config, events=events)
        )
        assert scripted.fingerprint() == again.fingerprint()


class TestEnvConfig:
    def test_from_env_reads_loadgen_switches(self):
        with unittest.mock.patch.dict(
            "os.environ",
            {
                "VIZIER_LOADGEN_SEED": "42",
                "VIZIER_LOADGEN_SCALE": "0.5",
                "VIZIER_LOADGEN_STUDIES": "10",
                "VIZIER_LOADGEN_TARGET": "inprocess",
            },
        ):
            config = models.ScenarioConfig.from_env()
        assert config.seed == 42
        assert config.scale == 0.5
        assert config.num_studies == 10
        assert config.target == "inprocess"
        assert config.total_studies == 5

    def test_from_env_event_track(self):
        with unittest.mock.patch.dict(
            "os.environ",
            {"VIZIER_LOADGEN_EVENTS": "chaos_on@0.2,chaos_off@0.4"},
        ):
            config = models.ScenarioConfig.from_env()
        assert [e.kind for e in config.events] == ["chaos_on", "chaos_off"]

    def test_overrides_beat_env(self):
        with unittest.mock.patch.dict(
            "os.environ", {"VIZIER_LOADGEN_SEED": "42"}
        ):
            assert models.ScenarioConfig.from_env(seed=7).seed == 7
