"""Tests for trial-curve regression (trial_regression_utils parity)."""

import numpy as np

from vizier_tpu import pyvizier as vz
from vizier_tpu.algorithms import regression
from vizier_tpu.pyvizier import trial as trial_


def _curve_trial(tid, lr, n_steps=10, final=None, metric="loss"):
    t = trial_.Trial(id=tid, parameters={"lr": lr})
    values = []
    for s in range(1, n_steps + 1):
        v = 1.0 / (lr * s + 0.1)  # decaying curve, faster for larger lr
        values.append(v)
        t.measurements.append(
            trial_.Measurement(metrics={metric: v}, steps=s)
        )
    if final is not None or n_steps:
        t.complete(
            trial_.Measurement(
                metrics={metric: final if final is not None else values[-1]},
                steps=n_steps,
            )
        )
    return t


class TestTrialData:
    def test_from_trial_sorted_deduped(self):
        t = trial_.Trial(id=1, parameters={"lr": 0.1})
        t.measurements.append(trial_.Measurement(metrics={"loss": 3.0}, steps=2))
        t.measurements.append(trial_.Measurement(metrics={"loss": 5.0}, steps=1))
        t.measurements.append(trial_.Measurement(metrics={"loss": 2.9}, steps=2))
        data = regression.TrialData.from_trial(t, "loss")
        assert data.steps == [1.0, 2.0]
        assert data.objective_values == [5.0, 2.9]  # later measurement wins

    def test_value_at_interpolates(self):
        data = regression.TrialData(
            id=1, parameters={}, steps=[0.0, 10.0], objective_values=[0.0, 1.0]
        )
        assert data.value_at(5.0) == 0.5
        assert data.value_at(20.0) == 1.0  # clamped

    def test_extrapolation_uses_final_slope(self):
        data = regression.TrialData(
            id=1, parameters={}, steps=[0.0, 1.0, 2.0],
            objective_values=[0.0, 1.0, 2.0],
        )
        assert data.extrapolate_objective_value(4.0) == 4.0

    def test_default_steps_fall_back_to_arrival_order(self):
        """Measurements appended without steps (default 0.0) must not
        collapse onto one point."""
        t = trial_.Trial(id=1, parameters={})
        for v in [5.0, 4.0, 3.0]:
            t.measurements.append(trial_.Measurement(metrics={"loss": v}))
        t.complete(trial_.Measurement(metrics={"loss": 2.0}))
        data = regression.TrialData.from_trial(t, "loss")
        assert len(data.steps) == 4
        assert data.objective_values == [5.0, 4.0, 3.0, 2.0]

    def test_missing_metric_returns_none(self):
        t = trial_.Trial(id=1, parameters={})
        t.complete(trial_.Measurement(metrics={"other": 1.0}))
        assert regression.TrialData.from_trial(t, "loss") is None


class TestGBMAutoRegressor:
    def test_underfit_guard(self):
        reg = regression.GBMAutoRegressor("loss", min_train_trials=5)
        assert not reg.train([_curve_trial(1, 0.1)])
        assert not reg.is_trained
        assert reg.predict(_curve_trial(9, 0.1)) is None

    def test_learns_curve_to_final_mapping(self):
        rng = np.random.default_rng(0)
        completed = [
            _curve_trial(i + 1, float(lr))
            for i, lr in enumerate(rng.uniform(0.05, 1.0, size=30))
        ]
        reg = regression.GBMAutoRegressor("loss", seed=0)
        assert reg.train(completed)
        # Predict for a partial (active) trial with only 4 of 10 steps.
        lr = 0.5
        partial = trial_.Trial(id=99, parameters={"lr": lr})
        for s in range(1, 5):
            partial.measurements.append(
                trial_.Measurement(metrics={"loss": 1.0 / (lr * s + 0.1)}, steps=s)
            )
        pred = reg.predict(partial)
        true_final = 1.0 / (lr * 10 + 0.1)
        assert pred is not None
        assert abs(pred - true_final) < 0.5  # same order as the true final


class TestHallucinator:
    def test_completes_stopped_trials(self):
        rng = np.random.default_rng(1)
        completed = [
            _curve_trial(i + 1, float(lr))
            for i, lr in enumerate(rng.uniform(0.05, 1.0, size=20))
        ]
        h = regression.TrialHallucinator("loss")
        assert h.train(completed)
        stopped = trial_.Trial(id=50, parameters={"lr": 0.3})
        for s in range(1, 4):
            stopped.measurements.append(
                trial_.Measurement(metrics={"loss": 1.0 / (0.3 * s + 0.1)}, steps=s)
            )
        out = h.hallucinate_final_measurements([stopped])
        assert len(out) == 1
        assert out[0].is_completed
        assert out[0].metadata.ns("regression")["hallucinated"] == "True"
        assert np.isfinite(out[0].final_measurement.metrics["loss"].value)

    def test_skips_trials_without_curves(self):
        h = regression.TrialHallucinator("loss")
        h.train(
            [_curve_trial(i + 1, 0.1 + 0.02 * i) for i in range(10)]
        )
        bare = trial_.Trial(id=9, parameters={"lr": 0.1})
        assert h.hallucinate_final_measurements([bare]) == []
