"""Regression-rule early stopping: policy behavior + service dispatch."""

import numpy as np

from vizier_tpu import pyvizier as vz
from vizier_tpu.algorithms import early_stopping
from vizier_tpu.pythia import local_policy_supporters
from vizier_tpu.pythia import policy as policy_lib
from vizier_tpu.pyvizier import trial as trial_


def _problem():
    p = vz.ProblemStatement()
    p.search_space.root.add_float_param("lr", 0.01, 1.0)
    p.metric_information.append(
        vz.MetricInformation(name="acc", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return p


def _curve_trial(tid, lr, n_steps=10, partial=False):
    """acc curves saturate at lr/(lr+0.1): larger lr → better final."""
    t = trial_.Trial(id=tid, parameters={"lr": lr})
    top = lr / (lr + 0.1)
    steps = range(1, (4 if partial else n_steps) + 1)
    for s in steps:
        t.measurements.append(
            trial_.Measurement(
                metrics={"acc": top * (1 - np.exp(-s / 3.0))}, steps=s
            )
        )
    if not partial:
        t.complete(trial_.Measurement(metrics={"acc": top}, steps=n_steps))
    return t


def _supporter_with_history(num_completed=20, seed=0):
    supporter = local_policy_supporters.InRamPolicySupporter(_problem())
    rng = np.random.default_rng(seed)
    trials = [
        _curve_trial(i + 1, float(lr))
        for i, lr in enumerate(rng.uniform(0.02, 0.9, size=num_completed))
    ]
    supporter.AddTrials(trials)
    return supporter


class TestRegressionEarlyStopPolicy:
    def _decide(self, supporter, active_trials):
        # AddTrials reassigns ids; recover them from the stored copies.
        supporter.AddTrials(active_trials)
        stored = supporter.GetTrials()[-len(active_trials):]
        ids = [t.id for t in stored]
        policy = early_stopping.RegressionEarlyStopPolicy(
            supporter=supporter, min_num_trials=10
        )
        request = policy_lib.EarlyStopRequest(
            study_descriptor=supporter.study_descriptor(),
            trial_ids=ids,
        )
        decisions = {d.id: d for d in policy.early_stop(request).decisions}
        return [decisions[i] for i in ids]

    def test_bad_trajectory_stopped_good_kept(self):
        supporter = _supporter_with_history()
        bad = _curve_trial(100, 0.03, partial=True)  # saturates low
        good = _curve_trial(101, 0.85, partial=True)  # saturates high
        d_bad, d_good = self._decide(supporter, [bad, good])
        assert d_bad.should_stop
        assert not d_good.should_stop

    def test_underfit_keeps_running(self):
        supporter = _supporter_with_history(num_completed=3)
        active = _curve_trial(50, 0.05, partial=True)
        (d,) = self._decide(supporter, [active])
        assert not d.should_stop
        assert "Too little" in d.reason

    def test_no_curve_keeps_running(self):
        supporter = _supporter_with_history()
        bare = trial_.Trial(id=60, parameters={"lr": 0.5})
        (d,) = self._decide(supporter, [bare])
        assert not d.should_stop


class TestServiceDispatch:
    def test_rule_round_trips_and_selects_policy(self):
        from vizier_tpu.service import proto_converters

        config = vz.StudyConfig.from_problem(_problem(), vz.Algorithm.RANDOM_SEARCH)
        config.automated_stopping_config = (
            vz.AutomatedStoppingConfig.regression_stopping_spec(min_num_trials=7)
        )
        proto = proto_converters.study_config_to_proto(config)
        assert proto.early_stopping.rule == "regression"
        back = proto_converters.study_config_from_proto(proto)
        assert back.automated_stopping_config.rule == "regression"
        assert back.automated_stopping_config.min_num_trials == 7

    def test_median_default_for_old_protos(self):
        from vizier_tpu.service import proto_converters
        from vizier_tpu.service.protos import study_pb2

        config = vz.StudyConfig.from_problem(_problem(), vz.Algorithm.RANDOM_SEARCH)
        config.automated_stopping_config = vz.AutomatedStoppingConfig()
        proto = proto_converters.study_config_to_proto(config)
        proto.early_stopping.rule = ""  # pre-field serialization
        back = proto_converters.study_config_from_proto(proto)
        assert back.automated_stopping_config.rule == "median"
