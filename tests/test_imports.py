"""Ship gate: every vizier_tpu module must import.

Walks the whole package with pkgutil so a facade typo (like round 2's
``NumpyDecoder`` import of a nonexistent name) can never again make the
package unimportable without failing CI at collection time.

Modules gated on libraries absent from the image (pyglove, ray) are allowed
to raise ImportError mentioning that library only.
"""

from __future__ import annotations

import importlib
import pkgutil

import pytest

import vizier_tpu

# Modules whose import is honestly gated on a library absent from the image.
_GATED_OK = ("pyglove", "ray")


def _iter_module_names():
    yield "vizier_tpu"
    for mod in pkgutil.walk_packages(vizier_tpu.__path__, prefix="vizier_tpu."):
        yield mod.name


@pytest.mark.parametrize("name", sorted(set(_iter_module_names())))
def test_module_imports(name: str) -> None:
    try:
        importlib.import_module(name)
    except ImportError as e:
        # Only a failure to import the gated library ITSELF is skippable —
        # matching on the message would also match e.g. "PaddedArray".
        missing = (getattr(e, "name", None) or "").split(".")[0]
        if missing in _GATED_OK:
            pytest.skip(f"gated on absent library: {e}")
        raise
