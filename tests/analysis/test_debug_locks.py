"""Instrumented-lock factory: runtime acquisition order is recorded and
cross-checked against the static graph — observed edges the static pass
missed are surfaced, intentional/static edges are confirmed."""

import threading

from vizier_tpu.analysis import debug_locks


class TestObservatoryMechanics:
    def test_nested_acquisition_records_edge(self):
        with debug_locks.instrument() as obs:
            a = threading.Lock()
            b = threading.Lock()
            with a:
                with b:
                    pass
        pairs = obs.edge_pairs()
        assert len(pairs) == 1
        ((src, dst),) = pairs
        assert src.line < dst.line  # a was created before b
        assert obs.acquisitions == 2

    def test_reentrant_rlock_no_self_edge(self):
        with debug_locks.instrument() as obs:
            r = threading.RLock()
            with r:
                with r:
                    pass
        assert obs.edge_pairs() == set()

    def test_condition_wait_releases_held_lock(self):
        # A waiter holding ONLY the condition must not manufacture edges
        # against locks acquired by the notifier while it sleeps.
        with debug_locks.instrument() as obs:
            cond = threading.Condition()
            other = threading.Lock()
            state = {"ready": False}

            def waiter():
                with cond:
                    while not state["ready"]:
                        cond.wait(timeout=5)

            t = threading.Thread(target=waiter)
            t.start()
            import time

            time.sleep(0.05)
            with other:  # acquired while the waiter sleeps in wait()
                with cond:
                    state["ready"] = True
                    cond.notify_all()
            t.join(timeout=5)
        sites = {s.line for s, _ in obs.edge_pairs()} | {
            d.line for _, d in obs.edge_pairs()
        }
        # The only edge is other->cond (the notifier's nesting); the
        # sleeping waiter contributes none.
        assert len(obs.edge_pairs()) == 1

    def test_unpatched_after_exit(self):
        with debug_locks.instrument():
            pass
        assert not isinstance(
            threading.Lock(), debug_locks._InstrumentedBase
        )


class TestCrossCheckAgainstStaticGraph:
    def test_real_serving_locks_confirmed_by_static_graph(
        self, real_suite_result, repo_root
    ):
        """Drive the REAL designer-cache/coalescer path under instrumented
        locks; every observed nesting must be predicted statically."""
        with debug_locks.instrument() as obs:
            from vizier_tpu.serving.coalescer import RequestCoalescer
            from vizier_tpu.serving.designer_cache import DesignerStateCache

            cache = DesignerStateCache(
                max_entries=4, observe_latency=False
            )
            coalescer = RequestCoalescer(observe_latency=False)

            def one_study(name):
                entry = cache.get_or_create(name, lambda: object())
                with entry.lock:
                    # The policy's error path: invalidate under the entry
                    # lock (the entry.lock -> map lock static edge).
                    cache.invalidate(name)

            threads = [
                threading.Thread(target=one_study, args=(f"s{i}",))
                for i in range(4)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=10)
            coalescer.coalesce("k", lambda: 42)
        check = debug_locks.check_against_static(
            obs, real_suite_result.lock_result, repo_root
        )
        assert check.missing_static == [], (
            "runtime lock order the static graph missed: "
            f"{[(s, d) for s, d, _ in check.missing_static]}"
        )
        assert (
            "CachedDesignerEntry.lock",
            "DesignerStateCache._lock",
        ) in check.confirmed

    def test_chaos_workload_order_matches_static_graph(
        self, real_suite_result, repo_root
    ):
        """Seeded chaos faults drive the serving cache down BOTH its happy
        and error paths (invalidate-under-entry-lock) across threads; every
        acquisition order the chaos run observes must be statically
        predicted."""
        from vizier_tpu.testing import chaos as chaos_lib

        monkey = chaos_lib.ChaosMonkey(seed=7, failure_prob=0.4)
        with debug_locks.instrument() as obs:
            from vizier_tpu.serving.coalescer import RequestCoalescer
            from vizier_tpu.serving.designer_cache import DesignerStateCache

            cache = DesignerStateCache(max_entries=3, observe_latency=False)
            coalescer = RequestCoalescer(observe_latency=False)

            def worker(tid):
                for step in range(6):
                    name = f"s{(tid + step) % 4}"
                    entry = cache.get_or_create(name, lambda: object())
                    try:
                        with entry.lock:
                            # The policy's critical section: chaos decides
                            # between a clean suggest and the error path,
                            # which (like CachedDesignerStatePolicy)
                            # invalidates UNDER the entry lock.
                            try:
                                monkey.strike(f"suggest/{name}")
                            except chaos_lib.InjectedFaultError:
                                cache.invalidate(name)
                                raise
                    except chaos_lib.InjectedFaultError:
                        pass
                    coalescer.coalesce((name, step), lambda: step)

            threads = [
                threading.Thread(target=worker, args=(i,)) for i in range(4)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
        assert monkey.total_faults() > 0, "chaos never fired; weak test"
        check = debug_locks.check_against_static(
            obs, real_suite_result.lock_result, repo_root
        )
        assert check.missing_static == [], (
            "chaos run observed lock order the static graph missed: "
            f"{[(s, d) for s, d, _ in check.missing_static]}"
        )

    def test_seeded_inversion_is_caught(self, real_suite_result, repo_root):
        """An acquisition order the static graph does NOT contain must be
        reported as a gap — the harness's whole point."""
        with debug_locks.instrument() as obs:
            from vizier_tpu.serving.coalescer import RequestCoalescer
            from vizier_tpu.serving.designer_cache import DesignerStateCache

            cache = DesignerStateCache(max_entries=4, observe_latency=False)
            coalescer = RequestCoalescer(observe_latency=False)
            entry = cache.get_or_create("s", lambda: object())
            with entry.lock:
                with coalescer._lock:  # no static code path does this
                    pass
        check = debug_locks.check_against_static(
            obs, real_suite_result.lock_result, repo_root
        )
        assert (
            "CachedDesignerEntry.lock",
            "RequestCoalescer._lock",
        ) in [(s, d) for s, d, _ in check.missing_static]

    def test_creation_site_maps_to_static_site(
        self, real_suite_result, repo_root
    ):
        with debug_locks.instrument() as obs:
            from vizier_tpu.serving.designer_cache import DesignerStateCache

            DesignerStateCache(max_entries=2, observe_latency=False)
        mapped = {
            debug_locks.map_site(
                s, real_suite_result.lock_result.sites, repo_root
            )
            for s in obs.sites
        }
        assert "DesignerStateCache._lock" in mapped
