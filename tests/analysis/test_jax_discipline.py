"""JAX-discipline pass: seeded host syncs / tracer branches / retrace
hazards are each reported; the disciplined fixture and the real tree are
clean modulo the baseline."""

from vizier_tpu.analysis import jax_discipline

_FIX = "tests/analysis/fixtures/bad_jit_sync.py"


def _result(fixtures_project):
    return jax_discipline.run(fixtures_project)


class TestSeededFixtures:
    def test_host_syncs_in_jitted_fn(self, fixtures_project):
        keys = {f.key for f in _result(fixtures_project).findings}
        assert f"host-sync@{_FIX}::bad_host_syncs:block_until_ready" in keys
        assert f"host-sync@{_FIX}::bad_host_syncs:np.asarray" in keys
        assert f"host-sync@{_FIX}::bad_host_syncs:.item()" in keys
        assert f"host-sync@{_FIX}::bad_host_syncs:float()" in keys

    def test_tracer_branch(self, fixtures_project):
        keys = {f.key for f in _result(fixtures_project).findings}
        assert f"tracer-branch@{_FIX}::bad_tracer_branch:total" in keys

    def test_sync_in_helper_reached_from_jit(self, fixtures_project):
        # Reachability, not just direct decoration: the helper itself is
        # not decorated but is traced through the jitted caller.
        result = _result(fixtures_project)
        assert f"{_FIX}::_helper_reached_from_jit" in result.traced
        keys = {f.key for f in result.findings}
        assert f"host-sync@{_FIX}::_helper_reached_from_jit:np.asarray" in keys

    def test_retrace_hazards_at_call_sites(self, fixtures_project):
        keys = {f.key for f in _result(fixtures_project).findings}
        assert (
            f"unhashable-static@{_FIX}::bad_call_sites:"
            "takes_static_sizes.sizes" in keys
        )
        assert (
            f"shape-unstable-static@{_FIX}::bad_call_sites:"
            "takes_static_sizes.sizes" in keys
        )
        assert f"jit-in-loop@{_FIX}::bad_call_sites" in keys

    def test_clean_fixture_and_tuple_static_unflagged(self, fixtures_project):
        findings = _result(fixtures_project).findings
        assert not any("clean_module" in f.path for f in findings)
        assert not any("clean_static_usage" in f.key for f in findings)

    def test_exact_seeded_finding_count(self, fixtures_project):
        # 4 host syncs + 1 tracer branch + 1 helper sync + 3 call-site
        # hazards and nothing else.
        assert len(_result(fixtures_project).findings) == 9


class TestRealTree:
    def test_no_unbaselined_findings(self, real_suite_result):
        assert real_suite_result.passes["jax_discipline"].new == []

    def test_roots_cover_the_designer_hot_path(self, real_suite_result):
        roots = {
            r.fn.qualname for r in real_suite_result.jax_result.roots
        }
        # The GP-bandit train/acquisition programs and the cross-study
        # batched entry points must all be discovered as jit roots.
        assert any("_train_gp" in q for q in roots)
        assert any("_maximize_acquisition" in q for q in roots)
        assert any("train_batched" in q for q in roots)
        assert len(roots) >= 15

    def test_statics_parsed_from_partial_decorators(self, real_suite_result):
        by_name = {
            r.fn.name: r for r in real_suite_result.jax_result.roots
        }
        assert "model" in by_name["_train_gp"].static_names
        assert "num_restarts" in by_name["_train_gp"].static_names
