"""Baseline machinery: the TOML-subset reader, matching, and staleness."""

import pytest

from vizier_tpu.analysis import baseline as baseline_lib
from vizier_tpu.analysis import common


class TestTomlSubset:
    def test_tables_arrays_and_scalars(self):
        data = baseline_lib.parse_toml_subset(
            """
            version = 1  # trailing comment
            title = "with # hash inside"

            [tool.vizier_analysis]
            paths = ["vizier_tpu", "tools"]
            fast = true
            ratio = 0.5

            [[finding]]
            pass = "lock_order"
            key = "a->b"
            reason = "why"

            [[finding]]
            pass = "env_registry"
            key = "c"
            reason = "also why"
            """
        )
        assert data["version"] == 1
        assert data["title"] == "with # hash inside"
        assert data["tool"]["vizier_analysis"]["paths"] == [
            "vizier_tpu",
            "tools",
        ]
        assert data["tool"]["vizier_analysis"]["fast"] is True
        assert data["tool"]["vizier_analysis"]["ratio"] == 0.5
        assert [f["key"] for f in data["finding"]] == ["a->b", "c"]

    def test_multiline_array(self):
        data = baseline_lib.parse_toml_subset(
            'paths = [\n  "a",\n  "b",\n]\n'
        )
        assert data["paths"] == ["a", "b"]

    def test_unsupported_value_is_loud(self):
        with pytest.raises(baseline_lib.TomlSubsetError):
            baseline_lib.parse_toml_subset("when = 2024-01-01\n")


def _finding(key, pass_name="lock_order"):
    return common.Finding(
        pass_name=pass_name,
        rule="r",
        key=key,
        message="m",
        path="p.py",
        line=1,
    )


class TestBaselineMatching:
    def test_apply_partitions_and_reports_stale(self, tmp_path):
        path = tmp_path / "baseline.toml"
        path.write_text(
            """
            [[finding]]
            pass = "lock_order"
            key = "known"
            reason = "intentional"

            [[finding]]
            pass = "lock_order"
            key = "gone"
            reason = "used to match"
            """
        )
        bl = baseline_lib.load_baseline(str(path))
        new, accepted, stale = bl.apply([_finding("known"), _finding("fresh")])
        assert [f.key for f in new] == ["fresh"]
        assert [f.key for f in accepted] == ["known"]
        assert [e.key for e in stale] == ["gone"]

    def test_key_matches_within_pass_only(self, tmp_path):
        path = tmp_path / "baseline.toml"
        path.write_text(
            '[[finding]]\npass = "env_registry"\nkey = "k"\nreason = "x"\n'
        )
        bl = baseline_lib.load_baseline(str(path))
        new, accepted, _ = bl.apply([_finding("k", pass_name="lock_order")])
        assert len(new) == 1 and not accepted

    def test_empty_reason_rejected(self, tmp_path):
        path = tmp_path / "baseline.toml"
        path.write_text('[[finding]]\npass = "p"\nkey = "k"\nreason = "  "\n')
        with pytest.raises(baseline_lib.TomlSubsetError, match="reason"):
            baseline_lib.load_baseline(str(path))

    def test_missing_file_is_empty_baseline(self, tmp_path):
        bl = baseline_lib.load_baseline(str(tmp_path / "nope.toml"))
        assert bl.entries == []
