"""tools/check_analysis.py: exit codes, config plumbing, JSON output.

The CLI is exercised in-process through its main() (cheap); one
subprocess test proves the real entry point works without pytest's import
state.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from vizier_tpu.analysis import suite


def _load_cli(repo_root):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_analysis", os.path.join(repo_root, "tools", "check_analysis.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def cli(repo_root):
    return _load_cli(repo_root)


class TestExitCodes:
    def test_clean_tree_exits_zero_under_budget(self, cli, capsys):
        t0 = time.perf_counter()
        rc = cli.main([])
        elapsed = time.perf_counter() - t0
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "ANALYSIS OK" in out
        # Acceptance bound is <30s for all four passes; enforce it with
        # headroom so drift is visible early.
        assert elapsed < 30, f"analysis took {elapsed:.1f}s"

    def test_seeded_fixtures_exit_nonzero(self, cli, tmp_path, capsys):
        empty = tmp_path / "empty_baseline.toml"
        empty.write_text("version = 1\n")
        rc = cli.main(
            [
                "--paths",
                "tests/analysis/fixtures",
                "--baseline",
                str(empty),
            ]
        )
        out = capsys.readouterr().out
        assert rc == 1
        assert "bad_lock_cycle" in out
        assert "ANALYSIS FAILED" in out

    def test_stale_baseline_fails_only_in_strict_mode(
        self, cli, tmp_path, capsys
    ):
        stale = tmp_path / "stale.toml"
        stale.write_text(
            '[[finding]]\npass = "lock_order"\nkey = "cycle:nope"\n'
            'reason = "never matches"\n'
        )
        rc = cli.main(
            ["--paths", "tests/analysis/fixtures/clean_module.py",
             "--baseline", str(stale)]
        )
        capsys.readouterr()
        assert rc == 0
        rc = cli.main(
            ["--paths", "tests/analysis/fixtures/clean_module.py",
             "--baseline", str(stale), "--strict-baseline"]
        )
        out = capsys.readouterr().out
        assert rc == 1
        assert "STALE" in out


class TestConfigPlumbing:
    def test_pyproject_section_is_read(self, repo_root):
        config = suite.load_config(repo_root)
        assert "vizier_tpu" in config.paths
        assert config.baseline == "vizier_tpu/analysis/baseline.toml"
        assert set(config.passes) == set(suite.ALL_PASSES)
        assert "VizierServicer._study_locks" in config.critical_locks

    def test_single_pass_selection(self, cli, capsys):
        rc = cli.main(["--pass", "env_registry"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "[env_registry]" in out
        assert "[lock_order]" not in out

    def test_json_output_with_lock_graph(self, cli, capsys):
        rc = cli.main(["--json"])
        payload = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert payload["ok"] is True
        assert payload["lock_graph"]["sites"], "lock graph missing"
        site_ids = {s["lock_id"] for s in payload["lock_graph"]["sites"]}
        assert "VizierServicer._study_locks" in site_ids


@pytest.mark.slow
class TestRealSubprocess:
    def test_entry_point_runs_standalone(self, repo_root):
        proc = subprocess.run(
            [sys.executable, os.path.join("tools", "check_analysis.py")],
            cwd=repo_root,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "ANALYSIS OK" in proc.stdout
