"""Seeded compute-IR conformance violations (tests/analysis only).

A registered program missing prewarm coverage, the device_phase literal,
and one IR hook — each must be a distinct finding of the compute_ir pass.
"""

from vizier_tpu.compute import registry as compute_registry


class _FixtureDesigner:
    def suggest(self, count=None):
        return []


class IncompleteProgram:
    """Registered but nonconforming: no finalize, no prewarm_factory, no
    device_phase, no shardable_batch_axis — the pass must flag each gap
    separately."""

    kind = "fixture_incomplete"

    def bucket_key(self, designer, count):
        return None

    def prepare(self, designer, count):
        return {}

    def device_program(self, items, pad_to=None):
        return []


def _register_fixture():  # never called; the pass scans the AST only
    compute_registry.register(_FixtureDesigner, IncompleteProgram())
