"""Seeded lock-order violations: a classic ABBA deadlock cycle plus a
blocking wait under a held lock. NEVER imported — the analysis passes read
this file as AST only; it exists so tests/analysis/test_lock_order.py can
assert each seeded finding is reported (and nothing else)."""

import threading
import time


class AccountA:
    def __init__(self):
        self.lock_a = threading.Lock()

    def transfer_to_b(self, b: "AccountB"):
        # A -> B ...
        with self.lock_a:
            with b.lock_b:
                pass

    def sleep_while_locked(self):
        # Blocking op under a held (critical) lock.
        with self.lock_a:
            time.sleep(0.1)


class AccountB:
    def __init__(self):
        self.lock_b = threading.Lock()

    def transfer_to_a(self, a: AccountA):
        # ... and B -> A: the ABBA cycle.
        with self.lock_b:
            with a.lock_a:
                pass


class Waiter:
    def __init__(self):
        self.cond = threading.Condition()
        self.done = threading.Event()

    def ok_same_condition_wait(self):
        # Waiting on the condition you hold RELEASES it: not a violation.
        with self.cond:
            self.cond.wait(timeout=0.1)

    def bad_event_wait_under_cond(self):
        # Waiting on a DIFFERENT primitive while holding the condition.
        with self.cond:
            self.done.wait(timeout=0.1)
