"""Seeded env-registry violations for tests/analysis/test_env_registry.py.
Never imported — analyzed as AST only."""

import os


def undeclared_get():
    return os.environ.get("VIZIER_NOT_A_REAL_SWITCH", "1")


def undeclared_subscript():
    return os.environ["VIZIER_ALSO_NOT_DECLARED"]


def undeclared_getenv():
    return os.getenv("VIZIER_NOT_A_REAL_SWITCH")


def read_of_reserved_constant():
    # VIZIER_METHODS is a declared *constant* (the gRPC method table), not
    # an environment switch; reading it from the environment is a bug.
    return os.environ.get("VIZIER_METHODS")


def dynamic_read(name: str):
    # Hides the switch name from static scanning; must go through
    # vizier_tpu.analysis.registry helpers instead.
    return os.environ.get(name, "0")


def declared_read_is_fine():
    return os.environ.get("VIZIER_BATCHING", "1")
