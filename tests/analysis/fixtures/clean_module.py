"""A well-behaved module: consistent lock order, leaf critical sections,
declared env reads, disciplined jit code. The analysis passes must report
ZERO findings here. Never imported — analyzed as AST only."""

import functools
import os
import threading

import jax
import jax.numpy as jnp


class OrderedPair:
    """Always outer -> inner: a consistent global order, no cycle."""

    def __init__(self):
        self.outer = threading.Lock()
        self.inner = threading.Lock()
        self.items = []

    def push(self, item):
        with self.outer:
            with self.inner:
                self.items.append(item)

    def pop(self):
        with self.outer:
            with self.inner:
                return self.items.pop() if self.items else None


@functools.partial(jax.jit, static_argnames=("axis",))
def disciplined_reduce(x, axis):
    # Shape-derived branching is static under tracing: allowed.
    if x.ndim > 1:
        return jnp.sum(x, axis=axis)
    return jnp.sum(x)


def read_declared_switch():
    return os.environ.get("VIZIER_OBSERVABILITY", "1")
