"""Seeded JAX-discipline violations (host syncs, tracer branch, retrace
hazards) for tests/analysis/test_jax_discipline.py. Never imported —
analyzed as AST only, so the bodies need not be runnable jax code."""

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("scale",))
def bad_host_syncs(x, scale):
    # Each of these forces a device flush inside the traced program.
    x.block_until_ready()
    host = np.asarray(x)
    scalar = x.mean().item()
    coerced = float(host)
    return x * scale + scalar + coerced


@jax.jit
def bad_tracer_branch(x):
    total = jnp.sum(x)
    if total > 0:  # Python branch on a traced value
        return x
    return -x


def _helper_reached_from_jit(y):
    # Reachable from jitted caller below: sync flagged here too.
    return np.asarray(y)


@jax.jit
def bad_sync_via_helper(x):
    return _helper_reached_from_jit(x)


@functools.partial(jax.jit, static_argnames=("sizes",))
def takes_static_sizes(x, sizes):
    return x


def bad_call_sites(x, items):
    # Unhashable literal as a jit-static: TypeError at trace time.
    takes_static_sizes(x, [1, 2, 3])
    # Per-request len() as a jit-static: a recompile per distinct size.
    takes_static_sizes(x, len(items))
    for _ in range(3):
        # A fresh jitted callable per iteration: retraces every pass.
        fresh = jax.jit(lambda v: v + 1)
        x = fresh(x)
    return x


def clean_static_usage(x):
    # Tuple statics and hoisted jit: no findings here.
    return takes_static_sizes(x, (1, 2, 3))
