"""Lock-order pass: seeded fixtures report exactly the planted findings;
the real tree is clean modulo the checked-in baseline; the static graph
covers every threading.Lock/RLock/Condition site in vizier_tpu/."""

import os
import re

from vizier_tpu.analysis import lock_order


def _fixture_result(fixtures_project):
    return lock_order.run(
        fixtures_project,
        critical_locks=("AccountA.lock_a", "Waiter.cond"),
    )


class TestSeededFixtures:
    def test_abba_cycle_detected(self, fixtures_project):
        result = _fixture_result(fixtures_project)
        cycles = [f for f in result.findings if f.rule == "lock-cycle"]
        assert len(cycles) == 1
        assert cycles[0].key == "cycle:AccountA.lock_a->AccountB.lock_b"

    def test_sleep_under_critical_lock_flagged(self, fixtures_project):
        result = _fixture_result(fixtures_project)
        keys = {f.key for f in result.findings}
        assert (
            "AccountA.lock_a->wait@tests/analysis/fixtures/"
            "bad_lock_cycle.py::AccountA.sleep_while_locked" in keys
        )

    def test_foreign_wait_under_condition_flagged(self, fixtures_project):
        result = _fixture_result(fixtures_project)
        keys = {f.key for f in result.findings}
        assert (
            "Waiter.cond->wait@tests/analysis/fixtures/"
            "bad_lock_cycle.py::Waiter.bad_event_wait_under_cond" in keys
        )

    def test_same_condition_wait_is_exempt(self, fixtures_project):
        result = _fixture_result(fixtures_project)
        assert not any(
            "ok_same_condition_wait" in f.key for f in result.findings
        )

    def test_clean_module_has_no_findings_and_ordered_edges(
        self, fixtures_project
    ):
        result = _fixture_result(fixtures_project)
        assert not any("clean_module" in f.path for f in result.findings)
        assert ("OrderedPair.outer", "OrderedPair.inner") in result.edge_pairs()

    def test_exactly_the_seeded_findings(self, fixtures_project):
        # Nothing beyond the three planted violations: precision matters as
        # much as recall, or the baseline rots.
        result = _fixture_result(fixtures_project)
        assert len(result.findings) == 3


class TestRealTree:
    def test_no_unbaselined_findings(self, real_suite_result):
        assert real_suite_result.passes["lock_order"].new == []

    def test_intentional_exceptions_are_baselined_not_silent(
        self, real_suite_result
    ):
        # The per-study entry-lock-over-compute design must stay VISIBLE as
        # a baselined finding — if it vanishes, either the code or the
        # analyzer regressed.
        accepted = {
            f.key for f in real_suite_result.passes["lock_order"].accepted
        }
        assert (
            "CachedDesignerEntry.lock->device_compute@vizier_tpu/serving/"
            "policy.py::CachedDesignerStatePolicy._run_designer" in accepted
        )

    def test_graph_covers_every_threading_lock_site(
        self, real_suite_result, repo_root
    ):
        """Every textual threading.Lock/RLock/Condition construction in
        vizier_tpu/ must appear as a node of the static graph."""
        sites = {
            (s.path, s.line) for s in real_suite_result.lock_result.sites
        }
        site_files = {s.path for s in real_suite_result.lock_result.sites}
        pattern = re.compile(r"threading\.(Lock|RLock|Condition)\(\)")
        missing = []
        for dirpath, dirnames, filenames in os.walk(
            os.path.join(repo_root, "vizier_tpu")
        ):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in filenames:
                if not fn.endswith(".py"):
                    continue
                abspath = os.path.join(dirpath, fn)
                rel = os.path.relpath(abspath, repo_root)
                with open(abspath, "r", encoding="utf-8") as f:
                    for lineno, line in enumerate(f, 1):
                        if pattern.search(line) and (rel, lineno) not in sites:
                            missing.append(f"{rel}:{lineno}")
        assert not missing, f"lock sites not in the static graph: {missing}"
        # Factory-constructed locks are covered too.
        ids = real_suite_result.lock_result.site_ids()
        assert "VizierServicer._study_locks" in ids
        assert "vizier_tpu/service/vizier_service.py" in site_files

    def test_cross_module_edges_resolved(self, real_suite_result):
        edges = real_suite_result.lock_result.edge_pairs()
        # Serving: one study's entry lock reaches the batch executor's
        # condition (slot wait) and the cache map lock (invalidate-on-error).
        assert ("CachedDesignerEntry.lock", "BatchExecutor._cond") in edges
        assert (
            "CachedDesignerEntry.lock",
            "DesignerStateCache._lock",
        ) in edges
        # Service: study locks nest over datastore locks (both impls).
        assert (
            "VizierServicer._study_locks",
            "NestedDictRAMDataStore._lock",
        ) in edges
        assert ("VizierServicer._study_locks", "SQLDataStore._lock") in edges

    def test_study_lock_never_reaches_compute_or_batching(
        self, real_suite_result
    ):
        # The deliberate design invariant the suggest path documents:
        # Pythia dispatch (and therefore designer compute / batch waits)
        # happens OUTSIDE the study lock.
        edges = real_suite_result.lock_result.edge_pairs()
        assert ("VizierServicer._study_locks", "BatchExecutor._cond") not in edges
        assert (
            "VizierServicer._study_locks",
            "CachedDesignerEntry.lock",
        ) not in edges

    def test_no_cycles_in_real_tree(self, real_suite_result):
        assert not any(
            f.rule == "lock-cycle"
            for f in real_suite_result.passes["lock_order"].findings
        )
