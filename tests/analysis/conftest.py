"""Shared fixtures: one whole-tree analysis run per session, one fixtures
run per session — the passes are pure functions of the source, so every
test can share them."""

import os

import pytest

from vizier_tpu.analysis import common, suite

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
FIXTURES_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")


@pytest.fixture(scope="session")
def repo_root():
    return REPO_ROOT


@pytest.fixture(scope="session")
def fixtures_dir():
    return FIXTURES_DIR


@pytest.fixture(scope="session")
def real_suite_result(repo_root):
    """The full configured suite over the real tree (baseline applied)."""
    return suite.run_suite(repo_root)


@pytest.fixture(scope="session")
def fixtures_project(fixtures_dir, repo_root):
    """AST project over the seeded-violation fixtures only."""
    return common.Project([fixtures_dir], rel_to=repo_root)
