"""Env-registry pass + registry helpers: undeclared reads are findings,
declared switches are documented, runtime helpers validate names."""

import os

import pytest

from vizier_tpu.analysis import env_registry, registry

_FIX = "tests/analysis/fixtures/bad_env_read.py"


def _result(fixtures_project, repo_root):
    return env_registry.run(
        fixtures_project, repo_root, check_registry_coverage=False
    )


class TestSeededFixtures:
    def test_undeclared_reads_flagged(self, fixtures_project, repo_root):
        keys = {f.key for f in _result(fixtures_project, repo_root).findings}
        assert f"undeclared-env-read:VIZIER_NOT_A_REAL_SWITCH@{_FIX}" in keys
        assert f"undeclared-env-read:VIZIER_ALSO_NOT_DECLARED@{_FIX}" in keys

    def test_constant_read_flagged(self, fixtures_project, repo_root):
        keys = {f.key for f in _result(fixtures_project, repo_root).findings}
        assert f"environ-read-of-constant:VIZIER_METHODS@{_FIX}" in keys

    def test_dynamic_read_flagged(self, fixtures_project, repo_root):
        rules = {f.rule for f in _result(fixtures_project, repo_root).findings}
        assert "dynamic-env-read" in rules

    def test_undeclared_literals_flagged(self, fixtures_project, repo_root):
        keys = {f.key for f in _result(fixtures_project, repo_root).findings}
        assert f"undeclared-literal:VIZIER_NOT_A_REAL_SWITCH@{_FIX}" in keys

    def test_declared_read_not_flagged(self, fixtures_project, repo_root):
        findings = _result(fixtures_project, repo_root).findings
        assert not any("VIZIER_BATCHING" in f.key for f in findings)


class TestRealTree:
    def test_no_unbaselined_findings(self, real_suite_result):
        assert real_suite_result.passes["env_registry"].new == []

    def test_every_switch_documented_where_declared(self, repo_root):
        for switch in registry.SWITCHES:
            doc = os.path.join(repo_root, switch.doc)
            assert os.path.isfile(doc), f"{switch.name}: missing {switch.doc}"
            with open(doc, "r", encoding="utf-8") as f:
                assert switch.name in f.read(), (
                    f"{switch.name} not mentioned in {switch.doc}"
                )

    def test_registry_covers_the_trees_switch_count(self):
        # 82 in-tree env switches (incl. the 12 VIZIER_DISTRIBUTED* tier
        # knobs — 6 topology/WAL + 4 replication + 2 lease/heartbeat —
        # the 5 VIZIER_SPARSE* surrogate knobs, the 6 VIZIER_SPECULATIVE*
        # pre-compute knobs, the 6 VIZIER_MESH* execution-plane knobs,
        # the 8 VIZIER_SLO* objectives, the 3 VIZIER_FLIGHT_RECORDER*
        # knobs, VIZIER_OBS_DUMP_DIR, the 5 VIZIER_LOADGEN*
        # traffic-engine knobs, the 11 VIZIER_ADMISSION*
        # overload-protection knobs, the 4 VIZIER_COMPUTE_TIER*
        # disaggregated-compute knobs, and the VIZIER_NETCHAOS fault
        # schedule) + 3 bench switches + the 2 reserved grpc constants.
        # Growing the tree means growing this registry.
        assert len(registry.SWITCHES) == 87
        assert len(registry.env_switch_names()) == 85

    def test_known_switches_declared(self):
        for name in (
            "VIZIER_DISABLE_MESH",
            "VIZIER_BATCHING",
            "VIZIER_RELIABILITY",
            "VIZIER_OBSERVABILITY",
            "VIZIER_BENCH_SCALE",
            "VIZIER_SPARSE",
            "VIZIER_DISTRIBUTED_ROUTE_CACHE_SIZE",
        ):
            assert registry.declared(name)
        assert registry.BY_NAME["VIZIER_METHODS"].kind == "constant"
        assert registry.BY_NAME["VIZIER_SERVICE_NAME"].kind == "constant"


class TestRuntimeHelpers:
    def test_undeclared_name_raises(self):
        with pytest.raises(KeyError, match="Undeclared"):
            registry.env_on("VIZIER_TOTALLY_MADE_UP")

    def test_constant_is_not_an_env_switch(self):
        with pytest.raises(KeyError, match="reserved constant"):
            registry.env_str("VIZIER_METHODS")

    def test_env_on_defaults_and_off_values(self, monkeypatch):
        monkeypatch.delenv("VIZIER_BATCHING", raising=False)
        assert registry.env_on("VIZIER_BATCHING") is True
        for off in ("0", "false", "False", ""):
            monkeypatch.setenv("VIZIER_BATCHING", off)
            assert registry.env_on("VIZIER_BATCHING") is False

    def test_env_set_opt_out_semantics(self, monkeypatch):
        monkeypatch.delenv("VIZIER_DISABLE_MESH", raising=False)
        assert registry.env_set("VIZIER_DISABLE_MESH") is False
        monkeypatch.setenv("VIZIER_DISABLE_MESH", "1")
        assert registry.env_set("VIZIER_DISABLE_MESH") is True
        # "0" means NOT disabled (the old raw-truthiness read got this wrong).
        monkeypatch.setenv("VIZIER_DISABLE_MESH", "0")
        assert registry.env_set("VIZIER_DISABLE_MESH") is False

    def test_numeric_helpers_survive_garbage(self, monkeypatch):
        monkeypatch.setenv("VIZIER_BATCH_MAX_SIZE", "not-a-number")
        assert registry.env_int("VIZIER_BATCH_MAX_SIZE", 8) == 8
        monkeypatch.setenv("VIZIER_BATCH_MAX_WAIT_MS", "2.5")
        assert registry.env_float("VIZIER_BATCH_MAX_WAIT_MS", 4.0) == 2.5

    def test_config_modules_round_trip_through_registry(self, monkeypatch):
        # The three config classes' from_env must honor registry reads.
        monkeypatch.setenv("VIZIER_SERVING_CACHE", "0")
        monkeypatch.setenv("VIZIER_RELIABILITY_BREAKER", "0")
        monkeypatch.setenv("VIZIER_OBSERVABILITY_SPAN_BUFFER", "128")
        from vizier_tpu.observability.config import ObservabilityConfig
        from vizier_tpu.reliability.config import ReliabilityConfig
        from vizier_tpu.serving.config import ServingConfig

        assert ServingConfig.from_env().designer_cache is False
        assert ReliabilityConfig.from_env().breaker is False
        assert ObservabilityConfig.from_env().span_buffer_size == 128
