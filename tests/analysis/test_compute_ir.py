"""Compute-IR conformance pass: every registered DesignerProgram carries
prewarm coverage, the tracing/kind metadata, and chaos-test coverage."""

from vizier_tpu.analysis import compute_ir

_FIX = "tests/analysis/fixtures/bad_compute_program.py"


def _result(fixtures_project, repo_root):
    return compute_ir.run(fixtures_project, repo_root)


class TestSeededFixtures:
    def test_registration_site_found(self, fixtures_project, repo_root):
        result = _result(fixtures_project, repo_root)
        assert any(
            r.program_class == "IncompleteProgram" for r in result.registered
        )
        assert any(r.kind == "fixture_incomplete" for r in result.registered)

    def test_missing_hook_flagged(self, fixtures_project, repo_root):
        keys = {f.key for f in _result(fixtures_project, repo_root).findings}
        assert "program-missing-hook:IncompleteProgram.finalize" in keys
        # The hooks it DOES define are not flagged.
        assert "program-missing-hook:IncompleteProgram.prepare" not in keys

    def test_missing_prewarm_coverage_flagged(
        self, fixtures_project, repo_root
    ):
        keys = {f.key for f in _result(fixtures_project, repo_root).findings}
        assert "program-missing-prewarm-coverage:IncompleteProgram" in keys

    def test_missing_device_phase_flagged(self, fixtures_project, repo_root):
        keys = {f.key for f in _result(fixtures_project, repo_root).findings}
        assert "program-missing-device-phase:IncompleteProgram" in keys

    def test_missing_shard_axis_flagged(self, fixtures_project, repo_root):
        # No literal shardable_batch_axis declaration: the mesh execution
        # plane requires every registered program to state whether its
        # device_program may shard over a placement.
        keys = {f.key for f in _result(fixtures_project, repo_root).findings}
        assert "program-missing-shard-axis:IncompleteProgram" in keys

    def test_unregistered_fixture_kind_needs_chaos_coverage(
        self, fixtures_project, repo_root
    ):
        # The fixture kind appears in no chaos-exercising test file (this
        # test file does not import the chaos harness), so the coverage
        # rule fires for it.
        keys = {f.key for f in _result(fixtures_project, repo_root).findings}
        assert "program-missing-chaos-coverage:fixture_incomplete" in keys


class TestRealTree:
    def test_no_unbaselined_findings(self, real_suite_result):
        assert real_suite_result.passes["compute_ir"].new == []

    def test_all_builtin_programs_registered(self, real_suite_result):
        result = real_suite_result.compute_ir_result
        kinds = {r.kind for r in result.registered}
        assert kinds >= {
            "gp_bandit",
            "gp_bandit_sparse",
            "gp_ucb_pe",
            "gp_ucb_pe_sparse",
        }

    def test_registered_set_matches_runtime_registry(self, real_suite_result):
        # The static scan and the live registry must agree — a program
        # registered behind dynamic construction would silently escape
        # every conformance rule.
        from vizier_tpu.compute import registry as compute_registry

        static_kinds = {
            r.kind for r in real_suite_result.compute_ir_result.registered
        }
        assert set(compute_registry.kinds()) <= static_kinds
