"""Tests for testing libs, acquisition optimizers, analyzers, integrations."""

import jax
import numpy as np
import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.benchmarks import NumpyExperimenter, bbob_problem
from vizier_tpu.benchmarks.analyzers import convergence_curve as cc
from vizier_tpu.benchmarks.analyzers.state_analyzer import BenchmarkStateAnalyzer
from vizier_tpu.benchmarks.experimenters.experimenter_factory import (
    SingleObjectiveExperimenterFactory,
)
from vizier_tpu.benchmarks.experimenters.synthetic import bbob
from vizier_tpu.designers import GridSearchDesigner, RandomDesigner
from vizier_tpu.testing import comparator_runner, failing, simplekd_runner


class TestComparatorRunner:
    def test_grid_beats_random_on_1d(self):
        exp = NumpyExperimenter(bbob.Sphere, bbob_problem(1))
        tester = comparator_runner.EfficiencyComparisonTester(
            num_trials=20, num_repeats=2, margin=0.0
        )
        score = tester.assert_better_efficiency(
            exp,
            candidate_factory=lambda p, **kw: GridSearchDesigner(
                p.search_space, double_grid_resolution=21
            ),
            baseline_factory=lambda p, **kw: RandomDesigner(
                p.search_space, seed=kw.get("seed", 0)
            ),
        )
        assert np.isfinite(score)

    def test_simple_regret_failure_raises(self):
        exp = NumpyExperimenter(bbob.Sphere, bbob_problem(2))

        class AwfulDesigner(core_lib.Designer):
            def update(self, completed, all_active=core_lib.ActiveTrials()):
                pass

            def suggest(self, count=None):
                # Always the worst corner.
                return [
                    vz.TrialSuggestion(parameters={"x0": 5.0, "x1": 5.0})
                    for _ in range(count or 1)
                ]

        tester = comparator_runner.SimpleRegretComparisonTester(
            num_trials=10, num_repeats=2
        )
        with pytest.raises(comparator_runner.FailedComparisonTestError):
            tester.assert_better_simple_regret(
                exp,
                candidate_factory=lambda p, **kw: AwfulDesigner(),
                baseline_factory=lambda p, **kw: RandomDesigner(
                    p.search_space, seed=kw.get("seed", 0)
                ),
            )


class TestSimpleKDRunner:
    def test_random_converges_loosely(self):
        tester = simplekd_runner.SimpleKDConvergenceTester(
            num_trials=80, batch_size=8, max_abs_error=1.5
        )
        best = tester.assert_converges(
            lambda p, **kw: RandomDesigner(p.search_space, seed=kw.get("seed", 0))
        )
        assert best <= 0.0

    def test_failing_designer_raises(self):
        with pytest.raises(failing.FailedSuggestError):
            simplekd_runner.SimpleKDConvergenceTester(num_trials=5).assert_converges(
                lambda p, **kw: failing.FailingDesigner()
            )


class TestFailingDesigners:
    def test_alternate_fails_odd_calls(self):
        space = vz.SearchSpace()
        space.root.add_float_param("x", 0, 1)
        inner = RandomDesigner(space, seed=0)
        d = failing.AlternateFailingDesigner(inner)
        with pytest.raises(failing.FailedSuggestError):
            d.suggest(1)
        assert len(d.suggest(1)) == 1


class TestLBFGSBOptimizer:
    def test_maximizes_smooth_acquisition(self):
        import jax.numpy as jnp

        from vizier_tpu.optimizers.lbfgsb_optimizer import LBFGSBOptimizer

        def score(feats):
            return -jnp.sum((feats.continuous - 0.7) ** 2, axis=-1)

        result = LBFGSBOptimizer(num_restarts=8, maxiter=40)(
            score, jax.random.PRNGKey(0), num_continuous=3, count=2
        )
        best = np.asarray(result.features.continuous[0])
        np.testing.assert_allclose(best, 0.7, atol=0.02)

    def test_designer_as_optimizer(self):
        from vizier_tpu.optimizers.lbfgsb_optimizer import DesignerAsOptimizer

        problem = vz.ProblemStatement()
        problem.search_space.root.add_float_param("x", 0.0, 1.0)
        problem.metric_information.append(
            vz.MetricInformation(name="acquisition", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
        opt = DesignerAsOptimizer(
            designer_factory=lambda p: RandomDesigner(p.search_space, seed=0),
            num_rounds=5,
            batch_size=8,
        )
        best = opt.optimize(
            lambda suggs: [-(s.parameters.get_value("x") - 0.4) ** 2 for s in suggs],
            problem,
            count=1,
        )
        assert abs(best[0].parameters.get_value("x") - 0.4) < 0.2


class TestAnalyzers:
    def test_hypervolume_curve_monotone(self):
        metrics = [
            vz.MetricInformation(name="f1", goal=vz.ObjectiveMetricGoal.MINIMIZE),
            vz.MetricInformation(name="f2", goal=vz.ObjectiveMetricGoal.MINIMIZE),
        ]
        trials = []
        rng = np.random.default_rng(0)
        for i in range(20):
            t = vz.Trial(id=i + 1, parameters={"x": 0.5})
            f1, f2 = rng.uniform(size=2)
            t.complete(vz.Measurement(metrics={"f1": f1, "f2": f2}))
            trials.append(t)
        curve = cc.HypervolumeCurveConverter(metrics, seed=1).convert(trials)
        assert curve.ys.shape == (1, 20)
        assert (np.diff(curve.ys[0]) >= -1e-6).all()  # cumulative HV grows

    def test_state_analyzer_records(self):
        from vizier_tpu.benchmarks import BenchmarkRunner, BenchmarkState, GenerateAndEvaluate

        exp = NumpyExperimenter(bbob.Sphere, bbob_problem(2))
        state = BenchmarkState.from_designer_factory(
            exp, lambda p, **kw: RandomDesigner(p.search_space, seed=0)
        )
        BenchmarkRunner([GenerateAndEvaluate(5)], num_repeats=2).run(state)
        records = BenchmarkStateAnalyzer.to_records([state], algorithm_names=["random"])
        assert records[0]["algorithm"] == "random"
        assert records[0]["num_trials"] == 10
        df = BenchmarkStateAnalyzer.to_dataframe([state])
        assert len(df) == 1

    def test_percentage_better(self):
        xs = np.arange(1, 6)
        a = cc.ConvergenceCurve(xs=xs, ys=np.array([[1, 2, 3, 4, 5.0]]),
                                trend=cc.ConvergenceCurve.YTrend.INCREASING)
        b = cc.ConvergenceCurve(xs=xs, ys=np.array([[2, 3, 4, 5, 6.0]]),
                                trend=cc.ConvergenceCurve.YTrend.INCREASING)
        assert cc.PercentageBetterComparator(a).score(b) == 1.0


class TestExperimenterFactory:
    def test_builds_wrapped(self):
        factory = SingleObjectiveExperimenterFactory(
            name="Rastrigin", dim=3, shift=np.array([1.0, 0.5, -1.0]), noise_std=0.1
        )
        exp = factory()
        t = vz.Trial(id=1, parameters={"x0": 1.0, "x1": 0.5, "x2": -1.0})
        exp.evaluate([t])
        # At the shifted optimum: value = 0 + noise.
        assert abs(t.final_measurement.metrics["bbob_eval"].value) < 1.0
        assert "Rastrigin_3d" in factory.description

    def test_unknown_name(self):
        with pytest.raises(ValueError):
            SingleObjectiveExperimenterFactory(name="NotAFunction")()

    def test_noise_type_builds_zoo_model(self):
        factory = SingleObjectiveExperimenterFactory(
            name="Sphere", dim=2, noise_type="severe_gaussian", seed=3
        )
        exp = factory()
        t = vz.Trial(id=1, parameters={"x0": 1.0, "x1": 1.0})
        exp.evaluate([t])
        m = t.final_measurement.metrics
        assert m["bbob_eval_before_noise"].value == pytest.approx(2.0)
        assert m["bbob_eval"].value != m["bbob_eval_before_noise"].value
        assert "severe_gaussian" in factory.description

    def test_noise_std_and_type_conflict(self):
        with pytest.raises(ValueError, match="not both"):
            SingleObjectiveExperimenterFactory(
                name="Sphere", noise_std=0.1, noise_type="NO_NOISE"
            )()


class TestIntegrations:
    def test_raytune_converter_dict_language(self):
        from vizier_tpu.raytune.vizier_search import SearchSpaceConverter

        space = SearchSpaceConverter.to_vizier(
            {
                "lr": {"type": "loguniform", "min": 1e-4, "max": 1e-1},
                "units": {"type": "randint", "min": 32, "max": 512},
                "act": {"type": "choice", "values": ["relu", "tanh"]},
                "drop": {"type": "uniform", "min": 0.0, "max": 0.5},
            }
        )
        assert space.parameter_names() == ["lr", "units", "act", "drop"]
        assert space.get("lr").scale_type == vz.ScaleType.LOG

    def test_raytune_searcher_full_loop(self, tmp_path):
        """The ray Searcher behavioral contract runs ray-free against the
        in-process service: suggest → intermediate results → complete."""
        from vizier_tpu.raytune.vizier_search import VizierSearch

        searcher = VizierSearch(
            {"x": {"type": "uniform", "min": 0.0, "max": 1.0}},
            metric="score",
            mode="max",
            algorithm="RANDOM_SEARCH",
            study_id="raytune-loop",
        )
        for i in range(4):
            cfg = searcher.suggest(f"ray_{i}")
            assert 0.0 <= float(cfg["x"]) <= 1.0
            searcher.on_trial_result(
                f"ray_{i}", {"score": 0.1, "training_iteration": 1}
            )
            searcher.on_trial_complete(f"ray_{i}", {"score": float(cfg["x"])})
        searcher.on_trial_complete("ray_err", error=True)  # unknown id: no-op
        trials = list(searcher._study.trials())
        assert len(trials) == 4
        assert all(t.materialize().is_completed for t in trials)

    def test_raytune_searcher_save_restore(self, tmp_path):
        from vizier_tpu.raytune.vizier_search import VizierSearch

        s1 = VizierSearch(
            {"x": {"type": "uniform", "min": 0.0, "max": 1.0}},
            metric="score",
            algorithm="RANDOM_SEARCH",
            study_id="raytune-ckpt",
        )
        s1.suggest("r1")
        path = str(tmp_path / "searcher.json")
        s1.save(path)
        s2 = VizierSearch(metric="score")
        s2.restore(path)
        assert s2._ray_to_vizier == s1._ray_to_vizier
        # The restored searcher completes the in-flight trial.
        s2.on_trial_complete("r1", {"score": 0.5})
        assert s2._study.get_trial(1).materialize().is_completed

    def test_raytune_set_search_properties_late_binding(self):
        from vizier_tpu.raytune.vizier_search import VizierSearch

        searcher = VizierSearch(study_id="raytune-late")
        assert searcher.suggest("r0") is None  # not ready yet
        ok = searcher.set_search_properties(
            "score", "min", {"y": {"type": "randint", "min": 1, "max": 4}}
        )
        assert ok
        cfg = searcher.suggest("r1")
        assert 1 <= int(cfg["y"]) <= 4
        # A second call must refuse (study already bound).
        assert not searcher.set_search_properties("other", "max", {})

    def test_pyglove_dna_converter(self):
        from vizier_tpu.pyglove.backend import DNATrialConverter

        decisions = {"layer": 3, "act": "relu", "widths": [64, 128]}
        s = DNATrialConverter.to_suggestion(decisions)
        t = s.to_trial(1)
        assert DNATrialConverter.to_decisions(t) == decisions

    def test_pyglove_backend_requires_pyglove(self):
        from vizier_tpu.pyglove import backend

        if not backend.PYGLOVE_AVAILABLE:
            with pytest.raises(ImportError):
                backend.VizierBackend("s")


class TestReviewRegressions:
    """Regressions from the eighth code review."""

    def test_hypervolume_curve_empty_trials(self):
        metrics = [vz.MetricInformation(name="f1"), vz.MetricInformation(name="f2")]
        curve = cc.HypervolumeCurveConverter(metrics).convert([])
        assert curve.ys.shape[-1] == 0


class TestRound1Additions:
    def test_mes_acquisition(self):
        import jax
        import jax.numpy as jnp

        from vizier_tpu.designers.gp import acquisitions

        y_star = jnp.asarray([1.0, 1.2, 0.9])
        mes = acquisitions.MaxValueEntropySearch(y_star_samples=y_star)
        mean = jnp.asarray([0.0, 0.8])
        std = jnp.asarray([0.5, 0.5])
        vals = np.asarray(mes(mean, std, jnp.asarray(0.0)))
        assert vals.shape == (2,)
        assert (vals >= 0).all()
        assert vals[1] > vals[0]  # closer to y* -> more informative

    def test_trial_cache_dedupes(self):
        from vizier_tpu.algorithms.trial_caches import IdDeduplicatingTrialLoader
        from vizier_tpu.pythia import local_policy_supporters

        config = vz.StudyConfig()
        config.search_space.root.add_float_param("x", 0.0, 1.0)
        config.metric_information.append(vz.MetricInformation(name="m"))
        supporter = local_policy_supporters.InRamPolicySupporter(config)
        t1 = vz.Trial(parameters={"x": 0.1})
        t1.complete(vz.Measurement(metrics={"m": 1.0}))
        supporter.AddTrials([t1])
        loader = IdDeduplicatingTrialLoader(supporter)
        assert len(loader.new_completed_trials()) == 1
        assert len(loader.new_completed_trials()) == 0
        # Serialization round trip.
        loader2 = IdDeduplicatingTrialLoader(supporter)
        loader2.load(loader.dump())
        assert len(loader2.new_completed_trials()) == 0

    def test_plot_utils_render(self, tmp_path):
        import matplotlib

        matplotlib.use("Agg")
        from vizier_tpu.benchmarks.analyzers import plot_utils

        xs = np.arange(1, 11)
        curve = cc.ConvergenceCurve(
            xs=xs,
            ys=np.stack([xs * 0.1, xs * 0.12]),
            trend=cc.ConvergenceCurve.YTrend.INCREASING,
        )
        ax = plot_utils.plot_median_convergence({"algo": curve}, title="t")
        fig = ax.get_figure()
        out = tmp_path / "plot.png"
        fig.savefig(out)
        assert out.exists() and out.stat().st_size > 0

    def test_gradient_free_optimizer_abc(self):
        from vizier_tpu.optimizers.base import BranchSelector, GradientFreeOptimizer

        assert hasattr(GradientFreeOptimizer, "optimize")
        assert hasattr(BranchSelector, "select_branches")


class TestValidatorsAndAssertions:
    def test_validators(self):
        import pytest as _pytest

        from vizier_tpu.utils import validators as v

        v.assert_not_empty("xs", [1])
        v.assert_not_negative("n", 0)
        v.assert_between("p", 0.5, 0.0, 1.0)
        v.assert_re_fullmatch("id", "abc_1", r"[a-z_0-9]+")
        v.assert_shape("m", np.zeros((3, 2)), (3, None))
        for bad in (
            lambda: v.assert_not_empty("xs", []),
            lambda: v.assert_not_negative("n", -1),
            lambda: v.assert_not_none("x", None),
            lambda: v.assert_between("p", 2.0, 0.0, 1.0),
            lambda: v.assert_re_fullmatch("id", "A!", r"[a-z]+"),
            lambda: v.assert_shape("m", np.zeros((3, 2)), (2, 2)),
        ):
            with _pytest.raises(ValueError):
                bad()

    def test_arraytree_allclose(self):
        import pytest as _pytest

        from vizier_tpu.testing import numpy_assertions as na

        na.assert_arraytree_allclose(
            {"a": np.ones(3), "b": {"c": 2.0, "s": "x"}},
            {"a": np.ones(3), "b": {"c": 2.0, "s": "x"}},
        )
        with _pytest.raises(AssertionError):
            na.assert_arraytree_allclose({"a": np.ones(3)}, {"a": np.zeros(3)})
        na.assert_pytree_allclose((np.ones(2), [3.0]), (np.ones(2), [3.0]))
        with _pytest.raises(AssertionError):
            na.assert_pytree_allclose((np.ones(2),), (np.ones(2), [3.0]))
