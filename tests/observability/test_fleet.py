"""Fleet aggregation: dump layout, cross-replica merge, failover timeline."""

import json

from vizier_tpu.observability import fleet as fleet_lib
from vizier_tpu.observability import flight_recorder as recorder_lib
from vizier_tpu.observability import metrics as metrics_lib
from vizier_tpu.observability import tracing as tracing_lib


def _span(name, trace_id, span_id, parent=None, start=0.0, **attrs):
    out = {
        "name": name,
        "trace_id": trace_id,
        "span_id": span_id,
        "parent_id": parent,
        "start_time": start,
        "duration_secs": 0.01,
        "status": "ok",
    }
    if attrs:
        out["attributes"] = attrs
    return out


class TestDumpAndLoad:
    def test_dump_process_round_trip(self, tmp_path):
        tracer = tracing_lib.Tracer()
        previous = tracing_lib.set_tracer(tracer)
        try:
            with tracer.span("service.suggest_trials", replica="replica-0"):
                pass
        finally:
            tracing_lib.set_tracer(previous)
        registry = metrics_lib.MetricsRegistry()
        registry.gauge("vizier_slo_burn_rate").set(
            2.0, slo="suggest_p99:pythia", window="60s"
        )
        recorder = recorder_lib.FlightRecorder()
        recorder.record(None, "replica_failover", replica="replica-0",
                        successors=["replica-1"])
        written = fleet_lib.dump_process(
            str(tmp_path), "replica-0", tracer=tracer, registry=registry,
            recorder=recorder,
        )
        assert set(written) == {"spans", "metrics", "recorder"}
        loaded = fleet_lib.load_fleet_dir(str(tmp_path))
        assert list(loaded["spans"]) == ["replica-0"]
        assert loaded["spans"]["replica-0"][0]["name"] == "service.suggest_trials"
        assert "vizier_slo_burn_rate" in loaded["metrics"]["replica-0"]
        assert loaded["recorder"]["replica-0"][0]["kind"] == "replica_failover"

    def test_noop_pieces_write_no_files(self, tmp_path):
        written = fleet_lib.dump_process(
            str(tmp_path), "r", tracer=tracing_lib.NOOP_TRACER,
            recorder=recorder_lib.NOOP_RECORDER,
        )
        assert written == {}

    def test_write_spans_explicit_list(self, tmp_path):
        path = fleet_lib.write_spans(
            str(tmp_path), "client", [_span("client.suggest", "t", "s")]
        )
        assert path.endswith("client-spans.jsonl")
        loaded = fleet_lib.load_fleet_dir(str(tmp_path))
        assert loaded["spans"]["client"][0]["trace_id"] == "t"


class TestMerge:
    def _sources(self):
        return {
            "client": [
                _span("client.suggest", "trace-a", "c1", start=1.0),
                _span("client.suggest", "trace-b", "c2", start=2.0),
            ],
            "replica-0": [
                _span("service.suggest_trials", "trace-a", "r1", parent="c1",
                      start=1.1, replica="replica-0"),
            ],
            "replica-1": [
                _span("service.suggest_trials", "trace-b", "r2", parent="c2",
                      start=2.1, replica="replica-1"),
                _span("service.complete_trial", "trace-c", "r3", start=3.0),
            ],
        }

    def test_merge_stamps_source_and_orders(self):
        merged = fleet_lib.merge_spans(self._sources())
        assert [s["source"] for s in merged] == [
            "client", "replica-0", "client", "replica-1", "replica-1",
        ]

    def test_cross_replica_traces(self):
        crossing = fleet_lib.cross_replica_traces(
            fleet_lib.merge_spans(self._sources())
        )
        by_id = {row["trace_id"]: row for row in crossing}
        # trace-a and trace-b each span two sources; trace-c is local-only.
        assert set(by_id) == {"trace-a", "trace-b"}
        assert by_id["trace-a"]["sources"] == ["client", "replica-0"]
        assert by_id["trace-b"]["spans"] == 2

    def test_fleet_report_end_to_end(self, tmp_path):
        for source, spans in self._sources().items():
            fleet_lib.write_spans(str(tmp_path), source, spans)
        recorder = recorder_lib.FlightRecorder()
        recorder.record(None, "replica_killed", replica="replica-1")
        recorder.record(None, "replica_failover", replica="replica-1",
                        successors=["replica-0"], restored_studies=2)
        recorder.dump_json(str(tmp_path / ("fleet" + fleet_lib.RECORDER_SUFFIX)))
        registry = metrics_lib.MetricsRegistry()
        registry.gauge("vizier_slo_breached").set(1.0, slo="suggest_p99:pythia")
        with open(tmp_path / ("fleet" + fleet_lib.METRICS_SUFFIX), "w") as f:
            json.dump(registry.snapshot(), f)

        report = fleet_lib.fleet_report(str(tmp_path))
        assert report["sources"] == ["client", "replica-0", "replica-1"]
        assert report["spans"] == 5 and report["traces"] == 3
        assert report["cross_replica_traces"] == 2
        timeline = report["failover_timeline"]
        assert [e["kind"] for e in timeline] == [
            "replica_killed", "replica_failover",
        ]
        assert timeline[1]["successors"] == ["replica-0"]
        assert "vizier_slo_breached" in report["slo"]
        rendered = fleet_lib.render_fleet_report(report)
        assert "replica_failover" in rendered
        assert "2 cross-replica" in rendered

    def test_merged_trace_lookup(self, tmp_path):
        for source, spans in self._sources().items():
            fleet_lib.write_spans(str(tmp_path), source, spans)
        trace = fleet_lib.merged_trace(str(tmp_path), "trace-a")
        assert [s["source"] for s in trace] == ["client", "replica-0"]


class TestTimeline:
    def test_non_timeline_kinds_excluded(self):
        events = {
            "fleet": [
                {"time": 1.0, "kind": "suggest", "study": "s"},
                {"time": 2.0, "kind": "replica_revive", "study": "<fleet>",
                 "attributes": {"replica": "replica-0"}},
                {"time": 0.5, "kind": "slo_breach", "study": "<fleet>",
                 "attributes": {"slos": ["suggest_p99:pythia"]}},
            ]
        }
        timeline = fleet_lib.failover_timeline(events)
        assert [e["kind"] for e in timeline] == ["slo_breach", "replica_revive"]
        assert timeline[1]["replica"] == "replica-0"
