"""Metrics registry: counters/gauges/histograms + Prometheus exposition."""

import json
import threading

import pytest

from vizier_tpu.observability import metrics as metrics_lib


class TestCounter:
    def test_inc_and_value(self):
        registry = metrics_lib.MetricsRegistry()
        c = registry.counter("requests", help="total requests")
        c.inc()
        c.inc(4)
        assert c.value() == 5

    def test_labeled_series_are_independent(self):
        c = metrics_lib.MetricsRegistry().counter("hits")
        c.inc(2, kind="a")
        c.inc(3, kind="b")
        assert c.value(kind="a") == 2
        assert c.value(kind="b") == 3
        assert c.value() == 0  # the unlabeled series is its own series

    def test_negative_increment_rejected(self):
        c = metrics_lib.MetricsRegistry().counter("c")
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_reset(self):
        c = metrics_lib.MetricsRegistry().counter("c")
        c.inc(7, k="v")
        c.reset()
        assert c.value(k="v") == 0

    def test_concurrent_increments_exact(self):
        c = metrics_lib.MetricsRegistry().counter("c")
        n, per_thread = 8, 500

        def work():
            for _ in range(per_thread):
                c.inc()

        threads = [threading.Thread(target=work) for _ in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value() == n * per_thread


class TestGauge:
    def test_set_inc_dec(self):
        g = metrics_lib.MetricsRegistry().gauge("inflight")
        g.set(5)
        g.inc(2)
        g.dec()
        assert g.value() == 6


class TestHistogram:
    def test_count_and_sum(self):
        h = metrics_lib.MetricsRegistry().histogram("lat", buckets=[0.1, 1, 10])
        for v in (0.05, 0.5, 5.0, 50.0):
            h.observe(v)
        assert h.count() == 4
        assert h.sum() == pytest.approx(55.55)

    def test_percentile_interpolates_within_bucket(self):
        h = metrics_lib.MetricsRegistry().histogram("lat", buckets=[1.0, 2.0, 4.0])
        for _ in range(100):
            h.observe(1.5)  # all mass in the (1, 2] bucket
        p50 = h.percentile(50)
        assert 1.0 <= p50 <= 2.0

    def test_percentile_empty_is_none(self):
        h = metrics_lib.MetricsRegistry().histogram("lat")
        assert h.percentile(50) is None

    def test_percentile_overflow_clamps_to_last_bound(self):
        h = metrics_lib.MetricsRegistry().histogram("lat", buckets=[1.0, 2.0])
        h.observe(100.0)
        assert h.percentile(99) == 2.0

    def test_percentile_ordering(self):
        h = metrics_lib.MetricsRegistry().histogram(
            "lat", buckets=metrics_lib.exponential_buckets(0.001, 1.3, 40)
        )
        for i in range(1, 101):
            h.observe(i / 100.0)  # 0.01 .. 1.0
        p50, p95, p99 = h.percentile(50), h.percentile(95), h.percentile(99)
        assert p50 < p95 <= p99
        assert 0.3 < p50 < 0.7

    def test_exponential_buckets_shape(self):
        b = metrics_lib.exponential_buckets(0.5, 2.0, 4)
        assert b == [0.5, 1.0, 2.0, 4.0]
        with pytest.raises(ValueError):
            metrics_lib.exponential_buckets(0.0, 2.0, 4)


class TestRegistry:
    def test_get_or_create_returns_same_object(self):
        registry = metrics_lib.MetricsRegistry()
        assert registry.counter("x") is registry.counter("x")

    def test_type_conflict_rejected(self):
        registry = metrics_lib.MetricsRegistry()
        registry.counter("x")
        with pytest.raises(ValueError):
            registry.gauge("x")

    def test_prometheus_text_counter(self):
        registry = metrics_lib.MetricsRegistry()
        registry.counter("vizier_hits", help="hit count").inc(3, kind="warm")
        text = registry.prometheus_text()
        assert "# HELP vizier_hits hit count" in text
        assert "# TYPE vizier_hits counter" in text
        assert 'vizier_hits_total{kind="warm"} 3' in text

    def test_prometheus_text_histogram(self):
        registry = metrics_lib.MetricsRegistry()
        h = registry.histogram("lat_seconds", buckets=[0.1, 1.0])
        h.observe(0.05)
        h.observe(0.5)
        h.observe(5.0)
        text = registry.prometheus_text()
        assert "# TYPE lat_seconds histogram" in text
        assert 'lat_seconds_bucket{le="0.1"} 1' in text
        assert 'lat_seconds_bucket{le="1"} 2' in text
        assert 'lat_seconds_bucket{le="+Inf"} 3' in text
        assert "lat_seconds_count 3" in text

    def test_label_escaping(self):
        registry = metrics_lib.MetricsRegistry()
        registry.counter("c").inc(1, name='we"ird\\stu\nff')
        text = registry.prometheus_text()
        assert '\\"' in text and "\\\\" in text and "\\n" in text

    def test_snapshot_json_serializable(self):
        registry = metrics_lib.MetricsRegistry()
        registry.counter("c").inc(2, k="v")
        registry.histogram("h").observe(0.2)
        snap = json.loads(registry.dump_json())
        assert snap["c"]["type"] == "counter"
        assert snap["h"]["series"]["{}"]["count"] == 1
