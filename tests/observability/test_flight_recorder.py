"""Flight recorder: rings, bounds, trace correlation, global accessor."""

import json

import pytest

from vizier_tpu.observability import flight_recorder as recorder_lib
from vizier_tpu.observability import tracing as tracing_lib


class TestRecording:
    def test_events_land_in_the_study_ring(self):
        rec = recorder_lib.FlightRecorder()
        rec.record("s1", "suggest", trace_id="t1", duration_secs=0.01)
        rec.record("s1", "complete", trace_id="t2", trial="s1/trials/1")
        rec.record("s2", "suggest", trace_id="t3")
        ring = rec.ring("s1")
        assert [e["kind"] for e in ring] == ["suggest", "complete"]
        assert ring[0]["trace_id"] == "t1"
        assert ring[0]["attributes"]["duration_secs"] == 0.01
        assert ring[0]["time"] <= ring[1]["time"]
        assert rec.studies() == ["s1", "s2"]

    def test_none_study_is_the_fleet_pseudo_study(self):
        rec = recorder_lib.FlightRecorder()
        rec.record(None, "replica_failover", replica="replica-0",
                   successors=["replica-1"])
        (event,) = rec.ring(recorder_lib.FLEET)
        assert event["attributes"]["successors"] == ["replica-1"]

    def test_ring_is_bounded_oldest_first_out(self):
        rec = recorder_lib.FlightRecorder(ring_size=3)
        for i in range(5):
            rec.record("s", "suggest", trace_id=f"t{i}")
        assert [e["trace_id"] for e in rec.ring("s")] == ["t2", "t3", "t4"]

    def test_study_population_is_lru_bounded(self):
        rec = recorder_lib.FlightRecorder(max_studies=2)
        rec.record("a", "suggest", trace_id="x")
        rec.record("b", "suggest", trace_id="x")
        rec.record("a", "suggest", trace_id="x")  # refresh a
        rec.record("c", "suggest", trace_id="x")  # evicts b, not a
        assert set(rec.studies()) == {"a", "c"}

    def test_ambient_trace_id_captured(self):
        tracer = tracing_lib.Tracer()
        previous = tracing_lib.set_tracer(tracer)
        try:
            rec = recorder_lib.FlightRecorder()
            with tracer.span("request") as span:
                rec.record("s", "suggest")
            (event,) = rec.ring("s")
            assert event["trace_id"] == span.trace_id
        finally:
            tracing_lib.set_tracer(previous)

    def test_events_filter_and_order(self):
        rec = recorder_lib.FlightRecorder()
        rec.record("s1", "suggest", trace_id="a")
        rec.record("s2", "complete", trace_id="b")
        rec.record("s1", "complete", trace_id="c")
        assert [e["trace_id"] for e in rec.events(kind="complete")] == ["b", "c"]
        assert len(rec.events()) == 3

    def test_invalidate_drops_the_ring(self):
        rec = recorder_lib.FlightRecorder()
        rec.record("s", "suggest", trace_id="x")
        assert rec.invalidate("s") is True
        assert rec.ring("s") == []
        assert rec.invalidate("s") is False

    def test_dump_json_round_trip(self, tmp_path):
        rec = recorder_lib.FlightRecorder()
        rec.record("s", "suggest", trace_id="x")
        rec.record(None, "slo_breach", slos=["suggest_p99:pythia"])
        path = tmp_path / "recorder.json"
        assert rec.dump_json(str(path)) == 2
        loaded = json.loads(path.read_text())
        assert [e["kind"] for e in loaded] == ["suggest", "slo_breach"]

    def test_snapshot_is_json_ready(self):
        rec = recorder_lib.FlightRecorder()
        rec.record("s", "batch_flush", members=["t1", "t2"], occupancy=2)
        json.dumps(rec.snapshot())  # must not raise


class TestNoopAndGlobal:
    def test_noop_recorder_absorbs_everything(self):
        rec = recorder_lib.NOOP_RECORDER
        rec.record("s", "suggest", trace_id="x")
        assert rec.ring("s") == []
        assert rec.events() == []
        assert rec.snapshot() == {}
        assert rec.enabled is False

    def test_default_env_yields_noop(self, monkeypatch):
        monkeypatch.delenv("VIZIER_FLIGHT_RECORDER", raising=False)
        previous = recorder_lib.set_recorder(None)
        try:
            assert recorder_lib.get_recorder() is recorder_lib.NOOP_RECORDER
        finally:
            recorder_lib.set_recorder(previous)

    def test_env_armed_yields_real_recorder(self, monkeypatch):
        monkeypatch.setenv("VIZIER_FLIGHT_RECORDER", "1")
        monkeypatch.setenv("VIZIER_FLIGHT_RECORDER_RING", "7")
        previous = recorder_lib.set_recorder(None)
        try:
            rec = recorder_lib.get_recorder()
            assert isinstance(rec, recorder_lib.FlightRecorder)
            assert rec.enabled is True
            config = recorder_lib.FlightRecorderConfig.from_env()
            assert config.enabled and config.ring_size == 7
        finally:
            recorder_lib.set_recorder(previous)

    def test_set_recorder_returns_previous(self):
        mine = recorder_lib.FlightRecorder()
        previous = recorder_lib.set_recorder(mine)
        try:
            assert recorder_lib.get_recorder() is mine
        finally:
            recorder_lib.set_recorder(previous)


class TestConfig:
    def test_defaults(self):
        config = recorder_lib.FlightRecorderConfig()
        assert not config.enabled
        assert config.ring_size == 256 and config.max_studies == 1024
        assert config.as_dict()["ring_size"] == 256
