"""device_phase: compile-vs-execute split, spans, disabled no-op."""

import pytest

from vizier_tpu.observability import config as config_lib
from vizier_tpu.observability import jax_timing
from vizier_tpu.observability import metrics as metrics_lib
from vizier_tpu.observability import tracing as tracing_lib


@pytest.fixture
def fresh_state():
    """Isolated tracer + registry + compile tracking per test."""
    tracer = tracing_lib.Tracer()
    old_tracer = tracing_lib.set_tracer(tracer)
    registry = metrics_lib.MetricsRegistry()
    old_registry_state = metrics_lib._default_registry
    metrics_lib.set_default_registry(registry)
    jax_timing.set_config(config_lib.ObservabilityConfig())
    jax_timing.reset_compile_tracking()
    yield tracer, registry
    tracing_lib.set_tracer(old_tracer)
    metrics_lib.set_default_registry(old_registry_state)
    jax_timing.set_config(None)
    jax_timing.reset_compile_tracking()


class TestDevicePhase:
    def test_first_call_is_compile_then_execute(self, fresh_state):
        tracer, registry = fresh_state
        for _ in range(3):
            with jax_timing.device_phase("unit.phase"):
                pass
        hist = registry.get("vizier_jax_phase_seconds")
        assert hist.count(phase="unit.phase", mode="compile") == 1
        assert hist.count(phase="unit.phase", mode="execute") == 2

    def test_phase_names_tracked_independently(self, fresh_state):
        _, registry = fresh_state
        with jax_timing.device_phase("a"):
            pass
        with jax_timing.device_phase("b"):
            pass
        hist = registry.get("vizier_jax_phase_seconds")
        assert hist.count(phase="a", mode="compile") == 1
        assert hist.count(phase="b", mode="compile") == 1

    def test_span_carries_mode_attribute(self, fresh_state):
        tracer, _ = fresh_state
        with jax_timing.device_phase("unit.span"):
            pass
        with jax_timing.device_phase("unit.span"):
            pass
        spans = [s for s in tracer.finished_spans() if s.name == "jax.unit.span"]
        assert [s.attributes["mode"] for s in spans] == ["compile", "execute"]
        assert spans[0].attributes["first_call"] is True
        assert spans[1].attributes["first_call"] is False

    def test_block_syncs_jax_outputs(self, fresh_state):
        import jax.numpy as jnp

        with jax_timing.device_phase("unit.block") as phase:
            out = phase.block(jnp.ones((4,)) * 2.0)
        assert float(out.sum()) == 8.0

    def test_exception_skips_observation_but_propagates(self, fresh_state):
        _, registry = fresh_state
        with pytest.raises(RuntimeError):
            with jax_timing.device_phase("unit.err"):
                raise RuntimeError("boom")
        hist = registry.get("vizier_jax_phase_seconds")
        # The failed phase was not observed (the family may not even exist).
        assert hist is None or hist.count(phase="unit.err", mode="compile") == 0

    def test_disabled_is_inert(self, fresh_state):
        tracer, registry = fresh_state
        jax_timing.set_config(config_lib.ObservabilityConfig.disabled())
        with jax_timing.device_phase("unit.off") as phase:
            # No device sync requested, no histogram, no span.
            assert phase.block("anything") == "anything"
            assert not phase.enabled
        assert registry.get("vizier_jax_phase_seconds") is None
        assert tracer.finished_spans() == []
