"""obs_report: JSON-lines span file round-trip + breakdown rendering."""

import json
import pathlib
import sys

from vizier_tpu.observability import fleet as fleet_lib
from vizier_tpu.observability import flight_recorder as recorder_lib
from vizier_tpu.observability import metrics as metrics_lib
from vizier_tpu.observability import slo as slo_lib
from vizier_tpu.observability import tracing as tracing_lib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2] / "tools"))
import obs_report  # noqa: E402  (tools/ is not a package)


def _trace_file(tmp_path) -> str:
    tracer = tracing_lib.Tracer()
    for _ in range(3):
        with tracer.span("client.suggest"):
            with tracer.span("designer.suggest"):
                pass
    path = tmp_path / "spans.jsonl"
    tracer.dump_jsonl(str(path))
    return str(path)


class TestRoundTrip:
    def test_dump_then_load(self, tmp_path):
        path = _trace_file(tmp_path)
        spans = obs_report.load_spans(path)
        assert len(spans) == 6
        assert {s["name"] for s in spans} == {"client.suggest", "designer.suggest"}
        # Every span survived with its timing + identity intact.
        for span in spans:
            assert span["duration_secs"] > 0
            assert span["trace_id"] and span["span_id"]

    def test_corrupt_lines_skipped(self, tmp_path):
        path = tmp_path / "bad.jsonl"
        good = json.dumps(
            {"name": "x", "trace_id": "t", "span_id": "s", "duration_secs": 0.1}
        )
        path.write_text(f"{good}\nnot json at all\n\n{good}\n")
        assert len(obs_report.load_spans(str(path))) == 2


class TestBreakdown:
    def test_phase_table(self, tmp_path):
        spans = obs_report.load_spans(_trace_file(tmp_path))
        rows = obs_report.phase_breakdown(spans)
        by_phase = {r["phase"]: r for r in rows}
        assert by_phase["client.suggest"]["count"] == 3
        row = by_phase["designer.suggest"]
        assert 0 < row["p50_ms"] <= row["p95_ms"] <= row["p99_ms"] <= row["max_ms"]
        # The outer span contains the inner one, so it owns more total time.
        assert (
            by_phase["client.suggest"]["total_ms"]
            >= by_phase["designer.suggest"]["total_ms"]
        )
        table = obs_report.render_table(rows)
        assert "client.suggest" in table and "p99 ms" in table

    def test_exact_percentiles(self):
        spans = [
            {"name": "p", "duration_secs": v / 1000.0} for v in range(1, 101)
        ]
        (row,) = obs_report.phase_breakdown(spans)
        assert row["p50_ms"] == 50.5  # interpolated median of 1..100 ms
        assert row["max_ms"] == 100.0

    def test_trace_tree(self, tmp_path):
        spans = obs_report.load_spans(_trace_file(tmp_path))
        trace_id = spans[0]["trace_id"]
        tree = obs_report.render_trace(spans, trace_id)
        lines = tree.splitlines()
        assert lines[0] == f"trace {trace_id}"
        # Child indented under its parent.
        assert any(l.startswith("  client.suggest") for l in lines)
        assert any(l.startswith("    designer.suggest") for l in lines)

    def test_trace_tree_missing(self, tmp_path):
        spans = obs_report.load_spans(_trace_file(tmp_path))
        assert "No spans" in obs_report.render_trace(spans, "nope")


class TestSurrogateActivity:
    def _spans(self, names):
        return [{"name": n, "duration_secs": 0.01} for n in names]

    def test_exact_only(self):
        act = obs_report.surrogate_activity(
            self._spans(["gp_bandit.train_gp", "gp_ucb_pe.train_gp", "other"])
        )
        assert act == {"mode": "exact", "exact": 2, "sparse": 0}

    def test_sparse_only(self):
        act = obs_report.surrogate_activity(
            self._spans(["sparse_gp.train", "sparse_gp.acquisition"])
        )
        assert act == {"mode": "sparse", "exact": 0, "sparse": 2}

    def test_mixed_and_none(self):
        mixed = obs_report.surrogate_activity(
            self._spans(["sparse_gp.train", "gp_bandit.train_gp"])
        )
        assert mixed["mode"] == "mixed"
        assert obs_report.surrogate_activity(self._spans(["rpc"]))["mode"] == "none"


class TestSpeculativeActivity:
    def test_counts_serve_events_and_precompute_spans(self):
        spans = [
            {
                "name": "pythia.suggest",
                "duration_secs": 0.001,
                "events": [{"name": "speculative.hit", "attributes": {}}],
            },
            {
                "name": "pythia.suggest",
                "duration_secs": 0.8,
                "events": [{"name": "speculative.miss", "attributes": {}}],
            },
            {
                "name": "pythia.suggest",
                "duration_secs": 0.9,
                "events": [{"name": "speculative.stale", "attributes": {}}],
            },
            {
                "name": "speculative.precompute",
                "duration_secs": 0.7,
                "attributes": {"outcome": "stored"},
            },
            {
                "name": "speculative.precompute",
                "duration_secs": 0.7,
                "attributes": {"outcome": "superseded"},
            },
        ]
        act = obs_report.speculative_activity(spans)
        assert act["hit"] == 1 and act["miss"] == 1 and act["stale"] == 1
        assert act["precomputes"] == 2 and act["stored"] == 1
        assert act["hit_rate"] == round(1 / 3, 4)

    def test_no_activity_is_all_zero(self):
        act = obs_report.speculative_activity(
            [{"name": "pythia.suggest", "duration_secs": 0.1}]
        )
        assert act["hit"] == act["miss"] == act["precomputes"] == 0
        assert act["hit_rate"] == 0.0


def _armed_registry():
    """A registry that has been through one real SLO evaluation."""
    registry = metrics_lib.MetricsRegistry()
    hist = registry.histogram("vizier_suggest_latency_seconds")
    for _ in range(9):
        hist.observe(0.001, hop="pythia")
    hist.observe(0.9, trace_id="t-slow", hop="pythia")
    engine = slo_lib.SloEngine(
        slo_lib.SloConfig(
            enabled=True, windows=(5.0,), min_samples=1, suggest_p99_ms=25.0
        ),
        registry,
        recorder=recorder_lib.FlightRecorder(),
    )
    engine.evaluate()
    return registry


class TestSloActivity:
    def test_round_trip_from_fresh_metrics_dump(self, tmp_path):
        # The full path every future PR must keep working: armed engine ->
        # registry snapshot -> JSON file -> load_metrics -> slo_activity.
        registry = _armed_registry()
        path = tmp_path / "metrics.json"
        path.write_text(registry.dump_json())
        slo = obs_report.slo_activity(obs_report.load_metrics(str(path)))
        assert slo["armed"] is True
        assert slo["evaluations"] == 1
        assert "suggest_p99:pythia" in slo["breached"]
        assert slo["burn_rates"]["suggest_p99:pythia"]["5s"] >= 5.0
        assert slo["values"]["suggest_p99:pythia"]["5s"] > 0.025
        rendered = obs_report.render_slo(slo)
        assert "BREACHED" in rendered and "suggest_p99:pythia" in rendered

    def test_unarmed_dump(self, tmp_path):
        registry = metrics_lib.MetricsRegistry()
        registry.counter("vizier_serving_fallbacks").inc()
        path = tmp_path / "metrics.json"
        path.write_text(registry.dump_json())
        slo = obs_report.slo_activity(obs_report.load_metrics(str(path)))
        assert slo["armed"] is False and slo["breached"] == []
        assert "not armed" in obs_report.render_slo(slo)

    def test_label_parser(self):
        labels = obs_report._parse_label_str(
            '{slo="suggest_p99:pythia",window="60s"}'
        )
        assert labels == {"slo": "suggest_p99:pythia", "window": "60s"}


class TestFleetSection:
    def _dump_dir(self, tmp_path):
        for source, spans in {
            "client": [
                {"name": "client.suggest", "trace_id": "t1", "span_id": "c",
                 "parent_id": None, "start_time": 1.0, "duration_secs": 0.2},
            ],
            "replica-0": [
                {"name": "service.suggest_trials", "trace_id": "t1",
                 "span_id": "s", "parent_id": "c", "start_time": 1.1,
                 "duration_secs": 0.1},
            ],
        }.items():
            fleet_lib.write_spans(str(tmp_path), source, spans)
        recorder = recorder_lib.FlightRecorder()
        recorder.record(None, "replica_failover", replica="replica-0",
                        successors=["replica-1"])
        recorder.dump_json(
            str(tmp_path / ("fleet" + fleet_lib.RECORDER_SUFFIX))
        )
        return str(tmp_path)

    def test_fleet_section_from_fresh_dump(self, tmp_path):
        section = obs_report.fleet_section(self._dump_dir(tmp_path))
        assert section["sources"] == ["client", "replica-0"]
        assert section["cross_replica_traces"] == 1
        assert section["failover_timeline"][0]["kind"] == "replica_failover"

    def test_json_report_schema_is_stable(self, tmp_path, capsys, monkeypatch):
        """Guards the --json contract: device_activity,
        speculative_activity, slo, and fleet sections must all parse from
        freshly-dumped span/metric files."""
        span_path = _trace_file(tmp_path)
        metrics_path = tmp_path / "metrics.json"
        metrics_path.write_text(_armed_registry().dump_json())
        dump_dir = self._dump_dir(tmp_path / "fleet")
        monkeypatch.setattr(
            sys, "argv",
            ["obs_report.py", span_path, "--json",
             "--slo", str(metrics_path), "--fleet", dump_dir],
        )
        obs_report.main()
        report = json.loads(capsys.readouterr().out)
        assert {
            "spans", "surrogate_activity", "speculative_activity",
            "program_kind_activity", "device_activity", "slo", "fleet",
            "phases",
        } <= set(report)
        assert report["spans"] == 6
        assert report["slo"]["armed"] is True
        assert report["slo"]["burn_rates"]["suggest_p99:pythia"]["5s"] >= 5.0
        assert report["fleet"]["cross_replica_traces"] == 1
        assert report["device_activity"] == {}
        assert report["speculative_activity"]["hit"] == 0

    def test_json_report_without_slo_or_fleet_keeps_keys(
        self, tmp_path, capsys, monkeypatch
    ):
        monkeypatch.setattr(
            sys, "argv", ["obs_report.py", _trace_file(tmp_path), "--json"]
        )
        obs_report.main()
        report = json.loads(capsys.readouterr().out)
        assert report["slo"] is None and report["fleet"] is None


class TestDeviceActivity:
    def _flush_span(self, device=None, occupancy=2, duration=0.01):
        attrs = {"bucket": "gp_ucb_pe/t16/f4x0/m1/q1", "occupancy": occupancy}
        if device is not None:
            attrs["device"] = device
        return {
            "name": "batch_executor.flush",
            "duration_secs": duration,
            "attributes": attrs,
        }

    def test_per_device_breakdown(self):
        spans = [
            self._flush_span("mesh0", occupancy=2, duration=0.010),
            self._flush_span("mesh0", occupancy=4, duration=0.030),
            self._flush_span("mesh1", occupancy=1, duration=0.020),
            {"name": "pythia.suggest", "duration_secs": 0.5},
        ]
        out = obs_report.device_activity(spans)
        assert set(out) == {"mesh0", "mesh1"}
        assert out["mesh0"]["flushes"] == 2
        assert out["mesh0"]["busy_ms"] == 40.0
        assert out["mesh0"]["mean_occupancy"] == 3.0
        assert out["mesh1"]["flushes"] == 1

    def test_single_device_run_is_empty(self):
        # VIZIER_MESH=0 stamps no device attribute -> no breakdown rows.
        spans = [self._flush_span(device=None) for _ in range(3)]
        assert obs_report.device_activity(spans) == {}

    def test_live_mesh_flush_spans_carry_device(self, tmp_path):
        # End-to-end: a real mesh-executor flush emits a device-attributed
        # span the report rolls up.
        from vizier_tpu.parallel.batch_executor import BatchExecutor
        from vizier_tpu.parallel.mesh import MeshConfig
        from tests.parallel.test_batch_executor import (
            StubDesigner,
            _run_concurrent,
        )

        tracer = tracing_lib.Tracer()
        previous = tracing_lib.set_tracer(tracer)
        try:
            ex = BatchExecutor(
                max_batch_size=4,
                max_wait_ms=5.0,
                mesh=MeshConfig(enabled=True, shard_devices=1),
            )
            try:
                results, errors = _run_concurrent(
                    ex, [StubDesigner(i) for i in range(3)]
                )
                assert all(e is None for e in errors)
            finally:
                ex.close()
            path = tmp_path / "mesh_spans.jsonl"
            tracer.dump_jsonl(str(path))
        finally:
            tracing_lib.set_tracer(previous)
        out = obs_report.device_activity(obs_report.load_spans(str(path)))
        assert out, "no device-attributed flush spans recorded"
        assert all(device.startswith("mesh") for device in out)
