"""obs_report: JSON-lines span file round-trip + breakdown rendering."""

import json
import pathlib
import sys

from vizier_tpu.observability import tracing as tracing_lib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2] / "tools"))
import obs_report  # noqa: E402  (tools/ is not a package)


def _trace_file(tmp_path) -> str:
    tracer = tracing_lib.Tracer()
    for _ in range(3):
        with tracer.span("client.suggest"):
            with tracer.span("designer.suggest"):
                pass
    path = tmp_path / "spans.jsonl"
    tracer.dump_jsonl(str(path))
    return str(path)


class TestRoundTrip:
    def test_dump_then_load(self, tmp_path):
        path = _trace_file(tmp_path)
        spans = obs_report.load_spans(path)
        assert len(spans) == 6
        assert {s["name"] for s in spans} == {"client.suggest", "designer.suggest"}
        # Every span survived with its timing + identity intact.
        for span in spans:
            assert span["duration_secs"] > 0
            assert span["trace_id"] and span["span_id"]

    def test_corrupt_lines_skipped(self, tmp_path):
        path = tmp_path / "bad.jsonl"
        good = json.dumps(
            {"name": "x", "trace_id": "t", "span_id": "s", "duration_secs": 0.1}
        )
        path.write_text(f"{good}\nnot json at all\n\n{good}\n")
        assert len(obs_report.load_spans(str(path))) == 2


class TestBreakdown:
    def test_phase_table(self, tmp_path):
        spans = obs_report.load_spans(_trace_file(tmp_path))
        rows = obs_report.phase_breakdown(spans)
        by_phase = {r["phase"]: r for r in rows}
        assert by_phase["client.suggest"]["count"] == 3
        row = by_phase["designer.suggest"]
        assert 0 < row["p50_ms"] <= row["p95_ms"] <= row["p99_ms"] <= row["max_ms"]
        # The outer span contains the inner one, so it owns more total time.
        assert (
            by_phase["client.suggest"]["total_ms"]
            >= by_phase["designer.suggest"]["total_ms"]
        )
        table = obs_report.render_table(rows)
        assert "client.suggest" in table and "p99 ms" in table

    def test_exact_percentiles(self):
        spans = [
            {"name": "p", "duration_secs": v / 1000.0} for v in range(1, 101)
        ]
        (row,) = obs_report.phase_breakdown(spans)
        assert row["p50_ms"] == 50.5  # interpolated median of 1..100 ms
        assert row["max_ms"] == 100.0

    def test_trace_tree(self, tmp_path):
        spans = obs_report.load_spans(_trace_file(tmp_path))
        trace_id = spans[0]["trace_id"]
        tree = obs_report.render_trace(spans, trace_id)
        lines = tree.splitlines()
        assert lines[0] == f"trace {trace_id}"
        # Child indented under its parent.
        assert any(l.startswith("  client.suggest") for l in lines)
        assert any(l.startswith("    designer.suggest") for l in lines)

    def test_trace_tree_missing(self, tmp_path):
        spans = obs_report.load_spans(_trace_file(tmp_path))
        assert "No spans" in obs_report.render_trace(spans, "nope")


class TestSurrogateActivity:
    def _spans(self, names):
        return [{"name": n, "duration_secs": 0.01} for n in names]

    def test_exact_only(self):
        act = obs_report.surrogate_activity(
            self._spans(["gp_bandit.train_gp", "gp_ucb_pe.train_gp", "other"])
        )
        assert act == {"mode": "exact", "exact": 2, "sparse": 0}

    def test_sparse_only(self):
        act = obs_report.surrogate_activity(
            self._spans(["sparse_gp.train", "sparse_gp.acquisition"])
        )
        assert act == {"mode": "sparse", "exact": 0, "sparse": 2}

    def test_mixed_and_none(self):
        mixed = obs_report.surrogate_activity(
            self._spans(["sparse_gp.train", "gp_bandit.train_gp"])
        )
        assert mixed["mode"] == "mixed"
        assert obs_report.surrogate_activity(self._spans(["rpc"]))["mode"] == "none"


class TestSpeculativeActivity:
    def test_counts_serve_events_and_precompute_spans(self):
        spans = [
            {
                "name": "pythia.suggest",
                "duration_secs": 0.001,
                "events": [{"name": "speculative.hit", "attributes": {}}],
            },
            {
                "name": "pythia.suggest",
                "duration_secs": 0.8,
                "events": [{"name": "speculative.miss", "attributes": {}}],
            },
            {
                "name": "pythia.suggest",
                "duration_secs": 0.9,
                "events": [{"name": "speculative.stale", "attributes": {}}],
            },
            {
                "name": "speculative.precompute",
                "duration_secs": 0.7,
                "attributes": {"outcome": "stored"},
            },
            {
                "name": "speculative.precompute",
                "duration_secs": 0.7,
                "attributes": {"outcome": "superseded"},
            },
        ]
        act = obs_report.speculative_activity(spans)
        assert act["hit"] == 1 and act["miss"] == 1 and act["stale"] == 1
        assert act["precomputes"] == 2 and act["stored"] == 1
        assert act["hit_rate"] == round(1 / 3, 4)

    def test_no_activity_is_all_zero(self):
        act = obs_report.speculative_activity(
            [{"name": "pythia.suggest", "duration_secs": 0.1}]
        )
        assert act["hit"] == act["miss"] == act["precomputes"] == 0
        assert act["hit_rate"] == 0.0


class TestDeviceActivity:
    def _flush_span(self, device=None, occupancy=2, duration=0.01):
        attrs = {"bucket": "gp_ucb_pe/t16/f4x0/m1/q1", "occupancy": occupancy}
        if device is not None:
            attrs["device"] = device
        return {
            "name": "batch_executor.flush",
            "duration_secs": duration,
            "attributes": attrs,
        }

    def test_per_device_breakdown(self):
        spans = [
            self._flush_span("mesh0", occupancy=2, duration=0.010),
            self._flush_span("mesh0", occupancy=4, duration=0.030),
            self._flush_span("mesh1", occupancy=1, duration=0.020),
            {"name": "pythia.suggest", "duration_secs": 0.5},
        ]
        out = obs_report.device_activity(spans)
        assert set(out) == {"mesh0", "mesh1"}
        assert out["mesh0"]["flushes"] == 2
        assert out["mesh0"]["busy_ms"] == 40.0
        assert out["mesh0"]["mean_occupancy"] == 3.0
        assert out["mesh1"]["flushes"] == 1

    def test_single_device_run_is_empty(self):
        # VIZIER_MESH=0 stamps no device attribute -> no breakdown rows.
        spans = [self._flush_span(device=None) for _ in range(3)]
        assert obs_report.device_activity(spans) == {}

    def test_live_mesh_flush_spans_carry_device(self, tmp_path):
        # End-to-end: a real mesh-executor flush emits a device-attributed
        # span the report rolls up.
        from vizier_tpu.parallel.batch_executor import BatchExecutor
        from vizier_tpu.parallel.mesh import MeshConfig
        from tests.parallel.test_batch_executor import (
            StubDesigner,
            _run_concurrent,
        )

        tracer = tracing_lib.Tracer()
        previous = tracing_lib.set_tracer(tracer)
        try:
            ex = BatchExecutor(
                max_batch_size=4,
                max_wait_ms=5.0,
                mesh=MeshConfig(enabled=True, shard_devices=1),
            )
            try:
                results, errors = _run_concurrent(
                    ex, [StubDesigner(i) for i in range(3)]
                )
                assert all(e is None for e in errors)
            finally:
                ex.close()
            path = tmp_path / "mesh_spans.jsonl"
            tracer.dump_jsonl(str(path))
        finally:
            tracing_lib.set_tracer(previous)
        out = obs_report.device_activity(obs_report.load_spans(str(path)))
        assert out, "no device-attributed flush spans recorded"
        assert all(device.startswith("mesh") for device in out)
