"""Tracer: span nesting, propagation, ring buffer, no-op surface."""

import json
import threading

from vizier_tpu.observability import tracing as tracing_lib


class TestSpanNesting:
    def test_parent_child_same_thread(self):
        tracer = tracing_lib.Tracer()
        with tracer.span("parent") as parent:
            with tracer.span("child") as child:
                assert child.trace_id == parent.trace_id
                assert child.parent_id == parent.span_id
            # After the child closes, the parent is current again.
            assert tracer.current_span() is parent
        assert tracer.current_span() is None
        names = [s.name for s in tracer.finished_spans()]
        assert names == ["child", "parent"]  # children end first

    def test_fresh_trace_without_parent(self):
        tracer = tracing_lib.Tracer()
        with tracer.span("a") as a:
            pass
        with tracer.span("b") as b:
            pass
        assert a.trace_id != b.trace_id
        assert a.parent_id is None

    def test_explicit_parent_context(self):
        tracer = tracing_lib.Tracer()
        remote = tracing_lib.SpanContext("t" * 32, "s" * 16)
        with tracer.span("child", parent=remote) as child:
            assert child.trace_id == remote.trace_id
            assert child.parent_id == remote.span_id

    def test_use_context_attaches_remote_parent(self):
        tracer = tracing_lib.Tracer()
        remote = tracing_lib.SpanContext("trace1", "span1")
        with tracer.use_context(remote):
            assert tracer.current_context() == remote
            with tracer.span("child") as child:
                assert child.trace_id == "trace1"
                assert child.parent_id == "span1"
        assert tracer.current_context() is None

    def test_exception_recorded_and_propagated(self):
        tracer = tracing_lib.Tracer()
        try:
            with tracer.span("boom"):
                raise ValueError("kapow")
        except ValueError:
            pass
        (span,) = tracer.finished_spans()
        assert span.status == "error"
        assert span.attributes["error.type"] == "ValueError"
        assert span.duration_secs is not None

    def test_cross_thread_propagation(self):
        tracer = tracing_lib.Tracer()
        child_ids = {}

        with tracer.span("root") as root:
            ctx = tracer.current_context()

            def worker():
                # A fresh thread starts with no ambient span; re-attach.
                assert tracer.current_span() is None
                with tracer.use_context(ctx):
                    with tracer.span("worker_span") as s:
                        child_ids["trace"] = s.trace_id
                        child_ids["parent"] = s.parent_id

            t = threading.Thread(target=worker)
            t.start()
            t.join()
        assert child_ids["trace"] == root.trace_id
        assert child_ids["parent"] == root.span_id


class TestWireFormat:
    def test_round_trip(self):
        ctx = tracing_lib.SpanContext("abc123", "def456")
        assert tracing_lib.parse_context(tracing_lib.format_context(ctx)) == ctx

    def test_none_formats_empty(self):
        assert tracing_lib.format_context(None) == ""

    def test_malformed_degrades_to_none(self):
        for bad in ("", "nodash", "-", "a-", "-b"):
            assert tracing_lib.parse_context(bad) is None


class TestEventsAndLinks:
    def test_events_carry_offsets_and_attributes(self):
        tracer = tracing_lib.Tracer()
        with tracer.span("s") as span:
            span.add_event("fallback", reason="circuit_open")
        (span,) = tracer.finished_spans()
        (event,) = span.events
        assert event["name"] == "fallback"
        assert event["attributes"]["reason"] == "circuit_open"
        assert event["offset_secs"] >= 0

    def test_links(self):
        tracer = tracing_lib.Tracer()
        leader = tracing_lib.SpanContext("t1", "s1")
        with tracer.span("follower") as span:
            span.add_link(leader, name="coalesced_leader")
            span.add_link(None)  # ignored
        (span,) = tracer.finished_spans()
        assert span.links == [
            {"trace_id": "t1", "span_id": "s1", "name": "coalesced_leader"}
        ]

    def test_add_current_event_helper(self):
        tracer = tracing_lib.Tracer()
        old = tracing_lib.set_tracer(tracer)
        try:
            tracing_lib.add_current_event("orphan")  # no active span: no-op
            with tracer.span("s"):
                tracing_lib.add_current_event("breaker.transition", to_state="open")
            (span,) = tracer.finished_spans()
            assert span.events[0]["name"] == "breaker.transition"
        finally:
            tracing_lib.set_tracer(old)


class TestRingBufferAndExport:
    def test_ring_buffer_bounded(self):
        tracer = tracing_lib.Tracer(max_spans=5)
        for i in range(12):
            with tracer.span(f"s{i}"):
                pass
        spans = tracer.finished_spans()
        assert len(spans) == 5
        assert spans[0].name == "s7"  # oldest evicted

    def test_drain_empties(self):
        tracer = tracing_lib.Tracer()
        with tracer.span("s"):
            pass
        assert len(tracer.drain()) == 1
        assert tracer.finished_spans() == []

    def test_dump_jsonl_round_trip(self, tmp_path):
        tracer = tracing_lib.Tracer()
        with tracer.span("outer", k="v"):
            with tracer.span("inner"):
                pass
        path = tmp_path / "spans.jsonl"
        assert tracer.dump_jsonl(str(path)) == 2
        lines = [json.loads(l) for l in path.read_text().splitlines()]
        assert {l["name"] for l in lines} == {"outer", "inner"}
        assert all(l["duration_secs"] > 0 for l in lines)

    def test_export_path_sink(self, tmp_path):
        path = tmp_path / "live.jsonl"
        tracer = tracing_lib.Tracer(export_path=str(path))
        with tracer.span("s"):
            pass
        tracer.close()
        (line,) = path.read_text().splitlines()
        assert json.loads(line)["name"] == "s"

    def test_spans_for_trace_ordered(self):
        tracer = tracing_lib.Tracer()
        with tracer.span("a") as a:
            with tracer.span("b"):
                pass
        spans = tracer.spans_for_trace(a.trace_id)
        assert [s.name for s in spans] == ["a", "b"]  # start-time order


class TestNoopTracer:
    def test_full_api_surface(self):
        tracer = tracing_lib.NOOP_TRACER
        assert not tracer.enabled
        with tracer.span("x", k="v") as span:
            span.set_attribute("a", 1)
            span.add_event("e")
            span.add_link(None)
            assert span.context() is None
        assert tracer.current_span() is None
        assert tracer.current_context() is None
        assert tracer.finished_spans() == []
        assert tracer.drain() == []
        assert tracer.dump_jsonl("/nonexistent/never-written") == 0

    def test_noop_span_is_shared_singleton(self):
        with tracing_lib.NOOP_TRACER.span("a") as s1:
            pass
        with tracing_lib.NOOP_TRACER.span("b") as s2:
            pass
        assert s1 is s2 is tracing_lib.NOOP_SPAN


class TestGlobalTracer:
    def test_set_and_restore(self):
        mine = tracing_lib.Tracer()
        old = tracing_lib.set_tracer(mine)
        try:
            assert tracing_lib.get_tracer() is mine
        finally:
            tracing_lib.set_tracer(old)

    def test_config_disabled_yields_noop(self):
        from vizier_tpu.observability import config as config_lib

        tracer = tracing_lib._tracer_from_config(
            config_lib.ObservabilityConfig.disabled()
        )
        assert tracer is tracing_lib.NOOP_TRACER
