"""End-to-end trace propagation: client → service → Pythia → designer.

One client ``suggest()`` against the in-process stack must yield ONE
``trace_id`` whose spans cover all four hops with correct parentage and
start-time ordering — including across the ResponseWaiter worker-thread
hop (deadlines on) — plus the coalesced-follower case where the follower's
Pythia span links to the leader's computation span.
"""

import threading
import time

import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.algorithms import designer_policy
from vizier_tpu.designers import random as random_designer
from vizier_tpu.observability import tracing as tracing_lib
from vizier_tpu.reliability import config as reliability_config_lib
from vizier_tpu.service import proto_converters as pc
from vizier_tpu.service import pythia_service, vizier_client, vizier_service
from vizier_tpu.service.protos import vizier_service_pb2

STUDY = "owners/obs/studies/trace"


def _study_config():
    config = vz.StudyConfig(algorithm="RANDOM_SEARCH")
    config.search_space.root.add_float_param("x", 0.0, 1.0)
    config.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return config


class _RandomDesignerPolicyFactory:
    """Routes every algorithm through DesignerPolicy → designer spans."""

    def __call__(self, problem, algorithm, supporter, study_name):
        return designer_policy.DesignerPolicy(
            supporter,
            lambda p, **kw: random_designer.RandomDesigner(p.search_space, seed=0),
        )


class _SlowDesignerPolicyFactory(_RandomDesignerPolicyFactory):
    """Same, but the designer's suggest dawdles so concurrents coalesce."""

    def __init__(self, delay_secs: float):
        self._delay = delay_secs

    def __call__(self, problem, algorithm, supporter, study_name):
        delay = self._delay

        class _SlowRandom(random_designer.RandomDesigner):
            def suggest(self, count=None):
                time.sleep(delay)
                return super().suggest(count)

        return designer_policy.DesignerPolicy(
            supporter, lambda p, **kw: _SlowRandom(p.search_space, seed=0)
        )


def _make_stack(policy_factory=None, reliability=None):
    servicer = vizier_service.VizierServicer(reliability_config=reliability)
    pythia = pythia_service.PythiaServicer(
        servicer, policy_factory, reliability_config=reliability
    )
    servicer.set_pythia(pythia)
    servicer.CreateStudy(
        vizier_service_pb2.CreateStudyRequest(
            parent="owners/obs",
            study=pc.study_to_proto(_study_config(), STUDY),
        )
    )
    return servicer, pythia


@pytest.fixture
def tracer():
    t = tracing_lib.Tracer()
    old = tracing_lib.set_tracer(t)
    yield t
    tracing_lib.set_tracer(old)


class TestFourHopTrace:
    def test_single_trace_with_ordered_spans(self, tracer):
        servicer, _ = _make_stack(policy_factory=_RandomDesignerPolicyFactory())
        client = vizier_client.VizierClient(servicer, STUDY, "worker-0")
        (trial,) = client.get_suggestions(1)
        assert trial.parameters

        spans = tracer.finished_spans()
        roots = [s for s in spans if s.name == "client.suggest"]
        assert len(roots) == 1
        trace_id = roots[0].trace_id
        # Every span this exchange produced belongs to ONE trace.
        assert {s.trace_id for s in spans} == {trace_id}

        chain = tracer.spans_for_trace(trace_id)
        names = [s.name for s in chain]
        hops = [
            "client.suggest",
            "service.suggest_trials",
            "service.pythia_dispatch",
            "pythia.suggest",
            "pythia.suggest_compute",
            "designer.update",
            "designer.suggest",
        ]
        for hop in hops:
            assert hop in names, f"missing span {hop!r} (got {names})"
        # Start-time order follows the request's path downward.
        positions = [names.index(h) for h in hops[:4]]
        assert positions == sorted(positions)

        by_name = {s.name: s for s in chain}
        # Parentage: each hop is a child of the previous one.
        assert by_name["client.suggest"].parent_id is None
        assert (
            by_name["service.suggest_trials"].parent_id
            == by_name["client.suggest"].span_id
        )
        assert (
            by_name["service.pythia_dispatch"].parent_id
            == by_name["service.suggest_trials"].span_id
        )
        # The Pythia hop crossed the ResponseWaiter worker thread (deadlines
        # default on) — its parent comes from the proto's trace_context.
        assert (
            by_name["pythia.suggest"].parent_id
            == by_name["service.pythia_dispatch"].span_id
        )
        assert (
            by_name["pythia.suggest_compute"].parent_id
            == by_name["pythia.suggest"].span_id
        )
        assert (
            by_name["designer.suggest"].parent_id
            == by_name["pythia.suggest_compute"].span_id
        )
        # Deadline budget was stamped at the service + pythia hops.
        assert by_name["service.suggest_trials"].attributes[
            "deadline_budget_secs"
        ] > 0
        assert by_name["pythia.suggest"].attributes["deadline_remaining_secs"] > 0

    def test_two_suggests_two_traces(self, tracer):
        servicer, _ = _make_stack(policy_factory=_RandomDesignerPolicyFactory())
        client = vizier_client.VizierClient(servicer, STUDY, "worker-0")
        client.get_suggestions(1)
        client.get_suggestions(1)
        roots = [s for s in tracer.finished_spans() if s.name == "client.suggest"]
        assert len(roots) == 2
        assert roots[0].trace_id != roots[1].trace_id


class TestCoalescedFollowerLink:
    def test_follower_span_links_to_leader_computation(self, tracer):
        servicer, pythia = _make_stack(
            policy_factory=_SlowDesignerPolicyFactory(delay_secs=0.4)
        )
        n = 2
        ops = [None] * n
        barrier = threading.Barrier(n)

        def worker(i):
            barrier.wait(timeout=10)
            ops[i] = servicer.SuggestTrials(
                vizier_service_pb2.SuggestTrialsRequest(
                    parent=STUDY, suggestion_count=1, client_id=f"client-{i}"
                )
            )

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        for op in ops:
            assert op is not None and op.done and not op.error
        assert pythia.serving_stats()["coalesced_requests"] == n - 1

        spans = tracer.finished_spans()
        computes = [s for s in spans if s.name == "pythia.suggest_compute"]
        assert len(computes) == 1  # ONE designer computation served both
        leader_compute = computes[0]

        pythia_spans = [s for s in spans if s.name == "pythia.suggest"]
        assert len(pythia_spans) == n
        followers = [s for s in pythia_spans if s.attributes.get("coalesced")]
        assert len(followers) == n - 1
        for follower in followers:
            # Different trace (different client request)...
            assert follower.trace_id != leader_compute.trace_id
            # ...but linked to the computation that produced its answer.
            assert {
                "trace_id": leader_compute.trace_id,
                "span_id": leader_compute.span_id,
                "name": "coalesced_leader",
            } in follower.links


class TestDisabledTracing:
    def test_noop_tracer_produces_no_spans_and_still_serves(self):
        old = tracing_lib.set_tracer(tracing_lib.NOOP_TRACER)
        try:
            servicer, _ = _make_stack(
                policy_factory=_RandomDesignerPolicyFactory()
            )
            client = vizier_client.VizierClient(servicer, STUDY, "worker-0")
            (trial,) = client.get_suggestions(1)
            assert trial.parameters
            assert tracing_lib.get_tracer().finished_spans() == []
        finally:
            tracing_lib.set_tracer(old)

    def test_untraced_request_starts_fresh_trace_at_service(self, tracer):
        servicer, _ = _make_stack(policy_factory=_RandomDesignerPolicyFactory())
        op = servicer.SuggestTrials(
            vizier_service_pb2.SuggestTrialsRequest(
                parent=STUDY, suggestion_count=1, client_id="bare"
            )
        )
        assert op.done and not op.error
        service_spans = [
            s for s in tracer.finished_spans() if s.name == "service.suggest_trials"
        ]
        assert len(service_spans) == 1
        assert service_spans[0].parent_id is None  # no client span upstream
