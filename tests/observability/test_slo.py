"""SLO engine: windowed burn rates, gauges, breach-triggered black box."""

import json

import pytest

from vizier_tpu.observability import flight_recorder as recorder_lib
from vizier_tpu.observability import metrics as metrics_lib
from vizier_tpu.observability import slo as slo_lib
from vizier_tpu.observability import tracing as tracing_lib


def _engine(registry, **overrides):
    base = dict(
        enabled=True, windows=(5.0,), min_samples=1, eval_interval_s=0.0
    )
    base.update(overrides)
    return slo_lib.SloEngine(
        slo_lib.SloConfig(**base),
        registry,
        recorder=recorder_lib.FlightRecorder(),
    )


def _by_slo(statuses, window=None):
    out = {}
    for status in statuses:
        if window is None or status.window_secs == window:
            out[status.slo] = status
    return out


class TestConfig:
    def test_window_parsing(self):
        assert slo_lib._parse_windows("60,300") == (60.0, 300.0)
        assert slo_lib._parse_windows(" 10 , junk, 20 ") == (10.0, 20.0)
        # Garbage degrades to the defaults, never to an empty set.
        assert slo_lib._parse_windows("") == (60.0, 300.0)

    def test_from_env_defaults_off(self, monkeypatch):
        for name in (
            "VIZIER_SLO", "VIZIER_SLO_WINDOWS", "VIZIER_SLO_SUGGEST_P99_MS"
        ):
            monkeypatch.delenv(name, raising=False)
        config = slo_lib.SloConfig.from_env()
        assert not config.enabled
        assert config.windows == (60.0, 300.0)
        assert config.as_dict()["suggest_p99_ms"] == 5000.0

    def test_from_env_overrides(self, monkeypatch):
        monkeypatch.setenv("VIZIER_SLO", "1")
        monkeypatch.setenv("VIZIER_SLO_WINDOWS", "7,11")
        monkeypatch.setenv("VIZIER_SLO_SUGGEST_P99_MS", "42.5")
        monkeypatch.setenv("VIZIER_SLO_DUMP_DIR", "/tmp/x")
        config = slo_lib.SloConfig.from_env()
        assert config.enabled and config.windows == (7.0, 11.0)
        assert config.suggest_p99_ms == 42.5 and config.dump_dir == "/tmp/x"


class TestLatencyObjective:
    def test_healthy_traffic_does_not_breach(self):
        registry = metrics_lib.MetricsRegistry()
        hist = registry.histogram("vizier_suggest_latency_seconds")
        for _ in range(50):
            hist.observe(0.002, hop="pythia")
        engine = _engine(registry, suggest_p99_ms=25.0)
        status = _by_slo(engine.evaluate())["suggest_p99:pythia"]
        assert status.total == 50 and not status.breached
        assert status.burn_rate == 0.0
        assert status.value is not None and status.value < 0.025

    def test_slow_tail_breaches_and_exports_gauges(self):
        registry = metrics_lib.MetricsRegistry()
        hist = registry.histogram("vizier_suggest_latency_seconds")
        for _ in range(45):
            hist.observe(0.002, hop="pythia")
        for _ in range(5):  # 10% above threshold >> the 1% budget
            hist.observe(0.5, hop="pythia")
        engine = _engine(registry, suggest_p99_ms=25.0)
        status = _by_slo(engine.evaluate())["suggest_p99:pythia"]
        assert status.breached and status.burn_rate >= 5.0
        burn = registry.get("vizier_slo_burn_rate")
        assert burn.value(slo="suggest_p99:pythia", window="5s") >= 5.0
        breached = registry.get("vizier_slo_breached")
        assert breached.value(slo="suggest_p99:pythia") == 1.0

    def test_per_hop_objectives_are_independent(self):
        registry = metrics_lib.MetricsRegistry()
        hist = registry.histogram("vizier_suggest_latency_seconds")
        for _ in range(20):
            hist.observe(0.001, hop="service")
            hist.observe(0.5, hop="pythia")
        statuses = _by_slo(_engine(registry, suggest_p99_ms=25.0).evaluate())
        assert statuses["suggest_p99:pythia"].breached
        assert not statuses["suggest_p99:service"].breached


class TestRatioObjectives:
    def test_hit_rate_skipped_without_speculative_traffic(self):
        registry = metrics_lib.MetricsRegistry()
        status = _by_slo(_engine(registry).evaluate())["speculative_hit_rate"]
        assert status.value is None and not status.breached

    def test_hit_rate_breaches_below_target(self):
        registry = metrics_lib.MetricsRegistry()
        registry.counter("vizier_serving_speculative_hits").inc(5)
        registry.counter("vizier_serving_speculative_misses").inc(5)
        engine = _engine(registry, speculative_hit_rate=0.8)
        status = _by_slo(engine.evaluate())["speculative_hit_rate"]
        assert status.value == 0.5
        assert status.breached  # 50% bad vs 20% allowed -> burn 2.5
        assert status.burn_rate == pytest.approx(2.5)

    def test_fallback_rate_over_pythia_volume(self):
        registry = metrics_lib.MetricsRegistry()
        hist = registry.histogram("vizier_suggest_latency_seconds")
        for _ in range(20):
            hist.observe(0.001, hop="pythia")
        registry.counter("vizier_serving_fallbacks").inc(4)
        engine = _engine(registry, fallback_rate=0.05)
        status = _by_slo(engine.evaluate())["reliability_fallback_rate"]
        assert status.value == pytest.approx(0.2)
        assert status.breached and status.burn_rate == pytest.approx(4.0)


class TestFleetObjectives:
    def test_occupancy_floor(self):
        registry = metrics_lib.MetricsRegistry()
        occ = registry.histogram(
            "vizier_batch_occupancy",
            buckets=metrics_lib.exponential_buckets(1, 2, 5),
        )
        for _ in range(10):
            occ.observe(1.0, bucket="b")
        engine = _engine(registry, occupancy_min=4.0)
        status = _by_slo(engine.evaluate())["batch_occupancy_mean"]
        assert status.value == pytest.approx(1.0)
        assert status.breached

    def test_mesh_balance_and_utilization_gauges(self):
        registry = metrics_lib.MetricsRegistry()
        flushes = registry.counter("vizier_batch_flushes")
        flushes.inc(30, reason="full", device="mesh0")
        flushes.inc(2, reason="full", device="mesh1")
        engine = _engine(registry, mesh_imbalance_max=4.0)
        status = _by_slo(engine.evaluate())["mesh_utilization_balance"]
        assert status.value == pytest.approx(15.0)
        assert status.breached
        util = registry.get("vizier_slo_mesh_utilization")
        assert util.value(device="mesh0") == pytest.approx(30 / 32)

    def test_single_placement_is_skipped(self):
        registry = metrics_lib.MetricsRegistry()
        registry.counter("vizier_batch_flushes").inc(30, device="mesh0")
        status = _by_slo(_engine(registry).evaluate())[
            "mesh_utilization_balance"
        ]
        assert status.value is None and not status.breached


class TestWindows:
    def test_old_traffic_falls_out_of_the_window(self):
        registry = metrics_lib.MetricsRegistry()
        hist = registry.histogram("vizier_suggest_latency_seconds")
        engine = _engine(registry, suggest_p99_ms=25.0, windows=(10.0,))
        for _ in range(20):  # the regression, at t=0
            hist.observe(0.5, hop="pythia")
        assert _by_slo(engine.evaluate(now=1000.0))[
            "suggest_p99:pythia"
        ].breached
        for _ in range(50):  # recovery traffic
            hist.observe(0.001, hop="pythia")
        engine.evaluate(now=1005.0)
        # 11s later the slow burst predates the 10s window baseline.
        status = _by_slo(engine.evaluate(now=1011.0))["suggest_p99:pythia"]
        assert not status.breached
        assert status.total == 50

    def test_min_samples_gates_breaching(self):
        registry = metrics_lib.MetricsRegistry()
        hist = registry.histogram("vizier_suggest_latency_seconds")
        hist.observe(0.5, hop="pythia")
        engine = _engine(registry, suggest_p99_ms=25.0, min_samples=5)
        status = _by_slo(engine.evaluate())["suggest_p99:pythia"]
        assert not status.breached and status.burn_rate is None


class TestBreachHandling:
    def _breach_engine(self, tmp_path, recorder=None):
        registry = metrics_lib.MetricsRegistry()
        hist = registry.histogram("vizier_suggest_latency_seconds")
        for _ in range(9):
            hist.observe(0.001, hop="pythia")
        hist.observe(0.9, trace_id="breach-trace", hop="pythia")
        engine = slo_lib.SloEngine(
            slo_lib.SloConfig(
                enabled=True,
                windows=(5.0,),
                min_samples=1,
                suggest_p99_ms=25.0,
                dump_dir=str(tmp_path),
                breach_cooldown_s=1e6,
            ),
            registry,
            recorder=recorder or recorder_lib.FlightRecorder(),
        )
        return engine, registry

    def test_blackbox_dump_contents(self, tmp_path):
        tracer = tracing_lib.Tracer()
        previous = tracing_lib.set_tracer(tracer)
        try:
            recorder = recorder_lib.FlightRecorder()
            recorder.record("s1", "suggest", trace_id="breach-trace")
            engine, _ = self._breach_engine(tmp_path, recorder=recorder)
            engine.evaluate()
            assert len(engine.dumps) == 1
            payload = json.loads(open(engine.dumps[0]).read())
            assert payload["version"] == 1
            slos = {s["slo"] for s in payload["breaching"]}
            assert "suggest_p99:pythia" in slos
            exemplars = payload["exemplars"]["pythia"]
            assert exemplars[0]["trace_id"] == "breach-trace"
            assert "breach-trace" in payload["exemplar_traces"]
            assert payload["flight_recorder"]["s1"][0]["kind"] == "suggest"
            assert "vizier_suggest_latency_seconds" in payload["metrics"]
            # The breach itself landed on the recorder's fleet ring.
            kinds = [e["kind"] for e in recorder.events(kind="slo_breach")]
            assert kinds == ["slo_breach"]
        finally:
            tracing_lib.set_tracer(previous)

    def test_cooldown_suppresses_repeat_dumps(self, tmp_path):
        engine, _ = self._breach_engine(tmp_path)
        engine.evaluate()
        engine.evaluate()
        assert len(engine.dumps) == 1

    def test_no_dump_dir_still_records_the_breach(self):
        registry = metrics_lib.MetricsRegistry()
        hist = registry.histogram("vizier_suggest_latency_seconds")
        hist.observe(0.9, hop="pythia")
        recorder = recorder_lib.FlightRecorder()
        engine = slo_lib.SloEngine(
            slo_lib.SloConfig(
                enabled=True, windows=(5.0,), min_samples=1,
                suggest_p99_ms=25.0,
            ),
            registry,
            recorder=recorder,
        )
        engine.evaluate()
        assert engine.dumps == []
        assert recorder.events(kind="slo_breach")


class TestRuntimeIntegration:
    def test_runtime_unarmed_by_default(self):
        from vizier_tpu.serving import runtime as runtime_lib

        runtime = runtime_lib.ServingRuntime()
        try:
            assert runtime.slo_engine is None
            assert runtime.slo_report() == {"armed": False}
        finally:
            runtime.shutdown()

    def test_runtime_armed_reports_and_shuts_down(self):
        import threading

        from vizier_tpu.serving import runtime as runtime_lib

        before = set(threading.enumerate())
        runtime = runtime_lib.ServingRuntime(
            slo=slo_lib.SloConfig(
                enabled=True, windows=(5.0,), eval_interval_s=0.01
            )
        )
        try:
            assert runtime.slo_engine is not None
            runtime.observe_suggest_latency("pythia", 0.001, trace_id="t")
            report = runtime.slo_report()
            assert report["armed"] is True
            assert any(
                s["slo"] == "suggest_p99:pythia" for s in report["statuses"]
            )
        finally:
            runtime.shutdown()
        leaked = [
            t
            for t in set(threading.enumerate()) - before
            if t.name == "vizier-slo-eval" and t.is_alive()
        ]
        assert not leaked
