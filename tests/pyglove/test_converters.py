"""DNASpec ⇄ search-space / DNA ⇄ trial converter tests.

Uses a structural test double of the ``pg.geno`` data model (Space /
Choices / Float / DNA with the same attribute surface), so the full tree
walk — nested conditional candidate subspaces, multi-subchoice Choices,
literal values, floats — is exercised without pyglove installed.
"""

import dataclasses
from typing import Any, List, Optional, Sequence

import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.pyglove import converters


# -- pg.geno test double -----------------------------------------------------


@dataclasses.dataclass
class Space:
    elements: Sequence[Any] = ()


@dataclasses.dataclass
class Choices:
    name: str
    candidates: Sequence[Space]
    literal_values: Optional[Sequence[Any]] = None
    num_choices: int = 1
    location: str = ""


@dataclasses.dataclass
class Float:
    name: str
    min_value: float
    max_value: float
    scale: Optional[str] = None
    location: str = ""


@dataclasses.dataclass
class DNA:
    value: Any = None
    children: Sequence["DNA"] = ()


def _nas_spec() -> Space:
    """model ∈ {mlp, cnn}; mlp→(units float, act ∈ {relu,tanh}); cnn→(filters)."""
    mlp_space = Space(
        elements=[
            Float("units", 16.0, 256.0, scale="log"),
            Choices("act", [Space(), Space()], literal_values=["relu", "tanh"]),
        ]
    )
    cnn_space = Space(elements=[Float("filters", 8.0, 64.0)])
    return Space(
        elements=[
            Choices(
                "model", [mlp_space, cnn_space], literal_values=["mlp", "cnn"]
            ),
            Float("lr", 1e-4, 1e-1, scale="log"),
        ]
    )


class TestToSearchSpace:
    def test_conditional_tree(self):
        space = converters.to_search_space(_nas_spec())
        assert space.is_conditional
        names = space.parameter_names()
        assert "model" in names and "lr" in names
        # Conditional children exist under candidate-scoped prefixes.
        assert any("units" in n for n in names)
        assert any("filters" in n for n in names)
        model = space.get("model")
        assert {c for cfg in model.children for c in cfg.matching_parent_values} == {
            "mlp",
            "cnn",
        }

    def test_literals_become_categories(self):
        space = converters.to_search_space(_nas_spec())
        # (SearchSpace stores categorical values sorted; membership is the
        # contract, the converter keeps its own candidate-index order.)
        assert set(space.get("model").feasible_values) == {"mlp", "cnn"}

    def test_float_scale(self):
        space = converters.to_search_space(_nas_spec())
        assert space.get("lr").scale_type == vz.ScaleType.LOG

    def test_multi_subchoice_expands(self):
        spec = Space(
            elements=[
                Choices(
                    "ops",
                    [Space(), Space(), Space()],
                    literal_values=["a", "b", "c"],
                    num_choices=2,
                )
            ]
        )
        space = converters.to_search_space(spec)
        assert set(space.parameter_names()) == {"ops[0]", "ops[1]"}


class TestDnaRoundTrip:
    def test_dna_to_parameters_conditional(self):
        conv = converters.DNASpecConverter(_nas_spec())
        dna = DNA(
            children=[
                DNA(value=0, children=[DNA(value=64.0), DNA(value=1)]),  # mlp
                DNA(value=0.01),
            ]
        )
        params = conv.dna_to_parameters(dna)
        assert params["model"] == "mlp"
        assert params["model/0/units"] == 64.0
        assert params["model/0/act"] == "tanh"
        assert params["lr"] == 0.01
        # The cnn branch's parameter is absent (inactive subtree).
        assert not any("filters" in k for k in params)

    def test_parameters_to_dna_values(self):
        conv = converters.DNASpecConverter(_nas_spec())
        values = conv.parameters_to_dna_values(
            {"model": "cnn", "model/1/filters": 32.0, "lr": 0.001}
        )
        # [(choice=1, [(32.0, [])]), (0.001, [])]
        assert values[0][0] == 1
        assert values[0][1][0][0] == 32.0
        assert values[1][0] == 0.001

    def test_round_trip_through_suggestion(self):
        conv = converters.DNASpecConverter(_nas_spec())
        dna = DNA(
            children=[
                DNA(value=1, children=[DNA(value=16.0)]),  # cnn
                DNA(value=0.05),
            ]
        )
        suggestion = conv.to_trial_suggestion(dna)
        trial = suggestion.to_trial(1)
        values = conv.to_dna_values(trial)
        assert values[0][0] == 1
        assert values[0][1][0][0] == 16.0
        assert values[1][0] == pytest.approx(0.05)

    def test_multi_subchoice_round_trip(self):
        spec = Space(
            elements=[
                Choices(
                    "ops",
                    [Space(), Space(), Space()],
                    literal_values=["a", "b", "c"],
                    num_choices=2,
                )
            ]
        )
        conv = converters.DNASpecConverter(spec)
        dna = DNA(children=[DNA(children=[DNA(value=2), DNA(value=0)])])
        params = conv.dna_to_parameters(dna)
        assert params == {"ops[0]": "c", "ops[1]": "a"}
        values = conv.parameters_to_dna_values(params)
        assert values[0][1][0][0] == 2 and values[0][1][1][0] == 0

    def test_bad_dna_arity_rejected(self):
        conv = converters.DNASpecConverter(_nas_spec())
        with pytest.raises(ValueError, match="children"):
            conv.dna_to_parameters(DNA(children=[DNA(value=0)]))

    def test_unknown_literal_rejected(self):
        conv = converters.DNASpecConverter(_nas_spec())
        with pytest.raises(ValueError, match="candidate literal"):
            conv.parameters_to_dna_values({"model": "transformer", "lr": 0.01})

    def test_missing_decision_rejected(self):
        conv = converters.DNASpecConverter(_nas_spec())
        with pytest.raises(ValueError, match="Missing decision"):
            conv.parameters_to_dna_values({"model": "cnn", "lr": 0.01})


class TestDuplicateLiterals:
    def test_duplicate_primitives_disambiguated(self):
        spec = Space(
            elements=[
                Choices(
                    "act",
                    [Space(), Space(elements=[Float("slope", 0.0, 1.0)])],
                    literal_values=["relu", "relu"],  # equal literals!
                )
            ]
        )
        space = converters.to_search_space(spec)
        values = list(space.get("act").feasible_values)
        assert len(set(values)) == 2
        conv = converters.DNASpecConverter(spec)
        # Choice 1 (with the conditional child) round-trips to index 1.
        params = conv.dna_to_parameters(
            DNA(children=[DNA(value=1, children=[DNA(value=0.5)])])
        )
        rebuilt = conv.parameters_to_dna_values(params)
        assert rebuilt[0][0] == 1
        assert rebuilt[0][1][0][0] == 0.5


class TestNonPrimitiveLiterals:
    def test_index_prefixed_categories(self):
        spec = Space(
            elements=[
                Choices(
                    "layer",
                    [Space(), Space()],
                    literal_values=[{"type": "conv"}, {"type": "pool"}],
                )
            ]
        )
        space = converters.to_search_space(spec)
        values = list(space.get("layer").feasible_values)
        assert values[0].startswith("0/") and values[1].startswith("1/")
        conv = converters.DNASpecConverter(spec)
        params = conv.dna_to_parameters(DNA(children=[DNA(value=1)]))
        assert params["layer"] == values[1]
        assert conv.parameters_to_dna_values(params)[0][0] == 1
