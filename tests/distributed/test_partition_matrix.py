"""The partition matrix over the wire link (satellite of PR 15).

Three behaviors a partition must not break, each proven on the REAL gRPC
delivery path with a netchaos schedule on the origin's outbound link:

- **partition-during-append** — deliveries dropped inside the window are
  recovered by the automatic re-baseline on heal: the standby log ends
  bit-identical to an unpartitioned reference log.
- **partition-then-failover-then-heal** — the fenced stale origin cannot
  write (its generation is rejected), and a recovery plan built from the
  fenced views does not resurrect a study whose deletion the origin
  missed (the baseline absence claim).
- **lease expiry vs slow-but-alive** — a lease only expires on SILENCE:
  renewals arriving under injected delay (shorter than the timeout) never
  trigger failover; a partition (no renewals at all) does.
"""

import time

import pytest

grpc = pytest.importorskip("grpc")

from concurrent import futures

from vizier_tpu.distributed import replication as replication_lib
from vizier_tpu.distributed import replication_service as repl_service
from vizier_tpu.distributed import subprocess_fleet
from vizier_tpu.distributed import wal as wal_lib
from vizier_tpu.service import grpc_stubs
from vizier_tpu.service.protos import study_pb2
from vizier_tpu.testing import netchaos as netchaos_lib

STUDY = "owners/o/studies/pm"


class _Receiver:
    def __init__(self, tmpdir, replica_id="replica-1"):
        self.standby = replication_lib.StandbyStore(str(tmpdir))
        self.servicer = repl_service.ReplicationServicer(
            replica_id, self.standby
        )
        self.server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
        grpc_stubs.add_replication_servicer_to_server(
            self.servicer, self.server
        )
        port = self.server.add_insecure_port("localhost:0")
        self.endpoint = f"localhost:{port}"
        self.server.start()

    def stop(self):
        self.server.stop(0).wait()
        grpc_stubs.close_channel(self.endpoint)
        self.standby.close()


def _replayed_state(receiver):
    """The state a failover would recover from this standby log."""
    from vizier_tpu.service import ram_datastore

    store = ram_datastore.NestedDictRAMDataStore()
    for _seq, opcode, payload in receiver.standby.records_for("replica-0"):
        wal_lib.apply_record(store, opcode, payload)
    return wal_lib.export_records(store)


def _host(tmp_path, receiver, *, netchaos=None, name="origin"):
    store = wal_lib.PersistentDataStore(
        str(tmp_path / name), snapshot_interval=10_000
    )
    link = repl_service.GrpcReplicationLink(
        {"replica-1": receiver.endpoint},
        src_id="replica-0",
        netchaos=netchaos,
        retry_attempts=1,
        retry_base_delay_secs=0.0,
        retry_max_delay_secs=0.0,
        down_cooldown_secs=0.05,
    )
    host = repl_service.ReplicaReplicationHost(
        "replica-0",
        ["replica-0", "replica-1"],
        datastore=store,
        link=link,
        factor=1,
        epoch=1,
        repair_interval_secs=0.1,
    )
    store.set_append_sink(host.sink())
    return store, host


class TestPartitionDuringAppend:
    def test_resync_converges_bit_identically_after_heal(self, tmp_path):
        # Reference: the same mutation sequence streamed with NO faults.
        reference = _Receiver(tmp_path / "ref_rx")
        ref_store, ref_host = _host(tmp_path, reference, name="ref_origin")
        # Partitioned arm: the link is severed for the middle third.
        net = netchaos_lib.NetChaos(seed=4)
        receiver = _Receiver(tmp_path / "rx")
        store, host = _host(tmp_path, receiver, netchaos=net)
        try:
            def mutate(target_store, i):
                if i == 0:
                    target_store.create_study(study_pb2.Study(name=STUDY))
                else:
                    trial = study_pb2.Trial(name=f"{STUDY}/trials/{i}")
                    target_store.create_trial(trial)

            for i in range(4):
                mutate(ref_store, i)
                mutate(store, i)
            assert host.flush(10.0)
            net.partition("replica-1")
            for i in range(4, 8):
                mutate(ref_store, i)
                mutate(store, i)  # deliveries dropped: log goes stale
            host.flush(2.0)
            assert receiver.standby.last_seq("replica-0") < 8
            net.heal("replica-1")
            for i in range(8, 10):
                mutate(ref_store, i)
                mutate(store, i)  # first post-heal sight re-baselines
            assert host.flush(10.0) and ref_host.flush(10.0)
            # The heal's re-baseline replaces the log with a COMPACTED
            # export (every record at the baseline seq), so convergence
            # is asserted where it matters: replaying either standby log
            # into a fresh store recovers bit-identical state, and the
            # partitioned log's sequence horizon reaches the reference's.
            # (The self-healing repair pass converges within its throttle
            # even when the first post-heal delivery lands in the link's
            # dead-peer cooldown — poll, bounded.)
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline and _replayed_state(
                receiver
            ) != _replayed_state(reference):
                time.sleep(0.05)
            assert _replayed_state(receiver) == _replayed_state(reference)
            assert receiver.standby.last_seq(
                "replica-0"
            ) == reference.standby.last_seq("replica-0")
            assert host.resyncs > ref_host.resyncs  # the heal cost a resync
        finally:
            host.close()
            store.close()
            ref_host.close()
            ref_store.close()
            receiver.stop()
            reference.stop()


class TestPartitionThenFailoverThenHeal:
    def test_stale_origin_fenced_and_deletions_not_resurrected(self, tmp_path):
        net = netchaos_lib.NetChaos(seed=2)
        receiver = _Receiver(tmp_path / "rx")
        store, host = _host(tmp_path, receiver, netchaos=net)
        try:
            store.create_study(study_pb2.Study(name=STUDY))
            doomed = "owners/o/studies/doomed"
            store.create_study(study_pb2.Study(name=doomed))
            assert host.flush(10.0)
            # Partition the origin away; the manager fences its epoch on
            # the reachable holder (failover cutover), and the NEW
            # generation — which deleted `doomed` after taking over —
            # announces itself with a baseline that no longer contains
            # it (seq 5, one mutation past the deletion).
            net.partition("replica-1")
            receiver.standby.fence("replica-0", 2)
            link2 = repl_service.GrpcReplicationLink(
                {"replica-1": receiver.endpoint}, src_id="replica-0b"
            )
            new_generation_state = [
                (
                    5,
                    wal_lib.CREATE_STUDY,
                    study_pb2.Study(name=STUDY).SerializeToString(),
                )
            ]
            assert link2.deliver(
                "replica-1", "replica-0", 2, new_generation_state, True, 5
            ) == (True, 5)
            net.heal("replica-1")
            # The healed zombie keeps appending to its local WAL; its
            # deliveries come from the DEAD generation and are REJECTED
            # by the fenced store — the split-brain write never lands.
            store.create_trial(study_pb2.Trial(name=f"{STUDY}/trials/99"))
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline and not host.fenced:
                time.sleep(0.02)
            assert host.fenced
            assert receiver.servicer.fenced_rejections >= 1
            assert receiver.standby.last_seq("replica-0") == 5
            # A LATER failover of the origin plans from the fenced view:
            # the zombie's longer local WAL — which still shows `doomed`
            # alive AND carries the post-fence trial — must not win.
            # `doomed` dies to the baseline's absence claim; the study's
            # records come from the newer-sequence standby baseline, so
            # the stale trial 99 never resurfaces either.
            local_records, local_torn = wal_lib.read_directory_with_seqs(
                str(tmp_path / "origin")
            )
            view = receiver.standby.view_for("replica-0")
            plan = replication_lib.plan_recovery(
                "replica-0",
                local_records,
                local_torn,
                [view],
                successors_fn=lambda study: ["replica-1"],
                holders=["replica-1"],
            )
            planned = {item.study: item for item in plan.studies}
            assert doomed not in planned  # not resurrected
            assert planned[STUDY].source == "standby"
            assert all(
                b"trials/99" not in payload
                for _opcode, payload in planned[STUDY].records
            )
        finally:
            host.close()
            store.close()
            receiver.stop()


class TestLeaseSemantics:
    def test_renewal_under_delay_never_expires(self):
        lease = subprocess_fleet.LeaseTable(timeout_s=0.5)
        now = 100.0
        for step in range(10):
            # Renewals arrive LATE (0.3s of injected delay) but inside
            # the timeout: the lease never lapses.
            lease.renew("replica-0", now + step * 0.3)
            assert not lease.expired("replica-0", now + step * 0.3 + 0.29)
        assert lease.expired("replica-0", now + 9 * 0.3 + 0.51)

    def test_silence_expires_and_drop_forgets(self):
        lease = subprocess_fleet.LeaseTable(timeout_s=0.2)
        lease.renew("replica-0", 50.0)
        assert not lease.expired("replica-0", 50.1)
        assert lease.expired("replica-0", 50.2)
        lease.drop("replica-0")
        # No lease at all is not "expired": an undeclared replica must
        # not be re-declared dead in a loop.
        assert not lease.expired("replica-0", 99.0)

    def test_snapshot_reports_remaining_seconds(self):
        lease = subprocess_fleet.LeaseTable(timeout_s=5.0)
        lease.renew("replica-0")
        snapshot = lease.snapshot()
        assert 0.0 < snapshot["replica-0"] <= 5.0
