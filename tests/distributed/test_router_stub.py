"""RoutedVizierStub: drop-in substitutability, affinity, failure notes."""

import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.distributed import router_stub, routing
from vizier_tpu.service import proto_converters as pc
from vizier_tpu.service import pythia_service, vizier_client, vizier_service
from vizier_tpu.service.protos import vizier_service_pb2


def study_config() -> vz.StudyConfig:
    config = vz.StudyConfig(algorithm="RANDOM_SEARCH")
    config.search_space.root.add_float_param("x", 0.0, 1.0)
    config.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return config


def make_servicer():
    servicer = vizier_service.VizierServicer()
    servicer.set_pythia(pythia_service.PythiaServicer(servicer))
    return servicer


@pytest.fixture
def tier():
    servicers = {f"replica-{i}": make_servicer() for i in range(3)}
    stub = router_stub.RoutedVizierStub(servicers)
    return servicers, stub


def create_study(stub, study_id: str) -> str:
    name = f"owners/o/studies/{study_id}"
    stub.CreateStudy(
        vizier_service_pb2.CreateStudyRequest(
            parent="owners/o", study=pc.study_to_proto(study_config(), name)
        )
    )
    return name


class TestDropIn:
    def test_vizier_client_runs_unchanged_over_the_router(self, tier):
        _, stub = tier
        name = create_study(stub, "dropin")
        client = vizier_client.VizierClient(stub, name, "w0")
        for i in range(5):
            (trial,) = client.get_suggestions(1)
            client.complete_trial(
                trial.id, vz.Measurement(metrics={"obj": float(i)})
            )
        trials = client.list_trials()
        assert len(trials) == 5
        assert all(t.status == vz.TrialStatus.COMPLETED for t in trials)
        assert len(client.list_optimal_trials()) == 1
        assert client.get_study_config().search_space.parameters[0].name == "x"

    def test_study_affinity_all_state_on_one_replica(self, tier):
        servicers, stub = tier
        names = [create_study(stub, f"aff{i}") for i in range(6)]
        client_trials = {}
        for name in names:
            client = vizier_client.VizierClient(stub, name, "w")
            (trial,) = client.get_suggestions(1)
            client_trials[name] = trial.id
        for name in names:
            owner_id = stub.router.replica_for(name)
            owner = servicers[owner_id]
            # The owning replica has the study AND its trials; nobody else
            # has either.
            assert owner.datastore.load_study(name).name == name
            assert owner.datastore.max_trial_id(name) == 1
            for rid, servicer in servicers.items():
                if rid != owner_id:
                    with pytest.raises(KeyError):
                        servicer.datastore.load_study(name)

    def test_list_studies_merges_across_replicas(self, tier):
        servicers, stub = tier
        names = {create_study(stub, f"merge{i}") for i in range(8)}
        response = stub.ListStudies(
            vizier_service_pb2.ListStudiesRequest(parent="owners/o")
        )
        assert {s.name for s in response.studies} == names
        # The workload really is spread (not all on one replica).
        owners = {stub.router.replica_for(n) for n in names}
        assert len(owners) > 1

    def test_operation_polling_routes_to_the_owner(self, tier):
        _, stub = tier
        name = create_study(stub, "ops")
        op = stub.SuggestTrials(
            vizier_service_pb2.SuggestTrialsRequest(
                parent=name, suggestion_count=1, client_id="w"
            )
        )
        polled = stub.GetOperation(
            vizier_service_pb2.GetOperationRequest(name=op.name)
        )
        assert polled.name == op.name and polled.done

    def test_list_studies_refuses_silent_partial_results(self, tier):
        """Review regression: a down replica with unaccounted studies must
        fail the fan-out loudly, not shrink the listing."""
        servicers, stub = tier
        names = {create_study(stub, f"part{i}") for i in range(8)}
        stub.router.mark_down("replica-1")
        with pytest.raises(ConnectionError, match="partial"):
            stub.ListStudies(
                vizier_service_pb2.ListStudiesRequest(parent="owners/o")
            )
        # Once something declares the studies failed over to successors,
        # the live fan-out counts as complete again.
        stub.note_failed_over("replica-1")
        response = stub.ListStudies(
            vizier_service_pb2.ListStudiesRequest(parent="owners/o")
        )
        expected = names - {
            s.name
            for s in servicers["replica-1"].datastore.list_studies("owners/o")
        }
        assert {s.name for s in response.studies} == expected
        # A restarted replica owns its studies again: the declaration is
        # dropped with the old endpoint.
        stub.set_endpoint("replica-1", servicers["replica-1"])
        stub.router.mark_up("replica-1")
        response = stub.ListStudies(
            vizier_service_pb2.ListStudiesRequest(parent="owners/o")
        )
        assert {s.name for s in response.studies} == names

    def test_routing_disabled_uses_first_replica_only(self):
        servicers = {f"replica-{i}": make_servicer() for i in range(3)}
        stub = router_stub.RoutedVizierStub(servicers, routing_enabled=False)
        for i in range(5):
            create_study(stub, f"pin{i}")
        assert len(servicers["replica-0"].datastore.list_studies("owners/o")) == 5
        assert not servicers["replica-1"].datastore.list_studies("owners/o")


class _DeadEndpoint:
    """Transport-dead replica: every RPC raises ConnectionError."""

    def __getattr__(self, name):
        def call(request):
            raise ConnectionError("connection refused")

        return call


class TestFailureHandling:
    def test_self_managed_mark_down_after_threshold(self):
        live = make_servicer()
        router = routing.StudyRouter(["replica-0", "replica-1"])
        # Find a study owned by replica-1, then kill replica-1.
        name = None
        for i in range(50):
            candidate = f"owners/o/studies/f{i}"
            if router.replica_for(candidate) == "replica-1":
                name = candidate
                break
        assert name is not None
        stub = router_stub.RoutedVizierStub(
            {"replica-0": live, "replica-1": _DeadEndpoint()},
            router=router,
            failure_threshold=2,
        )
        request = vizier_service_pb2.CreateStudyRequest(
            parent="owners/o", study=pc.study_to_proto(study_config(), name)
        )
        for _ in range(2):
            with pytest.raises(ConnectionError):
                stub.CreateStudy(request)
        # Threshold reached: replica-1 is down, the retry lands on 0.
        assert not stub.router.is_up("replica-1")
        stub.CreateStudy(request)
        assert live.datastore.load_study(name).name == name

    def test_failure_hook_receives_the_error(self):
        seen = []
        stub = router_stub.RoutedVizierStub(
            {"replica-0": _DeadEndpoint()},
            on_failure=lambda rid, e: seen.append((rid, type(e).__name__)),
        )
        with pytest.raises(ConnectionError):
            create_study(stub, "hooked")
        assert seen == [("replica-0", "ConnectionError")]
        # With a hook installed the stub does NOT mark down on its own.
        assert stub.router.is_up("replica-0")

    def test_success_resets_consecutive_failures(self):
        flaky_state = {"fail": True}
        inner = make_servicer()

        class Flaky:
            def __getattr__(self, name):
                method = getattr(inner, name)

                def call(request):
                    if flaky_state["fail"]:
                        flaky_state["fail"] = False
                        raise ConnectionError("blip")
                    return method(request)

                return call

        stub = router_stub.RoutedVizierStub(
            {"replica-0": Flaky()}, failure_threshold=2
        )
        with pytest.raises(ConnectionError):
            create_study(stub, "flaky")
        create_study(stub, "flaky")  # succeeds, resets the counter
        flaky_state["fail"] = True
        with pytest.raises(ConnectionError):
            create_study(stub, "flaky2")
        # One failure after a success: still below threshold 2.
        assert stub.router.is_up("replica-0")

    def test_stats_and_metrics(self, tier):
        _, stub = tier
        name = create_study(stub, "metrics")
        owner = stub.router.replica_for(name)
        stats = stub.stats()
        assert stats["replicas"][owner]["requests"] >= 1
        assert stats["replicas"][owner]["state"] == "up"

    def test_value_errors_do_not_implicate_the_replica(self, tier):
        _, stub = tier
        with pytest.raises(ValueError):
            stub.GetStudy(vizier_service_pb2.GetStudyRequest(name="garbage"))
        assert all(state == "up" for state in stub.router.snapshot().values())
