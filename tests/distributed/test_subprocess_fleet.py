"""SubprocessReplicaManager: real replica_main processes, end to end.

These tests spawn actual OS processes serving gRPC — the whole point of
the cross-process plane — so they are the slowest in this directory
(~10-20 s of fleet spin-up each). The kill/failover lifecycle rides in
one compact tier-1 test; the partition/lease matrix and the graceful-
shutdown contract get their own.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

grpc = pytest.importorskip("grpc")

from vizier_tpu import pyvizier as vz
from vizier_tpu.distributed import subprocess_fleet
from vizier_tpu.reliability import ReliabilityConfig
from vizier_tpu.service import grpc_stubs
from vizier_tpu.service import proto_converters as pc
from vizier_tpu.service import vizier_client
from vizier_tpu.service.protos import (
    replication_service_pb2 as rpb,
    study_pb2,
    vizier_service_pb2,
)
from vizier_tpu.testing import netchaos as netchaos_lib

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _study_config() -> vz.StudyConfig:
    config = vz.StudyConfig(algorithm="RANDOM_SEARCH")
    config.search_space.root.add_float_param("x", 0.0, 1.0)
    config.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return config


def _reliability() -> ReliabilityConfig:
    # Must ride out a full lease expiry + wire failover before the
    # attempt budget runs dry.
    return ReliabilityConfig(
        retry_max_attempts=16,
        retry_base_delay_secs=0.1,
        retry_max_delay_secs=0.5,
    )


def _fleet(tmp_path, n=3, **kwargs):
    kwargs.setdefault("lease_timeout_s", 1.0)
    kwargs.setdefault("heartbeat_interval_s", 0.1)
    return subprocess_fleet.SubprocessReplicaManager(
        n, wal_root=str(tmp_path / "fleet"), **kwargs
    )


def _drive(client, start, stop):
    for i in range(start, stop):
        (trial,) = client.get_suggestions(1)
        client.complete_trial(
            trial.id, vz.Measurement(metrics={"obj": 0.01 * i})
        )


class TestKillFailoverRevive:
    def test_sigkill_owner_fails_over_from_standby_and_revives(self, tmp_path):
        fleet = _fleet(tmp_path)
        try:
            study = "owners/sub/studies/kfr"
            fleet.stub.CreateStudy(
                vizier_service_pb2.CreateStudyRequest(
                    parent="owners/sub",
                    study=pc.study_to_proto(_study_config(), study),
                )
            )
            client = vizier_client.VizierClient(
                fleet.stub, study, "w", reliability=_reliability()
            )
            owner = fleet.owner_of(study)
            _drive(client, 0, 6)
            fleet.kill_replica(owner)  # SIGKILL; detection + failover are
            _drive(client, 6, 12)  # absorbed by the client's retries
            assert fleet.owner_of(study) != owner
            stats = fleet.serving_stats()
            assert stats["failovers"] >= 1
            assert stats["recovery_sources"].get("standby", 0) >= 1
            assert not fleet.is_alive(owner)
            # Every driven trial is accounted through the failed-over
            # tier (the records crossed the wire via standby logs).
            assert len(client.list_trials()) == 12
            # Revive: fenced restart on the old port + copy-back; the
            # study routes home and the fleet serves on.
            fleet.revive_replica(owner)
            assert fleet.is_alive(owner)
            assert fleet.owner_of(study) == owner
            _drive(client, 12, 14)
            assert len(client.list_trials()) == 14
        finally:
            fleet.shutdown()


@pytest.mark.slow
class TestPartitionMatrix:
    def test_partition_lease_expiry_fencing_and_slow_replica(self, tmp_path):
        net = netchaos_lib.NetChaos(seed=5)
        fleet = _fleet(tmp_path, netchaos=net)
        try:
            study = "owners/sub/studies/pmx"
            fleet.stub.CreateStudy(
                vizier_service_pb2.CreateStudyRequest(
                    parent="owners/sub",
                    study=pc.study_to_proto(_study_config(), study),
                )
            )
            client = vizier_client.VizierClient(
                fleet.stub, study, "w", reliability=_reliability()
            )
            owner = fleet.owner_of(study)
            _drive(client, 0, 4)

            # SLOW-BUT-ALIVE: heartbeat renewals under injected delay
            # (well under the 1.0 s lease) must never trigger failover.
            net.set_link("manager", owner, delay_prob=1.0, delay_secs=0.3)
            time.sleep(1.5)
            fleet.check_health()
            assert fleet.is_alive(owner)
            assert fleet.serving_stats()["failovers"] == 0
            net.clear_link("manager", owner)

            # PARTITION: total silence expires the lease; the manager
            # fences the zombie's generation and fails over — while the
            # zombie process keeps running.
            fleet._control.call_once(
                owner, "FlushStream", rpb.FlushStreamRequest(timeout_secs=5.0)
            )
            fleet.partition_replica(owner)
            _drive(client, 4, 8)  # retries ride lease expiry + failover
            assert fleet.owner_of(study) != owner
            with fleet._lock:
                zombie_running = fleet._replicas[owner].running()
            assert zombie_running

            # HEAL + stale append at the zombie: rejected by the fenced
            # standby stores (observable via heartbeat) and invisible to
            # the routed tier — no split-brain write wins.
            fleet.heal_partition(owner)
            zombie_stub = grpc_stubs.create_vizier_stub(
                fleet.endpoint_of(owner)
            )
            zombie_stub.CreateTrial(
                vizier_service_pb2.CreateTrialRequest(
                    parent=study,
                    trial=study_pb2.Trial(name=f"{study}/trials/888"),
                )
            )
            deadline = time.monotonic() + 10.0
            fenced = 0
            while time.monotonic() < deadline and not fenced:
                fleet.check_health()
                fenced = fleet.serving_stats()["replication"][
                    "fenced_rejections"
                ]
                time.sleep(0.2)
            assert fenced >= 1
            ids = sorted(t.id for t in client.list_trials())
            assert 888 not in ids and len(ids) == 8
        finally:
            fleet.shutdown()


class TestGracefulShutdown:
    def test_sigterm_drains_flushes_and_dumps(self, tmp_path):
        """The PR 15 shutdown contract: SIGTERM → drain → flush standby →
        compact WAL → observability dump, all before exit."""

        def pick():
            s = socket.socket()
            s.bind(("localhost", 0))
            port = s.getsockname()[1]
            s.close()
            return port

        ports = [pick(), pick()]
        peers = ",".join(
            f"replica-{i}=localhost:{ports[i]}" for i in range(2)
        )
        dump_dir = str(tmp_path / "obs")
        wal_dirs = [str(tmp_path / f"replica-{i}") for i in range(2)]
        procs = []
        for i in range(2):
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable,
                        "-m",
                        "vizier_tpu.distributed.replica_main",
                        "--replica-id",
                        f"replica-{i}",
                        "--port",
                        str(ports[i]),
                        "--wal-dir",
                        wal_dirs[i],
                        "--peers",
                        peers,
                        "--obs-dump-dir",
                        dump_dir,
                    ],
                    stdout=subprocess.PIPE,
                    stderr=subprocess.DEVNULL,
                    text=True,
                    cwd=_REPO_ROOT,
                    env={**os.environ, "JAX_PLATFORMS": "cpu"},
                )
            )
        try:
            endpoints = []
            for proc in procs:
                line = proc.stdout.readline().strip()
                assert line.startswith("READY "), line
                endpoints.append(line.split(" ", 1)[1])
            # One mutation on replica-0 so there is WAL + standby state
            # for the shutdown to make durable.
            study = study_pb2.Study(name="owners/sub/studies/gs")
            study.study_spec.algorithm = "RANDOM_SEARCH"
            vstub = grpc_stubs.create_vizier_stub(endpoints[0])
            vstub.CreateStudy(
                vizier_service_pb2.CreateStudyRequest(
                    parent="owners/sub", study=study
                )
            )
            rstub = grpc_stubs.create_replication_stub(endpoints[0])
            rstub.FlushStream(rpb.FlushStreamRequest(timeout_secs=10.0))

            for proc in procs:
                proc.send_signal(signal.SIGTERM)
            for proc in procs:
                assert proc.wait(timeout=30) == 0

            # WAL compacted on the way out: the snapshot holds the study.
            assert os.path.exists(os.path.join(wal_dirs[0], "snapshot.bin"))
            # The successor's standby log for replica-0 survived its own
            # graceful close.
            standby = os.path.join(
                wal_dirs[1], "standby", "replica-0", "standby.log"
            )
            assert os.path.exists(standby) and os.path.getsize(standby) > 0
            # Observability dumped per replica, after the stores closed.
            for i in range(2):
                metrics_path = os.path.join(
                    dump_dir, f"replica-{i}-metrics.json"
                )
                assert os.path.exists(metrics_path)
                json.load(open(metrics_path))
        finally:
            for proc in procs:
                if proc.poll() is None:
                    proc.kill()
            for endpoint in endpoints:
                grpc_stubs.close_channel(endpoint)
