"""ReplicaManager emits structured flight-recorder events (kill/failover/
revive with successors) and dumps per-replica observability files."""

import pytest

from vizier_tpu.distributed import ReplicaManager
from vizier_tpu.observability import fleet as fleet_lib
from vizier_tpu.observability import flight_recorder as recorder_lib
from vizier_tpu.observability import tracing as tracing_lib
from vizier_tpu.service import proto_converters as pc
from vizier_tpu.service.protos import vizier_service_pb2
from vizier_tpu import pyvizier as vz


def _study_config():
    config = vz.StudyConfig(algorithm="RANDOM_SEARCH")
    config.search_space.root.add_float_param("x", 0.0, 1.0)
    config.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return config


@pytest.fixture
def recorder():
    rec = recorder_lib.FlightRecorder()
    previous = recorder_lib.set_recorder(rec)
    yield rec
    recorder_lib.set_recorder(previous)


@pytest.fixture
def manager(tmp_path):
    mgr = ReplicaManager(3, wal_root=str(tmp_path / "wal"))
    yield mgr
    mgr.shutdown()


class TestFailoverEvents:
    def test_kill_failover_revive_timeline(self, recorder, manager):
        study = "owners/o/studies/recorder-events"
        manager.stub.CreateStudy(
            vizier_service_pb2.CreateStudyRequest(
                parent="owners/o",
                study=pc.study_to_proto(_study_config(), study),
            )
        )
        owner = manager.router.replica_for(study)
        manager.kill_replica(owner)
        manager.check_health()
        manager.revive_replica(owner)

        # The replication streamers interleave background resync events;
        # the topology timeline itself must stay exact.
        events = [
            e
            for e in recorder.ring(recorder_lib.FLEET)
            if e["kind"] != "replication_resync"
        ]
        kinds = [e["kind"] for e in events]
        assert kinds == ["replica_killed", "replica_failover", "replica_revive"]
        killed, failover, revive = events
        assert killed["attributes"]["replica"] == owner
        # The failover event reconstructs the handoff after the fact:
        # timestamp, dead replica, its successors, and the study count.
        assert failover["attributes"]["replica"] == owner
        assert failover["attributes"]["restored_studies"] == 1
        successors = failover["attributes"]["successors"]
        assert successors and owner not in successors
        assert set(successors) <= set(manager.replica_ids())
        assert failover["time"] >= killed["time"]
        assert revive["attributes"]["was_failed_over"] is True

    def test_ram_only_failover_has_no_successors(self, recorder, tmp_path):
        mgr = ReplicaManager(2, wal_root="")
        try:
            owner = mgr.replica_ids()[0]
            mgr.kill_replica(owner)
            mgr.check_health()
            (event,) = recorder.ring(recorder_lib.FLEET)[1:2]
            assert event["kind"] == "replica_failover"
            assert event["attributes"]["successors"] == []
            assert event["attributes"]["restored_studies"] == 0
        finally:
            mgr.shutdown()


class TestDumpObservability:
    def test_per_replica_span_split_and_fleet_files(
        self, recorder, manager, tmp_path
    ):
        tracer = tracing_lib.Tracer()
        previous = tracing_lib.set_tracer(tracer)
        try:
            for i in range(2):
                study = f"owners/o/studies/dump-{i}"
                manager.stub.CreateStudy(
                    vizier_service_pb2.CreateStudyRequest(
                        parent="owners/o",
                        study=pc.study_to_proto(_study_config(), study),
                    )
                )
                with tracer.span("client.suggest", study=study):
                    manager.stub.SuggestTrials(
                        vizier_service_pb2.SuggestTrialsRequest(
                            parent=study,
                            suggestion_count=1,
                            client_id="w",
                        )
                    )
            out = tmp_path / "dump"
            written = manager.dump_observability(str(out))
        finally:
            tracing_lib.set_tracer(previous)
        loaded = fleet_lib.load_fleet_dir(str(out))
        # Client spans split from replica-attributed service spans.
        assert "client" in loaded["spans"]
        replica_sources = [s for s in loaded["spans"] if s.startswith("replica-")]
        assert replica_sources, "no replica-attributed spans dumped"
        for source in replica_sources:
            for span in loaded["spans"][source]:
                assert span["attributes"]["replica"] == source
        assert "fleet" in loaded["metrics"]
        # A merged trace crosses the client and replica dump files.
        merged = fleet_lib.merge_spans(loaded["spans"])
        assert fleet_lib.cross_replica_traces(merged)
        assert written["spans"]
