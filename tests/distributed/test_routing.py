"""StudyRouter: rendezvous placement, liveness, minimal-disruption."""

import pytest

from vizier_tpu.distributed import routing

KEYS = [f"owners/o/studies/s{i}" for i in range(200)]


def make_router(n=4, **kwargs):
    return routing.StudyRouter([f"replica-{i}" for i in range(n)], **kwargs)


class TestPlacement:
    def test_deterministic_across_instances(self):
        a, b = make_router(), make_router()
        assert [a.replica_for(k) for k in KEYS] == [
            b.replica_for(k) for k in KEYS
        ]

    def test_every_replica_gets_a_share(self):
        router = make_router()
        assignments = router.assignments(KEYS)
        for rid, studies in assignments.items():
            # 200 keys over 4 replicas: a replica with none (or nearly
            # all) means the hash is degenerate, not just unlucky.
            assert 10 <= len(studies) <= 120, (rid, len(studies))

    def test_ranking_is_a_permutation(self):
        router = make_router()
        ranking = router.ranking(KEYS[0])
        assert sorted(ranking) == sorted(router.replica_ids)

    def test_routing_disabled_pins_first_replica(self):
        router = make_router(routing=False)
        assert {router.replica_for(k) for k in KEYS} == {"replica-0"}

    def test_duplicate_or_empty_ids_rejected(self):
        with pytest.raises(ValueError):
            routing.StudyRouter([])
        with pytest.raises(ValueError):
            routing.StudyRouter(["a", "a"])


class TestLiveness:
    def test_only_downed_replicas_studies_move(self):
        router = make_router()
        before = {k: router.replica_for(k) for k in KEYS}
        router.mark_down("replica-2")
        after = {k: router.replica_for(k) for k in KEYS}
        moved = {k for k in KEYS if before[k] != after[k]}
        assert moved == {k for k in KEYS if before[k] == "replica-2"}
        assert all(after[k] != "replica-2" for k in KEYS)

    def test_moved_studies_go_to_second_choice(self):
        router = make_router()
        key = next(k for k in KEYS if router.replica_for(k) == "replica-1")
        ranking = router.ranking(key)
        router.mark_down("replica-1")
        assert router.replica_for(key) == ranking[1]

    def test_mark_up_restores_original_placement(self):
        router = make_router()
        before = {k: router.replica_for(k) for k in KEYS}
        router.mark_down("replica-0")
        assert router.mark_up("replica-0")
        assert {k: router.replica_for(k) for k in KEYS} == before

    def test_mark_transitions_report_change(self):
        router = make_router()
        assert router.mark_down("replica-3")
        assert not router.mark_down("replica-3")  # already down
        assert router.mark_up("replica-3")
        assert not router.mark_up("replica-3")  # already up

    def test_all_down_raises_transient(self):
        router = make_router(2)
        router.mark_down("replica-0")
        router.mark_down("replica-1")
        with pytest.raises(routing.NoLiveReplicaError):
            router.replica_for(KEYS[0])
        # NoLiveReplicaError must classify as transient (retries can heal).
        from vizier_tpu.reliability import errors as errors_lib

        assert errors_lib.is_transient_exception(
            routing.NoLiveReplicaError("x")
        )

    def test_unknown_replica_rejected(self):
        router = make_router()
        with pytest.raises(KeyError):
            router.mark_down("replica-99")

    def test_route_cache_tracks_liveness_epoch(self):
        router = make_router()
        key = KEYS[0]
        first = router.replica_for(key)
        assert router.last_route(key) == first
        router.mark_down(first)
        second = router.replica_for(key)
        assert second != first
        assert router.last_route(key) == second
        router.mark_up(first)
        assert router.replica_for(key) == first

    def test_snapshot(self):
        router = make_router(2)
        router.mark_down("replica-1")
        assert router.snapshot() == {"replica-0": "up", "replica-1": "down"}


class TestRouteCacheLRU:
    """Million-study churn must not grow the placement cache unboundedly."""

    def test_cache_bounded_at_cap(self):
        router = make_router(route_cache_size=16)
        for k in KEYS:  # 200 distinct studies through a 16-entry cache
            router.replica_for(k)
        assert len(router._route_cache) == 16

    def test_lru_recency_keeps_hot_studies(self):
        router = make_router(route_cache_size=4)
        for k in KEYS[:4]:
            router.replica_for(k)
        router.replica_for(KEYS[0])  # touch: KEYS[0] becomes most-recent
        router.replica_for(KEYS[4])  # evicts the LRU entry (KEYS[1])
        assert KEYS[0] in router._route_cache
        assert KEYS[1] not in router._route_cache

    def test_evicted_study_reroutes_identically(self):
        # Eviction costs a re-rank, never a different placement.
        router = make_router(route_cache_size=2)
        want = {k: router.replica_for(k) for k in KEYS[:50]}
        for k in KEYS[:50]:
            assert router.replica_for(k) == want[k]

    def test_env_override_and_validation(self, monkeypatch):
        monkeypatch.setenv("VIZIER_DISTRIBUTED_ROUTE_CACHE_SIZE", "8")
        router = make_router()
        assert router._route_cache_size == 8
        with pytest.raises(ValueError):
            make_router(route_cache_size=0)

    def test_default_cap_is_large(self):
        assert make_router()._route_cache_size == 65536
