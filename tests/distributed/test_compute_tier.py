"""Disaggregated compute tier: RemotePythiaStub degradation mechanics,
shared-servicer config-hash invalidation (the two-frontend delete/recreate
race), and the end-to-end fleet (N frontends + 1 real compute server).
"""

import os
import threading
import time

import pytest

grpc = pytest.importorskip("grpc")

from vizier_tpu import pyvizier as vz
from vizier_tpu.distributed import compute_tier, subprocess_fleet
from vizier_tpu.observability import flight_recorder as flight_recorder_lib
from vizier_tpu.pythia import policy as policy_lib
from vizier_tpu.reliability import ReliabilityConfig
from vizier_tpu.reliability import retry as retry_lib
from vizier_tpu.serving.designer_cache import DesignerStateCache
from vizier_tpu.service import proto_converters as pc
from vizier_tpu.service import pythia_service, vizier_client, vizier_service
from vizier_tpu.service.protos import (
    pythia_service_pb2,
    vizier_service_pb2,
)

STUDY = "owners/tier/studies/s"


def _study_config(param="x", algorithm="RANDOM_SEARCH") -> vz.StudyConfig:
    config = vz.StudyConfig(algorithm=algorithm)
    config.search_space.root.add_float_param(param, 0.0, 1.0)
    config.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return config


def _suggest_request(config, name=STUDY, count=1):
    request = pythia_service_pb2.PythiaSuggestRequest(
        count=count, study_name=name
    )
    request.study_descriptor.config.CopyFrom(pc.study_to_proto(config, name).study_spec)
    request.study_descriptor.guid = name
    return request


# -- RemotePythiaStub unit mechanics (injected remotes, fake clock) --------


class _FakeRemote:
    """Scripted remote PythiaService stub."""

    def __init__(self, failures=0, error_factory=ConnectionError):
        self.failures = failures
        self.error_factory = error_factory
        self.calls = 0

    def Suggest(self, request):
        self.calls += 1
        if self.calls <= self.failures:
            raise self.error_factory("tier down")
        response = pythia_service_pb2.PythiaSuggestResponse()
        trial = response.suggestions.add()
        p = trial.parameters.add()
        p.name, p.value.double_value = "remote", 1.0
        return response

    EarlyStop = Suggest
    Ping = Suggest


class _FakeLocal:
    def __init__(self):
        self.calls = 0

    def Suggest(self, request):
        self.calls += 1
        response = pythia_service_pb2.PythiaSuggestResponse()
        trial = response.suggestions.add()
        p = trial.parameters.add()
        p.name, p.value.double_value = "local", 1.0
        return response

    EarlyStop = Suggest
    Ping = Suggest


def _stub(remote, local=None, clock=None, fallback="local", interval=5.0):
    config = compute_tier.ComputeTierConfig(
        enabled=True,
        endpoint="localhost:1",
        fallback=fallback,
        health_interval_s=interval,
    )
    factories = {"count": 0}

    def factory():
        factories["count"] += 1
        return remote

    stub = compute_tier.RemotePythiaStub(
        "localhost:1",
        local=local,
        replica_id="r0",
        config=config,
        # No in-hop retry: each scripted failure is one observed failure.
        retry_policy=retry_lib.RetryPolicy(max_attempts=1),
        stub_factory=factory,
        time_fn=(clock or time.monotonic),
    )
    return stub, factories


class TestRemotePythiaStub:
    def test_remote_path_serves_and_counts(self):
        stub, factories = _stub(_FakeRemote(), local=_FakeLocal())
        response = stub.Suggest(_suggest_request(_study_config()))
        assert response.suggestions[0].parameters[0].name == "remote"
        assert factories["count"] == 1
        stats = stub.stats()
        assert stats["remote_calls"] == 1
        assert stats["fallback_serves"] == 0
        assert not stats["cooling_down"]

    def test_unreachable_tier_falls_back_then_cools_down(self):
        clock = [100.0]
        local = _FakeLocal()
        remote = _FakeRemote(failures=1)
        stub, factories = _stub(
            remote, local=local, clock=lambda: clock[0], interval=5.0
        )

        # First call: remote raises ConnectionError -> local fallback.
        response = stub.Suggest(_suggest_request(_study_config()))
        assert response.suggestions[0].parameters[0].name == "local"
        stats = stub.stats()
        assert stats["remote_failures"] == 1
        assert stats["fallback_serves"] == 1
        assert stats["cooling_down"]

        # Inside the cooldown the remote is never touched again.
        stub.Suggest(_suggest_request(_study_config()))
        assert remote.calls == 1
        assert stub.stats()["fallback_serves"] == 2

        # Past the cooldown the stub re-probes (a fresh stub build) and
        # the recovered tier serves remotely again.
        clock[0] += 5.1
        response = stub.Suggest(_suggest_request(_study_config()))
        assert response.suggestions[0].parameters[0].name == "remote"
        assert factories["count"] == 2  # reconnect after eviction
        assert stub.stats()["remote_calls"] == 1

    def test_fallback_fail_mode_surfaces_the_error(self):
        stub, _ = _stub(_FakeRemote(failures=10), fallback="fail")
        with pytest.raises(ConnectionError):
            stub.Suggest(_suggest_request(_study_config()))

    def test_semantic_errors_propagate_without_fallback(self):
        local = _FakeLocal()
        remote = _FakeRemote(failures=10, error_factory=ValueError)
        stub, _ = _stub(remote, local=local)
        with pytest.raises(ValueError):
            stub.Suggest(_suggest_request(_study_config()))
        assert local.calls == 0
        assert not stub.stats()["cooling_down"]

    def test_closed_channel_race_takes_the_fallback(self):
        # A concurrent request's failure path can evict the shared channel
        # (close_channel in _note_tier_down) while this call is in flight;
        # grpcio raises ValueError("Cannot invoke RPC on closed channel!").
        # That is a tier-down signal, NOT a semantic error: the call must
        # fall back locally instead of surfacing the ValueError.
        local = _FakeLocal()
        remote = _FakeRemote(
            failures=10,
            error_factory=lambda msg: ValueError(
                "Cannot invoke RPC on closed channel!"
            ),
        )
        stub, _ = _stub(remote, local=local)
        response = stub.Suggest(_suggest_request(_study_config()))
        assert response.suggestions[0].parameters[0].name == "local"
        assert local.calls == 1
        assert stub.stats()["cooling_down"]

    def test_trace_context_is_restamped_across_the_hop(self):
        seen = {}

        class _Capture(_FakeRemote):
            def Suggest(self, request):
                seen["trace_context"] = request.trace_context
                return super().Suggest(request)

        stub, _ = _stub(_Capture())
        request = _suggest_request(_study_config())
        stub.Suggest(request)
        assert seen["trace_context"]  # the hop span rides the wire

    def test_maybe_wrap_off_switch_returns_local_unchanged(self, monkeypatch):
        monkeypatch.delenv("VIZIER_COMPUTE_TIER", raising=False)
        monkeypatch.delenv("VIZIER_COMPUTE_TIER_ENDPOINT", raising=False)
        local = _FakeLocal()
        assert compute_tier.maybe_wrap_pythia(local) is local

    def test_maybe_wrap_endpoint_flag_arms_the_tier(self, monkeypatch):
        monkeypatch.delenv("VIZIER_COMPUTE_TIER", raising=False)
        local = _FakeLocal()
        wrapped = compute_tier.maybe_wrap_pythia(
            local, replica_id="r1", endpoint="localhost:2"
        )
        assert isinstance(wrapped, compute_tier.RemotePythiaStub)
        assert wrapped.stats()["endpoint"] == "localhost:2"

    def test_bad_fallback_mode_rejected(self):
        with pytest.raises(ValueError):
            compute_tier.ComputeTierConfig(fallback="retry")


# -- config-hash turnover: the shared-tier delete/recreate race ------------


class TestDesignerCacheConfigHash:
    def test_turnover_drops_the_stale_entry(self):
        cache = DesignerStateCache()
        assert not cache.note_config_hash("s1", "aaaa")
        cache.get_or_create("s1", object)
        assert not cache.note_config_hash("s1", "aaaa")  # same incarnation
        assert "s1" in cache
        assert cache.note_config_hash("s1", "bbbb")  # delete/recreate
        assert "s1" not in cache
        assert cache.stats.get("cache_invalidations_config") == 1

    def test_hash_memory_is_bounded(self):
        cache = DesignerStateCache(max_entries=1)
        for i in range(cache._max_hashes + 10):
            cache.note_config_hash(f"s{i}", "h")
        assert len(cache._config_hashes) == cache._max_hashes


class _BakedPolicy:
    """Bakes the problem it was CONSTRUCTED from into every suggestion —
    the shape of a designer-backed policy (the designer's converters are
    pinned to the construction-time search space), so serving a cached
    instance across a config turnover is observable in the output."""

    should_be_cached = True

    def __init__(self, problem):
        self._names = [p.name for p in problem.search_space.parameters]

    def suggest(self, request):
        del request
        return policy_lib.SuggestDecision(
            suggestions=[
                vz.TrialSuggestion(
                    parameters={name: 0.5 for name in self._names}
                )
            ]
        )


class TestSharedServicerInvalidationRace:
    """One shared PythiaServicer, two frontends racing CreateStudy/
    DeleteStudy for the same resource name. Frontend B's delete/recreate
    never reaches this process (there is no invalidation RPC on the
    Pythia surface) — the request's config hash is the only staleness
    signal, and it must be enough."""

    def _service(self):
        servicer = vizier_service.VizierServicer()
        pythia = pythia_service.PythiaServicer(
            servicer,
            policy_factory=lambda problem, algorithm, supporter, name: (
                _BakedPolicy(problem)
            ),
        )
        servicer.set_pythia(pythia)
        return servicer, pythia

    def test_recreated_study_is_served_fresh_not_stale(self):
        _servicer, pythia = self._service()
        config_a = _study_config(param="a0")
        config_b = _study_config(param="b0")

        # Frontend A's traffic warms every per-study cache for config A.
        response = pythia.Suggest(_suggest_request(config_a))
        assert not response.error
        assert response.suggestions[0].parameters[0].name == "a0"
        assert STUDY in pythia._config_cache

        # Frontend B deleted + recreated the study (same name, different
        # search space) and its traffic arrives with the NEW descriptor.
        response = pythia.Suggest(_suggest_request(config_b))
        assert not response.error
        names = [p.name for p in response.suggestions[0].parameters]
        assert names == ["b0"]  # the stale cached policy would say a0

        # The stale incarnation's state is gone, not shadowed: the parse
        # cache holds B, and no policy-cache key references A's hash.
        hash_b = pythia._config_cache[STUDY][0]
        assert all(
            key[2] == hash_b
            for key in pythia._policy_cache
            if key[0] == STUDY
        )

    def test_same_config_does_not_churn_caches(self):
        _servicer, pythia = self._service()
        config = _study_config(param="a0")
        pythia.Suggest(_suggest_request(config))
        cached = pythia._config_cache[STUDY]
        pythia.Suggest(_suggest_request(config))
        assert pythia._config_cache[STUDY] is cached  # hash hit, no reparse
        stats = pythia.serving_runtime.designer_cache.stats
        assert stats.get("cache_invalidations_config") == 0

    def test_concurrent_turnover_never_serves_a_stale_policy(self):
        """Two frontends suggest concurrently, one with each incarnation:
        every response must match ITS request's config — never the other
        incarnation's — regardless of interleaving. (Policies key by the
        REQUEST's own hash, not a parse-cache read-back a racing thread
        may have overwritten.)"""
        _servicer, pythia = self._service()
        configs = {"a0": _study_config("a0"), "b0": _study_config("b0")}
        errors = []
        barrier = threading.Barrier(2)

        def drive(param):
            barrier.wait()
            for _ in range(16):
                response = pythia.Suggest(_suggest_request(configs[param]))
                if response.error:
                    errors.append(response.error)
                    continue
                names = [
                    p.name for p in response.suggestions[0].parameters
                ]
                if names != [param]:
                    errors.append(f"asked {param}, served {names}")

        threads = [
            threading.Thread(target=drive, args=(param,))
            for param in ("a0", "b0")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors

    def test_runtime_note_study_config_invalidates_serving_state(self):
        _servicer, pythia = self._service()
        runtime = pythia.serving_runtime
        runtime.designer_cache.get_or_create(STUDY, object)
        runtime.flight_recorder = flight_recorder_lib.FlightRecorder()
        runtime.flight_recorder.record(STUDY, "loadgen_outcome")
        assert not runtime.note_study_config(STUDY, "h1")
        assert STUDY in runtime.designer_cache
        assert runtime.note_study_config(STUDY, "h2")
        assert STUDY not in runtime.designer_cache
        # The recorder ring is forensic history, not derived state: a
        # metadata update (hash turnover) must not erase earlier events.
        assert runtime.flight_recorder.ring(STUDY)


# -- the real thing: frontends + one compute-server process ----------------


_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _reliability() -> ReliabilityConfig:
    return ReliabilityConfig(
        retry_max_attempts=16,
        retry_base_delay_secs=0.1,
        retry_max_delay_secs=0.5,
    )


class TestSharedComputeFleet:
    def test_kill_fallback_autorevive_loses_nothing(self, tmp_path):
        fleet = subprocess_fleet.SubprocessReplicaManager(
            2,
            wal_root=str(tmp_path / "fleet"),
            lease_timeout_s=1.0,
            heartbeat_interval_s=0.1,
            compute_tier=True,
        )
        try:
            assert fleet.has_compute_tier()
            assert fleet.compute_is_alive()
            study = "owners/tier/studies/e2e"
            fleet.stub.CreateStudy(
                vizier_service_pb2.CreateStudyRequest(
                    parent="owners/tier",
                    study=pc.study_to_proto(_study_config(), study),
                )
            )
            client = vizier_client.VizierClient(
                fleet.stub, study, "w", reliability=_reliability()
            )
            for i in range(4):
                (trial,) = client.get_suggestions(1)
                client.complete_trial(
                    trial.id, vz.Measurement(metrics={"obj": 0.01 * i})
                )
            stats = fleet.serving_stats()
            assert stats["compute_tier"]["alive"]

            # Crash the shared tier mid-run: suggests keep completing via
            # each frontend's local fallback — zero lost studies/trials.
            fleet.kill_compute_server()
            for i in range(4, 8):
                (trial,) = client.get_suggestions(1)
                client.complete_trial(
                    trial.id, vz.Measurement(metrics={"obj": 0.01 * i})
                )
            assert len(client.list_trials()) == 8

            # The manager's health loop respawns the server (its lease
            # expired); explicit revive is idempotent on a running one.
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if fleet.compute_is_alive():
                    break
                time.sleep(0.2)
            fleet.revive_compute_server()
            assert fleet.compute_is_alive()
            assert fleet.serving_stats()["compute_tier"]["restarts"] >= 1
            (trial,) = client.get_suggestions(1)
            client.complete_trial(
                trial.id, vz.Measurement(metrics={"obj": 0.99})
            )
            assert len(client.list_trials()) == 9
        finally:
            fleet.shutdown()
