"""ReplicaManager: fleet build-out, kill→failover, revive, health sweeps."""

import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.distributed import ReplicaManager
from vizier_tpu.distributed import wal as wal_lib
from vizier_tpu.reliability import ReliabilityConfig
from vizier_tpu.service import proto_converters as pc
from vizier_tpu.service import vizier_client
from vizier_tpu.service.protos import vizier_service_pb2

import dataclasses

# Fast client retries: the failover test exercises a real dead-replica
# transition; the defaults' backoff would dominate test wall time.
RELIABILITY = dataclasses.replace(
    ReliabilityConfig(),
    retry_base_delay_secs=0.001,
    retry_max_delay_secs=0.01,
)


def study_config() -> vz.StudyConfig:
    config = vz.StudyConfig(algorithm="RANDOM_SEARCH")
    config.search_space.root.add_float_param("x", 0.0, 1.0)
    config.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return config


def create_study(manager, study_id: str) -> str:
    name = f"owners/o/studies/{study_id}"
    manager.stub.CreateStudy(
        vizier_service_pb2.CreateStudyRequest(
            parent="owners/o", study=pc.study_to_proto(study_config(), name)
        )
    )
    return name


def make_client(manager, study_name: str) -> vizier_client.VizierClient:
    return vizier_client.VizierClient(
        manager.stub, study_name, "w0", reliability=RELIABILITY
    )


def run_trials(client, n: int, start: int = 0) -> None:
    for i in range(start, start + n):
        (trial,) = client.get_suggestions(1)
        client.complete_trial(
            trial.id, vz.Measurement(metrics={"obj": 0.01 * i})
        )


@pytest.fixture
def manager(tmp_path):
    mgr = ReplicaManager(3, wal_root=str(tmp_path))
    yield mgr
    mgr.shutdown()


class TestFleet:
    def test_build_out(self, manager):
        assert manager.replica_ids() == ["replica-0", "replica-1", "replica-2"]
        # All replicas share ONE Pythia (fleet-wide designer cache /
        # coalescer / batch executor).
        for rid in manager.replica_ids():
            assert manager.replica(rid).servicer._pythia is manager.pythia

    def test_studies_land_on_their_rendezvous_owner(self, manager):
        names = [create_study(manager, f"s{i}") for i in range(8)]
        for name in names:
            owner = manager.replica(manager.router.replica_for(name))
            assert owner.datastore.load_study(name).name == name
        # The population really is sharded.
        owners = {manager.router.replica_for(n) for n in names}
        assert len(owners) > 1

    def test_serving_stats_shape(self, manager):
        name = create_study(manager, "stats")
        run_trials(make_client(manager, name), 2)
        stats = manager.serving_stats()
        assert stats["failovers"] == 0
        assert stats["restored_studies"] == 0
        assert set(stats["router"]) == set(manager.replica_ids())
        assert all(state == "up" for state in stats["router"].values())
        owner = manager.router.replica_for(name)
        assert stats["replicas"][owner]["requests"] > 0
        text = manager.prometheus_text()
        assert "vizier_replica_failovers" in text


class TestFailover:
    def test_kill_owner_client_completes_via_successor(self, manager):
        name = create_study(manager, "failover")
        client = make_client(manager, name)
        run_trials(client, 5)
        owner_before = manager.router.replica_for(name)

        manager.kill_replica(owner_before)
        # The next RPC hits the dead replica, the failure hook fails it
        # over, and the client's retry lands on the rendezvous successor.
        run_trials(client, 5, start=5)

        owner_after = manager.router.replica_for(name)
        assert owner_after != owner_before
        assert not manager.router.is_up(owner_before)
        successor = manager.replica(owner_after)
        assert successor.datastore.load_study(name).name == name
        # WAL replay carried the pre-kill trials over, and the post-kill
        # trials continued the same id sequence.
        assert successor.datastore.max_trial_id(name) == 10
        assert len(client.list_trials()) == 10
        stats = manager.serving_stats()
        assert stats["failovers"] == 1
        assert stats["restored_studies"] >= 1

    def test_failover_handoff_is_durable(self, manager, tmp_path):
        name = create_study(manager, "durable")
        run_trials(make_client(manager, name), 3)
        owner_before = manager.router.replica_for(name)
        manager.kill_replica(owner_before)
        manager.fail_over(owner_before)
        successor = manager.replica(manager.router.replica_for(name))
        # Applying through the successor's datastore re-logged every
        # record: a COLD restart over the successor's WAL dir serves the
        # study.
        restarted = wal_lib.PersistentDataStore(successor.wal_dir)
        try:
            assert restarted.load_study(name).name == name
            assert restarted.max_trial_id(name) == 3
        finally:
            restarted.close()

    def test_fail_over_of_live_replica_is_noop_and_idempotent(self, manager):
        create_study(manager, "guard")
        # A live replica is never failed over — and it is a no-op rather
        # than an error because, under load, a concurrent revive can win
        # the failover lock between a caller observing the replica dead
        # and getting here (the loadgen soak's kill/revive track hits
        # exactly that interleaving).
        assert manager.fail_over("replica-0") == 0
        assert manager.serving_stats()["failovers"] == 0
        manager.kill_replica("replica-0")
        manager.fail_over("replica-0")
        assert manager.fail_over("replica-0") == 0  # no-op second time
        assert manager.serving_stats()["failovers"] == 1

    def test_ram_only_tier_fails_over_without_state(self):
        manager = ReplicaManager(3, wal_root=None)
        try:
            name = create_study(manager, "ram")
            owner = manager.router.replica_for(name)
            manager.kill_replica(owner)
            assert manager.fail_over(owner) == 0  # nothing to restore
            assert not manager.router.is_up(owner)
        finally:
            manager.shutdown()

    def test_transient_fault_on_live_replica_is_not_a_topology_change(
        self, manager
    ):
        # The hook only fails over replicas that are actually dead; a
        # chaos-injected fault on a live one is the retry layer's job.
        manager._on_endpoint_failure("replica-1", ConnectionError("blip"))
        assert manager.router.is_up("replica-1")
        assert manager.serving_stats()["failovers"] == 0


class TestHealthAndRevive:
    def test_health_sweep_fails_over_dead_replicas(self, manager):
        name = create_study(manager, "sweep")
        owner = manager.router.replica_for(name)
        manager.kill_replica(owner)
        snapshot = manager.check_health()
        assert snapshot[owner] == "down"
        assert manager.serving_stats()["failovers"] == 1
        # Sweeps are idempotent.
        manager.check_health()
        assert manager.serving_stats()["failovers"] == 1

    def test_health_loop_detects_kill_in_background(self, manager):
        import time

        name = create_study(manager, "loop")
        owner = manager.router.replica_for(name)
        manager.start_health_loop(interval_secs=0.01)
        try:
            manager.kill_replica(owner)
            deadline = time.monotonic() + 5.0
            while manager.router.is_up(owner):
                assert time.monotonic() < deadline, "health loop never swept"
                time.sleep(0.01)
        finally:
            manager.stop_health_loop()
        assert manager.serving_stats()["failovers"] == 1

    def test_revive_routes_studies_back_with_state(self, manager):
        name = create_study(manager, "revive")
        client = make_client(manager, name)
        run_trials(client, 4)
        owner = manager.router.replica_for(name)
        manager.kill_replica(owner)
        run_trials(client, 2, start=4)  # triggers failover, lands elsewhere
        interim = manager.router.replica_for(name)
        assert interim != owner

        manager.revive_replica(owner)
        assert manager.router.is_up(owner)
        assert manager.router.replica_for(name) == owner
        revived = manager.replica(owner)
        # Copied back from the interim successor: full pre- and
        # post-failover history, unique ownership again.
        assert revived.datastore.max_trial_id(name) == 6
        with pytest.raises(KeyError):
            manager.replica(interim).datastore.load_study(name)
        run_trials(client, 1, start=6)
        assert revived.datastore.max_trial_id(name) == 7

    def test_revive_without_failover_restarts_warm(self, manager):
        name = create_study(manager, "warm")
        run_trials(make_client(manager, name), 3)
        owner = manager.router.replica_for(name)
        manager.kill_replica(owner)
        # Revive before anything noticed: pure WAL restart, no copy-back.
        manager.revive_replica(owner)
        assert manager.router.replica_for(name) == owner
        assert manager.replica(owner).datastore.max_trial_id(name) == 3
        assert manager.serving_stats()["failovers"] == 0

    def test_delete_during_downtime_is_not_resurrected(self, manager):
        """Review regression: a study deleted on its interim successor
        while the owner was down must not come back from the owner's
        stale WAL on revival."""
        doomed = create_study(manager, "doomed")
        kept = create_study(manager, "kept")
        run_trials(make_client(manager, doomed), 2)
        owner = manager.router.replica_for(doomed)
        manager.kill_replica(owner)
        manager.check_health()  # failover: both studies lift to successors
        # Delete while the owner is down: the tombstone lands on the
        # successor's store (and WAL), never on the owner's.
        manager.stub.DeleteStudy(
            vizier_service_pb2.DeleteStudyRequest(name=doomed)
        )

        manager.revive_replica(owner)
        assert manager.router.is_up(owner)
        revived = manager.replica(owner)
        with pytest.raises(KeyError):
            revived.datastore.load_study(doomed)
        # The convergence is durable: a COLD restart over the revived
        # replica's WAL dir must not bring the study back either.
        restarted = wal_lib.PersistentDataStore(revived.wal_dir)
        try:
            with pytest.raises(KeyError):
                restarted.load_study(doomed)
        finally:
            restarted.close()
        # Studies NOT deleted during the downtime are untouched.
        if manager.router.replica_for(kept) == owner:
            assert revived.datastore.load_study(kept).name == kept
        else:
            owner_of_kept = manager.replica(manager.router.replica_for(kept))
            assert owner_of_kept.datastore.load_study(kept).name == kept


class TestListStudiesAcrossFailover:
    """Review regression: a down replica must never silently shrink
    ListStudies — either its studies are restored (complete listing) or
    the fan-out fails loudly."""

    LIST = vizier_service_pb2.ListStudiesRequest(parent="owners/o")

    def test_listing_complete_after_wal_failover(self, manager):
        names = {create_study(manager, f"ls{i}") for i in range(6)}
        victim = manager.router.replica_for(next(iter(names)))
        manager.kill_replica(victim)
        # The first fan-out hits the dead replica: transport error, which
        # synchronously triggers failover through the failure hook.
        with pytest.raises(ConnectionError):
            manager.stub.ListStudies(self.LIST)
        # The retry (here: the caller's next call) sees the complete
        # population, served from the successors.
        response = manager.stub.ListStudies(self.LIST)
        assert {s.name for s in response.studies} == names

    def test_ram_only_down_replica_keeps_listing_loud(self):
        manager = ReplicaManager(3, wal_root=None)
        try:
            names = [create_study(manager, f"ram{i}") for i in range(6)]
            victim = manager.router.replica_for(names[0])
            manager.kill_replica(victim)
            assert manager.fail_over(victim) == 0  # nothing restorable
            # The victim's studies are gone for good; a listing keeps
            # raising rather than pretending the subset is everything.
            with pytest.raises(ConnectionError, match="partial"):
                manager.stub.ListStudies(self.LIST)
        finally:
            manager.shutdown()

    def test_revive_restores_complete_quiet_listing(self, manager):
        names = {create_study(manager, f"rv{i}") for i in range(6)}
        victim = manager.router.replica_for(next(iter(names)))
        manager.kill_replica(victim)
        manager.check_health()
        manager.revive_replica(victim)
        response = manager.stub.ListStudies(self.LIST)
        assert {s.name for s in response.studies} == names
