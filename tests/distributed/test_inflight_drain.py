"""Failover vs in-flight RPCs: drain, late-write catch-up, barrier.

The loadgen soak surfaced three interleavings the single-threaded chaos
A/B never hits; these tests pin their fixes:

1. ``fail_over`` DRAINS a dead replica's in-flight RPCs before reading
   its WAL — otherwise a write the client already observed (a trial
   returned by an in-flight suggest) is missing from the successors and
   the very next ``CompleteTrial`` lands NotFound.
2. An RPC that outlives its own replica's failover (the self-triggered
   edge: a nested routed read inside the RPC trips the failover, which
   must not wait on its own thread) has its late WAL appends **caught
   up** onto the successors before its response reaches the client.
3. Fresh RPCs park on the ``failover_barrier`` while a replay/copy-back
   is mid-flight instead of reading a half-populated successor.
"""

import threading
import time

import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.distributed import ReplicaManager
from vizier_tpu.service import proto_converters as pc
from vizier_tpu.service.protos import vizier_service_pb2

from tests.distributed.test_replica_manager import (  # noqa: F401
    create_study,
    study_config,
)


@pytest.fixture
def manager(tmp_path):
    mgr = ReplicaManager(3, wal_root=str(tmp_path))
    yield mgr
    mgr.shutdown()


def _create_trial_request(study_name: str):
    trial = vz.Trial(parameters={"x": 0.5})
    return vizier_service_pb2.CreateTrialRequest(
        parent=study_name, trial=pc.trial_to_proto(trial)
    )


class TestInflightDrain:
    def test_fail_over_waits_for_inflight_rpc(self, manager):
        name = create_study(manager, "drain")
        owner = manager.router.replica_for(name)
        replica = manager.replica(owner)

        entered, release = threading.Event(), threading.Event()
        original = replica.servicer.CreateTrial

        def slow_create(request):
            entered.set()
            assert release.wait(10.0)
            return original(request)

        replica.servicer.CreateTrial = slow_create
        rpc = threading.Thread(
            target=lambda: manager.stub.CreateTrial(
                _create_trial_request(name)
            )
        )
        rpc.start()
        assert entered.wait(5.0)
        manager.kill_replica(owner)

        failed_over = threading.Event()
        failover = threading.Thread(
            target=lambda: (manager.fail_over(owner), failed_over.set())
        )
        failover.start()
        # The drain must hold the replay behind the in-flight write.
        time.sleep(0.25)
        assert not failed_over.is_set()
        release.set()
        rpc.join(5.0)
        failover.join(5.0)
        assert failed_over.is_set()
        # The in-flight write survived onto the successor.
        successor = manager.router.replica_for(name)
        assert successor != owner
        trials = manager.stub.ListTrials(
            vizier_service_pb2.ListTrialsRequest(parent=name)
        ).trials
        assert len(trials) == 1

    def test_late_writes_catch_up_after_self_triggered_failover(
        self, manager
    ):
        name = create_study(manager, "catchup")
        owner = manager.router.replica_for(name)
        replica = manager.replica(owner)

        entered, release = threading.Event(), threading.Event()
        original = replica.servicer.CreateTrial

        def write_after_own_failover(request):
            entered.set()
            assert release.wait(10.0)
            # The RPC's own thread completes the failover (the nested-
            # read edge): the drain must not wait on this thread, and the
            # write below lands AFTER the WAL replay.
            manager.fail_over(owner)
            return original(request)

        replica.servicer.CreateTrial = write_after_own_failover
        rpc = threading.Thread(
            target=lambda: manager.stub.CreateTrial(
                _create_trial_request(name)
            )
        )
        rpc.start()
        assert entered.wait(5.0)
        manager.kill_replica(owner)
        release.set()
        rpc.join(10.0)
        assert not rpc.is_alive()
        # The post-replay write was caught up onto the successor before
        # the RPC returned.
        trials = manager.stub.ListTrials(
            vizier_service_pb2.ListTrialsRequest(parent=name)
        ).trials
        assert len(trials) == 1

    def test_barrier_parks_fresh_rpcs_during_transition(self, manager):
        name = create_study(manager, "barrier")
        # Hold a transition open and check a fresh routed RPC waits.
        manager._begin_transition()
        started, finished = threading.Event(), threading.Event()

        def fresh_rpc():
            started.set()
            manager.stub.GetStudy(
                vizier_service_pb2.GetStudyRequest(name=name)
            )
            finished.set()

        thread = threading.Thread(target=fresh_rpc)
        thread.start()
        assert started.wait(5.0)
        time.sleep(0.2)
        assert not finished.is_set()
        manager._end_transition()
        thread.join(5.0)
        assert finished.is_set()

    def test_barrier_exempts_threads_inside_an_endpoint_call(self, manager):
        name = create_study(manager, "nested")
        owner = manager.router.replica_for(name)
        replica = manager.replica(owner)
        original = replica.servicer.GetStudy
        nested_done = threading.Event()

        def nested_read(request):
            # A routed read from INSIDE an endpoint call must pass the
            # barrier even mid-transition (the drain waits on us).
            manager._begin_transition()
            try:
                manager.stub.ListTrials(
                    vizier_service_pb2.ListTrialsRequest(parent=name)
                )
                nested_done.set()
            finally:
                manager._end_transition()
            return original(request)

        replica.servicer.GetStudy = nested_read
        manager.stub.GetStudy(
            vizier_service_pb2.GetStudyRequest(name=name)
        )
        assert nested_done.is_set()
