"""The long chaos soak: failover A/B + runtime lock-order cross-check.

Runs ``tools/chaos_ab.py --distributed --mesh-devices --instrument-locks``
end to end — the seeded fault schedule against the sharded tier, the
owning replica killed mid-study, the mesh-sharded batch executor struck on
one placement, every ``threading`` lock instrumented — and asserts the
full verdict: all trials complete via router failover, the mesh strike
stays isolated to its placement's flush, AND every observed
lock-acquisition edge (router/WAL locks plus the per-placement mesh
dispatch workers) was predicted by the static lock_order graph.
``slow``-marked so tier-1 stays fast; the soak runs in CI and via
``tools/reproduce_evidence.sh``.
"""

import json
import os
import pathlib
import subprocess
import sys

import pytest

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent.parent


@pytest.mark.slow
def test_chaos_soak_failover_with_lock_crosscheck(tmp_path):
    out = tmp_path / "chaos.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [
            sys.executable,
            str(REPO_ROOT / "tools" / "chaos_ab.py"),
            "--trials", "50",
            "--distributed", "4",
            "--mesh-devices", "8",
            "--instrument-locks",
            "--out", str(out),
        ],
        capture_output=True,
        text=True,
        env=env,
        cwd=str(REPO_ROOT),
        timeout=1200,
    )
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-3000:]

    report = json.loads(out.read_text())
    verdict = report["verdict"]
    # Single-server arms: reliability on completes, off dies (seed behavior).
    assert verdict["on_completed_all"]
    assert verdict["off_failed"]
    # Distributed arm: the kill-one-replica run completes every trial via
    # router failover + WAL handoff.
    assert verdict["distributed_completed_all"]
    assert verdict["distributed_failovers"] >= 1
    dist = report["arms"]["distributed_failover"]
    assert dist["killed_replica"] is not None
    assert dist["owner_after_failover"] != dist["killed_replica"]
    # Mesh arm: every suggest accounted (served or isolated designer
    # error), the struck placement's executor still lives afterwards.
    assert verdict["mesh_all_accounted"]
    assert verdict["mesh_post_soak_liveness"]
    assert report["arms"]["mesh_executor"]["mesh_flushes"] >= 1
    # Lock-order cross-check: observed runtime edges ⊆ static graph —
    # the instrumented run includes the vizier-mesh-worker-* threads.
    assert verdict["lock_order_confirmed"]
    assert report["lock_check"]["missing_from_static_graph"] == []
    assert report["lock_check"]["acquisitions"] > 0
