"""The cross-process replication surface: servicer, wire link, host.

Everything here runs against a REAL loopback gRPC server (module-scoped:
one server, many cases) — the point of PR 15 is that the epoch/fencing/
recovery protocol holds across an actual process/network boundary, so
these tests exercise the wire path, not the in-process shims.
"""

import threading
import time

import pytest

grpc = pytest.importorskip("grpc")

from concurrent import futures

from vizier_tpu.distributed import replication as replication_lib
from vizier_tpu.distributed import replication_service as repl_service
from vizier_tpu.distributed import wal as wal_lib
from vizier_tpu.service import grpc_stubs
from vizier_tpu.service.protos import replication_service_pb2 as pb
from vizier_tpu.service.protos import study_pb2
from vizier_tpu.testing import netchaos as netchaos_lib

STUDY = "owners/o/studies/wire"


def _study_record(seq, name=STUDY, opcode=wal_lib.CREATE_STUDY):
    return (seq, opcode, study_pb2.Study(name=name).SerializeToString())


class _Server:
    """One replica's receiver side behind a real gRPC server."""

    def __init__(self, tmpdir, replica_id="replica-1"):
        self.standby = replication_lib.StandbyStore(str(tmpdir))
        self.datastore = wal_lib.PersistentDataStore(
            str(tmpdir), snapshot_interval=10_000
        )
        self.servicer = repl_service.ReplicationServicer(
            replica_id, self.standby, datastore=self.datastore
        )
        self.server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
        grpc_stubs.add_replication_servicer_to_server(
            self.servicer, self.server
        )
        port = self.server.add_insecure_port("localhost:0")
        self.endpoint = f"localhost:{port}"
        self.server.start()

    def stop(self):
        self.server.stop(0).wait()
        grpc_stubs.close_channel(self.endpoint)
        self.datastore.close()
        self.standby.close()


@pytest.fixture
def server(tmp_path):
    s = _Server(tmp_path)
    yield s
    s.stop()


class TestWireProtocol:
    def test_baseline_then_append_acks_last_seq(self, server):
        link = repl_service.GrpcReplicationLink({"replica-1": server.endpoint})
        assert link.deliver(
            "replica-1", "replica-0", 1, [_study_record(1)], True, 1
        ) == (True, 1)
        assert link.deliver(
            "replica-1",
            "replica-0",
            1,
            [_study_record(2, opcode=wal_lib.UPDATE_STUDY)],
            False,
            0,
        ) == (True, 2)
        assert len(server.standby.records_for("replica-0")) == 2

    def test_fence_rejects_stale_epoch_and_counts_it(self, server):
        link = repl_service.GrpcReplicationLink({"replica-1": server.endpoint})
        link.deliver("replica-1", "replica-0", 1, [_study_record(1)], True, 1)
        stub = grpc_stubs.create_replication_stub(server.endpoint)
        fence = stub.Fence(pb.FenceRequest(origin="replica-0", epoch=5))
        assert fence.epoch == 5
        accepted, value = link.deliver(
            "replica-1", "replica-0", 1, [_study_record(2)], False, 0
        )
        assert (accepted, value) == (False, 5)
        heartbeat = stub.Heartbeat(pb.HeartbeatRequest(sender="t"))
        assert heartbeat.fenced_rejections == 1
        # Pre-fence state is untouched: fencing rejects writes, it does
        # not destroy the standby log.
        assert len(server.standby.records_for("replica-0")) == 1

    def test_behind_epoch_append_is_not_a_fencing_event(self, server):
        # A delivery AHEAD of the standby's epoch without a baseline
        # means the receiver missed the handoff — rejected, but not a
        # stale-generation write: the fenced counter must not move.
        link = repl_service.GrpcReplicationLink({"replica-1": server.endpoint})
        accepted, value = link.deliver(
            "replica-1", "replica-0", 3, [_study_record(1)], False, 0
        )
        assert not accepted
        stub = grpc_stubs.create_replication_stub(server.endpoint)
        assert stub.Heartbeat(pb.HeartbeatRequest()).fenced_rejections == 0

    def test_duplicate_delivery_dedupes_by_sequence(self, server):
        # At-least-once wire semantics: the same batch delivered twice
        # (a netchaos duplicate) must not double-append.
        net = netchaos_lib.NetChaos(seed=0)
        net.set_link("replica-0", "replica-1", duplicate_prob=1.0)
        link = repl_service.GrpcReplicationLink(
            {"replica-1": server.endpoint},
            src_id="replica-0",
            netchaos=net,
        )
        link.deliver("replica-1", "replica-0", 1, [_study_record(1)], True, 1)
        accepted, value = link.deliver(
            "replica-1",
            "replica-0",
            1,
            [_study_record(2, opcode=wal_lib.UPDATE_STUDY)],
            False,
            0,
        )
        assert (accepted, value) == (True, 2)
        assert net.total("duplicates") >= 1
        assert len(server.standby.records_for("replica-0")) == 2

    def test_export_standby_round_trips_view(self, server):
        link = repl_service.GrpcReplicationLink({"replica-1": server.endpoint})
        records = [_study_record(3), _study_record(4, opcode=wal_lib.UPDATE_STUDY)]
        link.deliver("replica-1", "replica-0", 2, records, True, 3)
        stub = grpc_stubs.create_replication_stub(server.endpoint)
        export = stub.ExportStandby(pb.ExportStandbyRequest(origin="replica-0"))
        assert export.present and export.epoch == 2 and export.baseline_seq == 3
        assert repl_service.records_from_proto(export.records) == records
        absent = stub.ExportStandby(pb.ExportStandbyRequest(origin="nobody"))
        assert not absent.present

    def test_apply_records_re_logs_through_the_datastore(self, server):
        stub = grpc_stubs.create_replication_stub(server.endpoint)
        request = pb.ApplyRecordsRequest()
        repl_service.records_to_proto([_study_record(1)], request.records)
        assert stub.ApplyRecords(request).applied == 1
        # Re-logged: the receiver's own mutation seq advanced (the
        # handoff is durable on ITS disk, not just in RAM).
        assert server.datastore.seq == 1
        state = stub.ExportState(pb.ExportStateRequest())
        assert state.seq == 1
        assert [r.opcode for r in state.records] == [wal_lib.CREATE_STUDY]

    def test_export_state_filters_to_requested_studies(self, server):
        stub = grpc_stubs.create_replication_stub(server.endpoint)
        request = pb.ApplyRecordsRequest()
        repl_service.records_to_proto(
            [
                _study_record(1, name="owners/o/studies/a"),
                _study_record(2, name="owners/o/studies/b"),
            ],
            request.records,
        )
        stub.ApplyRecords(request)
        state = stub.ExportState(
            pb.ExportStateRequest(studies=["owners/o/studies/b"])
        )
        names = {
            wal_lib.study_key_of(r.opcode, r.payload) for r in state.records
        }
        assert names == {"owners/o/studies/b"}


class TestLinkRobustness:
    def test_unreachable_peer_reports_none_not_raise(self):
        link = repl_service.GrpcReplicationLink(
            {"replica-9": "localhost:1"},
            connect_timeout_secs=0.2,
            retry_attempts=2,
            retry_base_delay_secs=0.0,
            retry_max_delay_secs=0.0,
        )
        assert (
            link.deliver("replica-9", "replica-0", 1, [_study_record(1)], True, 1)
            is None
        )

    def test_dead_peer_cooldown_skips_connect_wait(self):
        link = repl_service.GrpcReplicationLink(
            {"replica-9": "localhost:1"},
            connect_timeout_secs=0.2,
            retry_attempts=1,
            down_cooldown_secs=30.0,
        )
        link.deliver("replica-9", "replica-0", 1, [_study_record(1)], True, 1)
        t0 = time.monotonic()
        assert (
            link.deliver("replica-9", "replica-0", 1, [_study_record(2)], False, 0)
            is None
        )
        # In cooldown: the second delivery must fail fast, not pay the
        # connect timeout again (one dead successor must never stall
        # deliveries to live ones).
        assert time.monotonic() - t0 < 0.15

    def test_transport_drop_is_retried_with_jitter(self, server):
        # Seed 1's first draw drops, the retry succeeds: the streamer
        # sees ONE successful delivery, not a resync.
        net = netchaos_lib.NetChaos(seed=1)
        net.set_link("replica-0", "replica-1", drop_prob=0.5)
        link = repl_service.GrpcReplicationLink(
            {"replica-1": server.endpoint},
            src_id="replica-0",
            netchaos=net,
            retry_attempts=5,
            retry_base_delay_secs=0.0,
            retry_max_delay_secs=0.0,
        )
        for seq in range(1, 20):
            accepted, _ = link.deliver(
                "replica-1",
                "replica-0",
                1,
                [_study_record(seq, opcode=wal_lib.UPDATE_STUDY if seq > 1 else wal_lib.CREATE_STUDY)],
                seq == 1,
                1 if seq == 1 else 0,
            )
            assert accepted
        assert net.total("drops") >= 1  # faults happened and were absorbed

    def test_set_endpoint_clears_stub_and_cooldown(self, tmp_path):
        link = repl_service.GrpcReplicationLink(
            {"replica-1": "localhost:1"},
            connect_timeout_secs=2.0,
            retry_attempts=1,
            down_cooldown_secs=30.0,
        )
        assert (
            link.deliver("replica-1", "replica-0", 1, [_study_record(1)], True, 1)
            is None
        )
        fresh = _Server(tmp_path, replica_id="replica-1")
        try:
            link.set_endpoint("replica-1", fresh.endpoint)
            assert link.deliver(
                "replica-1", "replica-0", 1, [_study_record(1)], True, 1
            ) == (True, 1)
        finally:
            fresh.stop()


class TestReplicaReplicationHost:
    def test_host_streams_appends_over_the_wire(self, tmp_path, server):
        origin_store = wal_lib.PersistentDataStore(
            str(tmp_path / "origin"), snapshot_interval=10_000
        )
        link = repl_service.GrpcReplicationLink({"replica-1": server.endpoint})
        host = repl_service.ReplicaReplicationHost(
            "replica-0",
            ["replica-0", "replica-1"],
            datastore=origin_store,
            link=link,
            factor=1,
            epoch=1,
        )
        origin_store.set_append_sink(host.sink())
        try:
            origin_store.create_study(study_pb2.Study(name=STUDY))
            assert host.flush(10.0)
            records = server.standby.records_for("replica-0")
            assert [opcode for _seq, opcode, _p in records] == [
                wal_lib.CREATE_STUDY
            ]
            assert server.standby.last_seq("replica-0") == 1
        finally:
            host.close()
            origin_store.close()

    def test_fenced_host_stops_streaming(self, tmp_path, server):
        origin_store = wal_lib.PersistentDataStore(
            str(tmp_path / "origin"), snapshot_interval=10_000
        )
        link = repl_service.GrpcReplicationLink({"replica-1": server.endpoint})
        host = repl_service.ReplicaReplicationHost(
            "replica-0",
            ["replica-0", "replica-1"],
            datastore=origin_store,
            link=link,
            factor=1,
            epoch=1,
        )
        origin_store.set_append_sink(host.sink())
        try:
            origin_store.create_study(study_pb2.Study(name=STUDY))
            assert host.flush(10.0)
            # A newer generation exists: the standby store fences, the
            # stale host's next delivery is rejected, and the host's
            # streamer stops for good.
            server.standby.fence("replica-0", 9)
            origin_store.update_study(study_pb2.Study(name=STUDY))
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline and not host.fenced:
                time.sleep(0.02)
            assert host.fenced
            assert server.servicer.fenced_rejections >= 1
            assert server.standby.last_seq("replica-0") == 1  # stale write out
        finally:
            host.close()
            origin_store.close()

    def test_resync_reason_reaches_the_registry(self, tmp_path, server):
        from vizier_tpu.observability import metrics as metrics_lib

        registry = metrics_lib.MetricsRegistry()
        origin_store = wal_lib.PersistentDataStore(
            str(tmp_path / "origin"), snapshot_interval=10_000
        )
        link = repl_service.GrpcReplicationLink({"replica-1": server.endpoint})
        host = repl_service.ReplicaReplicationHost(
            "replica-0",
            ["replica-0", "replica-1"],
            datastore=origin_store,
            link=link,
            factor=1,
            epoch=1,
            registry=registry,
        )
        origin_store.set_append_sink(host.sink())
        try:
            origin_store.create_study(study_pb2.Study(name=STUDY))
            assert host.flush(10.0)
            host.request_resync("replica-1")
            assert host.flush(10.0)
            counter = registry.counter("vizier_replication_resyncs")
            assert counter.value(origin="replica-0", reason="requested") >= 1
        finally:
            host.close()
            origin_store.close()
