"""testing/netchaos.py: seeded per-link drop/delay/duplicate/partition."""

import pytest

from vizier_tpu.testing import chaos as chaos_lib
from vizier_tpu.testing import netchaos


class TestLinkSchedule:
    def test_same_seed_same_fault_sequence(self):
        outcomes = []
        for _ in range(2):
            net = netchaos.NetChaos(seed=7)
            net.set_link("a", "b", drop_prob=0.4)
            fn = net.wrap(lambda: "ok", "a", "b")
            run = []
            for _ in range(40):
                try:
                    run.append(fn())
                except netchaos.LinkDroppedError:
                    run.append("drop")
            outcomes.append(run)
        assert outcomes[0] == outcomes[1]
        assert "drop" in outcomes[0] and "ok" in outcomes[0]

    def test_drop_raises_transport_shaped(self):
        net = netchaos.NetChaos(seed=0)
        net.set_link("a", "b", drop_prob=1.0)
        fn = net.wrap(lambda: "ok", "a", "b")
        with pytest.raises(ConnectionError):
            fn()

    def test_delay_sleeps_through_injected_fn(self):
        slept = []
        net = netchaos.NetChaos(seed=0, sleep_fn=slept.append)
        net.set_link("a", "b", delay_prob=1.0, delay_secs=0.25)
        fn = net.wrap(lambda: "ok", "a", "b")
        assert fn() == "ok"
        assert slept == [0.25]

    def test_duplicate_runs_delegate_twice(self):
        calls = []
        net = netchaos.NetChaos(seed=0)
        net.set_link("a", "b", duplicate_prob=1.0)
        fn = net.wrap(lambda: calls.append(1) or len(calls), "a", "b")
        assert fn() == 2  # second copy's outcome is what the caller sees
        assert len(calls) == 2

    def test_wildcard_rules_match_any_node(self):
        net = netchaos.NetChaos(seed=0)
        net.set_link("a", "*", drop_prob=1.0)
        with pytest.raises(netchaos.LinkDroppedError):
            net.strike("a", "anything")
        net.strike("b", "anything")  # other sources unaffected

    def test_exact_rule_beats_wildcard(self):
        net = netchaos.NetChaos(seed=0)
        net.set_link("*", "*", drop_prob=1.0)
        net.set_link("a", "b", drop_prob=0.0)
        net.strike("a", "b")  # exact rule: clean link
        with pytest.raises(netchaos.LinkDroppedError):
            net.strike("a", "c")

    def test_counts_account_every_site(self):
        net = netchaos.NetChaos(seed=3)
        net.set_link("a", "b", drop_prob=1.0)
        for _ in range(3):
            with pytest.raises(netchaos.LinkDroppedError):
                net.strike("a", "b")
        net.strike("b", "a")
        counts = net.counts()
        assert counts["a>b"] == {
            "calls": 3,
            "drops": 3,
            "delays": 0,
            "duplicates": 0,
            "partitioned": 0,
        }
        assert counts["b>a"]["calls"] == 1
        assert net.total("drops") == 3


class TestPartitions:
    def test_node_partition_isolates_both_directions(self):
        net = netchaos.NetChaos(seed=0)
        net.partition("b")
        with pytest.raises(netchaos.PartitionedError):
            net.strike("a", "b")
        with pytest.raises(netchaos.PartitionedError):
            net.strike("b", "a")
        net.heal("b")
        net.strike("a", "b")
        net.strike("b", "a")

    def test_directional_link_partition_is_asymmetric(self):
        net = netchaos.NetChaos(seed=0)
        net.partition_link("a", "b")
        with pytest.raises(netchaos.PartitionedError):
            net.strike("a", "b")
        net.strike("b", "a")  # reverse direction unaffected
        net.heal_link("a", "b")
        net.strike("a", "b")

    def test_heal_node_clears_directional_links_touching_it(self):
        net = netchaos.NetChaos(seed=0)
        net.partition_link("a", "b")
        net.heal("b")
        assert not net.is_partitioned("a", "b")

    def test_partition_draws_keep_rng_stream_aligned(self):
        # A partition window must not consume a different number of RNG
        # variates than a clean call: the post-heal fault sequence stays
        # a pure function of (seed, call index).
        def run(partition_first: bool):
            net = netchaos.NetChaos(seed=9)
            net.set_link("a", "b", drop_prob=0.5)
            if partition_first:
                net.partition("b")
                for _ in range(5):
                    with pytest.raises(netchaos.PartitionedError):
                        net.strike("a", "b")
                net.heal("b")
            else:
                for _ in range(5):
                    try:
                        net.strike("a", "b")
                    except netchaos.LinkDroppedError:
                        pass
            out = []
            for _ in range(10):
                try:
                    net.strike("a", "b")
                    out.append("ok")
                except netchaos.LinkDroppedError:
                    out.append("drop")
            return out

        assert run(True) == run(False)


class TestStubWrapping:
    class _Stub:
        def Suggest(self, request):
            return ("served", request)

        def Other(self, request):
            return "other"

    def test_wrap_stub_strikes_listed_methods_only(self):
        net = netchaos.NetChaos(seed=0)
        net.partition("replica-0")
        stub = net.wrap_stub(
            self._Stub(), "client", "replica-0", methods=["Suggest"]
        )
        with pytest.raises(netchaos.PartitionedError):
            stub.Suggest("r")
        assert stub.Other("r") == "other"  # unlisted: clean passthrough

    def test_wrap_stub_default_wraps_all_public_callables(self):
        net = netchaos.NetChaos(seed=0)
        net.partition("replica-0")
        stub = net.wrap_stub(self._Stub(), "client", "replica-0")
        with pytest.raises(netchaos.PartitionedError):
            stub.Other("r")

    def test_composes_with_chaos_monkey(self):
        # Both injectors wrap the same call and draw from independent
        # seeded streams: netchaos partitions the link while ChaosMonkey
        # would have struck the RPC — the outer wrapper wins first.
        monkey = chaos_lib.ChaosMonkey(seed=1, failure_prob=1.0)
        chaos_stub = chaos_lib.ChaosServiceStub(
            self._Stub(), monkey, methods=("Suggest",)
        )
        net = netchaos.NetChaos(seed=2)
        stub = net.wrap_stub(chaos_stub, "client", "replica-0")
        net.partition("replica-0")
        with pytest.raises(netchaos.PartitionedError):
            stub.Suggest("r")
        net.heal("replica-0")
        with pytest.raises(chaos_lib.InjectedFaultError):
            stub.Suggest("r")


class TestSpecParsing:
    def test_full_spec_round_trip(self):
        net = netchaos.NetChaos.from_spec(
            "seed=9;drop=a>b:0.25;delay=a>*:0.05@0.3;dup=x>y:0.1;"
            "partition=c;partition=m>n"
        )
        assert net.seed == 9
        rule = net._rule_for("a", "b")
        assert rule.drop_prob == 0.25
        assert net._rule_for("a", "z").delay_secs == 0.05
        assert net._rule_for("x", "y").duplicate_prob == 0.1
        assert net.is_partitioned("c", "anything")
        assert net.is_partitioned("m", "n")
        assert not net.is_partitioned("n", "m")

    def test_delay_prob_defaults_to_one(self):
        net = netchaos.NetChaos.from_spec("delay=a>b:0.5")
        rule = net._rule_for("a", "b")
        assert rule.delay_secs == 0.5 and rule.delay_prob == 1.0

    def test_bad_directives_raise(self):
        with pytest.raises(ValueError):
            netchaos.NetChaos.from_spec("drop=a:0.5")  # no '>'
        with pytest.raises(ValueError):
            netchaos.NetChaos.from_spec("frobnicate=a>b:1")

    def test_probabilities_validated(self):
        with pytest.raises(ValueError):
            netchaos.NetChaos(seed=0).set_link("a", "b", drop_prob=1.5)
