"""The shared datastore conformance contract over the distributed backends.

Every backend the sharded tier adds must behave exactly like the RAM/SQL
stores — same suite, same assertions (tests/service/datastore_test_lib).
"""

import os
import tempfile

from vizier_tpu.distributed import sharded_datastore, wal
from vizier_tpu.service import ram_datastore

from tests.service import datastore_test_lib


class TestPersistentDataStore(datastore_test_lib.DataStoreConformance):
    def make_datastore(self):
        return wal.PersistentDataStore(tempfile.mkdtemp(prefix="vz-wal-"))


class TestPersistentDataStoreTinySnapshotInterval(
    datastore_test_lib.DataStoreConformance
):
    """Interval=1: every mutation compacts — the conformance contract must
    hold across constant snapshot churn, not just the append path."""

    def make_datastore(self):
        return wal.PersistentDataStore(
            tempfile.mkdtemp(prefix="vz-wal1-"), snapshot_interval=1
        )


class TestShardedDataStore(datastore_test_lib.DataStoreConformance):
    def make_datastore(self):
        return sharded_datastore.ShardedDataStore(
            [ram_datastore.NestedDictRAMDataStore() for _ in range(3)]
        )


class TestShardedOverPersistent(datastore_test_lib.DataStoreConformance):
    """The composite the sharded tier actually deploys: per-shard WAL."""

    def make_datastore(self):
        root = tempfile.mkdtemp(prefix="vz-swal-")
        return sharded_datastore.ShardedDataStore(
            [
                wal.PersistentDataStore(os.path.join(root, f"shard-{i}"))
                for i in range(2)
            ]
        )


class TestShardedPartitioning:
    def test_studies_land_on_their_rendezvous_shard(self):
        shards = [ram_datastore.NestedDictRAMDataStore() for _ in range(3)]
        store = sharded_datastore.ShardedDataStore(shards)
        names = []
        for i in range(12):
            study = datastore_test_lib.make_study(study=f"s{i}")
            store.create_study(study)
            names.append(study.name)
        # Every study is loadable through the composite, and each lives on
        # exactly the shard the router computes (and no other).
        for name in names:
            owner = store.shard_for(name)
            assert owner.load_study(name).name == name
            others = [s for s in shards if s is not owner]
            for other in others:
                assert not any(
                    s.name == name for s in other.list_studies("owners/o")
                )
        assert len(store.list_studies("owners/o")) == 12

    def test_trials_follow_their_study(self):
        shards = [ram_datastore.NestedDictRAMDataStore() for _ in range(3)]
        store = sharded_datastore.ShardedDataStore(shards)
        study = datastore_test_lib.make_study(study="affine")
        store.create_study(study)
        trial = datastore_test_lib.make_trial(study="affine", trial_id=1)
        store.create_trial(trial)
        owner = store.shard_for(study.name)
        assert owner.max_trial_id(study.name) == 1
        assert store.get_trial(trial.name).id == 1
