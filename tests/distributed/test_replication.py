"""Shared-nothing WAL replication: standby logs, streamers, recovery
source selection, multi-failure failover, epoch-fenced revive."""

import shutil
import time

import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.distributed import ReplicaManager
from vizier_tpu.distributed import replication as repl
from vizier_tpu.distributed import wal as wal_lib
from vizier_tpu.service import proto_converters as pc
from vizier_tpu.service import ram_datastore
from vizier_tpu.service.protos import study_pb2, vizier_service_pb2

from tests.service import datastore_test_lib


def _study_config(algorithm="RANDOM_SEARCH"):
    config = vz.StudyConfig(algorithm=algorithm)
    config.search_space.root.add_float_param("x", 0.0, 1.0)
    config.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return config


def _create_study(stub, name):
    parent = name.rsplit("/studies/", 1)[0]
    stub.CreateStudy(
        vizier_service_pb2.CreateStudyRequest(
            parent=parent, study=pc.study_to_proto(_study_config(), name)
        )
    )


def _complete_one_trial(stub, study_name, client_id="w"):
    from vizier_tpu.service import vizier_client

    client = vizier_client.VizierClient(stub, study_name, client_id)
    (trial,) = client.get_suggestions(1)
    client.complete_trial(
        trial.id, vz.Measurement(metrics={"obj": 0.5})
    )
    return f"{study_name}/trials/{trial.id}"


def _state_of(store) -> list:
    inner = getattr(store, "_inner", store)
    return list(wal_lib.export_records(inner))


def _records(*items):
    """(seq, opcode-ish study payloads) helper for plan tests."""
    out = []
    for seq, opcode, study in items:
        if opcode == wal_lib.DELETE_STUDY:
            payload = f"owners/o/studies/{study}".encode()
        else:
            payload = datastore_test_lib.make_study(
                study=study
            ).SerializeToString()
        out.append((seq, opcode, payload))
    return out


class TestStandbyStore:
    def test_append_ack_and_records(self, tmp_path):
        store = repl.StandbyStore(str(tmp_path))
        ok, last = store.append_batch(
            "origin-a", 1, _records((1, wal_lib.CREATE_STUDY, "s0")),
            reset=True, baseline_seq=0,
        )
        assert ok and last == 1
        ok, last = store.append_batch(
            "origin-a", 1, _records((2, wal_lib.UPDATE_STUDY, "s0"))
        )
        assert ok and last == 2
        assert [r[0] for r in store.records_for("origin-a")] == [1, 2]

    def test_stale_epoch_is_fenced(self, tmp_path):
        store = repl.StandbyStore(str(tmp_path))
        store.append_batch("origin-a", 2, [], reset=True)
        ok, value = store.append_batch(
            "origin-a", 1, _records((3, wal_lib.CREATE_STUDY, "s0"))
        )
        assert not ok and value == 2
        # A reset from the stale epoch is fenced too.
        ok, value = store.append_batch(
            "origin-a", 1, [], reset=True
        )
        assert not ok and value == 2

    def test_fence_without_data_rejects_old_generation(self, tmp_path):
        store = repl.StandbyStore(str(tmp_path))
        store.append_batch(
            "origin-a", 1, _records((1, wal_lib.CREATE_STUDY, "s0")),
            reset=True,
        )
        store.fence("origin-a", 2)
        ok, _ = store.append_batch(
            "origin-a", 1, _records((2, wal_lib.UPDATE_STUDY, "s0"))
        )
        assert not ok
        # The new generation introduces itself with a baseline.
        ok, _ = store.append_batch(
            "origin-a", 2, _records((5, wal_lib.CREATE_STUDY, "s0")),
            reset=True, baseline_seq=5,
        )
        assert ok

    def test_epoch_advance_requires_baseline(self, tmp_path):
        store = repl.StandbyStore(str(tmp_path))
        store.append_batch("origin-a", 1, [], reset=True)
        ok, _ = store.append_batch(
            "origin-a", 2, _records((9, wal_lib.CREATE_STUDY, "s0"))
        )
        assert not ok  # bare append across an epoch boundary

    def test_baseline_reset_replaces_log(self, tmp_path):
        store = repl.StandbyStore(str(tmp_path))
        store.append_batch(
            "origin-a", 1,
            _records((1, wal_lib.CREATE_STUDY, "s0"),
                     (2, wal_lib.CREATE_STUDY, "s1")),
            reset=True,
        )
        store.append_batch(
            "origin-a", 2, _records((10, wal_lib.CREATE_STUDY, "s2")),
            reset=True, baseline_seq=10,
        )
        records = store.records_for("origin-a")
        assert [r[0] for r in records] == [10]
        assert store.view_for("origin-a").baseline_seq == 10

    def test_stale_records_below_last_seq_dropped(self, tmp_path):
        store = repl.StandbyStore(str(tmp_path))
        store.append_batch(
            "origin-a", 1, _records((10, wal_lib.CREATE_STUDY, "s0")),
            reset=True, baseline_seq=10,
        )
        # A straggler older than the baseline must not append behind it:
        # replay order would regress state.
        ok, last = store.append_batch(
            "origin-a", 1, _records((7, wal_lib.UPDATE_STUDY, "s0"))
        )
        assert ok and last == 10
        assert [r[0] for r in store.records_for("origin-a")] == [10]

    def test_disk_round_trip(self, tmp_path):
        store = repl.StandbyStore(str(tmp_path))
        store.append_batch(
            "origin-a", 3,
            _records((5, wal_lib.CREATE_STUDY, "s0")),
            reset=True, baseline_seq=5,
        )
        store.append_batch(
            "origin-a", 3, _records((6, wal_lib.UPDATE_STUDY, "s0"))
        )
        store.close()
        reloaded = repl.StandbyStore(str(tmp_path))
        assert reloaded.epoch("origin-a") == 3
        assert [r[0] for r in reloaded.records_for("origin-a")] == [5, 6]
        assert reloaded.view_for("origin-a").baseline_seq == 5

    def test_memory_mode_without_directory(self):
        store = repl.StandbyStore(None)
        store.append_batch(
            "origin-a", 1, _records((1, wal_lib.CREATE_STUDY, "s0")),
            reset=True,
        )
        assert store.last_seq("origin-a") == 1


class TestPlanRecovery:
    """The per-study recovery-source matrices the ISSUE names."""

    def test_standby_wins_when_local_missing(self):
        plan = repl.plan_recovery(
            "origin",
            [],  # no shared fs: the corpse's disk is gone
            False,
            [repl.StandbyView(0, _records((1, wal_lib.CREATE_STUDY, "s0")))],
        )
        (item,) = plan.studies
        assert item.source == "standby" and item.seq == 1

    def test_standby_wins_ties(self):
        local = _records((5, wal_lib.CREATE_STUDY, "s0"))
        standby = repl.StandbyView(
            0, _records((5, wal_lib.CREATE_STUDY, "s0"))
        )
        plan = repl.plan_recovery("origin", local, False, [standby])
        (item,) = plan.studies
        assert item.source == "standby"

    def test_local_wins_only_when_strictly_longer(self):
        local = _records(
            (5, wal_lib.CREATE_STUDY, "s0"),
            (6, wal_lib.UPDATE_STUDY, "s0"),
        )
        standby = repl.StandbyView(
            0, _records((5, wal_lib.CREATE_STUDY, "s0"))
        )
        plan = repl.plan_recovery("origin", local, False, [standby])
        (item,) = plan.studies
        assert item.source == "local" and item.seq == 6
        assert len(item.records) == 2

    def test_corrupt_mid_log_prefix_loses_to_longer_standby(self):
        # The quarantine truncated local to seq 5; the standby streamed
        # through seq 8 before the host vanished.
        local = _records((5, wal_lib.CREATE_STUDY, "s0"))
        standby = repl.StandbyView(
            0,
            _records(
                (5, wal_lib.CREATE_STUDY, "s0"),
                (8, wal_lib.UPDATE_STUDY, "s0"),
            ),
        )
        plan = repl.plan_recovery("origin", local, True, [standby])
        (item,) = plan.studies
        assert item.source == "standby" and item.seq == 8
        assert plan.local_torn

    def test_best_standby_log_chosen_per_study(self):
        stale = repl.StandbyView(
            0, _records((3, wal_lib.CREATE_STUDY, "s0"))
        )
        fresh = repl.StandbyView(
            0,
            _records(
                (3, wal_lib.CREATE_STUDY, "s0"),
                (9, wal_lib.UPDATE_STUDY, "s0"),
            ),
        )
        plan = repl.plan_recovery("origin", [], False, [stale, fresh])
        (item,) = plan.studies
        assert item.seq == 9 and len(item.records) == 2

    def test_net_deleted_study_contributes_nothing(self):
        local = _records(
            (1, wal_lib.CREATE_STUDY, "s0"),
            (2, wal_lib.DELETE_STUDY, "s0"),
        )
        plan = repl.plan_recovery("origin", local, False, [])
        assert plan.studies == []
        assert plan.max_seq == 2  # watermark still advances past it

    def test_baseline_absence_outranks_stale_local_presence(self):
        # The handback tombstone fell into the quarantined corrupt
        # suffix: local still shows the moved-away study as live, but a
        # LATER baseline (seq 20) omits it — absence wins.
        local = _records((6, wal_lib.CREATE_STUDY, "s0"))
        standby = repl.StandbyView(20, [])
        plan = repl.plan_recovery("origin", local, True, [standby])
        assert plan.studies == []

    def test_absence_claim_ignored_for_non_successor_holders(self):
        local = _records((6, wal_lib.CREATE_STUDY, "s0"))
        standby = repl.StandbyView(20, [])
        plan = repl.plan_recovery(
            "origin",
            local,
            False,
            [standby],
            successors_fn=lambda study: ["replica-9"],  # holder not in set
            holders=["replica-1"],
        )
        (item,) = plan.studies
        assert item.source == "local"

    def test_catch_up_tail_keeps_late_deletes(self):
        local = _records(
            (1, wal_lib.CREATE_STUDY, "s0"),
            (7, wal_lib.DELETE_STUDY, "s0"),
        )
        plan = repl.plan_recovery("origin", local, False, [], min_seq=5)
        (item,) = plan.studies
        assert [opcode for opcode, _ in item.records] == [
            wal_lib.DELETE_STUDY
        ]

    def test_catch_up_skips_already_replayed(self):
        local = _records((1, wal_lib.CREATE_STUDY, "s0"))
        plan = repl.plan_recovery("origin", local, False, [], min_seq=4)
        assert plan.studies == []


@pytest.fixture
def manager(tmp_path):
    mgr = ReplicaManager(3, wal_root=str(tmp_path / "wal"))
    yield mgr
    mgr.shutdown()


class TestReplicatedFailover:
    def test_failover_with_wal_dir_deleted(self, manager, tmp_path):
        """The shared-nothing headline: the corpse's disk is GONE and the
        study still fails over, from the successors' standby logs."""
        study = "owners/o/studies/no-shared-fs"
        _create_study(manager.stub, study)
        _complete_one_trial(manager.stub, study)
        owner = manager.router.replica_for(study)
        assert manager.flush_replication(owner)
        shutil.rmtree(tmp_path / "wal" / owner)
        manager.kill_replica(owner)
        restored = manager.fail_over(owner)
        assert restored == 1
        stats = manager.serving_stats()
        assert stats["recovery_sources"].get("standby", 0) >= 1
        got = manager.stub.GetStudy(
            vizier_service_pb2.GetStudyRequest(name=study)
        )
        assert got.name == study
        trials = manager.stub.ListTrials(
            vizier_service_pb2.ListTrialsRequest(parent=study)
        )
        assert len(trials.trials) == 1
        assert trials.trials[0].state == study_pb2.Trial.SUCCEEDED

    def test_standby_replay_equals_local_replay_bit_for_bit(
        self, manager, tmp_path
    ):
        """The ISSUE's equivalence matrix: recovering a replica's state
        from the standby logs produces byte-identical records to
        recovering it from its own local WAL."""
        studies = [f"owners/o/studies/eq-{i}" for i in range(6)]
        for name in studies:
            _create_study(manager.stub, name)
        for name in studies[:3]:
            _complete_one_trial(manager.stub, name)
        origin = manager.router.replica_for(studies[0])
        assert manager.flush_replication(origin)
        replica = manager.replica(origin)

        # Local replay: the origin's own WAL directory.
        local_store = ram_datastore.NestedDictRAMDataStore()
        for opcode, payload in wal_lib.read_directory(replica.wal_dir)[0]:
            wal_lib.apply_record(local_store, opcode, payload)

        # Standby replay: merge the live peers' standby logs per study.
        standby_store = ram_datastore.NestedDictRAMDataStore()
        plan = manager.recovery_plan(origin, None)
        for item in plan.studies:
            assert item.source == "standby"
            for opcode, payload in item.records:
                wal_lib.apply_record(standby_store, opcode, payload)

        assert wal_lib.export_records(standby_store) == (
            wal_lib.export_records(local_store)
        )

    def test_concurrent_multi_replica_failure(self, manager):
        studies = [f"owners/o/studies/multi-{i}" for i in range(12)]
        for name in studies:
            _create_study(manager.stub, name)
        owners = {name: manager.router.replica_for(name) for name in studies}
        dead = sorted(set(owners.values()))[:2]
        for rid in dead:
            manager.kill_replica(rid)
        # ONE call sweeps every corpse, re-routing between steps.
        manager.fail_over(dead[0])
        assert manager.serving_stats()["failovers"] == 2
        for name in studies:
            assert manager.router.replica_for(name) not in dead
            got = manager.stub.GetStudy(
                vizier_service_pb2.GetStudyRequest(name=name)
            )
            assert got.name == name

    def test_corrupt_local_wal_recovers_from_standby(
        self, manager, tmp_path
    ):
        study = "owners/o/studies/corrupt-recovery"
        _create_study(manager.stub, study)
        trial_name = _complete_one_trial(manager.stub, study)
        owner = manager.router.replica_for(study)
        assert manager.flush_replication(owner)
        # Mid-file corruption of the live log: the suffix (which holds
        # the trial completion) becomes unreadable locally.
        log = tmp_path / "wal" / owner / wal_lib.LOG_FILE
        data = bytearray(log.read_bytes())
        midpoint = len(data) // 2
        data[midpoint : midpoint + 16] = b"\xff" * 16
        log.write_bytes(bytes(data))
        manager.kill_replica(owner)
        manager.fail_over(owner)
        trial = manager.stub.GetTrial(
            vizier_service_pb2.GetTrialRequest(name=trial_name)
        )
        assert trial.state == study_pb2.Trial.SUCCEEDED
        assert (
            manager.serving_stats()["recovery_sources"].get("standby", 0)
            >= 1
        )

    def test_replication_off_uses_legacy_local_path(self, tmp_path, monkeypatch):
        monkeypatch.setenv("VIZIER_DISTRIBUTED_REPLICATION", "0")
        mgr = ReplicaManager(3, wal_root=str(tmp_path / "wal"))
        try:
            assert not mgr.replication_active
            study = "owners/o/studies/legacy"
            _create_study(mgr.stub, study)
            owner = mgr.router.replica_for(study)
            mgr.kill_replica(owner)
            assert mgr.fail_over(owner) == 1
            stats = mgr.serving_stats()
            assert stats["recovery_sources"] == {"local": 1}
            assert "replication" not in stats
            got = mgr.stub.GetStudy(
                vizier_service_pb2.GetStudyRequest(name=study)
            )
            assert got.name == study
        finally:
            mgr.shutdown()


class TestEpochFencedRevive:
    def test_revive_bumps_epoch_and_fences_stale_streamer(self, manager):
        study = "owners/o/studies/fence"
        _create_study(manager.stub, study)
        owner = manager.router.replica_for(study)
        plane = manager._replication
        assert plane.epoch_of(owner) == 1
        manager.kill_replica(owner)
        manager.fail_over(owner)
        manager.revive_replica(owner)
        assert plane.epoch_of(owner) == 2
        # A delivery from the dead generation (epoch 1) is rejected by
        # every live standby store.
        successor = next(
            rid for rid in manager.replica_ids() if rid != owner
        )
        standby = manager.replica(successor).standby
        ok, value = standby.append_batch(
            owner, 1, _records((99, wal_lib.CREATE_STUDY, "stale"))
        )
        assert not ok and value == 2

    def test_revive_under_live_traffic_keeps_state(self, manager):
        study = "owners/o/studies/handback"
        _create_study(manager.stub, study)
        _complete_one_trial(manager.stub, study)
        owner = manager.router.replica_for(study)
        manager.kill_replica(owner)
        manager.fail_over(owner)
        _complete_one_trial(manager.stub, study, client_id="mid-failover")
        # No external traffic gate: the epoch fence + failover barrier
        # make the handback safe.
        manager.revive_replica(owner)
        assert manager.router.replica_for(study) == owner
        trials = manager.stub.ListTrials(
            vizier_service_pb2.ListTrialsRequest(parent=study)
        )
        completed = [
            t for t in trials.trials if t.state == study_pb2.Trial.SUCCEEDED
        ]
        assert len(completed) == 2

    def test_revive_resyncs_returning_replicas_standby_logs(self, manager):
        study = "owners/o/studies/resync"
        _create_study(manager.stub, study)
        owner = manager.router.replica_for(study)
        successor = manager._replication.successors_for(study, owner)[0]
        # Kill the SUCCESSOR, mutate the study, revive the successor: its
        # standby log must catch back up (proactive resync), so a
        # subsequent owner death with a dead disk still recovers.
        manager.kill_replica(successor)
        manager.fail_over(successor)
        _complete_one_trial(manager.stub, study)
        manager.revive_replica(successor)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            records = manager.replica(successor).standby.records_for(owner)
            if any(
                wal_lib.study_key_of(op, pl) == study
                and op == wal_lib.UPDATE_TRIAL
                for _s, op, pl in records
            ) or any(
                _s >= manager.replica(owner).datastore.seq
                for _s, op, pl in records
            ):
                break
            time.sleep(0.02)
        view = manager.replica(successor).standby.view_for(owner)
        assert view is not None
        assert max(
            [view.baseline_seq] + [r[0] for r in view.records]
        ) >= manager.replica(owner).datastore.seq - 1


class TestSpeculativeRearm:
    def test_failover_rearms_speculation_per_restored_study(
        self, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("VIZIER_SPECULATIVE", "1")
        mgr = ReplicaManager(3, wal_root=str(tmp_path / "wal"))
        try:
            engine = mgr.pythia.serving_runtime.speculative_engine
            assert engine is not None and engine.bound
            study = "owners/o/studies/rearm"
            _create_study(mgr.stub, study)
            _complete_one_trial(mgr.stub, study)
            owner = mgr.router.replica_for(study)
            mgr.kill_replica(owner)
            mgr.fail_over(owner)
            stats = mgr.serving_stats()
            assert stats.get("speculative_rearms", 0) >= 1
        finally:
            mgr.shutdown()

    def test_no_rearm_without_completed_trials(self, tmp_path, monkeypatch):
        monkeypatch.setenv("VIZIER_SPECULATIVE", "1")
        mgr = ReplicaManager(3, wal_root=str(tmp_path / "wal"))
        try:
            study = "owners/o/studies/no-rearm"
            _create_study(mgr.stub, study)  # no completions
            owner = mgr.router.replica_for(study)
            mgr.kill_replica(owner)
            mgr.fail_over(owner)
            assert mgr.serving_stats().get("speculative_rearms", 0) == 0
        finally:
            mgr.shutdown()


class TestStreamerMechanics:
    def _fake_plane(self):
        """A minimal in-memory successor pair driven directly."""
        stores = {
            "succ-a": repl.StandbyStore(None),
            "succ-b": repl.StandbyStore(None),
        }
        alive = {"succ-a": True, "succ-b": True}
        state = {"seq": 0, "records": []}

        def deliver(successor, origin, epoch, records, reset, baseline_seq):
            if not alive[successor]:
                return None
            return stores[successor].append_batch(
                origin, epoch, records, reset=reset, baseline_seq=baseline_seq
            )

        def baseline(successor):
            return state["seq"], [
                (state["seq"], op, pl) for op, pl in state["records"]
            ]

        return stores, alive, state, deliver, baseline

    def test_appends_reach_both_successors(self):
        stores, alive, state, deliver, baseline = self._fake_plane()
        streamer = repl.ReplicationStreamer(
            "origin",
            1,
            successors_fn=lambda key: ["succ-a", "succ-b"],
            deliver_fn=deliver,
            baseline_fn=baseline,
        )
        try:
            payload = datastore_test_lib.make_study(
                study="s0"
            ).SerializeToString()
            state["seq"] = 1
            state["records"] = [(wal_lib.CREATE_STUDY, payload)]
            streamer.submit(1, wal_lib.CREATE_STUDY, payload)
            assert streamer.flush(5)
            for store in stores.values():
                assert store.last_seq("origin") == 1
            assert streamer.lag() == 0
        finally:
            streamer.close()

    def test_dead_successor_resynced_on_return(self):
        stores, alive, state, deliver, baseline = self._fake_plane()
        alive["succ-b"] = False
        streamer = repl.ReplicationStreamer(
            "origin",
            1,
            successors_fn=lambda key: ["succ-a", "succ-b"],
            deliver_fn=deliver,
            baseline_fn=baseline,
        )
        try:
            payload = datastore_test_lib.make_study(
                study="s0"
            ).SerializeToString()
            state["seq"] = 1
            state["records"] = [(wal_lib.CREATE_STUDY, payload)]
            streamer.submit(1, wal_lib.CREATE_STUDY, payload)
            assert streamer.flush(5)
            assert stores["succ-b"].last_seq("origin") == 0
            alive["succ-b"] = True
            streamer.request_resync("succ-b")
            assert streamer.flush(5)
            assert stores["succ-b"].last_seq("origin") == 1
        finally:
            streamer.close()

    def test_queue_overflow_drops_then_rebaselines(self):
        stores, alive, state, deliver, baseline = self._fake_plane()
        alive["succ-a"] = alive["succ-b"] = False  # deliveries stall
        streamer = repl.ReplicationStreamer(
            "origin",
            1,
            successors_fn=lambda key: ["succ-a", "succ-b"],
            deliver_fn=deliver,
            baseline_fn=baseline,
            queue_size=4,
            batch_max=2,
        )
        try:
            payload = datastore_test_lib.make_study(
                study="s0"
            ).SerializeToString()
            for seq in range(1, 64):
                streamer.submit(seq, wal_lib.CREATE_STUDY, payload)
            state["seq"] = 63
            state["records"] = [(wal_lib.CREATE_STUDY, payload)]
            streamer.flush(2)
            assert streamer.dropped > 0  # never blocked the write path
            alive["succ-a"] = alive["succ-b"] = True
            streamer.submit(64, wal_lib.CREATE_STUDY, payload)
            state["seq"] = 64
            assert streamer.flush(5)
            # Overflow cost a resync, not correctness: both successors
            # hold the full-state baseline.
            for store in stores.values():
                assert store.last_seq("origin") == 64
        finally:
            streamer.close()

    def test_fenced_streamer_stops(self):
        stores, alive, state, deliver, baseline = self._fake_plane()
        streamer = repl.ReplicationStreamer(
            "origin",
            1,
            successors_fn=lambda key: ["succ-a"],
            deliver_fn=deliver,
            baseline_fn=baseline,
        )
        try:
            payload = datastore_test_lib.make_study(
                study="s0"
            ).SerializeToString()
            state["seq"] = 1
            state["records"] = [(wal_lib.CREATE_STUDY, payload)]
            streamer.submit(1, wal_lib.CREATE_STUDY, payload)
            assert streamer.flush(5)
            # A new generation fences the store; the old streamer's next
            # delivery must stop it for good.
            stores["succ-a"].fence("origin", 2)
            streamer.submit(2, wal_lib.UPDATE_STUDY, payload)
            deadline = time.monotonic() + 5
            while not streamer.fenced and time.monotonic() < deadline:
                time.sleep(0.01)
            assert streamer.fenced
        finally:
            streamer.close()
