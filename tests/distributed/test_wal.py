"""WAL/snapshot durability: crash tolerance, restart-warm state equality."""

import os

import pytest

from vizier_tpu.distributed import wal
from vizier_tpu.service import datastore as datastore_lib
from vizier_tpu.service import ram_datastore, resources
from vizier_tpu.service.protos import key_value_pb2, study_pb2, vizier_service_pb2

from tests.service import datastore_test_lib


def state_of(store) -> list:
    """Canonical dump of a (persistent or RAM) store for equality checks."""
    inner = getattr(store, "_inner", store)
    return [
        (opcode, payload) for opcode, payload in wal.export_records(inner)
    ]


def populate(ds, *, studies=3, trials=4, ops=2):
    """A representative mixed workload (every record type)."""
    for s in range(studies):
        ds.create_study(datastore_test_lib.make_study(study=f"s{s}"))
        study_name = f"owners/o/studies/s{s}"
        for t in range(1, trials + 1):
            trial = datastore_test_lib.make_trial(study=f"s{s}", trial_id=t)
            ds.create_trial(trial)
            if t % 2 == 0:
                trial.state = study_pb2.Trial.SUCCEEDED
                ds.update_trial(trial)
        for n in range(1, ops + 1):
            name = resources.SuggestionOperationResource("o", f"s{s}", "c", n).name
            ds.create_suggestion_operation(
                vizier_service_pb2.Operation(name=name, done=(n == 1))
            )
        es = resources.EarlyStoppingOperationResource("o", f"s{s}", 1).name
        ds.create_early_stopping_operation(
            vizier_service_pb2.EarlyStoppingOperation(name=es, should_stop=True)
        )
        ds.update_metadata(
            study_name,
            [key_value_pb2.KeyValue(key="k", ns=":m", string_value=f"v{s}")],
            [(1, key_value_pb2.KeyValue(key="tk", double_value=1.5))],
        )


class TestRestartWarm:
    def test_restart_equals_pre_crash_state(self, tmp_path):
        ds = wal.PersistentDataStore(str(tmp_path), snapshot_interval=7)
        populate(ds)
        before = state_of(ds)
        ds.close()  # crash: no compaction, state must come from snapshot+log
        revived = wal.PersistentDataStore(str(tmp_path))
        assert state_of(revived) == before
        assert not revived.recovered_torn_tail

    def test_restart_from_snapshot_only(self, tmp_path):
        ds = wal.PersistentDataStore(str(tmp_path))
        populate(ds, studies=2)
        before = state_of(ds)
        ds.compact_now()
        ds.close()
        assert os.path.getsize(tmp_path / wal.LOG_FILE) == 0
        revived = wal.PersistentDataStore(str(tmp_path))
        assert state_of(revived) == before

    def test_delete_study_survives_restart(self, tmp_path):
        ds = wal.PersistentDataStore(str(tmp_path))
        populate(ds, studies=2)
        ds.delete_study("owners/o/studies/s0")
        ds.close()
        revived = wal.PersistentDataStore(str(tmp_path))
        with pytest.raises(datastore_lib.NotFoundError):
            revived.load_study("owners/o/studies/s0")
        # ...including across a compaction boundary (the delete folded into
        # the snapshot, not just replayed from the log).
        revived.compact_now()
        revived.close()
        again = wal.PersistentDataStore(str(tmp_path))
        with pytest.raises(datastore_lib.NotFoundError):
            again.load_study("owners/o/studies/s0")
        assert again.load_study("owners/o/studies/s1").name

    def test_snapshot_interval_compacts_the_log(self, tmp_path):
        ds = wal.PersistentDataStore(str(tmp_path), snapshot_interval=5)
        populate(ds, studies=4)
        # With interval 5 and dozens of mutations, the live log holds at
        # most the tail since the last compaction.
        assert ds.wal.appended_since_snapshot < 5
        assert os.path.getsize(tmp_path / wal.SNAPSHOT_FILE) > 0


class TestCrashWindows:
    def test_truncated_last_record_dropped(self, tmp_path):
        ds = wal.PersistentDataStore(str(tmp_path))
        populate(ds, studies=1, trials=2, ops=1)
        before = state_of(ds)
        last_trial = datastore_test_lib.make_trial(study="s0", trial_id=99)
        ds.create_trial(last_trial)
        ds.close()
        # Crash mid-append: chop bytes off the final record.
        log = tmp_path / wal.LOG_FILE
        data = log.read_bytes()
        log.write_bytes(data[:-3])
        revived = wal.PersistentDataStore(str(tmp_path))
        assert revived.recovered_torn_tail
        # The torn mutation is gone; everything before it is intact.
        assert state_of(revived) == before
        with pytest.raises(datastore_lib.NotFoundError):
            revived.get_trial(last_trial.name)

    def test_corrupt_crc_tail_dropped(self, tmp_path):
        ds = wal.PersistentDataStore(str(tmp_path))
        ds.create_study(datastore_test_lib.make_study(study="s0"))
        before = state_of(ds)
        ds.create_study(datastore_test_lib.make_study(study="s1"))
        ds.close()
        log = tmp_path / wal.LOG_FILE
        data = bytearray(log.read_bytes())
        data[-1] ^= 0xFF  # flip a payload byte in the last record
        log.write_bytes(bytes(data))
        revived = wal.PersistentDataStore(str(tmp_path))
        assert revived.recovered_torn_tail
        assert state_of(revived) == before

    def test_crash_between_snapshot_and_truncate_converges(self, tmp_path):
        """The documented double-apply window: snapshot renamed, log not yet
        truncated. Replaying snapshot + full log must converge to the same
        state (tolerant replay)."""
        ds = wal.PersistentDataStore(str(tmp_path))
        populate(ds, studies=2)
        before = state_of(ds)
        ds.close()
        # Simulate the window: write the snapshot by hand, keep the log.
        inner = ram_datastore.NestedDictRAMDataStore()
        for opcode, payload in wal.read_directory(str(tmp_path))[0]:
            wal.apply_record(inner, opcode, payload)
        records = wal.export_records(inner)
        snapshot = tmp_path / wal.SNAPSHOT_FILE
        with open(snapshot, "wb") as f:
            for opcode, payload in records:
                f.write(wal.WriteAheadLog._frame(opcode, payload))
        revived = wal.PersistentDataStore(str(tmp_path))
        assert state_of(revived) == before

    def test_empty_directory_is_a_fresh_store(self, tmp_path):
        ds = wal.PersistentDataStore(str(tmp_path))
        assert ds.recovered_records == 0
        assert ds.list_studies("owners/o") == []


class TestDurabilityModes:
    """Review regression: the default mode flushes (process-crash durable
    only); VIZIER_DISTRIBUTED_WAL_FSYNC / fsync=True syncs per append."""

    def test_default_appends_flush_without_fsync(self, tmp_path, monkeypatch):
        synced = []
        monkeypatch.setattr(os, "fsync", lambda fd: synced.append(fd))
        ds = wal.PersistentDataStore(str(tmp_path), fsync=False)
        ds.create_study(datastore_test_lib.make_study(study="s0"))
        assert not synced  # appends hand the record to the OS only...
        ds.compact_now()
        assert len(synced) == 1  # ...snapshots always sync
        ds.close()

    def test_fsync_mode_syncs_every_append(self, tmp_path, monkeypatch):
        real_fsync = os.fsync
        synced = []

        def counting_fsync(fd):
            synced.append(fd)
            real_fsync(fd)

        monkeypatch.setattr(os, "fsync", counting_fsync)
        ds = wal.PersistentDataStore(str(tmp_path), fsync=True)
        ds.create_study(datastore_test_lib.make_study(study="s0"))
        ds.create_study(datastore_test_lib.make_study(study="s1"))
        assert len(synced) == 2
        ds.close()
        revived = wal.PersistentDataStore(str(tmp_path))
        assert len(revived.list_studies("owners/o")) == 2

    def test_env_switch_feeds_config(self, monkeypatch):
        from vizier_tpu.distributed import config as config_lib

        monkeypatch.delenv("VIZIER_DISTRIBUTED_WAL_FSYNC", raising=False)
        assert not config_lib.DistributedConfig.from_env().wal_fsync
        monkeypatch.setenv("VIZIER_DISTRIBUTED_WAL_FSYNC", "1")
        assert config_lib.DistributedConfig.from_env().wal_fsync


class TestDivergenceFailStop:
    """Review regression: a WAL write failing AFTER its mutation applied
    must not leave readers observing state a restart would revert."""

    def test_failed_append_poisons_the_store(self, tmp_path, monkeypatch):
        ds = wal.PersistentDataStore(str(tmp_path))
        ds.create_study(datastore_test_lib.make_study(study="s0"))

        def full_disk(opcode, payload):
            raise OSError("No space left on device")

        monkeypatch.setattr(ds.wal, "append", full_disk)
        with pytest.raises(OSError):
            ds.create_study(datastore_test_lib.make_study(study="s1"))
        # Fail-stop: the store refuses reads AND writes instead of serving
        # the un-logged mutation.
        with pytest.raises(wal.StoreDivergedError):
            ds.load_study("owners/o/studies/s1")
        with pytest.raises(wal.StoreDivergedError):
            ds.list_studies("owners/o")
        with pytest.raises(wal.StoreDivergedError):
            ds.create_study(datastore_test_lib.make_study(study="s2"))
        with pytest.raises(wal.StoreDivergedError):
            ds.compact_now()
        ds.close()
        # A restart recovers to exactly the logged state.
        revived = wal.PersistentDataStore(str(tmp_path))
        assert [s.name for s in revived.list_studies("owners/o")] == [
            "owners/o/studies/s0"
        ]


class TestCorruptionQuarantine:
    """Mid-log corruption must not poison appends made after a restart:
    the invalid suffix moves to a ``.corrupt`` sidecar and the live log
    truncates to its longest valid prefix."""

    def _corrupt_midpoint(self, log_path) -> bytes:
        data = bytearray(log_path.read_bytes())
        midpoint = len(data) // 2
        original = bytes(data)
        data[midpoint : midpoint + 16] = b"\xff" * 16
        log_path.write_bytes(bytes(data))
        return original[midpoint:]

    def test_mid_log_corruption_quarantined_on_reopen(self, tmp_path):
        ds = wal.PersistentDataStore(str(tmp_path))
        populate(ds, studies=3)
        ds.close()
        log = tmp_path / wal.LOG_FILE
        self._corrupt_midpoint(log)
        corrupted = log.read_bytes()
        revived = wal.PersistentDataStore(str(tmp_path))
        assert revived.recovered_torn_tail
        assert revived.recovered_quarantined_bytes > 0
        # Sidecar holds the EXACT invalid suffix; the live log is the
        # valid prefix.
        sidecar = tmp_path / (wal.LOG_FILE + wal.CORRUPT_SUFFIX)
        assert sidecar.exists()
        prefix = log.read_bytes()
        assert prefix + sidecar.read_bytes() == corrupted
        records, torn = wal.WriteAheadLog._read_records(str(log))
        assert not torn and records  # the prefix reads clean
        revived.close()

    def test_appends_after_quarantine_survive_replay(self, tmp_path):
        """The poison scenario the quarantine exists for: without it, a
        record appended after mid-log damage is acknowledged and then
        silently unreadable on the next replay."""
        ds = wal.PersistentDataStore(str(tmp_path))
        populate(ds, studies=2)
        ds.close()
        self._corrupt_midpoint(tmp_path / wal.LOG_FILE)
        revived = wal.PersistentDataStore(str(tmp_path))
        revived.create_study(datastore_test_lib.make_study(study="after"))
        after = state_of(revived)
        revived.close()
        again = wal.PersistentDataStore(str(tmp_path))
        assert state_of(again) == after
        assert again.load_study("owners/o/studies/after").name

    def test_clean_log_quarantines_nothing(self, tmp_path):
        ds = wal.PersistentDataStore(str(tmp_path))
        populate(ds, studies=1)
        ds.close()
        revived = wal.PersistentDataStore(str(tmp_path))
        assert revived.recovered_quarantined_bytes == 0
        assert not (tmp_path / (wal.LOG_FILE + wal.CORRUPT_SUFFIX)).exists()


class TestSequenceNumbers:
    def test_seq_counts_mutations(self, tmp_path):
        ds = wal.PersistentDataStore(str(tmp_path))
        assert ds.seq == 0
        ds.create_study(datastore_test_lib.make_study(study="s0"))
        ds.create_trial(datastore_test_lib.make_trial(study="s0"))
        assert ds.seq == 2

    def test_seq_survives_restart(self, tmp_path):
        ds = wal.PersistentDataStore(str(tmp_path))
        populate(ds, studies=2)
        seq = ds.seq
        ds.close()
        revived = wal.PersistentDataStore(str(tmp_path))
        assert revived.seq == seq

    def test_seq_survives_compaction(self, tmp_path):
        ds = wal.PersistentDataStore(str(tmp_path))
        populate(ds, studies=2)
        seq = ds.seq
        ds.compact_now()
        assert ds.seq == seq
        ds.close()
        # The snapshot's SNAPSHOT_META record carries the base.
        revived = wal.PersistentDataStore(str(tmp_path))
        assert revived.seq == seq
        revived.create_study(datastore_test_lib.make_study(study="extra"))
        assert revived.seq == seq + 1

    def test_read_directory_with_seqs_places_records(self, tmp_path):
        ds = wal.PersistentDataStore(str(tmp_path))
        ds.create_study(datastore_test_lib.make_study(study="s0"))
        ds.compact_now()
        ds.create_study(datastore_test_lib.make_study(study="s1"))
        seq = ds.seq
        ds.close()
        records, torn = wal.read_directory_with_seqs(str(tmp_path))
        assert not torn
        # Snapshot records carry the base seq; the live-log record sits
        # one past it.
        seqs = [s for s, _op, _pl in records]
        assert max(seqs) == seq
        assert seqs == sorted(seqs)
        # read_directory strips meta + seqs but keeps the records.
        plain, _ = wal.read_directory(str(tmp_path))
        assert [(op, pl) for _s, op, pl in records] == plain

    def test_export_with_seq_is_atomic_pair(self, tmp_path):
        ds = wal.PersistentDataStore(str(tmp_path))
        populate(ds, studies=1)
        seq, records = ds.export_with_seq()
        assert seq == ds.seq
        assert records == wal.export_records(ds._inner)

    def test_on_append_hook_sees_ordered_seqs(self, tmp_path):
        seen = []

        class Sink:
            def submit(self, seq, opcode, payload):
                seen.append(seq)

        ds = wal.PersistentDataStore(str(tmp_path), on_append=Sink())
        populate(ds, studies=1)
        assert seen == list(range(1, len(seen) + 1))

    def test_on_append_failure_never_fails_the_mutation(self, tmp_path):
        class BoomSink:
            def submit(self, seq, opcode, payload):
                raise RuntimeError("streamer exploded")

        ds = wal.PersistentDataStore(str(tmp_path), on_append=BoomSink())
        ds.create_study(datastore_test_lib.make_study(study="s0"))
        assert ds.load_study("owners/o/studies/s0").name
        assert ds.seq == 1


class TestRecordFraming:
    def test_unknown_opcode_rejected_at_append(self, tmp_path):
        log = wal.WriteAheadLog(str(tmp_path))
        with pytest.raises(ValueError):
            log.append(99, b"payload")

    def test_study_key_of_every_record_type(self, tmp_path):
        ds = wal.PersistentDataStore(str(tmp_path))
        populate(ds, studies=1)
        ds.delete_trial("owners/o/studies/s0/trials/3")
        ds.close()
        records, torn = wal.read_directory(str(tmp_path))
        assert not torn and records
        seen_opcodes = set()
        for opcode, payload in records:
            assert wal.study_key_of(opcode, payload) == "owners/o/studies/s0"
            seen_opcodes.add(opcode)
        assert wal.CREATE_STUDY in seen_opcodes
        assert wal.UPDATE_METADATA in seen_opcodes
        assert wal.DELETE_TRIAL in seen_opcodes
