"""Runs the datastore conformance suite against both backends."""

from vizier_tpu.service import ram_datastore, sql_datastore

from . import datastore_test_lib


class TestRAMDataStore(datastore_test_lib.DataStoreConformance):
    def make_datastore(self):
        return ram_datastore.NestedDictRAMDataStore()


class TestSQLDataStore(datastore_test_lib.DataStoreConformance):
    def make_datastore(self):
        return sql_datastore.SQLDataStore("sqlite:///:memory:")


class TestSQLFileDataStore(datastore_test_lib.DataStoreConformance):
    def make_datastore(self):
        import tempfile
        import os

        path = os.path.join(tempfile.mkdtemp(), "vizier.db")
        return sql_datastore.SQLDataStore(f"sqlite:///{path}")

    def test_persistence_across_connections(self, tmp_path):
        import os

        url = f"sqlite:///{tmp_path}/persist.db"
        ds1 = sql_datastore.SQLDataStore(url)
        ds1.create_study(datastore_test_lib.make_study())
        ds2 = sql_datastore.SQLDataStore(url)
        assert ds2.load_study("owners/o/studies/s").display_name == "s"
