"""Runs the datastore conformance suite against both backends."""

from vizier_tpu.service import ram_datastore, sql_datastore

from . import datastore_test_lib


class TestRAMDataStore(datastore_test_lib.DataStoreConformance):
    def make_datastore(self):
        return ram_datastore.NestedDictRAMDataStore()


class TestSQLDataStore(datastore_test_lib.DataStoreConformance):
    def make_datastore(self):
        return sql_datastore.SQLDataStore("sqlite:///:memory:")


class TestSQLFileDataStore(datastore_test_lib.DataStoreConformance):
    def make_datastore(self):
        import tempfile
        import os

        path = os.path.join(tempfile.mkdtemp(), "vizier.db")
        return sql_datastore.SQLDataStore(f"sqlite:///{path}")

    def test_persistence_across_connections(self, tmp_path):
        import os

        url = f"sqlite:///{tmp_path}/persist.db"
        ds1 = sql_datastore.SQLDataStore(url)
        ds1.create_study(datastore_test_lib.make_study())
        ds2 = sql_datastore.SQLDataStore(url)
        assert ds2.load_study("owners/o/studies/s").display_name == "s"


class TestSQLDoneColumnMigration:
    def test_pre_done_schema_backfills(self, tmp_path):
        """A database created before the `done` column gains it on open,
        backfilled from the stored protos."""
        import sqlite3

        from vizier_tpu.service import resources, sql_datastore
        from vizier_tpu.service.protos import study_pb2, vizier_service_pb2
        from tests.service.datastore_test_lib import make_study

        path = str(tmp_path / "old.db")
        conn = sqlite3.connect(path)
        conn.executescript(
            """
            CREATE TABLE studies (name TEXT PRIMARY KEY, owner TEXT NOT NULL,
                                  blob BLOB NOT NULL);
            CREATE TABLE trials (name TEXT PRIMARY KEY, study TEXT NOT NULL,
                                 trial_id INTEGER NOT NULL, blob BLOB NOT NULL);
            CREATE TABLE suggestion_ops (name TEXT PRIMARY KEY,
                                         study TEXT NOT NULL,
                                         client_id TEXT NOT NULL,
                                         op_number INTEGER NOT NULL,
                                         blob BLOB NOT NULL);
            CREATE TABLE early_stopping_ops (name TEXT PRIMARY KEY,
                                             study TEXT NOT NULL,
                                             blob BLOB NOT NULL);
            """
        )
        study = make_study()
        conn.execute(
            "INSERT INTO studies (name, owner, blob) VALUES (?, ?, ?)",
            (study.name, "o", study.SerializeToString()),
        )
        for i, done in ((1, False), (2, True)):
            name = resources.SuggestionOperationResource("o", "s", "c", i).name
            op = vizier_service_pb2.Operation(name=name, done=done)
            conn.execute(
                "INSERT INTO suggestion_ops (name, study, client_id, op_number, blob)"
                " VALUES (?, ?, ?, ?, ?)",
                (name, study.name, "c", i, op.SerializeToString()),
            )
        conn.commit()
        conn.close()

        # Plus a pre-state-column trial whose state must backfill too.
        conn = sqlite3.connect(path)
        t = study_pb2.Trial(
            name=study.name + "/trials/7", id=7, state=study_pb2.Trial.SUCCEEDED
        )
        conn.execute(
            "INSERT INTO trials (name, study, trial_id, blob) VALUES (?, ?, ?, ?)",
            (t.name, study.name, 7, t.SerializeToString()),
        )
        conn.commit()
        conn.close()

        ds = sql_datastore.SQLDataStore(f"sqlite:///{path}")
        undone = ds.list_suggestion_operations(study.name, "c", done=False)
        assert [o.name.rsplit("/", 1)[-1] for o in undone] == ["1"]
        assert len(ds.list_suggestion_operations(study.name, "c", done=True)) == 1
        assert [
            x.id
            for x in ds.list_trials(
                study.name, states=(study_pb2.Trial.SUCCEEDED,)
            )
        ] == [7]

    def test_crash_after_alter_rebackfills(self, tmp_path):
        """A crash between the autocommitted ALTER and the backfill leaves
        the column present with all-zero flags; user_version (still 0)
        must trigger a re-backfill on the next open."""
        import sqlite3

        from vizier_tpu.service import resources, sql_datastore
        from vizier_tpu.service.protos import vizier_service_pb2
        from tests.service.datastore_test_lib import make_study

        path = str(tmp_path / "crashed.db")
        conn = sqlite3.connect(path)
        conn.executescript(sql_datastore._SCHEMA)  # has the column already
        study = make_study()
        conn.execute(
            "INSERT INTO studies (name, owner, blob) VALUES (?, ?, ?)",
            (study.name, "o", study.SerializeToString()),
        )
        name = resources.SuggestionOperationResource("o", "s", "c", 1).name
        op = vizier_service_pb2.Operation(name=name, done=True)
        # Simulated crash state: blob says done, column says 0, version 0.
        conn.execute(
            "INSERT INTO suggestion_ops (name, study, client_id, op_number, done, blob)"
            " VALUES (?, ?, ?, ?, 0, ?)",
            (name, study.name, "c", 1, op.SerializeToString()),
        )
        conn.commit()
        conn.close()

        ds = sql_datastore.SQLDataStore(f"sqlite:///{path}")
        assert ds.list_suggestion_operations(study.name, "c", done=False) == []
        assert len(ds.list_suggestion_operations(study.name, "c", done=True)) == 1
