"""Multi-client service stress over REAL gRPC.

Parity with the reference's ``performance_test.py:44-89`` topology: one
``DefaultVizierServer``, N thread-pool clients each running its own
suggest→complete loop against one shared study, wall-time logged (the
reference asserts nothing beyond completion either — the invariants checked
here are stronger: trial-count accounting and per-worker trial disjointness).
"""

import concurrent.futures as cf
import time

import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.service import clients as clients_lib
from vizier_tpu.service import vizier_server


@pytest.fixture(scope="module")
def server():
    return vizier_server.DefaultVizierServer(host="localhost")


def _study_config():
    sc = vz.StudyConfig()
    sc.search_space.root.add_float_param("x", 0.0, 1.0)
    sc.search_space.root.add_float_param("y", 0.0, 1.0)
    sc.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MINIMIZE)
    )
    sc.algorithm = "RANDOM_SEARCH"
    return sc


@pytest.mark.parametrize(
    "num_clients,num_trials_each",
    [(1, 10), (2, 10), (10, 5), (25, 3)],
)
def test_multi_client_suggest_complete_over_grpc(
    server, num_clients, num_trials_each
):
    clients_lib.environment_variables.server_endpoint = server.endpoint
    try:
        study = clients_lib.Study.from_study_config(
            _study_config(),
            owner="perf",
            study_id=f"stress-{num_clients}x{num_trials_each}",
        )

        def worker(worker_id: int):
            my_ids = []
            for _ in range(num_trials_each):
                (trial,) = study.suggest(count=1, client_id=f"worker_{worker_id}")
                x = trial.parameters["x"]
                y = trial.parameters["y"]
                trial.complete(
                    vz.Measurement(
                        metrics={"obj": (float(x) - 0.3) ** 2 + (float(y) - 0.7) ** 2}
                    )
                )
                my_ids.append(trial.id)
            return my_ids

        t0 = time.time()
        with cf.ThreadPoolExecutor(num_clients) as ex:
            per_worker = list(ex.map(worker, range(num_clients)))
        elapsed = time.time() - t0

        all_ids = [tid for ids in per_worker for tid in ids]
        # Every worker's completions are distinct trials — no cross-worker
        # reuse, no lost updates under the per-study locks.
        assert len(set(all_ids)) == len(all_ids) == num_clients * num_trials_each
        completed = list(
            study.trials(vz.TrialFilter(status=[vz.TrialStatus.COMPLETED]))
        )
        assert len(completed) == num_clients * num_trials_each
        print(
            f"[perf] {num_clients} clients x {num_trials_each} trials over gRPC: "
            f"{elapsed:.2f}s ({len(all_ids) / elapsed:.1f} trials/s)"
        )
    finally:
        clients_lib.environment_variables.server_endpoint = clients_lib.NO_ENDPOINT


def test_distributed_pythia_topology_under_load(server):
    """Split Vizier/Pythia servers (two gRPC processes' worth of servicers),
    several concurrent workers using an algorithmic policy."""
    dist = vizier_server.DistributedPythiaVizierServer(host="localhost")
    clients_lib.environment_variables.server_endpoint = dist.endpoint
    try:
        sc = _study_config()
        sc.algorithm = "QUASI_RANDOM_SEARCH"
        study = clients_lib.Study.from_study_config(
            sc, owner="perf", study_id="dist-stress"
        )

        def worker(worker_id: int):
            for _ in range(3):
                (trial,) = study.suggest(count=1, client_id=f"w{worker_id}")
                trial.complete(vz.Measurement(metrics={"obj": float(trial.id)}))
            return worker_id

        with cf.ThreadPoolExecutor(4) as ex:
            done = list(ex.map(worker, range(4)))
        assert sorted(done) == [0, 1, 2, 3]
        completed = list(
            study.trials(vz.TrialFilter(status=[vz.TrialStatus.COMPLETED]))
        )
        assert len(completed) == 12
    finally:
        clients_lib.environment_variables.server_endpoint = clients_lib.NO_ENDPOINT
