"""Multi-client service stress over REAL gRPC.

Parity with the reference's ``performance_test.py:44-89`` topology: one
``DefaultVizierServer``, N thread-pool clients each running its own
suggest→complete loop against one shared study, wall-time logged (the
reference asserts nothing beyond completion either — the invariants checked
here are stronger: trial-count accounting and per-worker trial disjointness).
"""

import concurrent.futures as cf

import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.service import clients as clients_lib
from vizier_tpu.service import vizier_server


@pytest.fixture(scope="module")
def server():
    return vizier_server.DefaultVizierServer(host="localhost")


from vizier_tpu.testing import stress

_study_config = stress.stress_study_config


@pytest.mark.parametrize(
    "num_clients,num_trials_each",
    [(1, 10), (2, 10), (10, 5), (25, 3)],
)
def test_multi_client_suggest_complete_over_grpc(
    server, num_clients, num_trials_each
):
    clients_lib.environment_variables.server_endpoint = server.endpoint
    try:
        study = clients_lib.Study.from_study_config(
            _study_config(),
            owner="perf",
            study_id=f"stress-{num_clients}x{num_trials_each}",
        )
        # ONE shared topology with tools/service_throughput.py.
        elapsed, completed, per_worker = stress.run_stress_round(
            study, num_clients, num_trials_each
        )
        all_ids = [tid for ids in per_worker for tid in ids]
        # Every worker's completions are distinct trials — no cross-worker
        # reuse, no lost updates under the per-study locks.
        assert len(set(all_ids)) == len(all_ids) == num_clients * num_trials_each
        assert completed == num_clients * num_trials_each
        print(
            f"[perf] {num_clients} clients x {num_trials_each} trials over gRPC: "
            f"{elapsed:.2f}s ({len(all_ids) / elapsed:.1f} trials/s)"
        )
    finally:
        clients_lib.environment_variables.server_endpoint = clients_lib.NO_ENDPOINT


def test_distributed_pythia_topology_under_load(server):
    """Split Vizier/Pythia servers (two gRPC processes' worth of servicers),
    several concurrent workers using an algorithmic policy."""
    dist = vizier_server.DistributedPythiaVizierServer(host="localhost")
    clients_lib.environment_variables.server_endpoint = dist.endpoint
    try:
        sc = _study_config()
        sc.algorithm = "QUASI_RANDOM_SEARCH"
        study = clients_lib.Study.from_study_config(
            sc, owner="perf", study_id="dist-stress"
        )

        def worker(worker_id: int):
            for _ in range(3):
                (trial,) = study.suggest(count=1, client_id=f"w{worker_id}")
                trial.complete(vz.Measurement(metrics={"obj": float(trial.id)}))
            return worker_id

        with cf.ThreadPoolExecutor(4) as ex:
            done = list(ex.map(worker, range(4)))
        assert sorted(done) == [0, 1, 2, 3]
        completed = list(
            study.trials(vz.TrialFilter(status=[vz.TrialStatus.COMPLETED]))
        )
        assert len(completed) == 12
    finally:
        clients_lib.environment_variables.server_endpoint = clients_lib.NO_ENDPOINT


class TestSharedChannelLifecycle:
    def test_failed_ready_wait_evicts_entry_and_retries_fail_fast(self):
        from vizier_tpu.service import grpc_stubs

        dead = "127.0.0.1:1"  # nothing listens on port 1
        for _ in range(2):  # retry must re-attempt readiness, not hang
            with pytest.raises(Exception):
                grpc_stubs.create_vizier_stub(dead, timeout=0.5)
            assert dead not in grpc_stubs._CHANNELS

    def test_channel_closed_and_evicted_on_server_stop(self):
        from vizier_tpu.service import grpc_stubs

        srv = vizier_server.DefaultVizierServer(host="localhost")
        grpc_stubs.create_vizier_stub(srv.endpoint)
        assert srv.endpoint in grpc_stubs._CHANNELS
        srv.stop(0)
        assert srv.endpoint not in grpc_stubs._CHANNELS

    def test_broken_cached_channel_evicted_and_reconnected(self):
        from vizier_tpu.service import grpc_stubs

        srv = vizier_server.DefaultVizierServer(host="localhost")
        try:
            stub = grpc_stubs.create_vizier_stub(srv.endpoint)
            entry = grpc_stubs._CHANNELS[srv.endpoint]
            # Simulate a server dying WITHOUT close_channel(): the watcher
            # flags the entry; the next stub creation must not serve it.
            entry.broken = True
            stub2 = grpc_stubs.create_vizier_stub(srv.endpoint)
            new_entry = grpc_stubs._CHANNELS[srv.endpoint]
            assert new_entry is not entry
            assert not new_entry.broken
            assert stub2 is not stub  # fresh stub on the fresh channel
        finally:
            srv.stop(0)

    def test_only_shutdown_marks_entry_broken(self):
        import grpc as grpc_lib

        from vizier_tpu.service import grpc_stubs

        srv = vizier_server.DefaultVizierServer(host="localhost")
        try:
            grpc_stubs.create_vizier_stub(srv.endpoint)
            entry = grpc_stubs._CHANNELS[srv.endpoint]
            assert not entry.broken
            # TRANSIENT_FAILURE is a normal reconnect state (server restart
            # blip): it must NOT flag the channel — evicting on it would
            # close() the channel underneath every stub sharing it while
            # gRPC's auto-reconnect would have recovered.
            entry._watch(grpc_lib.ChannelConnectivity.TRANSIENT_FAILURE)
            assert not entry.broken
            entry._watch(grpc_lib.ChannelConnectivity.READY)
            assert not entry.broken
            # Only SHUTDOWN (the channel is permanently dead) flags it.
            entry._watch(grpc_lib.ChannelConnectivity.SHUTDOWN)
            assert entry.broken
        finally:
            srv.stop(0)


class TestSuggestScalesConstantTime:
    """Regression gate for the round-5 open/undone indexes: the per-suggest
    datastore work must not grow with completed history. Counted in proto
    copies (deterministic) rather than wall time (flaky)."""

    def test_copies_per_suggest_independent_of_history(self, monkeypatch):
        from tests.service.test_service import _make_servicer
        from vizier_tpu.service import proto_converters as pcv
        from vizier_tpu.service import ram_datastore
        from vizier_tpu.service.protos import study_pb2, vizier_service_pb2 as V
        from vizier_tpu.testing import stress

        servicer = _make_servicer()
        study = pcv.study_to_proto(
            stress.stress_study_config(), "owners/p/studies/s"
        )
        servicer.CreateStudy(V.CreateStudyRequest(parent="owners/p", study=study))
        name = "owners/p/studies/s"

        def round_():
            op = servicer.SuggestTrials(
                V.SuggestTrialsRequest(
                    parent=name, suggestion_count=1, client_id="w"
                )
            )
            assert not op.error, op.error
            t = op.response.trials[0]
            m = study_pb2.Measurement()
            m.metrics.add(name="obj", value=0.5)
            servicer.CompleteTrial(
                V.CompleteTrialRequest(name=t.name, final_measurement=m)
            )

        counter = {"copies": 0}
        real_copy = ram_datastore._copy

        def counting_copy(proto):
            counter["copies"] += 1
            return real_copy(proto)

        monkeypatch.setattr(ram_datastore, "_copy", counting_copy)

        def copies_for_round():
            counter["copies"] = 0
            round_()
            return counter["copies"]

        baseline = max(copies_for_round() for _ in range(3))
        for _ in range(300):  # grow the completed history
            round_()
        at_scale = max(copies_for_round() for _ in range(3))
        # Identical datastore work regardless of history size; allow +2
        # copies of slack for incidental bookkeeping.
        assert at_scale <= baseline + 2, (baseline, at_scale)
