"""Datastore conformance suite: one behavioral contract, every backend.

Parity with the reference's ``datastore_test_lib.py`` pattern: subclasses
provide ``make_datastore()`` and inherit every test.
"""

import pytest

from vizier_tpu.service import datastore as datastore_lib
from vizier_tpu.service import resources
from vizier_tpu.service.protos import key_value_pb2, study_pb2, vizier_service_pb2


def make_study(owner="o", study="s") -> study_pb2.Study:
    proto = study_pb2.Study(
        name=resources.StudyResource(owner, study).name, display_name=study
    )
    proto.state = study_pb2.Study.ACTIVE
    p = proto.study_spec.parameters.add()
    p.name = "x"
    p.double_range.min_value = 0.0
    p.double_range.max_value = 1.0
    m = proto.study_spec.metrics.add()
    m.name = "obj"
    m.goal = study_pb2.MetricSpec.MAXIMIZE
    proto.study_spec.algorithm = "RANDOM_SEARCH"
    return proto


def make_trial(owner="o", study="s", trial_id=1) -> study_pb2.Trial:
    proto = study_pb2.Trial(
        name=resources.StudyResource(owner, study).trial_resource(trial_id).name,
        id=trial_id,
        state=study_pb2.Trial.ACTIVE,
    )
    a = proto.parameters.add()
    a.name = "x"
    a.value.double_value = 0.5
    return proto


class DataStoreConformance:
    """Mixin: subclasses define ``make_datastore``."""

    def make_datastore(self) -> datastore_lib.DataStore:
        raise NotImplementedError

    @pytest.fixture
    def ds(self):
        return self.make_datastore()

    # -- studies -----------------------------------------------------------

    def test_study_crud(self, ds):
        study = make_study()
        assert ds.create_study(study) == study.name
        loaded = ds.load_study(study.name)
        assert loaded.study_spec.algorithm == "RANDOM_SEARCH"
        loaded.study_spec.algorithm = "QUASI_RANDOM_SEARCH"
        ds.update_study(loaded)
        assert ds.load_study(study.name).study_spec.algorithm == "QUASI_RANDOM_SEARCH"
        assert len(ds.list_studies("owners/o")) == 1
        ds.delete_study(study.name)
        with pytest.raises(datastore_lib.NotFoundError):
            ds.load_study(study.name)

    def test_create_duplicate_study_rejected(self, ds):
        ds.create_study(make_study())
        with pytest.raises(datastore_lib.AlreadyExistsError):
            ds.create_study(make_study())

    def test_load_missing_study(self, ds):
        with pytest.raises(datastore_lib.NotFoundError):
            ds.load_study("owners/o/studies/none")

    def test_stored_protos_are_isolated(self, ds):
        study = make_study()
        ds.create_study(study)
        study.study_spec.algorithm = "MUTATED"
        assert ds.load_study(study.name).study_spec.algorithm == "RANDOM_SEARCH"
        loaded = ds.load_study(study.name)
        loaded.study_spec.algorithm = "MUTATED2"
        assert ds.load_study(study.name).study_spec.algorithm == "RANDOM_SEARCH"

    # -- trials ------------------------------------------------------------

    def test_trial_crud(self, ds):
        ds.create_study(make_study())
        t = make_trial(trial_id=1)
        ds.create_trial(t)
        assert ds.get_trial(t.name).id == 1
        t.state = study_pb2.Trial.SUCCEEDED
        ds.update_trial(t)
        assert ds.get_trial(t.name).state == study_pb2.Trial.SUCCEEDED
        ds.create_trial(make_trial(trial_id=2))
        assert [x.id for x in ds.list_trials("owners/o/studies/s")] == [1, 2]
        assert ds.max_trial_id("owners/o/studies/s") == 2
        ds.delete_trial(t.name)
        assert [x.id for x in ds.list_trials("owners/o/studies/s")] == [2]

    def test_trial_requires_study(self, ds):
        with pytest.raises(datastore_lib.NotFoundError):
            ds.create_trial(make_trial())

    def test_max_trial_id_empty(self, ds):
        ds.create_study(make_study())
        assert ds.max_trial_id("owners/o/studies/s") == 0

    def test_max_trial_id_recomputes_after_deleting_max(self, ds):
        ds.create_study(make_study())
        for i in (1, 2, 5):
            ds.create_trial(make_trial(trial_id=i))
        assert ds.max_trial_id("owners/o/studies/s") == 5
        ds.delete_trial(make_trial(trial_id=5).name)
        assert ds.max_trial_id("owners/o/studies/s") == 2
        ds.delete_trial(make_trial(trial_id=1).name)  # non-max delete
        assert ds.max_trial_id("owners/o/studies/s") == 2

    def test_list_trials_state_prefilter(self, ds):
        """The storage-level states filter (the suggest hot path) agrees
        with the proto field, tracks updates, and composes as a tuple."""
        ds.create_study(make_study())
        study = "owners/o/studies/s"
        states = [
            study_pb2.Trial.ACTIVE,
            study_pb2.Trial.SUCCEEDED,
            study_pb2.Trial.REQUESTED,
            study_pb2.Trial.SUCCEEDED,
            study_pb2.Trial.ACTIVE,
        ]
        for i, st in enumerate(states, start=1):
            t = make_trial(trial_id=i)
            t.state = st
            ds.create_trial(t)
        open_rows = ds.list_trials(
            study, states=(study_pb2.Trial.ACTIVE, study_pb2.Trial.REQUESTED)
        )
        assert [t.id for t in open_rows] == [1, 3, 5]
        done_rows = ds.list_trials(study, states=(study_pb2.Trial.SUCCEEDED,))
        assert [t.id for t in done_rows] == [2, 4]
        assert len(ds.list_trials(study)) == 5  # unfiltered unchanged
        # State updates move rows between filters.
        t = ds.get_trial(open_rows[0].name)
        t.state = study_pb2.Trial.SUCCEEDED
        ds.update_trial(t)
        assert [x.id for x in ds.list_trials(
            study, states=(study_pb2.Trial.SUCCEEDED,)
        )] == [1, 2, 4]

    # -- suggestion operations --------------------------------------------

    def test_suggestion_operations(self, ds):
        ds.create_study(make_study())
        name = resources.SuggestionOperationResource("o", "s", "client0", 1).name
        op = vizier_service_pb2.Operation(name=name)
        ds.create_suggestion_operation(op)
        assert not ds.get_suggestion_operation(name).done
        op.done = True
        ds.update_suggestion_operation(op)
        assert ds.get_suggestion_operation(name).done
        assert ds.max_suggestion_operation_number("owners/o/studies/s", "client0") == 1
        assert ds.max_suggestion_operation_number("owners/o/studies/s", "other") == 0
        unfinished = ds.list_suggestion_operations(
            "owners/o/studies/s", "client0", lambda o: not o.done
        )
        assert unfinished == []

    def test_suggestion_operation_done_prefilter(self, ds):
        """The storage-level `done` filter (the hot dedup path) agrees with
        the proto field across mixed done/undone histories."""
        ds.create_study(make_study())
        study = "owners/o/studies/s"
        for i in range(1, 6):
            name = resources.SuggestionOperationResource("o", "s", "c", i).name
            op = vizier_service_pb2.Operation(name=name, done=(i % 2 == 0))
            ds.create_suggestion_operation(op)
        undone = ds.list_suggestion_operations(study, "c", done=False)
        assert [o.name.rsplit("/", 1)[-1] for o in undone] == ["1", "3", "5"]
        finished = ds.list_suggestion_operations(study, "c", done=True)
        assert len(finished) == 2 and all(o.done for o in finished)
        # done= composes with filter_fn and flips on update.
        op = ds.get_suggestion_operation(undone[0].name)
        op.done = True
        ds.update_suggestion_operation(op)
        assert len(ds.list_suggestion_operations(study, "c", done=False)) == 2
        assert (
            ds.list_suggestion_operations(
                study, "c", lambda o: o.name.endswith("5"), done=False
            )[0].name.endswith("5")
        )

    # -- early stopping ops ------------------------------------------------

    def test_early_stopping_operations(self, ds):
        ds.create_study(make_study())
        ds.create_trial(make_trial(trial_id=1))
        name = resources.EarlyStoppingOperationResource("o", "s", 1).name
        op = vizier_service_pb2.EarlyStoppingOperation(name=name, should_stop=True)
        ds.create_early_stopping_operation(op)
        assert ds.get_early_stopping_operation(name).should_stop
        op.status = vizier_service_pb2.EarlyStoppingOperation.DONE
        ds.update_early_stopping_operation(op)
        assert (
            ds.get_early_stopping_operation(name).status
            == vizier_service_pb2.EarlyStoppingOperation.DONE
        )

    # -- metadata ----------------------------------------------------------

    def test_update_metadata(self, ds):
        ds.create_study(make_study())
        ds.create_trial(make_trial(trial_id=1))
        study_kv = key_value_pb2.KeyValue(key="k", ns=":a", string_value="v")
        trial_kv = key_value_pb2.KeyValue(key="tk", ns="", double_value=2.5)
        ds.update_metadata("owners/o/studies/s", [study_kv], [(1, trial_kv)])
        study = ds.load_study("owners/o/studies/s")
        assert study.study_spec.metadata[0].string_value == "v"
        trial = ds.get_trial("owners/o/studies/s/trials/1")
        assert trial.metadata[0].double_value == 2.5
        # Same (ns, key) overwrites rather than duplicating.
        study_kv2 = key_value_pb2.KeyValue(key="k", ns=":a", string_value="v2")
        ds.update_metadata("owners/o/studies/s", [study_kv2], [])
        study = ds.load_study("owners/o/studies/s")
        assert len(study.study_spec.metadata) == 1
        assert study.study_spec.metadata[0].string_value == "v2"

    # -- error-path breadth (reference assert*API coverage) -----------------

    def test_create_duplicate_trial_rejected(self, ds):
        ds.create_study(make_study())
        ds.create_trial(make_trial(trial_id=1))
        with pytest.raises(datastore_lib.AlreadyExistsError):
            ds.create_trial(make_trial(trial_id=1))

    def test_get_missing_trial(self, ds):
        ds.create_study(make_study())
        with pytest.raises(datastore_lib.NotFoundError):
            ds.get_trial("owners/o/studies/s/trials/99")

    def test_update_missing_trial(self, ds):
        ds.create_study(make_study())
        with pytest.raises(datastore_lib.NotFoundError):
            ds.update_trial(make_trial(trial_id=99))

    def test_delete_missing_trial(self, ds):
        ds.create_study(make_study())
        ds.create_trial(make_trial(trial_id=1))
        ds.delete_trial("owners/o/studies/s/trials/1")
        with pytest.raises(datastore_lib.NotFoundError):
            ds.delete_trial("owners/o/studies/s/trials/1")  # already deleted

    def test_trial_ops_on_missing_study(self, ds):
        with pytest.raises(datastore_lib.NotFoundError):
            ds.max_trial_id("owners/o/studies/none")
        with pytest.raises(datastore_lib.NotFoundError):
            ds.list_trials("owners/o/studies/none")

    def test_trial_pass_by_value(self, ds):
        ds.create_study(make_study())
        t = make_trial(trial_id=1)
        ds.create_trial(t)
        loaded = ds.get_trial(t.name)
        assert loaded == t and loaded is not t
        loaded.state = study_pb2.Trial.INFEASIBLE
        assert ds.get_trial(t.name).state == study_pb2.Trial.ACTIVE

    def test_create_duplicate_suggestion_op_rejected(self, ds):
        ds.create_study(make_study())
        name = resources.SuggestionOperationResource("o", "s", "c", 1).name
        ds.create_suggestion_operation(vizier_service_pb2.Operation(name=name))
        with pytest.raises(datastore_lib.AlreadyExistsError):
            ds.create_suggestion_operation(vizier_service_pb2.Operation(name=name))

    def test_update_missing_suggestion_op(self, ds):
        ds.create_study(make_study())
        name = resources.SuggestionOperationResource("o", "s", "c", 7).name
        with pytest.raises(datastore_lib.NotFoundError):
            ds.update_suggestion_operation(vizier_service_pb2.Operation(name=name))

    def test_get_missing_suggestion_op(self, ds):
        ds.create_study(make_study())
        name = resources.SuggestionOperationResource("o", "s", "c", 7).name
        with pytest.raises(datastore_lib.NotFoundError):
            ds.get_suggestion_operation(name)

    def test_multi_owner_isolation(self, ds):
        ds.create_study(make_study(owner="alice", study="s1"))
        ds.create_study(make_study(owner="bob", study="s1"))
        ds.create_trial(make_trial(owner="alice", study="s1", trial_id=1))
        assert len(ds.list_trials("owners/alice/studies/s1")) == 1
        assert len(ds.list_trials("owners/bob/studies/s1")) == 0
        assert len(ds.list_studies("owners/alice")) == 1

    def test_list_studies_multiple(self, ds):
        ds.create_study(make_study(study="s1"))
        ds.create_study(make_study(study="s2"))
        names = {s.display_name for s in ds.list_studies("owners/o")}
        assert names == {"s1", "s2"}

    def test_delete_study_cascades(self, ds):
        ds.create_study(make_study())
        ds.create_trial(make_trial(trial_id=1))
        name = resources.SuggestionOperationResource("o", "s", "c", 1).name
        ds.create_suggestion_operation(vizier_service_pb2.Operation(name=name))
        ds.delete_study("owners/o/studies/s")
        ds.create_study(make_study())
        assert ds.list_trials("owners/o/studies/s") == []
        assert ds.max_suggestion_operation_number("owners/o/studies/s", "c") == 0
