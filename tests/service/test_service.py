"""Service + client tests: in-process, gRPC, distributed, multi-client."""

import threading

import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.service import clients as clients_lib
from vizier_tpu.service import proto_converters as pc
from vizier_tpu.service import pythia_service, vizier_client, vizier_service
from vizier_tpu.service.protos import study_pb2, vizier_service_pb2
from vizier_tpu.service.vizier_server import DefaultVizierServer, DistributedPythiaVizierServer


def _config(algorithm="RANDOM_SEARCH"):
    config = vz.StudyConfig(algorithm=algorithm)
    root = config.search_space.root
    root.add_float_param("x", 0.0, 1.0)
    root.add_categorical_param("c", ["a", "b"])
    config.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return config


def _make_servicer():
    servicer = vizier_service.VizierServicer()
    pythia = pythia_service.PythiaServicer(servicer)
    servicer.set_pythia(pythia)
    return servicer


class TestProtoConverters:
    def test_study_config_roundtrip(self):
        config = _config()
        config.search_space.root.add_int_param("n", 1, 5)
        config.search_space.root.add_discrete_param("d", [0.5, 1.5])
        sel = config.search_space.root.add_categorical_param("model", ["m1", "m2"])
        sel.select_values(["m2"]).add_float_param("lr", 1e-4, 1e-1, scale_type=vz.ScaleType.LOG)
        config.metadata.ns("alg")["state"] = b"\x00\x01"
        config.metric_information.append(
            vz.MetricInformation(name="safe", goal=vz.ObjectiveMetricGoal.MINIMIZE, safety_threshold=0.7)
        )
        proto = pc.study_config_to_proto(config)
        back = pc.study_config_from_proto(proto)
        assert back.search_space.parameter_names() == config.search_space.parameter_names()
        assert back.search_space.get("model").children[0].name == "lr"
        assert back.metric_information.get("safe").safety_threshold == 0.7
        assert back.metadata.ns("alg")["state"] == b"\x00\x01"
        assert back.algorithm == "RANDOM_SEARCH"

    def test_trial_roundtrip(self):
        t = vz.Trial(id=3, parameters={"x": 0.25, "c": "b", "n": 2})
        t.metadata.ns("m")["k"] = "v"
        t.measurements.append(vz.Measurement(metrics={"obj": 0.5}, steps=1))
        t.complete(vz.Measurement(metrics={"obj": vz.Metric(0.9, std=0.1)}))
        back = pc.trial_from_proto(pc.trial_to_proto(t))
        assert back.id == 3
        assert back.parameters.get_value("x") == 0.25
        assert back.parameters.get_value("n") == 2
        assert back.status == vz.TrialStatus.COMPLETED
        assert back.final_measurement.metrics["obj"].value == 0.9
        assert back.final_measurement.metrics["obj"].std == 0.1
        assert len(back.measurements) == 1
        assert back.metadata.ns("m")["k"] == "v"

    def test_infeasible_trial_roundtrip(self):
        t = vz.Trial(id=1)
        t.complete(infeasibility_reason="nan")
        back = pc.trial_from_proto(pc.trial_to_proto(t))
        assert back.infeasible
        assert back.infeasibility_reason == "nan"


class TestVizierServicer:
    def test_suggest_random(self):
        servicer = _make_servicer()
        study = pc.study_to_proto(_config(), "owners/o/studies/s")
        servicer.CreateStudy(
            vizier_service_pb2.CreateStudyRequest(parent="owners/o", study=study)
        )
        op = servicer.SuggestTrials(
            vizier_service_pb2.SuggestTrialsRequest(
                parent="owners/o/studies/s", suggestion_count=3, client_id="w0"
            )
        )
        assert op.done and not op.error
        assert len(op.response.trials) == 3
        assert all(t.state == study_pb2.Trial.ACTIVE for t in op.response.trials)

    def test_active_trial_reuse_per_client(self):
        """The worker-failover contract: re-request returns the same trials."""
        servicer = _make_servicer()
        study = pc.study_to_proto(_config(), "owners/o/studies/s")
        servicer.CreateStudy(
            vizier_service_pb2.CreateStudyRequest(parent="owners/o", study=study)
        )
        request = vizier_service_pb2.SuggestTrialsRequest(
            parent="owners/o/studies/s", suggestion_count=2, client_id="w0"
        )
        first = servicer.SuggestTrials(request)
        again = servicer.SuggestTrials(request)
        assert [t.id for t in first.response.trials] == [
            t.id for t in again.response.trials
        ]
        # A different client gets different trials.
        other = servicer.SuggestTrials(
            vizier_service_pb2.SuggestTrialsRequest(
                parent="owners/o/studies/s", suggestion_count=2, client_id="w1"
            )
        )
        assert set(t.id for t in other.response.trials).isdisjoint(
            t.id for t in first.response.trials
        )

    def test_pythia_error_captured_in_operation(self):
        servicer = _make_servicer()
        config = _config(algorithm="NO_SUCH_ALGORITHM")
        study = pc.study_to_proto(config, "owners/o/studies/s")
        servicer.CreateStudy(
            vizier_service_pb2.CreateStudyRequest(parent="owners/o", study=study)
        )
        op = servicer.SuggestTrials(
            vizier_service_pb2.SuggestTrialsRequest(
                parent="owners/o/studies/s", suggestion_count=1, client_id="w0"
            )
        )
        assert op.done and op.error

    def test_complete_trial_immutability(self):
        servicer = _make_servicer()
        study = pc.study_to_proto(_config(), "owners/o/studies/s")
        servicer.CreateStudy(
            vizier_service_pb2.CreateStudyRequest(parent="owners/o", study=study)
        )
        op = servicer.SuggestTrials(
            vizier_service_pb2.SuggestTrialsRequest(
                parent="owners/o/studies/s", suggestion_count=1, client_id="w0"
            )
        )
        name = op.response.trials[0].name
        request = vizier_service_pb2.CompleteTrialRequest(name=name)
        request.final_measurement.metrics.add().name = "obj"
        request.final_measurement.metrics[0].value = 1.0
        servicer.CompleteTrial(request)
        with pytest.raises(ValueError):
            servicer.CompleteTrial(request)

    def test_complete_promotes_last_measurement(self):
        servicer = _make_servicer()
        study = pc.study_to_proto(_config(), "owners/o/studies/s")
        servicer.CreateStudy(
            vizier_service_pb2.CreateStudyRequest(parent="owners/o", study=study)
        )
        trial = study_pb2.Trial()
        created = servicer.CreateTrial(
            vizier_service_pb2.CreateTrialRequest(
                parent="owners/o/studies/s", trial=trial
            )
        )
        add = vizier_service_pb2.AddTrialMeasurementRequest(trial_name=created.name)
        add.measurement.metrics.add().name = "obj"
        add.measurement.metrics[0].value = 0.7
        servicer.AddTrialMeasurement(add)
        done = servicer.CompleteTrial(
            vizier_service_pb2.CompleteTrialRequest(name=created.name)
        )
        assert done.state == study_pb2.Trial.SUCCEEDED
        assert done.final_measurement.metrics[0].value == 0.7

    def test_complete_without_measurement_is_infeasible(self):
        servicer = _make_servicer()
        study = pc.study_to_proto(_config(), "owners/o/studies/s")
        servicer.CreateStudy(
            vizier_service_pb2.CreateStudyRequest(parent="owners/o", study=study)
        )
        created = servicer.CreateTrial(
            vizier_service_pb2.CreateTrialRequest(
                parent="owners/o/studies/s", trial=study_pb2.Trial()
            )
        )
        done = servicer.CompleteTrial(
            vizier_service_pb2.CompleteTrialRequest(name=created.name)
        )
        assert done.state == study_pb2.Trial.INFEASIBLE

    def test_list_optimal_trials_single_objective(self):
        servicer = _make_servicer()
        study = pc.study_to_proto(_config(), "owners/o/studies/s")
        servicer.CreateStudy(
            vizier_service_pb2.CreateStudyRequest(parent="owners/o", study=study)
        )
        for value in (0.2, 0.9, 0.5):
            created = servicer.CreateTrial(
                vizier_service_pb2.CreateTrialRequest(
                    parent="owners/o/studies/s", trial=study_pb2.Trial()
                )
            )
            request = vizier_service_pb2.CompleteTrialRequest(name=created.name)
            request.final_measurement.metrics.add().name = "obj"
            request.final_measurement.metrics[0].value = value
            servicer.CompleteTrial(request)
        optimal = servicer.ListOptimalTrials(
            vizier_service_pb2.ListOptimalTrialsRequest(parent="owners/o/studies/s")
        )
        assert len(optimal.optimal_trials) == 1
        assert optimal.optimal_trials[0].final_measurement.metrics[0].value == 0.9

    def test_list_optimal_trials_pareto(self):
        config = vz.StudyConfig(algorithm="RANDOM_SEARCH")
        config.search_space.root.add_float_param("x", 0.0, 1.0)
        config.metric_information.append(
            vz.MetricInformation(name="m1", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
        config.metric_information.append(
            vz.MetricInformation(name="m2", goal=vz.ObjectiveMetricGoal.MINIMIZE)
        )
        servicer = _make_servicer()
        study = pc.study_to_proto(config, "owners/o/studies/s")
        servicer.CreateStudy(
            vizier_service_pb2.CreateStudyRequest(parent="owners/o", study=study)
        )
        # (m1, m2): (1, 1) and (2, 2) are non-dominated; (0.5, 3) is dominated.
        points = [(1.0, 1.0), (2.0, 2.0), (0.5, 3.0)]
        for m1, m2 in points:
            created = servicer.CreateTrial(
                vizier_service_pb2.CreateTrialRequest(
                    parent="owners/o/studies/s", trial=study_pb2.Trial()
                )
            )
            request = vizier_service_pb2.CompleteTrialRequest(name=created.name)
            a = request.final_measurement.metrics.add()
            a.name, a.value = "m1", m1
            b = request.final_measurement.metrics.add()
            b.name, b.value = "m2", m2
            servicer.CompleteTrial(request)
        optimal = servicer.ListOptimalTrials(
            vizier_service_pb2.ListOptimalTrialsRequest(parent="owners/o/studies/s")
        )
        assert len(optimal.optimal_trials) == 2

    def test_early_stopping_no_config_never_stops(self):
        servicer = _make_servicer()
        study = pc.study_to_proto(_config(), "owners/o/studies/s")
        servicer.CreateStudy(
            vizier_service_pb2.CreateStudyRequest(parent="owners/o", study=study)
        )
        op = servicer.SuggestTrials(
            vizier_service_pb2.SuggestTrialsRequest(
                parent="owners/o/studies/s", suggestion_count=1, client_id="w0"
            )
        )
        response = servicer.CheckTrialEarlyStoppingState(
            vizier_service_pb2.CheckTrialEarlyStoppingStateRequest(
                trial_name=op.response.trials[0].name
            )
        )
        assert response.should_stop is False

    def test_early_stopping_flow(self):
        """Median rule: a clearly-lagging curve gets stopped."""
        servicer = _make_servicer()
        config = _config()
        config.automated_stopping_config = vz.AutomatedStoppingConfig(min_num_trials=3)
        study = pc.study_to_proto(config, "owners/o/studies/s")
        servicer.CreateStudy(
            vizier_service_pb2.CreateStudyRequest(parent="owners/o", study=study)
        )

        def make_trial_with_curve(values):
            created = servicer.CreateTrial(
                vizier_service_pb2.CreateTrialRequest(
                    parent="owners/o/studies/s", trial=study_pb2.Trial()
                )
            )
            for step, v in enumerate(values, start=1):
                add = vizier_service_pb2.AddTrialMeasurementRequest(
                    trial_name=created.name
                )
                m = add.measurement
                m.steps = step
                metric = m.metrics.add()
                metric.name, metric.value = "obj", v
                servicer.AddTrialMeasurement(add)
            return created.name

        # Three healthy curves, one lagging curve (MAXIMIZE).
        for _ in range(3):
            make_trial_with_curve([0.5, 0.7, 0.9])
        laggard = make_trial_with_curve([0.1, 0.1])
        healthy = make_trial_with_curve([0.6, 0.8])
        assert servicer.CheckTrialEarlyStoppingState(
            vizier_service_pb2.CheckTrialEarlyStoppingStateRequest(trial_name=laggard)
        ).should_stop
        assert not servicer.CheckTrialEarlyStoppingState(
            vizier_service_pb2.CheckTrialEarlyStoppingStateRequest(trial_name=healthy)
        ).should_stop

    def test_update_metadata(self):
        servicer = _make_servicer()
        study = pc.study_to_proto(_config(), "owners/o/studies/s")
        servicer.CreateStudy(
            vizier_service_pb2.CreateStudyRequest(parent="owners/o", study=study)
        )
        request = vizier_service_pb2.UpdateMetadataRequest(name="owners/o/studies/s")
        unit = request.deltas.add()
        unit.trial_id = 0
        unit.key_value.key = "k"
        unit.key_value.ns = ":ns"
        unit.key_value.string_value = "v"
        response = servicer.UpdateMetadata(request)
        assert not response.error_details
        loaded = servicer.GetStudy(
            vizier_service_pb2.GetStudyRequest(name="owners/o/studies/s")
        )
        assert loaded.study_spec.metadata[0].string_value == "v"


class TestClientsInProcess:
    def setup_method(self):
        # Fresh local servicer per test.
        vizier_client._local_servicer = None

    def test_full_loop(self):
        study = clients_lib.Study.from_study_config(
            _config(), owner="me", study_id="loop"
        )
        for _ in range(2):
            for trial in study.suggest(count=2):
                trial.add_measurement(vz.Measurement(metrics={"obj": 0.1}, steps=1))
                trial.complete(
                    vz.Measurement(metrics={"obj": trial.parameters["x"]})
                )
        trials = list(study.trials())
        assert len(trials) == 4
        assert all(t.status == vz.TrialStatus.COMPLETED for t in trials)
        best = list(study.optimal_trials())
        assert len(best) == 1

    def test_from_resource_name_and_missing(self):
        study = clients_lib.Study.from_study_config(
            _config(), owner="me", study_id="named"
        )
        again = clients_lib.Study.from_resource_name(study.resource_name)
        assert again.resource_name == study.resource_name
        with pytest.raises(clients_lib.client_abc.ResourceNotFoundError):
            clients_lib.Study.from_resource_name("owners/me/studies/none")

    def test_materialize_study_config(self):
        study = clients_lib.Study.from_study_config(
            _config(), owner="me", study_id="mat"
        )
        config = study.materialize_study_config()
        assert config.search_space.parameter_names() == ["x", "c"]

    def test_trial_filter_and_get(self):
        study = clients_lib.Study.from_study_config(
            _config(), owner="me", study_id="filt"
        )
        (trial,) = study.suggest(count=1)
        trial.complete(vz.Measurement(metrics={"obj": 1.0}))
        completed = study.trials(vz.TrialFilter(status=[vz.TrialStatus.COMPLETED]))
        assert len(list(completed)) == 1
        with pytest.raises(clients_lib.client_abc.ResourceNotFoundError):
            study.get_trial(999)

    def test_study_metadata_update(self):
        study = clients_lib.Study.from_study_config(
            _config(), owner="me", study_id="md"
        )
        md = vz.Metadata()
        md.ns("user")["note"] = "hello"
        study.update_metadata(md)
        config = study.materialize_study_config()
        assert config.metadata.ns("user")["note"] == "hello"


class TestClientsOverGrpc:
    def test_grpc_end_to_end(self):
        server = DefaultVizierServer()
        try:
            study = clients_lib.Study.from_study_config(
                _config(), owner="me", study_id="grpc", endpoint=server.endpoint
            )
            for trial in study.suggest(count=2):
                trial.complete(vz.Measurement(metrics={"obj": trial.parameters["x"]}))
            assert len(list(study.trials())) == 2
        finally:
            server.stop(0)

    def test_distributed_pythia_topology(self):
        server = DistributedPythiaVizierServer()
        try:
            study = clients_lib.Study.from_study_config(
                _config(), owner="me", study_id="dist", endpoint=server.endpoint
            )
            suggestions = study.suggest(count=2)
            assert len(suggestions) == 2
        finally:
            server.stop(0)


class TestMultiClientConcurrency:
    def test_parallel_workers(self):
        """N workers suggest/complete concurrently against one study."""
        vizier_client._local_servicer = None
        study = clients_lib.Study.from_study_config(
            _config(), owner="me", study_id="conc"
        )
        errors = []

        def worker(wid: int):
            try:
                for _ in range(3):
                    for trial in study.suggest(count=1, client_id=f"w{wid}"):
                        trial.complete(vz.Measurement(metrics={"obj": 0.5}))
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        trials = list(study.trials())
        assert len(trials) == 24
        assert all(t.status == vz.TrialStatus.COMPLETED for t in trials)


class TestReviewRegressions:
    """Regressions from the fifth code review."""

    def test_default_algorithm_resolves(self):
        from vizier_tpu.service import policy_factory
        from vizier_tpu.pythia import local_policy_supporters

        config = _config(algorithm="DEFAULT")
        supporter = local_policy_supporters.InRamPolicySupporter(config)
        policy = policy_factory.DefaultPolicyFactory()(
            config.to_problem(), "DEFAULT", supporter, "s"
        )
        trials = supporter.SuggestTrials(policy, 1)
        assert len(trials) == 1

    def test_orphaned_operation_recovered(self):
        """A persisted not-done op from a crashed server must not wedge."""
        import tempfile, os

        url = f"sqlite:///{tempfile.mkdtemp()}/wedge.db"
        servicer1 = vizier_service.VizierServicer(database_url=url)
        pythia1 = pythia_service.PythiaServicer(servicer1)
        servicer1.set_pythia(pythia1)
        study = pc.study_to_proto(_config(), "owners/o/studies/s")
        servicer1.CreateStudy(
            vizier_service_pb2.CreateStudyRequest(parent="owners/o", study=study)
        )
        # Simulate a crash: op persisted not-done.
        from vizier_tpu.service import resources as res

        dead = vizier_service_pb2.Operation(
            name=res.SuggestionOperationResource("o", "s", "w0", 1).name
        )
        servicer1.datastore.create_suggestion_operation(dead)
        # "Restarted" server: fresh servicer instance on the same DB.
        servicer2 = vizier_service.VizierServicer(database_url=url)
        pythia2 = pythia_service.PythiaServicer(servicer2)
        servicer2.set_pythia(pythia2)
        op = servicer2.SuggestTrials(
            vizier_service_pb2.SuggestTrialsRequest(
                parent="owners/o/studies/s", suggestion_count=1, client_id="w0"
            )
        )
        assert op.done and not op.error
        assert len(op.response.trials) == 1

    def test_stale_active_early_stopping_op_recycled(self):
        import datetime as dt

        servicer = vizier_service.VizierServicer(
            early_stop_recycle_period=dt.timedelta(seconds=0)
        )
        pythia = pythia_service.PythiaServicer(servicer)
        servicer.set_pythia(pythia)
        config = _config()
        config.automated_stopping_config = vz.AutomatedStoppingConfig(min_num_trials=3)
        study = pc.study_to_proto(config, "owners/o/studies/s")
        servicer.CreateStudy(
            vizier_service_pb2.CreateStudyRequest(parent="owners/o", study=study)
        )

        def add_curve(values):
            created = servicer.CreateTrial(
                vizier_service_pb2.CreateTrialRequest(
                    parent="owners/o/studies/s", trial=study_pb2.Trial()
                )
            )
            for step, v in enumerate(values, start=1):
                add = vizier_service_pb2.AddTrialMeasurementRequest(
                    trial_name=created.name
                )
                add.measurement.steps = step
                metric = add.measurement.metrics.add()
                metric.name, metric.value = "obj", v
                servicer.AddTrialMeasurement(add)
            return created

        for _ in range(3):
            add_curve([0.5, 0.7, 0.9])
        laggard = add_curve([0.05, 0.06])
        # Plant a stale ACTIVE op pinned to should_stop=False.
        from vizier_tpu.service import resources as res

        stale = vizier_service_pb2.EarlyStoppingOperation(
            name=res.EarlyStoppingOperationResource("o", "s", laggard.id).name,
            status=vizier_service_pb2.EarlyStoppingOperation.ACTIVE,
            creation_time_secs=0.0,
        )
        servicer.datastore.create_early_stopping_operation(stale)
        response = servicer.CheckTrialEarlyStoppingState(
            vizier_service_pb2.CheckTrialEarlyStoppingStateRequest(
                trial_name=laggard.name
            )
        )
        # Recycled and re-queried: the laggard should now stop.
        assert response.should_stop is True

    def test_materialize_state_reads_service(self):
        vizier_client._local_servicer = None
        study = clients_lib.Study.from_study_config(
            _config(), owner="me", study_id="state"
        )
        assert study.materialize_state() == vz.StudyState.ACTIVE
        study.set_state(vz.StudyState.COMPLETED)
        assert study.materialize_state() == vz.StudyState.COMPLETED


class TestAlgorithmOverrideIsolation:
    """Review regression: a request's algorithm override must stay
    per-request — the cached StudyConfig parse is shared across requests
    (and servicer threads), so mutating it would make one client's
    override leak into every later no-override suggest for the study."""

    def test_suggest_override_leaves_cached_config_untouched(self):
        from vizier_tpu.service.protos import pythia_service_pb2

        servicer = _make_servicer()
        pythia = servicer._pythia
        name = "owners/o/studies/override"
        servicer.CreateStudy(
            vizier_service_pb2.CreateStudyRequest(
                parent="owners/o",
                study=pc.study_to_proto(_config("RANDOM_SEARCH"), name),
            )
        )
        spec = servicer.datastore.load_study(name).study_spec

        def suggest(algorithm):
            request = pythia_service_pb2.PythiaSuggestRequest(
                count=1, algorithm=algorithm, study_name=name
            )
            request.study_descriptor.config.CopyFrom(spec)
            request.study_descriptor.guid = name
            return pythia.Suggest(request)

        assert not suggest("QUASI_RANDOM_SEARCH").error
        # The override served that one request only: the cached parse (and
        # with it the next no-override request) keeps the study's own
        # algorithm.
        assert pythia._config_cache[name][1].algorithm == "RANDOM_SEARCH"
        assert not suggest("").error


class TestListStudies:
    def test_lists_owner_studies(self):
        vizier_client._local_servicer = None
        for sid in ("a", "b"):
            clients_lib.Study.from_study_config(_config(), owner="lister", study_id=sid)
        clients_lib.Study.from_study_config(_config(), owner="other", study_id="c")
        studies = clients_lib.list_studies("lister")
        names = sorted(s.resource_name for s in studies)
        assert names == ["owners/lister/studies/a", "owners/lister/studies/b"]
        # Handles are live: suggest works through them.
        (t,) = studies[0].suggest(count=1)
        assert t.status == vz.TrialStatus.ACTIVE


class TestBudgetPolicyViaMetadata:
    """gRPC-reachable acquisition budget policy (study metadata ns
    'gp_ucb_pe'), so clients can request reference per-pick semantics."""

    def _designer_for(self, metadata_value):
        from vizier_tpu.pythia import local_policy_supporters
        from vizier_tpu.service import policy_factory

        config = _config(algorithm="DEFAULT")
        problem = config.to_problem()
        if metadata_value is not None:
            problem.metadata.ns("gp_ucb_pe")[
                "acquisition_budget_policy"
            ] = metadata_value
        supporter = local_policy_supporters.InRamPolicySupporter(config)
        policy = policy_factory.DefaultPolicyFactory()(
            problem, "DEFAULT", supporter, "s"
        )
        # DesignerPolicy builds the designer lazily via its factory.
        return policy._designer_factory(problem)

    def test_default_is_first_pick_full(self):
        designer = self._designer_for(None)
        assert designer.acquisition_budget_policy == "first_pick_full"

    def test_metadata_requests_per_pick(self):
        designer = self._designer_for("per_pick")
        assert designer.acquisition_budget_policy == "per_pick"

    def test_invalid_value_raises(self):
        with pytest.raises(ValueError, match="acquisition_budget_policy"):
            self._designer_for("always_free_lunch")


class TestAcquisitionEvalsViaMetadata:
    """gRPC-reachable acquisition sweep budget (study metadata ns
    'gp_ucb_pe' key 'max_acquisition_evaluations') — the remote path a
    shared compute-tier client uses to bound designer cost, since the
    key rides the StudySpec across the Pythia surface."""

    def _designer_for(self, metadata_value):
        from vizier_tpu.pythia import local_policy_supporters
        from vizier_tpu.service import policy_factory

        config = _config(algorithm="DEFAULT")
        problem = config.to_problem()
        if metadata_value is not None:
            problem.metadata.ns("gp_ucb_pe")[
                "max_acquisition_evaluations"
            ] = metadata_value
        supporter = local_policy_supporters.InRamPolicySupporter(config)
        policy = policy_factory.DefaultPolicyFactory()(
            problem, "DEFAULT", supporter, "s"
        )
        return policy._designer_factory(problem)

    def test_metadata_bounds_the_sweep(self):
        designer = self._designer_for("300")
        assert designer.max_acquisition_evaluations == 300

    def test_absent_key_keeps_the_designer_default(self):
        from vizier_tpu.designers import gp_ucb_pe

        designer = self._designer_for(None)
        default = gp_ucb_pe.VizierGPUCBPEBandit(
            _config(algorithm="DEFAULT").to_problem()
        ).max_acquisition_evaluations
        assert designer.max_acquisition_evaluations == default

    def test_zero_means_designer_default(self):
        designer = self._designer_for("0")
        default = self._designer_for(None).max_acquisition_evaluations
        assert designer.max_acquisition_evaluations == default

    def test_invalid_value_raises_at_policy_construction(self):
        with pytest.raises(ValueError, match="max_acquisition_evaluations"):
            self._designer_for("lots")

    def test_negative_value_raises(self):
        with pytest.raises(ValueError, match="max_acquisition_evaluations"):
            self._designer_for("-5")
