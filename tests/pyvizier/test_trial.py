"""Tests for trials, measurements and parameter values."""

import math

import pytest

from vizier_tpu import pyvizier as vz


class TestParameterValue:
    def test_casts(self):
        assert vz.ParameterValue(3).as_float == 3.0
        assert vz.ParameterValue(3.0).as_int == 3
        assert vz.ParameterValue(True).as_str == "True"
        assert vz.ParameterValue("true").as_bool is True
        assert vz.ParameterValue("False").as_bool is False
        assert vz.ParameterValue(1).as_bool is True

    def test_bad_casts(self):
        with pytest.raises(ValueError):
            vz.ParameterValue(3.5).as_int
        with pytest.raises(ValueError):
            vz.ParameterValue("xyz").as_bool

    def test_type_check(self):
        with pytest.raises(TypeError):
            vz.ParameterValue([1, 2])  # type: ignore


class TestParameterDict:
    def test_wraps_raw(self):
        d = vz.ParameterDict({"a": 1})
        assert isinstance(d["a"], vz.ParameterValue)
        assert d.get_value("a") == 1
        assert d.as_dict() == {"a": 1}

    def test_eq_with_mapping(self):
        assert vz.ParameterDict({"a": 1}) == {"a": 1}


class TestMeasurement:
    def test_numbers_coerced(self):
        m = vz.Measurement(metrics={"loss": 0.5})
        assert m.metrics["loss"] == vz.Metric(0.5)

    def test_negative_elapsed_rejected(self):
        with pytest.raises(ValueError):
            vz.Measurement(elapsed_secs=-1.0)


class TestTrialLifecycle:
    def test_active_by_default(self):
        t = vz.Trial(id=1, parameters={"x": 1.0})
        assert t.status == vz.TrialStatus.ACTIVE
        assert not t.is_completed

    def test_requested(self):
        t = vz.Trial(id=1, is_requested=True)
        assert t.status == vz.TrialStatus.REQUESTED

    def test_complete_with_measurement(self):
        t = vz.Trial(id=1)
        t.complete(vz.Measurement(metrics={"obj": 1.0}))
        assert t.status == vz.TrialStatus.COMPLETED
        assert t.final_measurement.metrics["obj"].value == 1.0
        assert not t.infeasible
        assert t.duration is not None

    def test_complete_promotes_last_intermediate(self):
        t = vz.Trial(id=1)
        t.measurements.append(vz.Measurement(metrics={"obj": 1.0}, steps=1))
        t.measurements.append(vz.Measurement(metrics={"obj": 2.0}, steps=2))
        t.complete()
        assert t.final_measurement.metrics["obj"].value == 2.0

    def test_complete_empty_is_infeasible(self):
        t = vz.Trial(id=1)
        t.complete()
        assert t.infeasible

    def test_nan_metric_marks_infeasible(self):
        t = vz.Trial(id=1)
        t.complete(vz.Measurement(metrics={"obj": math.nan}))
        assert t.infeasible

    def test_complete_not_inplace(self):
        t = vz.Trial(id=1)
        done = t.complete(vz.Measurement(metrics={"obj": 1.0}), inplace=False)
        assert not t.is_completed
        assert done.is_completed

    def test_stop(self):
        t = vz.Trial(id=1)
        t.stop("plateau")
        assert t.status == vz.TrialStatus.STOPPING
        assert t.stopping_reason == "plateau"

    def test_suggestion_roundtrip(self):
        s = vz.TrialSuggestion(parameters={"x": 1.0})
        s.metadata["k"] = "v"
        t = s.to_trial(7)
        assert t.id == 7
        assert t.parameters.get_value("x") == 1.0
        assert t.to_suggestion().parameters == s.parameters


class TestTrialFilter:
    def _trials(self):
        a = vz.Trial(id=1)
        b = vz.Trial(id=2)
        b.complete(vz.Measurement(metrics={"m": 1.0}))
        c = vz.Trial(id=3, is_requested=True)
        return [a, b, c]

    def test_by_status(self):
        f = vz.TrialFilter(status=[vz.TrialStatus.COMPLETED])
        assert [t.id for t in filter(f, self._trials())] == [2]

    def test_by_ids_and_min_id(self):
        f = vz.TrialFilter(ids=[1, 3], min_id=2)
        assert [t.id for t in filter(f, self._trials())] == [3]


class TestContainers:
    def test_completed_trials_validates(self):
        t = vz.Trial(id=1)
        with pytest.raises(ValueError):
            vz.CompletedTrials([t])
        t.complete(vz.Measurement(metrics={"m": 1.0}))
        assert len(vz.CompletedTrials([t]).trials) == 1

    def test_active_trials_validates(self):
        t = vz.Trial(id=1, is_requested=True)
        with pytest.raises(ValueError):
            vz.ActiveTrials([t])


class TestMetadataDelta:
    def test_assign(self):
        d = vz.MetadataDelta()
        assert d.empty
        d.assign("ns", "k", "v")
        d.assign("ns", "k2", "v2", trial_id=5)
        assert not d.empty
        assert d.on_study.abs_ns(vz.Namespace(("ns",)))["k"] == "v"
        assert d.on_trials[5].abs_ns(vz.Namespace(("ns",)))["k2"] == "v2"


class TestStudyConfig:
    def test_trial_parameters_external_types(self):
        cfg = vz.StudyConfig()
        root = cfg.search_space.root
        root.add_bool_param("flag")
        root.add_discrete_param("bs", [32, 64])
        root.add_float_param("lr", 0.0, 1.0)
        t = vz.Trial(id=1, parameters={"flag": "True", "bs": 64.0, "lr": 0.5})
        mapped = cfg.trial_parameters(t)
        assert mapped == {"flag": True, "bs": 64, "lr": 0.5}
        assert isinstance(mapped["bs"], int)

    def test_from_problem(self):
        problem = vz.ProblemStatement()
        problem.search_space.root.add_float_param("x", 0, 1)
        problem.metric_information.append(
            vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
        cfg = vz.StudyConfig.from_problem(problem, vz.Algorithm.RANDOM_SEARCH)
        assert cfg.algorithm == "RANDOM_SEARCH"
        assert cfg.is_single_objective
        assert cfg.single_objective_metric_name == "obj"

    def test_metrics_config(self):
        mc = vz.MetricsConfig([vz.MetricInformation(name="a")])
        mc.append(vz.MetricInformation(name="safe", safety_threshold=0.5))
        assert mc.is_single_objective
        assert mc.is_safety_metric_present
        assert mc.item().name == "a"
        with pytest.raises(ValueError):
            mc.append(vz.MetricInformation(name="a"))


class TestReviewRegressions:
    """Regressions from the initial code review."""

    def test_parameter_dict_eq_bad_mapping_is_false(self):
        assert (vz.ParameterDict({"x": 1}) == {"x": None}) is False

    def test_stopping_takes_precedence_over_requested(self):
        t = vz.Trial(id=1, is_requested=True)
        t.stop("why")
        assert t.status == vz.TrialStatus.STOPPING


class TestReferenceConveniences:
    def test_as_float_dict(self):
        m = vz.Measurement(metrics={"a": 1.5, "b": vz.Metric(value=2.0)})
        assert m.as_float_dict() == {"a": 1.5, "b": 2.0}

    def test_final_measurement_or_die(self):
        t = vz.Trial(id=1)
        with pytest.raises(ValueError, match="no final measurement"):
            _ = t.final_measurement_or_die
        t.complete(vz.Measurement(metrics={"obj": 3.0}))
        assert t.final_measurement_or_die.metrics["obj"].value == 3.0


class TestMetricTypes:
    def test_metric_type_enum(self):
        obj = vz.MetricInformation(name="o", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        safe = vz.MetricInformation(name="s", safety_threshold=0.5)
        assert obj.type == vz.MetricType.OBJECTIVE and obj.type.is_objective
        assert safe.type == vz.MetricType.SAFETY and safe.type.is_safety
        assert obj.type == "OBJECTIVE"  # str-compat preserved

    def test_of_type_and_exclude_type(self):
        cfg = vz.MetricsConfig([
            vz.MetricInformation(name="o1"),
            vz.MetricInformation(name="s1", safety_threshold=0.0),
            vz.MetricInformation(name="o2"),
        ])
        assert {m.name for m in cfg.of_type(vz.MetricType.OBJECTIVE)} == {"o1", "o2"}
        assert {m.name for m in cfg.exclude_type("SAFETY")} == {"o1", "o2"}
        assert {m.name for m in cfg.of_type(["SAFETY"])} == {"s1"}

    def test_range(self):
        m = vz.MetricInformation(name="o", min_value=-1.0, max_value=3.0)
        assert m.range == 4.0
        assert vz.MetricInformation(name="u").range == float("inf")
