"""Tests for parameter configs and search spaces."""

import pytest

from vizier_tpu import pyvizier as vz


class TestParameterConfigFactory:
    def test_double(self):
        c = vz.ParameterConfig.factory("x", bounds=(0.0, 1.0))
        assert c.type == vz.ParameterType.DOUBLE
        assert c.bounds == (0.0, 1.0)
        assert c.num_feasible_values == float("inf")
        assert c.contains(0.5)
        assert not c.contains(1.5)
        assert not c.contains("a")

    def test_integer(self):
        c = vz.ParameterConfig.factory("n", bounds=(1, 5))
        assert c.type == vz.ParameterType.INTEGER
        assert c.num_feasible_values == 5
        assert c.feasible_values == [1, 2, 3, 4, 5]
        assert c.contains(3)
        assert c.contains(3.0)
        assert not c.contains(3.5)
        assert not c.contains(0)

    def test_discrete(self):
        c = vz.ParameterConfig.factory("d", feasible_values=[3, 1, 2])
        assert c.type == vz.ParameterType.DISCRETE
        assert c.feasible_values == [1.0, 2.0, 3.0]
        assert c.bounds == (1.0, 3.0)
        assert c.contains(2)
        assert not c.contains(2.5)

    def test_categorical(self):
        c = vz.ParameterConfig.factory("c", feasible_values=["b", "a"])
        assert c.type == vz.ParameterType.CATEGORICAL
        assert c.feasible_values == ["a", "b"]
        assert c.contains("a")
        assert not c.contains("z")
        assert not c.contains(1)

    def test_both_bounds_and_values_rejected(self):
        with pytest.raises(ValueError):
            vz.ParameterConfig.factory("x", bounds=(0, 1), feasible_values=[1, 2])

    def test_bad_bounds(self):
        with pytest.raises(ValueError):
            vz.ParameterConfig.factory("x", bounds=(2.0, 1.0))

    def test_log_scale_positive_bounds(self):
        with pytest.raises(ValueError):
            vz.ParameterConfig.factory("x", bounds=(0.0, 1.0), scale_type=vz.ScaleType.LOG)

    def test_default_value_validated(self):
        with pytest.raises(ValueError):
            vz.ParameterConfig.factory("x", bounds=(0.0, 1.0), default_value=2.0)

    def test_mixed_feasible_values_rejected(self):
        with pytest.raises(ValueError):
            vz.ParameterConfig.factory("x", feasible_values=["a", 1])

    def test_duplicate_feasible_values_rejected(self):
        with pytest.raises(ValueError):
            vz.ParameterConfig.factory("x", feasible_values=[1, 1, 2])

    def test_continuify(self):
        c = vz.ParameterConfig.factory("n", bounds=(1, 5)).continuify()
        assert c.type == vz.ParameterType.DOUBLE
        assert c.bounds == (1.0, 5.0)
        with pytest.raises(ValueError):
            vz.ParameterConfig.factory("c", feasible_values=["a"]).continuify()


class TestSearchSpaceBuilders:
    def test_flat_space(self):
        space = vz.SearchSpace()
        root = space.root
        root.add_float_param("lr", 1e-4, 1e-1, scale_type=vz.ScaleType.LOG)
        root.add_int_param("layers", 1, 8)
        root.add_discrete_param("batch", [32, 64, 128])
        root.add_categorical_param("opt", ["adam", "sgd"])
        root.add_bool_param("use_bn")
        assert space.parameter_names() == ["lr", "layers", "batch", "opt", "use_bn"]
        assert space.num_parameters() == 5
        assert space.num_parameters(vz.ParameterType.DOUBLE) == 1
        assert space.get("batch").external_type == vz.ExternalType.INTEGER
        assert space.get("use_bn").external_type == vz.ExternalType.BOOLEAN
        assert not space.is_conditional

    def test_duplicate_name_rejected(self):
        space = vz.SearchSpace()
        space.root.add_float_param("x", 0, 1)
        with pytest.raises(ValueError):
            space.root.add_float_param("x", 0, 1)

    def test_conditional_children(self):
        space = vz.SearchSpace()
        model = space.root.add_categorical_param("model", ["linear", "dnn"])
        dnn = model.select_values(["dnn"])
        dnn.add_float_param("hidden_lr", 1e-5, 1e-2, scale_type=vz.ScaleType.LOG)
        assert space.is_conditional
        assert "hidden_lr" in space
        cfg = space.get("model")
        assert len(cfg.children) == 1
        assert cfg.children[0].matching_parent_values == ("dnn",)

    def test_nested_conditional(self):
        space = vz.SearchSpace()
        a = space.root.add_categorical_param("a", ["x", "y"])
        b = a.select_values(["x"]).add_categorical_param("b", ["p", "q"])
        b.select_values(["p"]).add_float_param("c", 0.0, 1.0)
        names = space.parameter_names()
        assert names == ["a", "b", "c"]
        assert len(space.get("a").children) == 1
        assert len(space.get("a").children[0].children) == 1

    def test_conditional_requires_selected_values(self):
        space = vz.SearchSpace()
        sel = space.root.add_categorical_param("a", ["x", "y"])
        with pytest.raises(ValueError):
            sel.add_float_param("child", 0.0, 1.0)


class TestSearchSpaceContains:
    @pytest.fixture
    def space(self):
        s = vz.SearchSpace()
        root = s.root
        root.add_float_param("x", 0.0, 1.0)
        model = root.add_categorical_param("model", ["linear", "dnn"])
        model.select_values(["dnn"]).add_int_param("depth", 1, 4)
        return s

    def test_valid_flat(self, space):
        assert space.contains({"x": 0.5, "model": "linear"})

    def test_valid_conditional(self, space):
        assert space.contains({"x": 0.5, "model": "dnn", "depth": 2})

    def test_missing_active_child(self, space):
        assert not space.contains({"x": 0.5, "model": "dnn"})

    def test_inactive_child_assigned(self, space):
        assert not space.contains({"x": 0.5, "model": "linear", "depth": 2})

    def test_unknown_param(self, space):
        assert not space.contains({"x": 0.5, "model": "linear", "zzz": 1})

    def test_infeasible_value(self, space):
        assert not space.contains({"x": 5.0, "model": "linear"})

    def test_parameter_value_objects(self, space):
        params = vz.ParameterDict({"x": 0.5, "model": "linear"})
        assert space.contains(params)


class TestReviewRegressions:
    """Regressions from the initial code review."""

    def test_continuify_parent_raises(self):
        s = vz.SearchSpace()
        sel = s.root.add_discrete_param("d", [1, 2, 3])
        sel.select_values([1]).add_float_param("x", 0, 1)
        with pytest.raises(ValueError, match="parent"):
            s.get("d").continuify()

    def test_discrete_log_scale_positivity(self):
        with pytest.raises(ValueError, match="positive"):
            vz.ParameterConfig.factory("d", feasible_values=[0, 1, 10], scale_type=vz.ScaleType.LOG)


class TestMerge:
    def test_double_bounds_envelope(self):
        a = vz.ParameterConfig.factory("x", bounds=(0.0, 1.0))
        b = vz.ParameterConfig.factory("x", bounds=(0.5, 2.0))
        m = vz.ParameterConfig.merge(a, b)
        assert m.bounds == (0.0, 2.0)
        assert m.type == vz.ParameterType.DOUBLE

    def test_integer_stays_integer(self):
        a = vz.ParameterConfig.factory("n", bounds=(1, 5))
        b = vz.ParameterConfig.factory("n", bounds=(3, 9))
        m = vz.ParameterConfig.merge(a, b)
        assert m.type == vz.ParameterType.INTEGER
        assert m.bounds == (1, 9)

    def test_categorical_union(self):
        a = vz.ParameterConfig.factory("c", feasible_values=["a", "b"])
        b = vz.ParameterConfig.factory("c", feasible_values=["b", "z"])
        m = vz.ParameterConfig.merge(a, b)
        assert m.feasible_values == ["a", "b", "z"]

    def test_discrete_union(self):
        a = vz.ParameterConfig.factory("d", feasible_values=[1.0, 2.0])
        b = vz.ParameterConfig.factory("d", feasible_values=[2.0, 4.0])
        m = vz.ParameterConfig.merge(a, b)
        assert m.feasible_values == [1.0, 2.0, 4.0]

    def test_type_conflict_rejected(self):
        a = vz.ParameterConfig.factory("p", bounds=(0.0, 1.0))
        b = vz.ParameterConfig.factory("p", feasible_values=["a"])
        with pytest.raises(ValueError, match="Type conflict"):
            vz.ParameterConfig.merge(a, b)

    def test_children_rejected(self):
        s = vz.SearchSpace()
        sel = s.root.add_categorical_param("c", ["a", "b"])
        sel.select_values(["a"]).add_float_param("x", 0, 1)
        flat = vz.ParameterConfig.factory("c", feasible_values=["a", "b"])
        with pytest.raises(ValueError, match="children"):
            vz.ParameterConfig.merge(s.get("c"), flat)


class TestSubspaceExtraction:
    def _conditional_space(self):
        s = vz.SearchSpace()
        sel = s.root.add_categorical_param("model", ["linear", "dnn"])
        sel.select_values(["dnn"]).add_float_param("lr", 1e-4, 1e-1)
        sel.select_values(["dnn"]).add_int_param("layers", 1, 8)
        sel.select_values(["linear"]).add_float_param("l2", 0.0, 1.0)
        return s

    def test_subspace_for_value(self):
        s = self._conditional_space()
        sub = s.get("model").get_subspace_deepcopy("dnn")
        names = {c.name for c in sub.parameters}
        assert names == {"lr", "layers"}

    def test_subspace_other_value(self):
        s = self._conditional_space()
        sub = s.get("model").get_subspace_deepcopy("linear")
        assert {c.name for c in sub.parameters} == {"l2"}

    def test_subspace_is_a_copy(self):
        s = self._conditional_space()
        sub = s.get("model").get_subspace_deepcopy("dnn")
        sub.pop("lr")
        assert "lr" in {c.name for c in s.get("model").children}

    def test_double_parent_returns_empty(self):
        c = vz.ParameterConfig.factory("x", bounds=(0.0, 1.0))
        assert c.get_subspace_deepcopy(0.5).is_empty()

    def test_infeasible_value_rejected(self):
        s = self._conditional_space()
        with pytest.raises(Exception, match="feasible"):
            s.get("model").get_subspace_deepcopy("svm")


class TestTraverseAndClone:
    def test_clone_without_children(self):
        s = vz.SearchSpace()
        sel = s.root.add_categorical_param("c", ["a"])
        sel.select_values(["a"]).add_float_param("x", 0, 1)
        bare = s.get("c").clone_without_children()
        assert bare.children == () and s.get("c").children

    def test_traverse_hides_children_but_still_recurses(self):
        s = vz.SearchSpace()
        sel = s.root.add_categorical_param("c", ["a"])
        sel.select_values(["a"]).add_float_param("x", 0, 1)
        seen = list(s.get("c").traverse(show_children=False))
        assert [p.name for p in seen] == ["c", "x"]
        assert all(p.children == () for p in seen)


class TestCustomParam:
    def test_factory_neither_bounds_nor_values_is_custom(self):
        c = vz.ParameterConfig.factory("blob")
        assert c.type == vz.ParameterType.CUSTOM
        assert c.num_feasible_values == float("inf")
        assert c.contains("anything") and c.contains(42)

    def test_add_custom_param(self):
        s = vz.SearchSpace()
        s.root.add_custom_param("payload", default_value="serialized")
        cfg = s.get("payload")
        assert cfg.type == vz.ParameterType.CUSTOM
        assert cfg.first_feasible_value() == "serialized"

    def test_custom_without_default_cannot_seed(self):
        c = vz.ParameterConfig.factory("blob")
        with pytest.raises(Exception, match="default"):
            c.first_feasible_value()


class TestMultiDimensionalNames:
    def test_index_builds_bracketed_name(self):
        s = vz.SearchSpace()
        for i in range(3):
            s.root.add_float_param("rate", 0.0, 1.0, index=i)
        assert [c.name for c in s.parameters] == ["rate[0]", "rate[1]", "rate[2]"]

    def test_parse_roundtrip(self):
        parse = vz.SearchSpaceSelector.parse_multi_dimensional_parameter_name
        assert parse("rate[10]") == ("rate", 10)
        assert parse("rate") is None
        assert parse("rate[x]") is None

    def test_negative_index_rejected(self):
        s = vz.SearchSpace()
        with pytest.raises(ValueError, match=">= 0"):
            s.root.add_int_param("n", 0, 5, index=-1)

    def test_index_on_all_builders(self):
        s = vz.SearchSpace()
        s.root.add_int_param("n", 0, 5, index=0)
        s.root.add_discrete_param("d", [1, 2], index=1)
        s.root.add_categorical_param("c", ["a"], index=2)
        s.root.add_bool_param("b", index=3)
        assert {c.name for c in s.parameters} == {"n[0]", "d[1]", "c[2]", "b[3]"}

    def test_merge_preserves_shared_external_type(self):
        s1, s2 = vz.SearchSpace(), vz.SearchSpace()
        s1.root.add_bool_param("b")
        s2.root.add_bool_param("b")
        m = vz.ParameterConfig.merge(s1.get("b"), s2.get("b"))
        assert m.external_type == vz.ExternalType.BOOLEAN

    def test_merge_scale_conflict_warns(self):
        import warnings as w

        a = vz.ParameterConfig.factory("x", bounds=(0.1, 1.0), scale_type=vz.ScaleType.LOG)
        b = vz.ParameterConfig.factory("x", bounds=(0.1, 2.0), scale_type=vz.ScaleType.LINEAR)
        with w.catch_warnings(record=True) as caught:
            w.simplefilter("always")
            m = vz.ParameterConfig.merge(a, b)
        assert any("Scale type conflict" in str(c.message) for c in caught)
        assert m.scale_type == vz.ScaleType.LOG

    def test_subspace_rejects_truncatable_integer_value(self):
        s = vz.SearchSpace()
        sel = s.root.add_int_param("n", 1, 8)
        sel.select_values([2]).add_float_param("x", 0, 1)
        with pytest.raises(Exception, match="feasible"):
            s.get("n").get_subspace_deepcopy(2.7)
