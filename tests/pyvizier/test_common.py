"""Tests for namespaced metadata."""

import pytest

from vizier_tpu.pyvizier import common


class TestNamespace:
    def test_empty(self):
        ns = common.Namespace()
        assert len(ns) == 0
        assert ns.encode() == ""
        assert common.Namespace.decode("") == ns

    def test_roundtrip_simple(self):
        ns = common.Namespace(("a", "b", "c"))
        assert common.Namespace.decode(ns.encode()) == ns

    @pytest.mark.parametrize(
        "components",
        [
            ("a:b",),
            ("a\\", "b"),
            ("a:b", "c\\:d"),
            ("", "x"),
            (":", "\\"),
        ],
    )
    def test_roundtrip_escaping(self, components):
        ns = common.Namespace(components)
        assert tuple(common.Namespace.decode(ns.encode())) == components

    def test_single_string_is_one_component(self):
        assert tuple(common.Namespace("abc")) == ("abc",)

    def test_encoded_string_decodes(self):
        assert tuple(common.Namespace(":a:b")) == ("a", "b")

    def test_add(self):
        ns = common.Namespace(("a",)) + ("b",)
        assert tuple(ns) == ("a", "b")

    def test_startswith(self):
        ns = common.Namespace(("a", "b", "c"))
        assert ns.startswith(("a", "b"))
        assert not ns.startswith(("b",))

    def test_ancestors(self):
        ns = common.Namespace(("a", "b"))
        assert [tuple(a) for a in ns.ancestors()] == [(), ("a",), ("a", "b")]


class TestMetadata:
    def test_root_store(self):
        md = common.Metadata()
        md["k"] = "v"
        assert md["k"] == "v"
        assert "k" in md
        assert len(md) == 1

    def test_init_kwargs(self):
        md = common.Metadata({"a": "1"}, b="2")
        assert md["a"] == "1"
        assert md["b"] == "2"

    def test_ns_isolation(self):
        md = common.Metadata()
        md["k"] = "root"
        md.ns("sub")["k"] = "sub"
        assert md["k"] == "root"
        assert md.ns("sub")["k"] == "sub"
        assert md.abs_ns(common.Namespace(("sub",)))["k"] == "sub"

    def test_nested_ns(self):
        md = common.Metadata()
        md.ns("a").ns("b")["k"] = "v"
        assert md.abs_ns(common.Namespace(("a", "b")))["k"] == "v"
        assert ("a", "b") in [tuple(n) for n in md.namespaces()]

    def test_value_types(self):
        md = common.Metadata()
        md["s"] = "str"
        md["f"] = 1.5
        md["b"] = b"bytes"
        assert md["f"] == 1.5
        assert md["b"] == b"bytes"

    def test_attach_merge(self):
        a = common.Metadata()
        a.ns("x")["k"] = "a"
        b = common.Metadata()
        b.ns("x")["k"] = "b"
        b.ns("y")["j"] = "c"
        a.attach(b)
        assert a.ns("x")["k"] == "b"
        assert a.ns("y")["j"] == "c"

    def test_eq_ignores_empty_namespaces(self):
        a = common.Metadata()
        a.ns("x")  # creates nothing
        b = common.Metadata()
        assert a == b

    def test_subnamespaces(self):
        md = common.Metadata()
        md.ns("a").ns("b")["k"] = "v"
        md.ns("a")["k"] = "v"
        md.ns("c")["k"] = "v"
        subs = {tuple(n) for n in md.subnamespaces(("a",))}
        assert subs == {("a",), ("a", "b")}


class TestTypedAccess:
    def test_get_with_cls(self):
        m = common.Metadata({"int": "60", "float": "1.2"})
        assert m.get("int", cls=int) == 60
        assert m.get("float", cls=float) == 1.2
        assert m.get("missing", 7, cls=int) == 7

    def test_get_unconvertible_returns_default(self):
        m = common.Metadata({"word": "abc"})
        assert m.get("word", None, cls=int) is None

    def test_get_or_error(self):
        m = common.Metadata({"key": "value", "n": "3"})
        assert m.get_or_error("key") == "value"
        assert m.get_or_error("n", cls=int) == 3
        with pytest.raises(KeyError):
            m.get_or_error("badkey")

    def test_items_by_cls(self):
        m = common.Metadata({"a": "x", "b": 1.5, "c": "y"})
        assert dict(m.items_by_cls(cls=str)) == {"a": "x", "c": "y"}
        assert dict(m.items_by_cls(cls=float)) == {"b": 1.5}

    def test_current_ns(self):
        m = common.Metadata()
        sub = m.ns("alg").ns("state")
        assert sub.current_ns() == common.Namespace(["alg", "state"])
        assert sub.current_ns().encode() == ":alg:state"

    def test_bare_get_preserves_stored_types(self):
        m = common.Metadata({"f": 1.5, "b": b"\x08\x01", "s": "x"})
        assert m.get("f") == 1.5 and isinstance(m.get("f"), float)
        assert m.get("b") == b"\x08\x01" and isinstance(m.get("b"), bytes)
        assert m.get("s") == "x"

    def test_get_any_proto_with_nonproto_cls_returns_default(self):
        class FakeAny:
            def Unpack(self, message):  # pragma: no cover - guard path
                raise AssertionError("must not be called for non-proto cls")

        m = common.Metadata({"a": FakeAny()})
        assert m.get("a", "DEFAULT", cls=str) == "DEFAULT"
