"""Sparse UCB-PE: pending-pick conditioning through the SGPR posterior.

Covers the `gp_ucb_pe_sparse` compute-IR program: auto-switch engagement,
off-switch bit-identity, Nyström augmentation mechanics, batch-pick
diversity (the conditioning actually deflates stddev at earlier picks),
chaos slot isolation through the executor, and predict/sample over the
sparse fit."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.designers import gp_ucb_pe as gp_ucb_pe_lib
from vizier_tpu.designers.gp_ucb_pe import VizierGPUCBPEBandit
from vizier_tpu.models import kernels
from vizier_tpu.optimizers import lbfgs as lbfgs_lib
from vizier_tpu.parallel.batch_executor import BatchExecutor
from vizier_tpu.surrogates import SurrogateConfig
from vizier_tpu.surrogates import sparse_bandit
from vizier_tpu.surrogates import sparse_gp
from vizier_tpu.testing import chaos as chaos_lib

_FAST = dict(
    ard_optimizer=lbfgs_lib.AdamOptimizer(maxiter=15),
    ard_restarts=3,
    max_acquisition_evaluations=200,
    warm_start_min_trials=0,
)

_SPARSE = SurrogateConfig(
    sparse_threshold_trials=1, hysteresis_trials=0, num_inducing=6
)


def _problem(num_params=2):
    p = vz.ProblemStatement()
    for d in range(num_params):
        p.search_space.root.add_float_param(f"x{d}", 0.0, 1.0)
    p.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return p


def _feed(designer, seed, n=12, num_params=2):
    rng = np.random.default_rng(seed)
    trials = []
    for i in range(n):
        params = {f"x{d}": float(rng.uniform()) for d in range(num_params)}
        t = vz.Trial(parameters=params, id=i + 1)
        t.complete(
            vz.Measurement(
                metrics={"obj": float(-sum((v - 0.3) ** 2 for v in params.values()))}
            )
        )
        trials.append(t)
    designer.update(core_lib.CompletedTrials(trials))
    return designer


def _sparse_designer(seed, **kwargs):
    return _feed(
        VizierGPUCBPEBandit(
            _problem(), rng_seed=seed, surrogate=_SPARSE, **_FAST, **kwargs
        ),
        seed,
    )


def _params(suggestions):
    return [s.parameters.as_dict() for s in suggestions]


class TestAutoSwitch:
    def test_sparse_engages_above_threshold(self):
        d = _sparse_designer(0)
        out = d.suggest(3)
        assert len(out) == 3
        assert d.surrogate_mode == "sparse"
        assert d.surrogate_counts["sparse_suggests"] == 1
        assert d.sparse_inducing_state() is not None

    def test_below_threshold_stays_exact(self):
        cfg = SurrogateConfig(sparse_threshold_trials=500)
        d = _feed(
            VizierGPUCBPEBandit(_problem(), rng_seed=0, surrogate=cfg, **_FAST),
            0,
        )
        d.suggest(2)
        assert d.surrogate_mode == "exact"
        assert d.surrogate_counts["sparse_suggests"] == 0

    def test_crossover_resets_per_metric_warm_state(self):
        d = _sparse_designer(3)
        d.suggest(1)
        assert d.surrogate_counts["crossovers"] == 1
        assert d._cached_states is not None
        # The crossover (exact -> sparse on the first suggest) happened
        # BEFORE training, so the sparse train started from a fresh random
        # placeholder, never from exact-GP params.
        assert d.surrogate_mode == "sparse"

    def test_multiobjective_never_flips(self):
        p = _problem()
        p.metric_information.append(
            vz.MetricInformation(
                name="obj2", goal=vz.ObjectiveMetricGoal.MAXIMIZE
            )
        )
        d = VizierGPUCBPEBandit(p, rng_seed=0, surrogate=_SPARSE, **_FAST)
        rng = np.random.default_rng(0)
        trials = []
        for i in range(8):
            t = vz.Trial(
                parameters={
                    "x0": float(rng.uniform()), "x1": float(rng.uniform())
                },
                id=i + 1,
            )
            t.complete(
                vz.Measurement(
                    metrics={"obj": float(rng.uniform()), "obj2": float(rng.uniform())}
                )
            )
            trials.append(t)
        d.update(core_lib.CompletedTrials(trials))
        d.suggest(2)
        assert d.surrogate_mode == "exact"


class TestOffSwitchBitIdentity:
    def test_sparse_ucb_pe_false_is_exact_seed_path(self):
        """sparse_ucb_pe=False (VIZIER_SPARSE_UCB_PE=0) must reproduce the
        no-config exact path bit-for-bit, even above the threshold."""
        off_cfg = SurrogateConfig(
            sparse_threshold_trials=1, hysteresis_trials=0, num_inducing=6,
            sparse_ucb_pe=False,
        )

        def run(surrogate):
            d = _feed(
                VizierGPUCBPEBandit(
                    _problem(), rng_seed=5, surrogate=surrogate, **_FAST
                ),
                5,
            )
            return _params(d.suggest(3))

        assert run(None) == run(off_cfg)

    def test_master_off_is_exact_seed_path(self):
        off = SurrogateConfig(
            sparse=False, sparse_threshold_trials=1, hysteresis_trials=0
        )

        def run(surrogate):
            d = _feed(
                VizierGPUCBPEBandit(
                    _problem(), rng_seed=6, surrogate=surrogate, **_FAST
                ),
                6,
            )
            return _params(d.suggest(2))

        assert run(None) == run(off)


class TestNystromAugmentation:
    def _trained_member(self, seed=0):
        d = _sparse_designer(seed)
        d.suggest(1)
        states_me, _ = d._cached_states
        return jax.tree_util.tree_map(lambda a: a[0, 0], states_me), d

    def test_far_pick_augments_inducing_set(self):
        member, d = self._trained_member()
        all_data = d._all_points_data(2)
        sdata = sparse_gp.with_pending_capacity(member.sdata, all_data, 2)
        before = int(jnp.sum(sdata.inducing_mask))
        # The all-ones corner is far from the (0.3-centered) training data:
        # its Nyström residual under the trained lengthscales is large.
        far = kernels.MixedFeatures(
            jnp.full((1, sdata.z_continuous.shape[-1]), 4.0, jnp.float32),
            jnp.zeros((1, sdata.z_categorical.shape[-1]), jnp.int32),
        )
        grown = gp_ucb_pe_lib._append_row_sparse(sdata, far, member)
        assert int(jnp.sum(grown.inducing_mask)) == before + 1
        # The pick also joined the data rows (pending conditioning).
        assert int(jnp.sum(grown.data.row_mask)) == int(
            jnp.sum(sdata.data.row_mask)
        ) + 1

    def test_near_pick_does_not_augment(self):
        member, d = self._trained_member()
        all_data = d._all_points_data(2)
        sdata = sparse_gp.with_pending_capacity(member.sdata, all_data, 2)
        before = int(jnp.sum(sdata.inducing_mask))
        # An existing inducing row has zero Nyström residual by definition.
        near = kernels.MixedFeatures(
            sdata.z_continuous[:1], sdata.z_categorical[:1]
        )
        same = gp_ucb_pe_lib._append_row_sparse(sdata, near, member)
        assert int(jnp.sum(same.inducing_mask)) == before
        assert int(jnp.sum(same.data.row_mask)) == int(
            jnp.sum(sdata.data.row_mask)
        ) + 1

    def test_conditioning_deflates_stddev_at_the_pick(self):
        """Appending a pending pick must reduce the conditioned posterior's
        stddev there — the whole point of UCB-PE's all-points posterior."""
        member, d = self._trained_member()
        all_data = d._all_points_data(2)
        sdata = sparse_gp.with_pending_capacity(member.sdata, all_data, 2)
        aug_model = d._sparse_all_model(2)
        x = kernels.MixedFeatures(
            jnp.full((1, sdata.z_continuous.shape[-1]), 0.9, jnp.float32),
            jnp.zeros((1, sdata.z_categorical.shape[-1]), jnp.int32),
        )
        coll = aug_model.param_collection()
        p = member.params
        before_state = aug_model.precompute_constrained(p, sdata)
        _, std_before = before_state.predict(x)
        grown = gp_ucb_pe_lib._append_row_sparse(sdata, x, member)
        after_state = aug_model.precompute_constrained(p, grown)
        _, std_after = after_state.predict(x)
        assert float(std_after[0]) < float(std_before[0])


class TestBatchPickDiversity:
    def test_batch_picks_are_distinct_points(self):
        d = _sparse_designer(8)
        out = d.suggest(4)
        points = [tuple(sorted(p.items())) for p in _params(out)]
        assert len(set(points)) == len(points), (
            "pending-pick conditioning failed: duplicate batch picks"
        )


class TestChaosSlotIsolation:
    # ~27 s chaos soak on a 1-core box; slot isolation for the sparse
    # kind is also exercised by the generic executor chaos tests and the
    # chaos_ab harness, so this rides the slow tier (tier-1 timing,
    # ROADMAP.md).
    @pytest.mark.slow
    def test_faulting_sparse_slot_degrades_only_its_own_study(self):
        monkey = chaos_lib.ChaosMonkey(seed=0, failure_prob=1.0)
        chaotic = chaos_lib.ChaosDesigner(_sparse_designer(51), monkey)
        healthy = [_sparse_designer(52), _sparse_designer(53)]
        sequential = [_params(_sparse_designer(s).suggest(1)) for s in (52, 53)]
        ex = BatchExecutor(max_batch_size=3, max_wait_ms=10_000)
        try:
            designers = [chaotic] + healthy
            results = [None] * 3
            errors = [None] * 3

            def run(i):
                try:
                    results[i] = ex.suggest(designers[i], 1)
                except BaseException as e:  # noqa: BLE001
                    errors[i] = e

            threads = [
                threading.Thread(target=run, args=(i,)) for i in range(3)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=240)
            assert isinstance(errors[0], chaos_lib.failing.FailedSuggestError)
            assert errors[1] is None and errors[2] is None
            for seq, res in zip(sequential, (results[1], results[2])):
                got = _params(res)
                assert seq == got
        finally:
            ex.close()

    def test_chaos_program_wraps_sparse_kind(self):
        monkey = chaos_lib.ChaosMonkey(seed=0, failure_prob=0.0)
        wrapped = chaos_lib.ChaosDesigner(_sparse_designer(60), monkey)
        resolved = wrapped.compute_program(1)
        assert resolved is not None
        program, key = resolved
        assert isinstance(program, chaos_lib.ChaosProgram)
        assert key.kind == "gp_ucb_pe_sparse"
        assert program.surrogate_family == "sparse"


class TestSparseFitSurface:
    def test_predict_and_sample_over_sparse_fit(self):
        d = _sparse_designer(70)
        out = d.suggest(2)
        prediction = d.predict(out, rng=np.random.default_rng(0), num_samples=64)
        assert prediction.mean.shape == (2,)
        assert np.all(np.isfinite(prediction.mean))
        assert np.all(prediction.stddev >= 0)

    def test_sparse_metadata_kind_stamped(self):
        d = _sparse_designer(71)
        out = d.suggest(1)
        ns = out[0].metadata.ns("gp_ucb_pe")
        assert ns.get("acquisition") is not None

    def test_exact_and_sparse_never_share_a_bucket(self):
        sparse_key = _sparse_designer(80).batch_bucket_key(1)
        exact_key = _feed(
            VizierGPUCBPEBandit(_problem(), rng_seed=81, **_FAST), 81
        ).batch_bucket_key(1)
        assert sparse_key.kind == "gp_ucb_pe_sparse"
        assert exact_key.kind == "gp_ucb_pe"
        assert sparse_key != exact_key
