"""SurrogateConfig: validation, hysteresis semantics, env overrides."""

import pytest

from vizier_tpu.surrogates import SurrogateConfig
from vizier_tpu.surrogates import config as config_lib


class TestValidation:
    def test_defaults_valid(self):
        cfg = SurrogateConfig()
        assert cfg.sparse
        assert cfg.sparse_threshold_trials > cfg.hysteresis_trials

    @pytest.mark.parametrize(
        "kwargs",
        [
            dict(sparse_threshold_trials=0),
            dict(sparse_threshold_trials=-5),
            dict(hysteresis_trials=-1),
            dict(num_inducing=0),
        ],
    )
    def test_bad_knobs_rejected(self, kwargs):
        with pytest.raises(ValueError):
            SurrogateConfig(**kwargs)

    def test_disabled_is_exact_everywhere(self):
        cfg = SurrogateConfig.disabled()
        assert not cfg.sparse
        for n in (0, 10, 10_000):
            assert cfg.mode_for(n) == config_lib.MODE_EXACT
            assert (
                cfg.mode_for(n, current=config_lib.MODE_SPARSE)
                == config_lib.MODE_EXACT
            )


class TestModeFor:
    def test_threshold_crossing(self):
        cfg = SurrogateConfig(sparse_threshold_trials=50, hysteresis_trials=10)
        assert cfg.mode_for(49) == config_lib.MODE_EXACT
        assert cfg.mode_for(50) == config_lib.MODE_SPARSE
        assert cfg.mode_for(500) == config_lib.MODE_SPARSE

    def test_hysteresis_band_is_sticky(self):
        cfg = SurrogateConfig(sparse_threshold_trials=50, hysteresis_trials=10)
        # Inside [40, 50): a sparse study stays sparse, an exact study
        # stays exact — the boundary cannot flap.
        for n in range(40, 50):
            assert cfg.mode_for(n, current=config_lib.MODE_SPARSE) == (
                config_lib.MODE_SPARSE
            )
            assert cfg.mode_for(n, current=config_lib.MODE_EXACT) == (
                config_lib.MODE_EXACT
            )
        # Below the band, sparse drops back to exact.
        assert (
            cfg.mode_for(39, current=config_lib.MODE_SPARSE)
            == config_lib.MODE_EXACT
        )

    def test_zero_hysteresis(self):
        cfg = SurrogateConfig(sparse_threshold_trials=8, hysteresis_trials=0)
        assert cfg.mode_for(8, current=config_lib.MODE_SPARSE) == (
            config_lib.MODE_SPARSE
        )
        assert cfg.mode_for(7, current=config_lib.MODE_SPARSE) == (
            config_lib.MODE_EXACT
        )


class TestEnv:
    def test_from_env_defaults(self, monkeypatch):
        for name in (
            "VIZIER_SPARSE",
            "VIZIER_SPARSE_THRESHOLD",
            "VIZIER_SPARSE_HYSTERESIS",
            "VIZIER_SPARSE_INDUCING",
        ):
            monkeypatch.delenv(name, raising=False)
        cfg = SurrogateConfig.from_env()
        assert cfg == SurrogateConfig()

    def test_env_overrides(self, monkeypatch):
        monkeypatch.setenv("VIZIER_SPARSE_THRESHOLD", "100")
        monkeypatch.setenv("VIZIER_SPARSE_HYSTERESIS", "7")
        monkeypatch.setenv("VIZIER_SPARSE_INDUCING", "32")
        cfg = SurrogateConfig.from_env()
        assert cfg.sparse_threshold_trials == 100
        assert cfg.hysteresis_trials == 7
        assert cfg.num_inducing == 32

    def test_master_off_switch(self, monkeypatch):
        monkeypatch.setenv("VIZIER_SPARSE", "0")
        cfg = SurrogateConfig.from_env()
        assert not cfg.sparse
        assert cfg.mode_for(10_000) == config_lib.MODE_EXACT

    def test_as_dict_stampable(self):
        d = SurrogateConfig().as_dict()
        assert set(d) == {
            "sparse",
            "sparse_threshold_trials",
            "hysteresis_trials",
            "num_inducing",
            "sparse_ucb_pe",
        }
        import json

        json.dumps(d)  # must be JSON-serializable for bench artifacts
