"""Sparse-GP math: exact-recovery at Z=X, mask safety, k-center, training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vizier_tpu.models import gp as gp_lib
from vizier_tpu.models import kernels
from vizier_tpu.optimizers import lbfgs as lbfgs_lib
from vizier_tpu.surrogates import sparse_bandit
from vizier_tpu.surrogates import sparse_gp


def _data(n, d, seed=0, pad_to=None):
    """GPData with ``n`` valid rows of a smooth function, padded to
    ``pad_to`` masked filler rows."""
    rng = np.random.default_rng(seed)
    n_pad = pad_to or n
    cont = np.zeros((n_pad, d), np.float32)
    cont[:n] = rng.uniform(size=(n, d)).astype(np.float32)
    labels = np.zeros(n_pad, np.float32)
    labels[:n] = np.sin(3.0 * cont[:n, 0]) + cont[:n, 1:].sum(axis=1)
    # z-score the valid labels (what the output warper feeds the GP).
    labels[:n] = (labels[:n] - labels[:n].mean()) / max(labels[:n].std(), 1e-6)
    mask = np.arange(n_pad) < n
    return gp_lib.GPData(
        continuous=jnp.asarray(cont),
        categorical=jnp.zeros((n_pad, 0), jnp.int32),
        labels=jnp.asarray(labels),
        row_mask=jnp.asarray(mask),
        cont_dim_mask=jnp.ones((d,), bool),
        cat_dim_mask=jnp.ones((0,), bool),
    )


def _models(d, m):
    base = gp_lib.VizierGaussianProcess(num_continuous=d, num_categorical=0)
    return base, sparse_gp.SparseGaussianProcess(base=base, num_inducing=m)


def _mid_params(coll):
    """Fixed well-conditioned constrained params, mapped to unconstrained."""
    vals = {"amplitude": 1.0, "noise_stddev": 0.1, "continuous_length_scales": 0.5}
    constrained = {
        spec.name: jnp.full(spec.shape, vals[spec.name], jnp.float32)
        for spec in coll.specs
    }
    return coll.unconstrain(constrained)


def _queries(d, q=32, seed=9):
    rng = np.random.default_rng(seed)
    return kernels.MixedFeatures(
        jnp.asarray(rng.uniform(size=(q, d)).astype(np.float32)),
        jnp.zeros((q, 0), jnp.int32),
    )


class TestExactRecovery:
    def test_full_inducing_set_recovers_exact_posterior(self):
        # SGPR with Z = X is mathematically the exact GP; the implementation
        # must agree to numerical jitter.
        n, d = 24, 3
        data = _data(n, d)
        base, sparse = _models(d, n)
        u = _mid_params(base.param_collection())

        exact_state = base.precompute(u, data)
        sdata = sparse_gp.SparseGPData(
            data=data,
            z_continuous=data.continuous,
            z_categorical=data.categorical,
            inducing_mask=data.row_mask,
            inducing_indices=jnp.arange(n, dtype=jnp.int32),
        )
        sparse_state = sparse.precompute(u, sdata)

        q = _queries(d)
        em, es = exact_state.predict(q)
        sm, ss = sparse_state.predict(q)
        np.testing.assert_allclose(np.asarray(em), np.asarray(sm), atol=2e-3)
        np.testing.assert_allclose(np.asarray(es), np.asarray(ss), atol=2e-3)

    def test_collapsed_bound_lower_bounds_exact_likelihood(self):
        # Titsias: ELBO <= log p(y), so -bound >= exact NLL (both sides
        # carry the same ARD regularizer, which cancels in the comparison);
        # at Z = X the bound is tight.
        n, d = 20, 2
        data = _data(n, d, seed=3)
        base, sparse_full = _models(d, n)
        u = _mid_params(base.param_collection())
        exact_nll = float(base.neg_log_likelihood(u, data))

        sdata_full = sparse_gp.SparseGPData(
            data=data,
            z_continuous=data.continuous,
            z_categorical=data.categorical,
            inducing_mask=data.row_mask,
            inducing_indices=jnp.arange(n, dtype=jnp.int32),
        )
        tight = float(sparse_full.neg_log_likelihood(u, sdata_full))
        assert abs(tight - exact_nll) < 0.5, (tight, exact_nll)

        _, sparse_small = _models(d, 6)
        sdata_small = sparse_gp.select_inducing_kcenter(data, 6)
        loose = float(sparse_small.neg_log_likelihood(u, sdata_small))
        assert loose >= exact_nll - 0.5, (loose, exact_nll)


class TestMaskSafety:
    def test_padded_rows_do_not_change_posterior(self):
        n, d, m = 18, 3, 8
        u = _mid_params(
            gp_lib.VizierGaussianProcess(
                num_continuous=d, num_categorical=0
            ).param_collection()
        )
        _, sparse = _models(d, m)
        q = _queries(d)

        plain = sparse.precompute(
            u, sparse_gp.select_inducing_kcenter(_data(n, d, seed=5), m)
        )
        padded = sparse.precompute(
            u, sparse_gp.select_inducing_kcenter(_data(n, d, seed=5, pad_to=32), m)
        )
        pm, ps = plain.predict(q)
        qm, qs = padded.predict(q)
        np.testing.assert_allclose(np.asarray(pm), np.asarray(qm), atol=1e-5)
        np.testing.assert_allclose(np.asarray(ps), np.asarray(qs), atol=1e-5)

    def test_padded_inducing_slots_do_not_change_posterior(self):
        # Fewer valid rows than inducing slots: the surplus slots repeat
        # chosen rows and MUST be masked out of the posterior — m=8 over 5
        # valid rows equals m=5 over the same rows.
        n, d = 5, 2
        data = _data(n, d, seed=7)
        q = _queries(d)
        u = _mid_params(
            gp_lib.VizierGaussianProcess(
                num_continuous=d, num_categorical=0
            ).param_collection()
        )

        _, tight_model = _models(d, n)
        tight = tight_model.precompute(
            u, sparse_gp.select_inducing_kcenter(data, n)
        )
        _, padded_model = _models(d, 8)
        sdata = sparse_gp.select_inducing_kcenter(data, 8)
        assert int(jnp.sum(sdata.inducing_mask)) == n
        padded = padded_model.precompute(u, sdata)

        tm, ts = tight.predict(q)
        pm, ps = padded.predict(q)
        np.testing.assert_allclose(np.asarray(tm), np.asarray(pm), atol=1e-4)
        np.testing.assert_allclose(np.asarray(ts), np.asarray(ps), atol=1e-4)


class TestKCenterSelection:
    def test_deterministic_and_starts_at_incumbent(self):
        data = _data(30, 3, seed=11)
        a = sparse_gp.select_inducing_kcenter(data, 10)
        b = sparse_gp.select_inducing_kcenter(data, 10)
        np.testing.assert_array_equal(
            np.asarray(a.inducing_indices), np.asarray(b.inducing_indices)
        )
        incumbent = int(jnp.argmax(data.labels))
        assert int(a.inducing_indices[0]) == incumbent

    def test_selects_distinct_spread_points(self):
        data = _data(30, 3, seed=13)
        sdata = sparse_gp.select_inducing_kcenter(data, 10)
        idx = np.asarray(sdata.inducing_indices)
        assert len(set(idx.tolist())) == 10  # no duplicates while n > m
        assert bool(jnp.all(sdata.inducing_mask))

    def test_ignores_masked_rows(self):
        # Padding rows (mask False) must never be selected as inducing
        # points even though they sit at the (distant) origin.
        data = _data(12, 3, seed=17, pad_to=32)
        sdata = sparse_gp.select_inducing_kcenter(data, 8)
        idx = np.asarray(sdata.inducing_indices)
        assert (idx < 12).all(), idx


class TestTraining:
    def test_train_fits_and_warm_restart_is_stable(self):
        n, d, m = 40, 3, 16
        data = _data(n, d, seed=19)
        _, model = _models(d, m)
        opt = lbfgs_lib.LbfgsOptimizer(maxiter=30)

        state = sparse_bandit._train_sparse_gp(
            model, opt, data, jax.random.PRNGKey(0), 4, 1, None
        )
        mean, _ = jax.tree_util.tree_map(lambda a: a[0], state).predict(
            data.features()
        )
        mean = np.asarray(mean)[: n]
        labels = np.asarray(data.labels)[:n]
        corr = np.corrcoef(mean, labels)[0, 1]
        assert corr > 0.9, corr  # the collapsed bound trained a real fit

        # Warm restart: seeding with the trained optimum keeps the fit.
        coll = model.param_collection()
        warm = coll.unconstrain(
            jax.tree_util.tree_map(lambda a: a[0], state.params)
        )
        warm_state = sparse_bandit._train_sparse_gp(
            model, opt, data, jax.random.PRNGKey(1), 2, 1, warm
        )
        mean2, _ = jax.tree_util.tree_map(lambda a: a[0], warm_state).predict(
            data.features()
        )
        corr2 = np.corrcoef(np.asarray(mean2)[:n], labels)[0, 1]
        assert corr2 > 0.9, corr2

    def test_posterior_tracks_exact_gp_closely(self):
        # m = n/2 inducing points on smooth data: the sparse posterior mean
        # must stay close to the exact GP's at the same hyperparameters.
        n, d, m = 32, 2, 16
        data = _data(n, d, seed=23)
        base, sparse = _models(d, m)
        u = _mid_params(base.param_collection())
        exact_state = base.precompute(u, data)
        sparse_state = sparse.precompute(
            u, sparse_gp.select_inducing_kcenter(data, m)
        )
        q = _queries(d)
        em, _ = exact_state.predict(q)
        sm, _ = sparse_state.predict(q)
        err = float(jnp.max(jnp.abs(em - sm)))
        spread = float(jnp.max(jnp.abs(em))) + 1e-6
        assert err / spread < 0.25, (err, spread)

    def test_ensemble_predictive_moment_matches(self):
        n, d, m = 20, 2, 8
        data = _data(n, d, seed=29)
        _, model = _models(d, m)
        opt = lbfgs_lib.LbfgsOptimizer(maxiter=10)
        states = sparse_bandit._train_sparse_gp(
            model, opt, data, jax.random.PRNGKey(2), 4, 2, None
        )
        pred = sparse_gp.SparseEnsemblePredictive(states)
        mean, stddev = pred.predict(_queries(d, q=8))
        assert mean.shape == (8,) and stddev.shape == (8,)
        assert bool(jnp.all(jnp.isfinite(mean)))
        assert bool(jnp.all(stddev > 0))
