"""Designer-level auto-switch: threshold, hysteresis, crossover hygiene,
and the off-switch's bit-identity with the seed exact path."""

import jax
import numpy as np
import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.designers.gp_bandit import VizierGPBandit
from vizier_tpu.optimizers import lbfgs as lbfgs_lib
from vizier_tpu.surrogates import SurrogateConfig
from vizier_tpu.surrogates import config as config_lib

_FAST = dict(
    ard_optimizer=lbfgs_lib.AdamOptimizer(maxiter=15),
    ard_restarts=3,
    max_acquisition_evaluations=200,
    warm_start_min_trials=0,
    num_seed_trials=1,
)


def _problem(num_params=2):
    p = vz.ProblemStatement()
    for d in range(num_params):
        p.search_space.root.add_float_param(f"x{d}", 0.0, 1.0)
    p.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return p


def _trials(start_id, n, seed, num_params=2):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        params = {f"x{d}": float(rng.uniform()) for d in range(num_params)}
        t = vz.Trial(parameters=params, id=start_id + i)
        t.complete(
            vz.Measurement(metrics={"obj": float(sum(params.values()))})
        )
        out.append(t)
    return out


def _params_lists(suggestions):
    return [s.parameters.as_dict() for s in suggestions]


def _tree_equal(a, b):
    leaves_a = jax.tree_util.tree_leaves(a)
    leaves_b = jax.tree_util.tree_leaves(b)
    return len(leaves_a) == len(leaves_b) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(leaves_a, leaves_b)
    )


class TestAutoSwitch:
    def test_exact_below_threshold_sparse_above(self):
        cfg = SurrogateConfig(
            sparse_threshold_trials=8, hysteresis_trials=2, num_inducing=6
        )
        d = VizierGPBandit(_problem(), rng_seed=0, surrogate=cfg, **_FAST)
        d.update(core_lib.CompletedTrials(_trials(1, 5, seed=0)))
        d.suggest(1)
        assert d.surrogate_mode == config_lib.MODE_EXACT
        assert d.surrogate_counts == {"sparse_suggests": 0, "crossovers": 0}

        d.update(core_lib.CompletedTrials(_trials(6, 3, seed=1)))
        out = d.suggest(1)
        assert d.surrogate_mode == config_lib.MODE_SPARSE
        assert d.surrogate_counts["sparse_suggests"] == 1
        assert d.surrogate_counts["crossovers"] == 1
        assert d.sparse_inducing_state() is not None
        assert len(out) == 1
        for v in out[0].parameters.as_dict().values():
            assert np.isfinite(v)

    def test_no_config_means_exact_forever(self):
        d = VizierGPBandit(_problem(), rng_seed=0, **_FAST)
        d.update(core_lib.CompletedTrials(_trials(1, 12, seed=0)))
        d.suggest(1)
        assert d.surrogate_mode == config_lib.MODE_EXACT
        assert d.sparse_inducing_state() is None

    def test_sparse_suggestions_accumulate_without_recrossing(self):
        cfg = SurrogateConfig(
            sparse_threshold_trials=4, hysteresis_trials=0, num_inducing=6
        )
        d = VizierGPBandit(_problem(), rng_seed=1, surrogate=cfg, **_FAST)
        d.update(core_lib.CompletedTrials(_trials(1, 6, seed=2)))
        d.suggest(1)
        d.update(core_lib.CompletedTrials(_trials(7, 1, seed=3)))
        d.suggest(1)
        assert d.surrogate_counts["sparse_suggests"] == 2
        assert d.surrogate_counts["crossovers"] == 1  # one transition only


class TestCrossoverInvalidation:
    """Satellite: no stale exact-GP params may leak into the sparse path."""

    def test_crossover_drops_warm_and_posterior_state(self):
        cfg = SurrogateConfig(
            sparse_threshold_trials=8, hysteresis_trials=2, num_inducing=6
        )
        d = VizierGPBandit(_problem(), rng_seed=3, surrogate=cfg, **_FAST)
        d.update(core_lib.CompletedTrials(_trials(1, 6, seed=4)))
        d.suggest(1)  # exact train
        assert d._warm_is_trained
        exact_warm = jax.tree_util.tree_map(np.asarray, d._warm_params)
        assert d._last_predictive is not None

        # Crossing the threshold re-randomizes the warm seed BEFORE any
        # sparse train: the trained exact optimum must not seed (or be
        # served from) the sparse posterior.
        d.update(core_lib.CompletedTrials(_trials(7, 3, seed=5)))
        mode = d._refresh_surrogate_mode()
        assert mode == config_lib.MODE_SPARSE
        assert not d._warm_is_trained
        assert d._last_predictive is None
        assert d._last_sparse_state is None
        assert not _tree_equal(exact_warm, d._warm_params)

        # The next suggest runs the sparse path from the clean slate.
        d.suggest(1)
        assert d.surrogate_counts["sparse_suggests"] == 1
        assert d._warm_is_trained  # now holds the SPARSE optimum
        assert not _tree_equal(exact_warm, d._warm_params)

    def test_mode_is_sticky_across_suggests(self):
        cfg = SurrogateConfig(
            sparse_threshold_trials=6, hysteresis_trials=3, num_inducing=6
        )
        d = VizierGPBandit(_problem(), rng_seed=4, surrogate=cfg, **_FAST)
        d.update(core_lib.CompletedTrials(_trials(1, 7, seed=6)))
        d.suggest(1)
        assert d.surrogate_mode == config_lib.MODE_SPARSE
        # Repeated suggests at the same count stay sparse with no new
        # crossovers (the hysteresis floor is 3, trials stay at 7).
        d.suggest(1)
        assert d.surrogate_counts["crossovers"] == 1


class TestOffSwitchBitIdentity:
    """VIZIER_SPARSE=0 (or no config) must be the seed exact path exactly."""

    @pytest.mark.parametrize(
        "off_cfg", [None, SurrogateConfig.disabled()], ids=["none", "disabled"]
    )
    def test_disabled_matches_no_config_suggestions(self, off_cfg):
        seeds_trials = _trials(1, 10, seed=7)
        base = VizierGPBandit(_problem(), rng_seed=5, **_FAST)
        base.update(core_lib.CompletedTrials(seeds_trials))
        expected = _params_lists(base.suggest(2))

        d = VizierGPBandit(_problem(), rng_seed=5, surrogate=off_cfg, **_FAST)
        d.update(core_lib.CompletedTrials(seeds_trials))
        got = _params_lists(d.suggest(2))
        assert expected == got  # bit-identical, not approximately equal

    def test_below_threshold_matches_no_config_suggestions(self):
        # An enabled config whose threshold is never reached must also be
        # bit-identical to the seed path (the switch reads state only).
        seeds_trials = _trials(1, 10, seed=8)
        base = VizierGPBandit(_problem(), rng_seed=6, **_FAST)
        base.update(core_lib.CompletedTrials(seeds_trials))
        expected = _params_lists(base.suggest(1))

        cfg = SurrogateConfig(sparse_threshold_trials=10_000)
        d = VizierGPBandit(_problem(), rng_seed=6, surrogate=cfg, **_FAST)
        d.update(core_lib.CompletedTrials(seeds_trials))
        assert _params_lists(d.suggest(1)) == expected
