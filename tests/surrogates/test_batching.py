"""Sparse studies through the cross-study batch executor: bucket
separation from exact studies, slot parity, chaos isolation, prewarm."""

import threading

import numpy as np
import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.designers.gp_bandit import VizierGPBandit
from vizier_tpu.optimizers import lbfgs as lbfgs_lib
from vizier_tpu.parallel.batch_executor import BatchExecutor
from vizier_tpu.serving.stats import ServingStats
from vizier_tpu.surrogates import SurrogateConfig
from vizier_tpu.testing import chaos as chaos_lib

_FAST = dict(
    ard_optimizer=lbfgs_lib.AdamOptimizer(maxiter=15),
    ard_restarts=3,
    max_acquisition_evaluations=200,
    warm_start_min_trials=0,
    num_seed_trials=1,
)

# Sparse from 4 completed trials on; m=6 pads into the 8-slot bucket.
_SPARSE = SurrogateConfig(
    sparse_threshold_trials=4, hysteresis_trials=0, num_inducing=6
)


def _problem():
    p = vz.ProblemStatement()
    for d in range(2):
        p.search_space.root.add_float_param(f"x{d}", 0.0, 1.0)
    p.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return p


def _feed(designer, seed, n=6):
    rng = np.random.default_rng(seed)
    trials = []
    for i in range(n):
        t = vz.Trial(
            parameters={"x0": float(rng.uniform()), "x1": float(rng.uniform())},
            id=i + 1,
        )
        t.complete(vz.Measurement(metrics={"obj": float(rng.uniform())}))
        trials.append(t)
    designer.update(core_lib.CompletedTrials(trials))
    return designer


def _sparse_designer(seed):
    return VizierGPBandit(_problem(), rng_seed=seed, surrogate=_SPARSE, **_FAST)


def _exact_designer(seed):
    return VizierGPBandit(_problem(), rng_seed=seed, **_FAST)


def _params(suggestions):
    return [s.parameters.as_dict() for s in suggestions]


def _assert_params_equal(a, b, atol=1e-6):
    assert len(a) == len(b)
    for pa, pb in zip(a, b):
        assert pa.keys() == pb.keys()
        for k in pa:
            assert abs(pa[k] - pb[k]) <= atol, (k, pa[k], pb[k])


def _run_concurrent(executor, designers, count=1):
    results = [None] * len(designers)
    errors = [None] * len(designers)

    def run(i):
        try:
            results[i] = executor.suggest(designers[i], count)
        except BaseException as e:  # noqa: BLE001 - tests inspect the error
            errors[i] = e

    threads = [
        threading.Thread(target=run, args=(i,)) for i in range(len(designers))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=240)
    return results, errors


class TestBucketSeparation:
    def test_sparse_and_exact_studies_land_in_different_buckets(self):
        sparse_key = _feed(_sparse_designer(1), 1).batch_bucket_key(1)
        exact_key = _feed(_exact_designer(2), 2).batch_bucket_key(1)
        assert sparse_key is not None and exact_key is not None
        assert sparse_key.kind == "gp_bandit_sparse"
        assert exact_key.kind == "gp_bandit"
        assert sparse_key != exact_key

    def test_same_sparse_config_same_bucket(self):
        a = _feed(_sparse_designer(3), 3).batch_bucket_key(1)
        b = _feed(_sparse_designer(4), 4).batch_bucket_key(1)
        assert a == b

    def test_different_inducing_bucket_different_key(self):
        # m=6 pads to 8 slots; m=12 pads to 16 — a different compiled
        # program family, so a different bucket.
        big_m = SurrogateConfig(
            sparse_threshold_trials=4, hysteresis_trials=0, num_inducing=12
        )
        d_big = VizierGPBandit(_problem(), rng_seed=5, surrogate=big_m, **_FAST)
        a = _feed(_sparse_designer(5), 5).batch_bucket_key(1)
        b = _feed(d_big, 6).batch_bucket_key(1)
        assert a != b

    def test_below_threshold_uses_exact_bucket(self):
        cfg = SurrogateConfig(
            sparse_threshold_trials=100, hysteresis_trials=0, num_inducing=6
        )
        d = VizierGPBandit(_problem(), rng_seed=7, surrogate=cfg, **_FAST)
        key = _feed(d, 7).batch_bucket_key(1)
        assert key.kind == "gp_bandit"


class TestSparseBatchedParity:
    def test_batched_slots_match_sequential_sparse(self):
        seeds = (11, 12)
        sequential = [_feed(_sparse_designer(s), s).suggest(1) for s in seeds]

        batched = [_feed(_sparse_designer(s), s) for s in seeds]
        keys = [d.batch_bucket_key(1) for d in batched]
        assert keys[0] == keys[1]
        items = [d.batch_prepare(1) for d in batched]
        assert all(item["sparse"] for item in items)
        outs = batched[0].batch_execute(items, pad_to=4)
        batched_out = [
            d.batch_finalize(i, o) for d, i, o in zip(batched, items, outs)
        ]
        for i in range(len(seeds)):
            _assert_params_equal(_params(sequential[i]), _params(batched_out[i]))
        # Batched sparse suggests update the designer's sparse bookkeeping.
        assert batched[0].surrogate_counts["sparse_suggests"] == 1
        assert batched[0].sparse_inducing_state() is not None
        assert batched[0]._warm_is_trained

    # ~26 s end-to-end soak on a 1-core box; the per-kind slot parity it
    # composes is asserted directly by the faster tests in this class, so
    # the mixed-traffic composition rides the slow tier (tier-1 timing,
    # ROADMAP.md).
    @pytest.mark.slow
    def test_mixed_workload_end_to_end(self):
        # 2 exact + 2 sparse studies submitted concurrently: each kind
        # fuses into its own flush, and every slot matches its sequential
        # twin exactly.
        exact_seeds, sparse_seeds = (21, 22), (23, 24)
        seq_exact = [_feed(_exact_designer(s), s).suggest(1) for s in exact_seeds]
        seq_sparse = [
            _feed(_sparse_designer(s), s).suggest(1) for s in sparse_seeds
        ]

        stats = ServingStats()
        ex = BatchExecutor(max_batch_size=2, max_wait_ms=10_000, stats=stats)
        try:
            designers = [_feed(_exact_designer(s), s) for s in exact_seeds] + [
                _feed(_sparse_designer(s), s) for s in sparse_seeds
            ]
            results, errors = _run_concurrent(ex, designers)
            assert errors == [None] * 4
            for i in range(2):
                _assert_params_equal(_params(seq_exact[i]), _params(results[i]))
                _assert_params_equal(
                    _params(seq_sparse[i]), _params(results[i + 2])
                )
            assert stats.snapshot()["batched_suggests"] == 4
        finally:
            ex.close()


class TestSparseChaosIsolation:
    def test_faulting_sparse_slot_degrades_only_its_own_study(self):
        monkey = chaos_lib.ChaosMonkey(seed=0, failure_prob=1.0)
        chaotic = chaos_lib.ChaosDesigner(_feed(_sparse_designer(31), 31), monkey)
        healthy = [_feed(_sparse_designer(s), s) for s in (32, 33)]
        sequential = [_feed(_sparse_designer(s), s).suggest(1) for s in (32, 33)]
        stats = ServingStats()
        ex = BatchExecutor(max_batch_size=3, max_wait_ms=10_000, stats=stats)
        try:
            results, errors = _run_concurrent(ex, [chaotic] + healthy)
            assert isinstance(errors[0], chaos_lib.failing.FailedSuggestError)
            assert errors[1] is None and errors[2] is None
            for i, seq in enumerate(sequential):
                _assert_params_equal(_params(seq), _params(results[i + 1]))
            snap = stats.snapshot()
            assert snap["batch_slot_errors"] == 1
            assert snap["batched_suggests"] == 2
        finally:
            ex.close()


class TestSparsePrewarm:
    def test_prewarm_compiles_the_sparse_flush_program(self):
        from vizier_tpu.surrogates import sparse_bandit

        # Threshold 2 makes every prewarm bucket (>= 8 synthetic trials)
        # sparse, exercising the sparse program family end to end.
        cfg = SurrogateConfig(
            sparse_threshold_trials=2, hysteresis_trials=0, num_inducing=6
        )
        # A search-space shape no other test compiles, so the cache-growth
        # assertion holds regardless of in-process test order.
        problem = vz.ProblemStatement()
        for d in range(3):
            problem.search_space.root.add_float_param(f"p{d}", 0.0, 1.0)
        problem.metric_information.append(
            vz.MetricInformation(
                name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE
            )
        )
        before = sparse_bandit._sparse_flush_program._cache_size()
        ex = BatchExecutor(max_batch_size=2, max_wait_ms=10)
        try:
            report = ex.prewarm(
                problem,
                lambda p: VizierGPBandit(p, rng_seed=0, surrogate=cfg, **_FAST),
                max_trials=8,
                counts=(1,),
            )
            assert [r["pad_trials"] for r in report] == [8, 8]
            assert all(r["status"] == "ok" for r in report)
            # The batched (size=max) prewarm leg compiled the sparse flush.
            assert sparse_bandit._sparse_flush_program._cache_size() > before
        finally:
            ex.close()
