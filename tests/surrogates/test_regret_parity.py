"""Sparse surrogate must not change regret: rank-sum parity at 5 seeds.

A cheap CI-scale version of the full A/B in ``tools/surrogate_ab.py``
(SPARSE_AB.json): the sparse arm runs the SGPR collapsed-bound posterior
from the first post-seed suggest (threshold 1), the exact arm the seed
O(n³) path, on the same shifted-sphere instances. Deterministic given the
pinned seeds, so the gate is stable.
"""

import numpy as np

from vizier_tpu import pyvizier as vz
from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.benchmarks.experimenters import experimenter_factory
from vizier_tpu.designers.gp_bandit import VizierGPBandit
from vizier_tpu.optimizers import lbfgs as lbfgs_lib
from vizier_tpu.surrogates import SurrogateConfig

SEEDS = (1, 2, 3, 4, 5)
DIM = 4
TRIALS = 12
BATCH = 4


def _rank_sum_p(a, b) -> float:
    """Two-sided Mann-Whitney p (normal approximation), H0: same dist."""
    from scipy import stats

    a, b = np.asarray(a, float), np.asarray(b, float)
    ranks = stats.rankdata(np.concatenate([a, b]))
    n, m = len(a), len(b)
    u = ranks[:n].sum() - n * (n + 1) / 2.0
    mu, sigma = n * m / 2.0, np.sqrt(n * m * (n + m + 1) / 12.0)
    return float(2.0 * (1.0 - stats.norm.cdf(abs(u - mu) / max(sigma, 1e-9))))


def _run_arm(seed: int, sparse: bool) -> float:
    exp = experimenter_factory.shifted_bbob_instance("Sphere", seed, dim=DIM)
    surrogate = (
        SurrogateConfig(
            sparse_threshold_trials=1, hysteresis_trials=0, num_inducing=8
        )
        if sparse
        else None
    )
    designer = VizierGPBandit(
        exp.problem_statement(),
        rng_seed=seed,
        num_seed_trials=4,
        max_acquisition_evaluations=500,
        ard_restarts=2,
        ard_optimizer=lbfgs_lib.LbfgsOptimizer(maxiter=8),
        warm_start_min_trials=0,
        surrogate=surrogate,
    )
    best, tid = np.inf, 0
    while tid < TRIALS:
        batch = [
            s.to_trial(tid + i + 1) for i, s in enumerate(designer.suggest(BATCH))
        ]
        tid += len(batch)
        exp.evaluate(batch)
        designer.update(core_lib.CompletedTrials(batch))
        for t in batch:
            best = min(best, t.final_measurement.metrics["bbob_eval"].value)
    if sparse:
        assert designer.surrogate_counts["sparse_suggests"] > 0
    return best


def test_sparse_vs_exact_regret_parity():
    sparse_finals = [_run_arm(s, sparse=True) for s in SEEDS]
    exact_finals = [_run_arm(s, sparse=False) for s in SEEDS]
    p = _rank_sum_p(sparse_finals, exact_finals)
    # Parity: the sparse arm's final regrets must be statistically
    # indistinguishable from the exact arm's (deterministic given SEEDS).
    assert p > 0.05, (
        f"sparse={sparse_finals} exact={exact_finals} rank-sum p={p:.4f}"
    )
