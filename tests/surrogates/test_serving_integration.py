"""The surrogate tier through the serving stack: runtime config threading,
stats counters, cache-entry mirrors, and DeleteStudy invalidation."""

import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.service import proto_converters as pc
from vizier_tpu.service import pythia_service, vizier_service
from vizier_tpu.service.policy_factory import DefaultPolicyFactory
from vizier_tpu.service.protos import vizier_service_pb2
from vizier_tpu.serving.runtime import ServingRuntime
from vizier_tpu.surrogates import SurrogateConfig

STUDY = "owners/o/studies/s"


def _study_config(num_params=2):
    config = vz.StudyConfig(algorithm="GAUSSIAN_PROCESS_BANDIT")
    for d in range(num_params):
        config.search_space.root.add_float_param(f"x{d}", 0.0, 1.0)
    config.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return config


def _create_study(servicer, name=STUDY):
    study = pc.study_to_proto(_study_config(), name)
    servicer.CreateStudy(
        vizier_service_pb2.CreateStudyRequest(parent="owners/o", study=study)
    )


def _complete_some_trials(servicer, n, name=STUDY, start=0):
    from vizier_tpu.service.protos import study_pb2

    for i in range(n):
        created = servicer.CreateTrial(
            vizier_service_pb2.CreateTrialRequest(
                parent=name, trial=study_pb2.Trial()
            )
        )
        req = vizier_service_pb2.CompleteTrialRequest(name=created.name)
        m = req.final_measurement.metrics.add()
        m.name, m.value = "obj", 0.1 * ((start + i) % 9)
        servicer.CompleteTrial(req)


@pytest.fixture()
def sparse_service():
    """A real service whose GP designers auto-switch at 6 trials."""
    surrogates = SurrogateConfig(
        sparse_threshold_trials=6, hysteresis_trials=2, num_inducing=6
    )
    servicer = vizier_service.VizierServicer()
    pythia = pythia_service.PythiaServicer(servicer, surrogate_config=surrogates)
    runtime = pythia.serving_runtime
    assert runtime.surrogates is surrogates  # the passthrough under test
    pythia._policy_factory = _FastFactory(runtime)
    servicer.set_pythia(pythia)
    return servicer, pythia, runtime


class _FastFactory(DefaultPolicyFactory):
    """DefaultPolicyFactory with cheap GP knobs layered on top — the
    surrogate threading under test is the REAL factory code path."""

    def _gp_designer_kwargs(self):
        kwargs = super()._gp_designer_kwargs()
        from vizier_tpu.optimizers import lbfgs as lbfgs_lib

        kwargs.update(
            max_acquisition_evaluations=200,
            ard_restarts=2,
            ard_optimizer=lbfgs_lib.LbfgsOptimizer(maxiter=5),
            warm_start_min_trials=0,
            num_seed_trials=1,
        )
        return kwargs


def _suggest(servicer, step):
    op = servicer.SuggestTrials(
        vizier_service_pb2.SuggestTrialsRequest(
            parent=STUDY, suggestion_count=1, client_id=f"w{step}"
        )
    )
    assert op.done and not op.error, op.error
    return op


class TestFactoryThreading:
    def test_default_factory_threads_runtime_surrogates(self):
        surrogates = SurrogateConfig(sparse_threshold_trials=123)
        runtime = ServingRuntime(surrogates=surrogates)
        factory = DefaultPolicyFactory(runtime)
        kwargs = factory._gp_designer_kwargs()
        assert kwargs["surrogate"] is surrogates

    def test_no_runtime_no_surrogate_kwarg(self):
        assert "surrogate" not in DefaultPolicyFactory()._gp_designer_kwargs()

    def test_runtime_reads_env(self, monkeypatch):
        monkeypatch.setenv("VIZIER_SPARSE", "0")
        assert not ServingRuntime().surrogates.sparse
        monkeypatch.setenv("VIZIER_SPARSE", "1")
        monkeypatch.setenv("VIZIER_SPARSE_THRESHOLD", "77")
        rt = ServingRuntime()
        assert rt.surrogates.sparse
        assert rt.surrogates.sparse_threshold_trials == 77


class TestServingAutoSwitch:
    def test_crossover_counters_and_entry_mirrors(self, sparse_service):
        servicer, pythia, runtime = sparse_service
        _create_study(servicer)
        _complete_some_trials(servicer, 3)
        _suggest(servicer, 0)  # 3 trials: exact

        snap = pythia.serving_stats()
        assert snap["sparse_suggests"] == 0
        assert snap["surrogate_crossovers"] == 0
        entry = runtime.designer_cache.get_or_create(STUDY, lambda: None)
        assert entry.surrogate_mode == "exact"
        assert entry.sparse_state is None

        _complete_some_trials(servicer, 4, start=3)
        _suggest(servicer, 1)  # 7 completed trials: sparse

        snap = pythia.serving_stats()
        assert snap["sparse_suggests"] == 1
        assert snap["surrogate_crossovers"] == 1
        entry = runtime.designer_cache.get_or_create(STUDY, lambda: None)
        assert entry.surrogate_mode == "sparse"
        # The cached inducing state (selected set + factorization) is
        # mirrored for inspection/hand-off.
        assert entry.sparse_state is not None
        assert entry.sparse_state.sdata.z_continuous.shape[-2] >= 6

        _suggest(servicer, 2)  # stays sparse, no second crossover
        snap = pythia.serving_stats()
        assert snap["sparse_suggests"] == 2
        assert snap["surrogate_crossovers"] == 1

    def test_delete_study_drops_cached_inducing_state(self, sparse_service):
        # Satellite: DeleteStudy must invalidate the whole entry — warm
        # params AND sparse inducing state — so a recreated study of the
        # same name cold-starts with nothing stale.
        servicer, pythia, runtime = sparse_service
        _create_study(servicer)
        _complete_some_trials(servicer, 7)
        _suggest(servicer, 0)
        entry = runtime.designer_cache.get_or_create(STUDY, lambda: None)
        assert entry.sparse_state is not None
        assert pythia.serving_stats()["cached_studies"] == 1

        servicer.DeleteStudy(
            vizier_service_pb2.DeleteStudyRequest(name=STUDY)
        )
        snap = pythia.serving_stats()
        assert snap["cached_studies"] == 0
        assert snap["cache_invalidations"] == 1

        # A recreated same-name study builds a FRESH entry: no mirrored
        # mode, no sparse state, cold designer.
        _create_study(servicer)
        _complete_some_trials(servicer, 2)
        _suggest(servicer, 1)
        fresh = runtime.designer_cache.get_or_create(STUDY, lambda: None)
        assert fresh is not entry
        assert fresh.surrogate_mode == "exact"
        assert fresh.sparse_state is None

    def test_sparse_off_runtime_serves_exact_only(self):
        servicer = vizier_service.VizierServicer()
        pythia = pythia_service.PythiaServicer(
            servicer, surrogate_config=SurrogateConfig.disabled()
        )
        pythia._policy_factory = _FastFactory(pythia.serving_runtime)
        servicer.set_pythia(pythia)
        runtime = pythia.serving_runtime
        _create_study(servicer)
        _complete_some_trials(servicer, 8)
        _suggest(servicer, 0)
        snap = pythia.serving_stats()
        assert snap["sparse_suggests"] == 0
        assert snap["surrogate_crossovers"] == 0
        entry = runtime.designer_cache.get_or_create(STUDY, lambda: None)
        assert entry.surrogate_mode == "exact"
