"""Retrace regression guard: the jit cache must be stable within a padding
bucket and grow by exactly one entry at a bucket boundary.

Padding (``converters.padding``) exists so the designers' jitted programs
compile once per ``(pad_trials, features)`` bucket — every retrace costs
~seconds of XLA compile on TPU and silently destroys serving latency. This
test pins that contract for the hot entry points of both GP designers:
growing a study within one bucket must not add cache entries; crossing a
bucket boundary must add exactly one.
"""

import numpy as np
import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.designers import gp_bandit as gp_bandit_lib
from vizier_tpu.designers import gp_ucb_pe as gp_ucb_pe_lib
from vizier_tpu.optimizers import lbfgs as lbfgs_lib

_FAST = dict(
    ard_optimizer=lbfgs_lib.AdamOptimizer(maxiter=10),
    ard_restarts=2,
    max_acquisition_evaluations=200,
)


def _problem():
    p = vz.ProblemStatement()
    for d in range(2):
        p.search_space.root.add_float_param(f"x{d}", 0.0, 1.0)
    p.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return p


def _trials(start_id, n, seed):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        t = vz.Trial(
            parameters={"x0": float(rng.uniform()), "x1": float(rng.uniform())},
            id=start_id + i,
        )
        t.complete(vz.Measurement(metrics={"obj": float(rng.uniform())}))
        out.append(t)
    return out


def _cache_sizes(fns):
    return tuple(fn._cache_size() for fn in fns)


class TestGPBanditJitStability:
    def test_stable_within_bucket_one_retrace_at_boundary(self):
        fns = (gp_bandit_lib._train_gp, gp_bandit_lib._maximize_acquisition)
        designer = gp_bandit_lib.VizierGPBandit(_problem(), rng_seed=0, **_FAST)

        designer.update(core_lib.CompletedTrials(_trials(1, 4, seed=0)))
        designer.suggest(1)
        baseline = _cache_sizes(fns)

        # Growing 4 -> 8 trials stays inside the pad_trials=8 bucket: the
        # jit cache must not move while the study grows within it.
        for step in range(4):
            designer.update(
                core_lib.CompletedTrials(_trials(5 + step, 1, seed=10 + step))
            )
            designer.suggest(1)
            assert _cache_sizes(fns) == baseline, (
                f"retrace inside padding bucket at {5 + step} trials"
            )

        # Trial 9 crosses into the pad_trials=16 bucket: exactly one new
        # cache entry per program, never more.
        designer.update(core_lib.CompletedTrials(_trials(9, 1, seed=99)))
        designer.suggest(1)
        grown = _cache_sizes(fns)
        assert grown == tuple(b + 1 for b in baseline), (
            f"bucket boundary must add exactly one entry: {baseline} -> {grown}"
        )

        # And the new bucket is itself stable.
        designer.update(core_lib.CompletedTrials(_trials(10, 1, seed=100)))
        designer.suggest(1)
        assert _cache_sizes(fns) == grown


class TestGPUCBPEJitStability:
    def test_stable_within_bucket_one_retrace_at_boundary(self):
        fns = (gp_bandit_lib._train_gp, gp_ucb_pe_lib._suggest_batch)
        designer = gp_ucb_pe_lib.VizierGPUCBPEBandit(
            _problem(), rng_seed=0, **_FAST
        )

        designer.update(core_lib.CompletedTrials(_trials(1, 3, seed=0)))
        designer.suggest(1)
        baseline = _cache_sizes(fns)

        # 3 -> 7 completed trials: training data stays in the pad=8 bucket
        # AND the all-points set (trials + 1 batch pick) stays <= 8, so
        # neither program may retrace.
        for step in range(4):
            designer.update(
                core_lib.CompletedTrials(_trials(4 + step, 1, seed=10 + step))
            )
            designer.suggest(1)
            assert _cache_sizes(fns) == baseline, (
                f"retrace inside padding bucket at {4 + step} trials"
            )

        # Trial 8: training data still pads to 8, but the all-points set
        # (8 + 1 pick = 9 rows) crosses into the 16 bucket — the batch-loop
        # program retraces once, the ARD program must not.
        designer.update(core_lib.CompletedTrials(_trials(8, 1, seed=99)))
        designer.suggest(1)
        train_base, sweep_base = baseline
        assert gp_bandit_lib._train_gp._cache_size() == train_base
        assert gp_ucb_pe_lib._suggest_batch._cache_size() == sweep_base + 1


class TestSparseJitStability:
    """The sparse programs compile once per (n-bucket, m-bucket) pair."""

    def _sparse_designer(self, seed, num_inducing=6):
        from vizier_tpu.surrogates import SurrogateConfig

        cfg = SurrogateConfig(
            sparse_threshold_trials=1, hysteresis_trials=0,
            num_inducing=num_inducing,
        )
        return gp_bandit_lib.VizierGPBandit(
            _problem(), rng_seed=seed, surrogate=cfg, num_seed_trials=1,
            **_FAST,
        )

    def test_stable_within_bucket_one_retrace_at_n_boundary(self):
        from vizier_tpu.surrogates import sparse_bandit

        fns = (
            sparse_bandit._train_sparse_gp,
            sparse_bandit._maximize_sparse_acquisition,
        )
        designer = self._sparse_designer(seed=0)
        designer.update(core_lib.CompletedTrials(_trials(1, 4, seed=0)))
        designer.suggest(1)
        assert designer.surrogate_mode == "sparse"
        baseline = _cache_sizes(fns)

        # Growing 4 -> 8 trials stays inside the pad_trials=8 bucket (the
        # m-bucket is fixed at 8 inducing slots): no retrace allowed.
        for step in range(4):
            designer.update(
                core_lib.CompletedTrials(_trials(5 + step, 1, seed=10 + step))
            )
            designer.suggest(1)
            assert _cache_sizes(fns) == baseline, (
                f"sparse retrace inside padding bucket at {5 + step} trials"
            )

        # Trial 9 crosses into the pad_trials=16 n-bucket: exactly one new
        # entry per program.
        designer.update(core_lib.CompletedTrials(_trials(9, 1, seed=99)))
        designer.suggest(1)
        grown = _cache_sizes(fns)
        assert grown == tuple(b + 1 for b in baseline), (
            f"n-bucket boundary must add exactly one entry: {baseline} -> {grown}"
        )

        # And the new (n, m) pair is itself stable.
        designer.update(core_lib.CompletedTrials(_trials(10, 1, seed=100)))
        designer.suggest(1)
        assert _cache_sizes(fns) == grown

    def test_m_bucket_boundary_and_same_bucket_m_values(self):
        from vizier_tpu.surrogates import sparse_bandit

        train = sparse_bandit._train_sparse_gp
        base = self._sparse_designer(seed=1, num_inducing=6)
        base.update(core_lib.CompletedTrials(_trials(1, 4, seed=1)))
        base.suggest(1)
        size = train._cache_size()

        # m=7 pads to the SAME 8-slot m-bucket as m=6: one shared program.
        same_bucket = self._sparse_designer(seed=2, num_inducing=7)
        same_bucket.update(core_lib.CompletedTrials(_trials(1, 4, seed=2)))
        same_bucket.suggest(1)
        assert train._cache_size() == size, (
            "m values inside one inducing bucket must share a program"
        )

        # m=12 pads to 16 slots: a new m-bucket, exactly one new entry.
        new_bucket = self._sparse_designer(seed=3, num_inducing=12)
        new_bucket.update(core_lib.CompletedTrials(_trials(1, 4, seed=3)))
        new_bucket.suggest(1)
        assert train._cache_size() == size + 1

    def test_sparse_flush_program_stable_across_flushes_within_bucket(self):
        from vizier_tpu.surrogates import sparse_bandit

        def fresh(seed, n):
            d = self._sparse_designer(seed)
            d.update(core_lib.CompletedTrials(_trials(1, n, seed=seed)))
            return d

        def flush(seeds, n):
            designers = [fresh(s, n) for s in seeds]
            # Same calling convention as the executor: the bucket key
            # refreshes each designer's surrogate mode before prepare.
            keys = [d.batch_bucket_key(1) for d in designers]
            assert len(set(keys)) == 1 and keys[0].kind == "gp_bandit_sparse"
            items = [d.batch_prepare(1) for d in designers]
            outs = designers[0].batch_execute(items, pad_to=len(items))
            for d, i, o in zip(designers, items, outs):
                d.batch_finalize(i, o)

        program = sparse_bandit._sparse_flush_program
        flush((40, 41), n=4)
        size = program._cache_size()
        flush((42, 43), n=5)  # same (n, m) bucket pair, different studies
        assert program._cache_size() == size

        flush((44, 45), n=9)  # n-bucket boundary: exactly one new entry
        assert program._cache_size() == size + 1


class TestSparseUCBPEJitStability:
    """The sparse UCB-PE programs compile once per (n-bucket, m-bucket)
    pair — including the augmented-capacity re-conditioning model."""

    def _designer(self, seed, num_inducing=6):
        from vizier_tpu.surrogates import SurrogateConfig

        cfg = SurrogateConfig(
            sparse_threshold_trials=1, hysteresis_trials=0,
            num_inducing=num_inducing,
        )
        return gp_ucb_pe_lib.VizierGPUCBPEBandit(
            _problem(), rng_seed=seed, surrogate=cfg, **_FAST
        )

    def test_sequential_stable_within_bucket_one_retrace_at_n_boundary(self):
        from vizier_tpu.surrogates import sparse_bandit

        fns = (sparse_bandit._train_sparse_gp, gp_ucb_pe_lib._suggest_batch)
        designer = self._designer(seed=0)
        designer.update(core_lib.CompletedTrials(_trials(1, 3, seed=0)))
        designer.suggest(1)
        assert designer.surrogate_mode == "sparse"
        baseline = _cache_sizes(fns)

        # 3 -> 7 completed trials: the n-bucket stays 8 and the all-points
        # set (trials + 1 pick) stays <= 8 — no retrace of either program.
        for step in range(4):
            designer.update(
                core_lib.CompletedTrials(_trials(4 + step, 1, seed=10 + step))
            )
            designer.suggest(1)
            assert _cache_sizes(fns) == baseline, (
                f"sparse UCB-PE retrace inside bucket at {4 + step} trials"
            )

        # Trial 8: the all-points set (8 + 1 pick) crosses into the 16
        # bucket — the batch-loop program retraces once, the ARD must not.
        designer.update(core_lib.CompletedTrials(_trials(8, 1, seed=99)))
        designer.suggest(1)
        train_base, sweep_base = baseline
        from vizier_tpu.surrogates import sparse_bandit as sb

        assert sb._train_sparse_gp._cache_size() == train_base
        assert gp_ucb_pe_lib._suggest_batch._cache_size() == sweep_base + 1

    def test_m_bucket_boundary_and_same_bucket_m_values(self):
        from vizier_tpu.surrogates import sparse_bandit

        # 10 trials put the study in the n=16 bucket: an (n, m) grid point
        # no other test's train program touches (the sparse ARD program is
        # deliberately SHARED with the gp_bandit sparse path, so colliding
        # grid points would hide real retraces).
        train = sparse_bandit._train_sparse_gp
        base = self._designer(seed=1, num_inducing=6)
        base.update(core_lib.CompletedTrials(_trials(1, 10, seed=1)))
        base.suggest(1)
        size = train._cache_size()

        # m=7 pads to the SAME 8-slot m-bucket as m=6: one shared program.
        same_bucket = self._designer(seed=2, num_inducing=7)
        same_bucket.update(core_lib.CompletedTrials(_trials(1, 10, seed=2)))
        same_bucket.suggest(1)
        assert train._cache_size() == size, (
            "m values inside one inducing bucket must share a program"
        )

        # m=12 pads to 16 slots: a new (n=16, m=16) pair, exactly one new
        # entry.
        new_bucket = self._designer(seed=3, num_inducing=12)
        new_bucket.update(core_lib.CompletedTrials(_trials(1, 10, seed=3)))
        new_bucket.suggest(1)
        assert train._cache_size() == size + 1

    def test_sparse_flush_program_stable_across_flushes_within_bucket(self):
        def fresh(seed, n):
            d = self._designer(seed)
            d.update(core_lib.CompletedTrials(_trials(1, n, seed=seed)))
            return d

        def flush(seeds, n):
            designers = [fresh(s, n) for s in seeds]
            keys = [d.batch_bucket_key(1) for d in designers]
            assert len(set(keys)) == 1 and keys[0].kind == "gp_ucb_pe_sparse"
            items = [d.batch_prepare(1) for d in designers]
            outs = designers[0].batch_execute(items, pad_to=len(items))
            for d, i, o in zip(designers, items, outs):
                d.batch_finalize(i, o)

        program = gp_ucb_pe_lib._sparse_ucb_pe_flush_program
        flush((40, 41), n=3)
        size = program._cache_size()
        flush((42, 43), n=4)  # same (n, m) bucket pair, different studies
        assert program._cache_size() == size

        flush((44, 45), n=9)  # n-bucket boundary: exactly one new entry
        assert program._cache_size() == size + 1


class TestIRRoutedProgramJitStability:
    """The compute-IR port must not change compile-cache behavior: flushes
    routed through the registered programs share one compiled body per
    bucket, +1 exactly at a bucket boundary."""

    def test_ir_routed_flushes_share_the_bucket_program(self):
        from vizier_tpu.compute import registry as compute_registry

        def fresh(seed, n):
            d = gp_bandit_lib.VizierGPBandit(_problem(), rng_seed=seed, **_FAST)
            d.update(core_lib.CompletedTrials(_trials(1, n, seed=seed)))
            return d

        # count=2 keeps this test's compiled programs disjoint from the
        # count=1 flushes other tests in this file drive (count is a jit
        # static of the same shared flush body).
        def flush(seeds, n):
            designers = [fresh(s, n) for s in seeds]
            resolved = [compute_registry.resolve(d, 2) for d in designers]
            assert all(r is not None for r in resolved)
            program = resolved[0][0]
            assert program.kind == "gp_bandit"
            items = [program.prepare(d, 2) for d in designers]
            outs = program.device_program(items, pad_to=len(items))
            for d, i, o in zip(designers, items, outs):
                program.finalize(d, i, o)

        body = gp_bandit_lib._gp_bandit_flush_program
        flush((60, 61), n=4)
        size = body._cache_size()
        flush((62, 63), n=5)  # same bucket through the IR: no retrace
        assert body._cache_size() == size
        flush((64, 65), n=9)  # boundary: exactly one new entry
        assert body._cache_size() == size + 1


class TestBatchedProgramJitStability:
    def test_batched_programs_stable_across_flushes_within_bucket(self):
        # Two batched flushes over different studies in the same bucket
        # must share one compiled multi-study program.
        def fresh(seed, n):
            d = gp_bandit_lib.VizierGPBandit(_problem(), rng_seed=seed, **_FAST)
            d.update(core_lib.CompletedTrials(_trials(1, n, seed=seed)))
            return d

        def flush(seeds, n):
            designers = [fresh(s, n) for s in seeds]
            items = [d.batch_prepare(1) for d in designers]
            outs = designers[0].batch_execute(items, pad_to=len(items))
            for d, i, o in zip(designers, items, outs):
                d.batch_finalize(i, o)

        program = gp_bandit_lib._gp_bandit_flush_program
        flush((0, 1), n=4)
        size = program._cache_size()
        flush((2, 3), n=5)  # same pad bucket, different studies/data
        assert program._cache_size() == size

        flush((4, 5), n=9)  # bucket boundary: exactly one new entry
        assert program._cache_size() == size + 1


class TestMeshJitStability:
    """Mesh-mode compile contract: one compiled flush program per (bucket,
    placement, shard-granularity grid step).

    The mesh executor pads a placement's flushes to
    ``DevicePlacement.pad_to`` (power-of-two multiples of its device
    count) instead of the flat pad-to-max — so the compiled-shape set per
    (bucket, placement) is exactly the small ``pad_grid``, stable at fixed
    occupancy, +1 when the occupancy crosses a grid step, and +1 when the
    SAME bucket compiles on a different placement (sticky assignment makes
    that a prewarm-only event in production)."""

    def test_one_program_per_bucket_placement_grid_step(self):
        import jax

        from vizier_tpu.compute import registry as compute_registry
        from vizier_tpu.parallel.mesh import DevicePlacement

        def fresh(seed, n):
            d = gp_bandit_lib.VizierGPBandit(_problem(), rng_seed=seed, **_FAST)
            d.update(core_lib.CompletedTrials(_trials(1, n, seed=seed)))
            return d

        # count=3 keeps this test's compiled programs disjoint from the
        # count=1/2 flushes other tests in this file drive.
        def flush(seeds, placement):
            designers = [fresh(s, 4) for s in seeds]
            resolved = [compute_registry.resolve(d, 3) for d in designers]
            assert all(r is not None for r in resolved)
            program = resolved[0][0]
            assert program.shardable_batch_axis == "study"
            items = [program.prepare(d, 3) for d in designers]
            pad_to = placement.pad_to(len(items), 8)
            outs = program.device_program(
                items, pad_to=pad_to, placement=placement
            )
            for d, i, o in zip(designers, items, outs):
                program.finalize(d, i, o)

        body = gp_bandit_lib._gp_bandit_flush_program
        devices = jax.devices()
        p0 = DevicePlacement(0, devices[:1])
        p1 = DevicePlacement(1, devices[1:2])

        flush((70, 71), p0)  # occupancy 2 -> padded 2 on placement 0
        size = body._cache_size()
        flush((72, 73), p0)  # same (bucket, placement, grid step): stable
        assert body._cache_size() == size
        flush((74, 75, 76), p0)  # occupancy 3 -> grid step 4: one new entry
        assert body._cache_size() == size + 1
        flush((77, 78, 79, 80), p0)  # occupancy 4 -> same grid step: stable
        assert body._cache_size() == size + 1
        # The same bucket on a DIFFERENT placement compiles its own
        # program (sticky assignment keeps this out of the serving path).
        flush((81, 82), p1)
        assert body._cache_size() == size + 2
        flush((83, 84), p1)  # and stays stable there too
        assert body._cache_size() == size + 2
