"""GP-UCB-PE behavioral tests (reference ``gp_ucb_pe_test.py`` scenarios).

Covers: pending-point batch diversity, the UCB/PE decision logic and its
overwrite probabilities, multimetric penalty modes + HV-scalarized UCB,
the joint set acquisition, the high-noise regime, capacity guarding, and
unwarped prediction.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.designers.gp_ucb_pe import (
    UCBPEConfig,
    VizierGPUCBPEBandit,
    _append_row,
)
from vizier_tpu.optimizers import lbfgs as lbfgs_lib

_FAST_ARD = lbfgs_lib.AdamOptimizer(maxiter=20)


def _single_metric_problem(categorical: bool = False) -> vz.ProblemStatement:
    p = vz.ProblemStatement()
    p.search_space.root.add_float_param("x", 0.0, 1.0)
    if categorical:
        p.search_space.root.add_categorical_param("c", ["a", "b", "c"])
    p.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return p


def _multi_metric_problem() -> vz.ProblemStatement:
    p = vz.ProblemStatement()
    p.search_space.root.add_float_param("x", 0.0, 1.0)
    p.search_space.root.add_categorical_param("c", ["a", "b"])
    p.metric_information.append(
        vz.MetricInformation(name="f1", goal=vz.ObjectiveMetricGoal.MINIMIZE)
    )
    p.metric_information.append(
        vz.MetricInformation(name="f2", goal=vz.ObjectiveMetricGoal.MINIMIZE)
    )
    return p


def _designer(problem, **kwargs):
    kwargs.setdefault("max_acquisition_evaluations", 300)
    kwargs.setdefault("ard_restarts", 2)
    kwargs.setdefault("ard_optimizer", _FAST_ARD)
    return VizierGPUCBPEBandit(problem, **kwargs)


def _complete(problem, xs, fn, start_id=1):
    trials = []
    names = problem.search_space.parameter_names()
    for i, x in enumerate(xs):
        params = {"x": float(x)}
        if "c" in names:
            values = list(problem.search_space.get("c").feasible_values)
            params["c"] = values[i % len(values)]
        t = vz.Trial(id=start_id + i, parameters=params)
        metrics = fn(float(x))
        t.complete(vz.Measurement(metrics=metrics))
        trials.append(t)
    return trials


class TestDecisionLogic:
    def test_first_pick_is_ucb_with_fresh_completions(self):
        """pe_overwrite_probability=0 → fresh data forces UCB on pick 1."""
        p = _single_metric_problem()
        d = _designer(
            p,
            config=UCBPEConfig(
                pe_overwrite_probability=0.0,
                pe_overwrite_probability_in_high_noise=0.0,
                ucb_overwrite_probability=0.0,
            ),
        )
        d.update(
            core_lib.CompletedTrials(
                _complete(p, np.linspace(0, 1, 6), lambda x: {"obj": -((x - 0.6) ** 2)})
            )
        )
        s = d.suggest(3)
        flags = [si.metadata.ns("gp_ucb_pe")["use_ucb"] for si in s]
        assert flags[0] == "True"
        # Later picks see pick 1 as pending → PE (overwrite prob is 0).
        assert flags[1] == "False" and flags[2] == "False"

    def test_all_pe_when_no_new_completions(self):
        """Active trials newer than completions → PE (ucb_overwrite=0)."""
        p = _single_metric_problem()
        d = _designer(p, config=UCBPEConfig(ucb_overwrite_probability=0.0))
        completed = _complete(
            p, np.linspace(0, 1, 5), lambda x: {"obj": -((x - 0.4) ** 2)}
        )
        active = [vz.Trial(id=50, parameters={"x": 0.9})]  # created after
        d.update(core_lib.CompletedTrials(completed), core_lib.ActiveTrials(active))
        s = d.suggest(2)
        flags = [si.metadata.ns("gp_ucb_pe")["use_ucb"] for si in s]
        assert flags == ["False", "False"]

    def test_ucb_overwrite_probability_one_forces_ucb(self):
        p = _single_metric_problem()
        d = _designer(p, config=UCBPEConfig(ucb_overwrite_probability=1.0))
        completed = _complete(
            p, np.linspace(0, 1, 5), lambda x: {"obj": -((x - 0.4) ** 2)}
        )
        active = [vz.Trial(id=50, parameters={"x": 0.9})]
        d.update(core_lib.CompletedTrials(completed), core_lib.ActiveTrials(active))
        s = d.suggest(2)
        flags = [si.metadata.ns("gp_ucb_pe")["use_ucb"] for si in s]
        assert flags == ["True", "True"]


class TestBatchDiversity:
    def test_batch_picks_are_distinct(self):
        """Pending-point conditioning must spread the batch out.

        Sparse data keeps real posterior uncertainty between observations, so
        the PE picks have room to diversify; with a dense noiseless quadratic
        the promising region itself shrinks to a point and crowding is the
        semantically-correct behavior.
        """
        p = _single_metric_problem()
        d = _designer(
            p,
            max_acquisition_evaluations=800,
            config=UCBPEConfig(
                pe_overwrite_probability=0.0,
                ucb_overwrite_probability=0.0,
                cb_violation_penalty_coefficient=1.0,
            ),
        )
        d.update(
            core_lib.CompletedTrials(
                _complete(p, [0.1, 0.9], lambda x: {"obj": -((x - 0.5) ** 2)})
            )
        )
        s = d.suggest(4)
        xs = sorted(float(si.parameters["x"].value) for si in s)
        gaps = np.diff(xs)
        # No two suggestions collapse onto the same point.
        assert (gaps > 1e-3).all(), xs

    def test_pure_categorical_batch_explores_new_cells(self):
        """Regression: the trust region must not fence the batch onto
        observed categorical cells (it once put every unobserved combo at
        L-inf 1.0 > radius, collapsing all picks onto one observed cell)."""
        p = vz.ProblemStatement()
        for i in range(4):
            p.search_space.root.add_categorical_param(
                f"op{i}", ["a", "b", "c", "d"]
            )
        p.metric_information.append(
            vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
        d = _designer(p, max_acquisition_evaluations=800)
        rng = np.random.default_rng(0)
        trials = []
        for i in range(6):
            cell = {f"op{j}": "abcd"[rng.integers(4)] for j in range(4)}
            t = vz.Trial(id=i + 1, parameters=cell)
            t.complete(
                vz.Measurement(
                    metrics={"obj": float(sum(v == "a" for v in cell.values()))}
                )
            )
            trials.append(t)
        observed = {
            tuple(str(t.parameters.get_value(f"op{j}")) for j in range(4))
            for t in trials
        }
        d.update(core_lib.CompletedTrials(trials))
        suggested = {
            tuple(str(s.parameters[f"op{j}"].value) for j in range(4))
            for s in d.suggest(4)
        }
        # The batch is diverse AND reaches outside the observed cells.
        assert len(suggested) > 1, suggested
        assert suggested - observed, (suggested, observed)

    def test_pending_active_trials_are_avoided(self):
        """A pending point deflates stddev around itself → PE goes elsewhere."""
        p = _single_metric_problem()
        d = _designer(
            p,
            max_acquisition_evaluations=800,
            config=UCBPEConfig(ucb_overwrite_probability=0.0),
        )
        completed = _complete(
            p, np.linspace(0, 1, 6), lambda x: {"obj": -((x - 0.5) ** 2)}
        )
        active = [vz.Trial(id=40, parameters={"x": 0.52})]
        d.update(core_lib.CompletedTrials(completed), core_lib.ActiveTrials(active))
        s = d.suggest(1)
        x = float(s[0].parameters["x"].value)
        assert abs(x - 0.52) > 0.02


class TestMultimetric:
    @pytest.mark.parametrize("mode", ["union", "intersection", "average"])
    def test_penalty_modes_run_mixed_space(self, mode):
        p = _multi_metric_problem()
        d = _designer(
            p,
            config=UCBPEConfig(
                num_scalarizations=32,
                multimetric_promising_region_penalty_type=mode,
            ),
        )
        trials = []
        for i, x in enumerate(np.linspace(0, 1, 6)):
            t = vz.Trial(
                id=i + 1, parameters={"x": float(x), "c": ["a", "b"][i % 2]}
            )
            t.complete(
                vz.Measurement(metrics={"f1": x**2, "f2": (x - 1) ** 2})
            )
            trials.append(t)
        d.update(core_lib.CompletedTrials(trials))
        s = d.suggest(3)  # mixed-space multi-objective q-batch: the gap row
        assert len(s) == 3
        assert all("use_ucb" in si.metadata.ns("gp_ucb_pe") for si in s)

    def test_invalid_penalty_mode_rejected(self):
        with pytest.raises(ValueError):
            UCBPEConfig(multimetric_promising_region_penalty_type="bogus")

    def test_multimetric_predict_shapes(self):
        p = _multi_metric_problem()
        d = _designer(p, config=UCBPEConfig(num_scalarizations=16))
        trials = []
        for i, x in enumerate(np.linspace(0, 1, 5)):
            t = vz.Trial(
                id=i + 1, parameters={"x": float(x), "c": ["a", "b"][i % 2]}
            )
            t.complete(vz.Measurement(metrics={"f1": x, "f2": 1 - x}))
            trials.append(t)
        d.update(core_lib.CompletedTrials(trials))
        s = d.suggest(2)
        pred = d.predict(s, num_samples=64)
        assert pred.mean.shape == (2, 2)
        assert np.isfinite(pred.stddev).all()


class TestSetAcquisition:
    def test_joint_set_pe_batch(self):
        p = _single_metric_problem()
        d = _designer(
            p,
            config=UCBPEConfig(optimize_set_acquisition_for_exploration=True),
        )
        d.update(
            core_lib.CompletedTrials(
                _complete(p, np.linspace(0, 1, 6), lambda x: {"obj": -((x - 0.3) ** 2)})
            )
        )
        s = d.suggest(3)
        assert len(s) == 3
        xs = sorted(float(si.parameters["x"].value) for si in s)
        # log-det objective decorrelates the set: members must not coincide.
        assert (np.diff(xs) > 1e-4).all(), xs

    def test_set_acquisition_rejects_multimetric(self):
        p = _multi_metric_problem()
        d = _designer(
            p,
            config=UCBPEConfig(optimize_set_acquisition_for_exploration=True),
        )
        trials = []
        for i, x in enumerate(np.linspace(0, 1, 5)):
            t = vz.Trial(
                id=i + 1, parameters={"x": float(x), "c": ["a", "b"][i % 2]}
            )
            t.complete(vz.Measurement(metrics={"f1": x, "f2": 1 - x}))
            trials.append(t)
        d.update(core_lib.CompletedTrials(trials))
        with pytest.raises(ValueError, match="one objective"):
            d.suggest(2)


class TestHighNoiseRegime:
    def test_snr_flips_pe_probability(self):
        """In high noise, pe_overwrite_in_high_noise=1 forces PE on pick 1."""
        p = _single_metric_problem()
        d = _designer(
            p,
            config=UCBPEConfig(
                signal_to_noise_threshold=1e6,  # everything counts as noisy
                pe_overwrite_probability=0.0,
                pe_overwrite_probability_in_high_noise=1.0,
                ucb_overwrite_probability=0.0,
            ),
        )
        rng = np.random.default_rng(0)
        d.update(
            core_lib.CompletedTrials(
                _complete(
                    p,
                    np.linspace(0, 1, 8),
                    lambda x: {"obj": float(rng.normal())},  # pure noise
                )
            )
        )
        s = d.suggest(1)
        assert s[0].metadata.ns("gp_ucb_pe")["use_ucb"] == "False"


class TestPlumbing:
    def test_capacity_reserved_for_batch(self):
        p = _single_metric_problem()
        d = _designer(p)
        d.update(
            core_lib.CompletedTrials(
                _complete(p, np.linspace(0, 1, 7), lambda x: {"obj": x})
            )
        )
        all_data = d._all_points_data(5)
        spare = all_data.row_mask.shape[0] - int(jnp.sum(all_data.row_mask))
        assert spare >= 5

    def test_append_row_fills_first_free_slot(self):
        p = _single_metric_problem()
        d = _designer(p)
        d.update(
            core_lib.CompletedTrials(
                _complete(p, np.linspace(0, 1, 3), lambda x: {"obj": x})
            )
        )
        all_data = d._all_points_data(2)
        n_before = int(jnp.sum(all_data.row_mask))
        from vizier_tpu.models import kernels as kernels_lib

        x = kernels_lib.MixedFeatures(
            jnp.full((1, all_data.continuous.shape[-1]), 0.25),
            jnp.zeros((1, all_data.categorical.shape[-1]), jnp.int32),
        )
        grown = _append_row(all_data, x)
        assert int(jnp.sum(grown.row_mask)) == n_before + 1
        np.testing.assert_allclose(grown.continuous[n_before], 0.25)

    def test_seed_trials_count_includes_active(self):
        p = _single_metric_problem()
        d = _designer(p, num_seed_trials=3)
        active = [vz.Trial(id=i, parameters={"x": 0.5}) for i in range(1, 4)]
        d.update(core_lib.CompletedTrials([]), core_lib.ActiveTrials(active))
        # 3 active >= 3 seeds → GP path (runs ARD on an empty completed set).
        s = d.suggest(1)
        assert len(s) == 1

    def test_sample_with_zero_completed_trials(self):
        """sample()/predict() on a fresh study (active-only) must not crash."""
        p = _single_metric_problem()
        d = _designer(p, num_seed_trials=2)
        active = [vz.Trial(id=i, parameters={"x": 0.3 * i}) for i in (1, 2)]
        d.update(core_lib.CompletedTrials([]), core_lib.ActiveTrials(active))
        s = d.suggest(1)
        samples = d.sample(s, rng=jax.random.PRNGKey(0), num_samples=8)
        assert samples.shape == (8, 1)
        assert np.isfinite(samples).all()

    def test_predict_reuses_cached_fit(self):
        """predict() after suggest() must not retrain the GP."""
        p = _single_metric_problem()
        d = _designer(p)
        d.update(
            core_lib.CompletedTrials(
                _complete(p, np.linspace(0, 1, 6), lambda x: {"obj": x})
            )
        )
        s = d.suggest(1)
        assert d._cached_states is not None
        states_before = d._cached_states[0]
        d.predict(s, num_samples=16)
        assert d._cached_states[0] is states_before  # same fit object
        # New completed data invalidates the cache.
        d.update(
            core_lib.CompletedTrials(
                _complete(p, [0.55], lambda x: {"obj": x}, start_id=50)
            )
        )
        assert d._cached_states is None

    def test_unwarped_sample_scale(self):
        """Samples come back in the ORIGINAL metric scale, not warped."""
        p = _single_metric_problem()
        d = _designer(p)
        # Labels around 1000 — warped space is ~[-0.5, 0.5], so unwarping
        # must restore the magnitude.
        d.update(
            core_lib.CompletedTrials(
                _complete(p, np.linspace(0, 1, 8), lambda x: {"obj": 1000.0 + x})
            )
        )
        s = d.suggest(1)
        samples = d.sample(s, rng=jax.random.PRNGKey(1), num_samples=32)
        assert samples.shape == (32, 1)
        assert 900.0 < np.median(samples) < 1100.0


class TestPriorAcquisition:
    def _problem(self):
        p = vz.ProblemStatement()
        p.search_space.root.add_float_param("x", 0.0, 1.0)
        p.search_space.root.add_float_param("y", 0.0, 1.0)
        p.metric_information.append(
            vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
        return p

    def _run(self, designer, n=6):
        tid = 0
        rng = np.random.default_rng(0)
        for _ in range(n):
            (s,) = designer.suggest(1)
            tid += 1
            t = s.to_trial(tid)
            t.complete(
                vz.Measurement(
                    metrics={"obj": float(rng.normal())}
                )
            )
            designer.update(core_lib.CompletedTrials([t]), core_lib.ActiveTrials())
        return designer

    def test_prior_steers_suggestions(self):
        from vizier_tpu.designers.gp_ucb_pe import UCBPEConfig, VizierGPUCBPEBandit

        def corner_prior(query):
            # Overwhelming preference for the (1, 1) corner in scaled space.
            return -1e4 * jnp.sum((query.continuous - 1.0) ** 2, axis=-1)

        problem = self._problem()
        designer = VizierGPUCBPEBandit(
            problem,
            config=UCBPEConfig(ucb_coefficient=1.8),
            num_seed_trials=1,
            rng_seed=0,
            prior_acquisition=corner_prior,
        )
        self._run(designer, n=5)
        # Post-seed suggestions must hug the preferred corner.
        (s,) = designer.suggest(1)
        assert s.parameters["x"].value > 0.85, s.parameters.as_dict()
        assert s.parameters["y"].value > 0.85, s.parameters.as_dict()

    def test_prior_with_set_acquisition(self):
        from vizier_tpu.designers.gp_ucb_pe import UCBPEConfig, VizierGPUCBPEBandit

        def corner_prior(query):
            return -1e4 * jnp.sum((query.continuous - 1.0) ** 2, axis=-1)

        problem = self._problem()
        designer = VizierGPUCBPEBandit(
            problem,
            config=UCBPEConfig(
                optimize_set_acquisition_for_exploration=True
            ),
            num_seed_trials=1,
            rng_seed=0,
            prior_acquisition=corner_prior,
        )
        self._run(designer, n=3)
        batch = designer.suggest(3)
        assert len(batch) == 3
        for s in batch:
            assert s.parameters["x"].value > 0.8, s.parameters.as_dict()


class TestAcquisitionBudgetPolicy:
    """Batch budget semantics (TPU-first default: one sweep's evaluations
    per suggest() call, split across picks; per_pick = reference behavior,
    75k per pick, ref gp_ucb_pe.py:693-697,1440-1446)."""

    def test_default_is_first_pick_full(self):
        problem = _single_metric_problem()
        d = _designer(problem, max_acquisition_evaluations=75_000)
        assert d.acquisition_budget_policy == "first_pick_full"
        # Remaining 24 picks split one further full budget.
        assert d._pick_vec_opt(25).max_evaluations == 75_000 // 24
        # Single pick keeps the full budget.
        assert d._pick_vec_opt(1).max_evaluations == 75_000

    def test_per_batch_splits_across_all_picks(self):
        problem = _single_metric_problem()
        d = _designer(
            problem,
            max_acquisition_evaluations=75_000,
            acquisition_budget_policy="per_batch",
        )
        assert d._pick_vec_opt(25).max_evaluations == 3_000
        assert d._pick_vec_opt(1).max_evaluations == 75_000

    def test_split_budget_floors_at_minimum(self):
        from vizier_tpu.designers import gp_ucb_pe as mod

        problem = _single_metric_problem()
        d = _designer(
            problem,
            max_acquisition_evaluations=1_000,
            acquisition_budget_policy="per_batch",
        )
        assert d._pick_vec_opt(25).max_evaluations == mod._MIN_PICK_EVALUATIONS

    def test_first_pick_full_runs_two_programs(self):
        """Batch suggest under the default policy: first pick full budget,
        remainder split; the batch still comes back whole and in-box."""
        problem = _single_metric_problem()
        d = _designer(problem, max_acquisition_evaluations=900, num_seed_trials=1)
        trials = _complete(
            problem,
            np.random.default_rng(0).uniform(size=5),
            lambda x: {"obj": -((x - 0.5) ** 2)},
        )
        d.update(core_lib.CompletedTrials(trials))
        batch = d.suggest(3)
        assert len(batch) == 3
        for s in batch:
            assert 0.0 <= float(s.parameters["x"].value) <= 1.0
        # Picks 2-3 saw pick 1 as pending: no duplicate suggestions.
        xs = sorted(float(s.parameters["x"].value) for s in batch)
        assert all(b - a > 1e-4 for a, b in zip(xs, xs[1:])), xs

    def test_per_pick_policy_uses_full_budget(self):
        problem = _single_metric_problem()
        d = _designer(
            problem,
            max_acquisition_evaluations=75_000,
            acquisition_budget_policy="per_pick",
        )
        assert d._pick_vec_opt(25) is d._vec_opt
        assert d._pick_vec_opt(25).max_evaluations == 75_000

    def test_invalid_policy_rejected(self):
        problem = _single_metric_problem()
        with pytest.raises(ValueError, match="acquisition_budget_policy"):
            _designer(problem, acquisition_budget_policy="bogus")

    def test_pick_opt_cache_reuses_instances(self):
        problem = _single_metric_problem()
        d = _designer(problem, max_acquisition_evaluations=75_000)
        assert d._pick_vec_opt(25) is d._pick_vec_opt(25)

    def test_batch_suggest_runs_under_split_budget(self):
        problem = _single_metric_problem()
        d = _designer(problem, max_acquisition_evaluations=600, num_seed_trials=1)
        trials = _complete(
            problem,
            np.random.default_rng(0).uniform(size=5),
            lambda x: {"obj": -((x - 0.5) ** 2)},
        )
        d.update(core_lib.CompletedTrials(trials))
        batch = d.suggest(4)
        assert len(batch) == 4


class TestProfilerSpans:
    """suggest() emits the reference's profiler span names
    (ref gp_ucb_pe.py `profiler.timeit('acquisition_optimizer')` etc.)."""

    def test_suggest_emits_latency_events(self):
        from vizier_tpu.utils import profiler

        problem = _single_metric_problem()
        d = _designer(problem, num_seed_trials=1)
        trials = _complete(
            problem,
            np.random.default_rng(0).uniform(size=4),
            lambda x: {"obj": -((x - 0.5) ** 2)},
        )
        d.update(core_lib.CompletedTrials(trials))
        with profiler.collect_events() as events:
            d.suggest(2)
        names = {e.name for e in events}
        assert {"train_gp", "acquisition_optimizer", "best_candidates_to_trials"} <= names


class TestRetraceDiscipline:
    def test_no_retrace_within_padding_bucket_batch(self):
        """Steady-state batch suggests under first_pick_full reuse both
        compiled programs (the full-budget pick and the split rest-batch)."""
        from vizier_tpu.designers import gp_ucb_pe as mod

        problem = _single_metric_problem()
        d = _designer(problem, num_seed_trials=1, max_acquisition_evaluations=300)
        rng = np.random.default_rng(0)
        tid = 0

        def complete_round():
            nonlocal tid
            done = []
            for s in d.suggest(2):
                tid += 1
                t = s.to_trial(tid)
                t.complete(vz.Measurement(metrics={"obj": float(rng.uniform())}))
                done.append(t)
            d.update(core_lib.CompletedTrials(done))

        complete_round()  # seeding round
        complete_round()  # 2 trials: compile both programs in the 8-bucket
        complete_round()  # 4 trials: still 8-bucket (4+2 spare rows -> 8)
        size = mod._suggest_batch._cache_size()
        complete_round()  # 6 trials: 6+2 -> still the 8-bucket, no retrace
        assert mod._suggest_batch._cache_size() == size


class TestPredictionUserScale:
    def test_minimize_metrics_predict_in_user_scale(self):
        """Multimetric: a MINIMIZE metric's predictions come back positive
        (user scale), not negated into the model's all-MAXIMIZE space."""
        p = vz.ProblemStatement()
        p.search_space.root.add_float_param("x", 0.0, 1.0)
        p.metric_information.append(
            vz.MetricInformation(name="loss", goal=vz.ObjectiveMetricGoal.MINIMIZE)
        )
        p.metric_information.append(
            vz.MetricInformation(name="acc", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
        d = _designer(p, num_seed_trials=1)
        trials = []
        for i, x in enumerate(np.linspace(0.0, 1.0, 8)):
            t = vz.Trial(id=i + 1, parameters={"x": float(x)})
            t.complete(
                vz.Measurement(
                    metrics={
                        "loss": float(5.0 + (x - 0.5) ** 2),  # in [5, 5.25]
                        "acc": float(0.9 - (x - 0.5) ** 2),  # in [0.65, 0.9]
                    }
                )
            )
            trials.append(t)
        d.update(core_lib.CompletedTrials(trials))
        pred = d.predict(
            [vz.TrialSuggestion(parameters={"x": 0.5})], num_samples=500
        )
        loss_mean, acc_mean = float(pred.mean[0, 0]), float(pred.mean[0, 1])
        assert 4.5 < loss_mean < 5.8, pred.mean
        assert 0.5 < acc_mean < 1.1, pred.mean
