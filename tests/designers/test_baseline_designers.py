"""Tests for random/quasi-random/grid designers and the smoke runner."""

import numpy as np
import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.designers import GridSearchDesigner, HaltonSequence, QuasiRandomDesigner, RandomDesigner
from vizier_tpu.testing import test_runners, test_studies


def _problem(space=None):
    p = vz.ProblemStatement(
        search_space=space or test_studies.flat_space_with_all_types(),
        metric_information=test_studies.metrics_objective_maximize(),
    )
    return p


class TestRandomDesigner:
    def test_smoke_all_types(self):
        problem = _problem()
        designer = RandomDesigner(problem.search_space, seed=1)
        trials = test_runners.RandomMetricsRunner(problem, iters=5, batch_size=3).run_designer(
            designer
        )
        assert len(trials) == 15

    def test_conditional_space(self):
        space = test_studies.conditional_automl_space()
        problem = _problem(space)
        designer = RandomDesigner(space, seed=2)
        for s in designer.suggest(20):
            space.assert_contains(s.parameters)
            model = s.parameters.get_value("model_type")
            if model == "dnn":
                assert "learning_rate" in s.parameters
                assert "l2_reg" not in s.parameters
            else:
                assert "l2_reg" in s.parameters

    def test_seeded_reproducibility(self):
        space = test_studies.flat_space_with_all_types()
        a = RandomDesigner(space, seed=7).suggest(5)
        b = RandomDesigner(space, seed=7).suggest(5)
        assert [s.parameters.as_dict() for s in a] == [s.parameters.as_dict() for s in b]


class TestHalton:
    def test_low_discrepancy_coverage(self):
        seq = HaltonSequence(2, seed=0, skip=10)
        pts = seq.sample(200)
        assert pts.shape == (200, 2)
        assert (pts > 0).all() and (pts < 1).all()
        # Quadrant coverage should be near-uniform.
        for qx in (0, 1):
            for qy in (0, 1):
                frac = np.mean(
                    ((pts[:, 0] > 0.5) == qx) & ((pts[:, 1] > 0.5) == qy)
                )
                assert 0.15 < frac < 0.35

    def test_fast_forward_equivalence(self):
        a = HaltonSequence(3, seed=5, skip=0)
        a.sample(7)
        b = HaltonSequence(3, seed=5, skip=0)
        b.fast_forward(7)
        np.testing.assert_allclose(a.sample(3), b.sample(3))


class TestQuasiRandomDesigner:
    def test_smoke(self):
        problem = _problem()
        designer = QuasiRandomDesigner(problem.search_space, seed=1)
        trials = test_runners.RandomMetricsRunner(problem, iters=4, batch_size=2).run_designer(
            designer
        )
        assert len(trials) == 8

    def test_serialization_roundtrip(self):
        space = test_studies.flat_continuous_space_with_scaling()
        d1 = QuasiRandomDesigner(space, seed=3)
        d1.suggest(5)
        state = d1.dump()
        d2 = QuasiRandomDesigner(space, seed=3)
        d2.load(state)
        a = [s.parameters.as_dict() for s in d1.suggest(3)]
        b = [s.parameters.as_dict() for s in d2.suggest(3)]
        assert a == b

    def test_conditional_rejected(self):
        with pytest.raises(ValueError):
            QuasiRandomDesigner(test_studies.conditional_automl_space())


class TestGridSearchDesigner:
    def test_exhausts_grid(self):
        space = vz.SearchSpace()
        space.root.add_categorical_param("c", ["x", "y"])
        space.root.add_int_param("i", 1, 3)
        designer = GridSearchDesigner(space)
        assert designer.grid_size == 6
        suggestions = designer.suggest(10)
        assert len(suggestions) == 6  # exhausted, not padded
        seen = {(s.parameters.get_value("c"), s.parameters.get_value("i")) for s in suggestions}
        assert len(seen) == 6

    def test_double_resolution(self):
        space = vz.SearchSpace()
        space.root.add_float_param("x", 0.0, 1.0)
        designer = GridSearchDesigner(space, double_grid_resolution=5)
        xs = [s.parameters.get_value("x") for s in designer.suggest(5)]
        np.testing.assert_allclose(xs, [0.0, 0.25, 0.5, 0.75, 1.0])

    def test_shuffled_permutation(self):
        space = vz.SearchSpace()
        space.root.add_int_param("i", 1, 20)
        plain = [s.parameters.get_value("i") for s in GridSearchDesigner(space).suggest(20)]
        shuffled = [
            s.parameters.get_value("i")
            for s in GridSearchDesigner(space, shuffle_seed=4).suggest(20)
        ]
        assert sorted(shuffled) == plain
        assert shuffled != plain

    def test_position_serialization(self):
        space = vz.SearchSpace()
        space.root.add_int_param("i", 1, 10)
        d1 = GridSearchDesigner(space)
        d1.suggest(4)
        d2 = GridSearchDesigner(space)
        d2.load(d1.dump())
        assert d2.suggest(1)[0].parameters.get_value("i") == 5


class TestReviewRegressions:
    """Regressions from the third code review."""

    def test_grid_load_restores_shuffle_order(self):
        space = vz.SearchSpace()
        space.root.add_int_param("i", 1, 20)
        d1 = GridSearchDesigner(space, shuffle_seed=7)
        first_ten = [s.parameters.get_value("i") for s in d1.suggest(10)]
        # Restore into a designer constructed with a DIFFERENT seed.
        d2 = GridSearchDesigner(space, shuffle_seed=999)
        d2.load(d1.dump())
        rest = [s.parameters.get_value("i") for s in d2.suggest(10)]
        assert sorted(first_ten + rest) == list(range(1, 21))

    def test_quasi_random_dump_after_load_is_consistent(self):
        space = vz.SearchSpace()
        space.root.add_float_param("x", 0.0, 1.0)
        d1 = QuasiRandomDesigner(space, seed=11)
        d1.suggest(5)
        d2 = QuasiRandomDesigner(space, seed=42)  # different constructor seed
        d2.load(d1.dump())
        d2.suggest(2)
        d3 = QuasiRandomDesigner(space, seed=0)
        d3.load(d2.dump())  # dump after load must carry seed 11, index 7
        a = [s.parameters.as_dict() for s in d3.suggest(3)]
        ref = QuasiRandomDesigner(space, seed=11)
        ref.suggest(7)
        b = [s.parameters.as_dict() for s in ref.suggest(3)]
        assert a == b

    def test_reverse_log_requires_positive_bounds(self):
        with pytest.raises(ValueError, match="positive"):
            vz.ParameterConfig.factory(
                "x", bounds=(0.0, 1.0), scale_type=vz.ScaleType.REVERSE_LOG
            )

    def test_reverse_log_sampling_density(self):
        """REVERSE_LOG concentrates samples near the upper bound."""
        from vizier_tpu.designers.random import unit_to_double

        cfg = vz.ParameterConfig.factory(
            "x", bounds=(0.1, 1.0), scale_type=vz.ScaleType.REVERSE_LOG
        )
        vals = np.array([unit_to_double(cfg, u) for u in np.linspace(0, 1, 101)])
        assert vals[0] == pytest.approx(0.1) and vals[-1] == pytest.approx(1.0)
        assert (np.diff(vals) > 0).all()
        # More than half the u-grid maps above the midpoint of the range.
        assert np.mean(vals > 0.55) > 0.6
