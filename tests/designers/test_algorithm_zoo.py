"""Tests for the algorithm zoo: NSGA-II, CMA-ES, eagle, BOCS, harmonica,
scalarizing, ensemble, scheduled, meta-learning, safety wrapper, pareto ops."""

import jax
import numpy as np
import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.benchmarks import (
    BenchmarkRunner,
    BenchmarkState,
    GenerateAndEvaluate,
    NumpyExperimenter,
    bbob_problem,
)
from vizier_tpu.benchmarks.experimenters.synthetic import bbob, multiobjective
from vizier_tpu.ops import pareto as pareto_ops
from vizier_tpu.pyvizier import multimetric
from vizier_tpu.testing import test_runners


def _mixed_problem():
    p = vz.ProblemStatement()
    p.search_space.root.add_float_param("x", 0.0, 1.0)
    p.search_space.root.add_categorical_param("c", ["a", "b", "z"])
    p.metric_information.append(
        vz.MetricInformation(name="objective", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return p


def _binary_problem(dim=4):
    p = vz.ProblemStatement()
    for i in range(dim):
        p.search_space.root.add_bool_param(f"b{i}")
    p.metric_information.append(
        vz.MetricInformation(name="objective", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return p


class TestParetoOps:
    def test_frontier_and_rank(self):
        pts = np.array(
            [[1.0, 1.0], [2.0, 0.5], [0.5, 2.0], [0.4, 0.4], [2.0, 2.0]],
            dtype=np.float32,
        )
        frontier = np.asarray(pareto_ops.is_frontier(pts))
        assert frontier.tolist() == [False, False, False, False, True]
        rank = np.asarray(pareto_ops.pareto_rank(pts))
        assert rank[4] == 0 and rank[3] == 4

    def test_layers(self):
        pts = np.array([[2.0, 2.0], [1.0, 1.0], [0.5, 0.5]], dtype=np.float32)
        layers = np.asarray(pareto_ops.nondomination_layers(pts))
        assert layers.tolist() == [0, 1, 2]

    def test_hypervolume_exact_square(self):
        # Frontier {(1, 2), (2, 1)} vs origin: HV = 1*2 + 1*1 = 3.
        pts = np.array([[1.0, 2.0], [2.0, 1.0]], dtype=np.float32)
        hv = float(
            pareto_ops.hypervolume(pts, rng=jax.random.PRNGKey(0), num_vectors=20000)
        )
        assert hv == pytest.approx(3.0, rel=0.05)

    def test_multimetric_wrappers(self):
        algo = multimetric.ParetoOptimalAlgorithm()
        pts = np.array([[1.0, 0.0], [0.0, 1.0], [-1.0, -1.0]])
        assert algo.is_pareto_optimal(pts).tolist() == [True, True, False]
        frontier = multimetric.ParetoFrontier(
            np.array([[1.0, 1.0]]), origin=np.zeros(2), num_vectors=20000
        )
        assert frontier.hypervolume() == pytest.approx(1.0, rel=0.05)

    def test_safety_checker(self):
        metrics = vz.MetricsConfig(
            [
                vz.MetricInformation(name="obj"),
                vz.MetricInformation(name="safe", safety_threshold=0.5),
            ]
        )
        checker = multimetric.SafetyChecker(metrics)
        ok = vz.Trial(id=1)
        ok.complete(vz.Measurement(metrics={"obj": 1.0, "safe": 0.9}))
        bad = vz.Trial(id=2)
        bad.complete(vz.Measurement(metrics={"obj": 1.0, "safe": 0.1}))
        assert checker.is_safe(ok) and not checker.is_safe(bad)


class TestNSGA2:
    def test_smoke_mixed(self):
        from vizier_tpu.designers.evolution import NSGA2Designer

        problem = _mixed_problem()
        designer = NSGA2Designer(problem, population_size=10, seed=1)
        trials = test_runners.RandomMetricsRunner(
            problem, iters=4, batch_size=5
        ).run_designer(designer)
        assert len(trials) == 20

    def test_multiobjective_improves_hypervolume(self):
        from vizier_tpu.designers.evolution import NSGA2Designer

        exp = multiobjective.MultiObjectiveExperimenter.zdt("zdt1", dimension=6)
        problem = exp.problem_statement()
        designer = NSGA2Designer(problem, population_size=20, seed=0)
        tid = 0
        points = []
        for _ in range(10):
            batch = [s.to_trial(tid + i + 1) for i, s in enumerate(designer.suggest(10))]
            tid += len(batch)
            exp.evaluate(batch)
            designer.update(core_lib.CompletedTrials(batch))
            points.append(
                np.array(
                    [
                        [m.value for m in t.final_measurement.metrics.values()]
                        for t in batch
                    ]
                )
            )
        # MINIMIZE both: early generations should dominate... late ones better.
        early = points[0].min(axis=0)
        late = points[-1].min(axis=0)
        assert late[1] <= early[1] + 0.2  # f2 improves (or stays comparable)

    def test_serialization(self):
        from vizier_tpu.designers.evolution import NSGA2Designer

        problem = _mixed_problem()
        d1 = NSGA2Designer(problem, population_size=5, seed=1)
        test_runners.RandomMetricsRunner(problem, iters=2, batch_size=5).run_designer(d1)
        d2 = NSGA2Designer(problem, population_size=5, seed=1)
        d2.load(d1.dump())
        assert len(d2._population) == len(d1._population)


class TestCMAES:
    def test_converges_on_sphere(self):
        from vizier_tpu.designers.cmaes import CMAESDesigner

        problem = bbob_problem(3)
        exp = NumpyExperimenter(bbob.Sphere, problem)
        state = BenchmarkState.from_designer_factory(
            exp, lambda p, **kw: CMAESDesigner(p, seed=0)
        )
        BenchmarkRunner([GenerateAndEvaluate(8)], num_repeats=25).run(state)
        trials = state.algorithm.supporter.GetTrials(
            status_matches=vz.TrialStatus.COMPLETED
        )
        best = min(t.final_measurement.metrics["bbob_eval"].value for t in trials)
        assert best < 1.0  # random baseline is ~5+ on 3D [-5,5]^3 sphere

    def test_rejects_categorical(self):
        from vizier_tpu.designers.cmaes import CMAESDesigner

        with pytest.raises(ValueError):
            CMAESDesigner(_mixed_problem())


class TestEagleDesigner:
    def test_smoke_and_improvement(self):
        from vizier_tpu.designers.eagle_strategy import EagleStrategyDesigner

        problem = bbob_problem(2)
        exp = NumpyExperimenter(bbob.Sphere, problem)
        state = BenchmarkState.from_designer_factory(
            exp, lambda p, **kw: EagleStrategyDesigner(p, seed=0)
        )
        BenchmarkRunner([GenerateAndEvaluate(6)], num_repeats=25).run(state)
        trials = state.algorithm.supporter.GetTrials(
            status_matches=vz.TrialStatus.COMPLETED
        )
        values = [t.final_measurement.metrics["bbob_eval"].value for t in trials]
        assert min(values) < np.median(values[:12])  # improves over early random

    def test_serialization_roundtrip(self):
        from vizier_tpu.designers.eagle_strategy import EagleStrategyDesigner

        problem = _mixed_problem()
        d1 = EagleStrategyDesigner(problem, seed=3)
        test_runners.RandomMetricsRunner(problem, iters=3, batch_size=4).run_designer(d1)
        d2 = EagleStrategyDesigner(problem, seed=3)
        d2.load(d1.dump())
        assert set(d2._pool.keys()) == set(d1._pool.keys())
        for fid in d1._pool:
            assert d2._pool[fid].reward == d1._pool[fid].reward
            np.testing.assert_array_equal(d2._pool[fid].x, d1._pool[fid].x)

    def test_many_suggests_before_any_update(self):
        """More suggests than pool capacity with zero completions must not
        crash (multi-worker studies hold many active trials)."""
        from vizier_tpu.designers.eagle_strategy import EagleStrategyDesigner

        problem = bbob_problem(2)
        d = EagleStrategyDesigner(problem, seed=0)
        suggestions = d.suggest(d._capacity + 5)
        assert len(suggestions) == d._capacity + 5

    def test_pool_refills_after_eviction(self):
        """Evicted flies leave room that random suggestions refill."""
        from vizier_tpu.designers.eagle_strategy import (
            EagleStrategyDesigner,
            FireflyConfig,
        )
        from vizier_tpu.algorithms import core as core_lib

        problem = bbob_problem(2)
        d = EagleStrategyDesigner(
            problem,
            seed=0,
            config=FireflyConfig(penalize_factor=0.01),  # evict fast
        )
        tid = 0
        for rnd in range(10):
            trials = []
            for s in d.suggest(4):
                tid += 1
                t = s.to_trial(tid)
                # Constant objective: nothing ever improves → evictions.
                t.complete(vz.Measurement(metrics={"bbob_eval": 1.0}))
                trials.append(t)
            d.update(core_lib.CompletedTrials(trials))
        # Suggest still issues fresh random flies for the freed slots.
        assert len(d.suggest(3)) == 3

    def test_nsga2_restore_skips_first_generation(self):
        from vizier_tpu.designers.evolution import NSGA2Designer
        from vizier_tpu.algorithms import core as core_lib

        problem = bbob_problem(2)
        d1 = NSGA2Designer(problem, population_size=8, seed=0)
        tid = 0
        for _ in range(3):
            trials = []
            for s in d1.suggest(4):
                tid += 1
                t = s.to_trial(tid)
                t.complete(vz.Measurement(metrics={"bbob_eval": float(tid)}))
                trials.append(t)
            d1.update(core_lib.CompletedTrials(trials))
        d2 = NSGA2Designer(problem, population_size=8, seed=0)
        d2.load(d1.dump())
        # Restored state implies the random first generation already ran.
        assert d2._num_suggested >= d2.population_size


class TestBOCSAndHarmonica:
    def _quadratic_binary(self, trials):
        # Optimum at all-True.
        for t in trials:
            bits = [1.0 if t.parameters.get_value(f"b{i}") == "True" else 0.0 for i in range(4)]
            t.complete(
                vz.Measurement(metrics={"objective": sum(bits) + bits[0] * bits[1]})
            )

    @pytest.mark.parametrize("designer_name", ["bocs", "harmonica"])
    def test_finds_good_bits(self, designer_name):
        if designer_name == "bocs":
            from vizier_tpu.designers.bocs import BOCSDesigner as D
        else:
            from vizier_tpu.designers.harmonica import HarmonicaDesigner as D
        problem = _binary_problem(4)
        designer = D(problem, seed=0)
        tid = 0
        best = -np.inf
        for _ in range(12):
            batch = [s.to_trial(tid + i + 1) for i, s in enumerate(designer.suggest(4))]
            tid += len(batch)
            self._quadratic_binary(batch)
            designer.update(core_lib.CompletedTrials(batch))
            best = max(
                best,
                max(t.final_measurement.metrics["objective"].value for t in batch),
            )
        assert best >= 4.0  # found at least 4/5 of max (5.0)

    def test_bocs_rejects_nonbinary(self):
        from vizier_tpu.designers.bocs import BOCSDesigner

        with pytest.raises(ValueError):
            BOCSDesigner(_mixed_problem())


class TestScalarizingDesigner:
    def test_multiobjective_to_single(self):
        from vizier_tpu.designers.scalarizing_designer import ScalarizingDesigner
        from vizier_tpu.designers import scalarization

        exp = multiobjective.MultiObjectiveExperimenter.zdt("zdt1", dimension=3)
        problem = exp.problem_statement()
        designer = ScalarizingDesigner(
            problem,
            scalarization=scalarization.LinearScalarization(weights=(0.5, 0.5)),
            designer_factory=lambda p, **kw: __import__(
                "vizier_tpu.designers.random", fromlist=["RandomDesigner"]
            ).RandomDesigner(p.search_space, seed=0),
        )
        tid = 0
        for _ in range(3):
            batch = [s.to_trial(tid + i + 1) for i, s in enumerate(designer.suggest(3))]
            tid += len(batch)
            exp.evaluate(batch)
            designer.update(core_lib.CompletedTrials(batch))
        assert tid == 9


class TestEnsembleDesigner:
    def test_routes_and_learns(self):
        from vizier_tpu.designers.ensemble import (
            EnsembleDesigner,
            EXP3IXEnsembleDesign,
            UCBEnsembleDesign,
            RandomEnsembleDesign,
        )
        from vizier_tpu.designers import RandomDesigner, QuasiRandomDesigner

        problem = _mixed_problem()
        designer = EnsembleDesigner(
            problem,
            designers={
                "random": RandomDesigner(problem.search_space, seed=0),
                "quasi": QuasiRandomDesigner(problem.search_space, seed=0),
            },
            design=EXP3IXEnsembleDesign(2),
            seed=0,
        )
        trials = test_runners.RandomMetricsRunner(
            problem, iters=6, batch_size=2
        ).run_designer(designer)
        assert len(trials) == 12
        experts = {t.metadata.ns("ensemble").get("expert") for t in trials}
        assert experts <= {"random", "quasi"}

    def test_designs_select_valid_arms(self):
        from vizier_tpu.designers import ensemble

        rng = np.random.default_rng(0)
        for design in (
            ensemble.RandomEnsembleDesign(3),
            ensemble.EXP3UniformEnsembleDesign(3),
            ensemble.EXP3IXEnsembleDesign(3),
            ensemble.UCBEnsembleDesign(3),
        ):
            for _ in range(10):
                arm = design.select(rng)
                assert 0 <= arm < 3
                design.observe(arm, rng.uniform())
            probs = design.probabilities
            assert probs.shape == (3,)
            assert probs.sum() == pytest.approx(1.0, abs=1e-6)


class TestScheduledDesigner:
    def test_schedule_values_change(self):
        from vizier_tpu.designers.scheduled_designer import (
            ExponentialSchedule,
            LinearSchedule,
            ScheduledDesigner,
        )
        from vizier_tpu.designers import RandomDesigner

        sched = ExponentialSchedule(2.5, 0.8)
        assert sched(0.0) == pytest.approx(2.5)
        assert sched(1.0) == pytest.approx(0.8)
        assert 0.8 < sched(0.5) < 2.5
        lin = LinearSchedule(0.0, 10.0)
        assert lin(0.3) == pytest.approx(3.0)

        built = []

        def factory(problem, scale):
            built.append(scale)
            return RandomDesigner(problem.search_space, seed=0)

        problem = _mixed_problem()
        designer = ScheduledDesigner(
            problem,
            designer_factory=factory,
            scheduled_params={"scale": LinearSchedule(1.0, 0.0)},
            expected_total_num_trials=4,
        )
        test_runners.RandomMetricsRunner(problem, iters=4, batch_size=1).run_designer(
            designer
        )
        assert len(built) >= 2  # rebuilt as the schedule advanced
        assert built[0] == pytest.approx(1.0)


class TestMetaLearning:
    def test_meta_rounds(self):
        from vizier_tpu.designers.meta_learning import (
            MetaLearningConfig,
            MetaLearningDesigner,
        )
        from vizier_tpu.designers import RandomDesigner

        problem = _mixed_problem()
        tuning_space = vz.SearchSpace()
        tuning_space.root.add_float_param("dummy", 0.0, 1.0)
        builds = []

        def inner_factory(p, dummy):
            builds.append(dummy)
            return RandomDesigner(p.search_space, seed=0)

        designer = MetaLearningDesigner(
            problem,
            tuning_space=tuning_space,
            inner_factory=inner_factory,
            config=MetaLearningConfig(tuning_interval=4, tuning_min_num_trials=0),
            seed=0,
        )
        test_runners.RandomMetricsRunner(problem, iters=10, batch_size=1).run_designer(
            designer
        )
        assert len(builds) >= 2  # at least two meta rounds happened


class TestEagleMetaLearning:
    def test_search_space_matches_firefly_config(self):
        from vizier_tpu.designers import eagle_meta_learning
        from vizier_tpu.designers.eagle_strategy import FireflyConfig

        space = eagle_meta_learning.meta_eagle_search_space()
        names = {c.name for c in space.parameters}
        # Every tunable coefficient must exist on FireflyConfig so the inner
        # factory can construct it, and defaults must equal the config's.
        cfg = FireflyConfig()
        for c in space.parameters:
            assert hasattr(cfg, c.name)
            assert c.default_value == pytest.approx(getattr(cfg, c.name))
            assert c.scale_type == vz.ScaleType.LOG
        assert "gravity" in names and "perturbation" in names

    def test_preset_runs_and_tunes(self):
        from vizier_tpu.designers import eagle_meta_learning
        from vizier_tpu.designers.meta_learning import MetaLearningConfig

        problem = _mixed_problem()
        designer = eagle_meta_learning.eagle_meta_learning_designer(
            problem,
            config=MetaLearningConfig(tuning_interval=3, tuning_min_num_trials=0),
            seed=0,
        )
        trials = test_runners.RandomMetricsRunner(
            problem, iters=8, batch_size=1
        ).run_designer(designer)
        assert len(trials) == 8
        # At least one meta round was scored with the firefly coefficients.
        assert designer._meta_trials
        scored = designer._meta_trials[0].parameters
        assert "gravity" in scored

    def test_use_best_params_locks_in(self):
        from vizier_tpu.designers import eagle_meta_learning
        from vizier_tpu.designers.meta_learning import (
            MetaLearningConfig,
            MetaLearningState,
        )

        problem = _mixed_problem()
        designer = eagle_meta_learning.eagle_meta_learning_designer(
            problem,
            config=MetaLearningConfig(tuning_interval=2, tuning_min_num_trials=0, tuning_max_num_trials=5),
            seed=1,
        )
        test_runners.RandomMetricsRunner(problem, iters=8, batch_size=1).run_designer(
            designer
        )
        assert designer.state == MetaLearningState.USE_BEST_PARAMS


class TestUnsafeAsInfeasible:
    def test_unsafe_becomes_infeasible(self):
        from vizier_tpu.designers.unsafe_as_infeasible_designer import (
            UnsafeAsInfeasibleDesigner,
        )

        problem = vz.ProblemStatement()
        problem.search_space.root.add_float_param("x", 0.0, 1.0)
        problem.metric_information.append(vz.MetricInformation(name="obj"))
        problem.metric_information.append(
            vz.MetricInformation(name="safe", safety_threshold=0.5)
        )
        seen = []

        class Recorder(core_lib.Designer):
            def update(self, completed, all_active=core_lib.ActiveTrials()):
                seen.extend(completed.trials)

            def suggest(self, count=None):
                return [vz.TrialSuggestion(parameters={"x": 0.5})]

        designer = UnsafeAsInfeasibleDesigner(
            problem, designer_factory=lambda p, **kw: Recorder()
        )
        safe = vz.Trial(id=1, parameters={"x": 0.1})
        safe.complete(vz.Measurement(metrics={"obj": 1.0, "safe": 0.9}))
        unsafe = vz.Trial(id=2, parameters={"x": 0.9})
        unsafe.complete(vz.Measurement(metrics={"obj": 2.0, "safe": 0.1}))
        designer.update(core_lib.CompletedTrials([safe, unsafe]))
        assert not seen[0].infeasible
        assert seen[1].infeasible


class TestServiceIntegration:
    @pytest.mark.parametrize(
        "algorithm", ["NSGA2", "EAGLE_STRATEGY", "QUASI_RANDOM_SEARCH"]
    )
    def test_algorithms_through_service(self, algorithm):
        from vizier_tpu.service import clients as clients_lib
        from vizier_tpu.service import vizier_client

        vizier_client._local_servicer = None
        config = vz.StudyConfig(algorithm=algorithm)
        config.search_space.root.add_float_param("x", 0.0, 1.0)
        config.metric_information.append(
            vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
        study = clients_lib.Study.from_study_config(
            config, owner="me", study_id=f"zoo-{algorithm}"
        )
        for _ in range(2):
            for trial in study.suggest(count=2):
                trial.complete(vz.Measurement(metrics={"obj": trial.parameters["x"]}))
        assert len(list(study.trials())) == 4


class TestReviewRegressions:
    """Regressions from the sixth code review."""

    def test_meta_first_round_reward_neutral(self):
        from vizier_tpu.designers.meta_learning import (
            MetaLearningConfig,
            MetaLearningDesigner,
        )
        from vizier_tpu.designers import RandomDesigner

        problem = _mixed_problem()
        tuning_space = vz.SearchSpace()
        tuning_space.root.add_float_param("dummy", 0.0, 1.0)
        rewards = []

        class MetaRecorder(core_lib.Designer):
            def __init__(self, space):
                self._inner = RandomDesigner(space, seed=0)

            def update(self, completed, all_active=core_lib.ActiveTrials()):
                for t in completed.trials:
                    rewards.append(t.final_measurement.metrics["meta_reward"].value)

            def suggest(self, count=None):
                return self._inner.suggest(count)

        designer = MetaLearningDesigner(
            problem,
            tuning_space=tuning_space,
            inner_factory=lambda p, dummy: RandomDesigner(p.search_space, seed=0),
            meta_factory=lambda p, **kw: MetaRecorder(p.search_space),
            config=MetaLearningConfig(tuning_interval=3, tuning_min_num_trials=0),
            seed=0,
        )
        test_runners.RandomMetricsRunner(problem, iters=8, batch_size=1).run_designer(
            designer
        )
        assert rewards, "meta designer never scored a round"
        assert all(abs(r) < 100 for r in rewards), rewards

    def test_gp_ucb_pe_trust_region_flag(self):
        import jax
        from vizier_tpu.designers.gp_ucb_pe import VizierGPUCBPEBandit
        from vizier_tpu.optimizers.lbfgs import AdamOptimizer

        p = vz.ProblemStatement()
        p.search_space.root.add_float_param("x", 0.0, 1.0)
        p.metric_information.append(
            vz.MetricInformation(name="objective", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
        d = VizierGPUCBPEBandit(
            p,
            use_trust_region=False,
            max_acquisition_evaluations=300,
            ard_restarts=2,
            ard_optimizer=AdamOptimizer(maxiter=20),
        )
        trials = test_runners.RandomMetricsRunner(p, iters=3, batch_size=2).run_designer(d)
        assert len(trials) == 6

    def test_scheduled_designer_does_not_rebuild_every_call(self):
        from vizier_tpu.designers.scheduled_designer import LinearSchedule, ScheduledDesigner
        from vizier_tpu.designers import RandomDesigner

        builds = []

        def factory(problem, scale):
            builds.append(scale)
            return RandomDesigner(problem.search_space, seed=0)

        problem = _mixed_problem()
        designer = ScheduledDesigner(
            problem,
            designer_factory=factory,
            scheduled_params={"scale": LinearSchedule(1.0, 0.99)},
            expected_total_num_trials=1000,
        )
        test_runners.RandomMetricsRunner(problem, iters=10, batch_size=1).run_designer(
            designer
        )
        assert len(builds) == 1  # tiny schedule drift must not rebuild


class TestEaglePureCategoricalPerturbation:
    """Pure-categorical spaces use the reference's CONSTANT resample
    probability (eagle_strategy_utils.py:299), not the Laplace×25 path
    that resamples nearly every category per move."""

    def _pure_cat_problem(self, n=6, k=5):
        problem = vz.ProblemStatement()
        for i in range(n):
            problem.search_space.root.add_categorical_param(
                f"op{i}", [str(c) for c in range(k)]
            )
        problem.metric_information.append(
            vz.MetricInformation(
                name="acc", goal=vz.ObjectiveMetricGoal.MAXIMIZE
            )
        )
        return problem

    def test_resample_rate_matches_constant(self):
        from vizier_tpu.designers.eagle_strategy import EagleStrategyDesigner

        d = EagleStrategyDesigner(self._pure_cat_problem(), seed=0)
        cat = np.zeros(6, dtype=np.int32)
        x = np.zeros(0)
        changed = total = 0
        for _ in range(500):
            _, out = d._perturb(x, cat, level=0.1)
            # Uniform resample can redraw the same category: P(change) =
            # p_resample * (k-1)/k = 0.1 * 0.8 = 0.08.
            changed += int(np.sum(out != cat))
            total += len(cat)
        rate = changed / total
        assert 0.04 < rate < 0.13, rate

    def test_mixed_space_still_uses_scaled_path(self):
        from vizier_tpu.designers.eagle_strategy import EagleStrategyDesigner

        problem = _mixed_problem()
        d = EagleStrategyDesigner(problem, seed=0)
        nc = d._enc.num_continuous
        assert nc > 0
        x = np.full(nc, 0.5)
        cat = np.zeros(d._enc.num_categorical, dtype=np.int32)
        moved = False
        for _ in range(20):
            out_x, _ = d._perturb(x, cat, level=0.1)
            moved = moved or bool(np.any(out_x != x))
        assert moved  # continuous coordinates must keep perturbing


class TestPyCMAESWrapper:
    """The pycma wrapper protocol, executed against a stub cma module
    (the real package is absent from this image)."""

    def _problem(self, dim=3):
        return bbob_problem(dim)

    def _stub_cma(self, popsize=4):
        import types

        calls = {}

        class FakeEvolution:
            def __init__(self, x0, sigma0, options):
                calls["x0"] = np.array(x0)
                calls["sigma0"] = sigma0
                calls["options"] = options
                self.popsize = options.get("popsize", popsize)

            def feed_for_resume(self, features, labels):
                calls["fed_features"] = np.array(features)
                calls["fed_labels"] = np.array(labels)

            def ask(self, count):
                rng = np.random.default_rng(0)
                return rng.uniform(size=(count, len(calls["x0"])))

        mod = types.ModuleType("cma")
        mod.CMAEvolutionStrategy = FakeEvolution
        return mod, calls

    def test_validation(self):
        from vizier_tpu.designers.pycmaes import PyCMAESDesigner

        with pytest.raises(ValueError, match="popsize"):
            PyCMAESDesigner(self._problem(), popsize=1)
        with pytest.raises(ValueError, match="continuous"):
            PyCMAESDesigner(_mixed_problem())

    def test_import_gate(self):
        from vizier_tpu.designers.pycmaes import PyCMAESDesigner

        with pytest.raises(ImportError, match="pycma"):
            PyCMAESDesigner(self._problem()).suggest(1)

    def test_protocol_feeds_whole_generations_sign_flipped(self):
        from vizier_tpu.algorithms import core as core_lib
        from vizier_tpu.designers.pycmaes import PyCMAESDesigner

        problem = self._problem(2)
        d = PyCMAESDesigner(problem, popsize=4)
        mod, calls = self._stub_cma()
        # 6 completed trials, popsize 4 -> feed exactly the last 4.
        trials = []
        for i in range(6):
            t = vz.Trial(
                id=i + 1, parameters={"x0": float(i) - 2.5, "x1": 0.0}
            )
            t.complete(vz.Measurement(metrics={"bbob_eval": float(i)}))
            trials.append(t)
        d.update(core_lib.CompletedTrials(trials))
        out = d._suggest_with(mod, 3)
        assert len(out) == 3
        assert calls["fed_features"].shape == (4, 2)
        # bbob_eval is MINIMIZE: converter encodes maximization-signed
        # (negated), wrapper flips again for pycma -> raw values back.
        np.testing.assert_allclose(
            calls["fed_labels"], [2.0, 3.0, 4.0, 5.0]
        )
        # x0 is the scaled bounds midpoint.
        np.testing.assert_allclose(calls["x0"], [0.5, 0.5])
        for s in out:
            v = float(s.parameters["x0"].value)
            assert -5.0 <= v <= 5.0  # back in native bounds

    def test_no_feed_below_one_generation(self):
        from vizier_tpu.algorithms import core as core_lib
        from vizier_tpu.designers.pycmaes import PyCMAESDesigner

        d = PyCMAESDesigner(self._problem(2), popsize=4)
        mod, calls = self._stub_cma()
        t = vz.Trial(id=1, parameters={"x0": 0.0, "x1": 0.0})
        t.complete(vz.Measurement(metrics={"bbob_eval": 1.0}))
        d.update(core_lib.CompletedTrials([t]))
        d._suggest_with(mod, 2)
        assert "fed_features" not in calls

    def test_log_scale_x0_uses_converter_frame(self):
        from vizier_tpu.designers.pycmaes import PyCMAESDesigner
        from vizier_tpu.pyvizier import parameter_config as pcfg

        problem = vz.ProblemStatement()
        problem.search_space.root.add_float_param(
            "lr", 1e-4, 1.0, scale_type=pcfg.ScaleType.LOG, default_value=1e-2
        )
        problem.metric_information.append(
            vz.MetricInformation(
                name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE
            )
        )
        d = PyCMAESDesigner(problem)
        # log frame: 1e-2 sits exactly halfway between 1e-4 and 1.
        np.testing.assert_allclose(d._x0, [0.5], atol=1e-6)

    def test_infeasible_trials_filtered_from_feed(self):
        from vizier_tpu.algorithms import core as core_lib
        from vizier_tpu.designers.pycmaes import PyCMAESDesigner

        d = PyCMAESDesigner(self._problem(2), popsize=2)
        mod, calls = self._stub_cma(popsize=2)
        trials = []
        for i in range(4):
            t = vz.Trial(id=i + 1, parameters={"x0": 0.0, "x1": 0.0})
            if i == 1:
                t.complete(
                    vz.Measurement(), infeasibility_reason="diverged"
                )
            else:
                t.complete(vz.Measurement(metrics={"bbob_eval": float(i)}))
            trials.append(t)
        d.update(core_lib.CompletedTrials(trials))
        d._suggest_with(mod, 1)
        # 3 finite trials, popsize 2 -> feed the last whole generation (2).
        assert calls["fed_labels"].shape == (2,)
        assert np.isfinite(calls["fed_labels"]).all()
