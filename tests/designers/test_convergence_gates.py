"""Algorithm-quality gates: the convergence tests CI runs on every change.

The reference disabled its algorithm suite in CI for speed
(``run_tests.sh:26-35``); on this build the budgets are tuned to stay
minutes-cheap so the gates actually run.
"""

import numpy as np
import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.benchmarks import NumpyExperimenter, bbob_problem
from vizier_tpu.benchmarks.experimenters import wrappers
from vizier_tpu.benchmarks.experimenters.synthetic import bbob
from vizier_tpu.designers import RandomDesigner
from vizier_tpu.optimizers.lbfgs import AdamOptimizer
from vizier_tpu.testing import comparator_runner, simplekd_runner

_FAST_ARD = AdamOptimizer(maxiter=40)


def _gp_factory(problem, seed=None, **kw):
    from vizier_tpu.designers.gp_bandit import VizierGPBandit

    return VizierGPBandit(
        problem,
        rng_seed=seed or 0,
        max_acquisition_evaluations=1500,
        ard_restarts=4,
        ard_optimizer=_FAST_ARD,
        num_seed_trials=5,
    )


def _ucb_pe_factory(problem, seed=None, **kw):
    from vizier_tpu.designers.gp_ucb_pe import VizierGPUCBPEBandit

    return VizierGPUCBPEBandit(
        problem,
        rng_seed=seed or 0,
        max_acquisition_evaluations=800,
        ard_restarts=4,
        ard_optimizer=_FAST_ARD,
        num_seed_trials=5,
    )


class TestGPConvergenceGates:
    def test_gp_bandit_beats_random_on_shifted_sphere(self):
        exp = wrappers.ShiftingExperimenter(
            NumpyExperimenter(bbob.Sphere, bbob_problem(4)),
            shift=np.array([1.0, -2.0, 0.5, 2.5]),
        )
        tester = comparator_runner.SimpleRegretComparisonTester(
            num_trials=25, num_repeats=2, tolerance=0.0
        )
        # GP candidate must not be worse than random baseline (it should be
        # dramatically better; tolerance 0 keeps the gate strict).
        tester.assert_better_simple_regret(
            exp,
            candidate_factory=_gp_factory,
            baseline_factory=lambda p, **kw: RandomDesigner(
                p.search_space, seed=kw.get("seed", 0)
            ),
        )

    def test_gp_bandit_converges_on_simplekd(self):
        """The mixed-space gate: categorical+discrete+int+float."""
        tester = simplekd_runner.SimpleKDConvergenceTester(
            num_trials=40, batch_size=5, max_abs_error=0.6, seed=1
        )
        best = tester.assert_converges(_gp_factory)
        assert best > -0.6

    def test_gp_ucb_pe_converges_on_simplekd(self):
        tester = simplekd_runner.SimpleKDConvergenceTester(
            num_trials=40, batch_size=5, max_abs_error=0.8, seed=1
        )
        tester.assert_converges(_ucb_pe_factory)


class TestMultichipEntry:
    def test_dryrun_multichip_on_virtual_mesh(self):
        """The driver's multi-chip dry run must keep working (8 CPU devices)."""
        import os
        import sys

        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        if repo_root not in sys.path:
            sys.path.insert(0, repo_root)
        from __graft_entry__ import dryrun_multichip, entry

        dryrun_multichip(8)
        import jax

        fn, args = entry()
        out = jax.jit(fn)(*args)
        assert out.shape == (64,)


class TestShifted20DGates:
    """Pinned to the parity suite's shifted 20-D instances
    (regret_report_r4.json): the optimum is moved off the search-box center
    per seed, so center-seeding cannot fake convergence. Regressions in the
    DEFAULT designer's 20-D behavior fail here."""

    def _shifted_sphere_20d(self, seed):
        # THE pinned instance (shared with parity_suite.py + the A/B tool).
        from vizier_tpu.benchmarks.experimenters import experimenter_factory

        return experimenter_factory.shifted_bbob_instance("Sphere", seed)

    def test_ucb_pe_beats_random_on_shifted_sphere_20d(self):
        from vizier_tpu.algorithms import core as core_lib

        seed = 1
        exp = self._shifted_sphere_20d(seed)
        problem = exp.problem_statement()

        def run(designer_factory):
            designer = designer_factory(problem, seed=seed)
            best, tid = np.inf, 0
            while tid < 60:
                batch = [
                    s.to_trial(tid + i + 1)
                    for i, s in enumerate(designer.suggest(10))
                ]
                tid += len(batch)
                exp.evaluate(batch)
                designer.update(core_lib.CompletedTrials(batch))
                for t in batch:
                    # bbob_eval is MINIMIZE: raw f(x), optimum 0 at the shift.
                    best = min(
                        best, t.final_measurement.metrics["bbob_eval"].value
                    )
            return best

        best_ucbpe = run(_ucb_pe_factory)
        best_random = run(
            lambda p, seed=None, **kw: RandomDesigner(p.search_space, seed=seed)
        )
        # Finals must be non-zero (the optimum is shifted off-center) and
        # the GP must clearly dominate random at equal budget.
        assert best_ucbpe > 0.0
        assert best_ucbpe < 0.5 * best_random, (
            f"UCB-PE regret {best_ucbpe:.2f} vs random {best_random:.2f}"
        )


class TestBudgetPolicyGate:
    """CI gate for the shipped DEFAULT acquisition budget policy
    (budget_ab_r5.json, 5 seeds × 3 families): first_pick_full must stay
    within tolerance of per_pick (reference semantics) on the pinned
    shifted instance. A regression in the split-budget path fails here."""

    def _run(self, policy, seed=1, trials=60, batch=10):
        from vizier_tpu.algorithms import core as core_lib
        from vizier_tpu.benchmarks.experimenters import experimenter_factory
        from vizier_tpu.designers.gp_ucb_pe import VizierGPUCBPEBandit

        exp = experimenter_factory.shifted_bbob_instance("Sphere", seed)
        problem = exp.problem_statement()
        designer = VizierGPUCBPEBandit(
            problem,
            rng_seed=seed,
            max_acquisition_evaluations=800,
            ard_restarts=4,
            ard_optimizer=_FAST_ARD,
            num_seed_trials=5,
            acquisition_budget_policy=policy,
        )
        best, tid = np.inf, 0
        while tid < trials:
            batch_trials = [
                s.to_trial(tid + i + 1)
                for i, s in enumerate(designer.suggest(batch))
            ]
            tid += len(batch_trials)
            exp.evaluate(batch_trials)
            designer.update(core_lib.CompletedTrials(batch_trials))
            for t in batch_trials:
                best = min(best, t.final_measurement.metrics["bbob_eval"].value)
        return best

    def test_first_pick_full_within_tolerance_of_per_pick(self):
        default = self._run("first_pick_full")
        reference_semantics = self._run("per_pick")
        # The committed 5-seed A/B medians tie (0.433 vs 0.439 at full
        # budget); at this reduced CI budget allow 2x + an absolute floor
        # before declaring the default regressed.
        assert default <= max(2.0 * reference_semantics, 1.0), (
            f"first_pick_full regret {default:.3f} vs per_pick "
            f"{reference_semantics:.3f}"
        )
