"""Tests for acquisitions, the vectorized/eagle optimizers, and GP-Bandit."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.designers.gp import acquisitions
from vizier_tpu.designers.gp_bandit import VizierGPBandit
from vizier_tpu.models import kernels
from vizier_tpu.optimizers import eagle as eagle_lib
from vizier_tpu.optimizers import lbfgs as lbfgs_lib
from vizier_tpu.optimizers import vectorized as vectorized_lib
from vizier_tpu.testing import test_runners

_FAST_ARD = lbfgs_lib.AdamOptimizer(maxiter=40)


class TestAcquisitions:
    def test_ucb_monotone_in_stddev(self):
        acq = acquisitions.UCB(2.0)
        lo = acq(jnp.asarray([0.0]), jnp.asarray([0.1]), jnp.asarray(0.0))
        hi = acq(jnp.asarray([0.0]), jnp.asarray([1.0]), jnp.asarray(0.0))
        assert float(hi[0]) > float(lo[0])

    def test_ei_nonnegative_and_increasing_in_mean(self):
        acq = acquisitions.EI()
        m = jnp.asarray([-1.0, 0.0, 1.0])
        s = jnp.full((3,), 0.5)
        vals = np.asarray(acq(m, s, jnp.asarray(0.0)))
        assert (vals >= 0).all()
        assert vals[2] > vals[1] > vals[0]

    def test_log_ei_matches_ei_argmax_region(self):
        acq_ei = acquisitions.EI()
        acq_log = acquisitions.LogEI()
        m = jnp.linspace(-2, 2, 41)
        s = jnp.full((41,), 0.3)
        ei = np.asarray(acq_ei(m, s, jnp.asarray(0.0)))
        lei = np.asarray(acq_log(m, s, jnp.asarray(0.0)))
        # Compare against a float64 exact log-EI: the f32 EI itself cancels
        # catastrophically for z ≲ -2, which is exactly what LogEI fixes, so
        # log(EI_f32) is not a valid oracle in that region.
        from scipy import stats

        z = (np.asarray(m, np.float64)) / np.asarray(s, np.float64)
        exact = np.log(
            np.asarray(s, np.float64)
            * (z * stats.norm.cdf(z) + stats.norm.pdf(z))
        )
        np.testing.assert_allclose(lei, exact, atol=1e-3)
        assert np.argmax(ei) == np.argmax(lei)

    def test_pi_in_unit_interval(self):
        acq = acquisitions.PI()
        vals = np.asarray(
            acq(jnp.linspace(-3, 3, 10), jnp.full((10,), 1.0), jnp.asarray(0.0))
        )
        assert (vals >= 0).all() and (vals <= 1).all()

    def test_q_acquisition(self):
        rng = jax.random.PRNGKey(0)
        means = jnp.asarray([[0.0, 2.0]])
        stds = jnp.asarray([[0.5, 0.5]])
        qei = acquisitions.q_acquisition(
            means, stds, rng, best_label=jnp.asarray(0.0), kind="qei"
        )
        assert float(qei[1]) > float(qei[0])


class TestTrustRegion:
    def test_penalty_zero_near_data(self):
        obs = kernels.MixedFeatures(
            jnp.asarray([[0.5, 0.5]], jnp.float32), jnp.zeros((1, 0), jnp.int32)
        )
        tr = acquisitions.TrustRegion(
            observed_continuous=obs.continuous,
            observed_cat=obs.categorical,
            row_mask=jnp.asarray([True]),
        )
        near = kernels.MixedFeatures(
            jnp.asarray([[0.55, 0.5]], jnp.float32), jnp.zeros((1, 0), jnp.int32)
        )
        far = kernels.MixedFeatures(
            jnp.asarray([[0.0, 1.0]], jnp.float32), jnp.zeros((1, 0), jnp.int32)
        )
        assert float(tr.penalty(near)[0]) == 0.0
        assert float(tr.penalty(far)[0]) > 0.0

    def test_categorical_mismatch_not_penalized(self):
        """Unobserved categorical combos must stay explorable (reference
        min_linf_distance excludes categorical dims from the L-inf norm —
        a mismatch would otherwise forbid every new cell)."""
        tr = acquisitions.TrustRegion(
            observed_continuous=jnp.asarray([[0.5]], jnp.float32),
            observed_cat=jnp.asarray([[0, 0, 0]], jnp.int32),
            row_mask=jnp.asarray([True]),
        )
        new_cell = kernels.MixedFeatures(
            jnp.asarray([[0.5]], jnp.float32), jnp.asarray([[4, 2, 3]], jnp.int32)
        )
        assert float(tr.penalty(new_cell)[0]) == 0.0

    def test_pure_categorical_space_all_trusted(self):
        tr = acquisitions.TrustRegion(
            observed_continuous=jnp.zeros((2, 0), jnp.float32),
            observed_cat=jnp.asarray([[0, 0], [1, 1]], jnp.int32),
            row_mask=jnp.asarray([True, True]),
        )
        q = kernels.MixedFeatures(
            jnp.zeros((3, 0), jnp.float32), jnp.asarray([[4, 4], [2, 0], [3, 1]], jnp.int32)
        )
        assert np.all(np.asarray(tr.penalty(q)) == 0.0)

    def test_no_observations_no_penalty(self):
        tr = acquisitions.TrustRegion(
            observed_continuous=jnp.zeros((4, 2), jnp.float32),
            observed_cat=jnp.zeros((4, 0), jnp.int32),
            row_mask=jnp.zeros((4,), bool),
        )
        q = kernels.MixedFeatures(
            jnp.asarray([[0.9, 0.9]], jnp.float32), jnp.zeros((1, 0), jnp.int32)
        )
        assert float(tr.penalty(q)[0]) == 0.0


def _quadratic_score(feats: kernels.MixedFeatures):
    """Max at continuous=(0.7, 0.3), categorical=[1]."""
    target = jnp.asarray([0.7, 0.3])
    score = -jnp.sum((feats.continuous - target) ** 2, axis=-1)
    if feats.categorical.shape[-1]:
        score = score + 0.5 * (feats.categorical[:, 0] == 1)
    return score


class TestVectorizedOptimizers:
    def test_random_strategy_finds_region(self):
        result = vectorized_lib.optimize_random(
            _quadratic_score,
            jax.random.PRNGKey(0),
            num_continuous=2,
            category_sizes=(3,),
            count=1,
            max_evaluations=4000,
        )
        best = np.asarray(result.features.continuous[0])
        assert np.abs(best - [0.7, 0.3]).max() < 0.15
        assert int(result.features.categorical[0, 0]) == 1

    def test_eagle_beats_random_budget_for_budget(self):
        budget = 2500
        rand = vectorized_lib.optimize_random(
            _quadratic_score,
            jax.random.PRNGKey(1),
            num_continuous=2,
            category_sizes=(3,),
            count=1,
            max_evaluations=budget,
        )
        strategy = eagle_lib.VectorizedEagleStrategy(
            num_continuous=2, category_sizes=(3,)
        )
        eagle = vectorized_lib.VectorizedOptimizer(strategy, max_evaluations=budget)(
            _quadratic_score, jax.random.PRNGKey(1), count=1
        )
        assert float(eagle.scores[0]) >= float(rand.scores[0]) - 1e-6
        assert float(eagle.scores[0]) > 0.49  # ~optimum is 0.5

    def test_eagle_topk_sorted_and_count(self):
        strategy = eagle_lib.VectorizedEagleStrategy(num_continuous=2, category_sizes=())
        res = vectorized_lib.VectorizedOptimizer(strategy, max_evaluations=1000)(
            _quadratic_score, jax.random.PRNGKey(2), count=5
        )
        scores = np.asarray(res.scores)
        assert len(scores) == 5
        assert (np.diff(scores) <= 1e-9).all()

    def test_prior_features_seed_pool(self):
        strategy = eagle_lib.VectorizedEagleStrategy(num_continuous=2, category_sizes=())
        prior = kernels.MixedFeatures(
            jnp.asarray([[0.7, 0.3]], jnp.float32), jnp.zeros((1, 0), jnp.int32)
        )
        res = vectorized_lib.VectorizedOptimizer(strategy, max_evaluations=200)(
            _quadratic_score, jax.random.PRNGKey(3), count=1, prior_features=prior
        )
        assert float(res.scores[0]) > -0.01  # prior point is already optimal


class TestGPBandit:
    def _problem(self):
        p = vz.ProblemStatement()
        p.search_space.root.add_float_param("x", -1.0, 1.0)
        p.search_space.root.add_float_param("y", -1.0, 1.0)
        p.metric_information.append(
            vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
        return p

    def test_seeding_before_enough_trials(self):
        designer = VizierGPBandit(self._problem(), num_seed_trials=3, ard_optimizer=_FAST_ARD)
        suggestions = designer.suggest(3)
        assert len(suggestions) == 3
        # First-ever suggestion is the search-space center.
        assert suggestions[0].parameters.get_value("x") == pytest.approx(0.0)

    def test_converges_on_sphere(self):
        problem = self._problem()

        def f(params):
            return -((params.get_value("x") - 0.4) ** 2 + (params.get_value("y")) ** 2)

        designer = VizierGPBandit(
            problem, max_acquisition_evaluations=1500, ard_restarts=4, ard_optimizer=_FAST_ARD
        )
        tid = 0
        best = -np.inf
        for _ in range(9):
            batch = designer.suggest(2)
            done = []
            for s in batch:
                tid += 1
                t = s.to_trial(tid)
                t.complete(vz.Measurement(metrics={"obj": f(s.parameters)}))
                best = max(best, f(s.parameters))
                done.append(t)
            designer.update(core_lib.CompletedTrials(done))
        assert best > -0.05  # found the neighborhood of (0.4, 0)

    def test_mixed_space_smoke(self):
        p = vz.ProblemStatement()
        p.search_space.root.add_float_param("x", 0.0, 1.0)
        p.search_space.root.add_categorical_param("c", ["u", "v", "w"])
        p.search_space.root.add_int_param("i", 1, 4)
        p.metric_information.append(
            vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
        designer = VizierGPBandit(
            p, max_acquisition_evaluations=500, num_seed_trials=2, ard_optimizer=_FAST_ARD
        )
        trials = test_runners.RandomMetricsRunner(
            p, iters=4, batch_size=2, seed=1
        ).run_designer(designer)
        assert len(trials) == 8

    def test_predict_and_metadata(self):
        problem = self._problem()
        designer = VizierGPBandit(
            problem, max_acquisition_evaluations=500, ard_restarts=2, ard_optimizer=_FAST_ARD
        )
        trials = []
        rng = np.random.default_rng(0)
        for i in range(6):
            t = vz.Trial(
                id=i + 1,
                parameters={"x": float(rng.uniform(-1, 1)), "y": float(rng.uniform(-1, 1))},
            )
            t.complete(vz.Measurement(metrics={"obj": float(rng.uniform())}))
            trials.append(t)
        designer.update(core_lib.CompletedTrials(trials))
        suggestions = designer.suggest(2)
        assert len(suggestions) == 2
        for s in suggestions:
            assert "acquisition" in s.metadata.ns("gp_bandit")
        pred = designer.predict(suggestions)
        assert pred.mean.shape == (2,) and pred.stddev.shape == (2,)
        assert (pred.stddev > 0).all()

    def test_infeasible_trials_handled(self):
        problem = self._problem()
        designer = VizierGPBandit(
            problem, max_acquisition_evaluations=500, ard_restarts=2, ard_optimizer=_FAST_ARD
        )
        trials = []
        rng = np.random.default_rng(0)
        for i in range(6):
            t = vz.Trial(
                id=i + 1,
                parameters={"x": float(rng.uniform(-1, 1)), "y": float(rng.uniform(-1, 1))},
            )
            if i % 3 == 0:
                t.complete(infeasibility_reason="failed")
            else:
                t.complete(vz.Measurement(metrics={"obj": float(rng.uniform())}))
            trials.append(t)
        designer.update(core_lib.CompletedTrials(trials))
        assert len(designer.suggest(1)) == 1

    def test_conditional_space_rejected(self):
        p = vz.ProblemStatement()
        sel = p.search_space.root.add_categorical_param("m", ["a", "b"])
        sel.select_values(["a"]).add_float_param("x", 0, 1)
        p.metric_information.append(vz.MetricInformation(name="obj"))
        with pytest.raises(ValueError):
            VizierGPBandit(p)


class TestRetraceDiscipline:
    def test_no_retrace_within_padding_bucket(self):
        """Suggests within one padding bucket must reuse the jit caches."""
        from vizier_tpu.designers import gp_bandit as gpb

        problem = vz.ProblemStatement()
        problem.search_space.root.add_float_param("x", -1.0, 1.0)
        problem.metric_information.append(
            vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
        designer = gpb.VizierGPBandit(
            problem,
            max_acquisition_evaluations=300,
            ard_restarts=2,
            num_seed_trials=2,
            ard_optimizer=_FAST_ARD,
        )
        rng = np.random.default_rng(0)

        def complete_batch(k):
            done = []
            for s in designer.suggest(1):
                t = s.to_trial(k)
                t.complete(vz.Measurement(metrics={"obj": float(rng.uniform())}))
                done.append(t)
            designer.update(core_lib.CompletedTrials(done))

        # Get past seeding and into the 8-bucket (3..7 trials pad to 8).
        for k in range(1, 4):
            complete_batch(k)
        train_sizes = gpb._train_gp._cache_size()
        acq_sizes = gpb._maximize_acquisition._cache_size()
        for k in range(4, 7):  # still inside the 8-bucket
            complete_batch(k)
        assert gpb._train_gp._cache_size() == train_sizes
        assert gpb._maximize_acquisition._cache_size() == acq_sizes


class TestInputWarpingKnob:
    def test_designer_exposes_input_warping(self):
        p = vz.ProblemStatement()
        p.search_space.root.add_float_param("x", 0.0, 1.0)
        p.metric_information.append(
            vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
        d = VizierGPBandit(
            p,
            use_input_warping=True,
            max_acquisition_evaluations=300,
            ard_restarts=2,
            num_seed_trials=2,
            ard_optimizer=_FAST_ARD,
        )
        assert d._model.use_input_warping
        trials = test_runners.RandomMetricsRunner(p, iters=3, batch_size=2).run_designer(d)
        assert len(trials) == 6


class TestJointQEIBatch:
    def test_qei_batch_is_joint_and_diverse(self):
        p = vz.ProblemStatement()
        p.search_space.root.add_float_param("x", -1.0, 1.0)
        p.metric_information.append(
            vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
        d = VizierGPBandit(
            p,
            acquisition="qei",
            max_acquisition_evaluations=1000,
            ard_restarts=2,
            num_seed_trials=3,
            ard_optimizer=_FAST_ARD,
        )
        trials = []
        for i, x in enumerate(np.linspace(-1, 1, 6)):
            t = vz.Trial(id=i + 1, parameters={"x": float(x)})
            t.complete(vz.Measurement(metrics={"obj": -((x - 0.3) ** 2)}))
            trials.append(t)
        d.update(core_lib.CompletedTrials(trials))
        batch = d.suggest(3)
        xs = [s.parameters.get_value("x") for s in batch]
        kinds = {s.metadata.ns("gp_bandit")["acquisition_kind"] for s in batch}
        assert kinds == {"qei_joint"}
        assert len(set(round(x, 4) for x in xs)) == 3  # joint batch is diverse

    def test_qei_single_point_uses_ei(self):
        p = vz.ProblemStatement()
        p.search_space.root.add_float_param("x", -1.0, 1.0)
        p.metric_information.append(
            vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
        d = VizierGPBandit(
            p,
            acquisition="qei",
            max_acquisition_evaluations=500,
            ard_restarts=2,
            num_seed_trials=2,
            ard_optimizer=_FAST_ARD,
        )
        trials = test_runners.RandomMetricsRunner(p, iters=3, batch_size=1).run_designer(d)
        assert len(trials) == 3


class TestReferencePointHelpers:
    def test_best_worst_and_reference(self):
        from vizier_tpu.designers.gp import acquisitions as acq

        labels = jnp.asarray([[0.0, 1.0, 2.0, 99.0], [-1.0, 0.0, 3.0, 99.0]])
        mask = jnp.asarray([True, True, True, False])
        np.testing.assert_allclose(acq.get_best_labels(labels, mask), [2.0, 3.0])
        np.testing.assert_allclose(acq.get_worst_labels(labels, mask), [0.0, -1.0])
        # nadir - 0.1 * max(range, 1)
        np.testing.assert_allclose(
            acq.get_reference_point(labels, mask), [-0.2, -1.4]
        )

    def test_reference_point_zero_span_floor(self):
        from vizier_tpu.designers.gp import acquisitions as acq

        labels = jnp.zeros((2, 3))
        mask = jnp.ones((3,), bool)
        # All-equal labels: ref must sit strictly below the nadir.
        np.testing.assert_allclose(
            acq.get_reference_point(labels, mask), [-0.1, -0.1]
        )

    def test_reference_point_no_valid_rows(self):
        from vizier_tpu.designers.gp import acquisitions as acq

        labels = jnp.zeros((2, 3))
        mask = jnp.zeros((3,), bool)
        assert np.all(np.isfinite(acq.get_reference_point(labels, mask)))


class TestPredictionUserScale:
    def test_minimize_metric_predictions_are_not_sign_flipped(self):
        """Regression: the model trains all-MAXIMIZE (flipped labels); the
        Predictor contract is USER scale, so MINIMIZE predictions at an
        observed point must land near the observed (positive) value."""
        problem = vz.ProblemStatement()
        problem.search_space.root.add_float_param("x", 0.0, 1.0)
        problem.metric_information.append(
            vz.MetricInformation(
                name="loss", goal=vz.ObjectiveMetricGoal.MINIMIZE
            )
        )
        d = VizierGPBandit(
            problem, ard_restarts=2, ard_optimizer=_FAST_ARD, num_seed_trials=2
        )
        trials = []
        for i, x in enumerate(np.linspace(0.0, 1.0, 8)):
            t = vz.Trial(id=i + 1, parameters={"x": float(x)})
            # Loss in [5, 9]: strictly positive user-space values.
            t.complete(
                vz.Measurement(metrics={"loss": float(5.0 + 4.0 * (x - 0.5) ** 2 * 4)})
            )
            trials.append(t)
        d.update(core_lib.CompletedTrials(trials))
        pred = d.predict(
            [vz.TrialSuggestion(parameters={"x": 0.5})], num_samples=500
        )
        assert 4.0 < float(pred.mean[0]) < 10.0, pred.mean
