"""Behavioral tests for the vectorized eagle (firefly) strategy.

Reference analog: ``optimizers/eagle_strategy_test.py`` — attraction
toward better flies, perturbation penalization/decay, exhausted-fly
re-seeding (never the best), categorical mutation validity, prior-feature
pool seeding, and end-to-end optimization quality vs random search under
an equal evaluation budget.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from vizier_tpu.models import kernels
from vizier_tpu.optimizers import eagle as eagle_lib
from vizier_tpu.optimizers import vectorized as vectorized_lib


def _strategy(dc=2, sizes=(), **cfg):
    config = eagle_lib.EagleStrategyConfig(**cfg) if cfg else eagle_lib.EagleStrategyConfig()
    return eagle_lib.VectorizedEagleStrategy(
        num_continuous=dc, category_sizes=sizes, config=config
    )


class TestPoolDynamics:
    def test_attraction_moves_unseen_gap_toward_better_fly(self):
        """A low-reward fly's proposal drifts toward the high-reward fly."""
        s = _strategy(dc=2, pool_size=2, perturbation=0.0)
        state = eagle_lib.EagleState(
            features=jnp.asarray([[0.2, 0.2], [0.8, 0.8]], jnp.float32),
            categorical=jnp.zeros((2, 0), jnp.int32),
            rewards=jnp.asarray([0.0, 10.0], jnp.float32),
            perturbations=jnp.zeros((2,), jnp.float32),
        )
        proposal = s.suggest(state, jax.random.PRNGKey(0))
        moved = np.asarray(proposal.continuous)
        # Fly 0 (worse) moves toward fly 1; fly 1 barely moves toward fly 0.
        assert moved[0, 0] > 0.2 and moved[0, 1] > 0.2
        dist0 = np.linalg.norm(moved[0] - np.array([0.8, 0.8]))
        assert dist0 < np.linalg.norm([0.6, 0.6])

    def test_unimproved_fly_perturbation_decays(self):
        s = _strategy(dc=2, pool_size=4)
        rng = jax.random.PRNGKey(1)
        state = s.init_state(rng)
        cands = s.suggest(state, rng)
        worse = jnp.full((4,), -jnp.inf)  # nobody improves (rewards were -inf... use second round)
        state = s.update(state, rng, cands, jnp.zeros((4,)))  # first: all improve
        p0 = np.asarray(state.perturbations).copy()
        cands = s.suggest(state, jax.random.PRNGKey(2))
        state = s.update(state, jax.random.PRNGKey(3), cands, worse)
        p1 = np.asarray(state.perturbations)
        np.testing.assert_allclose(p1, p0 * s.config.penalize_factor, rtol=1e-5)

    def test_exhausted_fly_reseeds_but_best_survives(self):
        s = _strategy(dc=2, pool_size=3)
        rng = jax.random.PRNGKey(0)
        state = eagle_lib.EagleState(
            features=jnp.asarray([[0.1, 0.1], [0.5, 0.5], [0.9, 0.9]], jnp.float32),
            categorical=jnp.zeros((3, 0), jnp.int32),
            rewards=jnp.asarray([1.0, 5.0, 2.0], jnp.float32),
            # Below the lower bound after one penalization.
            perturbations=jnp.full((3,), 1e-5, jnp.float32),
        )
        cands = kernels.MixedFeatures(state.features, state.categorical)
        new = s.update(state, rng, cands, jnp.asarray([-1.0, -1.0, -1.0]))
        rewards = np.asarray(new.rewards)
        # Best fly (index 1) keeps its reward; the others were re-seeded.
        assert rewards[1] == 5.0
        assert rewards[0] == -np.inf and rewards[2] == -np.inf
        assert np.asarray(new.perturbations)[0] == pytest.approx(
            s.config.perturbation
        )

    def test_categorical_proposals_always_valid(self):
        sizes = (3, 5, 2)
        s = _strategy(dc=1, sizes=sizes)
        rng = jax.random.PRNGKey(0)
        state = s.init_state(rng)
        state = state.replace(rewards=jnp.arange(s.config.pool_size, dtype=jnp.float32))
        for i in range(5):
            prop = s.suggest(state, jax.random.PRNGKey(i))
            cat = np.asarray(prop.categorical)
            for d, size in enumerate(sizes):
                assert cat[:, d].min() >= 0 and cat[:, d].max() < size
            cont = np.asarray(prop.continuous)
            assert cont.min() >= 0.0 and cont.max() <= 1.0

    def test_prior_features_seed_pool_head(self):
        s = _strategy(dc=2, sizes=(4,))
        prior = kernels.MixedFeatures(
            jnp.asarray([[0.25, 0.75]], jnp.float32), jnp.asarray([[2]], jnp.int32)
        )
        state = s.init_state(jax.random.PRNGKey(0), prior_features=prior)
        np.testing.assert_allclose(
            np.asarray(state.features)[0], [0.25, 0.75], atol=1e-6
        )
        assert int(np.asarray(state.categorical)[0, 0]) == 2


class TestOptimizationQuality:
    def test_beats_random_search_at_equal_budget(self):
        """Eagle must beat pure random sampling on a smooth 6-D bowl."""
        dc = 6
        target = jnp.asarray([0.3, 0.7, 0.5, 0.2, 0.9, 0.4])

        def score(feats: kernels.MixedFeatures):
            return -jnp.sum((feats.continuous - target[None, :]) ** 2, axis=-1)

        budget = 4000
        eagle_opt = vectorized_lib.VectorizedOptimizer(
            _strategy(dc=dc), max_evaluations=budget
        )
        res = eagle_opt(score, jax.random.PRNGKey(0), count=1)
        eagle_best = float(res.scores[0])

        rand = jax.random.uniform(jax.random.PRNGKey(0), (budget, dc))
        rand_best = float(
            jnp.max(score(kernels.MixedFeatures(rand, jnp.zeros((budget, 0), jnp.int32))))
        )
        assert eagle_best > rand_best
        assert eagle_best > -1e-3  # essentially at the optimum

    def test_mixed_space_finds_categorical_optimum(self):
        sizes = (4, 4)

        def score(feats: kernels.MixedFeatures):
            cat_bonus = jnp.sum((feats.categorical == 2).astype(jnp.float32), axis=-1)
            return cat_bonus - jnp.sum((feats.continuous - 0.5) ** 2, axis=-1)

        opt = vectorized_lib.VectorizedOptimizer(
            _strategy(dc=2, sizes=sizes), max_evaluations=3000
        )
        res = opt(score, jax.random.PRNGKey(1), count=1)
        assert np.asarray(res.features.categorical)[0].tolist() == [2, 2]
        np.testing.assert_allclose(
            np.asarray(res.features.continuous)[0], 0.5, atol=0.05
        )
