"""Separable multitask GP inside UCB-PE (reference ``UCBPEConfig.multitask_type``,
``/root/reference/vizier/_src/algorithms/designers/gp_ucb_pe.py:130-134``).

The SEPARABLE option swaps the per-metric independent GPs for one joint GP
with a learned task covariance (B ⊗ Kx Gram, ``models/multitask_gp.py``);
every UCB-PE acquisition formula is shared between the two paths.
"""

import numpy as np
import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.benchmarks.analyzers import convergence_curve as cc
from vizier_tpu.benchmarks.experimenters.synthetic import multiobjective
from vizier_tpu.designers.gp_ucb_pe import (
    MultiTaskType,
    UCBPEConfig,
    VizierGPUCBPEBandit,
)
from vizier_tpu.models import multitask_gp as mtgp
from vizier_tpu.optimizers.lbfgs import AdamOptimizer

_FAST_ARD = AdamOptimizer(maxiter=40)


def _two_metric_problem(dim=3):
    problem = vz.ProblemStatement()
    for d in range(dim):
        problem.search_space.root.add_float_param(f"x{d}", 0.0, 1.0)
    for name in ("m1", "m2"):
        problem.metric_information.append(
            vz.MetricInformation(name=name, goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
    return problem


def _designer(problem, multitask_type, seed=1, evals=600):
    return VizierGPUCBPEBandit(
        problem,
        rng_seed=seed,
        max_acquisition_evaluations=evals,
        ard_restarts=4,
        ard_optimizer=_FAST_ARD,
        num_seed_trials=3,
        config=UCBPEConfig(multitask_type=multitask_type, num_scalarizations=50),
    )


def _run(designer, exp_fn, problem, num_trials, batch, dim):
    tid = 0
    trials = []
    while tid < num_trials:
        batch_trials = [
            s.to_trial(tid + i + 1) for i, s in enumerate(designer.suggest(batch))
        ]
        tid += len(batch_trials)
        for t in batch_trials:
            xs = np.array([t.parameters.get_value(f"x{d}") for d in range(dim)])
            t.complete(vz.Measurement(metrics=exp_fn(xs)))
        designer.update(core_lib.CompletedTrials(batch_trials))
        trials.extend(batch_trials)
    return trials


class TestMultitaskConfig:
    def test_config_rejects_non_enum(self):
        with pytest.raises(ValueError, match="multitask_type"):
            UCBPEConfig(multitask_type="SEPARABLE")

    def test_default_is_independent(self):
        assert UCBPEConfig().multitask_type is MultiTaskType.INDEPENDENT

    def test_single_metric_never_uses_multitask(self):
        problem = vz.ProblemStatement()
        problem.search_space.root.add_float_param("x0", 0.0, 1.0)
        problem.metric_information.append(
            vz.MetricInformation(name="m", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
        d = _designer(problem, MultiTaskType.SEPARABLE)
        assert not d._use_multitask(1)


class TestMultitaskSuggest:
    @pytest.mark.parametrize(
        "variant",
        [
            MultiTaskType.SEPARABLE,
            MultiTaskType.SEPARABLE_LKJ,
            MultiTaskType.SEPARABLE_DIAG,
        ],
    )
    def test_variant_trains_joint_state_and_suggests(self, variant):
        """Each SEPARABLE variant drives the full designer loop."""
        problem = _two_metric_problem()
        d = _designer(problem, variant)
        _run(
            d,
            lambda xs: {
                "m1": float(-np.sum((xs - 0.3) ** 2)),
                "m2": float(-np.sum((xs - 0.7) ** 2)),
            },
            problem,
            num_trials=6,
            batch=3,
            dim=3,
        )
        states, _ = d._train_states_me()
        assert isinstance(states, mtgp.MultiTaskGPState)
        # Suggestions stay inside the search box.
        for s in d.suggest(3):
            for di in range(3):
                assert 0.0 <= s.parameters.get_value(f"x{di}") <= 1.0

    def _learned_task_corr(self, multitask_type, metric_fn, seed=3):
        """Fits the joint GP on 12 random trials; returns B's correlation."""
        problem = _two_metric_problem()
        d = _designer(problem, multitask_type, seed=seed)
        rng = np.random.default_rng(0)
        trials = []
        for i in range(12):
            xs = rng.uniform(size=3)
            t = vz.Trial(
                id=i + 1, parameters={f"x{j}": float(xs[j]) for j in range(3)}
            )
            base = float(-np.sum((xs - 0.5) ** 2))
            t.complete(
                vz.Measurement(
                    metrics=metric_fn(base, float(rng.normal()))
                )
            )
            trials.append(t)
        d.update(core_lib.CompletedTrials(trials))
        states, _ = d._train_states_me()
        model = d._mt_model(2)
        # Best ensemble member's constrained params → task covariance.
        p0 = {k: v[0] for k, v in states.params.items()}
        b = np.asarray(model._task_cov(p0))
        return b[0, 1] / np.sqrt(b[0, 0] * b[1, 1])

    def test_correlated_metrics_learn_task_coupling(self):
        """Two strongly correlated metrics → learned B has positive
        off-diagonal correlation."""
        corr = self._learned_task_corr(
            MultiTaskType.SEPARABLE,
            lambda base, eps: {"m1": base, "m2": 0.9 * base + 0.01 * eps},
        )
        assert corr > 0.1, f"correlated tasks should couple, got corr={corr:.3f}"

    def test_anticorrelated_metrics_learn_negative_coupling(self):
        """Anti-correlated metrics (the multi-objective trade-off case) must
        learn a NEGATIVE task correlation — requires the signed off-diagonal
        Cholesky parameterization (reference signed Normal prior,
        multitask_tuned_gp_models.py:144-151)."""
        corr = self._learned_task_corr(
            MultiTaskType.SEPARABLE,
            lambda base, eps: {"m1": base, "m2": -0.9 * base + 0.01 * eps},
        )
        assert corr < -0.1, (
            f"anti-correlated tasks should couple negatively, got corr={corr:.3f}"
        )

    def test_lkj_learns_signed_coupling(self):
        corr_pos = self._learned_task_corr(
            MultiTaskType.SEPARABLE_LKJ,
            lambda base, eps: {"m1": base, "m2": 0.9 * base + 0.01 * eps},
        )
        corr_neg = self._learned_task_corr(
            MultiTaskType.SEPARABLE_LKJ,
            lambda base, eps: {"m1": base, "m2": -0.9 * base + 0.01 * eps},
        )
        assert corr_pos > 0.1, f"LKJ positive coupling, got {corr_pos:.3f}"
        assert corr_neg < -0.1, f"LKJ negative coupling, got {corr_neg:.3f}"

    def test_diag_has_no_cross_task_coupling(self):
        corr = self._learned_task_corr(
            MultiTaskType.SEPARABLE_DIAG,
            lambda base, eps: {"m1": base, "m2": 0.9 * base + 0.01 * eps},
        )
        assert abs(corr) < 0.05, f"DIAG B must be diagonal, got corr={corr:.3f}"

    def test_separable_normal_is_alias(self):
        assert MultiTaskType.SEPARABLE_NORMAL is MultiTaskType.SEPARABLE

    def test_predict_and_sample_shapes(self):
        problem = _two_metric_problem()
        d = _designer(problem, MultiTaskType.SEPARABLE)
        _run(
            d,
            lambda xs: {
                "m1": float(-np.sum(xs**2)),
                "m2": float(-np.sum((xs - 1.0) ** 2)),
            },
            problem,
            num_trials=6,
            batch=3,
            dim=3,
        )
        sugg = d.suggest(2)
        samples = d.sample(sugg, num_samples=16)
        assert samples.shape == (16, 2, 2)  # [S, T, M]
        pred = d.predict(sugg)
        assert pred.mean.shape == (2, 2)
        assert np.all(np.isfinite(pred.mean))


class TestMultitaskZDT1Quality:
    def test_separable_hypervolume_comparable_to_independent(self):
        """SEPARABLE must be a usable multimetric mode: its ZDT1 hypervolume
        stays within a band of the INDEPENDENT default at equal budget."""
        exp = multiobjective.MultiObjectiveExperimenter.zdt("zdt1", dimension=3)
        problem = exp.problem_statement()
        metrics = list(problem.metric_information)
        ref_point = np.array([-1.1, -6.0], dtype=np.float32)

        def final_hv(multitask_type, seed):
            d = VizierGPUCBPEBandit(
                problem,
                rng_seed=seed,
                max_acquisition_evaluations=600,
                ard_restarts=4,
                ard_optimizer=_FAST_ARD,
                num_seed_trials=4,
                config=UCBPEConfig(
                    multitask_type=multitask_type, num_scalarizations=50
                ),
            )
            tid = 0
            trials = []
            while tid < 20:
                batch = [
                    s.to_trial(tid + i + 1)
                    for i, s in enumerate(d.suggest(4))
                ]
                tid += len(batch)
                exp.evaluate(batch)
                d.update(core_lib.CompletedTrials(batch))
                trials.extend(batch)
            curve = cc.HypervolumeCurveConverter(
                metrics, reference_point=ref_point
            ).convert(trials)
            return float(curve.ys[0, -1])

        # Averaged over seeds so one unlucky ARD fit can neither trip the
        # gate spuriously nor hide a real collapse.
        seeds = (1, 2)
        hv_sep = float(
            np.mean([final_hv(MultiTaskType.SEPARABLE, seed=s) for s in seeds])
        )
        hv_ind = float(
            np.mean([final_hv(MultiTaskType.INDEPENDENT, seed=s) for s in seeds])
        )
        assert hv_sep > 0.0, "separable runs must dominate the reference point"
        # Statistical band, not superiority: equal-budget mean HV within 40%
        # of the independent default.
        assert hv_sep >= 0.6 * hv_ind, (
            f"separable HV {hv_sep:.3f} collapsed vs independent {hv_ind:.3f}"
        )
