"""Tests for the round-2 small-gap fills: scheduled UCB-PE preset,
meta-learning phases, and BOCS horseshoe/SDP upgrades."""

import numpy as np
import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.benchmarks.experimenters import combinatorial
from vizier_tpu.designers import bocs as bocs_lib
from vizier_tpu.designers import meta_learning, scheduled_designer
from vizier_tpu.pyvizier import trial as trial_


def _problem():
    p = vz.ProblemStatement()
    p.search_space.root.add_float_param("x", 0.0, 1.0)
    p.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return p


class TestScheduledUcbPe:
    def test_coefficients_decay_over_budget(self):
        d = scheduled_designer.scheduled_gp_ucb_pe(
            _problem(), expected_total_num_trials=10, seed=0
        )
        # Drive via the schedule machinery only (no GP work: inspect values).
        assert d._maybe_rebuild() is not None
        early = dict(d._current_values)
        trials = []
        for i in range(10):
            t = trial_.Trial(id=i + 1, parameters={"x": i / 10})
            t.complete(vz.Measurement(metrics={"obj": i / 10}))
            trials.append(t)
        d.update(core_lib.CompletedTrials(trials))
        d._maybe_rebuild()
        late = dict(d._current_values)
        assert late["ucb_coefficient"] < early["ucb_coefficient"]
        assert (
            late["explore_region_ucb_coefficient"]
            < early["explore_region_ucb_coefficient"]
        )

    def test_inner_designer_is_ucb_pe(self):
        from vizier_tpu.designers.gp_ucb_pe import VizierGPUCBPEBandit

        d = scheduled_designer.scheduled_gp_ucb_pe(_problem(), seed=0)
        inner = d._maybe_rebuild()
        assert isinstance(inner, VizierGPUCBPEBandit)


class TestMetaLearningPhases:
    def _designer(self, **cfg_kwargs):
        space = vz.SearchSpace()
        space.root.add_float_param("knob", 0.0, 1.0)

        from vizier_tpu.designers import RandomDesigner

        def inner_factory(problem, **hparams):
            return RandomDesigner(problem.search_space, seed=0)

        return meta_learning.MetaLearningDesigner(
            problem=_problem(),
            tuning_space=space,
            inner_factory=inner_factory,
            config=meta_learning.MetaLearningConfig(
                tuning_interval=4, **cfg_kwargs
            ),
            seed=0,
        )

    def _run(self, d, rounds, batch=2):
        tid = 0
        for _ in range(rounds):
            trials = []
            for s in d.suggest(batch):
                tid += 1
                t = s.to_trial(tid)
                t.complete(vz.Measurement(metrics={"obj": np.random.rand()}))
                trials.append(t)
            d.update(core_lib.CompletedTrials(trials))

    def test_initialize_phase_before_min_trials(self):
        d = self._designer(tuning_min_num_trials=10)
        assert d.state == meta_learning.MetaLearningState.INITIALIZE
        self._run(d, rounds=2)
        assert d.state == meta_learning.MetaLearningState.INITIALIZE
        # No meta trials scored while initializing.
        assert not d._meta_trials

    def test_tune_phase_scores_configs(self):
        d = self._designer(tuning_min_num_trials=0)
        self._run(d, rounds=6)
        assert d.state == meta_learning.MetaLearningState.TUNE
        assert len(d._meta_trials) >= 1
        for t in d._meta_trials:
            assert meta_learning.META_METRIC in t.final_measurement.metrics

    def test_use_best_params_locks_in(self):
        d = self._designer(tuning_min_num_trials=0, tuning_max_num_trials=8)
        self._run(d, rounds=8)
        assert d.state == meta_learning.MetaLearningState.USE_BEST_PARAMS
        n_meta = len(d._meta_trials)
        self._run(d, rounds=3)
        # Locked: no further meta exploration.
        assert len(d._meta_trials) == n_meta


class TestHarmonicaStages:
    def test_staged_fixing_converges(self):
        from vizier_tpu.designers.harmonica import HarmonicaDesigner

        p = vz.ProblemStatement()
        for i in range(10):
            p.search_space.root.add_bool_param(f"b{i}")
        p.metric_information.append(
            vz.MetricInformation(
                name="objective", goal=vz.ObjectiveMetricGoal.MAXIMIZE
            )
        )
        d = HarmonicaDesigner(p, seed=0, samples_per_stage=16, num_fixed_per_stage=2)
        tid = 0
        for _ in range(9):
            trials = []
            for s in d.suggest(8):
                tid += 1
                t = s.to_trial(tid)
                bits = [
                    1.0 if str(t.parameters[f"b{i}"].value) == "True" else 0.0
                    for i in range(10)
                ]
                t.complete(
                    vz.Measurement(
                        metrics={"objective": 5 * bits[0] + 4 * bits[1] + 0.1 * sum(bits[2:])}
                    )
                )
                trials.append(t)
            d.update(core_lib.CompletedTrials(trials))
        # Stages advanced; the dominant variables are fixed to True.
        assert d._stage >= 2
        assert d._fixed.get(0) == 1 and d._fixed.get(1) == 1

    def test_stage_budget_not_reached_keeps_sampling(self):
        from vizier_tpu.designers.harmonica import HarmonicaDesigner

        p = vz.ProblemStatement()
        for i in range(4):
            p.search_space.root.add_bool_param(f"b{i}")
        p.metric_information.append(
            vz.MetricInformation(
                name="objective", goal=vz.ObjectiveMetricGoal.MAXIMIZE
            )
        )
        d = HarmonicaDesigner(p, seed=0, samples_per_stage=100)
        assert len(d.suggest(5)) == 5
        assert d._stage == 0 and not d._fixed


class TestBocsUpgrades:
    def _loop(self, designer, exp, rounds=5, batch=2):
        tid = 0
        best = np.inf
        for _ in range(rounds):
            trials = []
            for s in designer.suggest(batch):
                tid += 1
                trials.append(s.to_trial(tid))
            exp.evaluate(trials)
            for t in trials:
                best = min(
                    best, t.final_measurement.metrics["main_objective"].value
                )
            designer.update(core_lib.CompletedTrials(trials))
        return best

    @pytest.mark.parametrize("surrogate", ["horseshoe", "ridge"])
    @pytest.mark.parametrize("opt", ["sa", "sdp"])
    def test_all_variants_run(self, surrogate, opt):
        exp = combinatorial.ContaminationExperimenter(seed=0, n_stages=8)
        d = bocs_lib.BOCSDesigner(
            exp.problem_statement(),
            seed=1,
            surrogate=surrogate,
            acquisition_optimizer=opt,
            gibbs_samples=10,
            anneal_steps=30,
            num_restarts=2,
        )
        best = self._loop(d, exp)
        assert np.isfinite(best)

    def test_horseshoe_shrinks_spurious_coefficients(self):
        """Sparse prior: inactive bits' coefficients shrink toward zero."""
        rng = np.random.default_rng(0)
        d = 10
        n = 60
        x = rng.integers(0, 2, size=(n, d)).astype(float)
        y = 3.0 * x[:, 0] - 2.0 * x[:, 1] + 0.01 * rng.standard_normal(n)
        phi = np.concatenate([np.ones((n, 1)), x], axis=1)
        coef = bocs_lib._horseshoe_gibbs(
            phi, y, np.random.default_rng(1), num_samples=100
        )
        active = np.abs(coef[1:3])
        inactive = np.abs(coef[3:])
        assert active.min() > 1.0
        assert inactive.max() < 0.5

    def test_unknown_options_rejected(self):
        exp = combinatorial.ContaminationExperimenter(seed=0, n_stages=4)
        d = bocs_lib.BOCSDesigner(
            exp.problem_statement(), surrogate="bogus", seed=0
        )
        t = trial_.Trial(id=1, parameters={f"x_{i}": False for i in range(4)})
        exp.evaluate([t])
        d.update(core_lib.CompletedTrials([t]))
        t2 = trial_.Trial(id=2, parameters={f"x_{i}": True for i in range(4)})
        exp.evaluate([t2])
        d.update(core_lib.CompletedTrials([t2]))
        with pytest.raises(ValueError, match="surrogate"):
            d.suggest(1)
