"""Bit-identity of the IR-ported programs vs their pre-port path.

The port moved the designers' ``batch_*`` method bodies into registered
``DesignerProgram`` classes; the pre-port contract — slot i of a batched
flush is bit-identical to study i run alone through the sequential
``suggest`` at the same seed, and a singleton through the executor IS the
sequential path — must survive the move for every ported kind."""

import numpy as np

from vizier_tpu import pyvizier as vz
from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.compute import registry as compute_registry
from vizier_tpu.designers.gp_bandit import VizierGPBandit
from vizier_tpu.designers.gp_ucb_pe import VizierGPUCBPEBandit
from vizier_tpu.optimizers import lbfgs as lbfgs_lib
from vizier_tpu.parallel.batch_executor import BatchExecutor
from vizier_tpu.surrogates import SurrogateConfig

_FAST = dict(
    ard_optimizer=lbfgs_lib.AdamOptimizer(maxiter=15),
    ard_restarts=3,
    max_acquisition_evaluations=200,
    warm_start_min_trials=0,
)

_SPARSE = SurrogateConfig(
    sparse_threshold_trials=1, hysteresis_trials=0, num_inducing=6
)


def _problem():
    p = vz.ProblemStatement()
    for d in range(2):
        p.search_space.root.add_float_param(f"x{d}", 0.0, 1.0)
    p.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return p


def _feed(designer, seed, n=5):
    rng = np.random.default_rng(seed)
    trials = []
    for i in range(n):
        t = vz.Trial(
            parameters={"x0": float(rng.uniform()), "x1": float(rng.uniform())},
            id=i + 1,
        )
        t.complete(vz.Measurement(metrics={"obj": float(rng.uniform())}))
        trials.append(t)
    designer.update(core_lib.CompletedTrials(trials))
    return designer


_FACTORIES = {
    "gp_bandit": lambda seed: _feed(
        VizierGPBandit(_problem(), rng_seed=seed, **_FAST), seed
    ),
    "gp_bandit_sparse": lambda seed: _feed(
        VizierGPBandit(
            _problem(), rng_seed=seed, surrogate=_SPARSE, num_seed_trials=1,
            **_FAST,
        ),
        seed,
    ),
    "gp_ucb_pe": lambda seed: _feed(
        VizierGPUCBPEBandit(_problem(), rng_seed=seed, **_FAST), seed
    ),
    "gp_ucb_pe_sparse": lambda seed: _feed(
        VizierGPUCBPEBandit(
            _problem(), rng_seed=seed, surrogate=_SPARSE, **_FAST
        ),
        seed,
    ),
}


def _params(suggestions):
    return [s.parameters.as_dict() for s in suggestions]


def _assert_bit_identical(a, b):
    assert len(a) == len(b)
    for pa, pb in zip(a, b):
        assert pa.keys() == pb.keys()
        for k in pa:
            # Same program, same keys, same inputs: float-EQUAL, not close.
            assert pa[k] == pb[k], (k, pa[k], pb[k])


class TestPortedProgramBitIdentity:
    """For each ported kind: batched slots == sequential runs, bit-for-bit."""

    def _run_kind(self, kind, count, batch_seeds):
        factory = _FACTORIES[kind]
        sequential = [factory(s).suggest(count) for s in batch_seeds]

        batched = [factory(s) for s in batch_seeds]
        resolved = [compute_registry.resolve(d, count) for d in batched]
        assert all(r is not None and r[1].kind == kind for r in resolved)
        program = resolved[0][0]
        items = [program.prepare(d, count) for d in batched]
        outs = program.device_program(items, pad_to=max(4, len(items)))
        results = [
            program.finalize(d, i, o) for d, i, o in zip(batched, items, outs)
        ]
        for seq, res in zip(sequential, results):
            _assert_bit_identical(_params(seq), _params(res))

    def test_gp_bandit_exact(self):
        self._run_kind("gp_bandit", count=2, batch_seeds=(11, 12, 13))

    def test_gp_bandit_sparse(self):
        self._run_kind("gp_bandit_sparse", count=2, batch_seeds=(21, 22, 23))

    def test_gp_ucb_pe_exact_two_phase(self):
        self._run_kind("gp_ucb_pe", count=3, batch_seeds=(31, 32))

    def test_gp_ucb_pe_exact_count_1(self):
        self._run_kind("gp_ucb_pe", count=1, batch_seeds=(41, 42))

    def test_gp_ucb_pe_sparse_two_phase(self):
        self._run_kind("gp_ucb_pe_sparse", count=3, batch_seeds=(51, 52))

    def test_gp_ucb_pe_sparse_count_1(self):
        self._run_kind("gp_ucb_pe_sparse", count=1, batch_seeds=(61, 62))


class TestExecutorSingletonIsSequential:
    """A lone slot through the IR-routed executor takes the plain
    sequential path — bit-identical to batching off."""

    def _run_kind(self, kind, seed=77):
        reference = _FACTORIES[kind](seed).suggest(1)
        executor = BatchExecutor(max_batch_size=8, max_wait_ms=1.0)
        try:
            routed = executor.suggest(_FACTORIES[kind](seed), 1)
        finally:
            executor.close()
        _assert_bit_identical(_params(reference), _params(routed))

    def test_gp_bandit_exact(self):
        self._run_kind("gp_bandit")

    def test_gp_bandit_sparse(self):
        self._run_kind("gp_bandit_sparse")

    def test_gp_ucb_pe_exact(self):
        self._run_kind("gp_ucb_pe")

    def test_gp_ucb_pe_sparse(self):
        self._run_kind("gp_ucb_pe_sparse")


class TestLegacyDuckSurfaceMatchesPrograms:
    """The thin designer-level ``batch_*`` methods delegate to the same
    registered programs (subclass/test/chaos compatibility)."""

    def test_designer_methods_route_to_registry(self):
        d = _FACTORIES["gp_bandit"](7)
        key = d.batch_bucket_key(1)
        program, resolved_key = compute_registry.resolve(
            _FACTORIES["gp_bandit"](7), 1
        )
        assert key == resolved_key
        item = d.batch_prepare(1)
        assert item["sparse"] is False
        outs = type(d).batch_execute([item], pad_to=2)
        result = d.batch_finalize(item, outs[0])
        reference = _FACTORIES["gp_bandit"](7).suggest(1)
        _assert_bit_identical(_params(reference), _params(result))
