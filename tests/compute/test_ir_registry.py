"""Compute-IR registry: resolution order, adapters, and the registered set."""

import numpy as np
import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.compute import ir as compute_ir
from vizier_tpu.compute import registry as compute_registry
from vizier_tpu.designers import gp_bandit as gp_bandit_lib
from vizier_tpu.designers import gp_ucb_pe as gp_ucb_pe_lib
from vizier_tpu.optimizers import lbfgs as lbfgs_lib
from vizier_tpu.surrogates import SurrogateConfig
from vizier_tpu.testing import chaos as chaos_lib

_FAST = dict(
    ard_optimizer=lbfgs_lib.AdamOptimizer(maxiter=10),
    ard_restarts=2,
    max_acquisition_evaluations=200,
    warm_start_min_trials=0,
)


def _problem():
    p = vz.ProblemStatement()
    for d in range(2):
        p.search_space.root.add_float_param(f"x{d}", 0.0, 1.0)
    p.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return p


def _feed(designer, seed, n=5):
    rng = np.random.default_rng(seed)
    trials = []
    for i in range(n):
        t = vz.Trial(
            parameters={"x0": float(rng.uniform()), "x1": float(rng.uniform())},
            id=i + 1,
        )
        t.complete(vz.Measurement(metrics={"obj": float(rng.uniform())}))
        trials.append(t)
    designer.update(core_lib.CompletedTrials(trials))
    return designer


_SPARSE = SurrogateConfig(
    sparse_threshold_trials=1, hysteresis_trials=0, num_inducing=6
)


class TestRegisteredSet:
    def test_builtin_kinds(self):
        assert set(compute_registry.kinds()) >= {
            "gp_bandit",
            "gp_bandit_sparse",
            "gp_ucb_pe",
            "gp_ucb_pe_sparse",
        }

    def test_every_program_satisfies_the_contract(self):
        for program in compute_registry.programs():
            assert program.kind
            assert program.device_phase
            assert program.surrogate_family in ("exact", "sparse")
            assert isinstance(program, compute_ir.DesignerProgram)
            # prewarm coverage: the factory builds a real designer.
            d = program.prewarm_factory(_problem())
            assert hasattr(d, "suggest")

    def test_get_by_kind(self):
        assert compute_registry.get("gp_bandit").kind == "gp_bandit"
        assert compute_registry.get("nope") is None

    def test_programs_for_algorithm(self):
        default = compute_registry.programs_for_algorithm("DEFAULT")
        assert {p.kind for p in default} == {"gp_ucb_pe", "gp_ucb_pe_sparse"}
        gpb = compute_registry.programs_for_algorithm("gaussian_process_bandit")
        assert {p.kind for p in gpb} == {"gp_bandit", "gp_bandit_sparse"}
        assert compute_registry.programs_for_algorithm("RANDOM_SEARCH") == ()


class TestResolution:
    def test_gp_bandit_resolves_exact(self):
        d = _feed(gp_bandit_lib.VizierGPBandit(_problem(), rng_seed=0, **_FAST), 0)
        program, key = compute_registry.resolve(d, 1)
        assert program.kind == key.kind == "gp_bandit"

    def test_gp_bandit_sparse_mode_resolves_sparse_program(self):
        d = _feed(
            gp_bandit_lib.VizierGPBandit(
                _problem(), rng_seed=0, surrogate=_SPARSE, num_seed_trials=1,
                **_FAST,
            ),
            0,
        )
        program, key = compute_registry.resolve(d, 1)
        assert program.kind == key.kind == "gp_bandit_sparse"

    def test_ucb_pe_subclass_resolves_its_own_programs(self):
        # VizierGPUCBPEBandit subclasses VizierGPBandit: MRO resolution must
        # stop at the most-derived registered type.
        d = _feed(
            gp_ucb_pe_lib.VizierGPUCBPEBandit(_problem(), rng_seed=0, **_FAST), 0
        )
        program, key = compute_registry.resolve(d, 1)
        assert program.kind == key.kind == "gp_ucb_pe"

    def test_ucb_pe_sparse_mode_resolves_sparse_program(self):
        d = _feed(
            gp_ucb_pe_lib.VizierGPUCBPEBandit(
                _problem(), rng_seed=0, surrogate=_SPARSE, **_FAST
            ),
            0,
        )
        program, key = compute_registry.resolve(d, 1)
        assert program.kind == key.kind == "gp_ucb_pe_sparse"

    def test_seeding_stage_resolves_none(self):
        d = gp_bandit_lib.VizierGPBandit(_problem(), rng_seed=0, **_FAST)
        assert compute_registry.resolve(d, 1) is None

    def test_duck_typed_designer_gets_adapter(self):
        class Duck:
            def suggest(self, count=1):
                return ["s"] * (count or 1)

            def batch_bucket_key(self, count=1):
                return compute_ir.BucketKey(
                    kind="duck", pad_trials=8, cont_width=1, cat_width=0,
                    metric_count=1, count=count or 1,
                )

            def batch_prepare(self, count=1):
                return dict(designer=self, count=count)

            def batch_execute(self, items, pad_to=None):
                return [dict(v=1) for _ in items]

            def batch_finalize(self, item, output):
                return ["done"] * item["count"]

        duck = Duck()
        program, key = compute_registry.resolve(duck, 2)
        assert isinstance(program, compute_registry.DuckTypedProgram)
        assert key.kind == "duck"
        item = program.prepare(duck, 2)
        out = program.device_program([item])
        assert program.finalize(duck, item, out[0]) == ["done", "done"]

    def test_plain_designer_resolves_none(self):
        class Plain:
            def suggest(self, count=1):
                return []

        assert compute_registry.resolve(Plain(), 1) is None

    def test_chaos_wrapper_resolves_chaos_program(self):
        monkey = chaos_lib.ChaosMonkey(seed=0, failure_prob=0.0)
        inner = _feed(
            gp_bandit_lib.VizierGPBandit(_problem(), rng_seed=0, **_FAST), 0
        )
        wrapped = chaos_lib.ChaosDesigner(inner, monkey)
        program, key = compute_registry.resolve(wrapped, 1)
        assert isinstance(program, chaos_lib.ChaosProgram)
        assert key.kind == "gp_bandit"
        assert program.kind == "gp_bandit"
        assert program.device_phase == "gp_bandit.suggest_batched"

    def test_register_validates_kind(self):
        class NoKind(compute_ir.DesignerProgram):
            def bucket_key(self, designer, count):
                return None

            def prepare(self, designer, count):
                return {}

            def device_program(self, items, pad_to=None):
                return []

            def finalize(self, designer, item, output):
                return []

            def prewarm_factory(self, problem, **kwargs):
                raise NotImplementedError

        with pytest.raises(ValueError):
            compute_registry.register(object, NoKind())
