"""Tests for the pythia protocol, supporters, and designer-policy wrappers."""

import pytest

from vizier_tpu import algorithms as alg
from vizier_tpu import pythia
from vizier_tpu import pyvizier as vz
from vizier_tpu.designers import QuasiRandomDesigner, RandomDesigner
from vizier_tpu.testing import test_studies


def _study_config(algorithm="RANDOM_SEARCH"):
    return vz.StudyConfig(
        search_space=test_studies.flat_space_with_all_types(),
        metric_information=test_studies.metrics_objective_maximize(),
        algorithm=algorithm,
    )


def _complete(trials, value=1.0):
    for t in trials:
        t.complete(vz.Measurement(metrics={"objective": value}))


class TestInRamPolicySupporter:
    def test_suggest_assigns_ids(self):
        supporter = pythia.InRamPolicySupporter(_study_config())
        policy = alg.RandomPolicy(supporter, seed=1)
        trials = supporter.SuggestTrials(policy, 5)
        assert [t.id for t in trials] == [1, 2, 3, 4, 5]
        assert supporter.study_descriptor().max_trial_id == 5

    def test_get_trials_filters(self):
        supporter = pythia.InRamPolicySupporter(_study_config())
        policy = alg.RandomPolicy(supporter, seed=1)
        trials = supporter.SuggestTrials(policy, 4)
        _complete(trials[:2])
        completed = supporter.GetTrials(status_matches=vz.TrialStatus.COMPLETED)
        active = supporter.GetTrials(status_matches=vz.TrialStatus.ACTIVE)
        assert [t.id for t in completed] == [1, 2]
        assert [t.id for t in active] == [3, 4]
        assert [t.id for t in supporter.GetTrials(min_trial_id=3)] == [3, 4]

    def test_early_stop(self):
        supporter = pythia.InRamPolicySupporter(_study_config())
        policy = alg.RandomPolicy(supporter, seed=1)
        trials = supporter.SuggestTrials(policy, 3)
        decisions = supporter.EarlyStopTrials(policy, [t.id for t in trials])
        stopped = [d.id for d in decisions.decisions if d.should_stop]
        assert len(stopped) == 1
        (stopped_trial,) = [t for t in supporter.trials if t.id == stopped[0]]
        assert stopped_trial.status == vz.TrialStatus.STOPPING

    def test_prior_study(self):
        main = pythia.InRamPolicySupporter(_study_config())
        prior = pythia.InRamPolicySupporter(_study_config(), study_guid="prior")
        prior.AddTrials([vz.Trial(parameters={"lineardouble": 0.5})])
        main.SetPriorStudy(prior)
        assert len(main.GetTrials(study_guid="prior")) == 1


class TestDesignerPolicy:
    def test_stateless_replay(self):
        supporter = pythia.InRamPolicySupporter(_study_config())
        policy = alg.DesignerPolicy(
            supporter, lambda p, **kw: RandomDesigner(p.search_space, seed=0)
        )
        trials = supporter.SuggestTrials(policy, 3)
        assert len(trials) == 3
        _complete(trials)
        more = supporter.SuggestTrials(policy, 2)
        assert len(more) == 2

    def test_seeding_uses_default(self):
        config = _study_config()
        config.search_space.get("lineardouble")  # exists
        supporter = pythia.InRamPolicySupporter(config)
        policy = alg.DesignerPolicy(
            supporter,
            lambda p, **kw: RandomDesigner(p.search_space, seed=0),
            use_seeding=True,
        )
        (first, second) = supporter.SuggestTrials(policy, 2)
        # Seed suggestion: center of lineardouble [-1, 2] is 0.5.
        assert first.parameters.get_value("lineardouble") == pytest.approx(0.5)

    def test_partially_serializable_policy_checkpoints(self):
        config = vz.StudyConfig(
            search_space=test_studies.flat_continuous_space_with_scaling(),
            metric_information=test_studies.metrics_objective_maximize(),
        )
        supporter = pythia.InRamPolicySupporter(config)
        factory = lambda p, **kw: QuasiRandomDesigner(p.search_space, seed=9)
        policy = alg.PartiallySerializableDesignerPolicy(supporter, factory)
        first = supporter.SuggestTrials(policy, 3)
        # State was persisted into study metadata.
        ns = config.metadata.abs_ns(vz.Namespace(("designer_policy_v0",)))
        assert "designer" in ns and "incorporated_trial_ids" in ns
        # A brand-new policy object resumes the Halton stream rather than
        # restarting: its next suggestions differ from the first three.
        policy2 = alg.PartiallySerializableDesignerPolicy(supporter, factory)
        second = supporter.SuggestTrials(policy2, 3)
        firsts = [t.parameters.as_dict() for t in first]
        seconds = [t.parameters.as_dict() for t in second]
        assert firsts != seconds
        # And a fresh-from-scratch designer would have repeated `firsts`.
        fresh = QuasiRandomDesigner(config.search_space, seed=9).suggest(3)
        assert [s.parameters.as_dict() for s in fresh] == firsts

    def test_corrupt_state_falls_back_to_replay(self):
        config = vz.StudyConfig(
            search_space=test_studies.flat_continuous_space_with_scaling(),
            metric_information=test_studies.metrics_objective_maximize(),
        )
        supporter = pythia.InRamPolicySupporter(config)
        factory = lambda p, **kw: QuasiRandomDesigner(p.search_space, seed=9)
        policy = alg.PartiallySerializableDesignerPolicy(supporter, factory)
        supporter.SuggestTrials(policy, 2)
        config.metadata.abs_ns(vz.Namespace(("designer_policy_v0",)))["designer"] = "%%corrupt%%"
        # Must not raise; falls back to a fresh designer.
        trials = supporter.SuggestTrials(
            alg.PartiallySerializableDesignerPolicy(supporter, factory), 2
        )
        assert len(trials) == 2


class TestSuggestRequestValidation:
    def test_count_positive(self):
        desc = vz.StudyDescriptor(config=_study_config())
        with pytest.raises(ValueError):
            pythia.SuggestRequest(study_descriptor=desc, count=0)


class TestEarlyStopEmptyIds:
    def test_empty_ids_considers_all_active(self):
        supporter = pythia.InRamPolicySupporter(_study_config())
        policy = alg.RandomPolicy(supporter, seed=1)
        supporter.SuggestTrials(policy, 3)
        decisions = supporter.EarlyStopTrials(policy)  # no ids given
        assert len(decisions.decisions) == 3
        assert sum(d.should_stop for d in decisions.decisions) == 1


class TestReviewRegressions2:
    """Regressions from the fourth code review."""

    def test_add_trials_copies(self):
        main = pythia.InRamPolicySupporter(_study_config())
        prior = pythia.InRamPolicySupporter(_study_config(), study_guid="prior")
        prior.AddTrials([vz.Trial(parameters={"lineardouble": 0.5})])
        original_ids = [t.id for t in prior.trials]
        main.AddTrials([vz.Trial(parameters={"lineardouble": 0.1})])
        main.AddTrials(prior.trials)
        assert [t.id for t in prior.trials] == original_ids

    def test_serializable_designer_without_load_falls_back(self):
        from vizier_tpu.algorithms import core as core_lib
        from vizier_tpu.pyvizier import common
        from vizier_tpu.utils import serializable as ser

        class RecoverOnly(core_lib.SerializableDesigner):
            def __init__(self, space):
                self._space = space

            @classmethod
            def recover(cls, metadata):
                raise ser.DecodeError("always fails")

            def dump(self):
                md = common.Metadata()
                md["k"] = "v"
                return md

            def update(self, completed, all_active=core_lib.ActiveTrials()):
                pass

            def suggest(self, count=None):
                from vizier_tpu.designers import random as rd
                import numpy as np

                rng = np.random.default_rng(0)
                return [
                    vz.TrialSuggestion(parameters=rd.sample_point(self._space, rng))
                    for _ in range(count or 1)
                ]

        supporter = pythia.InRamPolicySupporter(_study_config())
        factory = lambda p, **kw: RecoverOnly(p.search_space)
        policy1 = alg.SerializableDesignerPolicy(supporter, factory)
        assert len(supporter.SuggestTrials(policy1, 2)) == 2
        # Second policy: stored state exists, recover raises -> replay fallback.
        policy2 = alg.SerializableDesignerPolicy(supporter, factory)
        assert len(supporter.SuggestTrials(policy2, 2)) == 2
