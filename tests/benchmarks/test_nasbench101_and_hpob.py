"""NASBench-101 graph encoding + real HPO-B v3 layout (VERDICT r3 #3/#4).

Both are data-gated in production; these tests drive the encoding/parsing
logic on synthetic fixtures: the NASBench-101 trial→spec→prune→hash path
and the HPO-B split semantics / discrete evaluation protocol.
"""

import json

import numpy as np
import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.benchmarks.experimenters import nasbench101 as nb
from vizier_tpu.benchmarks.experimenters.surrogates import HPOBHandler


def _spec_to_params(spec: nb.ModelSpec) -> dict:
    params = {}
    for y in range(nb.NUM_VERTICES):
        for x in range(nb.NUM_VERTICES):
            if y > x:
                params[f"{x}_{y}"] = str(bool(spec.matrix[x, y]))
    for i in range(nb.OP_SPOTS):
        params[f"ops_{i}"] = spec.ops[i + 1]
    return params


class TestModelSpec:
    def test_rejects_non_dag(self):
        m = np.zeros((3, 3), dtype=int)
        m[2, 0] = 1  # lower-triangular edge
        with pytest.raises(ValueError, match="upper-triangular"):
            nb.ModelSpec(matrix=m, ops=[nb.INPUT_OP, "conv3x3-bn-relu", nb.OUTPUT_OP])

    def test_prune_removes_dangling_vertices(self):
        # 0 -> 1 -> 3 with vertex 2 dangling (no path to output).
        m = np.zeros((4, 4), dtype=int)
        m[0, 1] = m[1, 3] = 1
        m[0, 2] = 1  # 2 reaches nothing
        spec = nb.ModelSpec(
            matrix=m,
            ops=[nb.INPUT_OP, "conv3x3-bn-relu", "maxpool3x3", nb.OUTPUT_OP],
        )
        pruned = spec.pruned()
        assert pruned.matrix.shape == (3, 3)
        assert pruned.ops == [nb.INPUT_OP, "conv3x3-bn-relu", nb.OUTPUT_OP]

    def test_disconnected_graph_prunes_to_none(self):
        m = np.zeros((3, 3), dtype=int)  # no edges at all
        spec = nb.ModelSpec(
            matrix=m, ops=[nb.INPUT_OP, "conv3x3-bn-relu", nb.OUTPUT_OP]
        )
        assert spec.pruned() is None
        assert spec.graph_hash() == "invalid"

    def test_hash_invariant_under_vertex_relabeling(self):
        """Two labelings of the same computation graph hash identically."""
        # Graph A: 0->1->3, 0->2->3 with ops conv3x3 at 1, maxpool at 2.
        m1 = np.zeros((4, 4), dtype=int)
        m1[0, 1] = m1[1, 3] = m1[0, 2] = m1[2, 3] = 1
        s1 = nb.ModelSpec(
            matrix=m1,
            ops=[nb.INPUT_OP, "conv3x3-bn-relu", "maxpool3x3", nb.OUTPUT_OP],
        )
        # Graph B: identical but with the two interior vertices swapped.
        m2 = np.zeros((4, 4), dtype=int)
        m2[0, 1] = m2[1, 3] = m2[0, 2] = m2[2, 3] = 1
        s2 = nb.ModelSpec(
            matrix=m2,
            ops=[nb.INPUT_OP, "maxpool3x3", "conv3x3-bn-relu", nb.OUTPUT_OP],
        )
        assert s1.graph_hash() == s2.graph_hash()

    def test_hash_distinguishes_ops(self):
        m = np.zeros((3, 3), dtype=int)
        m[0, 1] = m[1, 2] = 1
        a = nb.ModelSpec(matrix=m, ops=[nb.INPUT_OP, "conv3x3-bn-relu", nb.OUTPUT_OP])
        b = nb.ModelSpec(matrix=m, ops=[nb.INPUT_OP, "maxpool3x3", nb.OUTPUT_OP])
        assert a.graph_hash() != b.graph_hash()

    def test_hash_ignores_pruned_vertices(self):
        """A dangling vertex must not change the hash (it prunes away)."""
        m1 = np.zeros((3, 3), dtype=int)
        m1[0, 1] = m1[1, 2] = 1
        core = nb.ModelSpec(
            matrix=m1, ops=[nb.INPUT_OP, "conv3x3-bn-relu", nb.OUTPUT_OP]
        )
        m2 = np.zeros((4, 4), dtype=int)
        m2[0, 1] = m2[1, 3] = 1
        m2[0, 2] = 1  # dangling
        padded = nb.ModelSpec(
            matrix=m2,
            ops=[nb.INPUT_OP, "conv3x3-bn-relu", "maxpool3x3", nb.OUTPUT_OP],
        )
        assert core.graph_hash() == padded.graph_hash()


class TestNASBench101Experimenter:
    def test_problem_statement_shape(self):
        api, _ = nb.synthetic_nasbench101(num_cells=4)
        problem = nb.NASBench101Experimenter(api).problem_statement()
        # 21 bools + 5 op spots.
        assert problem.search_space.num_parameters() == 26
        assert problem.metric_information.item().name == "validation_accuracy"

    def test_valid_cell_completes_with_all_metrics(self):
        api, specs = nb.synthetic_nasbench101(num_cells=8)
        exp = nb.NASBench101Experimenter(api)
        t = vz.Trial(id=1, parameters=_spec_to_params(specs[0]))
        exp.evaluate([t])
        assert not t.infeasible
        for name in nb.METRIC_NAMES:
            assert name in t.final_measurement.metrics

    def test_invalid_cell_is_infeasible(self):
        api, _ = nb.synthetic_nasbench101(num_cells=4)
        exp = nb.NASBench101Experimenter(api)
        empty = nb.ModelSpec(
            matrix=np.zeros((nb.NUM_VERTICES, nb.NUM_VERTICES), dtype=int),
            ops=[nb.INPUT_OP]
            + ["conv3x3-bn-relu"] * nb.OP_SPOTS
            + [nb.OUTPUT_OP],
        )
        t = vz.Trial(id=1, parameters=_spec_to_params(empty))
        exp.evaluate([t])
        assert t.infeasible

    def test_edge_budget_enforced(self):
        api, _ = nb.synthetic_nasbench101(num_cells=4)
        dense = nb.ModelSpec(
            matrix=np.triu(np.ones((nb.NUM_VERTICES, nb.NUM_VERTICES), int), 1),
            ops=[nb.INPUT_OP]
            + ["conv3x3-bn-relu"] * nb.OP_SPOTS
            + [nb.OUTPUT_OP],
        )
        assert dense.matrix.sum() > nb.MAX_EDGES
        assert not api.is_valid(dense)

    def test_designer_runs_on_nasbench_space(self):
        """The conditional-free mixed bool/categorical space drives a real
        suggest→evaluate loop (random designer: the space is all-discrete)."""
        from vizier_tpu.algorithms import core as core_lib
        from vizier_tpu.designers import RandomDesigner

        api, _ = nb.synthetic_nasbench101(num_cells=16)
        exp = nb.NASBench101Experimenter(api)
        problem = exp.problem_statement()
        designer = RandomDesigner(problem.search_space, seed=1)
        feasible = 0
        for i in range(10):
            trials = [s.to_trial(i + 1) for s in designer.suggest(1)]
            exp.evaluate(trials)
            feasible += sum(not t.infeasible for t in trials)
            designer.update(core_lib.CompletedTrials(trials))
        # Random 35%-density DAGs rarely match the tiny synthetic table;
        # what matters is every trial completes one way or the other.
        assert feasible >= 0


@pytest.fixture
def hpob_root(tmp_path):
    """A miniature but layout-faithful HPO-B dump."""
    xs = [[0.1, 0.2], [0.4, 0.5], [0.9, 0.1], [0.3, 0.8], [0.6, 0.6], [0.2, 0.9]]
    ys = [[1.0], [3.0], [2.0], [5.0], [4.0], [0.5]]
    test = {"5860": {"145833": {"X": xs, "y": ys}}}
    train = {"5860": {"300": {"X": xs[:3], "y": ys[:3]}}}
    train_aug = {"5860": {"300aug": {"X": xs[:4], "y": ys[:4]}}}
    valid = {"5860": {"400": {"X": xs[1:4], "y": ys[1:4]}}}
    inits = {"5860": {"145833": {s: [0, 1, 2, 3, 4] for s in HPOBHandler.SEEDS}}}
    (tmp_path / "meta-test-dataset.json").write_text(json.dumps(test))
    (tmp_path / "meta-train-dataset.json").write_text(json.dumps(train))
    (tmp_path / "meta-train-dataset-augmented.json").write_text(
        json.dumps(train_aug)
    )
    (tmp_path / "meta-validation-dataset.json").write_text(json.dumps(valid))
    (tmp_path / "bo-initializations.json").write_text(json.dumps(inits))
    return str(tmp_path)


class TestHPOBHandler:
    def test_v3_test_loads_only_test_split(self, hpob_root):
        h = HPOBHandler(root_dir=hpob_root, mode="v3-test")
        h._ensure_loaded()
        assert "145833" in h.meta_test_data["5860"]
        assert h.meta_train_data == {}

    def test_v3_loads_all_splits(self, hpob_root):
        h = HPOBHandler(root_dir=hpob_root, mode="v3")
        h._ensure_loaded()
        assert "300" in h.meta_train_data["5860"]
        assert "400" in h.meta_validation_data["5860"]
        assert "145833" in h.meta_test_data["5860"]

    def test_v3_train_augmented_uses_augmented_file(self, hpob_root):
        h = HPOBHandler(root_dir=hpob_root, mode="v3-train-augmented")
        h._ensure_loaded()
        assert "300aug" in h.meta_train_data["5860"]

    def test_v1_merges_splits_into_test(self, hpob_root):
        h = HPOBHandler(root_dir=hpob_root, mode="v1")
        h._ensure_loaded()
        # v1: augmented train merged with test+validation per search space.
        merged = h.meta_test_data["5860"]
        assert {"300aug", "145833", "400"} <= set(merged)

    def test_evaluate_discrete_protocol(self, hpob_root):
        h = HPOBHandler(root_dir=hpob_root, mode="v3-test")

        class GreedyNearBest:
            def observe_and_suggest(self, x_obs, y_obs, x_pen):
                # Always pick the first pending candidate.
                assert x_obs.shape[1] == x_pen.shape[1] == 2
                return 0

        history = h.evaluate(
            GreedyNearBest(),
            search_space_id="5860",
            dataset_id="145833",
            seed="test0",
            n_trials=1,
        )
        # Initial 5 points include the y-max (5.0 -> normalized 1.0).
        assert len(history) == 2
        assert history[0] == pytest.approx(1.0)
        assert history[-1] >= history[0]

    def test_evaluate_requires_protocol_method(self, hpob_root):
        h = HPOBHandler(root_dir=hpob_root)
        with pytest.raises(ValueError, match="observe_and_suggest"):
            h.evaluate(object(), "5860", "145833", "test0")

    def test_seeds_match_published_names(self):
        assert HPOBHandler().get_seeds() == [
            "test0", "test1", "test2", "test3", "test4",
        ]

    def test_make_experimenter_serves_table(self, hpob_root):
        h = HPOBHandler(root_dir=hpob_root, mode="v3-test")
        exp = h.make_experimenter("5860", "145833")
        t = vz.Trial(id=1, parameters={"x0": 0.3, "x1": 0.8})
        exp.evaluate([t])
        assert t.final_measurement.metrics["objective"].value == 5.0

    def test_missing_data_raises(self):
        with pytest.raises(FileNotFoundError):
            HPOBHandler(root_dir=None).make_experimenter("ss", "ds")

    def test_continuous_protocol_rejects_invalid_method(self, hpob_root):
        h = HPOBHandler(root_dir=hpob_root)
        with pytest.raises(ValueError, match="observe_and_suggest"):
            h.evaluate_continuous(
                object(), "5860", "145833", "test0", n_trials=1
            )


class TestPredictorExperimenter:
    """Reference surrogate_experimenter.py parity: a fitted GP serves as
    the objective for benchmarking other algorithms."""

    def test_gp_predictor_serves_objective(self):
        from vizier_tpu.algorithms import core as core_lib
        from vizier_tpu.benchmarks.experimenters.surrogates import (
            PredictorExperimenter,
        )
        from vizier_tpu.designers.gp_bandit import VizierGPBandit
        from vizier_tpu.optimizers.lbfgs import AdamOptimizer

        problem = vz.ProblemStatement()
        problem.search_space.root.add_float_param("x", 0.0, 1.0)
        problem.metric_information.append(
            vz.MetricInformation(
                name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE
            )
        )
        gp = VizierGPBandit(
            problem, ard_restarts=2, ard_optimizer=AdamOptimizer(maxiter=20)
        )
        trials = []
        for i, x in enumerate(np.linspace(0.0, 1.0, 8)):
            t = vz.Trial(id=i + 1, parameters={"x": float(x)})
            t.complete(
                vz.Measurement(metrics={"obj": float(-(x - 0.7) ** 2)})
            )
            trials.append(t)
        gp.update(core_lib.CompletedTrials(trials))

        exp = PredictorExperimenter(gp, problem, seed=1)
        probe = [
            vz.Trial(id=100, parameters={"x": 0.7}),
            vz.Trial(id=101, parameters={"x": 0.05}),
        ]
        exp.evaluate(probe)
        near = probe[0].final_measurement.metrics["obj"].value
        far = probe[1].final_measurement.metrics["obj"].value
        # Surrogate preserves the objective's shape: 0.7 beats 0.05.
        assert near > far
        assert exp.problem_statement().search_space.num_parameters() == 1

    def test_rejects_multi_objective(self):
        from vizier_tpu.benchmarks.experimenters.surrogates import (
            PredictorExperimenter,
        )

        problem = vz.ProblemStatement()
        problem.search_space.root.add_float_param("x", 0.0, 1.0)
        for name in ("a", "b"):
            problem.metric_information.append(
                vz.MetricInformation(
                    name=name, goal=vz.ObjectiveMetricGoal.MAXIMIZE
                )
            )
        with pytest.raises(ValueError, match="single-objective"):
            PredictorExperimenter(object(), problem)


@pytest.fixture
def hpob_surrogates_dir(tmp_path):
    """summary-stats.json matching the hpob_root fixture's (5860, 145833)."""
    d = tmp_path / "saved-surrogates"
    d.mkdir()
    stats = {"surrogate-5860-145833": {"y_min": 0.0, "y_max": 10.0}}
    (d / "summary-stats.json").write_text(json.dumps(stats))
    return str(d)


class TestHPOBContinuous:
    def _handler(self, hpob_root, surrogates_dir):
        return HPOBHandler(
            root_dir=hpob_root, mode="v3-test", surrogates_dir=surrogates_dir
        )

    def test_protocol_executes_with_fake_predictor(
        self, hpob_root, hpob_surrogates_dir
    ):
        h = self._handler(hpob_root, hpob_surrogates_dir)

        class MidpointMethod:
            """Suggests the mean of the observed points."""

            def observe_and_suggest(self, x_obs, y_obs):
                assert x_obs.shape[1] == 2
                assert y_obs.min() >= 0.0 and y_obs.max() <= 1.0
                return np.mean(x_obs, axis=0)

        # Fake surrogate: higher near the origin.
        predictor = lambda x: 10.0 - np.sum(x**2, axis=-1)
        trace = h.evaluate_continuous(
            MidpointMethod(),
            search_space_id="5860",
            dataset_id="145833",
            seed="test0",
            n_trials=4,
            predictor=predictor,
        )
        assert len(trace) == 5  # n_trials pre-suggest entries + final
        assert all(0.0 <= v <= 1.0 for v in trace)
        assert trace == sorted(trace)  # incumbent trace is monotone

    def test_final_entry_includes_last_suggestion(
        self, hpob_root, hpob_surrogates_dir
    ):
        h = self._handler(hpob_root, hpob_surrogates_dir)

        class Fixed:
            def observe_and_suggest(self, x_obs, y_obs):
                return np.array([0.5, 0.5])

        # Surrogate always returns the best possible value: the final trace
        # entry must reflect it even though no further suggest happens.
        trace = h.evaluate_continuous(
            Fixed(),
            search_space_id="5860",
            dataset_id="145833",
            seed="test1",
            n_trials=1,
            predictor=lambda x: np.full(x.shape[0], 10.0),
        )
        assert trace[-1] == pytest.approx(1.0)
        assert trace[0] < 1.0

    def test_normalization_uses_published_stats(
        self, hpob_root, hpob_surrogates_dir
    ):
        h = self._handler(hpob_root, hpob_surrogates_dir)

        seen = {}

        class Recorder:
            def observe_and_suggest(self, x_obs, y_obs):
                seen["y"] = np.array(y_obs)
                return np.array([0.1, 0.1])

        h.evaluate_continuous(
            Recorder(),
            search_space_id="5860",
            dataset_id="145833",
            seed="test0",
            n_trials=1,
            predictor=lambda x: np.zeros(x.shape[0]),
        )
        # init ids 0..4 -> ys [1, 3, 2, 5, 4] normalized by (0, 10).
        np.testing.assert_allclose(seen["y"], [0.1, 0.3, 0.2, 0.5, 0.4])

    def test_missing_stats_key_raises(self, hpob_root, tmp_path):
        d = tmp_path / "other-surrogates"
        d.mkdir()
        (d / "summary-stats.json").write_text(json.dumps({}))
        h = self._handler(hpob_root, str(d))

        class Fixed:
            def observe_and_suggest(self, x_obs, y_obs):
                return np.array([0.5, 0.5])

        with pytest.raises(KeyError, match="summary-stats"):
            h.evaluate_continuous(
                Fixed(),
                search_space_id="5860",
                dataset_id="145833",
                seed="test0",
                predictor=lambda x: np.zeros(x.shape[0]),
            )

    def test_xgboost_gate_is_narrow(self, hpob_root, hpob_surrogates_dir):
        # Without a predictor, only the surrogate-serving step should fail
        # (xgboost is absent from this image) — after the protocol wiring
        # validated its inputs.
        h = self._handler(hpob_root, hpob_surrogates_dir)

        class Fixed:
            def observe_and_suggest(self, x_obs, y_obs):
                return np.array([0.5, 0.5])

        with pytest.raises(ImportError, match="xgboost"):
            h.evaluate_continuous(
                Fixed(),
                search_space_id="5860",
                dataset_id="145833",
                seed="test0",
            )

    def test_normalize_zero_span_guard(self):
        out = HPOBHandler.normalize([2.0, 2.0, 2.0])
        np.testing.assert_array_equal(out, [0.0, 0.0, 0.0])
        assert np.isfinite(HPOBHandler.normalize([3.0], 1.0, 1.0)).all()

    def test_no_surrogates_dir_raises(self, hpob_root):
        h = HPOBHandler(root_dir=hpob_root, mode="v3-test")
        with pytest.raises(ValueError, match="surrogates_dir"):
            h.surrogates_stats()
