"""Tests for MultiMetricCurveConverter, RestartingCurveConverter, and
build_convergence_curve (reference convergence_curve.py:464,516,1108)."""

import numpy as np
import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.benchmarks.analyzers import convergence_curve as cc


def _trial(i, metrics):
    t = vz.Trial(id=i, parameters={"x": 0.5})
    t.complete(vz.Measurement(metrics=metrics))
    return t


class TestMultiMetricCurveConverter:
    def test_single_objective_routes_to_convergence(self):
        config = vz.MetricsConfig(
            [vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)]
        )
        conv = cc.MultiMetricCurveConverter.from_metrics_config(config)
        assert isinstance(conv.converter, cc.ConvergenceCurveConverter)
        curve = conv.convert([_trial(i + 1, {"obj": float(v)}) for i, v in enumerate([1, 3, 2])])
        np.testing.assert_allclose(curve.ys[0], [1, 3, 3])

    def test_multi_objective_routes_to_hypervolume(self):
        config = vz.MetricsConfig(
            [
                vz.MetricInformation(name="f1", goal=vz.ObjectiveMetricGoal.MAXIMIZE),
                vz.MetricInformation(name="f2", goal=vz.ObjectiveMetricGoal.MAXIMIZE),
            ]
        )
        conv = cc.MultiMetricCurveConverter.from_metrics_config(
            config, reference_point=np.zeros(2)
        )
        assert isinstance(conv.converter, cc.HypervolumeCurveConverter)
        trials = [
            _trial(1, {"f1": 1.0, "f2": 0.2}),
            _trial(2, {"f1": 0.2, "f2": 1.0}),
        ]
        curve = conv.convert(trials)
        assert curve.ys.shape == (1, 2)
        assert curve.ys[0, 1] >= curve.ys[0, 0] - 1e-9

    def test_unsafe_trials_are_warped_out(self):
        config = vz.MetricsConfig(
            [
                vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE),
                vz.MetricInformation(
                    name="safe",
                    goal=vz.ObjectiveMetricGoal.MAXIMIZE,
                    safety_threshold=0.5,
                ),
            ]
        )
        conv = cc.MultiMetricCurveConverter.from_metrics_config(config)
        trials = [
            _trial(1, {"obj": 1.0, "safe": 0.9}),
            _trial(2, {"obj": 100.0, "safe": 0.1}),  # unsafe: must not count
        ]
        curve = conv.convert(trials)
        np.testing.assert_allclose(curve.ys[0], [1.0, 1.0])
        # The caller's trials are untouched (conversion deep-copies).
        assert trials[1].infeasibility_reason is None

    def test_empty_trials_raise(self):
        config = vz.MetricsConfig(
            [vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)]
        )
        conv = cc.MultiMetricCurveConverter.from_metrics_config(config)
        with pytest.raises(ValueError):
            conv.convert([])


class TestRestartingCurveConverter:
    class _CountingFactory:
        def __init__(self):
            self.builds = 0
            self.metric = vz.MetricInformation(
                name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE
            )

        def __call__(self):
            self.builds += 1
            return cc.ConvergenceCurveConverter(self.metric)

    def test_restarts_at_rate_crossings(self):
        factory = self._CountingFactory()
        conv = cc.RestartingCurveConverter(
            factory, restart_min_trials=0, restart_rate=2.0
        )
        next_id = 1
        for batch in range(6):
            trials = [_trial(next_id + j, {"obj": float(next_id + j)}) for j in range(3)]
            next_id += 3
            curve = conv.convert(trials)
            assert curve.ys.shape[1] == 3  # tail slice covers only the batch
            # Best-so-far of the latest batch is always its own max.
            assert curve.ys[0, -1] == float(next_id - 1)
        # 18 trials at rate 2 -> restarts after crossing 4,8,16 -> >1 build.
        assert factory.builds >= 3

    def test_replay_preserves_best_so_far(self):
        factory = self._CountingFactory()
        conv = cc.RestartingCurveConverter(
            factory, restart_min_trials=0, restart_rate=2.0
        )
        conv.convert([_trial(1, {"obj": 10.0})])
        conv.convert([_trial(2, {"obj": 1.0})])
        # The full history feeds every call: best-so-far keeps 10 across
        # batches and converter rebuilds.
        curve = conv.convert([_trial(3, {"obj": 2.0})])
        assert curve.ys[0, -1] == 10.0

    def test_validation(self):
        with pytest.raises(ValueError):
            cc.RestartingCurveConverter(lambda: None, restart_min_trials=-1)
        with pytest.raises(ValueError):
            cc.RestartingCurveConverter(lambda: None, restart_rate=0.5)
        with pytest.raises(ValueError):
            cc.RestartingCurveConverter(lambda: None, restart_rate=1.0)


class TestBuildConvergenceCurve:
    def test_first_reaching_indices(self):
        out = cc.build_convergence_curve([1.0, 2.0, 3.0], [0.5, 1.5, 2.5])
        assert out == [1.0, 2.0, float("inf")]

    def test_identical_curves_are_diagonal(self):
        curve = [1.0, 2.0, 3.0]
        assert cc.build_convergence_curve(curve, curve) == [0.0, 1.0, 2.0]
