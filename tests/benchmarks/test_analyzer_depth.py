"""Analyzer depth tests: alignment/extrapolation, new comparators, and the
BenchmarkRecord comparison machinery (the regret-parity instrument)."""

import numpy as np
import pytest

from vizier_tpu.benchmarks.analyzers import convergence_curve as cc
from vizier_tpu.benchmarks.analyzers import state_analyzer as sa


def _curve(ys, trend=None):
    ys = np.atleast_2d(np.asarray(ys, dtype=np.float64))
    return cc.ConvergenceCurve(
        xs=np.arange(1, ys.shape[1] + 1),
        ys=ys,
        trend=trend or cc.ConvergenceCurve.YTrend.INCREASING,
    )


class TestAlignment:
    def test_align_combines_batches(self):
        c1 = _curve([[0, 1, 2]])
        c2 = _curve([[0, 2, 4, 6]])
        combined = cc.ConvergenceCurve.align_xs([c1, c2])
        assert combined.ys.shape == (2, 4)
        # Shorter curve extends by interpolation clamp at its final value.
        assert combined.ys[0, -1] == 2

    def test_align_keep_separate(self):
        c1 = _curve([[0, 1, 2]])
        c2 = _curve([[0, 2, 4, 6]])
        aligned = cc.ConvergenceCurve.align_xs([c1, c2], keep_curves_separate=True)
        assert len(aligned) == 2
        assert all(len(a.xs) == 4 for a in aligned)
        assert aligned[0].ys.shape == (1, 4)

    def test_align_rejects_mixed_trends(self):
        c1 = _curve([[0, 1]])
        c2 = _curve([[1, 0]], trend=cc.ConvergenceCurve.YTrend.DECREASING)
        with pytest.raises(ValueError, match="trend"):
            cc.ConvergenceCurve.align_xs([c1, c2])

    def test_interpolate_at(self):
        c = _curve([[0, 2, 4]])
        out = c.interpolate_at(np.array([1.5, 2.5]))
        np.testing.assert_allclose(out.ys[0], [1.0, 3.0])

    def test_extrapolate_holds_incumbent(self):
        c = _curve([[0, 3, 5]])
        out = c.extrapolate_ys(2)
        assert len(out.xs) == 5
        np.testing.assert_allclose(out.ys[0, -2:], [5, 5])


class TestOptimalityGap:
    def test_closer_to_optimum_scores_positive(self):
        base = _curve([[0, 1, 2]])
        better = _curve([[0, 2, 3.9]])
        comp = cc.OptimalityGapComparator(baseline_curve=base, optimum=4.0)
        assert comp.score(better) > 0
        assert comp.score(base) == pytest.approx(0.0)

    def test_decreasing_trend(self):
        base = _curve([[10, 5, 2]], trend=cc.ConvergenceCurve.YTrend.DECREASING)
        better = _curve([[10, 3, 0.5]], trend=cc.ConvergenceCurve.YTrend.DECREASING)
        comp = cc.OptimalityGapComparator(baseline_curve=base, optimum=0.0)
        assert comp.score(better) > 0


class TestBenchmarkRecords:
    def _records(self):
        meta = {"name": "sphere", "dim": "4"}
        base = sa.BenchmarkRecord(
            algorithm="random",
            experimenter_metadata=meta,
            plot_elements={"objective": sa.PlotElement(_curve([[0, 1, 2, 3]]))},
        )
        good = sa.BenchmarkRecord(
            algorithm="gp",
            experimenter_metadata=meta,
            plot_elements={"objective": sa.PlotElement(_curve([[0, 3, 3.5]]))},
        )
        return [base, good]

    def test_add_comparison_metrics(self):
        records = sa.BenchmarkRecordAnalyzer.add_comparison_metrics(
            self._records(), baseline_algo="random"
        )
        gp = next(r for r in records if r.algorithm == "gp")
        assert gp.scores["log_efficiency_vs_random"] > 0
        assert 0.0 <= gp.scores["win_rate_vs_random"] <= 1.0
        assert gp.scores["pct_better_vs_random"] > 0.5

    def test_mismatched_lengths_are_extrapolated(self):
        records = sa.BenchmarkRecordAnalyzer.add_comparison_metrics(
            self._records(), baseline_algo="random"
        )
        # Did not raise despite 4-vs-3 lengths; scores exist for both.
        assert all("win_rate_vs_random" in r.scores for r in records)

    def test_summarize_rows(self):
        records = sa.BenchmarkRecordAnalyzer.add_comparison_metrics(
            self._records(), baseline_algo="random"
        )
        rows = sa.BenchmarkRecordAnalyzer.summarize(records)
        assert len(rows) == 2
        assert {"algorithm", "experimenter", "objective_final_median"} <= set(
            rows[0]
        )

    def test_summarize_dataframe(self):
        df = sa.BenchmarkRecordAnalyzer.summarize_dataframe(self._records())
        assert len(df) == 2
        assert "objective_final_median" in df.columns
