"""Tests for experimenters, the benchmark runner, and convergence analyzers."""

import numpy as np
import pytest

from vizier_tpu import benchmarks
from vizier_tpu import pyvizier as vz
from vizier_tpu.benchmarks.experimenters.synthetic import bbob
from vizier_tpu.benchmarks.experimenters.synthetic import multiobjective
from vizier_tpu.benchmarks.experimenters.synthetic import simplekd
from vizier_tpu.benchmarks.experimenters import wrappers
from vizier_tpu.designers import GridSearchDesigner, RandomDesigner


class TestBBOB:
    @pytest.mark.parametrize("name,fn", sorted(bbob.BBOB_FUNCTIONS.items()))
    def test_optimum_value_is_zero(self, name, fn):
        for dim in (2, 5):
            if name == "LinearSlope":
                # Linear function: the optimum sits at the +5 corner.
                opt = np.full((1, dim), 5.0)
            else:
                opt = np.zeros((1, dim))
            val = fn(opt)[0]
            assert np.isfinite(val), name
            assert val == pytest.approx(0.0, abs=1e-6), f"{name}: f(opt)={val}"

    @pytest.mark.parametrize("name,fn", sorted(bbob.BBOB_FUNCTIONS.items()))
    def test_batch_and_positive(self, name, fn):
        rng = np.random.default_rng(0)
        x = rng.uniform(-5, 5, size=(16, 4))
        vals = fn(x)
        assert vals.shape == (16,)
        assert np.all(np.isfinite(vals)), name
        assert np.all(vals >= -1e-9), f"{name} has negative values"

    def test_sphere_exact(self):
        np.testing.assert_allclose(
            bbob.Sphere(np.array([[1.0, 2.0], [0.0, 3.0]])), [5.0, 9.0]
        )


class TestNumpyExperimenter:
    def test_evaluate_completes_trials(self):
        problem = benchmarks.bbob_problem(2)
        exp = benchmarks.NumpyExperimenter(bbob.Sphere, problem)
        t = vz.Trial(id=1, parameters={"x0": 1.0, "x1": 2.0})
        exp.evaluate([t])
        assert t.final_measurement.metrics["bbob_eval"].value == pytest.approx(5.0)

    def test_nan_marks_infeasible(self):
        problem = benchmarks.bbob_problem(1)
        exp = benchmarks.NumpyExperimenter(lambda x: np.full(x.shape[0], np.nan), problem)
        t = vz.Trial(id=1, parameters={"x0": 0.0})
        exp.evaluate([t])
        assert t.infeasible


class TestWrappers:
    def _sphere(self, dim=2):
        return benchmarks.NumpyExperimenter(bbob.Sphere, benchmarks.bbob_problem(dim))

    def test_noisy(self):
        exp = wrappers.NoisyExperimenter(self._sphere(), noise_std=0.1, seed=1)
        t = vz.Trial(id=1, parameters={"x0": 0.0, "x1": 0.0})
        exp.evaluate([t])
        v = t.final_measurement.metrics["bbob_eval"].value
        assert v != 0.0 and abs(v) < 1.0

    def test_shifting_moves_optimum(self):
        exp = wrappers.ShiftingExperimenter(self._sphere(), shift=np.array([1.0, -2.0]))
        at_shift = vz.Trial(id=1, parameters={"x0": 1.0, "x1": -2.0})
        at_origin = vz.Trial(id=2, parameters={"x0": 0.0, "x1": 0.0})
        exp.evaluate([at_shift, at_origin])
        assert at_shift.final_measurement.metrics["bbob_eval"].value == pytest.approx(0.0)
        assert at_origin.final_measurement.metrics["bbob_eval"].value > 0

    def test_sign_flip(self):
        exp = wrappers.SignFlipExperimenter(self._sphere())
        assert (
            exp.problem_statement().metric_information.item().goal
            == vz.ObjectiveMetricGoal.MAXIMIZE
        )
        t = vz.Trial(id=1, parameters={"x0": 1.0, "x1": 0.0})
        exp.evaluate([t])
        assert t.final_measurement.metrics["bbob_eval"].value == pytest.approx(-1.0)

    def test_discretizing(self):
        exp = wrappers.DiscretizingExperimenter(
            self._sphere(), {"x0": [-1.0, 0.0, 1.0]}
        )
        space = exp.problem_statement().search_space
        assert space.get("x0").type == vz.ParameterType.DISCRETE
        assert space.get("x1").type == vz.ParameterType.DOUBLE

    def test_infeasible(self):
        exp = wrappers.InfeasibleExperimenter(self._sphere(), infeasible_prob=1.0, seed=0)
        t = vz.Trial(id=1, parameters={"x0": 0.0, "x1": 0.0})
        exp.evaluate([t])
        assert t.infeasible


class TestSimpleKD:
    def test_optimum(self):
        exp = simplekd.SimpleKDExperimenter("corner")
        best = exp.optimal_trial()
        exp.evaluate([best])
        assert best.final_measurement.metrics["value"].value == pytest.approx(0.0)

    def test_suboptimal_is_worse(self):
        exp = simplekd.SimpleKDExperimenter("corner")
        t = vz.Trial(
            parameters={
                "categorical": "center",
                "discrete": 5.0,
                "int": 4,
                "float_0": 0.9,
                "float_1": 0.9,
            }
        )
        exp.evaluate([t])
        assert t.final_measurement.metrics["value"].value < -1.0


class TestMultiObjective:
    @pytest.mark.parametrize("which", ["zdt1", "zdt2", "zdt3", "zdt4", "zdt6"])
    def test_zdt_shapes(self, which):
        exp = multiobjective.MultiObjectiveExperimenter.zdt(which, dimension=5)
        t = vz.Trial(parameters={f"x{i}": 0.5 for i in range(5)})
        exp.evaluate([t])
        assert len(t.final_measurement.metrics) == 2

    def test_zdt1_pareto_front(self):
        # On the front (x1..=0), f2 = 1 - sqrt(f1).
        exp = multiobjective.MultiObjectiveExperimenter.zdt("zdt1", dimension=4)
        t = vz.Trial(parameters={"x0": 0.25, "x1": 0.0, "x2": 0.0, "x3": 0.0})
        exp.evaluate([t])
        m = t.final_measurement.metrics
        assert m["zdt1_f0"].value == pytest.approx(0.25)
        assert m["zdt1_f1"].value == pytest.approx(1 - 0.5)

    def test_dtlz2(self):
        exp = multiobjective.MultiObjectiveExperimenter.dtlz("dtlz2", dimension=4)
        t = vz.Trial(parameters={f"x{i}": 0.5 for i in range(4)})
        exp.evaluate([t])
        m = list(t.final_measurement.metrics.values())
        # On the unit sphere: sum of squares == 1 when g == 0.
        assert sum(v.value**2 for v in m) == pytest.approx(1.0)


class TestRunnerAndAnalyzers:
    def test_benchmark_loop_and_convergence(self):
        problem = benchmarks.bbob_problem(2)
        exp = benchmarks.NumpyExperimenter(bbob.Sphere, problem)
        state = benchmarks.BenchmarkState.from_designer_factory(
            exp, lambda p, **kw: RandomDesigner(p.search_space, seed=kw.get("seed", 0)), seed=1
        )
        runner = benchmarks.BenchmarkRunner(
            benchmark_subroutines=[benchmarks.GenerateAndEvaluate(5)], num_repeats=6
        )
        runner.run(state)
        trials = state.algorithm.supporter.GetTrials(
            status_matches=vz.TrialStatus.COMPLETED
        )
        assert len(trials) == 30
        curve = benchmarks.ConvergenceCurveConverter(
            problem.metric_information.item()
        ).convert(trials)
        assert curve.ys.shape == (1, 30)
        # Best-so-far must be monotone non-increasing for MINIMIZE.
        assert np.all(np.diff(curve.ys[0]) <= 1e-12)

    def test_suggest_then_evaluate_subroutines(self):
        problem = benchmarks.bbob_problem(2)
        exp = benchmarks.NumpyExperimenter(bbob.Sphere, problem)
        state = benchmarks.BenchmarkState.from_designer_factory(
            exp, lambda p, **kw: RandomDesigner(p.search_space, seed=0)
        )
        benchmarks.BenchmarkRunner(
            [benchmarks.GenerateSuggestions(4), benchmarks.EvaluateActiveTrials()],
            num_repeats=2,
        ).run(state)
        assert (
            len(state.algorithm.supporter.GetTrials(status_matches=vz.TrialStatus.COMPLETED))
            == 8
        )

    def test_log_efficiency_comparator(self):
        # A faster-converging curve should score positive.
        xs = np.arange(1, 21)
        slow = benchmarks.ConvergenceCurve(
            xs=xs, ys=(xs / 20.0)[None, :], trend=benchmarks.ConvergenceCurve.YTrend.INCREASING
        )
        fast = benchmarks.ConvergenceCurve(
            xs=xs,
            ys=np.minimum(xs / 5.0, 1.0)[None, :],
            trend=benchmarks.ConvergenceCurve.YTrend.INCREASING,
        )
        comparator = benchmarks.LogEfficiencyConvergenceCurveComparator(slow)
        assert comparator.score(fast) > 0.5
        assert benchmarks.LogEfficiencyConvergenceCurveComparator(fast).score(slow) < -0.5

    def test_win_rate(self):
        xs = np.arange(1, 4)
        a = benchmarks.ConvergenceCurve(
            xs=xs, ys=np.array([[1, 2, 3.0]]), trend=benchmarks.ConvergenceCurve.YTrend.INCREASING
        )
        b = benchmarks.ConvergenceCurve(
            xs=xs, ys=np.array([[1, 2, 5.0]]), trend=benchmarks.ConvergenceCurve.YTrend.INCREASING
        )
        assert benchmarks.WinRateComparator(a).score(b) == 1.0

    def test_grid_beats_random_on_1d(self):
        """Sanity: exhaustive grid finds the 1-D optimum exactly."""
        problem = benchmarks.bbob_problem(1)
        exp = benchmarks.NumpyExperimenter(bbob.Sphere, problem)
        state = benchmarks.BenchmarkState.from_designer_factory(
            exp, lambda p, **kw: GridSearchDesigner(p.search_space, double_grid_resolution=21)
        )
        benchmarks.BenchmarkRunner([benchmarks.GenerateAndEvaluate(21)]).run(state)
        trials = state.algorithm.supporter.GetTrials(
            status_matches=vz.TrialStatus.COMPLETED
        )
        best = min(t.final_measurement.metrics["bbob_eval"].value for t in trials)
        assert best == pytest.approx(0.0, abs=1e-9)


class TestNoiseTypes:
    """Per-type parity for the BBOB-noisy model zoo (wrappers.make_noise_fn)."""

    def _fn(self, noise_type, dim=4, seed=7):
        return wrappers.make_noise_fn(
            noise_type, dimension=dim, rng=np.random.default_rng(seed)
        )

    def test_no_noise_identity(self):
        fn = self._fn("NO_NOISE")
        # Stabilization still applies its floor offset above target_value.
        assert fn(5.0) == pytest.approx(5.0 + 1.01e-8)
        assert fn(1e-12) == 1e-12

    def test_gaussian_matches_lognormal_formula(self):
        for sev, sigma in [("MODERATE", 0.01), ("SEVERE", 0.1)]:
            fn = self._fn(f"{sev}_GAUSSIAN", seed=3)
            ref_rng = np.random.default_rng(3)
            expected = 5.0 * ref_rng.lognormal(0.0, sigma) + 1.01e-8
            assert fn(5.0) == pytest.approx(expected, rel=1e-12)

    def test_uniform_matches_formula(self):
        dim = 5
        for sev, e in [("MODERATE", 0.01), ("SEVERE", 0.1)]:
            fn = self._fn(f"{sev}_UNIFORM", dim=dim, seed=11)
            ref_rng = np.random.default_rng(11)
            v = 3.0
            shrink = ref_rng.uniform() ** max(0.0, e)
            amplify = (1e9 / (v + 1e-99)) ** (e * (0.49 + 1.0 / dim) * ref_rng.uniform())
            expected = v * shrink * max(1.0, amplify) + 1.01e-8
            assert fn(v) == pytest.approx(expected, rel=1e-12)

    def test_cauchy_matches_formula(self):
        for sev, (strength, freq) in [
            ("MODERATE", (0.01, 0.05)),
            ("SEVERE", (0.1, 0.25)),
        ]:
            fn = self._fn(f"{sev}_SELDOM_CAUCHY", seed=13)
            ref_rng = np.random.default_rng(13)
            v = 2.0
            c = (ref_rng.uniform() < freq) * ref_rng.standard_cauchy()
            expected = v + strength * max(0.0, 1000.0 + c) + 1.01e-8
            assert fn(v) == pytest.approx(expected, rel=1e-12)

    def test_additive_gaussian_no_stabilization(self):
        for sev, std in [("LIGHT", 0.01), ("MODERATE", 0.1), ("SEVERE", 1.0)]:
            fn = self._fn(f"{sev}_ADDITIVE_GAUSSIAN", seed=17)
            ref_rng = np.random.default_rng(17)
            assert fn(1.0) == pytest.approx(1.0 + ref_rng.normal(0.0, std))
            # Below-target values are noised too (additive is unstabilized).
            assert fn(0.0) != 0.0

    def test_stabilization_passes_near_optimum(self):
        fn = self._fn("SEVERE_UNIFORM")
        assert fn(1e-9) == 1e-9  # below target_value: untouched

    def test_unknown_type_raises(self):
        with pytest.raises(ValueError, match="Unknown noise type"):
            self._fn("EXTREME_LAPLACE")

    def test_from_type_preserves_before_noise(self):
        sphere = benchmarks.NumpyExperimenter(
            bbob.Sphere, benchmarks.bbob_problem(2)
        )
        exp = wrappers.NoisyExperimenter.from_type(
            sphere, "SEVERE_GAUSSIAN", seed=1
        )
        t = vz.Trial(id=1, parameters={"x0": 1.0, "x1": 1.0})
        exp.evaluate([t])
        m = t.final_measurement.metrics
        assert m["bbob_eval_before_noise"].value == pytest.approx(2.0)
        assert m["bbob_eval"].value != m["bbob_eval_before_noise"].value

    def test_all_types_run_through_experimenter(self):
        for noise_type in wrappers.NOISE_TYPES:
            sphere = benchmarks.NumpyExperimenter(
                bbob.Sphere, benchmarks.bbob_problem(3)
            )
            exp = wrappers.NoisyExperimenter.from_type(sphere, noise_type, seed=2)
            t = vz.Trial(id=1, parameters={"x0": 0.5, "x1": -0.5, "x2": 1.5})
            exp.evaluate([t])
            assert np.isfinite(t.final_measurement.metrics["bbob_eval"].value)

    def test_known_family_unknown_severity_raises(self):
        with pytest.raises(ValueError, match="Unknown noise type"):
            self._fn("LIGHT_GAUSSIAN")
