"""Tests: COMBO combinatorial suite, L1-categorical, and the new wrappers
(Sparse / Permuting / Switch), plus surrogate-pipeline e2e runs."""

import numpy as np
import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.benchmarks.experimenters import base as exp_base
from vizier_tpu.benchmarks.experimenters import combinatorial, surrogates, wrappers
from vizier_tpu.benchmarks.experimenters.synthetic import bbob
from vizier_tpu.designers import GridSearchDesigner, RandomDesigner
from vizier_tpu.pyvizier import trial as trial_


def _run_designer_loop(designer, experimenter, n_rounds=6, batch=2):
    best = np.inf
    tid = 0
    from vizier_tpu.algorithms import core as core_lib

    goal = experimenter.problem_statement().metric_information.item()
    sign = 1.0 if goal.goal == vz.ObjectiveMetricGoal.MINIMIZE else -1.0
    for _ in range(n_rounds):
        trials = []
        for s in designer.suggest(batch):
            tid += 1
            trials.append(s.to_trial(tid))
        experimenter.evaluate(trials)
        for t in trials:
            if t.final_measurement is not None:
                v = t.final_measurement.metrics[goal.name].value
                best = min(best, sign * v)
        designer.update(core_lib.CompletedTrials(trials))
    return best


class TestIsing:
    def test_keeping_all_edges_is_zero_kld(self):
        exp = combinatorial.IsingExperimenter(lamda=0.0, seed=1)
        n = exp.problem_statement().search_space.parameter_names()
        t = trial_.Trial(id=1, parameters={name: True for name in n})
        exp.evaluate([t])
        assert t.final_measurement.metrics["main_objective"].value == pytest.approx(
            0.0, abs=1e-6
        )

    def test_dropping_edges_costs_kld(self):
        exp = combinatorial.IsingExperimenter(lamda=0.0, seed=1)
        names = exp.problem_statement().search_space.parameter_names()
        t = trial_.Trial(id=1, parameters={name: False for name in names})
        exp.evaluate([t])
        assert t.final_measurement.metrics["main_objective"].value > 0.0

    def test_lambda_penalizes_kept_edges(self):
        e0 = combinatorial.IsingExperimenter(lamda=0.0, seed=2)
        e1 = combinatorial.IsingExperimenter(lamda=0.5, seed=2)
        names = e0.problem_statement().search_space.parameter_names()
        t0 = trial_.Trial(id=1, parameters={n: True for n in names})
        t1 = trial_.Trial(id=1, parameters={n: True for n in names})
        e0.evaluate([t0])
        e1.evaluate([t1])
        diff = (
            t1.final_measurement.metrics["main_objective"].value
            - t0.final_measurement.metrics["main_objective"].value
        )
        assert diff == pytest.approx(0.5 * len(names), rel=1e-6)


class TestContamination:
    def test_evaluates_and_is_deterministic(self):
        exp = combinatorial.ContaminationExperimenter(seed=3)
        names = exp.problem_statement().search_space.parameter_names()
        vals = {}
        for _ in range(2):
            t = trial_.Trial(id=1, parameters={n: (i % 2 == 0) for i, n in enumerate(names)})
            exp.evaluate([t])
            vals[_] = t.final_measurement.metrics["main_objective"].value
        assert vals[0] == vals[1]

    def test_no_intervention_fails_constraints(self):
        exp = combinatorial.ContaminationExperimenter(lamda=0.0, seed=3)
        names = exp.problem_statement().search_space.parameter_names()
        t_none = trial_.Trial(id=1, parameters={n: False for n in names})
        t_all = trial_.Trial(id=2, parameters={n: True for n in names})
        exp.evaluate([t_none, t_all])
        # All-interventions pays cost 25 but satisfies constraints; the gap to
        # no-intervention is bounded by the constraint payoff.
        v_none = t_none.final_measurement.metrics["main_objective"].value
        v_all = t_all.final_measurement.metrics["main_objective"].value
        assert v_none != v_all


class TestCentroid:
    def test_runs_on_categorical_space(self):
        exp = combinatorial.CentroidExperimenter(seed=4)
        problem = exp.problem_statement()
        names = problem.search_space.parameter_names()
        t = trial_.Trial(id=1, parameters={n: "0" for n in names})
        exp.evaluate([t])
        assert np.isfinite(t.final_measurement.metrics["main_objective"].value)

    def test_matching_single_model_not_worse_than_random_mix(self):
        exp = combinatorial.CentroidExperimenter(seed=5, n_models=2)
        names = exp.problem_statement().search_space.parameter_names()
        t_pure = trial_.Trial(id=1, parameters={n: "0" for n in names})
        rng = np.random.default_rng(0)
        t_mix = trial_.Trial(
            id=2, parameters={n: str(rng.integers(0, 2)) for n in names}
        )
        exp.evaluate([t_pure, t_mix])
        assert np.isfinite(t_pure.final_measurement.metrics["main_objective"].value)
        assert np.isfinite(t_mix.final_measurement.metrics["main_objective"].value)


class TestPestControl:
    def test_deterministic_given_seed(self):
        exp = combinatorial.PestControlExperimenter(seed=6)
        names = exp.problem_statement().search_space.parameter_names()
        results = []
        for _ in range(2):
            t = trial_.Trial(id=1, parameters={n: "1" for n in names})
            exp.evaluate([t])
            results.append(t.final_measurement.metrics["main_objective"].value)
        assert results[0] == results[1]

    def test_control_beats_no_control(self):
        exp = combinatorial.PestControlExperimenter(seed=6)
        names = exp.problem_statement().search_space.parameter_names()
        t_none = trial_.Trial(id=1, parameters={n: "0" for n in names})
        t_ctrl = trial_.Trial(id=2, parameters={n: "4" for n in names})
        exp.evaluate([t_none, t_ctrl])
        # No control → pests exceed threshold at ~every stage (cost ≈ 25);
        # cheap pesticide keeps pests down at bounded price.
        assert (
            t_ctrl.final_measurement.metrics["main_objective"].value
            < t_none.final_measurement.metrics["main_objective"].value
        )


class TestL1Categorical:
    def test_optimum_scores_zero(self):
        exp = combinatorial.L1CategoricalExperimenter(
            num_categories=[3, 4, 2], seed=7
        )
        t = exp.optimal_trial
        assert t.final_measurement.metrics["objective"].value == 0.0

    def test_loss_counts_mismatches(self):
        exp = combinatorial.L1CategoricalExperimenter(
            num_categories=[3, 3], optimum=[1, 2]
        )
        t = trial_.Trial(id=1, parameters={"c0": "1", "c1": "0"})
        exp.evaluate([t])
        assert t.final_measurement.metrics["objective"].value == 1.0

    def test_invalid_optimum_rejected(self):
        with pytest.raises(ValueError):
            combinatorial.L1CategoricalExperimenter(
                num_categories=[2], optimum=[5]
            )

    def test_random_designer_converges(self):
        exp = combinatorial.L1CategoricalExperimenter(num_categories=[2, 2], seed=8)
        d = RandomDesigner(exp.problem_statement().search_space, seed=0)
        best = _run_designer_loop(d, exp, n_rounds=10, batch=4)
        assert best == 0.0  # 4 combos, 40 samples: must hit the optimum


def _quadratic_problem(dim=2):
    problem = vz.ProblemStatement()
    for i in range(dim):
        problem.search_space.root.add_float_param(f"x{i}", -5.0, 5.0)
    problem.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MINIMIZE)
    )
    return problem


class TestSparseExperimenter:
    def test_space_expanded_and_placeholders_ignored(self):
        inner = exp_base.NumpyExperimenter(bbob.Sphere, _quadratic_problem())
        sparse = wrappers.SparseExperimenter.create_default(
            inner, num_float=2, num_categorical=1
        )
        names = sparse.problem_statement().search_space.parameter_names()
        assert "_SPARSE_float0" in names and "_SPARSE_categorical0" in names
        t1 = trial_.Trial(
            id=1,
            parameters={
                "x0": 1.0, "x1": 2.0,
                "_SPARSE_float0": -3.0, "_SPARSE_float1": 4.0,
                "_SPARSE_categorical0": "a",
            },
        )
        t2 = trial_.Trial(
            id=2,
            parameters={
                "x0": 1.0, "x1": 2.0,
                "_SPARSE_float0": 5.0, "_SPARSE_float1": -1.0,
                "_SPARSE_categorical0": "f",
            },
        )
        sparse.evaluate([t1, t2])
        assert (
            t1.final_measurement.metrics["obj"].value
            == t2.final_measurement.metrics["obj"].value
        )

    def test_collision_rejected(self):
        problem = vz.ProblemStatement()
        problem.search_space.root.add_float_param("_SPARSE_float0", -5.0, 5.0)
        problem.metric_information.append(
            vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MINIMIZE)
        )
        inner = exp_base.NumpyExperimenter(bbob.Sphere, problem)
        with pytest.raises(ValueError, match="collides"):
            wrappers.SparseExperimenter.create_default(inner, num_float=1)


class TestPermutingExperimenter:
    def test_permutation_changes_values_consistently(self):
        problem = vz.ProblemStatement()
        problem.search_space.root.add_discrete_param("d", [0.0, 1.0, 2.0, 3.0, 4.0])
        problem.metric_information.append(
            vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MINIMIZE)
        )

        class Echo(exp_base.Experimenter):
            def evaluate(self, suggestions):
                for t in suggestions:
                    t.complete(
                        trial_.Measurement(
                            metrics={"obj": float(t.parameters["d"].value)}
                        )
                    )

            def problem_statement(self):
                return problem

        perm = wrappers.PermutingExperimenter(Echo(), ["d"], seed=1)
        vals = {}
        for v in [0.0, 1.0, 2.0, 3.0, 4.0]:
            t = trial_.Trial(id=1, parameters={"d": v})
            perm.evaluate([t])
            vals[v] = t.final_measurement.metrics["obj"].value
        # Bijective map over the same value set.
        assert sorted(vals.values()) == [0.0, 1.0, 2.0, 3.0, 4.0]
        # Deterministic: re-evaluating gives the same mapping.
        t = trial_.Trial(id=9, parameters={"d": 2.0})
        perm.evaluate([t])
        assert t.final_measurement.metrics["obj"].value == vals[2.0]

    def test_continuous_rejected(self):
        inner = exp_base.NumpyExperimenter(bbob.Sphere, _quadratic_problem())
        with pytest.raises(ValueError, match="continuous"):
            wrappers.PermutingExperimenter(inner, ["x0"])


class TestSwitchExperimenter:
    def _make(self):
        inner1 = exp_base.NumpyExperimenter(bbob.Sphere, _quadratic_problem())
        p2 = vz.ProblemStatement()
        p2.search_space.root.add_float_param("y", -1.0, 1.0)
        p2.metric_information.append(
            vz.MetricInformation(name="other", goal=vz.ObjectiveMetricGoal.MINIMIZE)
        )
        inner2 = exp_base.NumpyExperimenter(bbob.Sphere, p2)
        return wrappers.SwitchExperimenter([inner1, inner2])

    def test_conditional_space_structure(self):
        sw = self._make()
        problem = sw.problem_statement()
        assert problem.search_space.is_conditional
        cfg = problem.search_space.get("switch")
        assert len(cfg.children) == 3  # x0, x1 under "0"; y under "1"

    def test_routes_to_selected_experimenter(self):
        sw = self._make()
        t = trial_.Trial(id=1, parameters={"switch": "1", "y": 0.5})
        sw.evaluate([t])
        assert "switch_metric" in t.final_measurement.metrics

    def test_mixed_goals_rejected(self):
        inner1 = exp_base.NumpyExperimenter(bbob.Sphere, _quadratic_problem())
        p2 = vz.ProblemStatement()
        p2.search_space.root.add_float_param("y", -1.0, 1.0)
        p2.metric_information.append(
            vz.MetricInformation(name="acc", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
        inner2 = exp_base.NumpyExperimenter(bbob.Sphere, p2)
        with pytest.raises(ValueError, match="goal"):
            wrappers.SwitchExperimenter([inner1, inner2])

    def test_conditional_space_benchmark_end_to_end(self):
        """The NAS-style conditional benchmark runs with a real designer."""
        sw = self._make()
        d = RandomDesigner(sw.problem_statement().search_space, seed=0)
        best = _run_designer_loop(d, sw, n_rounds=8, batch=2)
        assert np.isfinite(best)


class TestNASBench201Synthetic:
    def test_end_to_end_with_designer(self):
        handler = surrogates.NASBench201Handler()
        exp = handler.make_synthetic_experimenter(num_rows=256, seed=0)
        d = RandomDesigner(exp.problem_statement().search_space, seed=1)
        best = _run_designer_loop(d, exp, n_rounds=10, batch=4)
        assert np.isfinite(best)
        # accuracy-like scale
        assert -100.0 <= best <= 0.0 or 0.0 <= -best <= 100.0

    def test_real_data_gated_with_clear_error(self):
        handler = surrogates.NASBench201Handler(data_path="/nonexistent.json")
        with pytest.raises(FileNotFoundError, match="NASBench-201"):
            handler.make_experimenter()


class TestAtari100k:
    def test_gated_without_data(self):
        handler = surrogates.Atari100kHandler()
        with pytest.raises(FileNotFoundError, match="Atari100k"):
            handler.make_experimenter()

    def test_live_experimenter_space_matches_reference(self):
        """The published 14-parameter gin space + eval_average_return."""
        exp = surrogates.Atari100kExperimenter(game_name="Pong", agent_name="DrQ")
        problem = exp.problem_statement()
        names = set(problem.search_space.parameter_names())
        assert problem.search_space.num_parameters() == 14
        assert {
            "JaxDQNAgent.gamma",
            "JaxFullRainbowAgent.noisy",
            "Atari100kRainbowAgent.data_augmentation",
            "create_optimizer.learning_rate",
        } <= names
        assert problem.metric_information.item().name == "eval_average_return"

    def test_live_experimenter_gated_on_dopamine(self):
        exp = surrogates.Atari100kExperimenter()
        t = trial_.Trial(id=1, parameters={"JaxDQNAgent.update_horizon": 3})
        with pytest.raises(ImportError, match="dopamine"):
            exp.evaluate([t])

    def test_invalid_agent_rejected(self):
        with pytest.raises(ValueError, match="agent_name"):
            surrogates.Atari100kExperimenter(agent_name="Rainbow9000")

    def test_loads_json_table_with_gin_columns(self, tmp_path):
        import json

        table = []
        rng = np.random.default_rng(0)
        for _ in range(16):
            table.append(
                {
                    "create_optimizer.learning_rate": float(
                        10 ** rng.uniform(-5, -2)
                    ),
                    "JaxDQNAgent.update_horizon": int(rng.integers(1, 21)),
                    "JaxFullRainbowAgent.num_atoms": int(rng.integers(1, 101)),
                    "eval_average_return": float(rng.normal()),
                }
            )
        path = tmp_path / "atari.json"
        path.write_text(json.dumps(table))
        handler = surrogates.Atari100kHandler(data_path=str(path))
        exp = handler.make_experimenter()
        t = trial_.Trial(
            id=1,
            parameters={
                "create_optimizer.learning_rate": 1e-3,
                "JaxDQNAgent.update_horizon": 5,
                "JaxFullRainbowAgent.num_atoms": 51,
            },
        )
        exp.evaluate([t])
        assert np.isfinite(
            t.final_measurement.metrics["eval_average_return"].value
        )

    def test_unknown_column_rejected(self, tmp_path):
        import json

        path = tmp_path / "atari.json"
        path.write_text(json.dumps([{"bogus_param": 1.0, "score": 0.5}]))
        handler = surrogates.Atari100kHandler(data_path=str(path))
        with pytest.raises(ValueError, match="bogus_param"):
            handler.make_experimenter()

    def test_bool_params_bind_as_python_bools(self):
        from vizier_tpu.benchmarks.experimenters.surrogates import (
            _gin_native_value,
        )

        assert _gin_native_value("JaxFullRainbowAgent.noisy", "False") is False
        assert _gin_native_value("JaxFullRainbowAgent.noisy", "True") is True
        # Non-bool params pass through untouched.
        assert _gin_native_value("JaxDQNAgent.update_horizon", 7) == 7

    def test_problem_statement_matches_table_columns(self, tmp_path):
        import json

        path = tmp_path / "atari.json"
        path.write_text(
            json.dumps(
                [{"JaxDQNAgent.update_horizon": 3, "eval_average_return": 1.0}]
            )
        )
        handler = surrogates.Atari100kHandler(data_path=str(path))
        assert handler.problem_statement().search_space.parameter_names() == [
            "JaxDQNAgent.update_horizon"
        ]
        # Without data: the full published space.
        assert (
            surrogates.Atari100kHandler().problem_statement()
            .search_space.num_parameters()
            == 14
        )

    def test_mismatched_row_columns_rejected(self, tmp_path):
        import json

        path = tmp_path / "atari.json"
        path.write_text(
            json.dumps(
                [
                    {"JaxDQNAgent.update_horizon": 3, "score": 1.0},
                    {"JaxDQNAgent.update_period": 2, "score": 2.0},
                ]
            )
        )
        handler = surrogates.Atari100kHandler(data_path=str(path))
        with pytest.raises(ValueError, match="differ from row"):
            handler.make_experimenter()

    def test_empty_table_rejected(self, tmp_path):
        path = tmp_path / "atari.json"
        path.write_text("[]")
        handler = surrogates.Atari100kHandler(data_path=str(path))
        with pytest.raises(ValueError, match="Empty Atari100k"):
            handler.make_experimenter()


class TestMAXSAT:
    WCNF = (
        "c tiny instance\n"
        "p wcnf 3 4\n"
        "2.0 1 -2 0\n"
        "1.0 2 3 0\n"
        "4.0 -1 0\n"
        "3.0 -3 0\n"
    )

    def test_parse_shapes_and_header(self):
        n, w, var_idx, want_true, mask = combinatorial.parse_wcnf(self.WCNF)
        assert n == 3
        assert w.shape == (4,)
        assert var_idx.shape == want_true.shape == mask.shape == (4, 2)
        assert mask[2, 1] == False  # unit clause padded
        np.testing.assert_array_equal(var_idx[0], [0, 1])
        np.testing.assert_array_equal(want_true[0], [True, False])

    def test_header_mismatch_raises(self):
        with pytest.raises(ValueError):
            combinatorial.parse_wcnf("p wcnf 2 5\n1.0 1 0\n")

    def test_matches_naive_reference_semantics(self):
        rng = np.random.default_rng(7)
        text = combinatorial.random_wcnf(8, 20, rng)
        exp = combinatorial.MAXSATExperimenter(text)
        n, raw_w, _, _, _ = combinatorial.parse_wcnf(text)
        w = (raw_w - raw_w.mean()) / raw_w.std()
        # Naive per-clause loop (reference combo_experimenter.py:409-420).
        clauses = []
        for line in text.splitlines():
            if line.startswith(("c", "p")) or not line.strip():
                continue
            lits = [int(p) for p in line.split()[1:-1]]
            clauses.append(([abs(l) - 1 for l in lits], [l > 0 for l in lits]))
        for code in rng.integers(0, 2**8, size=16):
            x = np.array([(code >> i) & 1 for i in range(8)], dtype=bool)
            sat = np.array(
                [(x[idx] == np.array(sgn)).any() for idx, sgn in clauses]
            )
            expected = -np.sum(w * sat)
            got = exp.evaluate_batch(x[None])[0]
            np.testing.assert_allclose(got, expected, rtol=1e-12)

    def test_evaluate_trials_and_problem(self):
        exp = combinatorial.MAXSATExperimenter(self.WCNF)
        problem = exp.problem_statement()
        assert len(problem.search_space.parameter_names()) == 3
        t = trial_.Trial(
            id=1, parameters={"x_0": False, "x_1": False, "x_2": False}
        )
        exp.evaluate([t])
        assert t.final_measurement is not None
        # All-false satisfies clauses 1 (-2), 3 (-1), 4 (-3), not clause 2.
        w = np.array([2.0, 1.0, 4.0, 3.0])
        wz = (w - w.mean()) / w.std()
        expected = -(wz[0] + wz[2] + wz[3])
        np.testing.assert_allclose(
            t.final_measurement.metrics["main_objective"].value, expected, rtol=1e-6
        )

    def test_constant_weights_keep_raw_signal(self):
        # Unweighted instances must not z-score to a flat-zero objective.
        text = "p wcnf 2 2\n1.0 1 0\n1.0 2 0\n"
        exp = combinatorial.MAXSATExperimenter(text)
        v = exp.evaluate_batch(np.array([[True, True], [False, False]]))
        assert np.isfinite(v).all()
        np.testing.assert_allclose(v, [-2.0, 0.0])

    def test_random_designer_loop(self):
        rng = np.random.default_rng(3)
        exp = combinatorial.MAXSATExperimenter(combinatorial.random_wcnf(6, 12, rng))
        designer = RandomDesigner(exp.problem_statement().search_space, seed=1)
        best = _run_designer_loop(designer, exp, n_rounds=4, batch=3)
        assert np.isfinite(best)

    def test_multiple_clauses_per_line(self):
        # DIMACS permits several "weight lits 0" groups on one line; a
        # mid-line 0 is a clause boundary, not a literal.
        one_per_line = "p wcnf 3 2\n2.0 1 -2 0\n3.0 3 0\n"
        merged = "p wcnf 3 2\n2.0 1 -2 0 3.0 3 0\n"
        a = combinatorial.MAXSATExperimenter(one_per_line)
        b = combinatorial.MAXSATExperimenter(merged)
        X = np.array([[0, 0, 0], [1, 1, 1], [1, 0, 1], [0, 1, 0]], dtype=bool)
        np.testing.assert_allclose(a.evaluate_batch(X), b.evaluate_batch(X))

    def test_no_clauses_raises(self):
        with pytest.raises(ValueError, match="no clauses"):
            combinatorial.parse_wcnf("p wcnf 3 0\n")


class TestAtari100kLivePath:
    """Executes Atari100kExperimenter.evaluate's gin-binding + bool
    conversion with stub gin/dopamine modules (the real stack is absent)."""

    def _install_stubs(self, monkeypatch, tmp_path, final_return=42.0):
        import contextlib
        import sys
        import types

        bindings = {}
        parsed_files = []

        gin_stub = types.ModuleType("gin")
        gin_stub.unlock_config = contextlib.nullcontext
        gin_stub.parse_config_file = parsed_files.append
        gin_stub.bind_parameter = lambda name, value: bindings.__setitem__(
            name, value
        )

        class FakeStatistics:
            data_lists = {"eval_average_return": [10.0, final_return]}

        class FakeRunner:
            def __init__(self, base_dir):
                self.base_dir = base_dir

            def run_experiment(self):
                return FakeStatistics()

        eval_mod = types.ModuleType("dopamine.labs.atari_100k.eval_run_experiment")
        eval_mod.MaxEpisodeEvalRunner = FakeRunner
        atari_mod = types.ModuleType("dopamine.labs.atari_100k")
        atari_mod.eval_run_experiment = eval_mod
        labs_mod = types.ModuleType("dopamine.labs")
        labs_mod.atari_100k = atari_mod
        dopamine_mod = types.ModuleType("dopamine")
        dopamine_mod.labs = labs_mod

        monkeypatch.setitem(sys.modules, "gin", gin_stub)
        monkeypatch.setitem(sys.modules, "dopamine", dopamine_mod)
        monkeypatch.setitem(sys.modules, "dopamine.labs", labs_mod)
        monkeypatch.setitem(sys.modules, "dopamine.labs.atari_100k", atari_mod)
        monkeypatch.setitem(
            sys.modules,
            "dopamine.labs.atari_100k.eval_run_experiment",
            eval_mod,
        )
        gin_dir = tmp_path / "configs"
        gin_dir.mkdir()
        (gin_dir / "DER.gin").write_text("# stub agent config\n")
        return bindings, parsed_files, str(gin_dir)

    def test_evaluate_binds_and_completes(self, monkeypatch, tmp_path):
        bindings, parsed, gin_dir = self._install_stubs(monkeypatch, tmp_path)
        exp = surrogates.Atari100kExperimenter(
            game_name="Breakout",
            agent_name="DER",
            initial_gin_bindings={"Runner.num_iterations": 1},
            gin_config_dir=gin_dir,
        )
        t = trial_.Trial(
            id=1,
            parameters={
                "JaxDQNAgent.gamma": 0.97,
                "JaxFullRainbowAgent.noisy": False,
                "JaxFullRainbowAgent.dueling": True,
                "JaxDQNAgent.update_horizon": 3,
            },
        )
        exp.evaluate([t])
        assert t.final_measurement.metrics["eval_average_return"].value == 42.0
        assert parsed and parsed[0].endswith("DER.gin")
        assert (
            bindings["atari_lib.create_atari_environment.game_name"]
            == "Breakout"
        )
        assert bindings["Runner.num_iterations"] == 1
        # Bool parameters must arrive as real bools, not "True"/"False"
        # strings (a truthy-string bind would flip every agent flag on).
        assert bindings["JaxFullRainbowAgent.noisy"] is False
        assert bindings["JaxFullRainbowAgent.dueling"] is True
        assert bindings["JaxDQNAgent.gamma"] == pytest.approx(0.97)

    def test_missing_gin_dir_raises(self, monkeypatch, tmp_path):
        self._install_stubs(monkeypatch, tmp_path)
        exp = surrogates.Atari100kExperimenter(agent_name="DrQ")
        t = trial_.Trial(id=1, parameters={"JaxDQNAgent.gamma": 0.9})
        with pytest.raises(ValueError, match="gin_config_dir"):
            exp.evaluate([t])

    def test_missing_agent_config_raises(self, monkeypatch, tmp_path):
        _, _, gin_dir = self._install_stubs(monkeypatch, tmp_path)
        exp = surrogates.Atari100kExperimenter(
            agent_name="OTRainbow", gin_config_dir=gin_dir
        )
        t = trial_.Trial(id=1, parameters={"JaxDQNAgent.gamma": 0.9})
        with pytest.raises(FileNotFoundError):
            exp.evaluate([t])
