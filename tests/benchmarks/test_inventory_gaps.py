"""Tests for the round-2 inventory gap fills: classic experimenters,
exploration/simple-regret scores, random_sample, Context/ProblemAndTrials,
optimizer test utils, and the raytune run_tune plumbing."""

import numpy as np
import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.algorithms import random_sample
from vizier_tpu.benchmarks.analyzers import (
    compute_average_marginal_parameter_entropy,
    compute_parameter_entropy,
    t_test_mean_score,
)
from vizier_tpu.benchmarks.experimenters.synthetic import classic


def _run(experimenter, parameters_list):
    trials = [
        vz.Trial(id=i + 1, parameters=p) for i, p in enumerate(parameters_list)
    ]
    experimenter.evaluate(trials)
    return trials


class TestClassicExperimenters:
    def test_branin_optima(self):
        exptr = classic.Branin2DExperimenter()
        trials = _run(
            exptr,
            [
                {"x1": -np.pi, "x2": 12.275},
                {"x1": np.pi, "x2": 2.275},
                {"x1": 9.42478, "x2": 2.475},
                {"x1": 0.0, "x2": 0.0},
            ],
        )
        for t in trials[:3]:
            assert t.final_measurement.metrics["value"].value == pytest.approx(
                0.397887, abs=1e-4
            )
        assert trials[3].final_measurement.metrics["value"].value > 10.0

    def test_hartmann3_optimum(self):
        exptr = classic.HartmannExperimenter.from_3d()
        (t,) = _run(exptr, [{"x1": 0.114614, "x2": 0.555649, "x3": 0.852547}])
        assert t.final_measurement.metrics["value"].value == pytest.approx(
            -3.86278, abs=1e-4
        )
        assert len(exptr.problem_statement().search_space.parameters) == 3

    def test_hartmann6_optimum(self):
        exptr = classic.HartmannExperimenter.from_6d()
        opt = {
            "x1": 0.20169, "x2": 0.150011, "x3": 0.476874,
            "x4": 0.275332, "x5": 0.311652, "x6": 0.6573,
        }
        (t,) = _run(exptr, [opt])
        assert t.final_measurement.metrics["value"].value == pytest.approx(
            -3.32237, abs=1e-4
        )

    def test_fixed_multiarm(self):
        exptr = classic.FixedMultiArmExperimenter({"a": 0.1, "b": 0.9})
        problem = exptr.problem_statement()
        assert problem.metric_information.item().goal.is_maximize
        trials = _run(exptr, [{"arm": "a"}, {"arm": "b"}])
        assert trials[0].final_measurement.metrics["reward"].value == 0.1
        assert trials[1].final_measurement.metrics["reward"].value == 0.9

    def test_bernoulli_multiarm_statistics(self):
        exptr = classic.BernoulliMultiArmExperimenter({"a": 0.0, "b": 1.0}, seed=7)
        trials = _run(exptr, [{"arm": "a"}, {"arm": "b"}] * 20)
        rewards_a = [
            t.final_measurement.metrics["reward"].value
            for t in trials
            if t.parameters.get_value("arm") == "a"
        ]
        rewards_b = [
            t.final_measurement.metrics["reward"].value
            for t in trials
            if t.parameters.get_value("arm") == "b"
        ]
        assert set(rewards_a) == {0.0} and set(rewards_b) == {1.0}


class TestExplorationScore:
    def _config(self, kind):
        space = vz.SearchSpace()
        if kind == "double":
            space.root.add_float_param("p", 0.0, 1.0)
        elif kind == "int":
            space.root.add_int_param("p", 0, 9)
        else:
            space.root.add_categorical_param("p", ["a", "b", "c"])
        return space.parameters[0]

    def test_uniform_beats_constant(self):
        config = self._config("double")
        rng = np.random.default_rng(0)
        uniform = [vz.ParameterValue(float(v)) for v in rng.uniform(size=200)]
        constant = [vz.ParameterValue(0.5)] * 200
        assert compute_parameter_entropy(config, uniform) > compute_parameter_entropy(
            config, constant
        )

    def test_categorical_entropy(self):
        config = self._config("cat")
        balanced = [vz.ParameterValue(v) for v in ["a", "b", "c"] * 30]
        skewed = [vz.ParameterValue("a")] * 90
        assert compute_parameter_entropy(config, balanced) == pytest.approx(
            np.log(3), abs=1e-6
        )
        assert compute_parameter_entropy(config, skewed) == 0.0

    def test_out_of_bounds_raises(self):
        config = self._config("double")
        with pytest.raises(ValueError):
            compute_parameter_entropy(config, [vz.ParameterValue(2.0)])

    def test_average_marginal_entropy(self):
        problem = vz.ProblemStatement()
        problem.search_space.root.add_categorical_param("p", ["a", "b"])
        trials = [
            vz.Trial(id=i + 1, parameters={"p": "a" if i % 2 else "b"})
            for i in range(40)
        ]
        study = vz.ProblemAndTrials(problem=problem, trials=trials)
        results = {"algo": {"spec": {0: study}}}
        assert compute_average_marginal_parameter_entropy(results) == pytest.approx(
            np.log(2), abs=1e-6
        )
        assert compute_average_marginal_parameter_entropy({}) == 0.0


class TestSimpleRegretScore:
    def test_better_candidate_scores_low(self):
        rng = np.random.default_rng(0)
        baseline = rng.normal(0.0, 0.1, size=20)
        candidate = rng.normal(1.0, 0.1, size=20)
        p_better = t_test_mean_score(
            baseline, candidate, vz.ObjectiveMetricGoal.MAXIMIZE
        )
        p_worse = t_test_mean_score(
            candidate, baseline, vz.ObjectiveMetricGoal.MAXIMIZE
        )
        assert p_better < 0.01 < p_worse

    def test_minimize_flips_direction(self):
        baseline = [1.0, 1.1, 0.9, 1.05]
        candidate = [0.1, 0.2, 0.15, 0.12]
        p = t_test_mean_score(baseline, candidate, vz.ObjectiveMetricGoal.MINIMIZE)
        assert p < 0.01

    def test_single_candidate_uses_one_sample_test(self):
        baseline = [0.0, 0.1, -0.1, 0.05, -0.02]
        p = t_test_mean_score([*baseline], [5.0], vz.ObjectiveMetricGoal.MAXIMIZE)
        assert p < 0.01


class TestRandomSample:
    def test_sample_parameters_in_space(self):
        space = vz.SearchSpace()
        space.root.add_float_param("f", -1.0, 1.0)
        space.root.add_int_param("i", 0, 5)
        space.root.add_discrete_param("d", [0.1, 0.5, 2.5])
        space.root.add_categorical_param("c", ["x", "y"])
        rng = np.random.default_rng(0)
        for _ in range(25):
            params = random_sample.sample_parameters(rng, space)
            space.assert_contains(params)

    def test_discrete_snaps_to_closest(self):
        assert random_sample.get_closest_element([0.0, 1.0, 10.0], 0.9) == 1.0
        rng = np.random.default_rng(0)
        for _ in range(20):
            assert random_sample.sample_discrete(rng, [1.0, 2.0, 7.0]) in {
                1.0, 2.0, 7.0,
            }

    def test_bernoulli_and_shuffle(self):
        rng = np.random.default_rng(0)
        assert random_sample.sample_bernoulli(rng, 1.0, "yes", "no") == "yes"
        assert random_sample.sample_bernoulli(rng, 0.0, "yes", "no") == "no"
        items = list(range(10))
        shuffled = random_sample.shuffle_list(rng, list(items))
        assert sorted(shuffled) == items


class TestContextAndStudy:
    def test_context_validation(self):
        ctx = vz.Context(
            description="ctx",
            parameters={"p": vz.ParameterValue(1.0)},
            related_links={"doc": "http://x"},
        )
        assert ctx.parameters["p"].value == 1.0
        with pytest.raises(TypeError):
            vz.Context(parameters={"p": 1.0})
        with pytest.raises(TypeError):
            vz.Context(description=3)

    def test_problem_and_trials_copies_list(self):
        problem = vz.ProblemStatement()
        trials = (vz.Trial(id=1),)
        study = vz.ProblemAndTrials(problem=problem, trials=trials)
        assert isinstance(study.trials, list) and len(study.trials) == 1


class TestOptimizerTestUtils:
    def test_designer_as_optimizer_passes(self):
        from vizier_tpu.designers.random import RandomDesigner
        from vizier_tpu.optimizers.lbfgsb_optimizer import DesignerAsOptimizer
        from vizier_tpu.testing import optimizer_test_utils

        space = vz.SearchSpace()
        space.root.add_float_param("x", 0.0, 1.0)
        space.root.add_categorical_param("c", ["a", "b"])
        opt = DesignerAsOptimizer(
            designer_factory=lambda p: RandomDesigner(p.search_space, seed=1),
            num_rounds=3,
            batch_size=5,
        )
        optimizer_test_utils.assert_passes_on_random_single_metric_function(
            space, opt, np_random_seed=1
        )
        optimizer_test_utils.assert_passes_on_random_multi_metric_function(
            space, opt, np_random_seed=1
        )


class TestRunTunePlumbing:
    def test_param_space_and_objective(self):
        from vizier_tpu.raytune import run_tune

        exptr = classic.Branin2DExperimenter()
        space = run_tune.experimenter_param_space(exptr)
        assert space["x1"] == {"type": "uniform", "min": -5.0, "max": 10.0}
        objective = run_tune.experimenter_objective(exptr)
        result = objective({"x1": np.pi, "x2": 2.275})
        assert result["value"] == pytest.approx(0.397887, abs=1e-4)

    def test_ray_gated_entry_points_raise(self):
        from vizier_tpu.raytune import run_tune

        if run_tune._RAY_AVAILABLE:
            pytest.skip("ray installed")
        with pytest.raises(ImportError):
            run_tune.run_tune_bbob("sphere", 2)
        with pytest.raises(ImportError):
            run_tune.run_tune_distributed([], lambda: None)
