"""End-to-end explicit-coordinator multihost init (VERDICT r2/r3 carry-over).

Spawns TWO real OS processes that each call
``parallel.initialize_multihost(coordinator_address=..., num_processes=2,
process_id=i)`` on the CPU backend and assert the returned mesh is GLOBAL
(it spans both processes' devices). This executes the explicit-coordinator
branch of ``parallel/__init__.py`` — ``jax.distributed.initialize`` wiring
over a real localhost socket — which the in-process suite cannot reach
(jax.distributed refuses to initialize twice in one process).

Two tests split what CPU semantics allow from what needs real hardware:

- ``test_two_process_explicit_coordinator_returns_global_mesh`` runs the
  distributed init + global-mesh wiring end-to-end and PASSES on the CPU
  backend (cluster rendezvous, process count, global device view — the
  seam ``parallel.mesh.multihost_mesh`` builds placements from);
- ``test_two_process_global_mesh_spmd_compute`` additionally executes a
  pool-sharded computation OVER the global mesh. jax 0.4.37's CPU client
  raises ``Multiprocess computations aren't implemented on the CPU
  backend`` at dispatch of any computation whose sharding spans another
  process's devices — that one dispatch is the whole xfail; everything
  before it (init, mesh, placement math) is covered by the passing test.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent(
    """
    import sys

    # Pin the CPU platform BEFORE any jax import side effects (the image's
    # sitecustomize force-inits the TPU plugin otherwise).
    import jax

    jax.config.update("jax_platforms", "cpu")

    coordinator, process_id, mode = sys.argv[1], int(sys.argv[2]), sys.argv[3]

    from vizier_tpu import parallel

    mesh = parallel.initialize_multihost(
        coordinator_address=coordinator, num_processes=2, process_id=process_id
    )
    n_global = len(mesh.devices.flat)
    n_local = len(jax.local_devices())
    n_procs = jax.process_count()
    print(
        f"RESULT process_id={process_id} global={n_global} "
        f"local={n_local} procs={n_procs}",
        flush=True,
    )
    assert n_procs == 2, n_procs
    assert n_global == 2 * n_local, (n_global, n_local)

    # The mesh executor's multi-host seam sees the same global device
    # list the data plane shards over.
    from vizier_tpu.parallel import mesh as mesh_lib

    devices = mesh_lib.multihost_mesh(mesh_lib.MeshConfig())
    assert len(devices) == n_global, (len(devices), n_global)
    placements = mesh_lib.build_placements(
        mesh_lib.MeshConfig(enabled=True, shard_devices=n_local)
    )
    assert len(placements) == 2, placements
    print(f"PLACEMENTS process_id={process_id} count={len(placements)}", flush=True)

    if mode == "init":
        sys.exit(0)

    # Data plane over the GLOBAL mesh: a pool-sharded acquisition sweep
    # whose pools live on BOTH processes' devices, merged by a global
    # top-k (the cross-host collective), result replicated so every
    # process reads the same optimum. THIS dispatch is what the CPU
    # backend refuses ("Multiprocess computations aren't implemented on
    # the CPU backend") — it needs a real multi-process runtime (TPU/GPU).
    import jax.numpy as jnp

    from vizier_tpu.optimizers import eagle as eagle_lib
    from vizier_tpu.optimizers import vectorized as vectorized_lib

    target = jnp.asarray([0.25, 0.75])

    def score_fn(feats):
        return -jnp.sum((feats.continuous - target) ** 2, axis=-1)

    strategy = eagle_lib.VectorizedEagleStrategy(
        num_continuous=2, category_sizes=()
    )
    vec = vectorized_lib.VectorizedOptimizer(strategy, max_evaluations=200)

    @jax.jit
    def run(key):
        res = parallel.maximize_score_fn_sharded(
            vec, score_fn, key, 1, n_global, mesh
        )
        return jax.lax.with_sharding_constraint(
            res, parallel.replicated(mesh)
        )

    res = run(jax.random.PRNGKey(0))
    best = float(res.scores[0])
    xy = [round(float(v), 3) for v in res.features.continuous[0]]
    print(f"SPMD process_id={process_id} best={best:.5f} xy={xy}", flush=True)
    assert best > -0.01, best  # planted optimum found across both hosts
    """
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_workers(tmp_path, mode: str):
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    coordinator = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # the worker pins cpu via jax.config
    # 2 virtual devices per process -> the global mesh must see 4.
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    # Repo root from this file's location, not cwd, so the test passes
    # regardless of where pytest is invoked from.
    env["PYTHONPATH"] = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), coordinator, str(i), mode],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for i in range(2)
    ]
    outputs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outputs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, outputs


def test_two_process_explicit_coordinator_returns_global_mesh(tmp_path):
    """The CPU backend CAN do this much: rendezvous, global device view,
    and the mesh-plane placement math over it — a pod slice's control
    plane, end to end over a real localhost socket."""
    procs, outputs = _spawn_workers(tmp_path, "init")
    for i, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"process {i} failed:\n{out}"
        assert f"RESULT process_id={i} global=4 local=2 procs=2" in out, out
        assert f"PLACEMENTS process_id={i} count=2" in out, out


@pytest.mark.xfail(
    reason=(
        "Needs a multi-process jax runtime for exactly ONE step: executing "
        "a computation whose sharding spans another process's devices. jax "
        "0.4.37's CPU client raises 'Multiprocess computations aren't "
        "implemented on the CPU backend' at that dispatch. Everything "
        "before it — distributed init, global mesh, placement math — runs "
        "and passes on CPU (see "
        "test_two_process_explicit_coordinator_returns_global_mesh). "
        "Tracked in PARITY.md 'Multihost explicit-coordinator e2e'."
    ),
    strict=False,
)
def test_two_process_global_mesh_spmd_compute(tmp_path):
    procs, outputs = _spawn_workers(tmp_path, "spmd")
    spmd_lines = []
    for i, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"process {i} failed:\n{out}"
        line = [l for l in out.splitlines() if l.startswith(f"SPMD process_id={i}")]
        assert line, f"no SPMD result from process {i}:\n{out}"
        spmd_lines.append(line[0].split(" ", 2)[2])
    # Replicated output: both processes must report the identical optimum.
    assert spmd_lines[0] == spmd_lines[1], spmd_lines
