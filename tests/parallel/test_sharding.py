"""Multi-device sharding tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vizier_tpu import parallel
from vizier_tpu import types
from vizier_tpu.designers.gp import acquisitions
from vizier_tpu.models import gp as gp_lib
from vizier_tpu.models import kernels
from vizier_tpu.optimizers import eagle as eagle_lib
from vizier_tpu.optimizers import lbfgs as lbfgs_lib
from vizier_tpu.optimizers import vectorized as vectorized_lib


def _data(n=8, n_pad=8, dc=2, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.uniform(size=(n, dc)).astype(np.float32)
    y = -np.sum((x - 0.5) ** 2, axis=1)
    features = types.ContinuousAndCategorical(
        continuous=types.PaddedArray.from_array(x, (n_pad, dc)),
        categorical=types.PaddedArray.from_array(
            np.zeros((n, 0), np.int32), (n_pad, 0), fill_value=0
        ),
    )
    labels = types.PaddedArray.from_array(
        y[:, None].astype(np.float32), (n_pad, 1), fill_value=np.nan
    )
    return gp_lib.GPData.from_model_data(types.ModelData(features, labels))


class TestMesh:
    def test_eight_virtual_devices(self):
        assert len(jax.devices()) == 8

    def test_create_mesh(self):
        mesh = parallel.create_mesh()
        assert mesh.axis_names == ("devices",)
        assert mesh.devices.size == 8
        half = parallel.create_mesh(4)
        assert half.devices.size == 4

    def test_too_many_devices_rejected(self):
        with pytest.raises(ValueError):
            parallel.create_mesh(1000)


class TestShardedTrain:
    def test_matches_unsharded_quality(self):
        model = gp_lib.VizierGaussianProcess(num_continuous=2, num_categorical=0)
        data = _data()
        mesh = parallel.create_mesh()
        opt = lbfgs_lib.AdamOptimizer(maxiter=30)
        states = parallel.train_gp_sharded(
            model, opt, data, jax.random.PRNGKey(0), 8, 2, mesh
        )
        assert states.alpha.shape[0] == 2  # ensemble of 2
        # The trained GP must beat a random init's likelihood.
        coll = model.param_collection()
        rand = coll.random_init_unconstrained(jax.random.PRNGKey(3))
        rand_loss = float(model.neg_log_likelihood(rand, data))
        trained_unconstrained = coll.unconstrain(
            jax.tree_util.tree_map(lambda a: a[0], states.params)
        )
        trained_loss = float(model.neg_log_likelihood(trained_unconstrained, data))
        assert trained_loss <= rand_loss

    def test_restart_axis_sharded(self):
        model = gp_lib.VizierGaussianProcess(num_continuous=2, num_categorical=0)
        mesh = parallel.create_mesh()
        inits = model.param_collection().batch_random_init_unconstrained(
            jax.random.PRNGKey(0), 8
        )
        sharded = jax.device_put(inits, parallel.batch_sharded(mesh))
        shards = sharded["amplitude"].sharding.device_set
        assert len(shards) == 8


class TestShardedAcquisition:
    def test_pools_across_devices(self):
        mesh = parallel.create_mesh()
        model = gp_lib.VizierGaussianProcess(num_continuous=2, num_categorical=0)
        data = _data()
        params = model.param_collection().random_init_unconstrained(
            jax.random.PRNGKey(0)
        )
        state = model.precompute(params, data)
        states = jax.tree_util.tree_map(lambda a: a[None], state)
        scoring = acquisitions.ScoringFunction(
            predictive=gp_lib.EnsemblePredictive(states),
            acquisition=acquisitions.UCB(1.8),
            best_label=jnp.asarray(0.0),
            trust_region=None,
        )
        strategy = eagle_lib.VectorizedEagleStrategy(num_continuous=2, category_sizes=())
        vec_opt = vectorized_lib.VectorizedOptimizer(strategy, max_evaluations=500)
        result = parallel.maximize_acquisition_sharded(
            vec_opt, scoring, jax.random.PRNGKey(1), 3, 8, mesh
        )
        assert result.scores.shape == (3,)
        assert np.all(np.diff(np.asarray(result.scores)) <= 1e-9)

    def test_full_suggest_step(self):
        mesh = parallel.create_mesh()
        model = gp_lib.VizierGaussianProcess(num_continuous=2, num_categorical=0)
        strategy = eagle_lib.VectorizedEagleStrategy(num_continuous=2, category_sizes=())
        result = parallel.suggest_step_sharded(
            model,
            lbfgs_lib.AdamOptimizer(maxiter=20),
            vectorized_lib.VectorizedOptimizer(strategy, max_evaluations=300),
            _data(),
            jax.random.PRNGKey(0),
            count=2,
            num_restarts=8,
            ensemble_size=2,
            mesh=mesh,
        )
        cont = np.asarray(result.features.continuous)
        assert cont.shape == (2, 2)
        assert np.isfinite(np.asarray(result.scores)).all()


class TestMultihostInit:
    def test_single_host_returns_full_mesh(self):
        mesh = parallel.initialize_multihost()
        assert len(mesh.devices.flat) == len(jax.devices())
        # Sharded train accepts the returned mesh unchanged.
        model = gp_lib.VizierGaussianProcess(num_continuous=2, num_categorical=0)
        states = parallel.train_gp_sharded(
            model, lbfgs_lib.AdamOptimizer(maxiter=5), _data(),
            jax.random.PRNGKey(0), num_restarts=8, ensemble_size=1, mesh=mesh,
        )
        assert np.isfinite(np.asarray(states.chol)).all()
