"""The sharded data plane IS the production designer path (VERDICT r1 #2).

Asserts that designers auto-build a mesh, route ARD restarts + acquisition
pools through ``vizier_tpu.parallel``, and that an 8-device mesh suggest()
agrees with the single-device suggest() within tolerance on a peaked
objective.
"""

import numpy as np
import jax

from vizier_tpu import pyvizier as vz
from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.designers.gp_bandit import VizierGPBandit
from vizier_tpu.designers.gp_ucb_pe import UCBPEConfig, VizierGPUCBPEBandit
from vizier_tpu.optimizers import lbfgs as lbfgs_lib

_FAST_ARD = lbfgs_lib.LbfgsOptimizer(maxiter=25)


def _problem():
    p = vz.ProblemStatement()
    p.search_space.root.add_float_param("x", 0.0, 1.0)
    p.search_space.root.add_float_param("y", 0.0, 1.0)
    p.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return p


def _trials(n=12, seed=0):
    rng = np.random.default_rng(seed)
    trials = []
    for i in range(n):
        x, y = rng.uniform(), rng.uniform()
        t = vz.Trial(id=i + 1, parameters={"x": float(x), "y": float(y)})
        t.complete(
            vz.Measurement(
                metrics={"obj": -((x - 0.62) ** 2) - (y - 0.31) ** 2}
            )
        )
        trials.append(t)
    return trials


def _suggest_xy(designer, count=1):
    designer.update(core_lib.CompletedTrials(_trials()))
    s = designer.suggest(count)
    return np.array(
        [[float(si.parameters["x"].value), float(si.parameters["y"].value)] for si in s]
    )


class TestDesignerMeshIsProductionPath:
    def test_gp_bandit_builds_mesh_automatically(self, monkeypatch):
        monkeypatch.delenv("VIZIER_DISABLE_MESH", raising=False)
        d = VizierGPBandit(_problem())
        assert d._mesh is not None
        assert len(d._mesh.devices.flat) == len(jax.devices())

    def test_mesh_suggest_matches_single_device(self):
        kwargs = dict(
            ard_restarts=8,
            ard_optimizer=_FAST_ARD,
            max_acquisition_evaluations=2000,
            num_seed_trials=2,
            rng_seed=3,
        )
        single = VizierGPBandit(_problem(), use_mesh=False, **kwargs)
        meshed = VizierGPBandit(_problem(), use_mesh=True, **kwargs)
        assert meshed._mesh is not None and len(meshed._mesh.devices.flat) == 8
        xy_single = _suggest_xy(single)[0]
        xy_meshed = _suggest_xy(meshed)[0]
        # Both must land near the optimum (0.62, 0.31); the sharded path runs
        # 8 independent pools so exact equality is not expected.
        assert np.linalg.norm(xy_single - np.array([0.62, 0.31])) < 0.25
        assert np.linalg.norm(xy_meshed - np.array([0.62, 0.31])) < 0.25
        assert np.linalg.norm(xy_single - xy_meshed) < 0.3

    def test_ucb_pe_default_runs_on_mesh(self):
        d = VizierGPUCBPEBandit(
            _problem(),
            use_mesh=True,
            ard_restarts=8,
            ard_optimizer=_FAST_ARD,
            max_acquisition_evaluations=600,
            config=UCBPEConfig(num_scalarizations=16),
        )
        assert d._mesh is not None
        xy = _suggest_xy(d, count=3)
        assert xy.shape == (3, 2)
        assert np.isfinite(xy).all()

    def test_mesh_restarts_round_up_to_device_multiple(self):
        d = VizierGPBandit(
            _problem(), use_mesh=True, ard_restarts=5, ard_optimizer=_FAST_ARD
        )
        # 5 restarts on 8 devices → padded to 8 at the _train boundary.
        ndev = d._mesh_size()
        restarts = -(-d.ard_restarts // ndev) * ndev
        assert restarts == 8


def _two_metric_problem():
    p = vz.ProblemStatement()
    p.search_space.root.add_float_param("x", 0.0, 1.0)
    p.search_space.root.add_float_param("y", 0.0, 1.0)
    for name in ("m1", "m2"):
        p.metric_information.append(
            vz.MetricInformation(name=name, goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
    return p


class TestMeshSeparableMultitask:
    """mesh x SEPARABLE: the sharded joint-GP train path
    (``gp_ucb_pe._train_states_me`` mesh branch) must be exercised and agree
    with the unsharded trainer."""

    def _mt_designer(self, use_mesh):
        from vizier_tpu.models import multitask_gp as mtgp

        return VizierGPUCBPEBandit(
            _two_metric_problem(),
            use_mesh=use_mesh,
            ard_restarts=8,
            ard_optimizer=_FAST_ARD,
            max_acquisition_evaluations=600,
            rng_seed=5,
            num_seed_trials=2,
            config=UCBPEConfig(
                multitask_type=mtgp.MultiTaskType.SEPARABLE,
                num_scalarizations=16,
            ),
        )

    def _mt_trials(self, n=10, seed=0):
        rng = np.random.default_rng(seed)
        trials = []
        for i in range(n):
            x, y = rng.uniform(), rng.uniform()
            base = -((x - 0.62) ** 2) - (y - 0.31) ** 2
            t = vz.Trial(id=i + 1, parameters={"x": float(x), "y": float(y)})
            t.complete(
                vz.Measurement(
                    metrics={"m1": base, "m2": 0.8 * base + 0.01 * rng.normal()}
                )
            )
            trials.append(t)
        return trials

    def test_separable_suggests_on_mesh(self):
        from vizier_tpu.models import multitask_gp as mtgp

        d = self._mt_designer(use_mesh=True)
        assert d._mesh is not None and len(d._mesh.devices.flat) == 8
        d.update(core_lib.CompletedTrials(self._mt_trials()))
        suggestions = d.suggest(3)
        assert len(suggestions) == 3
        states, _ = d._train_states_me()
        assert isinstance(states, mtgp.MultiTaskGPState)
        for s in suggestions:
            for name in ("x", "y"):
                assert 0.0 <= float(s.parameters[name].value) <= 1.0

    def test_sharded_joint_train_matches_unsharded(self):
        """The mesh branch of ``_train_states_me`` (which routes through
        ``parallel.train_gp_sharded`` on the duck-typed multitask model) must
        produce the same fit as the unsharded trainer given the same rng."""
        from vizier_tpu.models import multitask_gp as mtgp

        meshed = self._mt_designer(use_mesh=True)
        meshed.update(core_lib.CompletedTrials(self._mt_trials()))
        states_sharded, _ = meshed._train_states_me()
        assert isinstance(states_sharded, mtgp.MultiTaskGPState)

        # Rebuild the same joint data and rng stream unsharded.
        unsharded = self._mt_designer(use_mesh=False)
        unsharded.update(core_lib.CompletedTrials(self._mt_trials()))
        states_plain, _ = unsharded._train_states_me()

        # Same seed + same restart count (8 rounds up to 8) -> same selected
        # hyperparameters up to float reduction order.
        for k in states_plain.params:
            np.testing.assert_allclose(
                np.asarray(states_sharded.params[k]),
                np.asarray(states_plain.params[k]),
                rtol=0.1,
                atol=0.05,
                err_msg=f"param {k} diverged between sharded/unsharded",
            )


class TestShardedQEI:
    """The joint-batch qEI sweep on the mesh (round-5): pool-sharded
    search of (q*D)-space must return valid batches, and the top-k merge
    must equal the best over its own per-key pools."""

    def _designer(self, use_mesh):
        return VizierGPBandit(
            _problem(),
            use_mesh=use_mesh,
            rng_seed=5,
            ard_restarts=2,
            ard_optimizer=lbfgs_lib.LbfgsOptimizer(maxiter=10),
            max_acquisition_evaluations=300,
            acquisition="qei",
            num_seed_trials=2,
        )

    def test_mesh_qei_batch_valid_and_distinct(self):
        d = self._designer(use_mesh=True)
        assert d._mesh is not None
        pts = _suggest_xy(d, count=3)
        assert pts.shape == (3, 2)
        assert np.all((0.0 <= pts) & (pts <= 1.0))
        # The joint posterior penalizes duplicated batch members; the three
        # suggestions should not collapse onto one point.
        assert np.unique(np.round(pts, 3), axis=0).shape[0] > 1

    def test_mesh_qei_merge_is_best_over_pools(self):
        """Deterministic merge property of the mechanism qEI rides: with a
        closure score_fn over flattened (q*D)-space (no MC randomness),
        the sharded result equals the argmax over its per-key pools."""
        import jax.numpy as jnp

        from vizier_tpu import parallel
        from vizier_tpu.optimizers import eagle as eagle_lib
        from vizier_tpu.optimizers import vectorized as vectorized_lib

        q, dc = 2, 2
        target = jnp.asarray([0.2, 0.8, 0.7, 0.3])  # one optimum per slot

        def score_fn(feats):
            return -jnp.sum((feats.continuous - target) ** 2, axis=-1)

        strategy = eagle_lib.VectorizedEagleStrategy(
            num_continuous=q * dc, category_sizes=()
        )
        vec = vectorized_lib.VectorizedOptimizer(strategy, max_evaluations=300)
        mesh = parallel.create_mesh()
        n_pools = len(mesh.devices.flat)
        key = jax.random.PRNGKey(9)
        sharded = parallel.maximize_score_fn_sharded(
            vec, score_fn, key, count=1, num_pools=n_pools, mesh=mesh
        )
        pool_best = [
            float(vec(score_fn, jnp.asarray(k), count=1).scores[0])
            for k in np.asarray(jax.random.split(key, n_pools))
        ]
        np.testing.assert_allclose(
            float(sharded.scores[0]), max(pool_best), rtol=1e-5
        )
        # And the merged optimum is near the planted target.
        np.testing.assert_allclose(
            np.asarray(sharded.features.continuous[0]), np.asarray(target),
            atol=0.1,
        )
