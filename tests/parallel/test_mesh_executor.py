"""Mesh execution plane: placements, scheduling, parity, fail isolation.

Runs on the suite's virtual 8-device CPU mesh (tests/conftest.py sets
``--xla_force_host_platform_device_count=8``). The contracts pinned here:

- ``VIZIER_MESH=0`` / ``MeshConfig()`` never builds placements — the
  executor is the bit-identical single-device seed path;
- a mesh of size 1 serves suggestions bit-identical to the single-device
  executor, and an 8-device sharded flush is slot-by-slot bit-identical
  to the sequential path;
- buckets are sticky-assigned across placements and execute on
  per-placement workers concurrently;
- a device-program failure on ONE placement degrades only that flush's
  slots (sequential fallback / isolated errors) while other placements
  keep serving.
"""

import threading
import time

import numpy as np
import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.designers.gp_ucb_pe import VizierGPUCBPEBandit
from vizier_tpu.optimizers import lbfgs as lbfgs_lib
from vizier_tpu.parallel.batch_executor import BatchExecutor
from vizier_tpu.parallel.mesh import DevicePlacement, MeshConfig, build_placements
from vizier_tpu.serving.stats import ServingStats
from vizier_tpu.testing import chaos as chaos_lib

from tests.parallel.test_batch_executor import (  # noqa: F401  (shared idioms)
    StubDesigner,
    _run_concurrent,
)

_FAST = dict(
    ard_optimizer=lbfgs_lib.AdamOptimizer(maxiter=15),
    ard_restarts=3,
    max_acquisition_evaluations=200,
    warm_start_min_trials=0,
)


def _problem():
    p = vz.ProblemStatement()
    for d in range(2):
        p.search_space.root.add_float_param(f"x{d}", 0.0, 1.0)
    p.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return p


def _designer(seed, n=5, **overrides):
    kwargs = dict(_FAST, **overrides)
    d = VizierGPUCBPEBandit(_problem(), rng_seed=seed, **kwargs)
    rng = np.random.default_rng(seed)
    trials = []
    for i in range(n):
        t = vz.Trial(
            parameters={"x0": float(rng.uniform()), "x1": float(rng.uniform())},
            id=i + 1,
        )
        t.complete(vz.Measurement(metrics={"obj": float(rng.uniform())}))
        trials.append(t)
    d.update(core_lib.CompletedTrials(trials))
    return d


def _params(suggestions):
    return [s.parameters.as_dict() for s in suggestions]


class TestMeshConfigAndPlacements:
    def test_default_config_is_off(self):
        config = MeshConfig.from_env()
        assert not config.enabled

    def test_executor_without_mesh_has_no_placements(self):
        ex = BatchExecutor(mesh=MeshConfig())  # enabled=False
        assert not ex.mesh_enabled
        assert ex.placements() == []
        ex.close()

    def test_build_placements_shard_groups(self):
        ones = build_placements(MeshConfig(enabled=True, shard_devices=1))
        assert len(ones) == 8
        assert all(p.num_devices == 1 for p in ones)
        pairs = build_placements(MeshConfig(enabled=True, shard_devices=2))
        assert len(pairs) == 4
        assert all(p.num_devices == 2 for p in pairs)
        whole = build_placements(MeshConfig(enabled=True, shard_devices=8))
        assert len(whole) == 1 and whole[0].num_devices == 8
        capped = build_placements(
            MeshConfig(enabled=True, num_devices=4, shard_devices=2)
        )
        assert len(capped) == 2
        # Devices are disjoint across placements.
        seen = [d.id for p in pairs for d in p.devices]
        assert len(seen) == len(set(seen))

    def test_multihost_carve_prefers_process_local_groups(self):
        from vizier_tpu.parallel.mesh import _carve_device_groups

        class FakeDevice:
            def __init__(self, device_id, process_index):
                self.id = device_id
                self.process_index = process_index

        # 2 hosts x 4 devices, divisible shard count: every group stays
        # on one host (the flat slice would already do this — sanity).
        devices = [FakeDevice(i, i // 4) for i in range(8)]
        groups = _carve_device_groups(devices, 2)
        assert len(groups) == 4
        for group in groups:
            assert len({d.process_index for d in group}) == 1
        # Non-divisible shard count: the old flat slice produced [0,1,2]
        # and [3,4,5] — the second group SPANS hosts. Process-local
        # carving keeps one full group per host; the per-host remainders
        # (3 and 7) pool to fewer than s and are dropped, like any
        # trailing remainder.
        groups = _carve_device_groups(devices, 3)
        assert [[d.id for d in g] for g in groups] == [[0, 1, 2], [4, 5, 6]]
        for group in groups:
            assert len({d.process_index for d in group}) == 1
        # Remainders still pool into a (necessarily) cross-host group when
        # they add up to a full shard group: 2 hosts x 3 devices at s=2
        # gives one local pair per host plus the pooled [2, 5].
        tight = [FakeDevice(i, i // 3) for i in range(6)]
        groups = _carve_device_groups(tight, 2)
        assert [[d.id for d in g] for g in groups] == [[0, 1], [3, 4], [2, 5]]
        # Single-host meshes are untouched by the preference: same carve
        # as the flat slice.
        single = [FakeDevice(i, 0) for i in range(8)]
        assert [[d.id for d in g] for g in _carve_device_groups(single, 2)] == [
            [0, 1], [2, 3], [4, 5], [6, 7],
        ]

    def test_pad_to_shard_granularity(self):
        import jax

        p1 = DevicePlacement(0, jax.devices()[:1])
        assert [p1.pad_to(o, 8) for o in (1, 2, 3, 4, 5, 8)] == [1, 2, 4, 4, 8, 8]
        assert p1.pad_grid(8) == [1, 2, 4, 8]
        p4 = DevicePlacement(0, jax.devices()[:4])
        assert [p4.pad_to(o, 8) for o in (1, 4, 5, 8)] == [4, 4, 8, 8]
        assert p4.pad_grid(8) == [4, 8]
        # Padded batches always divide by the device count and cover the
        # occupancy.
        p3 = DevicePlacement(0, jax.devices()[:3])
        for occupancy in range(1, 9):
            padded = p3.pad_to(occupancy, 8)
            assert padded >= occupancy and padded % 3 == 0


class TestMeshScheduling:
    def test_distinct_buckets_spread_and_stick(self):
        ex = BatchExecutor(
            max_batch_size=8,
            max_wait_ms=5.0,
            mesh=MeshConfig(enabled=True, shard_devices=1),
        )
        try:
            groups = [
                [StubDesigner(10 * g + c, group=f"g{g}") for c in range(2)]
                for g in range(4)
            ]
            for _ in range(2):  # two rounds: assignments must not move
                flat = [d for group in groups for d in group]
                results, errors = _run_concurrent(ex, flat)
                assert all(e is None for e in errors), errors
                assert all(r for r in results)
            placements = ex.bucket_placements()["stub/t8/f1x0/m1/q1"]
            # 4 distinct buckets spread over 4 distinct placements
            # (least-loaded assignment never doubles up before all 8
            # placements hold a bucket).
            assert len(placements) == 4
            assert len(set(placements)) == 4
            flushes = ex.placement_flush_counts()
            assert sum(flushes.values()) >= 4
        finally:
            ex.close()

    def test_worker_threads_execute_flushes(self):
        ex = BatchExecutor(
            max_batch_size=4,
            max_wait_ms=5.0,
            mesh=MeshConfig(enabled=True, shard_devices=1),
        )
        try:
            seen_threads = set()

            class Recorder(StubDesigner):
                def batch_execute(self, items, pad_to=None):
                    seen_threads.add(threading.current_thread().name)
                    return super().batch_execute(items, pad_to=pad_to)

            results, errors = _run_concurrent(
                ex, [Recorder(i) for i in range(4)]
            )
            assert all(e is None for e in errors)
            assert seen_threads and all(
                name.startswith("vizier-mesh-worker-") for name in seen_threads
            )
        finally:
            ex.close()

    def test_close_drains_mesh_queues(self):
        ex = BatchExecutor(
            max_batch_size=8,
            max_wait_ms=10_000,  # nothing flushes on its own
            mesh=MeshConfig(enabled=True, shard_devices=1),
        )
        designers = [StubDesigner(i) for i in range(3)]
        results = [None] * 3

        def run(i):
            results[i] = ex.suggest(designers[i], 1)

        threads = [threading.Thread(target=run, args=(i,)) for i in range(3)]
        for t in threads:
            t.start()
        for _ in range(400):
            if ex.queue_depth()["live"] == 3:
                break
            time.sleep(0.005)
        ex.close()  # drain through the workers
        for t in threads:
            t.join(timeout=60)
        assert all(r for r in results)


class TestMeshParity:
    """Slot values must not depend on the execution plane."""

    def test_mesh_size_1_bit_identical_to_single_device(self):
        seeds = (21, 22, 23)
        single = BatchExecutor(max_batch_size=8, max_wait_ms=60.0)
        mesh1 = BatchExecutor(
            max_batch_size=8,
            max_wait_ms=60.0,
            mesh=MeshConfig(enabled=True, num_devices=1),
        )
        try:
            ref, errors = _run_concurrent(
                single, [_designer(s) for s in seeds]
            )
            assert all(e is None for e in errors)
            out, errors = _run_concurrent(mesh1, [_designer(s) for s in seeds])
            assert all(e is None for e in errors)
            assert len(mesh1.placements()) == 1
            for r, o in zip(ref, out):
                assert _params(r) == _params(o)  # bitwise, not approx
        finally:
            single.close()
            mesh1.close()

    def test_sharded_flush_slot_parity_at_mesh_8(self):
        seeds = tuple(range(31, 39))
        sequential = [_designer(s).suggest(1) for s in seeds]
        ex = BatchExecutor(
            max_batch_size=8,
            max_wait_ms=120.0,
            mesh=MeshConfig(enabled=True, shard_devices=8),
        )
        try:
            results, errors = _run_concurrent(
                ex, [_designer(s) for s in seeds]
            )
            assert all(e is None for e in errors)
            (placement,) = ex.placements()
            assert placement.num_devices == 8
            for seq, out in zip(sequential, results):
                assert _params(seq) == _params(out)  # bitwise slot parity
        finally:
            ex.close()

    def test_mesh_off_config_is_seed_executor(self):
        # MeshConfig.from_env() with VIZIER_MESH unset must change nothing
        # observable: same slot values as an executor built without mesh.
        seeds = (41, 42)
        plain = BatchExecutor(max_batch_size=8, max_wait_ms=60.0)
        from_env = BatchExecutor(
            max_batch_size=8, max_wait_ms=60.0, mesh=MeshConfig.from_env()
        )
        try:
            assert not from_env.mesh_enabled
            ref, _ = _run_concurrent(plain, [_designer(s) for s in seeds])
            out, _ = _run_concurrent(from_env, [_designer(s) for s in seeds])
            for r, o in zip(ref, out):
                assert _params(r) == _params(o)
        finally:
            plain.close()
            from_env.close()


class TestMeshChaosIsolation:
    def test_device_failure_on_one_placement_isolated(self):
        # Two distinct buckets -> two placements. Bucket A's device
        # program is chaos-poisoned: its slots recover through their own
        # sequential runs (the chaos designer's plain suggest also strikes
        # -> ITS slot errors; the healthy same-bucket slot succeeds).
        # Bucket B, on ANOTHER placement, is untouched and stays batched.
        monkey = chaos_lib.ChaosMonkey(seed=0, failure_prob=1.0)
        chaotic = chaos_lib.ChaosDesigner(_designer(51), monkey)
        chaotic.batch_prepare = chaotic._inner.batch_prepare  # reach execute
        mate = _designer(52)
        other_bucket = [
            _designer(s, max_acquisition_evaluations=208) for s in (53, 54)
        ]
        other_sequential = [
            _designer(s, max_acquisition_evaluations=208).suggest(1)
            for s in (53, 54)
        ]
        stats = ServingStats()
        ex = BatchExecutor(
            max_batch_size=2,
            max_wait_ms=10_000,
            stats=stats,
            mesh=MeshConfig(enabled=True, shard_devices=1),
        )
        try:
            results = [None] * 4
            errors = [None] * 4

            def run(i, designer):
                try:
                    results[i] = ex.suggest(designer, 1)
                except BaseException as e:  # noqa: BLE001
                    errors[i] = e

            # The chaos designer must arrive first so the poisoned bucket's
            # flush dispatches through ITS device program.
            t0 = threading.Thread(target=run, args=(0, chaotic))
            t0.start()
            for _ in range(400):
                if ex.pending_counts():
                    break
                time.sleep(0.005)
            rest = [
                threading.Thread(target=run, args=(i, d))
                for i, d in ((1, mate), (2, other_bucket[0]), (3, other_bucket[1]))
            ]
            for t in rest:
                t.start()
            t0.join(timeout=120)
            for t in rest:
                t.join(timeout=120)

            assert isinstance(
                errors[0], chaos_lib.failing.FailedSuggestError
            )
            assert errors[1] is None and results[1]
            assert errors[2] is None and errors[3] is None
            for seq, out in zip(other_sequential, (results[2], results[3])):
                assert _params(seq) == _params(out)
            snap = stats.snapshot()
            assert snap["batch_fallbacks"] == 2  # only the poisoned flush
            assert snap["mesh_flushes"] >= 2
            # Both buckets really lived on different placements.
            assignments = ex.bucket_placements()
            placements = {p for ps in assignments.values() for p in ps}
            assert len(placements) == 2
        finally:
            ex.close()


class TestMeshServingIntegration:
    def test_runtime_threads_mesh_config(self):
        from vizier_tpu.serving import runtime as runtime_lib

        rt = runtime_lib.ServingRuntime(
            mesh=MeshConfig(enabled=True, num_devices=2)
        )
        try:
            assert rt.batch_executor is not None
            assert rt.batch_executor.mesh_enabled
            assert len(rt.batch_executor.placements()) == 2
        finally:
            rt.shutdown()

    def test_runtime_default_env_is_single_device(self):
        from vizier_tpu.serving import runtime as runtime_lib

        rt = runtime_lib.ServingRuntime()
        try:
            assert rt.batch_executor is not None
            assert not rt.batch_executor.mesh_enabled
        finally:
            rt.shutdown()

    def test_pythia_servicer_threads_mesh_config(self):
        from vizier_tpu.service import pythia_service

        servicer = pythia_service.PythiaServicer(
            mesh_config=MeshConfig(enabled=True, num_devices=2)
        )
        try:
            executor = servicer.serving_runtime.batch_executor
            assert executor is not None and executor.mesh_enabled
        finally:
            servicer.shutdown()
