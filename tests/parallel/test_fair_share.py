"""Lane-fairness invariants for the N-lane batch-executor scheduler.

Pins the PR's fairness guarantees at the selection layer (no device
programs needed): the deficit-round-robin starvation bound (a
continuously-hot tenant cannot delay a light tenant's first slot by more
than its configured quantum), weighted long-run shares, FIFO bit-identity
with the admission plane off, the N-lane generalization of the old
two-lane live/speculative scheduler, and cross-bucket ordering by
weighted served-slot credit.
"""

import threading

import pytest

from vizier_tpu.compute import ir as compute_ir
from vizier_tpu.parallel import batch_executor as be
from vizier_tpu.serving import admission as adm


def controller(weights=()):
    return adm.AdmissionController(
        adm.AdmissionConfig(enabled=True, weights=tuple(weights))
    )


def slot(tenant=None, at=0.0, lane=be.LANE_LIVE):
    return be._Slot(None, None, 1, at, None, lane=lane, tenant=tenant)


def bucket_key(tag):
    return compute_ir.BucketKey(
        kind="t", pad_trials=8, cont_width=2, cat_width=0, metric_count=1,
        count=1, statics=(("tag", tag),),
    )


class TestFairOrder:
    def test_starvation_bound_light_within_one_round(self):
        """A continuously-hot tenant (weight w) cannot push a light
        tenant's queued slot past position w: one DRR round serves it."""
        ex = be.BatchExecutor(
            max_batch_size=4, admission=controller([("hot", 4.0)])
        )
        slots = [slot("hot", i) for i in range(12)] + [slot("light", 99)]
        with ex._cond:
            ordered = ex._fair_order(list(slots))
        position = [s.tenant for s in ordered].index("light")
        assert position <= 4
        ex.close()

    def test_weighted_shares(self):
        ex = be.BatchExecutor(
            max_batch_size=8,
            admission=controller([("a", 3.0), ("b", 1.0)]),
        )
        slots = [slot("a", i) for i in range(12)] + [
            slot("b", 100 + i) for i in range(12)
        ]
        with ex._cond:
            ordered = ex._fair_order(list(slots))
        first8 = [s.tenant for s in ordered[:8]]
        assert first8.count("a") == 6
        assert first8.count("b") == 2
        ex.close()

    def test_fifo_within_tenant(self):
        ex = be.BatchExecutor(
            max_batch_size=4, admission=controller([("a", 2.0)])
        )
        slots = [slot("a", i) for i in range(4)] + [slot("b", 10)]
        with ex._cond:
            ordered = ex._fair_order(list(slots))
        a_times = [s.enqueued_at for s in ordered if s.tenant == "a"]
        assert a_times == sorted(a_times)
        ex.close()

    def test_single_tenant_is_fifo(self):
        ex = be.BatchExecutor(max_batch_size=4, admission=controller())
        slots = [slot("a", i) for i in range(6)]
        with ex._cond:
            assert ex._fair_order(list(slots)) == slots
        ex.close()

    def test_ring_remembers_tenants_across_flushes(self):
        """DRR state is persistent: the ring keeps every tenant ever
        seen and the cursor advances, so rotation is fair across flushes
        rather than restarting at the same tenant when rounds end
        mid-ring."""
        ex = be.BatchExecutor(max_batch_size=2, admission=controller())
        with ex._cond:
            ex._fair_order([slot("a", 0), slot("b", 1)])
            assert ex._drr_ring == ["a", "b"]
            # An uneven round (only c present) advances the cursor past
            # the absent tenants without banking them credit.
            ex._fair_order([slot("c", 0), slot("a", 1)])
            assert set(ex._drr_ring) == {"a", "b", "c"}
            # An absent tenant banks no credit for later rounds.
            assert ex._drr_deficit.get("b", 0.0) == 0.0
        ex.close()


class TestTakeDueFairness:
    def _executor(self, weights=(), admission="on"):
        ctl = controller(weights) if admission == "on" else None
        clock = [1000.0]
        ex = be.BatchExecutor(
            max_batch_size=4,
            max_wait_ms=4.0,
            admission=ctl,
            time_fn=lambda: clock[0],
        )
        ex._clock = clock
        return ex

    def test_full_bucket_chunks_follow_drr(self):
        ex = self._executor(weights=[("hot", 2.0)])
        key = bucket_key("x")
        with ex._cond:
            ex._queues[key] = [slot("hot", i) for i in range(7)] + [
                slot("light", 50)
            ]
            due = ex._take_due()
        assert len(due) == 2  # one "full" chunk + the timeout remainder
        first_chunk = [s.tenant for s in due[0][1]]
        # DRR (hot quantum 2): light rides the FIRST flush despite seven
        # hot slots queued ahead of it in FIFO order.
        assert "light" in first_chunk
        ex.close()

    def test_fifo_bit_identity_with_admission_off(self):
        """No controller -> selection is exactly the seed FIFO prefix."""
        ex = self._executor(admission="off")
        key = bucket_key("x")
        ordered_in = [slot("hot", i) for i in range(7)] + [slot("light", 50)]
        with ex._cond:
            ex._queues[key] = list(ordered_in)
            due = ex._take_due()
        assert due[0][1] == ordered_in[:4]
        assert due[0][2] == "full"
        ex.close()

    def test_cross_bucket_order_prefers_underserved_tenant(self):
        ex = self._executor(weights=[("hot", 1.0), ("light", 1.0)])
        hot_key, light_key = bucket_key("hot"), bucket_key("light")
        with ex._cond:
            # Bill the hot tenant with prior served slots.
            ex._tenant_served["hot"] = 50.0
            ex._queues[hot_key] = [slot("hot", i) for i in range(4)]
            ex._queues[light_key] = [slot("light", i) for i in range(4)]
            due = ex._take_due()
        assert [slots[0].tenant for _k, slots, _r in due] == ["light", "hot"]
        ex.close()

    def test_timeout_uses_true_oldest_after_reorder(self):
        """A DRR-reordered remainder still times out by its OLDEST slot's
        enqueue time, not whatever landed at position 0."""
        ex = self._executor(weights=[("hot", 4.0)])
        key = bucket_key("x")
        ex._clock[0] = 1000.002
        with ex._cond:
            # 6 slots: the full chunk takes hot0..3 (quantum 4); the DRR
            # remainder is [light (newest), hot4 (older)] — no longer FIFO.
            ex._queues[key] = [
                slot("hot", 1000.0 + i * 0.0001) for i in range(5)
            ] + [slot("light", 1000.001)]
            due = ex._take_due()
            assert due and due[0][2] == "full"
            remainder = list(ex._queues[key])
        assert [s.tenant for s in remainder] == ["light", "hot"]
        assert remainder[0].enqueued_at > remainder[1].enqueued_at
        # Position 0 (light) is NOT yet past the window, but the true
        # oldest (hot4) is: the bucket must flush.
        ex._clock[0] = 1000.0049
        with ex._cond:
            due = ex._take_due()
        assert due and due[0][2] == "timeout"
        ex.close()


class TestLanes:
    def test_default_lane_table_matches_two_lane_contract(self):
        lanes = be.default_lanes(250.0)
        by_name = {lane.name: lane for lane in lanes}
        assert by_name[be.LANE_LIVE].priority < by_name[
            be.LANE_SPECULATIVE
        ].priority
        assert not by_name[be.LANE_LIVE].deferrable
        assert by_name[be.LANE_SPECULATIVE].deferrable
        assert by_name[be.LANE_SPECULATIVE].starvation_cap_ms == 250.0

    def test_slot_lane_back_compat(self):
        live = slot()
        spec = slot(lane=be.LANE_SPECULATIVE)
        assert not live.speculative
        assert spec.speculative

    def test_deferrable_lane_waits_for_idle_window(self):
        clock = [0.0]
        ex = be.BatchExecutor(
            max_batch_size=4,
            max_wait_ms=4.0,
            speculative_max_wait_ms=250.0,
            time_fn=lambda: clock[0],
        )
        live_key, spec_key = bucket_key("live"), bucket_key("spec")
        with ex._cond:
            ex._queues[spec_key] = [slot(lane=be.LANE_SPECULATIVE, at=0.0)]
            ex._queues[live_key] = [slot(at=0.0)]
            clock[0] = 0.01  # past the live window, not the starvation cap
            due = ex._take_due()
            names = [key for key, _s, _r in due]
            assert names == [live_key]  # spec bucket deferred
            # Fresh live traffic keeps the spec bucket deferring until the
            # starvation cap fires...
            ex._queues[live_key] = [slot(at=0.299)]
            clock[0] = 0.3  # past the cap for the spec slot
            due = ex._take_due()
            assert [r for _k, _s, r in due] == ["spec_starved"]
            # ... while with NO priority traffic queued, the idle window
            # opens and the ordinary flush rules apply (reason timeout).
            ex._queues[spec_key] = [slot(lane=be.LANE_SPECULATIVE, at=0.3)]
            ex._queues.pop(live_key, None)
            clock[0] = 0.31
            due = ex._take_due()
        assert [r for _k, _s, r in due] == ["timeout"]
        ex.close()

    def test_third_lane_slots_order_after_live(self):
        """The N-lane generalization: a custom lane between live and
        speculative orders by priority with no scheduler edits."""
        lanes = (
            be.LaneSpec("live", priority=0),
            be.LaneSpec("batchwork", priority=1, deferrable=True,
                        starvation_cap_ms=100.0),
            be.LaneSpec("speculative", priority=2, deferrable=True,
                        starvation_cap_ms=250.0),
        )
        clock = [0.0]
        ex = be.BatchExecutor(
            max_batch_size=4, max_wait_ms=4.0, lanes=lanes,
            time_fn=lambda: clock[0],
        )
        keys = {name: bucket_key(name) for name in ("live", "mid", "spec")}
        with ex._cond:
            ex._queues[keys["spec"]] = [slot(lane="speculative", at=0.0)]
            ex._queues[keys["mid"]] = [slot(lane="batchwork", at=0.0)]
            ex._queues[keys["live"]] = [slot(at=0.0)]
            clock[0] = 0.5  # everything past every cap
            due = ex._take_due()
        assert [key for key, _s, _r in due] == [
            keys["live"], keys["mid"], keys["spec"]
        ]
        ex.close()

    def test_queue_depth_reports_all_lanes(self):
        lanes = (
            be.LaneSpec("live", priority=0),
            be.LaneSpec("bulk", priority=1, deferrable=True),
        )
        ex = be.BatchExecutor(max_batch_size=4, lanes=lanes)
        with ex._cond:
            ex._queues[bucket_key("a")] = [slot(), slot(lane="bulk")]
        assert ex.queue_depth() == {"live": 1, "bulk": 1}
        assert ex.live_pending() == 1
        ex.close()


class TestEndToEndFairness:
    def test_concurrent_submissions_carry_admission_tenant(self):
        """suggest() reads the admission contextvar on the submitting
        thread: slots carry the tenant the gate admitted."""

        class FakeProgram:
            def prepare(self, designer, count):
                return {}

        class FakeDesigner:
            def suggest(self, count):
                return [object() for _ in range(count)]

        ctl = controller([("a", 2.0)])
        ex = be.BatchExecutor(max_batch_size=4, admission=ctl)
        seen = {}
        original = be.compute_registry.resolve

        def fake_resolve(designer, count):
            return FakeProgram(), bucket_key("e2e")

        be.compute_registry.resolve = fake_resolve
        try:
            barrier = threading.Barrier(2)

            def submit(tenant):
                decision = ctl.decide(tenant)
                with ctl.in_flight(decision):
                    barrier.wait(timeout=5)
                    ex.suggest(FakeDesigner(), 1)

            threads = [
                threading.Thread(target=submit, args=(t,))
                for t in ("a", "b")
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=10)
            # The flush ran; DRR billed both tenants.
            with ex._cond:
                seen = dict(ex._tenant_served)
        finally:
            be.compute_registry.resolve = original
            ex.close()
        assert set(seen) == {"a", "b"}

    def test_no_admission_no_tenant_lookup(self):
        ex = be.BatchExecutor(max_batch_size=4)
        with adm.tenant_scope("ambient"):
            s = slot()
        assert s.tenant is None  # _Slot default; suggest() skips the read
        ex.close()
