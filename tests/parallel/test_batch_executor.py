"""Cross-study batch executor: bucketing, parity, masking, fail isolation."""

import threading
import time

import numpy as np
import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.algorithms import core as core_lib
from vizier_tpu.designers.gp_bandit import VizierGPBandit
from vizier_tpu.designers.gp_ucb_pe import VizierGPUCBPEBandit
from vizier_tpu.optimizers import lbfgs as lbfgs_lib
from vizier_tpu.parallel.batch_executor import (
    BatchExecutor,
    BatchSlotError,
    BucketKey,
)
from vizier_tpu.serving.stats import ServingStats
from vizier_tpu.testing import chaos as chaos_lib

_FAST = dict(
    ard_optimizer=lbfgs_lib.AdamOptimizer(maxiter=15),
    ard_restarts=3,
    max_acquisition_evaluations=200,
    # Parity tests feed ~5 trials and assert warm-state writeback; keep
    # warm seeding engaged below the production floor.
    warm_start_min_trials=0,
)


def _problem(num_params=2, num_metrics=1):
    p = vz.ProblemStatement()
    for d in range(num_params):
        p.search_space.root.add_float_param(f"x{d}", 0.0, 1.0)
    for m in range(num_metrics):
        p.metric_information.append(
            vz.MetricInformation(
                name=f"obj{m}" if num_metrics > 1 else "obj",
                goal=vz.ObjectiveMetricGoal.MAXIMIZE,
            )
        )
    return p


def _feed(designer, seed, n=5, num_metrics=1):
    rng = np.random.default_rng(seed)
    trials = []
    for i in range(n):
        t = vz.Trial(
            parameters={"x0": float(rng.uniform()), "x1": float(rng.uniform())},
            id=i + 1,
        )
        names = ["obj"] if num_metrics == 1 else [f"obj{m}" for m in range(num_metrics)]
        t.complete(
            vz.Measurement(metrics={nm: float(rng.uniform()) for nm in names})
        )
        trials.append(t)
    designer.update(core_lib.CompletedTrials(trials))
    return designer


def _gp_bandit(seed):
    return VizierGPBandit(_problem(), rng_seed=seed, **_FAST)


def _gp_ucb_pe(seed):
    return VizierGPUCBPEBandit(_problem(), rng_seed=seed, **_FAST)


def _params(suggestions):
    return [s.parameters.as_dict() for s in suggestions]


def _assert_params_equal(a, b, atol=1e-6):
    assert len(a) == len(b)
    for pa, pb in zip(a, b):
        assert pa.keys() == pb.keys()
        for k in pa:
            assert abs(pa[k] - pb[k]) <= atol, (k, pa[k], pb[k])


# -- a designer-shaped stub for executor mechanics (no GP cost) -------------


def _stub_suggestion(value):
    return vz.TrialSuggestion(parameters={"x": float(value)})


class StubDesigner:
    """Implements the batch protocol with trivial arithmetic."""

    def __init__(self, value, group="g", batchable=True):
        self.value = value
        self.group = group
        self.batchable = batchable
        self.sequential_calls = 0
        self.batched = False

    def suggest(self, count=1):
        self.sequential_calls += 1
        return [_stub_suggestion(self.value)] * (count or 1)

    def batch_bucket_key(self, count=1):
        if not self.batchable:
            return None
        return BucketKey(
            kind="stub",
            pad_trials=8,
            cont_width=1,
            cat_width=0,
            metric_count=1,
            count=count or 1,
            statics=(self.group,),
        )

    def batch_prepare(self, count=1):
        return dict(designer=self, count=count or 1, value=self.value)

    def batch_execute(self, items, pad_to=None):
        return [dict(value=item["value"]) for item in items]

    def batch_finalize(self, item, output):
        self.batched = True
        return [_stub_suggestion(output["value"])] * item["count"]


class FailPrepareStub(StubDesigner):
    def batch_prepare(self, count=1):
        raise RuntimeError("prepare exploded")


class FailExecuteStub(StubDesigner):
    def batch_execute(self, items, pad_to=None):
        raise RuntimeError("device program exploded")


class NanStub(StubDesigner):
    def batch_finalize(self, item, output):
        return [_stub_suggestion(float("nan"))]


def _run_concurrent(executor, designers, count=1):
    results = [None] * len(designers)
    errors = [None] * len(designers)

    def run(i):
        try:
            results[i] = executor.suggest(designers[i], count)
        except BaseException as e:  # noqa: BLE001 - tests inspect the error
            errors[i] = e

    threads = [
        threading.Thread(target=run, args=(i,)) for i in range(len(designers))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    return results, errors


class TestBucketKeys:
    def test_seeding_stage_unbatchable(self):
        d = _gp_bandit(0)  # no trials yet: quasi-random seeding path
        assert d.batch_bucket_key(1) is None

    def test_multiobjective_unbatchable(self):
        d = VizierGPBandit(_problem(num_metrics=2), rng_seed=0, **_FAST)
        _feed(d, 0, num_metrics=2)
        assert d.batch_bucket_key(1) is None

    def test_priors_unbatchable(self):
        d = _feed(_gp_bandit(0), 0)
        d.set_priors([])
        assert d.batch_bucket_key(1) is not None  # empty priors list is falsy
        d.set_priors([[t for t in d._trials]])
        assert d.batch_bucket_key(1) is None

    def test_same_config_same_bucket(self):
        a, b = _feed(_gp_bandit(1), 1), _feed(_gp_bandit(2), 2)
        assert a.batch_bucket_key(1) == b.batch_bucket_key(1)

    def test_different_shape_different_bucket(self):
        a = _feed(_gp_bandit(1), 1, n=5)  # pad bucket 8
        b = _feed(_gp_bandit(2), 2, n=9)  # pad bucket 16
        assert a.batch_bucket_key(1) != b.batch_bucket_key(1)

    def test_ucb_pe_cached_fit_unbatchable(self):
        d = _feed(_gp_ucb_pe(3), 3, n=4)
        assert d.batch_bucket_key(1) is not None
        d.suggest(1)  # populates the cached fit
        assert d.batch_bucket_key(1) is None


class TestExecutorMechanics:
    def test_full_flush_batches_and_demuxes(self):
        stats = ServingStats()
        ex = BatchExecutor(
            max_batch_size=3, max_wait_ms=5000, stats=stats,
            metrics=stats.registry,
        )
        try:
            designers = [StubDesigner(v) for v in (0.1, 0.2, 0.3)]
            results, errors = _run_concurrent(ex, designers)
            assert errors == [None, None, None]
            for d, r in zip(designers, results):
                assert r[0].parameters.as_dict()["x"] == pytest.approx(d.value)
                assert d.batched and d.sequential_calls == 0
            snap = stats.snapshot()
            assert snap["batch_flushes"] == 1
            assert snap["batched_suggests"] == 3
            text = stats.registry.prometheus_text()
            assert "vizier_batch_occupancy" in text
            assert 'reason="full"' in text
        finally:
            ex.close()

    def test_timeout_flush_singleton_takes_sequential_path(self):
        stats = ServingStats()
        ex = BatchExecutor(max_batch_size=8, max_wait_ms=10, stats=stats)
        try:
            d = StubDesigner(0.7)
            out = ex.suggest(d, 1)
            assert out[0].parameters.as_dict()["x"] == pytest.approx(0.7)
            # A batch of one is the plain per-study path: bit-identical to
            # batching off, no vmap overhead.
            assert d.sequential_calls == 1 and not d.batched
            assert stats.snapshot()["batch_flushes"] == 1
        finally:
            ex.close()

    def test_unbatchable_runs_inline(self):
        ex = BatchExecutor(max_batch_size=4, max_wait_ms=5000)
        try:
            d = StubDesigner(0.4, batchable=False)
            out = ex.suggest(d, 2)
            assert len(out) == 2 and d.sequential_calls == 1
        finally:
            ex.close()

    def test_different_groups_do_not_batch(self):
        ex = BatchExecutor(max_batch_size=2, max_wait_ms=50)
        try:
            a, b = StubDesigner(0.1, group="g1"), StubDesigner(0.2, group="g2")
            results, errors = _run_concurrent(ex, [a, b])
            assert errors == [None, None]
            # Each bucket flushed alone (timeout), hence sequentially.
            assert a.sequential_calls == 1 and b.sequential_calls == 1
        finally:
            ex.close()

    def test_prepare_fault_isolated_to_its_slot(self):
        stats = ServingStats()
        ex = BatchExecutor(max_batch_size=3, max_wait_ms=5000, stats=stats)
        try:
            good = [StubDesigner(0.1), StubDesigner(0.2)]
            bad = FailPrepareStub(0.9)
            results, errors = _run_concurrent(ex, good + [bad])
            assert errors[0] is None and errors[1] is None
            assert isinstance(errors[2], RuntimeError)
            assert all(d.batched for d in good)
            snap = stats.snapshot()
            assert snap["batch_slot_errors"] == 1
            assert snap["batched_suggests"] == 2
        finally:
            ex.close()

    def test_execute_failure_falls_back_to_sequential_per_slot(self):
        stats = ServingStats()
        ex = BatchExecutor(max_batch_size=2, max_wait_ms=5000, stats=stats)
        try:
            designers = [FailExecuteStub(0.3), FailExecuteStub(0.6)]
            results, errors = _run_concurrent(ex, designers)
            assert errors == [None, None]
            for d, r in zip(designers, results):
                assert r[0].parameters.as_dict()["x"] == pytest.approx(d.value)
                assert d.sequential_calls == 1
            assert stats.snapshot()["batch_fallbacks"] == 2
        finally:
            ex.close()

    def test_nan_slot_gets_typed_transient_error(self):
        stats = ServingStats()
        ex = BatchExecutor(max_batch_size=2, max_wait_ms=5000, stats=stats)
        try:
            good, bad = StubDesigner(0.5), NanStub(0.5)
            results, errors = _run_concurrent(ex, [good, bad])
            assert errors[0] is None and good.batched
            assert isinstance(errors[1], BatchSlotError)
            assert "TRANSIENT" in str(errors[1])
            assert stats.snapshot()["batch_slot_errors"] == 1
        finally:
            ex.close()

    def test_close_drains_pending(self):
        ex = BatchExecutor(max_batch_size=8, max_wait_ms=60_000)
        d = StubDesigner(0.8)
        out = [None]
        t = threading.Thread(target=lambda: out.__setitem__(0, ex.suggest(d, 1)))
        t.start()
        import time

        for _ in range(200):  # wait until the slot is queued
            if ex.pending_counts():
                break
            time.sleep(0.005)
        ex.close()
        t.join(timeout=30)
        assert out[0] is not None and out[0][0].parameters.as_dict()["x"] == 0.8


class TestBatchedVsSequentialParity:
    """Same seeds ⇒ identical suggestions slot-by-slot (CPU, f32)."""

    def test_gp_bandit_parity_and_partial_batch_masking(self):
        seeds = (11, 12)
        sequential = [_feed(_gp_bandit(s), s).suggest(1) for s in seeds]

        # Padded partial batch (2 real slots padded to 4) ...
        padded = [_feed(_gp_bandit(s), s) for s in seeds]
        items = [d.batch_prepare(1) for d in padded]
        outs = padded[0].batch_execute(items, pad_to=4)
        padded_out = [
            d.batch_finalize(i, o) for d, i, o in zip(padded, items, outs)
        ]
        # ... and the unpadded batch must both match the sequential run:
        # masked filler slots never leak into real slots' posteriors.
        plain = [_feed(_gp_bandit(s), s) for s in seeds]
        items2 = [d.batch_prepare(1) for d in plain]
        outs2 = plain[0].batch_execute(items2, pad_to=None)
        plain_out = [
            d.batch_finalize(i, o) for d, i, o in zip(plain, items2, outs2)
        ]
        for i in range(len(seeds)):
            _assert_params_equal(_params(sequential[i]), _params(padded_out[i]))
            _assert_params_equal(_params(padded_out[i]), _params(plain_out[i]))
        # Batched designers carry the same trained warm state forward.
        assert padded[0]._warm_is_trained

    def test_gp_ucb_pe_parity_count_1(self):
        seeds = (21, 22)
        sequential = [_feed(_gp_ucb_pe(s), s, n=4).suggest(1) for s in seeds]
        batched = [_feed(_gp_ucb_pe(s), s, n=4) for s in seeds]
        keys = [d.batch_bucket_key(1) for d in batched]
        assert keys[0] == keys[1]
        items = [d.batch_prepare(1) for d in batched]
        outs = batched[0].batch_execute(items, pad_to=4)
        batched_out = [
            d.batch_finalize(i, o) for d, i, o in zip(batched, items, outs)
        ]
        for i in range(len(seeds)):
            _assert_params_equal(_params(sequential[i]), _params(batched_out[i]))
        # predict() after a batched suggest reuses the cached fit.
        pred = batched[0].predict(batched_out[0])
        assert np.isfinite(pred.mean).all()

    def test_gp_ucb_pe_parity_two_phase_batch(self):
        # count > 1 under first_pick_full: full-budget first pick, split
        # budget for the rest — two vmapped device sweeps.
        seeds = (31, 32)
        sequential = [_feed(_gp_ucb_pe(s), s, n=4).suggest(2) for s in seeds]
        batched = [_feed(_gp_ucb_pe(s), s, n=4) for s in seeds]
        items = [d.batch_prepare(2) for d in batched]
        outs = batched[0].batch_execute(items, pad_to=None)
        batched_out = [
            d.batch_finalize(i, o) for d, i, o in zip(batched, items, outs)
        ]
        for i in range(len(seeds)):
            assert len(batched_out[i]) == 2
            _assert_params_equal(_params(sequential[i]), _params(batched_out[i]))

    def test_executor_end_to_end_matches_sequential(self):
        seeds = (41, 42, 43)
        sequential = [_feed(_gp_bandit(s), s).suggest(1) for s in seeds]
        stats = ServingStats()
        ex = BatchExecutor(max_batch_size=3, max_wait_ms=10_000, stats=stats)
        try:
            designers = [_feed(_gp_bandit(s), s) for s in seeds]
            results, errors = _run_concurrent(ex, designers)
            assert errors == [None] * 3
            for i in range(3):
                _assert_params_equal(_params(sequential[i]), _params(results[i]))
            assert stats.snapshot()["batched_suggests"] == 3
        finally:
            ex.close()


class TestChaosIsolation:
    def test_faulting_slot_degrades_only_its_own_study(self):
        monkey = chaos_lib.ChaosMonkey(seed=0, failure_prob=1.0)
        chaotic = chaos_lib.ChaosDesigner(_feed(_gp_bandit(51), 51), monkey)
        healthy = [_feed(_gp_bandit(s), s) for s in (52, 53)]
        sequential = [_feed(_gp_bandit(s), s).suggest(1) for s in (52, 53)]
        stats = ServingStats()
        ex = BatchExecutor(max_batch_size=3, max_wait_ms=10_000, stats=stats)
        try:
            results, errors = _run_concurrent(ex, [chaotic] + healthy)
            # The chaos slot fails at batch_prepare and is dropped from the
            # batch; its error reaches only its own study's waiter.
            assert isinstance(errors[0], chaos_lib.failing.FailedSuggestError)
            assert errors[1] is None and errors[2] is None
            for i, seq in enumerate(sequential):
                _assert_params_equal(_params(seq), _params(results[i + 1]))
            snap = stats.snapshot()
            assert snap["batch_slot_errors"] == 1
            assert snap["batched_suggests"] == 2
            assert monkey.total_faults() == 1
        finally:
            ex.close()

    def test_chaos_execute_poisons_batch_but_sequential_fallback_recovers(self):
        # One strike in batch_execute kills the shared device program; every
        # slot recovers through its own sequential run (chaos designer's
        # plain suggest also strikes -> ITS slot errors, batchmate succeeds).
        monkey = chaos_lib.ChaosMonkey(seed=0, failure_prob=1.0)
        chaotic = chaos_lib.ChaosDesigner(_feed(_gp_bandit(61), 61), monkey)
        healthy = _feed(_gp_bandit(62), 62)
        # Force the chaos slot to pass prepare: only strike execute/suggest.
        chaotic.batch_prepare = chaotic._inner.batch_prepare
        stats = ServingStats()
        ex = BatchExecutor(max_batch_size=2, max_wait_ms=10_000, stats=stats)
        try:
            import time

            results = [None, None]
            errors = [None, None]

            def run(i, designer):
                try:
                    results[i] = ex.suggest(designer, 1)
                except BaseException as e:  # noqa: BLE001
                    errors[i] = e

            # The chaos designer must arrive FIRST so the flush dispatches
            # through ITS batch_execute (the executor uses the first live
            # slot's program entry point).
            t0 = threading.Thread(target=run, args=(0, chaotic))
            t0.start()
            for _ in range(400):
                if ex.pending_counts():
                    break
                time.sleep(0.005)
            t1 = threading.Thread(target=run, args=(1, healthy))
            t1.start()
            t0.join(timeout=120)
            t1.join(timeout=120)
            assert isinstance(errors[0], chaos_lib.failing.FailedSuggestError)
            assert errors[1] is None and results[1]
            assert stats.snapshot()["batch_fallbacks"] == 2
        finally:
            ex.close()


class TestPrewarm:
    def test_prewarm_walks_bucket_grid_and_compiles(self):
        ex = BatchExecutor(max_batch_size=2, max_wait_ms=10)
        try:
            report = ex.prewarm(
                _problem(),
                lambda p: VizierGPBandit(p, rng_seed=0, **_FAST),
                max_trials=8,
                counts=(1,),
            )
            # One grid bucket (pad 8) x batch sizes {1, max}.
            assert [r["pad_trials"] for r in report] == [8, 8]
            assert sorted(r["batch_size"] for r in report) == [1, 2]
            assert all(r["status"] == "ok" for r in report)
            assert all(r["seconds"] >= 0 for r in report)
        finally:
            ex.close()


class TestSpeculativeLane:
    """The low-priority lane for serving.speculative pre-computes."""

    def test_queue_depth_reports_lanes(self):
        executor = BatchExecutor(max_batch_size=8, max_wait_ms=10_000)
        try:
            order = []

            def run(designer, speculative):
                order.append(executor.suggest(designer, 1, speculative=speculative))

            live = StubDesigner(1.0, group="live")
            spec = StubDesigner(2.0, group="spec")
            threads = [
                threading.Thread(target=run, args=(spec, True)),
                threading.Thread(target=run, args=(live, False)),
            ]
            for t in threads:
                t.start()
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                depth = executor.queue_depth()
                if depth == {"live": 1, "speculative": 1}:
                    break
                time.sleep(0.001)
            assert executor.queue_depth() == {"live": 1, "speculative": 1}
            assert executor.live_pending() == 1
        finally:
            executor.close()
            for t in threads:
                t.join(timeout=10)

    def test_live_singleton_never_waits_behind_speculative_flush(self):
        """A queued speculative-only bucket must not become due while a
        live slot is queued: the live singleton flushes first.

        Deterministic via a fake clock: nothing becomes due until the
        clock advances, so the speculative bucket cannot sneak an
        idle-window flush in before the live slot is even submitted (a
        real-time race on a loaded machine). Flush ORDER is observed at
        the scheduler's _execute (sequential on its thread) — the
        waiter-side suggest/finalize calls run on racing client threads
        and cannot order-assert reliably."""
        clock = [0.0]
        executor = BatchExecutor(
            max_batch_size=8,
            max_wait_ms=30.0,
            speculative_max_wait_ms=10_000,
            time_fn=lambda: clock[0],
        )
        flush_order = []
        original_execute = executor._execute

        def recording_execute(key, slots, reason, placement=None):
            flush_order.append(
                "spec" if all(s.speculative for s in slots) else "live"
            )
            return original_execute(key, slots, reason, placement)

        executor._execute = recording_execute

        class Recording(StubDesigner):
            def __init__(self, value, group, tag):
                super().__init__(value, group=group)
                self.tag = tag

        try:
            results = {}

            def run(tag, designer, speculative):
                results[tag] = executor.suggest(
                    designer, 1, speculative=speculative
                )

            # Two speculative slots share a bucket (so they'd flush
            # batched); the live singleton arrives afterwards in its own
            # bucket, i.e. with a LATER timeout window — yet must run
            # first because pure-speculative buckets defer to queued live.
            spec_a = Recording(1.0, "spec", "spec")
            spec_b = Recording(2.0, "spec", "spec")
            live = Recording(3.0, "live", "live")
            t1 = threading.Thread(target=run, args=("a", spec_a, True))
            t2 = threading.Thread(target=run, args=("b", spec_b, True))
            t1.start()
            t2.start()
            deadline = time.monotonic() + 5.0
            while (
                executor.queue_depth()["speculative"] < 2
                and time.monotonic() < deadline
            ):
                time.sleep(0.001)
            t3 = threading.Thread(target=run, args=("live", live, False))
            t3.start()
            deadline = time.monotonic() + 5.0
            while (
                executor.live_pending() < 1 and time.monotonic() < deadline
            ):
                time.sleep(0.001)
            # Everything queued at t=0; advance past every window at once.
            clock[0] = 1.0
            for t in (t1, t2, t3):
                t.join(timeout=30)
            assert flush_order[0] == "live", flush_order
            assert set(flush_order) == {"live", "spec"}
        finally:
            executor.close()

    def test_speculative_flushes_in_idle_window(self):
        executor = BatchExecutor(max_batch_size=8, max_wait_ms=5.0)
        try:
            spec = StubDesigner(1.0, group="spec")
            out = executor.suggest(spec, 1, speculative=True)
            assert [s.parameters["x"].value for s in out] == [1.0]
        finally:
            executor.close()

    def test_speculative_rides_a_live_flush(self):
        """A speculative slot in a bucket a live slot joins flushes WITH
        the live batch (shared compute is the good case)."""
        executor = BatchExecutor(max_batch_size=2, max_wait_ms=10_000)
        try:
            spec = StubDesigner(1.0, group="g")
            live = StubDesigner(2.0, group="g")
            results, errors = [None, None], [None, None]

            def run(i, designer, speculative):
                results[i] = executor.suggest(designer, 1, speculative=speculative)

            t1 = threading.Thread(target=run, args=(0, spec, True))
            t1.start()
            deadline = time.monotonic() + 5.0
            while (
                executor.queue_depth()["speculative"] < 1
                and time.monotonic() < deadline
            ):
                time.sleep(0.001)
            t2 = threading.Thread(target=run, args=(1, live, False))
            t2.start()
            t1.join(timeout=30)
            t2.join(timeout=30)
            # Full flush at size 2: both went through the batched path.
            assert spec.batched and live.batched
        finally:
            executor.close()

    def test_starvation_cap_flushes_speculative_under_constant_live(self):
        """speculative_max_wait bounds the hold: a speculative slot is
        flushed eventually even while live slots keep the queues busy."""
        executor = BatchExecutor(
            max_batch_size=8,
            max_wait_ms=10_000,  # live bucket never times out on its own
            speculative_max_wait_ms=30.0,
        )
        try:
            spec = StubDesigner(1.0, group="spec")
            live = StubDesigner(2.0, group="live")
            results = {}

            def run(tag, designer, speculative):
                results[tag] = executor.suggest(
                    designer, 1, speculative=speculative
                )

            t_live = threading.Thread(target=run, args=("live", live, False))
            t_spec = threading.Thread(target=run, args=("spec", spec, True))
            t_live.start()
            t_spec.start()
            # The speculative slot must complete despite the live slot
            # still parked in its (never-due) bucket.
            t_spec.join(timeout=10)
            assert not t_spec.is_alive()
            assert results["spec"] is not None
        finally:
            executor.close()
            t_live.join(timeout=10)

    def test_close_drains_speculative_slots(self):
        executor = BatchExecutor(
            max_batch_size=8, max_wait_ms=10_000, speculative_max_wait_ms=10_000
        )
        spec = StubDesigner(1.0, group="spec")
        result = []
        t = threading.Thread(
            target=lambda: result.append(
                executor.suggest(spec, 1, speculative=True)
            )
        )
        t.start()
        deadline = time.monotonic() + 5.0
        while (
            executor.queue_depth()["speculative"] < 1
            and time.monotonic() < deadline
        ):
            time.sleep(0.001)
        executor.close()
        t.join(timeout=10)
        assert result and result[0] is not None
