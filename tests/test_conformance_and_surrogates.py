"""Client-ABC conformance (run against the OSS client) + surrogate tests."""

import numpy as np
import pytest

from vizier_tpu import pyvizier as vz
from vizier_tpu.client.client_abc_testing import StudyConformance
from vizier_tpu.service import clients as clients_lib
from vizier_tpu.service import vizier_client


class TestOSSClientConformance(StudyConformance):
    """The shipped service client must pass the full behavioral contract."""

    def setup_method(self):
        vizier_client._local_servicer = None

    def create_study(self, problem, study_id):
        config = vz.StudyConfig.from_problem(problem, vz.Algorithm.RANDOM_SEARCH)
        return clients_lib.Study.from_study_config(
            config, owner="conformance", study_id=study_id
        )


class TestGrpcClientConformance(StudyConformance):
    """The same behavioral contract over a REAL localhost gRPC channel.

    In-process-servicer and network transports must be indistinguishable
    (reference ``client_abc_testing`` is run against both by
    ``clients_test.py`` / cloud clients).
    """

    _server = None

    @classmethod
    def setup_class(cls):
        from vizier_tpu.service import vizier_server

        cls._server = vizier_server.DefaultVizierServer(host="localhost")

    def setup_method(self):
        clients_lib.environment_variables.server_endpoint = self._server.endpoint

    def teardown_method(self):
        clients_lib.environment_variables.server_endpoint = clients_lib.NO_ENDPOINT

    def create_study(self, problem, study_id):
        config = vz.StudyConfig.from_problem(problem, vz.Algorithm.RANDOM_SEARCH)
        return clients_lib.Study.from_study_config(
            config, owner="conformance-grpc", study_id=study_id
        )


class TestTabularSurrogate:
    def _experimenter(self):
        from vizier_tpu.benchmarks.experimenters.surrogates import (
            TabularSurrogateExperimenter,
        )

        problem = vz.ProblemStatement()
        problem.search_space.root.add_float_param("x", 0.0, 1.0)
        problem.search_space.root.add_categorical_param("op", ["a", "b"])
        problem.metric_information.append(
            vz.MetricInformation(name="objective", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
        )
        rows = [
            {"x": 0.0, "op": "a"},
            {"x": 1.0, "op": "a"},
            {"x": 0.5, "op": "b"},
        ]
        return TabularSurrogateExperimenter(problem, rows, [0.1, 0.9, 0.5])

    def test_exact_lookup(self):
        exp = self._experimenter()
        t = vz.Trial(id=1, parameters={"x": 1.0, "op": "a"})
        exp.evaluate([t])
        assert t.final_measurement.metrics["objective"].value == 0.9

    def test_nearest_snap(self):
        exp = self._experimenter()
        t = vz.Trial(id=1, parameters={"x": 0.93, "op": "a"})
        exp.evaluate([t])
        assert t.final_measurement.metrics["objective"].value == 0.9

    def test_handlers_require_data(self):
        from vizier_tpu.benchmarks.experimenters.surrogates import (
            HPOBHandler,
            NASBench201Handler,
        )

        with pytest.raises(FileNotFoundError):
            HPOBHandler(root_dir=None).make_experimenter("ss", "ds")
        with pytest.raises(FileNotFoundError):
            NASBench201Handler().make_experimenter()
        # The NASBench problem shell itself works without data.
        problem = NASBench201Handler().problem_statement()
        assert problem.search_space.num_parameters() == 6


class TestYeoJohnson:
    def test_gaussianizes_skew(self):
        from scipy import stats

        from vizier_tpu.models.output_warpers import YeoJohnsonWarper

        rng = np.random.default_rng(0)
        y = np.exp(rng.normal(size=300))
        warped = YeoJohnsonWarper()(y)
        assert abs(stats.skew(warped)) < abs(stats.skew(y)) / 3


class TestSurrogateRegressions:
    """Regressions from the ninth code review."""

    def test_categorical_mismatch_is_disqualifying(self):
        from vizier_tpu.benchmarks.experimenters.surrogates import (
            TabularSurrogateExperimenter,
        )

        problem = vz.ProblemStatement()
        problem.search_space.root.add_float_param("x", 0.0, 10.0)
        problem.search_space.root.add_categorical_param("op", ["a", "b"])
        problem.metric_information.append(vz.MetricInformation(name="objective"))
        # Row with op='a' is numerically distant; op='b' rows don't exist
        # near x=0 — the exact-category row must still win.
        rows = [{"x": 9.0, "op": "a"}, {"x": 0.1, "op": "b"}]
        exp = TabularSurrogateExperimenter(problem, rows, [0.9, 0.1])
        t = vz.Trial(id=1, parameters={"x": 0.0, "op": "a"})
        exp.evaluate([t])
        assert t.final_measurement.metrics["objective"].value == 0.9

    def test_unknown_categorical_combo_infeasible(self):
        from vizier_tpu.benchmarks.experimenters.surrogates import (
            TabularSurrogateExperimenter,
        )

        problem = vz.ProblemStatement()
        problem.search_space.root.add_categorical_param("op", ["a", "b"])
        problem.metric_information.append(vz.MetricInformation(name="objective"))
        exp = TabularSurrogateExperimenter(problem, [{"op": "a"}], [1.0])
        t = vz.Trial(id=1, parameters={"op": "b"})
        exp.evaluate([t])
        assert t.infeasible

    def test_hpob_modes(self):
        from vizier_tpu.benchmarks.experimenters.surrogates import HPOBHandler

        assert "v3-test" in HPOBHandler.MODES
        with pytest.raises(ValueError, match="Unknown HPO-B mode"):
            HPOBHandler(root_dir="/tmp", mode="bogus")
