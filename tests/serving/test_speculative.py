"""Speculative pre-compute: engine mechanics, invalidation races, serving
integration, and the bit-equality contract (a hit IS the live compute,
run early)."""

import threading
import time

import pytest

from vizier_tpu.serving import designer_cache as cache_lib
from vizier_tpu.serving import speculative as spec_lib
from vizier_tpu.serving.speculative import (
    SpeculativeConfig,
    SpeculativeEngine,
    make_fingerprint,
)
from vizier_tpu.serving.stats import ServingStats
from vizier_tpu.surrogates import config as surrogate_config_lib


class TestConfig:
    def test_default_is_off(self):
        assert SpeculativeConfig().speculative is False
        assert SpeculativeConfig.from_env().speculative is False

    def test_env_opt_in(self, monkeypatch):
        monkeypatch.setenv("VIZIER_SPECULATIVE", "1")
        monkeypatch.setenv("VIZIER_SPECULATIVE_WORKERS", "3")
        monkeypatch.setenv("VIZIER_SPECULATIVE_MAX_AGE_S", "12.5")
        monkeypatch.setenv("VIZIER_SPECULATIVE_ON_FILL", "1")
        cfg = SpeculativeConfig.from_env()
        assert cfg.speculative is True
        assert cfg.workers == 3
        assert cfg.max_speculation_age_s == 12.5
        assert cfg.speculate_on_fill is True

    def test_validation(self):
        with pytest.raises(ValueError):
            SpeculativeConfig(workers=0)
        with pytest.raises(ValueError):
            SpeculativeConfig(max_speculation_age_s=0.0)

    def test_as_dict_is_json_shaped(self):
        d = SpeculativeConfig().as_dict()
        assert set(d) == {
            "speculative",
            "workers",
            "max_speculation_age_s",
            "speculate_on_fill",
            "count_memory",
            "debounce_ms",
        }


class TestFingerprint:
    def test_order_insensitive_ids(self):
        a = make_fingerprint(b"cfg", [3, 1, 2], [7, 5])
        b = make_fingerprint(b"cfg", [2, 3, 1], [5, 7])
        assert a == b

    def test_sensitive_to_every_component(self):
        base = make_fingerprint(b"cfg", [1, 2], [3])
        assert base != make_fingerprint(b"cfg2", [1, 2], [3])
        assert base != make_fingerprint(b"cfg", [1, 2, 4], [3])
        assert base != make_fingerprint(b"cfg", [1, 2], [3, 4])
        # A completion moving a trial active->completed changes both sets.
        assert base != make_fingerprint(b"cfg", [1, 2, 3], [])


# ---------------------------------------------------------------------------
# Engine unit tests: a fake compute path with controllable latency.
# ---------------------------------------------------------------------------


class _FakeResponse:
    """Stands in for a PythiaSuggestResponse (opaque to the engine)."""

    def __init__(self, batch, error=""):
        self.batch = batch
        self.error = error


class _Harness:
    """A bound engine over a real designer cache and scripted frontiers."""

    def __init__(self, config=None, executor=None, time_fn=None):
        self.stats = ServingStats()
        self.cache = cache_lib.DesignerStateCache(stats=self.stats)
        self.engine = SpeculativeEngine(
            config or SpeculativeConfig(speculative=True),
            cache=self.cache,
            stats=self.stats,
            executor=executor,
            time_fn=time_fn or time.monotonic,
        )
        self.frontier = ([], [], 0)  # completed, active, max_id
        self.spec_bytes = b"study-config"
        self.computes = 0
        self.compute_started = threading.Event()
        self.compute_release = threading.Event()
        self.compute_release.set()  # compute returns immediately by default
        self.compute_result = lambda study, count: _FakeResponse(
            [f"{study}#{count}"] * count
        )
        self.engine.bind(
            fingerprint_fn=self._fingerprint,
            compute_fn=self._compute,
            accept_fn=self._accept,
        )

    def _fingerprint(self, study):
        completed, active, max_id = self.frontier
        return make_fingerprint(self.spec_bytes, completed, active), max_id

    def _compute(self, study, count, max_trial_id):
        assert spec_lib.in_speculative_compute()
        self.computes += 1
        self.compute_started.set()
        assert self.compute_release.wait(timeout=30.0)
        return self.compute_result(study, count)

    @staticmethod
    def _accept(response):
        if response is None or response.error or not response.batch:
            return None
        return len(response.batch)

    def fill_entry(self, study="s"):
        """A live suggest would have created the designer entry; fake it."""
        return self.cache.get_or_create(study, lambda: object())

    def current_fp(self):
        completed, active, _ = self.frontier
        return make_fingerprint(self.spec_bytes, completed, active)

    def close(self):
        self.engine.close()


@pytest.fixture
def harness():
    h = _Harness()
    yield h
    h.close()


def _spec_stats(stats):
    return {
        k.replace("speculative_", ""): v
        for k, v in stats.snapshot().items()
        if k.startswith("speculative_")
    }


class TestEngineParkAndServe:
    def test_completion_park_then_one_shot_hit(self, harness):
        harness.fill_entry("s")
        harness.frontier = ([1], [], 1)
        assert harness.engine.notify_completion("s")
        assert harness.engine.wait_idle(10.0)
        response, outcome = harness.engine.try_serve("s", 1, harness.current_fp())
        assert outcome == "hit"
        assert response.batch == ["s#1"]
        # One-shot: the slot was consumed.
        response2, outcome2 = harness.engine.try_serve(
            "s", 1, harness.current_fp()
        )
        assert response2 is None and outcome2 == "miss"
        counters = _spec_stats(harness.stats)
        assert counters["hits"] == 1
        assert counters["precomputes"] == 1

    def test_fingerprint_mismatch_is_a_miss_and_drops_slot(self, harness):
        entry = harness.fill_entry("s")
        harness.frontier = ([1], [], 1)
        harness.engine.notify_completion("s")
        assert harness.engine.wait_idle(10.0)
        moved = make_fingerprint(harness.spec_bytes, [1, 2], [])
        response, outcome = harness.engine.try_serve("s", 1, moved)
        assert response is None and outcome == "miss"
        assert entry.speculative is None  # unservable batch dropped

    def test_config_change_is_a_miss(self, harness):
        harness.fill_entry("s")
        harness.frontier = ([1], [], 1)
        harness.engine.notify_completion("s")
        assert harness.engine.wait_idle(10.0)
        other_config = make_fingerprint(b"other-config", [1], [])
        response, outcome = harness.engine.try_serve("s", 1, other_config)
        assert response is None and outcome == "miss"

    def test_count_reconciliation(self):
        h = _Harness()
        try:
            h.fill_entry("s")
            h.frontier = ([1], [], 1)
            h.engine.note_live_suggest("s", 3)  # speculate batches of 3
            h.engine.notify_completion("s")
            assert h.engine.wait_idle(10.0)
            # Larger request: miss, slot retained for a matching peer.
            response, outcome = h.engine.try_serve("s", 4, h.current_fp())
            assert response is None and outcome == "miss"
            # Smaller request: hit (Pythia serves the batch prefix).
            response, outcome = h.engine.try_serve("s", 2, h.current_fp())
            assert outcome == "hit" and len(response.batch) == 3
        finally:
            h.close()

    def test_staleness_deadline(self):
        clock = [0.0]
        h = _Harness(
            config=SpeculativeConfig(speculative=True, max_speculation_age_s=5.0),
            time_fn=lambda: clock[0],
        )
        try:
            h.fill_entry("s")
            h.frontier = ([1], [], 1)
            h.engine.notify_completion("s")
            assert h.engine.wait_idle(10.0)
            clock[0] = 6.0
            response, outcome = h.engine.try_serve("s", 1, h.current_fp())
            assert response is None and outcome == "stale"
            assert _spec_stats(h.stats)["stale"] == 1
        finally:
            h.close()

    def test_no_cache_entry_skips_the_compute(self, harness):
        # Bulk trial loading before any suggest: no designer entry exists,
        # so speculating would burn RNG state for an unservable batch.
        harness.frontier = ([1], [], 1)
        harness.engine.notify_completion("nobody-served-me")
        assert harness.engine.wait_idle(10.0)
        assert harness.computes == 0
        assert _spec_stats(harness.stats)["cancelled"] == 1


class TestCountMemory:
    """Last-K-distinct-counts speculation (the PR 8 last-seen-only
    residual): the job computes the LARGEST recent count, so bigger
    requests stop falling through and smaller ones serve a prefix."""

    def test_speculates_largest_recent_count(self, harness):
        harness.fill_entry("s")
        harness.engine.note_live_suggest("s", 1)
        harness.engine.note_live_suggest("s", 5)
        harness.engine.note_live_suggest("s", 2)  # 5 stays in the window
        harness.frontier = ([1], [], 1)
        harness.engine.notify_completion("s")
        assert harness.engine.wait_idle(10.0)
        response, outcome = harness.engine.try_serve(
            "s", 5, harness.current_fp()
        )
        assert outcome == "hit"
        assert len(response.batch) == 5

    def test_smaller_request_hits_the_larger_parked_batch(self, harness):
        harness.fill_entry("s")
        harness.engine.note_live_suggest("s", 1)
        harness.engine.note_live_suggest("s", 4)
        harness.frontier = ([1], [], 1)
        harness.engine.notify_completion("s")
        assert harness.engine.wait_idle(10.0)
        # A count-1 client consumes the count-4 batch (the servicer serves
        # the prefix); under last-seen-only this parked a count-1 batch
        # that the next count-4 request would have missed.
        response, outcome = harness.engine.try_serve(
            "s", 1, harness.current_fp()
        )
        assert outcome == "hit"
        assert len(response.batch) == 4

    def test_memory_evicts_oldest_distinct_count(self):
        h = _Harness(
            config=SpeculativeConfig(speculative=True, count_memory=2)
        )
        try:
            h.fill_entry("s")
            h.engine.note_live_suggest("s", 7)  # evicted by the next two
            h.engine.note_live_suggest("s", 1)
            h.engine.note_live_suggest("s", 2)
            h.frontier = ([1], [], 1)
            h.engine.notify_completion("s")
            assert h.engine.wait_idle(10.0)
            response, outcome = h.engine.try_serve("s", 2, h.current_fp())
            assert outcome == "hit"
            assert len(response.batch) == 2  # max of the kept {1, 2}, not 7
        finally:
            h.close()

    def test_repeated_count_is_one_distinct_entry(self):
        h = _Harness(
            config=SpeculativeConfig(speculative=True, count_memory=2)
        )
        try:
            h.fill_entry("s")
            h.engine.note_live_suggest("s", 6)
            for _ in range(5):
                h.engine.note_live_suggest("s", 3)  # must not evict the 6
            h.frontier = ([1], [], 1)
            h.engine.notify_completion("s")
            assert h.engine.wait_idle(10.0)
            response, outcome = h.engine.try_serve("s", 6, h.current_fp())
            assert outcome == "hit"
            assert len(response.batch) == 6
        finally:
            h.close()


class TestDebounce:
    def test_debounce_holds_the_job_until_quiet(self):
        h = _Harness(
            config=SpeculativeConfig(speculative=True, debounce_ms=500.0)
        )
        try:
            h.fill_entry("s")
            h.frontier = ([1], [], 1)
            h.engine.notify_completion("s")
            # Still inside the debounce window: no compute started.
            assert not h.compute_started.wait(timeout=0.15)
            assert h.engine.pending_jobs() == 1
            assert h.engine.wait_idle(10.0)
            assert h.computes == 1
        finally:
            h.close()

    def test_completion_burst_coalesces_into_one_compute(self):
        h = _Harness(
            config=SpeculativeConfig(speculative=True, debounce_ms=250.0)
        )
        try:
            h.fill_entry("s")
            for trial in range(1, 5):  # 4 completions inside the window
                h.frontier = (list(range(1, trial + 1)), [], trial)
                h.engine.notify_completion("s")
                time.sleep(0.02)
            assert h.engine.wait_idle(10.0)
            # The burst superseded in place: ONE compute, at the final
            # frontier.
            assert h.computes == 1
            response, outcome = h.engine.try_serve("s", 1, h.current_fp())
            assert outcome == "hit"
        finally:
            h.close()

    def test_zero_debounce_is_immediate(self, harness):
        harness.fill_entry("s")
        harness.frontier = ([1], [], 1)
        harness.engine.notify_completion("s")
        assert harness.compute_started.wait(timeout=5.0)


class TestInvalidationRaces:
    def test_completion_mid_flight_discards_the_result(self, harness):
        harness.fill_entry("s")
        harness.frontier = ([1], [], 1)
        harness.compute_release.clear()
        harness.engine.notify_completion("s")
        assert harness.compute_started.wait(10.0)
        # A second completion lands while the job computes for the OLD
        # frontier: its result must be discarded, not served. The new
        # job recomputes against the new frontier.
        harness.frontier = ([1, 2], [], 2)
        harness.engine.notify_completion("s")
        harness.compute_release.set()
        assert harness.engine.wait_idle(10.0)
        # Only the superseding job's batch parked: the slot's fingerprint
        # is the NEW frontier's, so the first job's result (computed for
        # the old frontier) was discarded, never served.
        entry = harness.cache.peek("s")
        assert entry.speculative is not None
        assert entry.speculative.fingerprint == harness.current_fp()
        response, outcome = harness.engine.try_serve("s", 1, harness.current_fp())
        assert outcome == "hit"
        assert harness.computes == 2

    def test_delete_study_mid_flight(self, harness):
        harness.fill_entry("s")
        harness.frontier = ([1], [], 1)
        harness.compute_release.clear()
        harness.engine.notify_completion("s")
        assert harness.compute_started.wait(10.0)
        harness.engine.invalidate("s", reason="delete_study")
        harness.cache.invalidate("s")
        harness.compute_release.set()
        assert harness.engine.wait_idle(10.0)
        # Nothing served for the deleted (then recreated) study.
        harness.fill_entry("s")
        response, outcome = harness.engine.try_serve("s", 1, harness.current_fp())
        assert response is None and outcome == "miss"

    def test_invalidate_drops_parked_slot_and_queued_job(self, harness):
        entry = harness.fill_entry("s")
        harness.frontier = ([1], [], 1)
        harness.engine.notify_completion("s")
        assert harness.engine.wait_idle(10.0)
        assert entry.speculative is not None
        harness.engine.invalidate("s", reason="surgery")
        assert entry.speculative is None

    def test_crossover_hook_invalidates(self, harness):
        entry = harness.fill_entry("s")
        harness.frontier = ([1], [], 1)
        harness.engine.notify_completion("s")
        assert harness.engine.wait_idle(10.0)
        assert entry.speculative is not None

        class _Designer:
            pass

        designer = _Designer()
        surrogate_config_lib.install_crossover_listener(
            designer,
            lambda old, new: harness.engine.invalidate(
                "s", reason=f"crossover:{old}->{new}"
            ),
        )
        surrogate_config_lib.fire_crossover_hook(designer, "exact", "sparse")
        assert entry.speculative is None

    def test_crossover_hook_swallows_listener_errors(self):
        class _Designer:
            pass

        designer = _Designer()
        surrogate_config_lib.install_crossover_listener(
            designer, lambda old, new: 1 / 0
        )
        # Must not raise: a broken observer cannot fail the compute.
        surrogate_config_lib.fire_crossover_hook(designer, "exact", "sparse")
        # No listener installed is a no-op too.
        surrogate_config_lib.fire_crossover_hook(object(), "a", "b")


class TestFailureIsolation:
    def test_compute_error_leaves_no_slot(self, harness):
        entry = harness.fill_entry("s")
        harness.frontier = ([1], [], 1)

        def boom(study, count):
            raise RuntimeError("designer died")

        harness.compute_result = boom
        harness.engine.notify_completion("s")
        assert harness.engine.wait_idle(10.0)
        assert entry.speculative is None
        assert _spec_stats(harness.stats)["errors"] == 1

    def test_error_response_rejected(self, harness):
        entry = harness.fill_entry("s")
        harness.frontier = ([1], [], 1)
        harness.compute_result = lambda s, c: _FakeResponse([], error="TRANSIENT: x")
        harness.engine.notify_completion("s")
        assert harness.engine.wait_idle(10.0)
        assert entry.speculative is None

    def test_worker_survives_fingerprint_failure(self, harness):
        harness.fill_entry("s")
        original = harness.engine._fingerprint_fn
        harness.engine._fingerprint_fn = lambda study: 1 / 0
        harness.engine.notify_completion("s")
        assert harness.engine.wait_idle(10.0)
        # The pool is still alive and serves the next job.
        harness.engine._fingerprint_fn = original
        harness.frontier = ([1], [], 1)
        harness.engine.notify_completion("s")
        assert harness.engine.wait_idle(10.0)
        _, outcome = harness.engine.try_serve("s", 1, harness.current_fp())
        assert outcome == "hit"


class _FakeExecutor:
    def __init__(self, live=0):
        self.live = live

    def live_pending(self):
        return self.live


class TestAdmissionGate:
    def test_busy_executor_drops_the_job(self):
        executor = _FakeExecutor(live=5)
        h = _Harness(
            config=SpeculativeConfig(
                speculative=True,
                admission_backoff_s=0.005,
                admission_max_wait_s=0.02,
            ),
            executor=executor,
        )
        try:
            h.fill_entry("s")
            h.frontier = ([1], [], 1)
            h.engine.notify_completion("s")
            assert h.engine.wait_idle(10.0)
            assert h.computes == 0  # refused: live traffic owns the buckets
            assert _spec_stats(h.stats)["cancelled"] == 1
        finally:
            h.close()

    def test_gate_opens_when_live_drains(self):
        executor = _FakeExecutor(live=5)
        h = _Harness(
            config=SpeculativeConfig(
                speculative=True,
                admission_backoff_s=0.005,
                admission_max_wait_s=5.0,
            ),
            executor=executor,
        )
        try:
            h.fill_entry("s")
            h.frontier = ([1], [], 1)
            h.engine.notify_completion("s")
            time.sleep(0.02)
            executor.live = 0  # live traffic drained mid-backoff
            assert h.engine.wait_idle(10.0)
            assert h.computes == 1
        finally:
            h.close()


class TestShutdown:
    def test_close_joins_workers_no_thread_leak(self, harness):
        harness.fill_entry("s")
        harness.frontier = ([1], [], 1)
        harness.engine.notify_completion("s")
        assert harness.engine.wait_idle(10.0)
        harness.engine.close()
        assert not any(
            t.name.startswith("vizier-speculative") and t.is_alive()
            for t in threading.enumerate()
        )

    def test_close_under_load_cancels_and_discards(self, harness):
        entry = harness.fill_entry("s")
        harness.frontier = ([1], [], 1)
        harness.compute_release.clear()
        harness.engine.notify_completion("s")
        assert harness.compute_started.wait(10.0)
        # Queue a second study's job behind the wedged compute.
        harness.fill_entry("s2")
        harness.engine.notify_completion("s2")
        closer = threading.Thread(target=harness.engine.close)
        closer.start()
        time.sleep(0.02)
        harness.compute_release.set()  # the in-flight compute finishes late
        closer.join(timeout=10.0)
        assert not closer.is_alive()
        assert entry.speculative is None  # late result discarded, not parked
        assert harness.computes == 1  # queued job never started
        assert not any(
            t.name.startswith("vizier-speculative") and t.is_alive()
            for t in threading.enumerate()
        )

    def test_close_is_idempotent(self, harness):
        harness.engine.close()
        harness.engine.close()

    def test_notify_after_close_is_refused(self, harness):
        harness.fill_entry("s")
        harness.engine.close()
        assert harness.engine.notify_completion("s") is False


class TestRuntimeWiring:
    def test_runtime_default_has_no_engine(self):
        from vizier_tpu.serving import ServingRuntime

        runtime = ServingRuntime()
        try:
            assert runtime.speculative_engine is None
        finally:
            runtime.shutdown()

    def test_runtime_builds_engine_when_opted_in(self):
        from vizier_tpu.serving import ServingRuntime

        runtime = ServingRuntime(
            speculative=SpeculativeConfig(speculative=True)
        )
        try:
            engine = runtime.speculative_engine
            assert engine is not None
            assert not engine.bound  # needs a Pythia servicer to bind
        finally:
            runtime.shutdown()

    def test_requires_designer_cache(self):
        from vizier_tpu.serving import ServingConfig, ServingRuntime

        runtime = ServingRuntime(
            ServingConfig(designer_cache=False),
            speculative=SpeculativeConfig(speculative=True),
        )
        try:
            assert runtime.speculative_engine is None
        finally:
            runtime.shutdown()

    def test_shutdown_closes_engine(self):
        from vizier_tpu.serving import ServingRuntime

        runtime = ServingRuntime(
            speculative=SpeculativeConfig(speculative=True)
        )
        runtime.shutdown()
        assert runtime.speculative_engine._closed

    def test_invalidate_study_reaches_engine(self):
        from vizier_tpu.serving import ServingRuntime

        runtime = ServingRuntime(
            speculative=SpeculativeConfig(speculative=True)
        )
        try:
            entry = runtime.designer_cache.get_or_create("s", lambda: object())
            entry.speculative = spec_lib.SpeculativeSlot(
                "s", make_fingerprint(b"c", [], []), object(), 1, 0.0
            )
            runtime.invalidate_study("s")
            assert runtime.designer_cache.peek("s") is None
        finally:
            runtime.shutdown()


class TestCachePeek:
    def test_peek_never_creates(self):
        cache = cache_lib.DesignerStateCache()
        assert cache.peek("missing") is None
        assert len(cache) == 0

    def test_peek_touch_refreshes_lru(self):
        cache = cache_lib.DesignerStateCache(max_entries=2)
        cache.get_or_create("a", lambda: object())
        cache.get_or_create("b", lambda: object())
        cache.peek("a")  # refresh: "b" becomes the LRU victim
        cache.get_or_create("c", lambda: object())
        assert "a" in cache and "c" in cache and "b" not in cache

    def test_peek_honors_ttl(self):
        clock = [0.0]
        cache = cache_lib.DesignerStateCache(
            ttl_seconds=10.0, time_fn=lambda: clock[0]
        )
        cache.get_or_create("a", lambda: object())
        clock[0] = 11.0
        assert cache.peek("a") is None

    def test_peek_no_touch_is_pure(self):
        clock = [0.0]
        cache = cache_lib.DesignerStateCache(
            ttl_seconds=10.0, time_fn=lambda: clock[0]
        )
        cache.get_or_create("a", lambda: object())
        clock[0] = 5.0
        entry = cache.peek("a", touch=False)
        assert entry is not None
        assert entry.last_used_at == 0.0


# ---------------------------------------------------------------------------
# Serving-stack integration: real service + cheap GP designer. The contract
# under test is the headline one: a speculative hit is bit-equal to the
# live compute it replaced, and the whole trajectory matches the
# non-speculative path suggestion-for-suggestion.
# ---------------------------------------------------------------------------


def _fast_gp_factory(runtime):
    from vizier_tpu.designers import gp_ucb_pe
    from vizier_tpu.optimizers import lbfgs as lbfgs_lib
    from vizier_tpu.serving.policy import CachedDesignerStatePolicy

    kwargs = dict(
        max_acquisition_evaluations=200,
        ard_restarts=2,
        ard_optimizer=lbfgs_lib.AdamOptimizer(maxiter=10),
        warm_start_min_trials=0,
        rng_seed=7,
    )

    class _Factory:
        def __init__(self, serving):
            self._serving = serving

        def __call__(self, problem, algorithm, supporter, study_name):
            kw = dict(kwargs)
            cfg = self._serving.config
            kw["use_warm_start_ard"] = cfg.warm_start
            if cfg.warm_start:
                kw["warm_ard_restarts"] = cfg.warm_ard_restarts
            return CachedDesignerStatePolicy(
                supporter,
                lambda p, **_: gp_ucb_pe.VizierGPUCBPEBandit(p, **kw),
                self._serving,
                study_name,
                use_seeding=True,
            )

    return _Factory(runtime)


def _gp_stack(speculative_config=None):
    from vizier_tpu.service import pythia_service, vizier_service
    from vizier_tpu.serving import runtime as runtime_lib

    servicer = vizier_service.VizierServicer()
    pythia = pythia_service.PythiaServicer(servicer)
    if speculative_config is not None:
        pythia._serving = runtime_lib.ServingRuntime(
            speculative=speculative_config
        )
    pythia._policy_factory = _fast_gp_factory(pythia.serving_runtime)
    pythia._bind_speculative()
    servicer.set_pythia(pythia)
    return servicer, pythia


def _speculative_study_config():
    from vizier_tpu import pyvizier as vz

    config = vz.StudyConfig(algorithm="DEFAULT")
    for d in range(2):
        config.search_space.root.add_float_param(f"x{d}", 0.0, 1.0)
    config.metric_information.append(
        vz.MetricInformation(name="obj", goal=vz.ObjectiveMetricGoal.MAXIMIZE)
    )
    return config


def _drive_loop(servicer, pythia, study_name, steps):
    """Sequential complete→suggest loop; the engine's wait_idle models an
    evaluation that outlasts the pre-compute (the serving steady state).
    Returns (per-suggest parameter tuples, hit-stamp flags)."""
    from vizier_tpu.service import proto_converters as pc
    from vizier_tpu.service.protos import vizier_service_pb2
    from vizier_tpu.serving import speculative as spec

    servicer.CreateStudy(
        vizier_service_pb2.CreateStudyRequest(
            parent="owners/o",
            study=pc.study_to_proto(_speculative_study_config(), study_name),
        )
    )
    engine = pythia.serving_runtime.speculative_engine
    trajectory, stamped = [], []
    for _ in range(steps):
        op = servicer.SuggestTrials(
            vizier_service_pb2.SuggestTrialsRequest(
                parent=study_name, suggestion_count=1, client_id="worker"
            )
        )
        assert not op.error, op.error
        trial = op.response.trials[0]
        trajectory.append(
            tuple(
                sorted(
                    (p.name, p.value.double_value) for p in trial.parameters
                )
            )
        )
        stamped.append(
            any(
                kv.key == spec.SPECULATIVE_KEY
                and kv.string_value == spec.SPECULATIVE_HIT_VALUE
                for kv in trial.metadata
            )
        )
        request = vizier_service_pb2.CompleteTrialRequest(name=trial.name)
        metric = request.final_measurement.metrics.add()
        metric.name = "obj"
        metric.value = -sum(
            (p.value.double_value - 0.3) ** 2 for p in trial.parameters
        )
        servicer.CompleteTrial(request)
        if engine is not None:
            assert engine.wait_idle(120.0)
    return trajectory, stamped


class TestServingIntegration:
    STEPS = 5

    def test_hits_are_bit_equal_to_the_live_path(self):
        off_servicer, off_pythia = _gp_stack()
        try:
            assert off_pythia.serving_runtime.speculative_engine is None
            baseline, off_stamps = _drive_loop(
                off_servicer, off_pythia, "owners/o/studies/base", self.STEPS
            )
        finally:
            off_pythia.shutdown()
        assert not any(off_stamps)

        on_servicer, on_pythia = _gp_stack(SpeculativeConfig(speculative=True))
        try:
            speculated, on_stamps = _drive_loop(
                on_servicer, on_pythia, "owners/o/studies/spec", self.STEPS
            )
            counters = {
                k: v
                for k, v in on_pythia.serving_stats().items()
                if k.startswith("speculative_")
            }
        finally:
            on_pythia.shutdown()

        # Suggestion-for-suggestion bit equality: every hit is exactly the
        # batch live compute would have produced for the same frontier.
        assert speculated == baseline
        # Suggest 0 is the seeding stage (no cache entry yet) and suggest 1
        # computes live (the entry is born there); everything after hits.
        assert on_stamps == [False, False] + [True] * (self.STEPS - 2)
        assert counters["speculative_hits"] == self.STEPS - 2
        assert counters["speculative_errors"] == 0

    def test_delete_study_never_serves_the_predecessors_batch(self):
        from vizier_tpu.service.protos import vizier_service_pb2

        servicer, pythia = _gp_stack(SpeculativeConfig(speculative=True))
        study_name = "owners/o/studies/reused"
        try:
            _drive_loop(servicer, pythia, study_name, 3)
            engine = pythia.serving_runtime.speculative_engine
            entry = pythia.serving_runtime.designer_cache.peek(study_name)
            assert entry is not None and entry.speculative is not None
            servicer.DeleteStudy(
                vizier_service_pb2.DeleteStudyRequest(name=study_name)
            )
            assert pythia.serving_runtime.designer_cache.peek(study_name) is None
            # The reused name starts from scratch: fresh study, no stamp on
            # its first suggests.
            trajectory, stamps = _drive_loop(servicer, pythia, study_name, 2)
            assert not any(stamps)
        finally:
            pythia.shutdown()

    def test_shutdown_under_live_speculation(self):
        servicer, pythia = _gp_stack(SpeculativeConfig(speculative=True))
        try:
            _drive_loop(servicer, pythia, "owners/o/studies/load", 3)
        finally:
            pythia.shutdown()
        assert not any(
            t.name.startswith("vizier-speculative") and t.is_alive()
            for t in threading.enumerate()
        )
